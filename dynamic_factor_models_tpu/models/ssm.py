"""State-space DFM: Kalman filter/smoother (lax.scan) + EM, end-to-end jitted.

This is the `Parametric` estimation path the reference declared but never
implemented (dfm_functions.ipynb cell 1:3; SURVEY.md section 0) — the spec is
Doz-Giannone-Reichlin (2012) / Banbura-Modugno (2014) EM for factor models
with arbitrary missing-data patterns:

    x_t = Lam f_t + eps_t,        eps_t ~ N(0, diag(R))
    f_t = A_1 f_{t-1} + ... + A_p f_{t-p} + u_t,   u_t ~ N(0, Q)

TPU-first design choices:
  * the filter/smoother are ``lax.scan`` over time with static shapes;
  * missing observations are handled by masking rows of Lam (never by
    changing shapes), so one compiled program serves every missing pattern;
  * the measurement update is OBSERVATION-COLLAPSED (Jungbacker-Koopman
    2008): the panel enters only through per-step statistics
    C_t = Lam' R_t^-1 Lam and b_t = Lam' R_t^-1 x_t, precomputed for all t
    as two MXU-shaped matmuls before the scan — the scan body is O(k^3)
    with k = r*p the state dim, with NO N-dependence (previously
    O(N r^2 + k^3) per sequential step) and never O(N^3);
  * one EM iteration (E-step scans + closed-form M-step) is a single jitted
    function; `em iters/sec` is the tracked benchmark metric (BASELINE.json).
"""

from __future__ import annotations

from functools import lru_cache, partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import jax.scipy.linalg as jsl
import numpy as np

from ..ops.linalg import solve_normal, standardize_data
from ..ops.masking import fillz, mask_of
from ..utils.backend import on_backend
from .dfm import DFMConfig, estimate_dfm

__all__ = [
    "SSMParams",
    "KalmanResult",
    "PanelStats",
    "compute_panel_stats",
    "kalman_filter",
    "kalman_smoother",
    "em_step",
    "em_step_stats",
    "em_step_assoc",
    "em_step_sqrt",
    "em_step_sqrt_collapsed",
    "em_step_steady",
    "SteadyEMState",
    "estimate_dfm_em",
    "estimate_dfm_twostep",
    "estimate_dfm_mle",
    "ssm_standard_errors",
    "SSMStandardErrors",
    "EMResults",
]


class SSMParams(NamedTuple):
    """Parameters of the state-space DFM.

    lam: (N, r) loadings; R: (N,) idiosyncratic variances;
    A: (p, r, r) VAR coefficient blocks; Q: (r, r) factor innovation cov.
    """

    lam: jnp.ndarray
    R: jnp.ndarray
    A: jnp.ndarray
    Q: jnp.ndarray

    @property
    def r(self) -> int:
        return self.lam.shape[1]

    @property
    def p(self) -> int:
        return self.A.shape[0]


class KalmanResult(NamedTuple):
    loglik: jnp.ndarray
    means: jnp.ndarray  # (T, k) filtered or smoothed state means
    covs: jnp.ndarray  # (T, k, k)
    pred_means: jnp.ndarray  # (T, k) one-step-ahead means (filter only)
    pred_covs: jnp.ndarray  # (T, k, k)


# Unroll factor for the time scans: small per-step bodies (k x k Cholesky
# algebra) leave XLA's per-iteration dispatch visible at T in the thousands;
# unrolling amortizes it on CPU and gives the TPU scheduler a longer basic
# block, at negligible compile-time cost for the shapes used here.
# Default 8: measured best on the reference-scale EM sweep (quiet CPU,
# 149/168/139 it/s at 4/8/16 — bench.py --run-em-refscale).
# Env-overridable (read once at import) so the bench's reference-scale
# latency decomposition can sweep it in child processes on the live chip.
import os as _os

_SCAN_UNROLL = int(_os.environ.get("DFM_SCAN_UNROLL", "8"))


def _psd_floor(Q: jnp.ndarray) -> jnp.ndarray:
    """Symmetrize and floor the eigenvalues of a covariance estimate.

    The filter's Cholesky updates require Q strictly PD (Pp = TPT' + Qs is
    PD iff Q and P are); the EM M-step covariance S11 - A S10' is only PSD
    up to float error and can acquire tiny negative eigenvalues with
    near-collinear factors.  Flooring at eps-scale keeps the fast Cholesky
    path valid without measurably moving a healthy Q.
    """
    Q = 0.5 * (Q + Q.T)
    e, v = jnp.linalg.eigh(Q)
    eps = jnp.asarray(jnp.finfo(Q.dtype).eps, Q.dtype)
    floor = jnp.maximum(e[-1] * 16.0 * eps, eps)
    return (v * jnp.maximum(e, floor)) @ v.T


# Auto-dispatch threshold for the N-free collapsed variants of the fan /
# news / simulation-smoother entry points (scenarios/fanout.py, models/
# news.py, models/bayes.py): above this panel width the per-lane masked
# filters would each drag (T, N) operands through their scans, so the
# entry points switch to sharing ONE (T, N) collapse projection across
# every lane/draw.  Parity between the two forms is exact (pinned), so
# the threshold is purely a performance crossover, overridable per call
# via each entry point's `collapsed=` flag.
LARGE_N_THRESHOLD = 512


def _psd_sqrt(C: jnp.ndarray) -> jnp.ndarray:
    """Symmetric PSD square root, batched over leading axes.

    Used by the collapsed Durbin-Koopman simulation smoothers: the
    collapse of simulated measurement noise is Lam'R^-1 M_t eps_t ~
    N(0, C_t), so drawing the r-dim pseudo-observation noise needs
    C_t^{1/2}.  C_t is singular when fewer than r series are observed at
    t (rank = min(n_obs_t, r)); the eigenvalue clamp keeps the root exact
    on the range and zero on the null space — an all-missing step yields
    C = 0 and a zero root, which is the correct degenerate draw."""
    C = 0.5 * (C + jnp.swapaxes(C, -1, -2))
    e, v = jnp.linalg.eigh(C)
    return (v * jnp.sqrt(jnp.maximum(e, 0.0))[..., None, :]) @ jnp.swapaxes(
        v, -1, -2
    )


def _companion(params: SSMParams):
    r, p = params.r, params.p
    k = r * p
    Tm = jnp.zeros((k, k), params.A.dtype)
    Tm = Tm.at[:r, :].set(jnp.concatenate([params.A[i] for i in range(p)], axis=1))
    if p > 1:
        Tm = Tm.at[r:, : k - r].set(jnp.eye(k - r, dtype=params.A.dtype))
    Qs = jnp.zeros((k, k), params.Q.dtype).at[:r, :r].set(params.Q)
    return Tm, Qs


def _init_state(params: SSMParams):
    """Diffuse-ish init: zero mean, large isotropic covariance."""
    k = params.r * params.p
    return jnp.zeros(k, params.lam.dtype), 1e2 * jnp.eye(k, dtype=params.lam.dtype)


def _info_filter_scan(Tm, Qs, obs_inputs, obs_step, s0, P0, qdiag=None,
                      want_pinv=False):
    """Generic masked information-form Kalman filter (shared scan body).

    `obs_inputs` is a tuple of (T, ...) arrays scanned over;
    `obs_step(inp, sp) -> (C, rhs, ld_R, quad0, n_obs)` supplies the
    model-specific measurement update from the per-step slice `inp`:
    information matrix C = H'R⁻¹H, gain right-hand side
    rhs = H'R⁻¹(x - H sp), the observed-rows log|R|, the observation
    quadratic Σ (x - H sp)'R⁻¹(x - H sp), and the count.  The prediction,
    Cholesky updates, and determinant-lemma log-likelihood are identical
    across models (ssm.py collapsed form; ssm_ar.py structured dense
    observation map; mixed_freq.py lag-aggregated collapsed form) and live
    only here.

    `qdiag` (T, r) optionally supplies time-varying transition-noise
    variances for the leading r state dims (stochastic-volatility models);
    it is ADDED to the constant Qs, so pass Qs with a zero top-left block
    when the variances are fully time-varying.

    Returns (means, covs, pred_means, pred_covs, lls) with lls the
    PER-STEP log-likelihood terms (T,) — callers sum; inference code
    (OPG scores) differentiates them individually.  `want_pinv=True`
    appends the per-step predicted-covariance inverses Pp⁻¹ (already a
    byproduct of the information update) so an RTS pass can reuse them
    instead of refactorizing — the EM E-step path does.
    """
    k = Tm.shape[0]
    dtype = s0.dtype
    log2pi = jnp.asarray(np.log(2.0 * np.pi), dtype)
    eye_k = jnp.eye(k, dtype=dtype)
    r_tv = 0 if qdiag is None else qdiag.shape[1]

    def step(carry, inp):
        s, P = carry
        if qdiag is not None:
            qt = inp[-1]
            inp = inp[:-1]
        sp = Tm @ s
        Pp = Tm @ P @ Tm.T + Qs
        Pp = 0.5 * (Pp + Pp.T)
        if qdiag is not None:
            Pp = Pp.at[jnp.arange(r_tv), jnp.arange(r_tv)].add(qt)
        C, rhs, ld_R, quad0, n_obs = obs_step(inp, sp)
        # Pp is PD (Q PD ⇒ the prediction keeps full rank), so Cholesky
        # replaces the eigh-based pinv and yields log-dets for free
        Lp = jnp.linalg.cholesky(Pp)
        Ppinv = jsl.cho_solve((Lp, True), eye_k)
        M = Ppinv + C
        Lm = jnp.linalg.cholesky(0.5 * (M + M.T))
        Pu = jsl.cho_solve((Lm, True), eye_k)
        Pu = 0.5 * (Pu + Pu.T)
        su = sp + Pu @ rhs
        # log-likelihood via matrix determinant lemma:
        # log|S| = log|R|_obs + log|Pp| - log|Pu|
        ld_pp = 2.0 * jnp.log(jnp.diagonal(Lp)).sum()
        ld_pu = -2.0 * jnp.log(jnp.diagonal(Lm)).sum()
        quad = quad0 - rhs @ Pu @ rhs
        ll = -0.5 * (n_obs * log2pi + ld_R + ld_pp - ld_pu + quad)
        out = (su, Pu, sp, Pp, ll)
        if want_pinv:
            out = out + (Ppinv,)
        return (su, Pu), out

    inputs = obs_inputs if qdiag is None else (*obs_inputs, qdiag)
    (_, _), outs = jax.lax.scan(step, (s0, P0), inputs, unroll=_SCAN_UNROLL)
    return outs


class PanelStats(NamedTuple):
    """Loop-invariant data statistics, computed once per panel and threaded
    through the EM loop (run_em_loop args) so no per-iteration work is spent
    on them.  The transposed copies matter most: XLA does not hoist a
    transpose of a loop constant out of ``lax.while_loop``, and the M-step's
    series-side Gram contractions run ~5x faster (measured, CPU) in the
    contiguous-reduction orientation (N, T) @ (T, cols) than as
    (T, N).T @ (T, cols) strided reads.  Sxx / n_i / n_obs are pure data
    sums (x zero-filled at missing, so m*x == x)."""

    m: jnp.ndarray  # (T, N) float mask (dtype of x, ready for GEMM)
    xT: jnp.ndarray  # (N, T) transposed zero-filled panel
    mT: jnp.ndarray  # (N, T) transposed float mask
    Sxx: jnp.ndarray  # (N,) sum_t x_it^2
    n_i: jnp.ndarray  # (N,) per-series observation counts
    n_obs: jnp.ndarray  # (T,) per-period observation counts
    # optional bfloat16 twins of the four GEMM-side panel copies (None on
    # the exact path).  When present, `_collapse_obs_stats` and
    # `_em_m_step` run their panel contractions on bf16 operands with f32
    # accumulation (the ops/pallas_gram.py dtype contract) — the panel
    # enters each EM iteration through exactly four (T, N)-sized GEMMs,
    # all HBM-bandwidth-bound at scale, and bf16 halves that traffic.
    m16: jnp.ndarray | None = None  # (T, N)
    x16: jnp.ndarray | None = None  # (T, N)
    mT16: jnp.ndarray | None = None  # (N, T)
    xT16: jnp.ndarray | None = None  # (N, T)
    # optional (T,) time-validity weight for shape-bucketed panels
    # (utils.compile.pad_panel): 1 on real periods, 0 on padding.  Padded
    # periods are fully masked, so every observation-side statistic is
    # already exact; tw exists for the ONE term that sums over time
    # without a mask — the M-step's factor-VAR moments, whose padded
    # forecast states would otherwise contaminate A and Q (see
    # `_var_moments`).  None on unbucketed panels (the exact legacy
    # program).
    tw: jnp.ndarray | None = None


def _with_bf16_twins(stats: PanelStats, x) -> PanelStats:
    """The single copy of the bf16-twin construction: adds bfloat16 casts
    of the four GEMM-side panel operands to existing exact stats (no
    duplicate f32 copies — `_replace` shares the f32 fields)."""
    return stats._replace(
        m16=stats.m.astype(jnp.bfloat16),
        x16=x.astype(jnp.bfloat16),
        mT16=stats.mT.astype(jnp.bfloat16),
        xT16=stats.xT.astype(jnp.bfloat16),
    )


def compute_panel_stats(x, mask, bf16: bool = False) -> PanelStats:
    """Materialize the loop-invariant statistics for (x zero-filled, mask).

    bf16=True additionally stores bfloat16 copies of the panel/mask (and
    transposes), switching the EM iteration's four panel GEMMs to the
    mixed-precision path — used by `estimate_dfm_em(gram_dtype=...)`'s
    bulk phase; the exact statistics (Sxx, counts) stay full-precision."""
    m = mask.astype(x.dtype)
    xT = jnp.asarray(x.T)
    mT = jnp.asarray(m.T)
    stats = PanelStats(
        m=m,
        xT=xT,
        mT=mT,
        Sxx=(xT * xT).sum(axis=1),
        n_i=mT.sum(axis=1),
        n_obs=m.sum(axis=1),
    )
    return _with_bf16_twins(stats, x) if bf16 else stats


def _sym_pack_idx(q: int):
    """Packed-symmetric index maps: (iu, iv) the upper-triangle coordinate
    lists (q(q+1)/2 entries) and `unpack` (q*q,) mapping each full (a, b)
    cell to its packed column — symmetric matmuls then carry only the
    unique columns (45% fewer FLOPs at q=8) and rebuild by one gather."""
    a, b_ = np.triu_indices(q)
    full = np.zeros((q, q), np.int32)
    full[a, b_] = np.arange(a.size, dtype=np.int32)
    full = np.maximum(full, full.T)
    return jnp.asarray(a), jnp.asarray(b_), jnp.asarray(full.reshape(-1))


def _collapse_obs(Hq, R, x, m, n_obs=None):
    """Per-step collapsed observation statistics (Jungbacker-Koopman 2008,
    "Likelihood-based analysis for dynamic factor models"; exact — see
    `_filter_scan`).

    Hq: (N, q) the observation-loaded columns of an obs map H = [Hq, 0];
    R: (N,) diagonal noise variances; x: (T, N) zero-filled panel;
    m: (T, N) float mask.  Returns per-step arrays

        C[t]     = Hq' diag(m_t / R) Hq          (q, q)
        b[t]     = Hq' (m_t / R * x_t)           (q,)
        ld_R[t]  = sum over observed of log R_i
        xRx[t]   = x_t' R_t^-1 x_t
        n_obs[t] = observed count

    — everything a measurement update needs, computed as two
    (T, N) @ (N, *) matmuls (MXU-shaped, one HBM pass) instead of T
    sequential O(N q^2) products inside the filter scan.  C is symmetric,
    so its matmul carries only the q(q+1)/2 unique loading-pair columns
    (plus one fused column for ld_R: m = rinv * R makes m @ log R an
    rinv @ (R log R) product) and rebuilds the full matrix by one gather.
    """
    N, q = Hq.shape
    iu, iv, unpack = _sym_pack_idx(q)
    rinv = m / R
    pair_u = jnp.concatenate(
        [Hq[:, iu] * Hq[:, iv], (R * jnp.log(R))[:, None]], axis=1
    )
    Cu = rinv @ pair_u  # (T, q(q+1)/2 + 1)
    C = Cu[:, unpack].reshape(-1, q, q)
    ld_R = Cu[:, -1]
    w2 = rinv * x
    b = w2 @ Hq
    xRx = (w2 * x).sum(axis=1)
    if n_obs is None:
        n_obs = m.sum(axis=1)
    return C, b, ld_R, xRx, n_obs


def _bf16_gemm(subscripts: str, a16, b, out_dtype):
    """The mixed-precision panel-GEMM contract in one place: bf16 panel
    operand (pre-cast, held in PanelStats), small operand cast to bf16 per
    call, accumulation at >= f32, result in the caller's dtype."""
    acc = jnp.promote_types(out_dtype, jnp.float32)
    return jnp.einsum(
        subscripts, a16, b.astype(jnp.bfloat16), preferred_element_type=acc
    ).astype(out_dtype)


def _collapse_obs_stats(Hq, R, x, stats: PanelStats):
    """`_collapse_obs` for looped callers holding PanelStats: the 1/R
    weighting rides the GEMMs' N-indexed right operands (C = m @ (pair/R),
    b = x @ (Hq/R); m*x == x), and the state-independent quadratic
    sum_t x'R^-1x_t leaves the per-step stream entirely — returned instead
    as the scalar log-likelihood correction -1/2 sum_i Sxx_i/R_i (exact:
    it never touches the state update).  Two panel GEMMs per call, zero
    (T, N) temporaries."""
    q = Hq.shape[1]
    iu, iv, unpack = _sym_pack_idx(q)
    pair_R = jnp.concatenate(
        [(Hq[:, iu] * Hq[:, iv]) / R[:, None], jnp.log(R)[:, None]], axis=1
    )
    if stats.m16 is not None:
        Cu = _bf16_gemm("tn,nc->tc", stats.m16, pair_R, x.dtype)
        b = _bf16_gemm("tn,nq->tq", stats.x16, Hq / R[:, None], x.dtype)
    else:
        Cu = stats.m @ pair_R
        b = x @ (Hq / R[:, None])
    C = Cu[:, unpack].reshape(-1, q, q)
    ld_R = Cu[:, -1]
    xRx = jnp.zeros(x.shape[0], x.dtype)
    ll_corr = -0.5 * (stats.Sxx / R).sum()
    return C, b, ld_R, xRx, stats.n_obs, ll_corr


def _collapse_obs_stats_partial(Hq, R, x, stats: PanelStats):
    """Per-shard half of `_collapse_obs_stats`: the two panel GEMMs on a
    cross-section slice, returned as one packed (T, q(q+1)/2 + 1 + q)
    payload — [Cu | b] with the fused log|R| column — plus the scalar
    log-likelihood correction.  Every collapsed statistic is a sum over
    series, so shard partials reduce EXACTLY: the caller all-reduces the
    payload across the mesh (`ops.pallas_gram.ring_allreduce`), psums the
    scalar, and unpacks with `_unpack_collapsed`.  n_obs is NOT part of
    the payload — it is precomputed globally in PanelStats and rides the
    replicated spec."""
    q = Hq.shape[1]
    iu, iv, _ = _sym_pack_idx(q)
    pair_R = jnp.concatenate(
        [(Hq[:, iu] * Hq[:, iv]) / R[:, None], jnp.log(R)[:, None]], axis=1
    )
    Cu = stats.m @ pair_R
    b = x @ (Hq / R[:, None])
    ll_corr = -0.5 * (stats.Sxx / R).sum()
    return jnp.concatenate([Cu, b], axis=1), ll_corr


def _unpack_collapsed(payload, q: int):
    """Invert the `_collapse_obs_stats_partial` packing after reduction."""
    npack = q * (q + 1) // 2
    _, _, unpack = _sym_pack_idx(q)
    C = payload[:, unpack].reshape(-1, q, q)
    ld_R = payload[:, npack]
    b = payload[:, npack + 1 :]
    return C, b, ld_R


def _filter_scan_collapsed_stats(params, C, b, ld_R, n_obs, ll_corr,
                                 want_pinv=False):
    """`_filter_scan`'s scan assembly on pre-reduced collapsed statistics.

    The sharded EM step computes C/b/ld_R as per-shard partials and
    all-reduces them across the mesh BEFORE the state recursion, which is
    O(k^3) per step with no N-dependence and therefore runs replicated on
    every device.  Kept as a separate function — not a refactor of
    `_filter_scan` — so the single-device program stays byte-identical to
    its HLO pins.  xRx is identically zero on the stats path (the
    quadratic is the ll_corr scalar)."""
    Tm, Qs = _companion(params)
    k = Tm.shape[0]
    r = params.r
    s0, P0 = _init_state(params)
    dtype = b.dtype
    xRx = jnp.zeros(b.shape[0], dtype)

    def obs_step(inp, sp):
        Ct, bt, ld, xr, no = inp
        f = sp[:r]
        Cf = jnp.zeros((k, k), dtype).at[:r, :r].set(Ct)
        rhs = jnp.zeros(k, dtype).at[:r].set(bt - Ct @ f)
        quad0 = xr - 2.0 * (f @ bt) + f @ Ct @ f
        return Cf, rhs, ld, quad0, no

    outs = _info_filter_scan(
        Tm, Qs, (C, b, ld_R, xRx, n_obs), obs_step, s0, P0,
        want_pinv=want_pinv,
    )
    means, covs, pmeans, pcovs, lls = outs[:5]
    res = KalmanResult(lls.sum() + ll_corr, means, covs, pmeans, pcovs)
    return (res, outs[5]) if want_pinv else res


def _pos_diag(Rf):
    # QR sign convention: flip rows so the triangular factor has a
    # positive diagonal (keeps log-det real and factors comparable)
    sgn = jnp.sign(jnp.diagonal(Rf))
    sgn = jnp.where(sgn == 0, 1.0, sgn)
    return sgn[:, None] * Rf


@jax.jit
def _sqrt_filter_scan_collapsed(params: SSMParams, x, mask):
    """Collapsed square-root (array-form) masked Kalman filter: the
    SCALABLE sqrt variant (method="sqrt_collapsed").

    Covariances propagate as Cholesky factors through one QR per step
    (Kailath-Sayed array algorithm): updated covariances are S S' —
    symmetric PSD by construction, no drift to fix up — and the state
    recursion is array-form stable.  Know the trade-off, though: forming
    C_t = Lam'R_t^-1 Lam squares the observation-side conditioning exactly
    the way normal equations do, so the FULL sqrt filter's f32
    log-likelihood advantage does NOT survive the collapse (measured on
    the ill-conditioned DGP family of tests/test_ssm.py: f32 loglik error
    0.3-0.6 here vs 0.0003-0.0006 full-sqrt vs 0.27 information filter).
    Use method="sqrt" when f32 likelihood precision is the point and N is
    moderate; use this when the panel is wide and the O((N+k)^3) full
    pre-array is unaffordable but an array-form state recursion is still
    wanted.  Posteriors and log-likelihood remain EXACT in exact
    arithmetic (f64 agreement with the full filter pinned at 1e-10 in
    tests/test_collapsed.py).

    This version carries the Jungbacker-Koopman collapse (`_collapse_obs`)
    into the array algorithm: the N observed series at time t enter only
    through C_t = Lam' R_t^-1 Lam = V_t D_t V_t' and b_t = Lam' R_t^-1 x_t,
    and the equivalent r-dim pseudo-observation

        z_t = L_t' f_t + w_t,  w_t ~ N(0, I_r),  L_t = V_t D_t^{1/2},
        z_t = D_t^{-1/2} V_t' b_t

    has the identical state posterior; the exact full-panel log-likelihood
    is recovered from the collapsed one by the per-step constant

        c_t = -1/2 [(n_t - rho_t) log 2pi + ld_R_t + x'R^-1x_t - z_t'z_t]

    (rho_t = rank C_t; exactness holds because b_t ∈ range(C_t), so the
    discarded (N - rho_t)-dim residual component is free of f_t).
    Rank-deficient steps — n_t < r, collinear observed loadings, or fully
    missing rows — get dummy pseudo-rows (zero H-row, unit noise, z = 0)
    that contribute nothing to the update, determinant, or quadratic, so
    one compiled program serves every missing pattern.  The QR pre-array is
    (r+k)-square instead of (N+k)-square: the sqrt method stops costing
    O((N+k)^3) per step and stays viable at full panel width.

        prediction:   qr([S_u' Tm' ; chol(Q_s)'])            -> S_p'
        measurement:  qr([I_r  0 ; S_p' L_t  S_p']) = [S_e'  K' ; 0  S_u']
        update:       s_u = s_p + K solve(S_e, z - L'f_p)
        loglik:       c_t - 1/2 [2 sum log diag S_e + e'e]
    """
    Tm, _ = _companion(params)
    k = Tm.shape[0]
    r = params.r
    dtype = x.dtype
    log2pi = jnp.asarray(np.log(2.0 * np.pi), dtype)
    # Q is pre-floored by every caller (the _filter_scan contract), so the
    # Cholesky here is safe without a second eps-floor
    sqrtQ = jnp.linalg.cholesky(params.Q)  # (r, r)
    s0, P0 = _init_state(params)
    S0 = jnp.linalg.cholesky(P0)

    m = mask.astype(dtype)
    C, b, ld_R, xRx, n_obs = _collapse_obs(params.lam, params.R, x, m)
    d, V = jnp.linalg.eigh(C)  # batched over T; C = V diag(d) V'
    eps = jnp.asarray(jnp.finfo(dtype).eps, dtype)
    rank_tol = (r * eps) * jnp.maximum(d[:, -1:], 1.0)
    use = d > rank_tol  # (T, r) pseudo-rows carrying information
    dsafe = jnp.where(use, d, 1.0)
    # H_t = L_t' with L_t = V_t D_t^{1/2} (dummy rows zeroed)
    Ht = (V * jnp.where(use, jnp.sqrt(dsafe), 0.0)[:, None, :]).swapaxes(-1, -2)
    z = jnp.where(use, jnp.einsum("tij,ti->tj", V, b) / jnp.sqrt(dsafe), 0.0)
    # c_t combined with the collapsed model's rho_t log 2pi term: the
    # (n - rho) and rho pieces recombine into one n_t log 2pi
    base = -0.5 * (n_obs * log2pi + ld_R + xRx - (z * z).sum(axis=1))

    def step(carry, inp):
        s, S = carry  # S lower: P = S S'
        Ht_t, zt, base_t = inp
        # --- prediction (array form) ---
        sp = Tm @ s
        pre_p = jnp.concatenate(
            [S.T @ Tm.T, jnp.zeros((r, k), dtype).at[:, :r].set(sqrtQ.T)]
        )
        Sp = _pos_diag(jnp.linalg.qr(pre_p, mode="r")).T  # (k, k) lower

        # --- collapsed measurement update (array form) ---
        HS = Ht_t @ Sp[:r, :]  # (r, k)
        pre = jnp.zeros((r + k, r + k), dtype)
        pre = pre.at[:r, :r].set(jnp.eye(r, dtype=dtype))  # unit pseudo-noise
        pre = pre.at[r:, :r].set(HS.T)
        pre = pre.at[r:, r:].set(Sp.T)
        post = _pos_diag(jnp.linalg.qr(pre, mode="r")).T  # lower
        Se = post[:r, :r]  # (r, r) lower sqrt pseudo-innovation cov
        Kbar = post[r:, :r]  # (k, r)
        Su = post[r:, r:]  # (k, k) lower sqrt updated cov

        v = zt - Ht_t @ sp[:r]  # dummy rows: exactly zero
        e = jsl.solve_triangular(Se, v, lower=True)
        su = sp + Kbar @ e
        # dummy rows: diag(Se) = 1 there, e = 0 there — both sums exact
        ll = base_t - 0.5 * (
            2.0 * jnp.log(jnp.diagonal(Se)).sum() + (e * e).sum()
        )
        return (su, Su), (su, Su @ Su.T, sp, Sp @ Sp.T, ll)

    (_, _), (means, covs, pmeans, pcovs, lls) = jax.lax.scan(
        step, (s0, S0), (Ht, z, base), unroll=_SCAN_UNROLL
    )
    return KalmanResult(lls.sum(), means, covs, pmeans, pcovs)


@jax.jit
def _sqrt_filter_scan(params: SSMParams, x, mask):
    """Square-root filter, full (N+k)-square pre-array form — the
    ACCURACY-FIRST path behind method="sqrt".

    It keeps the measured f32 log-likelihood win (~8-16x tighter than the
    information filter on ill-conditioned DGPs; tests/test_ssm.py
    `test_f32_loglik_precision_win`, docs/PARITY.md) precisely because the
    panel is never compressed: the observation block enters the QR as
    [R^1/2; S_p'H'] without ever forming the squared normal matrix
    Lam'R^-1 Lam.  The Jungbacker-Koopman collapse cannot preserve this
    (`_sqrt_filter_scan_collapsed` measures f32 errors at info-filter
    level), so the scalable collapsed variant is a separate method and
    this one stays O((N+k)^3) per step by design.
    Missing data: masked rows get a zero observation row and unit dummy
    variance — the innovation is exactly zero and the dummy rows are
    uncoupled, so they contribute nothing to the update, the determinant,
    or the quadratic.

        prediction:   qr([S_u' Tm' ; chol(Q_s)'])          -> S_p'
        measurement:  qr([R^1/2  0 ; S_p' H'  S_p']) = [S_e'  K' ; 0  S_u']
        update:       s_u = s_p + K solve(S_e, v)
        loglik:       log|HPH'+R| = 2 sum log diag S_e  (dummy rows add 0)
    """
    Tm, _ = _companion(params)
    k = Tm.shape[0]
    r = params.r
    N = params.lam.shape[0]
    dtype = x.dtype
    log2pi = jnp.asarray(np.log(2.0 * np.pi), dtype)
    sqrtQ = jnp.linalg.cholesky(params.Q)  # (r, r)
    s0, P0 = _init_state(params)
    S0 = jnp.linalg.cholesky(P0)

    def step(carry, inp):
        s, S = carry  # S lower: P = S S'
        xt, mt = inp
        # --- prediction (array form) ---
        sp = Tm @ s
        pre_p = jnp.concatenate([S.T @ Tm.T, jnp.zeros((r, k), dtype).at[:, :r].set(sqrtQ.T)])
        Sp = _pos_diag(jnp.linalg.qr(pre_p, mode="r")).T  # (k, k) lower

        # --- measurement update (array form, masked) ---
        lam_m = params.lam * mt[:, None]  # zero rows at missing
        rstd = jnp.where(mt > 0, jnp.sqrt(params.R), 1.0)  # dummy unit sd
        HS = lam_m @ Sp[:r, :]  # (N, k): H = [lam_m, 0] so H @ Sp hits top rows
        pre = jnp.zeros((N + k, N + k), dtype)
        pre = pre.at[:N, :N].set(jnp.diag(rstd))
        pre = pre.at[N:, :N].set(HS.T)
        pre = pre.at[N:, N:].set(Sp.T)
        post = _pos_diag(jnp.linalg.qr(pre, mode="r")).T  # lower
        Se = post[:N, :N]  # (N, N) lower sqrt innovation cov
        Kbar = post[N:, :N]  # (k, N) = P_p H' S_e^{-T}
        Su = post[N:, N:]  # (k, k) lower sqrt updated cov

        v = mt * (xt - params.lam @ sp[:r])  # masked innovation
        e = jsl.solve_triangular(Se, v, lower=True)
        su = sp + Kbar @ e
        # dummy rows: diag(Se) = 1 there, e = 0 there — both sums exact
        ll = -0.5 * (
            mt.sum() * log2pi
            + 2.0 * jnp.log(jnp.diagonal(Se)).sum()
            + (e * e).sum()
        )
        return (su, Su), (su, Su @ Su.T, sp, Sp @ Sp.T, ll)

    (_, _), (means, covs, pmeans, pcovs, lls) = jax.lax.scan(
        step, (s0, S0), (x, mask.astype(dtype))
    )
    return KalmanResult(lls.sum(), means, covs, pmeans, pcovs)


@partial(jax.jit, static_argnames=("want_pinv",))
def _filter_scan(params: SSMParams, x, mask, qdiag=None, stats=None,
                 want_pinv=False):
    """Collapsed masked Kalman filter; x (T, N) NaN-free, mask (T, N).

    Only the first r state dims load on observations, so the measurement
    update depends on the panel only through the per-step statistics of
    `_collapse_obs` (Jungbacker-Koopman 2008) — precomputed for all t as
    batched MXU matmuls, leaving the scan body O(k^3) with no N-dependence.
    Algebraically identical to `_filter_scan_full` (exactness pinned in
    tests/test_collapsed.py): the information matrix, gain right-hand side
    and quadratic reconstruct exactly as

        rhs_t   = b_t - C_t f_p,
        quad0_t = x'R^-1x_t - 2 f_p'b_t + f_p'C_t f_p,   f_p = sp[:r].

    `qdiag` (T, r) replaces params.Q with time-varying diagonal
    factor-innovation variances (stochastic-volatility models).

    `stats` (PanelStats) switches to the bandwidth-minimal formulation for
    looped callers: the per-series 1/R weighting rides the GEMMs'
    N-indexed right operands (C = m @ (pair/R), b = x @ (Lam/R); m*x == x),
    and the state-independent quadratic sum_t x'R^-1x_t leaves the scan
    entirely as the scalar correction sum_i Sxx_i/R_i on the total
    log-likelihood — two panel GEMMs per iteration, zero (T, N)
    temporaries.
    """
    Tm, Qs = _companion(params)
    if qdiag is not None:
        Qs = jnp.zeros_like(Qs)  # fully time-varying top block
    k = Tm.shape[0]
    r = params.r
    s0, P0 = _init_state(params)
    dtype = x.dtype
    if stats is None:
        C, b, ld_R, xRx, n_obs = _collapse_obs(
            params.lam, params.R, x, mask.astype(dtype)
        )
        ll_corr = jnp.asarray(0.0, dtype)
    else:
        C, b, ld_R, xRx, n_obs, ll_corr = _collapse_obs_stats(
            params.lam, params.R, x, stats
        )

    def obs_step(inp, sp):
        Ct, bt, ld, xr, no = inp
        f = sp[:r]
        Cf = jnp.zeros((k, k), dtype).at[:r, :r].set(Ct)
        rhs = jnp.zeros(k, dtype).at[:r].set(bt - Ct @ f)
        quad0 = xr - 2.0 * (f @ bt) + f @ Ct @ f
        return Cf, rhs, ld, quad0, no

    outs = _info_filter_scan(
        Tm, Qs, (C, b, ld_R, xRx, n_obs), obs_step, s0, P0, qdiag=qdiag,
        want_pinv=want_pinv,
    )
    means, covs, pmeans, pcovs, lls = outs[:5]
    res = KalmanResult(lls.sum() + ll_corr, means, covs, pmeans, pcovs)
    return (res, outs[5]) if want_pinv else res


@jax.jit
def _filter_scan_full(params: SSMParams, x, mask, qdiag=None):
    """Uncollapsed masked information filter: the O(N r^2)-per-step
    Woodbury-restricted obs_step applied inside the scan.  Reference
    implementation for the collapse exactness tests
    (tests/test_collapsed.py); `_filter_scan` is the production path."""
    Tm, Qs = _companion(params)
    if qdiag is not None:
        Qs = jnp.zeros_like(Qs)
    k = Tm.shape[0]
    r = params.r
    lam = params.lam  # (N, r) — state loadings are [lam, 0, ..., 0]
    s0, P0 = _init_state(params)
    dtype = x.dtype

    def obs_step(inp, sp):
        xt, mt = inp
        rinv = mt / params.R  # (N,), 0 at missing
        lam_r = lam * rinv[:, None]  # (N, r)
        C = jnp.zeros((k, k), dtype).at[:r, :r].set(lam.T @ lam_r)
        v = xt - lam @ sp[:r]  # innovation (garbage at missing; weighted by 0)
        rhs = jnp.zeros(k, dtype).at[:r].set(lam_r.T @ v)
        ld_R = (mt * jnp.log(params.R)).sum()
        return C, rhs, ld_R, (rinv * v * v).sum(), mt.sum()

    means, covs, pmeans, pcovs, lls = _info_filter_scan(
        Tm, Qs, (x, mask.astype(dtype)), obs_step, s0, P0, qdiag=qdiag
    )
    return KalmanResult(lls.sum(), means, covs, pmeans, pcovs)


_FILTER_METHODS = ("sequential", "associative", "sqrt", "sqrt_collapsed", "steady")


# ---------------------------------------------------------------------------
# Steady-state fast path (method="steady")
#
# The model is time-invariant, so on any stretch of the sample where the
# observation pattern is also time-invariant (every series observed — the
# "complete tail" of a ragged-edge macro panel) the filter covariances
# converge geometrically to the DARE fixed point (models/steady.py).  The
# split program runs an EXACT head of t* steps — `_info_filter_scan`'s
# collapsed step, byte-for-byte the sequential update — and a constant-gain
# tail s_t = Ā s_{t-1} + K∞ b_t with no factorizations at all; smoother
# covariances on the tail are the closed-form constants Ps∞ (interior) and
# Ps∞ + J∞^j(Pu∞-Ps∞)J∞'^j (right boundary), so the E-step covariance
# reductions collapse to (T-t*)·P∞-style O(1) terms plus the head sum.
# t* is a SHAPE (the head scan length): computed host-side per estimate
# call (`_steady_plan`), never traced.
# ---------------------------------------------------------------------------


def _steady_collapse(params: SSMParams, x, stats: PanelStats, t_star: int):
    """Collapsed observation statistics for the split program: exact
    per-step (C_t, ld_R_t) on the head rows only — a (t*, N) GEMM — and
    the complete-tail constants C∞ = Lam'R^-1Lam, ld_R∞ = Σ_i log R_i
    (the masked GEMM's all-ones row: one column sum replaces the tail's
    share of the (T, N) product).  b_t is still needed at every t (the
    tail recursion consumes it), so that GEMM stays full-T."""
    r = params.r
    iu, iv, unpack = _sym_pack_idx(r)
    lam, R = params.lam, params.R
    pair_R = jnp.concatenate(
        [(lam[:, iu] * lam[:, iv]) / R[:, None], jnp.log(R)[:, None]], axis=1
    )
    Cu_head = stats.m[:t_star] @ pair_R
    C_head = Cu_head[:, unpack].reshape(-1, r, r)
    ld_R_head = Cu_head[:, -1]
    pairsum = pair_R.sum(axis=0)
    C_inf = pairsum[unpack].reshape(r, r)
    ld_R_inf = pairsum[-1]
    b = x @ (lam / R[:, None])
    ll_corr = -0.5 * (stats.Sxx / R).sum()
    return C_head, ld_R_head, C_inf, ld_R_inf, b, ll_corr


def _steady_core(params: SSMParams, x, stats: PanelStats, Pp0, t_star: int, block: int):
    """Shared forward pass of the steady path: DARE solve (warm-started
    from Pp0 when given), exact collapsed head of t* steps, constant-gain
    tail.  Returns (steady, head scan outputs, tail filtered means, tail
    per-step lls, ll correction, Tm)."""
    from .steady import steady_state, steady_tail

    Tm, Qs = _companion(params)
    k = Tm.shape[0]
    r = params.r
    dtype = x.dtype
    s0, P0 = _init_state(params)
    C_head, ld_R_head, C_inf, ld_R_inf, b, ll_corr = _steady_collapse(
        params, x, stats, t_star
    )
    st = steady_state(Tm, C_inf, Qs, q=r, Pp0=Pp0)

    def obs_step(inp, sp):
        Ct, bt, ld, xr, no = inp
        f = sp[:r]
        Cf = jnp.zeros((k, k), dtype).at[:r, :r].set(Ct)
        rhs = jnp.zeros(k, dtype).at[:r].set(bt - Ct @ f)
        quad0 = xr - 2.0 * (f @ bt) + f @ Ct @ f
        return Cf, rhs, ld, quad0, no

    head = _info_filter_scan(
        Tm,
        Qs,
        (
            C_head,
            b[:t_star],
            ld_R_head,
            jnp.zeros(t_star, dtype),
            stats.n_obs[:t_star],
        ),
        obs_step,
        s0,
        P0,
    )
    ld_const = ld_R_inf + st.ld_pp - st.ld_pu
    su_tail, lls_tail = steady_tail(
        Tm, C_inf, st.Pu[:r, :r], st.K, st.Abar, b[t_star:],
        head[0][-1], stats.n_obs[t_star:], ld_const, block=block,
    )
    return st, head, su_tail, lls_tail, ll_corr, Tm


@partial(jax.jit, static_argnames=("t_star", "block"))
def _steady_filter(params: SSMParams, x, mask, stats: PanelStats, t_star: int, block: int):
    """Steady-path masked Kalman filter (cold DARE solve in-graph).  The
    tail covariances of the returned KalmanResult are the broadcast
    constants Pu∞ / Pp∞ — exact up to the convergence tolerance the
    dispatch (`_steady_plan`) verified."""
    params = params._replace(Q=_psd_floor(params.Q))
    st, head, su_tail, lls_tail, ll_corr, Tm = _steady_core(
        params, x, stats, None, t_star, block
    )
    means_h, covs_h, pmeans_h, pcovs_h, lls_h = head
    n_tail = su_tail.shape[0]
    sp_tail = jnp.concatenate([means_h[-1:], su_tail[:-1]]) @ Tm.T
    return KalmanResult(
        lls_h.sum() + lls_tail.sum() + ll_corr,
        jnp.concatenate([means_h, su_tail]),
        jnp.concatenate(
            [covs_h, jnp.broadcast_to(st.Pu, (n_tail, *st.Pu.shape))]
        ),
        jnp.concatenate([pmeans_h, sp_tail]),
        jnp.concatenate(
            [pcovs_h, jnp.broadcast_to(st.Pp, (n_tail, *st.Pp.shape))]
        ),
    )


@partial(jax.jit, static_argnames=("t_star", "block"))
def _steady_smoother(params: SSMParams, x, mask, stats: PanelStats, t_star: int, block: int):
    """Steady-path smoother: exact RTS over the head (closed at the
    boundary by the steady smoothed covariance Ps∞), the backward
    constant-gain mean recursion over the tail, and closed-form tail
    covariances Ps∞ + J∞^j(Pu∞-Ps∞)J∞'^j.  Returns (means, covs, ll)."""
    from .steady import steady_smooth_tail

    params = params._replace(Q=_psd_floor(params.Q))
    st, head, su_tail, lls_tail, ll_corr, Tm = _steady_core(
        params, x, stats, None, t_star, block
    )
    means_h, covs_h, pmeans_h, pcovs_h, lls_h = head
    n_tail = su_tail.shape[0]
    s_sm_tail = steady_smooth_tail(Tm, st.J, su_tail, block=block)
    s_all, P_all, _ = _rts_scan(
        Tm,
        jnp.concatenate([means_h, s_sm_tail[:1]]),
        jnp.concatenate([covs_h, st.Ps[None]]),
        jnp.concatenate([pmeans_h, (Tm @ means_h[-1])[None]]),
        jnp.concatenate([pcovs_h, st.Pp[None]]),
    )
    W = st.Pu - st.Ps

    def dev_step(D, _):
        return st.J @ D @ st.J.T, D

    _, devs = jax.lax.scan(dev_step, W, None, length=n_tail)
    means = jnp.concatenate([s_all[:t_star], s_sm_tail])
    covs = jnp.concatenate([P_all[:t_star], st.Ps[None] + devs[::-1]])
    return means, covs, lls_h.sum() + lls_tail.sum() + ll_corr


def _steady_block_for(n_tail: int) -> int:
    """Tail-kernel block size: 0 (lax.scan of matvecs) below the length
    where the blocked einsum form pays for its W-operator setup; 32 past
    it.  DFM_STEADY_BLOCK overrides (the bench sweeps it)."""
    env = _os.environ.get("DFM_STEADY_BLOCK")
    if env is not None:
        return int(env)
    return 32 if n_tail >= 1024 else 0


def _steady_plan(params: SSMParams, mask, min_tail: int = 8):
    """Host-side dispatch decision for method="steady".

    The fast path applies when (a) the mask has a COMPLETE TAIL — from
    some period on, every series is observed (ragged heads are fine: they
    extend the exact head; interior missingness keeps the gains
    time-varying and falls back to sequential), (b) the init-params DARE
    solve converges with spectral radius ρ(Ā) < 1, and (c) the verified
    convergence horizon — padded by a 1.5x + 8 safety margin, since EM
    moves the parameters between horizon computations — leaves a tail at
    least as long as itself (the closed-form tail moment sums truncate
    infinite series whose remainder decays like ρ^{2·n_tail}).

    Returns (t_star, SteadyState at the init params, ρ(Ā)) or None when
    gated off.  t_star becomes a static scan length; this never runs
    under jit."""
    from .steady import convergence_horizon, steady_state

    m_np = np.asarray(mask)
    T = int(m_np.shape[0])
    full = m_np.all(axis=1)
    nz = np.nonzero(~full)[0]
    complete_from = 0 if nz.size == 0 else int(nz[-1]) + 1
    if complete_from >= T:
        return None
    params = params._replace(Q=_psd_floor(params.Q))
    Tm, Qs = _companion(params)
    C_inf = (params.lam.T * (1.0 / params.R)) @ params.lam
    st = steady_state(Tm, C_inf, Qs, q=params.r)
    if not bool(st.converged):
        return None
    _, P0 = _init_state(params)
    t_model, rho = convergence_horizon(
        Tm, C_inf, Qs, st, P0, t_max=max(4 * T, 64)
    )
    if t_model > T:
        return None
    t_pad = int(np.ceil(1.5 * t_model)) + 8
    # the horizon clock starts where the mask becomes complete: ragged-head
    # steps carry PARTIAL information (C_t < C∞), so the covariance there
    # can be farther from the fixed point than the complete-data recursion
    # the horizon verified — but never farther than the diffuse P0 the
    # verification started from, so complete_from + t_pad is safe
    t_star = max(complete_from + t_pad, 2)
    if T - t_star < max(t_pad, min_tail):
        return None
    return t_star, st, rho


def kalman_filter(
    params: SSMParams, x, backend: str | None = None, method: str = "sequential"
) -> KalmanResult:
    """Masked Kalman filter over a (T, N) panel with NaN missing values.

    method="sequential" is the O(T) ``lax.scan`` with the collapsed
    (Jungbacker-Koopman) measurement update; "associative" is the
    O(log T)-depth parallel-in-time formulation (models/pkalman.py) —
    identical results to float tolerance, preferable for long samples;
    "sqrt" is the full square-root array filter (`_sqrt_filter_scan`) —
    same results in f64, an order of magnitude tighter log-likelihood in
    f32 (the accuracy option; O((N+k)^3) per step); "sqrt_collapsed" is
    the collapsed square-root form (`_sqrt_filter_scan_collapsed`) —
    exact posteriors at O((r+k)^3) per step, but f32 accuracy at
    information-filter level (the compression squares the conditioning);
    "steady" runs the exact collapsed head to the Riccati convergence
    horizon, then the constant-gain factorization-free tail
    (models/steady.py) — requires a complete-tail observation pattern and
    a mixing model, and falls back to "sequential" silently when the
    dispatch (`_steady_plan`) gates it off.
    """
    if method not in _FILTER_METHODS:
        raise ValueError(f"method must be one of {_FILTER_METHODS}, got {method!r}")
    with on_backend(backend):
        # the Cholesky-based recursions need Q strictly PD; floor here so a
        # caller-supplied singular/indefinite Q degrades gracefully
        params = params._replace(Q=_psd_floor(params.Q))
        x = jnp.asarray(x)
        mask = mask_of(x)
        if method == "associative":
            from .pkalman import kalman_filter_associative

            return kalman_filter_associative(params, fillz(x), mask)
        if method == "sqrt":
            return _sqrt_filter_scan(params, fillz(x), mask)
        if method == "sqrt_collapsed":
            return _sqrt_filter_scan_collapsed(params, fillz(x), mask)
        if method == "steady":
            plan = _steady_plan(params, mask)
            if plan is not None:
                t_star = plan[0]
                xz = fillz(x)
                return _steady_filter(
                    params, xz, mask, compute_panel_stats(xz, mask),
                    t_star, _steady_block_for(xz.shape[0] - t_star),
                )
        return _filter_scan(params, fillz(x), mask)


def _rts_scan(Tm, means, covs, pmeans, pcovs, pinvs=None):
    """Rauch-Tung-Striebel backward pass (shared scan body); also returns
    lag-one covariances lag1[t] = Cov(s_{t+1}, s_t | T) for t = 0..T-2.

    `pinvs` (T, k, k) optionally supplies the predicted-covariance
    inverses the information filter already formed (`want_pinv=True`);
    the gain then needs only a matmul per step instead of a fresh
    Cholesky + two triangular solves — the per-matrix factorizations are
    the one part of the backward pass that does NOT batch well (looped
    LAPACK calls under vmap on CPU), so the EM paths feeding the batched
    multi-tenant loop always pass them."""

    def step(carry, inp):
        s_next, P_next = carry
        if pinvs is None:
            su, Pu, sp_next, Pp_next = inp
            # J = Pu Tm' Pp_next^{-1}; Pp_next PD, Pu symmetric, so solve
            # the transposed system with Cholesky instead of forming a pinv
            J = jsl.cho_solve((jnp.linalg.cholesky(Pp_next), True), Tm @ Pu).T
        else:
            su, Pu, sp_next, Pp_next, Pinv_next = inp
            J = (Pinv_next @ (Tm @ Pu)).T
        s_sm = su + J @ (s_next - sp_next)
        P_sm = Pu + J @ (P_next - Pp_next) @ J.T
        lag1 = P_next @ J.T
        return (s_sm, P_sm), (s_sm, P_sm, lag1)

    # iterate t = T-2 .. 0 pairing (filtered_t, predicted_{t+1}, smoothed_{t+1})
    last = (means[-1], covs[-1])
    inputs = (means[:-1], covs[:-1], pmeans[1:], pcovs[1:])
    if pinvs is not None:
        inputs = inputs + (pinvs[1:],)
    (_, _), (s_sm, P_sm, lag1) = jax.lax.scan(
        step, last, inputs, reverse=True, unroll=_SCAN_UNROLL
    )
    s_all = jnp.concatenate([s_sm, means[-1:]], axis=0)
    P_all = jnp.concatenate([P_sm, covs[-1:]], axis=0)
    return s_all, P_all, lag1


@jax.jit
def _smoother_scan(params: SSMParams, filt: KalmanResult, pinvs=None):
    """RTS backward pass for the SSMParams model (shared body: _rts_scan)."""
    Tm, _ = _companion(params)
    return _rts_scan(
        Tm, filt.means, filt.covs, filt.pred_means, filt.pred_covs,
        pinvs=pinvs,
    )


def kalman_smoother(
    params: SSMParams, x, backend: str | None = None, method: str = "sequential"
):
    """Kalman smoother: returns (smoothed_means, smoothed_covs, loglik).

    The `backend={"cpu","tpu"}` kwarg follows the north-star API
    (BASELINE.json): same program, device chosen by flag.  method as in
    `kalman_filter`; "associative" also parallelizes the backward pass;
    "sqrt" runs the RTS pass on the square-root filter's outputs (the
    forward pass dominates the error, so f32 accuracy improves with it).
    """
    if method not in _FILTER_METHODS:
        raise ValueError(f"method must be one of {_FILTER_METHODS}, got {method!r}")
    with on_backend(backend):
        params = params._replace(Q=_psd_floor(params.Q))
        x = jnp.asarray(x)
        if method == "associative":
            from .pkalman import kalman_smoother_associative

            means, covs, ll, _ = kalman_smoother_associative(
                params, fillz(x), mask_of(x)
            )
            return means, covs, ll
        if method == "steady":
            mask = mask_of(x)
            plan = _steady_plan(params, mask)
            if plan is not None:
                t_star = plan[0]
                xz = fillz(x)
                return _steady_smoother(
                    params, xz, mask, compute_panel_stats(xz, mask),
                    t_star, _steady_block_for(xz.shape[0] - t_star),
                )
            method = "sequential"  # gated off: exact fallback
        filt_fn = {
            "sqrt": _sqrt_filter_scan,
            "sqrt_collapsed": _sqrt_filter_scan_collapsed,
            "sequential": _filter_scan,
        }[method]
        filt = filt_fn(params, fillz(x), mask_of(x))
        means, covs, _ = _smoother_scan(params, filt)
        return means, covs, filt.loglik


# ---------------------------------------------------------------------------
# EM
# ---------------------------------------------------------------------------


def _solve_loadings_and_R(S, Sx, Sxx, n_i):
    """Batched loading solve + idiosyncratic-variance update from per-series
    sufficient statistics (shared by the ssm and mixed-frequency M-steps):

        lam_i = S_i^-1 Sx_i,
        R_i   = (Sxx_i - 2 lam_i'Sx_i + lam_i'S_i lam_i) / n_i.

    S_i is PD whenever a series has any observation (it sums PD smoothed
    second moments), so the solve is Cholesky with an eps-scaled trace
    jitter; all-missing series (S_i = 0, Sx_i = 0) land on lam_i = 0 and
    the n_i floor keeps R_i finite (then floored to 1e-8).
    """
    dtype = Sx.dtype
    r = Sx.shape[1]
    eps = jnp.asarray(jnp.finfo(dtype).eps, dtype)
    jitter = (
        eps * jnp.maximum(jnp.trace(S, axis1=1, axis2=2), 1.0)[:, None, None]
        * jnp.eye(r, dtype=dtype)
    )
    L = jnp.linalg.cholesky(S + jitter)
    lam = jax.vmap(lambda Lc, b: jsl.cho_solve((Lc, True), b))(L, Sx)
    R = (
        Sxx - 2.0 * (lam * Sx).sum(1)
        + jnp.einsum("ir,irs,is->i", lam, S, lam)
    ) / jnp.maximum(n_i, 1.0)
    return lam, jnp.maximum(R, 1e-8)


def _em_m_step(params: SSMParams, x, m, s_sm, P_sm, lag1, stats=None):
    """Closed-form M-step from smoothed first/second moments (shared by the
    sequential-scan and associative E-steps).

    Bandwidth-lean formulation: the panel enters through exactly three
    contractions — Sff_i = sum_t m_it E[f f'] (one (N, T) @ (T, r^2)
    matmul: E[f f'] = E f E f' + Pf folds the covariance correction into
    the same product), Sxf_i = sum_t x_it E[f_t]' and Sxx_i = sum_t x_it^2
    (x is zero-filled at missing, so the mask weighting is already baked
    in).  R then follows from the same statistics,

        R_i = (Sxx_i - 2 lam_i'Sxf_i + lam_i'Sff_i lam_i) / n_i,

    with no residual-panel materialization.  Sff is PD whenever a series
    has any observation (Pf is PD), so the batched solve is Cholesky, not
    the eigh pseudo-inverse; all-missing series get an eps-jitter solve
    that lands on lam_i = 0 (b_i = 0).

    `stats` (PanelStats) supplies the loop-invariant pieces — transposed
    copies for the fast GEMM orientation plus Sxx / n_i — when the caller
    runs many iterations on one panel (estimate_dfm_em does); without it
    the same quantities are formed in place.
    """
    r, p = params.r, params.p
    f = s_sm[:, :r]  # E[f_t | T]
    Pf = P_sm[:, :r, :r]  # Var(f_t | T)

    Tn = x.shape[0]
    iu, iv, unpack = _sym_pack_idx(r)
    Eff_u = f[:, iu] * f[:, iv] + Pf[:, iu, iv]  # packed E[f f' | T]
    if stats is None:
        mT, xT = m.T, x.T
        Sxx = (x * x).sum(axis=0)  # (N,)
        n_i = m.sum(axis=0)
    else:
        mT, xT, Sxx, n_i = stats.mT, stats.xT, stats.Sxx, stats.n_i
    if stats is not None and stats.mT16 is not None:
        Sff = _bf16_gemm("nt,tc->nc", stats.mT16, Eff_u, x.dtype)[
            :, unpack
        ].reshape(-1, r, r)
        Sxf = _bf16_gemm("nt,tr->nr", stats.xT16, f, x.dtype)
    else:
        Sff = (mT @ Eff_u)[:, unpack].reshape(-1, r, r)  # (N, r, r)
        Sxf = xT @ f  # (N, r); m*x == x (zero-filled)
    lam, R = _solve_loadings_and_R(Sff, Sxf, Sxx, n_i)

    # --- factor VAR blocks + Q from smoothed second moments ---
    tw = None if stats is None else stats.tw
    S11, S00, S10, Tn_eff = _var_moments(s_sm, P_sm, lag1, r, Tn, tw)
    Ak = S10 @ jnp.linalg.pinv(S00, hermitian=True)  # (r, k)
    Q = _psd_floor((S11 - Ak @ S10.T) / (Tn_eff - 1))
    A = jnp.stack([Ak[:, i * r : (i + 1) * r] for i in range(p)])
    return SSMParams(lam, R, A, Q)


def _var_moments(s_sm, P_sm, lag1, r: int, Tn: int, tw=None):
    """Smoothed second-moment blocks of the factor-VAR regression.

    This is the one EM statistic that sums over TIME without an
    observation mask, so on a shape-bucketed panel (utils.compile) the
    padded trailing periods — whose smoothed states are pure forecasts —
    would bias A and Q.  `tw` (PanelStats.tw, 1 on real periods) weights
    each transition pair by the validity of its LATER period (padding is a
    contiguous suffix, so tw[t] = 1 implies tw[t-1] = 1) and replaces the
    Tn divisor with the real-period count; tw=None is the exact
    legacy program, term for term.
    """
    s1, s0 = s_sm[1:, :r], s_sm[:-1]
    if tw is None:
        S11 = jnp.einsum("tr,ts->rs", s1, s1) + P_sm[1:, :r, :r].sum(axis=0)
        S00 = jnp.einsum("tk,tl->kl", s0, s0) + P_sm[:-1].sum(axis=0)
        S10 = jnp.einsum("tr,tk->rk", s1, s0) + lag1[:, :r, :].sum(axis=0)
        return S11, S00, S10, Tn
    w1 = tw[1:]
    S11 = (jnp.einsum("t,tr,ts->rs", w1, s1, s1)
           + jnp.einsum("t,trs->rs", w1, P_sm[1:, :r, :r]))
    S00 = (jnp.einsum("t,tk,tl->kl", w1, s0, s0)
           + jnp.einsum("t,tkl->kl", w1, P_sm[:-1]))
    S10 = (jnp.einsum("t,tr,tk->rk", w1, s1, s0)
           + jnp.einsum("t,trk->rk", w1, lag1[:, :r, :]))
    return S11, S00, S10, tw.sum()


@jax.jit
def em_step(params: SSMParams, x, mask):
    """One EM iteration (sequential-scan E-step + closed-form M-step);
    returns (new_params, loglik of the *current* params)."""
    m = mask.astype(x.dtype)
    # guard caller-supplied params the same way kalman_filter does: the
    # Cholesky recursions need Q strictly PD (M-step outputs are pre-floored,
    # so for internal EM loops this is a no-op re-floor)
    params = params._replace(Q=_psd_floor(params.Q))
    filt, pinvs = _filter_scan(params, x, mask, want_pinv=True)
    s_sm, P_sm, lag1 = _smoother_scan(params, filt, pinvs=pinvs)
    return _em_m_step(params, x, m, s_sm, P_sm, lag1), filt.loglik


@jax.jit
def em_step_stats(params: SSMParams, x, mask, stats: PanelStats):
    """`em_step` with the loop-invariant PanelStats supplied by the caller:
    identical update, but the per-iteration cost excludes the transposed
    panel copies and data sums — the production path of
    `estimate_dfm_em(method="sequential")` and the large-panel benchmark.
    """
    params = params._replace(Q=_psd_floor(params.Q))
    filt, pinvs = _filter_scan(params, x, mask, stats=stats, want_pinv=True)
    s_sm, P_sm, lag1 = _smoother_scan(params, filt, pinvs=pinvs)
    return (
        _em_m_step(params, x, stats.m, s_sm, P_sm, lag1, stats=stats),
        filt.loglik,
    )


@jax.jit
def em_step_stats_bulk(params: SSMParams, x, mask, stats: PanelStats):
    """`em_step_stats` with the idiosyncratic variances floored at 1e-3:
    the mixed-precision bulk map.  The collapse weights the panel by 1/R,
    so bf16 operand error is amplified by max_i(lam_i^2 / R_i) — a series
    fit nearly exactly (R_i -> 0) turns rounding into likelihood garbage.
    Flooring R bounds the amplification; the bulk phase converges to the
    floored map's fixed point and the exact polish phase then removes the
    floor.  Used only by `estimate_dfm_em(gram_dtype=...)`."""
    return em_step_stats(
        params._replace(
            R=jnp.maximum(params.R, jnp.asarray(1e-3, params.R.dtype))
        ),
        x,
        mask,
        stats,
    )


@jax.jit
def em_step_sqrt(params: SSMParams, x, mask):
    """`em_step` with the square-root array E-step: in f32 the convergence
    test consumes a log-likelihood an order of magnitude more accurate
    (see `_sqrt_filter_scan`) — the accuracy-first EM variant for chips
    without f64."""
    m = mask.astype(x.dtype)
    params = params._replace(Q=_psd_floor(params.Q))
    filt = _sqrt_filter_scan(params, x, mask)
    s_sm, P_sm, lag1 = _smoother_scan(params, filt)
    return _em_m_step(params, x, m, s_sm, P_sm, lag1), filt.loglik


@jax.jit
def em_step_sqrt_collapsed(params: SSMParams, x, mask):
    """`em_step` with the collapsed square-root E-step
    (`_sqrt_filter_scan_collapsed`): array-form state recursion at
    O((r+k)^3) per step — the sqrt option that stays affordable on wide
    panels, at information-filter-level f32 likelihood accuracy."""
    m = mask.astype(x.dtype)
    params = params._replace(Q=_psd_floor(params.Q))
    filt = _sqrt_filter_scan_collapsed(params, x, mask)
    s_sm, P_sm, lag1 = _smoother_scan(params, filt)
    return _em_m_step(params, x, m, s_sm, P_sm, lag1), filt.loglik


@jax.jit
def em_step_assoc_fused(params: SSMParams, x, mask):
    """`em_step_assoc` with FUSED collapsed elements: the N-dim panel is
    collapsed ONCE per step into the O(r^2) payload (C, b, ld_R, xRx)
    and the scan elements are built from it at O(r^3) per step — element
    construction never touches N again, so the associative variant's
    per-element cost matches the sequential collapsed path instead of
    paying O(N r) per element (the regression that made `em_step_assoc`
    LOSE to sequential on wide panels)."""
    from .pkalman import kalman_smoother_associative_collapsed

    m = mask.astype(x.dtype)
    params = params._replace(Q=_psd_floor(params.Q))
    C, b, ld_R, xRx, n_obs = _collapse_obs(params.lam, params.R, x, m)
    s_sm, P_sm, ll, lag1 = kalman_smoother_associative_collapsed(
        params, C, b, ld_R, xRx, n_obs
    )
    return _em_m_step(params, x, m, s_sm, P_sm, lag1), ll


@jax.jit
def em_step_assoc(params: SSMParams, x, mask):
    """`em_step` with the parallel-in-time (associative-scan) E-step
    (models.pkalman): log-depth instead of T-depth recursions — the
    TPU-friendly shape when the sequential scan's per-step latency
    dominates.

    Panels wider than `LARGE_N_THRESHOLD` auto-dispatch (static shape,
    resolved at trace time) to `em_step_assoc_fused`, whose elements are
    built from the collapsed O(r^2) payload instead of the N-dim
    observation model — same public name, same results to fp tolerance,
    no O(N r) per-element work."""
    if x.shape[1] > LARGE_N_THRESHOLD:
        from .pkalman import kalman_smoother_associative_collapsed

        m = mask.astype(x.dtype)
        params = params._replace(Q=_psd_floor(params.Q))
        C, b, ld_R, xRx, n_obs = _collapse_obs(params.lam, params.R, x, m)
        s_sm, P_sm, ll, lag1 = kalman_smoother_associative_collapsed(
            params, C, b, ld_R, xRx, n_obs
        )
        return _em_m_step(params, x, m, s_sm, P_sm, lag1), ll
    from .pkalman import kalman_smoother_associative

    m = mask.astype(x.dtype)
    params = params._replace(Q=_psd_floor(params.Q))
    s_sm, P_sm, ll, lag1 = kalman_smoother_associative(params, x, mask)
    return _em_m_step(params, x, m, s_sm, P_sm, lag1), ll


class SteadyEMState(NamedTuple):
    """EM-loop carry of the steady path: the model parameters plus the
    previous iteration's steady predicted covariance Pp∞ — the warm start
    that turns each doubling solve into 2-3 iterations instead of a cold
    6-8 — and the cumulative doubling count (telemetry `riccati_iters`).
    Rides `run_em_loop`'s opaque params pytree exactly as
    emaccel.SquaremState does; `estimate_dfm_em` wraps and unwraps it."""

    params: SSMParams
    Pp: jnp.ndarray  # (k, k) previous steady predicted covariance
    riccati_iters: jnp.ndarray  # () i32 cumulative doubling steps


def _em_step_steady_impl(
    state: SteadyEMState, x, mask, stats: PanelStats, t_star: int, block: int
):
    """One steady-path EM iteration: exact head + constant-gain tail
    E-step, closed-form tail covariance moments, shared M-step solves.

    The E-step sufficient statistics split at t*: head sums run over
    materialized smoothed paths exactly as `_em_m_step` does, tail sums
    use Σ_{t>=t*} P_sm_t = n_tail·Ps∞ + S_dev (S_dev the right-boundary
    deviation sum; the series truncation error decays like ρ^{2·n_tail},
    which `_steady_plan` keeps below tolerance), the endpoint identity
    P_sm_{T-1} = Pu∞, and Σ lag1 = (Σ_{u>t*} P_sm_u) J∞' — all O(1) in T.
    """
    from .steady import steady_smooth_tail

    params = state.params._replace(Q=_psd_floor(state.params.Q))
    r, p = params.r, params.p
    Tn = x.shape[0]
    st, head, su_tail, lls_tail, ll_corr, Tm = _steady_core(
        params, x, stats, state.Pp, t_star, block
    )
    means_h, covs_h, pmeans_h, pcovs_h, lls_h = head
    n_tail = Tn - t_star

    # --- backward pass: tail means by constant-gain recursion, head by the
    # exact RTS scan closed at the boundary with (s_sm_{t*}, Ps∞) ---
    s_sm_tail = steady_smooth_tail(Tm, st.J, su_tail, block=block)
    s_all, P_head, lag1_h = _rts_scan(
        Tm,
        jnp.concatenate([means_h, s_sm_tail[:1]]),
        jnp.concatenate([covs_h, st.Ps[None]]),
        jnp.concatenate([pmeans_h, (Tm @ means_h[-1])[None]]),
        jnp.concatenate([pcovs_h, st.Pp[None]]),
    )
    f_sm = jnp.concatenate([s_all[:t_star], s_sm_tail])  # (T, k)
    P_head = P_head[:t_star]

    # --- loadings/R: the (N, T) Gram contraction shrinks to (N, t*) ---
    iu, iv, unpack = _sym_pack_idx(r)
    f = f_sm[:, :r]
    Eff_head = f[:t_star, iu] * f[:t_star, iv] + P_head[:, :r, :r][:, iu, iv]
    Psum_tail = n_tail * st.Ps + st.Sdev  # Σ_{t>=t*} P_sm_t, closed form
    eff_tail = (f[t_star:, iu] * f[t_star:, iv]).sum(axis=0) + Psum_tail[
        :r, :r
    ][iu, iv]
    Sff = (stats.mT[:, :t_star] @ Eff_head + eff_tail[None, :])[
        :, unpack
    ].reshape(-1, r, r)
    Sxf = stats.xT @ f
    lam, R = _solve_loadings_and_R(Sff, Sxf, stats.Sxx, stats.n_i)

    # --- factor VAR moments: head sums + closed-form tail constants ---
    s1, s0_ = f_sm[1:, :r], f_sm[:-1]
    S11 = (
        jnp.einsum("tr,ts->rs", s1, s1)
        + P_head[1:, :r, :r].sum(axis=0)
        + Psum_tail[:r, :r]
    )
    # Σ_{t<=T-2} P_sm: the tail sum minus the exact endpoint P_sm_{T-1} = Pu∞
    S00 = (
        jnp.einsum("tk,tl->kl", s0_, s0_)
        + P_head.sum(axis=0)
        + Psum_tail
        - st.Pu
    )
    # tail lag-one sum: Σ_{t>=t*} Cov(s_{t+1}, s_t) = (Σ_{u>t*} P_sm_u) J∞'
    S10 = (
        jnp.einsum("tr,tk->rk", s1, s0_)
        + lag1_h[:, :r, :].sum(axis=0)
        + ((Psum_tail - st.Ps) @ st.J.T)[:r, :]
    )
    Ak = S10 @ jnp.linalg.pinv(S00, hermitian=True)
    Q = _psd_floor((S11 - Ak @ S10.T) / (Tn - 1))
    A = jnp.stack([Ak[:, i * r : (i + 1) * r] for i in range(p)])

    ll = lls_h.sum() + lls_tail.sum() + ll_corr
    return (
        SteadyEMState(
            SSMParams(lam, R, A, Q),
            st.Pp,
            state.riccati_iters + st.riccati_iters,
        ),
        ll,
    )


@lru_cache(maxsize=None)
def _steady_step_for(t_star: int, block: int = 0):
    """The jitted steady EM step specialized to a static convergence
    horizon (the head length is a scan SHAPE) and tail block size.
    lru_cached so repeated estimates at one horizon share a traced
    program, and named per specialization so `run_em_loop`'s AOT-registry
    statics key (utils.compile.aot_statics uses __module__ + __qualname__)
    distinguishes horizons."""

    def step(state: SteadyEMState, x, mask, stats: PanelStats):
        return _em_step_steady_impl(state, x, mask, stats, t_star, block)

    step.__name__ = step.__qualname__ = f"em_step_steady_t{t_star}_b{block}"
    step.__module__ = __name__
    return jax.jit(step)


def em_step_steady(state, x, mask, stats: PanelStats, t_star: int, block: int = 0):
    """One steady-path EM iteration (see `_em_step_steady_impl`): exact
    head of `t_star` steps, constant-gain factorization-free tail, E-step
    tail moments in closed form.  `state` is a `SteadyEMState`; a bare
    `SSMParams` is wrapped with a cold-start carry.  Returns
    (SteadyEMState, loglik) — `run_em_loop`-compatible via
    `_steady_step_for(t_star, block)`."""
    if not isinstance(state, SteadyEMState):
        k = state.r * state.p
        state = SteadyEMState(
            params=state,
            Pp=jnp.zeros((k, k), state.lam.dtype),
            riccati_iters=jnp.asarray(0, jnp.int32),
        )
    return _steady_step_for(int(t_star), int(block))(state, x, mask, stats)


def _resolve_mesh_hosts(hosts: int) -> int:
    """Resolve the `hosts` knob of a sharded-step factory: 0/None means
    "the runtime's process count" (1 in a plain single-process session,
    >1 only after `parallel.distributed.initialize_distributed`), and
    anything <= 1 collapses to the flat single-host mesh."""
    if hosts is None or hosts == 0:
        hosts = jax.process_count()
    return max(int(hosts), 1)


def _sharded_step_for(n_shards: int, hosts: int = 0):
    """The cross-section-sharded EM step over an N-axis data mesh of
    `n_shards` devices — same (params, x, mask, stats) -> (params,
    loglik) contract as `em_step_stats`, N must be a shard multiple
    (`estimate_dfm_em(n_shards=)` pads with inert series first).

    `hosts=0` (default) resolves to `jax.process_count()`: a plain
    single-process session gets the flat single-host ``("data",)`` mesh
    (byte-identical program to pre-multi-host builds), while a
    `jax.distributed`-initialized runtime transparently gets the
    process-spanning ``("dcn", "ici")`` mesh with the hierarchical
    reduction.  Pass `hosts` explicitly to force a topology (the tier-1
    proxy runs hosts=2 on the single-process 8-device CPU mesh).

    Work split per iteration: the Jungbacker-Koopman collapse and the
    M-step panel GEMMs — everything O(N) — run on local shards; the packed
    collapse payload is all-reduced once per iteration (flat ring on one
    host; ring-within-ICI then one cross-host DCN psum on many — see
    `ops.pallas_gram.hierarchical_allreduce`); the O(k^3) filter/smoother
    scans and the factor-VAR moments are N-free and run replicated; the
    loading/R solves are per-series and stay shard-local.  With the
    guarded while-loop outside, a whole sharded EM run executes with ONE
    cross-device reduction and ZERO host syncs per iteration.

    This dispatcher is a plain function so `f(2)`, `f(2, 0)` and
    `f(2, hosts=0)` all hit ONE cache entry (functools.lru_cache keys
    them differently, which would break the resolve-identity pins in
    tests/test_transform_stack.py); the lru_cached impl is keyed on the
    resolved (n_shards, hosts) pair."""
    return _sharded_step_impl(int(n_shards), _resolve_mesh_hosts(hosts))


@lru_cache(maxsize=None)
def _sharded_step_impl(n_shards: int, hosts: int):
    """lru_cached and named per (shard count, host count) so
    `run_em_loop`'s AOT-registry statics key (utils.compile.aot_statics
    uses __module__ + __qualname__) is stable across processes, like
    `_steady_step_for`.  hosts<=1 keeps the exact pre-multi-host name
    (`em_step_sharded_d{n}`) and program."""
    from ..ops.pallas_gram import hierarchical_allreduce, ring_allreduce
    from ..parallel import shard_map_nocheck
    from ..parallel.mesh import P, data_mesh

    mesh = data_mesh(n_shards, hosts=hosts)
    if hosts > 1:
        dax = ("dcn", "ici")
        n_ici = n_shards // hosts

        def _reduce(payload):
            return hierarchical_allreduce(payload, "ici", "dcn", n_ici)

        name = f"em_step_sharded_d{n_shards}_h{hosts}"
    else:
        dax = "data"

        def _reduce(payload):
            return ring_allreduce(payload, "data", n_shards)

        name = f"em_step_sharded_d{n_shards}"

    def step(params: SSMParams, x, mask, stats: PanelStats):
        del mask  # collapse statistics already carry the mask
        params = params._replace(Q=_psd_floor(params.Q))
        payload, llc = _collapse_obs_stats_partial(params.lam, params.R, x, stats)
        payload = _reduce(payload)
        llc = jax.lax.psum(llc, dax)
        C, b, ld_R = _unpack_collapsed(payload, params.r)
        filt, pinvs = _filter_scan_collapsed_stats(
            params, C, b, ld_R, stats.n_obs, llc, want_pinv=True
        )
        s_sm, P_sm, lag1 = _smoother_scan(params, filt, pinvs=pinvs)
        return (
            _em_m_step(params, x, stats.m, s_sm, P_sm, lag1, stats=stats),
            filt.loglik,
        )

    step.__name__ = step.__qualname__ = name
    step.__module__ = __name__

    params_spec = SSMParams(lam=P(dax, None), R=P(dax), A=P(), Q=P())
    stats_spec = PanelStats(
        m=P(None, dax), xT=P(dax, None), mT=P(dax, None),
        Sxx=P(dax), n_i=P(dax), n_obs=P(),
        m16=None, x16=None, mT16=None, xT16=None, tw=P(),
    )
    return jax.jit(
        shard_map_nocheck(
            step,
            mesh=mesh,
            in_specs=(params_spec, P(None, dax), P(None, dax), stats_spec),
            out_specs=(params_spec, P()),
        )
    )


def em_step_sharded(params: SSMParams, x, mask, stats: PanelStats, n_shards: int):
    """One sharded EM iteration (see `_sharded_step_for`)."""
    return _sharded_step_for(int(n_shards))(params, x, mask, stats)


class EMResults(NamedTuple):
    params: SSMParams
    factors: jnp.ndarray  # (T, r) smoothed factors (standardized units)
    factor_covs: jnp.ndarray  # (T, r, r)
    loglik_path: np.ndarray
    n_iter: int
    stds: jnp.ndarray  # per-series standardization scale
    means: jnp.ndarray
    trace: object | None = None  # ConvergenceTrace when collect_path=True
    # actual tolerance break of the EM loop (NOT the n_iter < cap proxy,
    # which misreported a run converging on its final permitted iteration)
    converged: bool = False
    health: int = 0  # final utils.guards health code (0 = healthy)


def _init_params_from_als(
    data, inclcode, initperiod, lastperiod, config, xz, m_arr
) -> SSMParams:
    """Initialize EM from the non-parametric ALS fit: VAR blocks from the
    factor VAR, loadings/R from masked OLS of the standardized panel on the
    ALS factors."""
    res = estimate_dfm(data, inclcode, initperiod, lastperiod, config)
    r = config.nfac_u
    p = config.n_factorlag
    b = res.var.betahat[1:].T  # (r, r*p) companion top rows
    A = jnp.stack([b[:, i * r : (i + 1) * r] for i in range(p)])
    Q = _psd_floor(res.var.seps)
    fw = res.factor[initperiod : lastperiod + 1]
    W = m_arr.astype(xz.dtype)
    Sff = jnp.einsum("ti,tr,ts->irs", W, fw, fw)
    Sxf = jnp.einsum("ti,tr->ir", W * xz, fw)
    lam0 = jax.vmap(solve_normal)(Sff, Sxf)
    resid0 = jnp.where(m_arr, xz - fw @ lam0.T, 0.0)
    R0 = jnp.maximum((resid0**2).sum(axis=0) / W.sum(axis=0), 1e-6)
    return SSMParams(lam0, R0, A, Q)


def _window_panel(data, inclcode, initperiod: int, lastperiod: int):
    """Shared estimator prologue: slice the included panel to the window,
    standardize, mask/zero-fill, and keep the original per-series means for
    reconstruction.  Returns (xz, m_arr, stds, n_mean)."""
    est = data[:, inclcode == 1]
    xw = est[initperiod : lastperiod + 1]
    xstd, stds = standardize_data(xw)
    m_arr = mask_of(xstd)
    xz = fillz(xstd)
    mw = mask_of(xw)
    n_mean = (fillz(xw) * mw).sum(axis=0) / mw.sum(axis=0)
    return xz, m_arr, stds, n_mean


def _project_params(params: SSMParams) -> SSMParams:
    """Feasibility projection after SQUAREM extrapolation: extrapolated
    idiosyncratic variances are floored positive and the factor innovation
    covariance is symmetrized/eigenvalue-floored so the Cholesky filter
    stays on its fast path; A is left free — an explosive extrapolation
    shows up as a loglik drop and the acceleration guard rejects it."""
    return params._replace(
        R=jnp.maximum(params.R, jnp.asarray(1e-8, params.R.dtype)),
        Q=_psd_floor(params.Q),
    )


def estimate_dfm_em(
    data,
    inclcode,
    initperiod: int,
    lastperiod: int,
    config: DFMConfig = DFMConfig(nfac_u=4),
    max_em_iter: int = 200,
    tol: float = 1e-6,
    backend: str | None = None,
    collect_path: bool = False,
    method: str = "sequential",
    checkpoint_path: str | None = None,
    checkpoint_every: int = 25,
    accel: str | None = None,
    gram_dtype: str | None = None,
    bucket=None,
    n_shards: int | None = None,
    t_blocks: int | None = None,
) -> EMResults:
    """State-space DFM via EM on the standardized included panel
    (BASELINE.json config 2: `State-space DFM via EM + Kalman smoother`).

    Converges when the relative log-likelihood improvement drops below tol.
    The convergence loop runs on device (`emloop.run_em_loop`);
    collect_path=True switches to a host loop whose per-iteration wall
    clock is recorded in EMResults.trace.  method="associative" swaps the
    E-step for the parallel-in-time scans (`em_step_assoc`); method="sqrt"
    uses the square-root array E-step (`em_step_sqrt`, f32-accurate);
    method="steady" runs the steady-state fast path (`em_step_steady`:
    exact head to the Riccati convergence horizon, constant-gain
    factorization-free tail, closed-form tail covariance moments, with
    the previous iteration's Pp∞ carried through the loop to warm-start
    each DARE solve) when the panel has a complete-tail observation
    pattern, and falls back to the sequential program otherwise
    (telemetry records `steady_gated`).

    gram_dtype="bfloat16" (sequential method only) runs a mixed-precision
    bulk phase first — the iteration's four panel GEMMs (collapse C/b,
    M-step Sff/Sxf) on bf16 operands with f32 accumulation, at a loosened
    tolerance — then finishes with exact iterations under the caller's
    tol from the bulk fixed point.  The phases share max_em_iter; a
    non-finite bulk outcome falls back to the exact path from the
    original init.

    accel="squarem" wraps the chosen E/M step in one SQUAREM extrapolation
    cycle per loop iteration (`emaccel.squarem`: three EM-map evaluations,
    loglik-guarded, never worse than two plain EM steps) — n_iter then
    counts cycles, and the same fixed point is reached in materially fewer
    map evaluations on slow-converging (persistent-factor) panels.

    bucket (sequential method only) pads the panel up to a shape bucket
    (utils.compile) so ONE compiled EM executable serves every panel in
    the bucket: None reads the ``DFM_SHAPE_BUCKETS`` env default, True
    uses the default bucket tables, (t_buckets, n_buckets) is explicit.
    Padding is exact — padded cells are fully masked (inert in every
    observation statistic) and `PanelStats.tw` keeps padded periods out
    of the factor-VAR moments; results match the unbucketed run to
    numerical precision (pinned by tests/test_compile_cache.py).

    n_shards > 1 (sequential method only) shards the cross-section over a
    ``("data",)`` device mesh (`_sharded_step_for`): the panel is padded
    with inert series up to a shard multiple (`parallel.mesh.series_pad`),
    the O(N) collapse/M-step work runs shard-local with one ring
    all-reduce per iteration, and the recovery ladder demotes a tripped
    sharded run to the exact single-device sequential step.  Parity with
    the unsharded run is pinned at 1e-10 in tests/test_sharding.py.

    t_blocks > 1 (sequential method only) runs the E-step PARALLEL IN
    TIME on the collapsed statistics (models/emtime): each device owns a
    contiguous time slab running the cheap sequential combine recursion,
    and only O(r^2) slab-boundary elements cross devices
    (`parallel.timescan.sharded_scan`).  Composes with n_shards into the
    3-D hosts x time x series mesh (`parallel.mesh.data_mesh`); parity
    with the sequential run is pinned at 1e-10 in
    tests/test_timeparallel.py.
    """
    from ..utils.compile import (
        bucket_shape,
        configure_compilation_cache,
        pad_panel,
        pad_ssm_params,
        resolve_buckets,
        unpad_ssm_params,
    )

    configure_compilation_cache()
    buckets = resolve_buckets(bucket)
    if method not in _FILTER_METHODS:
        raise ValueError(f"method must be one of {_FILTER_METHODS}, got {method!r}")
    if accel not in (None, "squarem"):
        raise ValueError(f"accel must be None or 'squarem', got {accel!r}")
    if accel is not None and method == "steady":
        raise ValueError(
            "accel is not combinable with method='steady': the steady EM "
            "carry (SteadyEMState: params + warm-start Pp∞ + solver "
            "counters) is not an extrapolable parameter vector"
        )
    if gram_dtype not in (None, "bfloat16"):
        raise ValueError(
            f"gram_dtype must be None or 'bfloat16', got {gram_dtype!r}"
        )
    if gram_dtype is not None and method != "sequential":
        raise ValueError("gram_dtype requires method='sequential' (the stats path)")
    if gram_dtype is not None and checkpoint_path is not None:
        raise ValueError("gram_dtype is not combinable with checkpoint_path")
    if buckets is not None and method != "sequential":
        raise ValueError(
            "bucket requires method='sequential' (the PanelStats path "
            "carries the time-validity weight padding needs)"
        )
    ns = int(n_shards) if n_shards is not None else 0
    if ns > 1:
        if method != "sequential":
            raise ValueError(
                "n_shards requires method='sequential' (the stats path)"
            )
        if gram_dtype is not None:
            raise ValueError(
                "n_shards is not combinable with gram_dtype: the bf16 "
                "panel twins are not sharded"
            )
        if ns > jax.device_count():
            raise ValueError(
                f"n_shards={ns} exceeds the {jax.device_count()} visible "
                "devices"
            )
        if jax.process_count() > 1 and ns % jax.process_count() != 0:
            raise ValueError(
                f"n_shards={ns} must be a multiple of "
                f"jax.process_count()={jax.process_count()} so every host "
                "owns the same number of local shards"
            )
    tb = int(t_blocks) if t_blocks is not None else 0
    if tb > 1:
        if method != "sequential":
            raise ValueError(
                "t_blocks requires method='sequential' (the collapsed "
                "stats path feeds the time-sharded fused smoother)"
            )
        if gram_dtype is not None:
            raise ValueError(
                "t_blocks is not combinable with gram_dtype: the bf16 "
                "bulk phase is not time-sharded"
            )
        if tb * max(ns, 1) > jax.device_count():
            raise ValueError(
                f"t_blocks={tb} x n_shards={max(ns, 1)} exceeds the "
                f"{jax.device_count()} visible devices"
            )
    from ..utils.telemetry import run_record

    with on_backend(backend), run_record(
        "estimate_dfm_em",
        config={
            "method": method, "accel": accel, "gram_dtype": gram_dtype,
            "tol": tol, "max_em_iter": max_em_iter,
            "checkpointed": checkpoint_path is not None,
        },
    ) as rec:
        data = jnp.asarray(data)
        inclcode = np.asarray(inclcode)
        xz, m_arr, stds, n_mean = _window_panel(
            data, inclcode, initperiod, lastperiod
        )

        r = config.nfac_u
        params = _init_params_from_als(
            data, inclcode, initperiod, lastperiod, config, xz, m_arr
        )

        from . import transforms as tfm
        from .emloop import run_em_loop

        T0, N0 = xz.shape
        rec.set(shapes={"T": T0, "N": N0, "r": r, "p": config.n_factorlag})
        # recovery-ladder demotion target (emloop guarded path): the exact
        # sequential step the tripped method falls back to, with the loop
        # state unwrapped to its bare parameter pytree.  Steps are chosen
        # by RESOLVING a transform stack (models/transforms) — resolve
        # returns the same module-level jitted objects this function used
        # to name directly, so the dispatched programs (and their AOT
        # statics keys) are byte-identical to the pre-stack selection.
        fallback_step = None
        fallback_unwrap = None
        if method == "sequential":
            step = tfm.resolve(tfm.Stack("ssm")).step
            if buckets is not None or ns > 1:
                # pad up to the bucket and/or a shard multiple; even at
                # exact size the padded program carries tw, so every panel
                # in the bucket shares ONE compiled executable (same
                # avals, same pytree), and every sharded panel splits
                # evenly over the data mesh
                if buckets is not None:
                    Tb, Nb = bucket_shape(T0, N0, *buckets)
                else:
                    Tb, Nb = T0, N0
                if ns > 1:
                    from ..parallel.mesh import series_pad

                    Nb = series_pad(Nb, ns)
                if buckets is not None:
                    rec.set(bucket=[Tb, Nb])
                xz_b, m_b, tw = pad_panel(xz, m_arr, Tb, Nb)
                params = pad_ssm_params(params, Nb)
                stats = compute_panel_stats(xz_b, m_b)._replace(tw=tw)
                xz, m_arr = xz_b, m_b
            else:
                stats = compute_panel_stats(xz, m_arr)
            if ns > 1 or tb > 1:
                # a tripped sharded / time-sharded run demotes to the
                # exact single-device sequential step: same
                # (xz, mask, stats) args
                axes = []
                if tb > 1:
                    axes.append(tfm.time_shard(tb))
                if ns > 1:
                    axes.append(tfm.shard(ns))
                res_t = tfm.resolve(tfm.Stack("ssm", tuple(axes)))
                step, fallback_step = res_t.step, res_t.fallback_step
                nproc = jax.process_count()
                if nproc > 1:
                    # multi-process SPMD: hand the loop host (numpy)
                    # arrays — identical on every process by construction
                    # — so jit can shard them onto the global
                    # ("dcn", "ici") mesh (a committed single-device
                    # array cannot be resharded across processes)
                    xz, m_arr = np.asarray(xz), np.asarray(m_arr)
                    params = jax.tree.map(np.asarray, params)
                    stats = jax.tree.map(np.asarray, stats)
                    shape = [nproc]
                    if tb > 1:
                        shape.append(tb)
                    shape.append(max(ns, nproc) // nproc)
                    rec.set(
                        mesh_shape=shape, sharded=ns > 1,
                        process_count=nproc,
                    )
                else:
                    shape = ([1, tb, max(ns, 1)] if tb > 1 else [ns])
                    rec.set(mesh_shape=shape, sharded=ns > 1)
                if tb > 1:
                    rec.set(t_blocks=tb)
            args = (xz, m_arr, stats)
        elif method == "steady":
            stats = compute_panel_stats(xz, m_arr)
            args = (xz, m_arr, stats)
            plan = _steady_plan(params, m_arr)
            if plan is None:
                # gated off (incomplete tail / slow mixing / short sample):
                # the exact sequential program, same args
                step = em_step_stats
                rec.set(steady_gated=True, steady_frac=0.0)
            else:
                t_star, st0, rho = plan
                block = _steady_block_for(T0 - t_star)
                res_t = tfm.resolve(
                    tfm.Stack("ssm", (tfm.steady_tail(t_star, block),))
                )
                step = res_t.step
                params = SteadyEMState(
                    params=params,
                    # warm-start iteration 1 from the init-params solve the
                    # dispatch already paid for
                    Pp=jnp.asarray(st0.Pp, xz.dtype),
                    riccati_iters=jnp.asarray(0, jnp.int32),
                )
                # a tripped steady run demotes to the exact sequential
                # step: same (xz, mask, stats) args, SteadyEMState peeled
                from .emaccel import unwrap_state

                fallback_step = res_t.fallback_step
                fallback_unwrap = unwrap_state
                rec.set(
                    t_star=t_star,
                    steady_frac=float(T0 - t_star) / float(T0),
                    riccati_rho=float(rho),
                    steady_block=block,
                )
        else:
            res_t = tfm.resolve(
                tfm.Stack(
                    {
                        "associative": "ssm.assoc",
                        "sqrt": "ssm.sqrt",
                        "sqrt_collapsed": "ssm.sqrt_collapsed",
                    }[method]
                )
            )
            step = res_t.step
            args = (xz, m_arr)
            # the exact sequential filter on the same (xz, mask) args
            fallback_step = res_t.fallback_step
        if accel == "squarem":
            from .emaccel import squarem, squarem_state, unwrap_state

            step = squarem(step, _project_params)
            params = squarem_state(params)
            if fallback_step is None:
                fallback_step = em_step_stats  # plain map, SQUAREM peeled
            fallback_unwrap = unwrap_state

        if gram_dtype is not None:
            # mixed-precision bulk + exact polish (emloop.run_bulk_then_exact
            # holds the single copy of the orchestration): bf16 twins are
            # built inline so the driver holds the only reference and can
            # release them before the exact phase
            from .emloop import run_bulk_then_exact

            bulk_step = em_step_stats_bulk
            if accel == "squarem":
                # same wrapper on both phases: the SquaremState flows from
                # the bulk loop into the exact loop unchanged
                bulk_step = squarem(em_step_stats_bulk, _project_params)
            res = run_bulk_then_exact(
                bulk_step, step, params,
                (xz, m_arr, _with_bf16_twins(args[2], xz)), args,
                tol, max_em_iter,
                trace_name=f"em_dfm_{method}", collect_path=collect_path,
                fallback_step=fallback_step, fallback_unwrap=fallback_unwrap,
            )
        else:
            res = run_em_loop(
                step, params, args, tol, max_em_iter,
                collect_path=collect_path, trace_name=f"em_dfm_{method}",
                checkpoint_path=checkpoint_path,
                checkpoint_every=checkpoint_every,
                fallback_step=fallback_step, fallback_unwrap=fallback_unwrap,
            )
        params, llpath, n_iter, trace = res

        # unwrap by TYPE, not by the requested configuration: the recovery
        # ladder's demote rung may already have peeled the loop state
        from .emaccel import SquaremState

        if isinstance(params, SquaremState):
            params = params.params
        if isinstance(params, SteadyEMState):
            rec.set(riccati_iters=int(params.riccati_iters))
            params = params.params
        rec.set(
            n_iter=n_iter,
            converged=res.converged,
            final_loglik=float(llpath[-1]) if len(llpath) else None,
        )
        if res.faults_detected:
            from ..utils.guards import HEALTH_NAMES

            rec.set(
                faults_detected=res.faults_detected,
                recoveries=res.recoveries,
                ladder_rung=res.ladder_rung,
                final_health=HEALTH_NAMES[res.health],
            )
        if ns > 1 and jax.process_count() > 1:
            # gather the mesh-sharded loop output to replicated host
            # copies before the local smoother readout (fully-replicated
            # arrays are locally addressable on every process)
            from jax.sharding import NamedSharding

            from ..parallel.mesh import P as _P, data_mesh

            gmesh = data_mesh(ns, hosts=0)
            gather = jax.jit(
                lambda t: t, out_shardings=NamedSharding(gmesh, _P())
            )
            params = jax.tree.map(np.asarray, gather(params))
        # on the bucketed path the smoother also runs at the bucket shape
        # (padded cells are NaN -> missing; trailing all-missing periods
        # add no information at real times), then the readout slices back
        means, covs, _ = kalman_smoother(params, jnp.where(m_arr, xz, jnp.nan))
        if buckets is not None or ns > 1:
            params = unpad_ssm_params(params, N0)
        return EMResults(
            params=params,
            factors=means[:T0, :r],
            factor_covs=covs[:T0, :r, :r],
            loglik_path=llpath,
            n_iter=n_iter,
            stds=stds,
            means=n_mean,
            trace=trace,
            converged=res.converged,
            health=res.health,
        )


def estimate_dfm_twostep(
    data,
    inclcode,
    initperiod: int,
    lastperiod: int,
    config: DFMConfig = DFMConfig(nfac_u=4),
    backend: str | None = None,
    method: str = "sequential",
) -> EMResults:
    """Doz-Giannone-Reichlin (2011, J. Econometrics 164(1)) TWO-STEP
    estimator: principal-component/ALS estimates of (Lam, R, A, Q) in step
    one, a single Kalman-smoother pass for the factors in step two — the
    workhorse quick estimator of the nowcasting literature, consistent for
    large (N, T) without EM iteration.

    Exactly `estimate_dfm_em` with zero EM iterations (same initialization
    from the non-parametric ALS fit, same smoothing readout, same
    EMResults), so the two-step and the full QML/EM estimates are directly
    comparable: `n_iter` is 0 and `loglik_path` empty by construction.
    """
    return estimate_dfm_em(
        data,
        inclcode,
        initperiod,
        lastperiod,
        config,
        max_em_iter=0,
        backend=backend,
        method=method,
    )


def _pack_ssm(params: SSMParams):
    """Unconstrained reparametrization for direct gradient MLE: loadings
    and VAR blocks free, R through log, Q through its Cholesky factor
    (log-diagonal) — stationarity of A is NOT enforced (an explosive
    excursion shows up as a likelihood collapse and adam steps back).

    Q is PSD-floored before factoring so caller-supplied indefinite
    covariances degrade gracefully (as in kalman_filter) instead of
    silently NaN-ing the Cholesky.  The pack floors and the unpack clips
    cover the same ranges: every value this function can emit maps back
    through `_unpack_ssm` unchanged — a mismatch would create zero-
    gradient dead zones that freeze adam coordinates and zero out OPG
    scores at legally-fitted parameters."""
    L = jnp.linalg.cholesky(_psd_floor(params.Q))
    r = params.r
    il = jnp.tril_indices(r, -1)
    return {
        "lam": params.lam,
        "log_R": jnp.log(jnp.clip(params.R, 1e-10, 1e10)),
        "A": params.A,
        "log_qdiag": jnp.log(jnp.clip(jnp.diagonal(L), 1e-8, 1e8)),
        "q_lower": L[il],
    }


def _unpack_ssm(theta, r: int) -> SSMParams:
    il = jnp.tril_indices(r, -1)
    L = jnp.zeros((r, r), theta["lam"].dtype)
    # clip bounds strictly contain _pack_ssm's emit ranges (log 1e-8 =
    # -18.4, log 1e-10 = -23.03): round-trip exact, no dead zones
    L = L.at[jnp.arange(r), jnp.arange(r)].set(
        jnp.exp(jnp.clip(theta["log_qdiag"], -20.0, 20.0))
    )
    L = L.at[il].set(theta["q_lower"])
    return SSMParams(
        lam=theta["lam"],
        R=jnp.exp(jnp.clip(theta["log_R"], -25.0, 25.0)),
        A=theta["A"],
        Q=L @ L.T,
    )


@partial(jax.jit, static_argnames=("n_steps", "r"))
def _mle_adam(theta0, xz, m, stats, n_steps: int, lr, r: int):
    import optax

    opt = optax.adam(lr)

    def loss_fn(theta):
        p = _unpack_ssm(theta, r)
        filt = _filter_scan(p, xz, m, stats=stats)
        return -filt.loglik / xz.shape[0]

    def step(carry, _):
        theta, state, best_theta, best_loss = carry
        loss, g = jax.value_and_grad(loss_fn)(theta)
        # best-so-far over the path (loss is evaluated BEFORE the update,
        # so step 0 covers the init itself); a NaN loss never wins
        better = loss < best_loss
        best_theta = jax.tree.map(
            lambda b, t: jnp.where(better, t, b), best_theta, theta
        )
        best_loss = jnp.where(better, loss, best_loss)
        updates, state = opt.update(g, state, theta)
        theta = optax.apply_updates(theta, updates)
        return (theta, state, best_theta, best_loss), loss

    (theta, _, best_theta, _), losses = jax.lax.scan(
        step,
        (theta0, opt.init(theta0), theta0, jnp.asarray(jnp.inf, xz.dtype)),
        None,
        length=n_steps,
    )
    return theta, losses, best_theta


def estimate_dfm_mle(
    data,
    inclcode,
    initperiod: int,
    lastperiod: int,
    config: DFMConfig = DFMConfig(nfac_u=4),
    n_steps: int = 500,
    lr: float = 0.02,
    backend: str | None = None,
) -> EMResults:
    """Direct maximum likelihood for the state-space DFM: optax.adam
    through the collapsed Kalman-filter log-likelihood — the JAX-native
    fourth estimation route beside EM (`estimate_dfm_em`), the DGR
    two-step (`estimate_dfm_twostep`), and the Gibbs posterior
    (`bayes.estimate_dfm_bayes`).

    Same ALS initialization and smoothing readout as the EM path, so all
    four estimators return comparable `EMResults`; `loglik_path` holds
    the PER-STEP negative-loss path times -T (i.e., the loglik path of
    the optimizer), and `n_iter` = n_steps.  Gradient MLE climbs past
    EM's per-iteration monotone steps when the EM map's contraction is
    slow; EM is safer far from the optimum.  Stationarity of A is not
    enforced — an explosive excursion collapses the likelihood and adam
    retreats (document-and-monitor, as in the MS-DFM fit).
    """
    with on_backend(backend):
        data = jnp.asarray(data)
        inclcode = np.asarray(inclcode)
        xz, m_arr, stds, n_mean = _window_panel(
            data, inclcode, initperiod, lastperiod
        )
        r = config.nfac_u

        params0 = _init_params_from_als(
            data, inclcode, initperiod, lastperiod, config, xz, m_arr
        )
        stats = compute_panel_stats(xz, m_arr)
        theta, losses, best_theta = _mle_adam(
            _pack_ssm(params0), xz, m_arr, stats, n_steps, lr, r
        )
        params = _unpack_ssm(theta, r)
        params = params._replace(Q=_psd_floor(params.Q))
        # losses[i] is recorded BEFORE update i: evaluate the RETURNED
        # parameters' own likelihood; return the best-so-far adam iterate
        # instead when the final step overshot (a finite-but-worse last
        # iterate was previously returned as-is), and fall back to the ALS
        # init if everything is non-finite (A is unconstrained, so an
        # explosive excursion can collapse the likelihood)
        filt = _filter_scan(params, xz, m_arr, stats=stats)
        ll_final = float(filt.loglik)
        params_b = _unpack_ssm(best_theta, r)
        params_b = params_b._replace(Q=_psd_floor(params_b.Q))
        filt_b = _filter_scan(params_b, xz, m_arr, stats=stats)
        ll_best = float(filt_b.loglik)
        if np.isfinite(ll_best) and (
            not np.isfinite(ll_final) or ll_best > ll_final
        ):
            params, filt, ll_final = params_b, filt_b, ll_best
        if not np.isfinite(ll_final):
            params = params0
            filt = _filter_scan(params, xz, m_arr, stats=stats)
            ll_final = float(filt.loglik)
        means, covs, _ = kalman_smoother(params, jnp.where(m_arr, xz, jnp.nan))
        T = xz.shape[0]
        llpath = np.concatenate([-np.asarray(losses) * T, [ll_final]])
        return EMResults(
            params=params,
            factors=means[:, :r],
            factor_covs=covs[:, :r, :r],
            loglik_path=llpath,
            n_iter=int(n_steps),
            stds=stds,
            means=n_mean,
            trace=None,
        )


def _ssm_step_lls(params: SSMParams, x, mask):
    """Per-step log-likelihood terms (T,) of the collapsed filter — the
    score source for OPG standard errors.  Uses the stats-free collapse so
    the x'R^-1 x quadratic stays attributed to its own step (the PanelStats
    formulation moves it out of the scan as a TOTAL correction, which sums
    to the same likelihood but has no per-step decomposition)."""
    Tm, Qs = _companion(params)
    k = Tm.shape[0]
    r = params.r
    s0, P0 = _init_state(params)
    dtype = x.dtype
    C, b, ld_R, xRx, n_obs = _collapse_obs(
        params.lam, params.R, x, mask.astype(dtype)
    )

    def obs_step(inp, sp):
        Ct, bt, ld, xr, no = inp
        f = sp[:r]
        Cf = jnp.zeros((k, k), dtype).at[:r, :r].set(Ct)
        rhs = jnp.zeros(k, dtype).at[:r].set(bt - Ct @ f)
        quad0 = xr - 2.0 * (f @ bt) + f @ Ct @ f
        return Cf, rhs, ld, quad0, no

    _, _, _, _, lls = _info_filter_scan(
        Tm, Qs, (C, b, ld_R, xRx, n_obs), obs_step, s0, P0
    )
    return lls


def _score_covariance(
    lls_of, flat0, cov: str, adjust_scores=None, hac_lags: int = 0
):
    """Shared covariance engine for the score-based SE functions
    (ssm_standard_errors / msdfm.ms_standard_errors): forward-mode scores,
    then OPG or the sandwich H^-1 (S'S) H^-1.  The sandwich guards the
    Hessian: these estimates are near, not at, the optimum (EM stops on a
    likelihood-change rule; adam on a step budget): near-flat and
    noise-negative curvature directions are excluded by an eigenvalue
    floor (they carry no information and would otherwise be amplified by
    1/lambda^2), and substantially indefinite points fall back to OPG
    with a warning.

    `adjust_scores` maps the raw (T, d) score matrix to an adjusted one —
    the two-step M-estimation hook (msdfm standardization propagation
    replaces s_t with s_t - C u_t).  `hac_lags` > 0 replaces the plain
    S'S with a Bartlett long-run covariance of the (adjusted) scores:
    adjusted scores inherit the serial correlation of the first-stage
    moment contributions even when the raw scores are near-m.d.s."""
    import warnings

    scores = jax.jit(jax.jacfwd(lls_of))(flat0)  # (T, d)
    if adjust_scores is not None:
        scores = adjust_scores(scores)
    opg = scores.T @ scores
    if hac_lags > 0:
        Tn = scores.shape[0]
        for lag in range(1, min(hac_lags, Tn - 1) + 1):
            w = 1.0 - lag / (hac_lags + 1.0)
            g = scores[lag:].T @ scores[:-lag]
            opg = opg + w * (g + g.T)
    if cov == "sandwich":
        H = jax.jit(jax.hessian(lambda f: lls_of(f).sum()))(flat0)
        negH = -0.5 * (H + H.T)
        evals, evecs = jnp.linalg.eigh(negH)
        emax = jnp.maximum(evals[-1], 1e-30)
        if bool(evals[0] < -1e-4 * emax):
            # substantially negative curvature: these parameters are far
            # from any local maximum and a sandwich there is meaningless
            warnings.warn(
                "sandwich covariance: -Hessian is substantially indefinite "
                "at these parameters (not near a local optimum); falling "
                "back to OPG",
                stacklevel=3,
            )
        else:
            # eigenvalue-floored inverse: near-flat (and noise-negative)
            # directions — weakly identified combinations, EM's slow-tail
            # residual drift — carry no curvature information and are
            # excluded exactly as pinv excludes rank deficiency, instead
            # of being amplified by 1/lambda^2
            keep = evals > 1e-8 * emax
            inv_e = jnp.where(keep, 1.0 / jnp.where(keep, evals, 1.0), 0.0)
            Hinv = (evecs * inv_e[None, :]) @ evecs.T
            return Hinv @ opg @ Hinv
    return jnp.linalg.pinv(opg, hermitian=True)


class SSMStandardErrors(NamedTuple):
    """Delta-method OPG standard errors for the state-space DFM.  The
    structural mode covers the dynamics block (A, Q); lam/R fields are
    NaN unless which="all"."""

    A: jnp.ndarray  # (p, r, r)
    Q: jnp.ndarray  # (r, r)
    lam: jnp.ndarray  # (N, r)
    R: jnp.ndarray  # (N,)


def ssm_standard_errors(
    params: SSMParams,
    x,
    mask=None,
    which: str = "structural",
    cov: str = "sandwich",
) -> SSMStandardErrors:
    """Sandwich/OPG standard errors for a fitted state-space DFM (the EM,
    two-step, or direct-MLE estimate): the per-step collapsed-filter
    log-likelihood terms are differentiable, so the score matrix is one
    jitted forward-mode jacobian; the covariance defaults to the sandwich
    H^-1 (S'S) H^-1 (robust to quasi-likelihood effects; cov="opg" for
    the bare outer product); delta-method through the Cholesky/log
    reparametrization gives natural-scale SEs.

    which="structural" (default) scores (A, Q) holding (lam, R) fixed —
    well-posed on wide panels; which="all" scores everything and refuses
    rank-deficient designs (T <= #params).  `x` is the STANDARDIZED panel
    (NaN = missing) the model was fitted on.  First-order inference near
    the optimum; EM stops on a likelihood-change rule, so treat the last
    digits with the usual caution.
    """
    from jax.flatten_util import ravel_pytree

    x = jnp.asarray(x)
    if mask is None:
        mask = mask_of(x)
    xz = jnp.where(mask, x, 0.0)
    if which not in ("structural", "all"):
        raise ValueError(f"which must be 'structural' or 'all', got {which!r}")
    if cov not in ("sandwich", "opg"):
        raise ValueError(f"cov must be 'sandwich' or 'opg', got {cov!r}")
    r = params.r
    theta0 = _pack_ssm(params)
    struct_keys = ("A", "log_qdiag", "q_lower")
    if which == "structural":
        free0 = {k: theta0[k] for k in struct_keys}
        fixed = {k: v for k, v in theta0.items() if k not in struct_keys}
    else:
        free0 = dict(theta0)
        fixed = {}
    flat0, unravel = ravel_pytree(free0)
    d = flat0.shape[0]
    T = x.shape[0]
    if T <= d:
        raise ValueError(
            f"score-based inference needs more time steps than free "
            f"parameters: T={T} vs {d} (which={which!r}); use "
            "which='structural' or a longer sample"
        )

    def lls_of(flat):
        theta = dict(fixed)
        theta.update(unravel(flat))
        p = _unpack_ssm(theta, r)
        return _ssm_step_lls(p, xz, mask)

    cov_theta = _score_covariance(lls_of, flat0, cov)

    def natural(flat):
        theta = dict(fixed)
        theta.update(unravel(flat))
        p = _unpack_ssm(theta, r)
        return jnp.concatenate(
            [p.A.ravel(), p.Q.ravel(), p.lam.ravel(), p.R]
        )

    G = jax.jacobian(natural)(flat0)
    var_nat = jnp.einsum("ij,jk,ik->i", G, cov_theta, G)
    se = jnp.sqrt(jnp.maximum(var_nat, 0.0))
    p_, N = params.p, params.lam.shape[0]
    i = 0
    se_A = se[i : i + p_ * r * r].reshape(p_, r, r); i += p_ * r * r
    se_Q = se[i : i + r * r].reshape(r, r); i += r * r
    se_lam = se[i : i + N * r].reshape(N, r); i += N * r
    se_R = se[i : i + N]
    if which == "structural":
        se_lam = jnp.full((N, r), jnp.nan)
        se_R = jnp.full(N, jnp.nan)
    return SSMStandardErrors(A=se_A, Q=se_Q, lam=se_lam, R=se_R)
