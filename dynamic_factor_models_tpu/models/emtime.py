"""Parallel-in-time EM steps: blocked-slab fused smoothers on a time mesh.

The associative-scan EM variant (`ssm.em_step_assoc`) parallelizes the
state recursion over T but used to LOSE to the sequential collapsed path:
its elements were built from the full N-dim observation model (O(N r) per
element) and the scan ran on one device.  This module is the production
time-parallel path that fixes both:

  * elements come from the COLLAPSED per-step payload (C, b, ld_R) —
    `pkalman.filter_elements_collapsed` — so element construction is
    O(r^3) per step, never O(N r);
  * the scan runs BLOCKED over the mesh "time" axis
    (`parallel.timescan.sharded_scan` with ``local="sequential"``): each
    device owns a contiguous slab and runs the cheap sequential combine
    recursion (~1x combine work vs the associative form's ~2x), and only
    the O(k^2) slab-boundary elements cross devices in the log-depth
    exclusive-prefix exchange.

Step factories are lru_cached and NAMED (`em_step_tp_b{b}`,
`em_step_tp_b{b}_d{n}[_h{h}]`, `em_step_ar_tp_b{b}`) so the AOT registry
statics key (utils.compile.aot_statics uses __module__ + __qualname__) is
stable across processes, exactly like `ssm._sharded_step_impl`.  The
composed time x shard step splits work over the 3-D
``("dcn", "time", "ici")`` mesh (`parallel.mesh.data_mesh`): the
Jungbacker-Koopman collapse runs shard-local over the series axes with
one psum, the blocked slab scans ride the "time" axis, and the M-step
(N-free solves plus the per-series regressions on the replicated smoothed
moments) runs replicated — correctness-first; the per-series M-step GEMMs
could be re-sharded later without changing this module's contract.

Padded/boundary time steps are exactly inert: `sharded_scan` pads ragged
T at the END with repeats of the last element, which an inclusive causal
scan never reads back into real positions (pinned at 1e-10 EM parity in
tests/test_timeparallel.py).
"""

from __future__ import annotations

from functools import lru_cache

import jax
import jax.numpy as jnp

from ..parallel import shard_map_nocheck
from ..parallel.mesh import P, data_mesh
from ..parallel.timescan import sharded_scan
from . import pkalman as pk
from .ssm import (
    PanelStats,
    SSMParams,
    _collapse_obs_stats,
    _collapse_obs_stats_partial,
    _em_m_step,
    _psd_floor,
    _resolve_mesh_hosts,
    _unpack_collapsed,
)

__all__ = ["em_step_tp_for", "em_step_ar_tp_for"]


def _time_scan(mesh):
    """The injected scan: blocked slabs over the mesh "time" axis with the
    sequential within-slab recursion (the production choice — within one
    device depth is free, so the ~1x-combine-work form wins on FLOPs)."""
    return lambda comb, elems: sharded_scan(
        comb, elems, mesh, axis="time", local="sequential"
    )


def em_step_tp_for(t_blocks: int, n_shards: int = 0, hosts: int = 0):
    """The parallel-in-time iid-core EM step over `t_blocks` time slabs —
    same (params, x, mask, stats) -> (params, loglik) contract as
    `ssm.em_step_stats`, any T (ragged slabs pad inertly inside the scan).

    n_shards > 1 composes with the cross-section sharding into the 3-D
    ``("dcn", "time", "ici")`` mesh; `hosts` resolves exactly as in
    `ssm._sharded_step_for` (0 -> jax.process_count()).  Plain-function
    dispatcher over the lru_cached impls so every call spelling hits one
    cache entry (the resolve-identity contract of models/transforms)."""
    if t_blocks <= 1:
        raise ValueError(f"t_blocks must be > 1, got {t_blocks}")
    ns = int(n_shards)
    if ns > 1:
        return _tp_sharded_step_impl(
            int(t_blocks), ns, _resolve_mesh_hosts(hosts)
        )
    return _tp_step_impl(int(t_blocks))


def em_step_ar_tp_for(t_blocks: int):
    """The parallel-in-time AR-idiosyncratic (kappa = 0) EM step — same
    (params, x, qd) -> (params, loglik) contract as
    `ssm_ar.em_step_ar_qd`, with the quasi-differenced collapsed payload
    (q = 2r active state coordinates) feeding the same fused blocked-slab
    smoother."""
    if t_blocks <= 1:
        raise ValueError(f"t_blocks must be > 1, got {t_blocks}")
    return _tp_ar_step_impl(int(t_blocks))


@lru_cache(maxsize=None)
def _tp_step_impl(t_blocks: int):
    mesh = data_mesh(1, hosts=1, t_blocks=t_blocks)
    scan = _time_scan(mesh)

    def step(params: SSMParams, x, mask, stats: PanelStats):
        del mask  # collapse statistics already carry the mask
        params = params._replace(Q=_psd_floor(params.Q))
        C, b, ld_R, xRx, n_obs, llc = _collapse_obs_stats(
            params.lam, params.R, x, stats
        )
        s_sm, P_sm, ll, lag1 = pk.kalman_smoother_associative_collapsed(
            params, C, b, ld_R, xRx, n_obs, ll_corr=llc, scan=scan
        )
        return (
            _em_m_step(params, x, stats.m, s_sm, P_sm, lag1, stats=stats),
            ll,
        )

    step.__name__ = step.__qualname__ = f"em_step_tp_b{t_blocks}"
    step.__module__ = __name__
    return jax.jit(step)


@lru_cache(maxsize=None)
def _tp_sharded_step_impl(t_blocks: int, n_shards: int, hosts: int):
    mesh = data_mesh(n_shards, hosts=hosts, t_blocks=t_blocks)
    scan = _time_scan(mesh)
    dax = ("dcn", "ici")

    params_spec = SSMParams(lam=P(dax, None), R=P(dax), A=P(), Q=P())
    stats_spec = PanelStats(
        m=P(None, dax), xT=P(dax, None), mT=P(dax, None),
        Sxx=P(dax), n_i=P(dax), n_obs=P(),
        m16=None, x16=None, mT16=None, xT16=None, tw=P(),
    )

    def _collapse(params: SSMParams, x, stats: PanelStats):
        payload, llc = _collapse_obs_stats_partial(
            params.lam, params.R, x, stats
        )
        # every collapsed statistic is a sum over series: one psum over
        # the series axes reduces shard partials exactly; the "time" axis
        # carries identical replicas, so the output is fully replicated
        return jax.lax.psum(payload, dax), jax.lax.psum(llc, dax)

    collapse = shard_map_nocheck(
        _collapse,
        mesh=mesh,
        in_specs=(params_spec, P(None, dax), stats_spec),
        out_specs=(P(), P()),
    )

    def step(params: SSMParams, x, mask, stats: PanelStats):
        del mask
        params = params._replace(Q=_psd_floor(params.Q))
        payload, llc = collapse(params, x, stats)
        C, b, ld_R = _unpack_collapsed(payload, params.r)
        xRx = jnp.zeros(b.shape[0], b.dtype)
        s_sm, P_sm, ll, lag1 = pk.kalman_smoother_associative_collapsed(
            params, C, b, ld_R, xRx, stats.n_obs, ll_corr=llc, scan=scan
        )
        return (
            _em_m_step(params, x, stats.m, s_sm, P_sm, lag1, stats=stats),
            ll,
        )

    name = f"em_step_tp_b{t_blocks}_d{n_shards}"
    if hosts > 1:
        name += f"_h{hosts}"
    step.__name__ = step.__qualname__ = name
    step.__module__ = __name__
    return jax.jit(step)


@lru_cache(maxsize=None)
def _tp_ar_step_impl(t_blocks: int):
    from .ssm_ar import (
        _collapse_obs_qd,
        _guard_params_qd,
        _m_step_ar_qd,
        _qd_companion,
    )

    mesh = data_mesh(1, hosts=1, t_blocks=t_blocks)
    scan = _time_scan(mesh)

    def step(params, x, qd):
        params = _guard_params_qd(params)
        Tm, Qs = _qd_companion(params)
        k = Tm.shape[0]
        s0 = jnp.zeros(k, x.dtype)
        P0 = 1e2 * jnp.eye(k, dtype=x.dtype)
        C, b, ld_V, xRx, n_obs = _collapse_obs_qd(params, x, qd)
        s_sm, P_sm, ll, lag1 = pk._assoc_smooth_collapsed(
            Tm, Qs, s0, P0, C, b, ld_V, xRx, n_obs, 0.0, scan=scan
        )
        return _m_step_ar_qd(params, x, qd, s_sm, P_sm, lag1), ll

    step.__name__ = step.__qualname__ = f"em_step_ar_tp_b{t_blocks}"
    step.__module__ = __name__
    return jax.jit(step)
