"""Stochastic-volatility DFM: factor innovations with AR(1) log-volatility,
sampled by Gibbs with the Kim-Shephard-Chib auxiliary mixture.

New capability (the reference is entirely homoskedastic — its factor VAR
carries one constant `seps`, dfm_functions.ipynb cell 23): time-varying
macro volatility (Great Moderation, crisis spikes) is the canonical
extension of the Stock-Watson DFM (Del Negro-Otrok 2008).  Model:

    x_t = Lam f_t + eps_t,            eps_t ~ N(0, diag(R))
    f_t = A_1 f_{t-1} + ... + A_p f_{t-p} + u_t,
    u_{j,t} ~ N(0, exp(h_{j,t}))
    h_{j,t} = mu_j + phi_j (h_{j,t-1} - mu_j) + sig_j eta_{j,t}

Gibbs blocks, all scans/vmaps on device:
1. f | rest     — Durbin-Koopman simulation smoother on the masked
                  information-form filter with time-varying
                  Q_t = diag(exp(h_t)) (shared core, models/bayes.py);
2. Lam, R | f   — conjugate block shared with models/bayes.py;
3. A | f, h     — the diagonal Q_t decouples the VAR rows: per-factor
                  weighted least squares with weights exp(-h_{j,t}), vmapped;
4. s | h, u     — KSC 7-component mixture indicators for log u^2
                  (categorical draw per (t, j));
5. h | s, u     — univariate linear-Gaussian simulation smoother per factor
                  (scalar Kalman + backward draw, vmapped over factors);
6. mu, phi, sig — conjugate AR(1) regression draws on the h path.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..parallel.mesh import NamedSharding, P
from ..utils.backend import on_backend
from .bayes import (
    _draw_lam_r_block,
    _draw_mvn,
    _prepare_panel,
    _simulation_smoother_core,
    rhat,
)
from .dfm import DFMConfig
from .ssm import SSMParams, _init_params_from_als

__all__ = ["SVPriors", "SVResults", "estimate_dfm_sv"]

# Kim-Shephard-Chib (1998, Table 4) 7-component normal mixture for log eps^2
_KSC_P = np.array([0.00730, 0.10556, 0.00002, 0.04395, 0.34001, 0.24566, 0.25750])
_KSC_M = np.array(
    [-11.40039, -5.24321, -9.83726, 1.50746, -0.65098, 0.52478, -2.35859]
)
_KSC_V2 = np.array([5.79596, 2.61369, 5.17950, 0.16735, 0.64009, 0.34023, 1.26261])


class SVPriors(NamedTuple):
    """Hyperparameters: loading/variance block as BayesPriors; AR(1)
    log-volatility with Normal (c, phi) prior and IG sigma^2 prior."""

    lam_scale: float = 10.0
    r_shape: float = 0.01
    r_rate: float = 0.01
    a_scale: float = 10.0  # prior sd of each VAR coefficient
    h_coef_scale: float = 2.0  # prior sd of the h-AR intercept and slope
    h_sig_shape: float = 2.5
    h_sig_rate: float = 0.1
    phi_max: float = 0.99  # stationarity clip for the volatility AR


class SVResults(NamedTuple):
    factor_draws: jnp.ndarray  # (chains, keep, T, r)
    vol_draws: jnp.ndarray  # (chains, keep, T, r) innovation sds exp(h/2)
    lam_draws: jnp.ndarray  # (chains, keep, N, r)
    r_draws: jnp.ndarray  # (chains, keep, N)
    a_draws: jnp.ndarray  # (chains, keep, p, r, r)
    mu_draws: jnp.ndarray  # (chains, keep, r)
    phi_draws: jnp.ndarray  # (chains, keep, r)
    sig_draws: jnp.ndarray  # (chains, keep, r)
    loglik_path: np.ndarray  # (chains, iters) conditional filter loglik
    rhat_loglik: float
    stds: jnp.ndarray
    means: jnp.ndarray


# ---------------------------------------------------------------------------
# VAR rows by WLS, volatilities by KSC
# ---------------------------------------------------------------------------


def _draw_var_rows(key, f, h, p: int, a_scale):
    """A | f, h: diagonal Q_t decouples equations; per-factor WLS draw."""
    T, r = f.shape
    dtype = f.dtype
    Z = jnp.concatenate([f[p - 1 - i : T - 1 - i] for i in range(p)], axis=1)
    Y = f[p:]
    w = jnp.exp(-h[p:])  # (T-p, r) precision weights per equation
    keys = jax.random.split(key, r)

    def one_row(y_j, w_j, k_j):
        Zw = Z * w_j[:, None]
        prec = Zw.T @ Z + jnp.eye(r * p, dtype=dtype) / a_scale**2
        pinv = jnp.linalg.pinv(0.5 * (prec + prec.T), hermitian=True)
        return _draw_mvn(k_j, pinv @ (Zw.T @ y_j), pinv)

    rows = jax.vmap(one_row, in_axes=(1, 1, 0))(Y, w, keys)  # (r, r*p)
    A = jnp.stack([rows[:, i * r : (i + 1) * r] for i in range(p)])
    u = Y - Z @ rows.T  # (T-p, r) innovations for the h blocks
    return A, u


def _draw_h_block(key, u, h_prev, mu, phi, sig, priors: tuple):
    """KSC mixture indicators + univariate simulation smoother + AR(1)
    hyperparameter draws, vmapped over factors.

    u: (Tu, r) VAR innovations; h_prev: (Tu, r) current log-variances
    (aligned with u).  Returns (h, mu, phi, sig) draws."""
    h_coef_scale, h_sig_shape, h_sig_rate, phi_max = priors
    Tu, r = u.shape
    dtype = u.dtype
    c_off = jnp.asarray(1e-6, dtype)
    ystar = jnp.log(u**2 + c_off)  # (Tu, r)

    pk = jnp.asarray(_KSC_P, dtype)
    mk = jnp.asarray(_KSC_M, dtype)
    v2k = jnp.asarray(_KSC_V2, dtype)

    ks, kh, kcoef, ksig = jax.random.split(key, 4)

    # --- mixture indicators: categorical over 7 components per (t, j) ---
    resid = ystar[:, :, None] - h_prev[:, :, None] - mk[None, None, :]
    logits = (
        jnp.log(pk)[None, None, :]
        - 0.5 * jnp.log(v2k)[None, None, :]
        - 0.5 * resid**2 / v2k[None, None, :]
    )
    s = jax.random.categorical(ks, logits, axis=-1)  # (Tu, r)
    ms, v2s = mk[s], v2k[s]

    # --- h | s: scalar Kalman forward + backward sampling per factor ---
    def one_factor(y_j, ms_j, v2_j, mu_j, phi_j, sig_j, k_j):
        sig2 = sig_j**2
        p0 = sig2 / jnp.maximum(1.0 - phi_j**2, 1e-4)

        def fstep(carry, inp):
            hf, Pf = carry
            yt, mt, vt = inp
            hp = mu_j + phi_j * (hf - mu_j)
            Pp = phi_j**2 * Pf + sig2
            K = Pp / (Pp + vt)
            hf_n = hp + K * (yt - mt - hp)
            return (hf_n, (1.0 - K) * Pp), (hf_n, (1.0 - K) * Pp)

        (_, _), (hf, Pf) = jax.lax.scan(
            fstep, (mu_j, p0), (y_j, ms_j, v2_j)
        )

        kl, kb = jax.random.split(k_j)
        h_last = hf[-1] + jnp.sqrt(jnp.maximum(Pf[-1], 1e-12)) * jax.random.normal(
            kl, dtype=dtype
        )
        keys_b = jax.random.split(kb, Tu - 1)

        def bstep(h_next, inp):
            hf_t, Pf_t, kt = inp
            denom = phi_j**2 * Pf_t + sig2
            J = phi_j * Pf_t / denom
            mean = hf_t + J * (h_next - mu_j - phi_j * (hf_t - mu_j))
            var = Pf_t - J * phi_j * Pf_t
            h_t = mean + jnp.sqrt(jnp.maximum(var, 1e-12)) * jax.random.normal(
                kt, dtype=dtype
            )
            return h_t, h_t

        _, h_rest = jax.lax.scan(
            bstep, h_last, (hf[:-1], Pf[:-1], keys_b), reverse=True
        )
        return jnp.concatenate([h_rest, h_last[None]])

    mu_a, phi_a, sig_a = mu, phi, sig
    hkeys = jax.random.split(kh, r)
    h = jax.vmap(one_factor, in_axes=(1, 1, 1, 0, 0, 0, 0), out_axes=1)(
        ystar, ms, v2s, mu_a, phi_a, sig_a, hkeys
    )

    # --- (c, phi, sig) | h: conjugate AR(1) regression per factor ---
    ckeys = jax.random.split(kcoef, r)
    skeys = jax.random.split(ksig, r)

    def one_ar(h_j, sig_j, kc, ks_):
        y = h_j[1:]
        kc1, kc2 = jax.random.split(kc)
        Zr = jnp.stack([jnp.ones(Tu - 1, dtype), h_j[:-1]], axis=1)
        prec = Zr.T @ Zr / sig_j**2 + jnp.eye(2, dtype=dtype) / h_coef_scale**2
        pinv = jnp.linalg.pinv(0.5 * (prec + prec.T), hermitian=True)
        beta = _draw_mvn(kc1, pinv @ (Zr.T @ y) / sig_j**2, pinv)
        phi_n = jnp.clip(beta[1], -phi_max, phi_max)
        # if the slope was clipped, the jointly-drawn intercept no longer
        # matches it (mu = c/(1-phi) blows up near the boundary); redraw the
        # intercept from its conditional posterior given the clipped slope,
        # and use the same (c, phi) pair for both mu and the sigma residuals
        resid_y = y - phi_n * h_j[:-1]
        prec_c = (Tu - 1) / sig_j**2 + 1.0 / h_coef_scale**2
        c_cond = resid_y.sum() / sig_j**2 / prec_c + jax.random.normal(
            kc2, dtype=dtype
        ) / jnp.sqrt(prec_c)
        c_n = jnp.where(phi_n == beta[1], beta[0], c_cond)
        mu_n = c_n / (1.0 - phi_n)
        e = resid_y - c_n
        g = jax.random.gamma(ks_, h_sig_shape + 0.5 * (Tu - 1), dtype=dtype)
        sig2_n = (h_sig_rate + 0.5 * (e**2).sum()) / g
        return mu_n, phi_n, jnp.sqrt(sig2_n)

    mu_n, phi_n, sig_n = jax.vmap(one_ar, in_axes=(1, 0, 0, 0))(
        h, sig_a, ckeys, skeys
    )
    return h, mu_n, phi_n, sig_n


# ---------------------------------------------------------------------------
# sweep / chain / entry
# ---------------------------------------------------------------------------


def _sv_sweep(carry, xz, m, p: int, priors: tuple):
    key, params, h, mu, phi, sig = carry
    (lam_scale, a0, b0, a_scale, h_coef_scale, h_sig_shape, h_sig_rate,
     phi_max) = priors

    key, kf, klamr, kvar, kh = jax.random.split(key, 5)

    f, ll = _simulation_smoother_core(params, xz, m, kf, qdiag=jnp.exp(h))
    lam, R = _draw_lam_r_block(klamr, f, xz, m, params.R, lam_scale, a0, b0)
    A, u = _draw_var_rows(kvar, f, h, p, a_scale)
    h_u, mu_n, phi_n, sig_n = _draw_h_block(
        kh, u, h[p:], mu, phi, sig, (h_coef_scale, h_sig_shape, h_sig_rate, phi_max)
    )
    # extend the drawn h (aligned with u, t = p..T-1) back over the seed rows
    h_new = jnp.concatenate([jnp.repeat(h_u[:1], p, axis=0), h_u], axis=0)

    # Q in params is unused by the tv filter but kept coherent for init reuse
    new_params = SSMParams(lam=lam, R=R, A=A, Q=jnp.diag(jnp.exp(mu_n)))
    return (key, new_params, h_new, mu_n, phi_n, sig_n), (
        f, jnp.exp(0.5 * h_new), lam, R, A, mu_n, phi_n, sig_n, ll,
    )


@partial(jax.jit, static_argnames=("n_burn", "n_keep", "thin", "p"))
def _sv_chain(key, init_carry_tail, xz, m, n_burn, n_keep, thin, p, priors):
    def sweep_ll(carry, _):
        carry, outs = _sv_sweep(carry, xz, m, p, priors)
        return carry, outs[-1]

    def keep_body(carry, _):
        carry, lls_thin = jax.lax.scan(sweep_ll, carry, None, length=thin - 1)
        carry, outs = _sv_sweep(carry, xz, m, p, priors)
        return carry, (outs[:-1], jnp.concatenate([lls_thin, outs[-1][None]]))

    carry = (key,) + init_carry_tail
    carry, ll_burn = jax.lax.scan(sweep_ll, carry, None, length=n_burn)
    _, (kept, ll_keep) = jax.lax.scan(keep_body, carry, None, length=n_keep)
    return kept + (jnp.concatenate([ll_burn, ll_keep.reshape(-1)]),)


def estimate_dfm_sv(
    data,
    inclcode,
    initperiod: int,
    lastperiod: int,
    config: DFMConfig = DFMConfig(nfac_u=4),
    n_keep: int = 500,
    n_burn: int = 500,
    thin: int = 1,
    n_chains: int = 2,
    seed: int = 0,
    priors: SVPriors = SVPriors(),
    mesh=None,
    backend: str | None = None,
) -> SVResults:
    """Stochastic-volatility DFM posterior by Gibbs (Del Negro-Otrok style),
    chains vmapped on device and shardable over a 1-axis mesh.

    Same data path and ALS initialization as `estimate_dfm_bayes`; the
    log-volatility state starts at the ALS factor-VAR innovation variances.
    Returns sign-normalized factor draws, the volatility paths
    exp(h/2), and per-factor (mu, phi, sig) hyperparameter draws.
    """
    from .bayes import _sign_normalize

    with on_backend(backend):
        data, inclcode, xz, m_arr, stds, n_mean = _prepare_panel(
            data, inclcode, initperiod, lastperiod
        )
        params0 = _init_params_from_als(
            data, inclcode, initperiod, lastperiod, config, xz, m_arr
        )
        p = config.n_factorlag
        r = config.nfac_u
        Tw = xz.shape[0]

        h0_level = jnp.log(jnp.maximum(jnp.diagonal(params0.Q), 1e-4))
        init_tail = (
            params0,
            jnp.broadcast_to(h0_level, (Tw, r)).astype(xz.dtype),
            h0_level.astype(xz.dtype),
            jnp.full((r,), 0.95, xz.dtype),
            jnp.full((r,), 0.2, xz.dtype),
        )
        prior_t = (
            float(priors.lam_scale), float(priors.r_shape), float(priors.r_rate),
            float(priors.a_scale), float(priors.h_coef_scale),
            float(priors.h_sig_shape), float(priors.h_sig_rate),
            float(priors.phi_max),
        )

        keys = jax.random.split(jax.random.PRNGKey(seed), n_chains)
        if mesh is not None:
            keys = jax.device_put(
                keys, NamedSharding(mesh, P(mesh.axis_names[0]))
            )

        run = jax.vmap(
            lambda k: _sv_chain(
                k, init_tail, xz, m_arr.astype(xz.dtype),
                n_burn, n_keep, thin, p, prior_t,
            )
        )
        f_k, vol_k, lam_k, r_k, a_k, mu_k, phi_k, sig_k, ll_all = run(keys)

        f_k, lam_k, a_k, _ = _sign_normalize(
            f_k, lam_k, a_k, jnp.eye(r, dtype=xz.dtype)
        )
        ll_np = np.asarray(ll_all)
        return SVResults(
            factor_draws=f_k,
            vol_draws=vol_k,  # volatilities are sign-invariant
            lam_draws=lam_k,
            r_draws=r_k,
            a_draws=a_k,
            mu_draws=mu_k,
            phi_draws=phi_k,
            sig_draws=sig_k,
            loglik_path=ll_np,
            rhat_loglik=rhat(ll_np[:, n_burn:]),
            stds=stds,
            means=n_mean,
        )
