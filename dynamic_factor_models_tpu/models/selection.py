"""Factor-number selection: Bai-Ng ICp2, Amengual-Watson, Ahn-Horenstein.

Rewrite of reference cells 35-40.  The reference's O(max_nfac^2) loop of full
DFM refits (SURVEY.md section 3.3, "embarrassingly parallel across nfac") is
fanned out here: all static fits for r = 1..max_nfac run as ONE vmapped
batched ALS (`estimate_factor_batch`), the per-r residualizations are one
vmapped masked-OLS, and all max_nfac*(max_nfac+1)/2 Amengual-Watson refits
run as a second single batched ALS — three jitted programs total instead of
O(max_nfac^2) sequential while-loops.
"""

from __future__ import annotations

import dataclasses

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.lags import lagmat
from ..ops.linalg import ols_batched_series
from ..ops.masking import fillz, mask_of
from .dfm import DFMConfig, FactorEstimateStats, estimate_factor_batch

__all__ = [
    "bai_ng_criterion",
    "bai_ng_criterion_variant",
    "amengual_watson_test",
    "estimate_factor_numbers",
    "ahn_horenstein_er",
    "ahn_horenstein_gr",
    "onatski_ed",
    "FactorNumberEstimateStats",
]


def bai_ng_criterion(fes: FactorEstimateStats, nfac_t: int) -> jnp.ndarray:
    """Bai-Ng ICp2 with unbalanced-panel-adjusted counts (reference cell 35)."""
    return bai_ng_criterion_variant(fes, nfac_t, "icp2")


def bai_ng_criterion_variant(
    fes: FactorEstimateStats, nfac_t: int, variant: str = "icp2"
) -> jnp.ndarray:
    """All three Bai-Ng (2002, Econometrica 70(1) eq. 9) ICp penalties with
    the same unbalanced-count convention as `bai_ng_criterion`:

        icp1: g = log(nobs/(nbar+T)) * (nbar+T)/nobs
        icp2: g = log(min(nbar, T)) * (nbar+T)/nobs     (the reference's)
        icp3: g = log(min(nbar, T)) / min(nbar, T)

    ICp2 penalizes hardest in typical macro panels; ICp3 is the most
    liberal.  All three are consistent under the paper's assumptions.
    """
    nbar = fes.nobs / fes.T
    c2 = jnp.minimum(nbar, fes.T)
    if variant == "icp1":
        g = jnp.log(fes.nobs / (nbar + fes.T)) * (nbar + fes.T) / fes.nobs
    elif variant == "icp2":
        g = jnp.log(c2) * (nbar + fes.T) / fes.nobs
    elif variant == "icp3":
        g = jnp.log(c2) / c2
    else:
        raise ValueError(f"variant must be icp1/icp2/icp3, got {variant!r}")
    return jnp.log(fes.ssr / fes.nobs) + nfac_t * g


class FactorNumberEstimateStats(NamedTuple):
    """Selection-statistics bundle (reference cell 37)."""

    bn_icp: np.ndarray  # (max_nfac,)
    ssr_static: np.ndarray  # (max_nfac,)
    R2_static: np.ndarray  # (ns, max_nfac)
    aw_icp: np.ndarray  # (max_nfac, max_nfac), NaN above diagonal
    ssr_dynamic: np.ndarray
    R2_dynamic: np.ndarray  # (ns, max_nfac, max_nfac)
    tss: float
    nobs: float
    T: int

    @property
    def trace_r2(self) -> np.ndarray:
        return 1.0 - self.ssr_static / self.tss

    @property
    def marginal_r2(self) -> np.ndarray:
        tr = self.trace_r2
        return np.concatenate([tr[:1], np.diff(tr)])

    def icp(self, variant: str = "icp2") -> np.ndarray:
        """Bai-Ng criterion values over the sweep for any ICp variant
        (same penalties as `bai_ng_criterion_variant`, computed in f64
        numpy like `bn_icp`, so icp("icp2") == bn_icp unconditionally)."""
        nbar = self.nobs / self.T
        c2 = min(nbar, self.T)
        if variant == "icp1":
            g = np.log(self.nobs / (nbar + self.T)) * (nbar + self.T) / self.nobs
        elif variant == "icp2":
            g = np.log(c2) * (nbar + self.T) / self.nobs
        elif variant == "icp3":
            g = np.log(c2) / c2
        else:
            raise ValueError(f"variant must be icp1/icp2/icp3, got {variant!r}")
        nfacs = np.arange(1, len(self.ssr_static) + 1)
        return np.log(np.asarray(self.ssr_static) / self.nobs) + nfacs * g

    @property
    def growth_ratio(self) -> np.ndarray:
        """Ahn-Horenstein GR over the sweep's marginal trace-R^2 shares."""
        return ahn_horenstein_gr(self.marginal_r2)


def ahn_horenstein_er(marginal_r2: np.ndarray) -> np.ndarray:
    """Ahn-Horenstein eigenvalue-ratio criterion from marginal trace R^2
    (driver cell 31/35 convention: ER_r = margR2_r / margR2_{r+1})."""
    return marginal_r2[:-1] / marginal_r2[1:]


def ahn_horenstein_gr(marginal_r2: np.ndarray) -> np.ndarray:
    """Ahn-Horenstein (2013, Econometrica 81(3)) GROWTH-ratio criterion,
    the companion to ER on the same marginal shares:

        GR_r = log(V_{r-1}/V_r) / log(V_r/V_{r+1}),
        V_r  = 1 - sum_{j<=r} share_j  (variance left after r factors).

    `marginal_r2` entries must be FRACTIONS OF TOTAL panel variance
    (`FactorNumberEstimateStats.marginal_r2` or eigenvalue shares) so V_r
    keeps the idiosyncratic remainder — a truncated max_nfac sweep then
    yields finite values at every r, unlike a total-of-the-passed-shares
    normalization whose V_R collapses to 0.  Entries where V hits zero
    (e.g. the last step of an exhaustive full-spectrum decomposition) are
    returned as NaN, never inf — nanargmax-safe.  Like ER, pick the r that
    maximizes GR; more robust than ER when the eigenvalue tail decays
    slowly (their Monte Carlos).
    """
    m = np.asarray(marginal_r2, dtype=float)
    V = 1.0 - np.concatenate([[0.0], np.cumsum(m)])  # V_0..V_R
    with np.errstate(divide="ignore", invalid="ignore"):
        num = np.log(V[:-2] / V[1:-1])
        den = np.log(V[1:-1] / V[2:])
        gr = np.where((V[1:-1] > 0) & (V[2:] > 0), num / den, np.nan)
    return gr


def amengual_watson_test(
    data,
    inclcode,
    factor,
    initperiod: int,
    lastperiod: int,
    config: DFMConfig,
    nfac_static: int,
):
    """Number-of-dynamic-factors test (reference cell 40).

    Residualize each included series on [1, lags 1..p of the static factors]
    over the full sample, then re-estimate static DFMs of every order on the
    residual panel (window shifted +nlag) and return their Bai-Ng values.
    """
    data = jnp.asarray(data)
    inclcode = np.asarray(inclcode)
    est = data[:, inclcode == 1]
    T, ns = est.shape
    nlag = config.n_factorlag

    x = jnp.hstack(
        [jnp.ones((T, 1), data.dtype), lagmat(jnp.asarray(factor), range(1, nlag + 1))]
    )
    xm = mask_of(x).all(axis=1)
    W = (mask_of(est) & xm[:, None]).astype(data.dtype)
    _, resid = ols_batched_series(est, fillz(x), W)
    ndf = W.sum(axis=0) - x.shape[1]
    keep = ndf >= config.nt_min_factor
    resid = jnp.where(keep[None, :], resid, jnp.nan)

    ones = np.ones(ns, dtype=inclcode.dtype)
    resid_np = np.asarray(resid)
    cfg_d = dataclasses.replace(config, nfac_o=0)
    batch = estimate_factor_batch(
        [
            (resid_np, ones, initperiod + nlag, lastperiod, d)
            for d in range(1, nfac_static + 1)
        ],
        cfg_d,
    )
    ssr_np = np.asarray(batch.ssr)
    nobs_np = np.asarray(batch.nobs)
    aw = np.array(
        [
            _bai_ng(ssr_np[i], nobs_np[i], int(batch.Tw[i]), i + 1)
            for i in range(nfac_static)
        ]
    )
    return aw, ssr_np, np.asarray(batch.R2).T


def _bai_ng(ssr, nobs, T, nfac_t):
    """Bai-Ng ICp2 from raw bookkeeping scalars (cell 35 formula)."""
    nbar = nobs / T
    g = np.log(min(nbar, T)) * (nbar + T) / nobs
    return np.log(ssr / nobs) + nfac_t * g


def estimate_factor_numbers(
    data,
    inclcode,
    initperiod: int,
    lastperiod: int,
    config: DFMConfig,
    max_nfac: int,
    dynamic: bool = True,
    backend: str | None = None,
) -> FactorNumberEstimateStats:
    """Fit DFMs for r = 1..max_nfac and collect selection statistics
    (reference cell 39).  Set dynamic=False to skip the O(r^2)
    Amengual-Watson refits.

    All static fits run as one `estimate_factor_batch` call; with
    dynamic=True the per-r residualizations are one vmapped masked OLS and
    all r*(r+1)/2 Amengual-Watson refits a second batched call.
    """
    inclcode = np.asarray(inclcode)
    data = np.asarray(data)
    ns = int((inclcode == 1).sum())
    bn = np.full(max_nfac, np.nan)
    ssr_s = np.full(max_nfac, np.nan)
    R2_s = np.full((ns, max_nfac), np.nan)
    aw = np.full((max_nfac, max_nfac), np.nan)
    ssr_d = np.full((max_nfac, max_nfac), np.nan)
    R2_d = np.full((ns, max_nfac, max_nfac), np.nan)

    panels = [
        (data, inclcode, initperiod, lastperiod, r) for r in range(1, max_nfac + 1)
    ]
    batch = estimate_factor_batch(panels, config, backend=backend)
    ssr_b = np.asarray(batch.ssr)
    nobs_b = np.asarray(batch.nobs)
    tss_b = np.asarray(batch.tss)
    for i, nfac in enumerate(range(1, max_nfac + 1)):
        bn[i] = _bai_ng(ssr_b[i], nobs_b[i], int(batch.Tw[i]), nfac)
        ssr_s[i] = ssr_b[i]
        R2_s[:, i] = np.asarray(batch.R2[i])
    tss, nobs, T = float(tss_b[-1]), float(nobs_b[-1]), int(batch.Tw[-1])

    if dynamic:
        est = jnp.asarray(data[:, inclcode == 1])
        Tfull = est.shape[0]
        nlag = config.n_factorlag
        kmax = 1 + nlag * max_nfac
        X_b = np.zeros((max_nfac, Tfull, kmax), data.dtype)
        W_b = np.zeros((max_nfac, Tfull, ns), data.dtype)
        k_real = np.zeros(max_nfac, int)
        est_mask = ~np.isnan(np.asarray(est))
        for i, r in enumerate(range(1, max_nfac + 1)):
            f_r = np.asarray(batch.factor[i])[:, :r]
            x = np.concatenate(
                [
                    np.ones((Tfull, 1), data.dtype),
                    np.asarray(lagmat(jnp.asarray(f_r), range(1, nlag + 1))),
                ],
                axis=1,
            )
            k_real[i] = x.shape[1]
            xm = ~np.isnan(x).any(axis=1)
            X_b[i, :, : x.shape[1]] = np.nan_to_num(x)
            W_b[i] = (est_mask & xm[:, None]).astype(data.dtype)

        resid_b = jax.vmap(
            lambda Xi, Wi: ols_batched_series(est, Xi, Wi)[1]
        )(jnp.asarray(X_b), jnp.asarray(W_b))
        ndf = W_b.sum(axis=1) - k_real[:, None]
        keep = ndf >= config.nt_min_factor
        resid_np = np.where(keep[:, None, :], np.asarray(resid_b), np.nan)

        ones = np.ones(ns, dtype=inclcode.dtype)
        pairs = [
            (r, d) for r in range(1, max_nfac + 1) for d in range(1, r + 1)
        ]
        aw_panels = [
            (resid_np[r - 1], ones, initperiod + nlag, lastperiod, d)
            for r, d in pairs
        ]
        aw_batch = estimate_factor_batch(aw_panels, config, backend=backend)
        aw_ssr = np.asarray(aw_batch.ssr)
        aw_nobs = np.asarray(aw_batch.nobs)
        for j, (r, d) in enumerate(pairs):
            aw[d - 1, r - 1] = _bai_ng(
                aw_ssr[j], aw_nobs[j], int(aw_batch.Tw[j]), d
            )
            ssr_d[d - 1, r - 1] = aw_ssr[j]
            R2_d[:, d - 1, r - 1] = np.asarray(aw_batch.R2[j])

    return FactorNumberEstimateStats(bn, ssr_s, R2_s, aw, ssr_d, R2_d, tss, nobs, T)


def onatski_ed(x, rmax: int = 10, n_iter: int = 4):
    """Onatski (2010) eigenvalue-differences estimator of the number of
    static factors.

    New capability (complements the reference's Bai-Ng ICp2 and the
    Ahn-Horenstein ER, cells 35/37): r_hat = max{ j <= rmax :
    lambda_j - lambda_{j+1} >= delta } where delta is calibrated from the
    near-linear tail of the scree plot — OLS of the eigenvalues
    lambda_{rmax+1..rmax+5} on (j-1)^{2/3}, delta = 2 |slope|, iterated to
    a fixed point.  Robust to weak cross-sectional/serial correlation in
    the idiosyncratic terms, where ratio criteria over-select.

    x: (T, N) panel (NaN missing — masked pairwise moments).  The panel is
    standardized per series first (`ops.linalg.standardize_data_np`, the
    same population-std convention as the ALS/EM pipeline): on raw
    heterogeneous-unit data the leading eigenvalues just rank series
    variances.  `n_iter` caps the delta/r_hat recursion; it stops early at
    a fixed point (the recursion can 2-cycle on borderline spectra, in
    which case the n_iter-th iterate is returned).
    Returns (r_hat, eigenvalues, delta).
    """
    from ..ops.linalg import standardize_data_np

    if n_iter < 1:
        raise ValueError(f"n_iter must be >= 1, got {n_iter}")
    x = np.asarray(x, float)
    xc, m, _ = standardize_data_np(x)
    xc = np.nan_to_num(xc)  # constant series standardize to NaN; drop them
    n_pair = np.maximum(m.T.astype(float) @ m.astype(float), 1.0)
    S = (xc.T @ xc) / n_pair
    lam = np.linalg.eigvalsh(0.5 * (S + S.T))[::-1]  # descending

    # the tail regression reads eigenvalues rmax .. rmax+4 (0-based)
    if rmax + 5 > lam.size:
        raise ValueError(
            f"rmax={rmax} needs at least rmax+5 <= N={lam.size} eigenvalues"
        )
    j0 = rmax + 1
    for _ in range(n_iter):
        js = np.arange(j0, j0 + 5)
        Z = np.column_stack([np.ones(5), (js - 1.0) ** (2.0 / 3.0)])
        beta = np.linalg.lstsq(Z, lam[js - 1], rcond=None)[0]
        delta = 2.0 * abs(beta[1])
        diffs = lam[:rmax] - lam[1 : rmax + 1]
        above = np.flatnonzero(diffs >= delta)
        r_hat = int(above[-1] + 1) if above.size else 0
        if r_hat + 1 == j0:  # fixed point
            break
        j0 = r_hat + 1
    return r_hat, lam, float(delta)
