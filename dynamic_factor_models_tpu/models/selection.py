"""Factor-number selection: Bai-Ng ICp2, Amengual-Watson, Ahn-Horenstein.

Rewrite of reference cells 35-40.  The reference's O(max_nfac^2) loop of full
DFM refits (SURVEY.md section 3.3) is kept serial per r (each fit is already
one jitted while-loop; the fits for different r have different shapes), but
every inner regression is batched.
"""

from __future__ import annotations

import dataclasses

from typing import NamedTuple

import jax.numpy as jnp
import numpy as np

from ..ops.lags import lagmat
from ..ops.linalg import ols_batched_series
from ..ops.masking import fillz, mask_of
from .dfm import DFMConfig, FactorEstimateStats, estimate_factor

__all__ = [
    "bai_ng_criterion",
    "amengual_watson_test",
    "estimate_factor_numbers",
    "ahn_horenstein_er",
    "FactorNumberEstimateStats",
]


def bai_ng_criterion(fes: FactorEstimateStats, nfac_t: int) -> jnp.ndarray:
    """Bai-Ng ICp2 with unbalanced-panel-adjusted counts (reference cell 35)."""
    nbar = fes.nobs / fes.T
    g = jnp.log(jnp.minimum(nbar, fes.T)) * (nbar + fes.T) / fes.nobs
    return jnp.log(fes.ssr / fes.nobs) + nfac_t * g


class FactorNumberEstimateStats(NamedTuple):
    """Selection-statistics bundle (reference cell 37)."""

    bn_icp: np.ndarray  # (max_nfac,)
    ssr_static: np.ndarray  # (max_nfac,)
    R2_static: np.ndarray  # (ns, max_nfac)
    aw_icp: np.ndarray  # (max_nfac, max_nfac), NaN above diagonal
    ssr_dynamic: np.ndarray
    R2_dynamic: np.ndarray  # (ns, max_nfac, max_nfac)
    tss: float
    nobs: float
    T: int

    @property
    def trace_r2(self) -> np.ndarray:
        return 1.0 - self.ssr_static / self.tss

    @property
    def marginal_r2(self) -> np.ndarray:
        tr = self.trace_r2
        return np.concatenate([tr[:1], np.diff(tr)])


def ahn_horenstein_er(marginal_r2: np.ndarray) -> np.ndarray:
    """Ahn-Horenstein eigenvalue-ratio criterion from marginal trace R^2
    (driver cell 31/35 convention: ER_r = margR2_r / margR2_{r+1})."""
    return marginal_r2[:-1] / marginal_r2[1:]


def amengual_watson_test(
    data,
    inclcode,
    factor,
    initperiod: int,
    lastperiod: int,
    config: DFMConfig,
    nfac_static: int,
):
    """Number-of-dynamic-factors test (reference cell 40).

    Residualize each included series on [1, lags 1..p of the static factors]
    over the full sample, then re-estimate static DFMs of every order on the
    residual panel (window shifted +nlag) and return their Bai-Ng values.
    """
    data = jnp.asarray(data)
    inclcode = np.asarray(inclcode)
    est = data[:, inclcode == 1]
    T, ns = est.shape
    nlag = config.n_factorlag

    x = jnp.hstack(
        [jnp.ones((T, 1), data.dtype), lagmat(jnp.asarray(factor), range(1, nlag + 1))]
    )
    xm = mask_of(x).all(axis=1)
    W = (mask_of(est) & xm[:, None]).astype(data.dtype)
    _, resid = ols_batched_series(est, fillz(x), W)
    ndf = W.sum(axis=0) - x.shape[1]
    keep = ndf >= config.nt_min_factor
    resid = jnp.where(keep[None, :], resid, jnp.nan)

    aw = np.full(nfac_static, np.nan)
    ssr = np.full(nfac_static, np.nan)
    r2 = np.full((ns, nfac_static), np.nan)
    ones = np.ones(ns, dtype=inclcode.dtype)
    for nfac_d in range(1, nfac_static + 1):
        cfg_d = dataclasses.replace(config, nfac_u=nfac_d, nfac_o=0)
        _, fes = estimate_factor(
            resid, ones, initperiod + nlag, lastperiod, cfg_d
        )
        aw[nfac_d - 1] = float(bai_ng_criterion(fes, nfac_d))
        ssr[nfac_d - 1] = float(fes.ssr)
        r2[:, nfac_d - 1] = np.asarray(fes.R2)
    return aw, ssr, r2


def estimate_factor_numbers(
    data,
    inclcode,
    initperiod: int,
    lastperiod: int,
    config: DFMConfig,
    max_nfac: int,
    dynamic: bool = True,
) -> FactorNumberEstimateStats:
    """Fit DFMs for r = 1..max_nfac and collect selection statistics
    (reference cell 39).  Set dynamic=False to skip the O(r^2)
    Amengual-Watson refits."""
    inclcode = np.asarray(inclcode)
    ns = int((inclcode == 1).sum())
    bn = np.full(max_nfac, np.nan)
    ssr_s = np.full(max_nfac, np.nan)
    R2_s = np.full((ns, max_nfac), np.nan)
    aw = np.full((max_nfac, max_nfac), np.nan)
    ssr_d = np.full((max_nfac, max_nfac), np.nan)
    R2_d = np.full((ns, max_nfac, max_nfac), np.nan)
    tss = nobs = T = None
    for i, nfac in enumerate(range(1, max_nfac + 1)):
        cfg = dataclasses.replace(config, nfac_u=nfac)
        factor, fes = estimate_factor(data, inclcode, initperiod, lastperiod, cfg)
        bn[i] = float(bai_ng_criterion(fes, nfac))
        ssr_s[i] = float(fes.ssr)
        R2_s[:, i] = np.asarray(fes.R2)
        if dynamic:
            aw[: nfac, i], ssr_d[: nfac, i], R2_d[:, : nfac, i] = amengual_watson_test(
                data, inclcode, factor, initperiod, lastperiod, cfg, nfac
            )
        tss, nobs, T = float(fes.tss), float(fes.nobs), fes.T
    return FactorNumberEstimateStats(bn, ssr_s, R2_s, aw, ssr_d, R2_d, tss, nobs, T)
