"""Dynamic factor model estimation: iterated PCA / alternating least squares.

TPU-native rewrite of the reference estimation core (dfm_functions.ipynb
cells 4-7, 20-21, 25, 27).  The reference's ALS loop — per-series OLS for
loadings, then per-period OLS for factors, until the SSR change falls below
tol*T*ns (cell 20:25-43) — becomes a ``lax.while_loop`` whose body is two
batched masked normal-equation solves, entirely inside ``jit``:

    lambda-step:  for all series i at once:   (F'W_i F) lam_i = F'W_i x_i
    F-step:       for all periods t at once:  (L'W_t L) f_t  = L'W_t x_t

with W the observation mask.  Series failing the minimum-observation rule are
excluded by zero weights (the reference leaves their loadings `missing`, which
drops them from every per-period regression — same effect).

Missing-data semantics match the reference exactly: tss/nobs bookkeeping over
observed entries of the standardized window (cell 20:15-16), the
sqrt((n-1)/n) population-std correction (cell 25), and the convergence rule
|SSR_old - SSR| < tol*T*ns (cell 20:41).
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.lags import uar
from ..ops.linalg import (
    ols_batched_series,
    pca_score,
    pca_score_np,
    solve_normal,
    standardize_data,
    standardize_data_np,
)
from ..ops.masking import compact, fillz, mask_of
from ..utils.backend import on_backend
from ..utils.telemetry import span
from .constraints import LambdaConstraint, apply_constraint_batch
from .var import VARResults, estimate_var

__all__ = [
    "DFMConfig",
    "FactorEstimateStats",
    "DFMResults",
    "BatchFactorResults",
    "RollingFactorResults",
    "estimate_factor",
    "estimate_factor_batch",
    "estimate_factor_loading",
    "rolling_factor_estimates",
    "estimate_dfm",
    "compute_series",
]


@dataclasses.dataclass(frozen=True)
class DFMConfig:
    """Hyperparameters of the DFM (reference cells 6-7 + driver cell 15)."""

    nfac_u: int = 1  # unobserved factors
    nfac_o: int = 0  # observed factors (reference declares but never exercises)
    nt_min_factor: int = 20  # min obs for a series to enter factor estimation
    nt_min_loading: int = 40  # min obs for a series to get a loading
    tol: float = 1e-8  # ALS convergence tolerance (scaled by T*ns)
    n_uarlag: int = 4  # idiosyncratic AR lags
    n_factorlag: int = 4  # factor-VAR lags
    max_iter: int = 200_000

    @property
    def nfac_t(self) -> int:
        return self.nfac_o + self.nfac_u


class FactorEstimateStats(NamedTuple):
    """SSR/TSS bookkeeping of the factor stage (reference cell 4)."""

    T: int
    ns: int
    nobs: jnp.ndarray
    tss: jnp.ndarray
    ssr: jnp.ndarray
    R2: jnp.ndarray  # per included series, NaN where below nt_min
    n_iter: jnp.ndarray
    # polish="float64" only: whether the host f64 polish converged within
    # its cap (None when no polish ran).  A capped polish means the
    # returned factors may still depend on the starting iterate — the
    # cross-backend parity guarantee is void, so the flag rides along
    # into bench evidence instead of being discarded.
    polish_converged: bool | None = None


class DFMResults(NamedTuple):
    factor: jnp.ndarray  # (T, nfac_t), NaN outside the estimation window
    lam: jnp.ndarray  # (ns, nfac_t) loadings, NaN where below nt_min_loading
    uar_coef: jnp.ndarray  # (ns, n_uarlag) idiosyncratic AR coefficients
    uar_ser: jnp.ndarray  # (ns,) idiosyncratic AR standard errors
    r2: jnp.ndarray  # (ns,) loading-regression R^2
    fes: FactorEstimateStats
    var: VARResults | None  # factor-evolution VAR
    lam_const: jnp.ndarray | None = None  # (ns,) loading-regression intercepts


# ---------------------------------------------------------------------------
# ALS core (jitted)
# ---------------------------------------------------------------------------


@partial(
    jax.jit, static_argnames=("nfac", "nfac_o", "max_iter", "n_constr", "gram_dtype")
)
def _als_core(
    xz,  # (Tw, ns) standardized data, NaN->0
    m,  # (Tw, ns) observation mask (float)
    lam_ok,  # (ns,) series passing nt_min
    f0,  # (Tw, nfac) PCA initialization of the unobserved block
    tol_scaled,  # tol * T * ns
    nfac: int,
    max_iter: int,
    n_constr: int = 0,
    c_series=None,  # (nc,) constrained series indices
    c_R=None,  # (nc, k, nfac_o+nfac)
    c_r=None,  # (nc, k) standardized restriction values
    nfac_o: int = 0,
    fo=None,  # (Tw, nfac_o) observed factors (NaN-free in the window)
    gram_dtype: str | None = None,
    n_iter_cap=None,  # traced iteration cap <= max_iter (shared-budget phases)
):
    from ..ops.pallas_gram import _TPU_PLATFORMS, _context_platform, masked_gram

    W = m * lam_ok[None, :]
    if nfac_o == 0:
        fo = jnp.zeros((xz.shape[0], 0), xz.dtype)

    # gram_dtype="bfloat16": run both Gram contractions on bf16 operands
    # (ops/pallas_gram.py dtype contract — f32 accumulation, f32 Grams) —
    # the HBM-bandwidth option for the large-panel regime.  The panel
    # copies are cast ONCE here, outside the while_loop; solves, factors,
    # and the SSR stay f32, so the loop converges to the bf16-Gram map's
    # fixed point, which estimate_factor's f32 polish phase then refines
    # to the exact one.  Forces the masked_gram path so the semantics are
    # identical (and testable) on every platform.
    gd = None if gram_dtype is None else jnp.dtype(gram_dtype)

    # CPU fast-orientation path: both Gram contractions run as
    # contiguous-reduction GEMMs with packed-symmetric columns, with the
    # loop-invariant transposed copies hoisted out of the while_loop (the
    # PanelStats lesson from models/ssm.py: the strided orientation measures
    # ~5x slower on CPU, and XLA does not hoist transposes of loop
    # constants).  On TPU the natural layout feeds the Pallas kernel /
    # MXU-tiled einsums, so the generic masked_gram path stays.
    fast_cpu = _context_platform() not in _TPU_PLATFORMS and gd is None
    K = nfac_o + nfac
    if gd is not None:
        xz_g = xz.astype(gd)
        m_g = m.astype(gd)
        xzT_g = xz_g.T
        WT_g = W.T.astype(gd)
    if fast_cpu:
        from .ssm import _sym_pack_idx

        iuK, ivK, unpackK = _sym_pack_idx(K)
        iun, ivn, unpackn = _sym_pack_idx(nfac)
        # loop-invariant copies; mask applied EXPLICITLY (callers like
        # multilevel._als_level pass residual panels that are nonzero at
        # masked cells, so zero-filling cannot be assumed here)
        xzm = m * xz  # (Tw, ns)
        mT = jnp.asarray(m.T)  # (ns, Tw)
        xzmT = jnp.asarray(xzm.T)  # (ns, Tw)
        xzW = xzm * lam_ok[None, :]  # == W * xz
        lam_okf = lam_ok.astype(xz.dtype)
        Sxxw0 = (xzW * xz).sum()

    def lam_step(fu):
        # per-series masked Gram (K4's Unbalanced loop) — Pallas at scale;
        # loadings are estimated jointly on [observed, unobserved] factors
        f = jnp.concatenate([fo, fu], axis=1)
        if fast_cpu:
            pair = f[:, iuK] * f[:, ivK]  # (Tw, K(K+1)/2)
            A = (mT @ pair)[:, unpackK].reshape(-1, K, K)
            rhs = xzmT @ f
        elif gd is not None:
            A, rhs = masked_gram(f.astype(gd), xz_g, m_g)
            # Grams are tiny (ns, K, K); solves and the loop carry stay in
            # the panel dtype (f64 under x64 would otherwise clash with
            # the f32 accumulators)
            A, rhs = A.astype(xz.dtype), rhs.astype(xz.dtype)
        else:
            A, rhs = masked_gram(f, xz, m)
        lam = jax.vmap(solve_normal)(A, rhs)
        if n_constr:
            constraint = LambdaConstraint(c_series, c_R, c_r)
            lam = apply_constraint_batch(lam, A, constraint, ok=lam_ok)
        return lam

    def f_step(lam):
        # per-period masked Gram over the unobserved block only: the observed
        # factors' contribution is subtracted from the target first
        lam_o, lam_u = lam[:, :nfac_o], lam[:, nfac_o:]
        if fast_cpu:
            pair_l = (lam_u[:, iun] * lam_u[:, ivn]) * lam_okf[:, None]
            A = (m @ pair_l)[:, unpackn].reshape(-1, nfac, nfac)
            if nfac_o:
                xr = xz - fo @ lam_o.T
                wxr = W * xr
                Sxxw = (wxr * xr).sum()
            else:
                wxr = xzW
                Sxxw = Sxxw0
            rhs = wxr @ lam_u  # (Tw, nfac)
            fu = jax.vmap(solve_normal)(A, rhs)
            # SSR from the same sufficient statistics — no residual panel
            ssr = (
                Sxxw
                - 2.0 * (fu * rhs).sum()
                + jnp.einsum("tk,tkl,tl->", fu, A, fu)
            )
        else:
            xr = xz - fo @ lam_o.T
            if gd is not None:
                # nfac_o == 0 keeps the hoisted bf16 panel transpose; an
                # observed-factor residual changes per iteration and must
                # be re-cast (the Gram read is still halved)
                xrT = xzT_g if nfac_o == 0 else xr.T.astype(gd)
                A, rhs = masked_gram(lam_u.astype(gd), xrT, WT_g)
                A, rhs = A.astype(xz.dtype), rhs.astype(xz.dtype)
            else:
                A, rhs = masked_gram(lam_u, xr.T, W.T)
            fu = jax.vmap(solve_normal)(A, rhs)
            ssr = (W * (xr - fu @ lam_u.T) ** 2).sum()
        return fu, ssr

    cap_eff = (
        max_iter
        if n_iter_cap is None
        else jnp.minimum(jnp.asarray(max_iter, jnp.int32), n_iter_cap)
    )

    def cond(carry):
        _, _, ssr, diff, it = carry
        return (diff >= tol_scaled) & (it < cap_eff)

    def body(carry):
        fu, _, ssr_old, _, it = carry
        lam = lam_step(fu)
        fu, ssr = f_step(lam)
        return fu, lam, ssr, jnp.abs(ssr_old - ssr), it + 1

    lam0 = jnp.zeros((xz.shape[1], nfac_o + nfac), xz.dtype)
    init = (f0, lam0, jnp.asarray(0.0, xz.dtype), jnp.asarray(jnp.inf, xz.dtype), 0)
    fu, lam, ssr, _, n_iter = jax.lax.while_loop(cond, body, init)
    return jnp.concatenate([fo, fu], axis=1), lam, ssr, n_iter


@jax.jit
def _r2_pass(xz, m, f, lam_ok):
    """Final per-series R^2 of x_i on the estimated factors (cell 20:45-52)."""
    _, resid = ols_batched_series(xz, f, m)
    ssr = (fillz(resid) ** 2 * m).sum(axis=0)
    n = m.sum(axis=0)
    ybar = (m * xz).sum(axis=0) / n
    tss = (m * (xz - ybar[None, :]) ** 2).sum(axis=0)
    return jnp.where(lam_ok, 1.0 - ssr / tss, jnp.nan)


def _polish_fixed_point_f64(
    xz,
    m,
    lam_ok,
    f,
    nfac_o: int = 0,
    fo=None,
    tol: float = 1e-11,
    max_iter: int = 4000,
):
    """Host float64 polish of the ALS fixed point.

    Iterates the exact ALS map (lambda-step then F-step, identical
    semantics to `_als_core`: mask-only Grams in the lambda-step,
    mask*lam_ok weights in the F-step, minimum-norm pinv solves) in NumPy
    float64 from the jitted loop's terminal iterate until the max-abs
    factor update falls below `tol`.  Because the map contracts toward its
    fixed point, the ambient-precision (f32) terminal iterate is already in
    the basin; the polish removes the accumulated f32 trajectory error so
    the returned factors sit at the float64 fixed point regardless of the
    ambient JAX precision or backend — this is what closes the north star's
    1e-5 factor-parity bar (the f32 60-iteration trajectory alone diverges
    from f64's by ~8e-5; see docs/PARITY.md).

    Host-side by design: NumPy float64 is available under any JAX x64
    setting and on any backend, and the panels at reference scale are tiny
    (the polish is O(T*ns*K^2) per iteration).  Plain fixed-point iteration
    plus one Aitken/Steffensen extrapolation step every 8 iterations (the
    scalar-secant estimate of the contraction rate applied per-entry-safe,
    on the whole factor block) to cover slowly-contracting spectra.

    Returns (f_full, lam, ssr, n_it, converged) in float64; converged
    False means the iteration hit max_iter with the last update still at
    or above tol (also warned).
    """
    x = np.asarray(xz, np.float64)
    m = np.asarray(m, np.float64)
    ok = np.asarray(lam_ok, np.float64)
    Tw = x.shape[0]
    nfac = f.shape[1] - nfac_o
    fu = np.asarray(f[:, nfac_o:], np.float64)
    fo = (
        np.zeros((Tw, 0), np.float64)
        if nfac_o == 0
        else np.asarray(fo, np.float64)
    )
    K = nfac_o + nfac
    iuK, ivK = np.triu_indices(K)
    iun, ivn = np.triu_indices(nfac)
    W = m * ok[None, :]
    xm = m * x  # masked panel (zero-filled cells stay zero under the mask)

    def lam_step(fu):
        ff = np.concatenate([fo, fu], axis=1)
        pair = ff[:, iuK] * ff[:, ivK]  # (Tw, K(K+1)/2)
        Ap = m.T @ pair  # (ns, packed)
        A = np.empty((m.shape[1], K, K))
        A[:, iuK, ivK] = Ap
        A[:, ivK, iuK] = Ap
        rhs = xm.T @ ff  # (ns, K)
        lam = np.einsum(
            "ikl,il->ik", np.linalg.pinv(A, hermitian=True), rhs
        )
        return lam

    def f_step(lam):
        lam_o, lam_u = lam[:, :nfac_o], lam[:, nfac_o:]
        pair_l = (lam_u[:, iun] * lam_u[:, ivn]) * ok[:, None]
        Ap = m @ pair_l  # (Tw, packed)
        A = np.empty((Tw, nfac, nfac))
        A[:, iun, ivn] = Ap
        A[:, ivn, iun] = Ap
        xr = xm - (m * (fo @ lam_o.T) if nfac_o else 0.0)
        rhs = (xr * ok[None, :]) @ lam_u  # (Tw, nfac)
        fu = np.einsum("tkl,tl->tk", np.linalg.pinv(A, hermitian=True), rhs)
        return fu

    def als_map(fu):
        return f_step(lam_step(fu))

    prev_delta = np.inf
    delta = np.inf
    f_prev = fu
    n_it = 0
    for n_it in range(1, max_iter + 1):
        fu = als_map(f_prev)
        delta = np.abs(fu - f_prev).max()
        if delta < tol:
            break
        # Aitken/Steffensen extrapolation: near the fixed point the error
        # contracts linearly, e_{k+1} ~ rho e_k, so the limit is
        # f + (f_new - f) / (1 - rho) with rho estimated from successive
        # update norms.  Applied only when the rate estimate is stable
        # (0 < rho < 1) and verified by a fresh map application.
        if n_it % 8 == 0 and np.isfinite(prev_delta) and prev_delta > 0:
            rho = delta / prev_delta
            if 1e-3 < rho < 0.999:
                f_ex = fu + (fu - f_prev) * (rho / (1.0 - rho))
                f_chk = als_map(f_ex)
                if np.abs(f_chk - f_ex).max() < delta:
                    fu, delta = f_chk, np.abs(f_chk - f_ex).max()
        f_prev, prev_delta = fu, delta
    if not (delta < tol):
        # a capped, non-converged iterate is NOT a function of the data
        # alone (two backends would polish to different points) — the
        # parity guarantee fails, so say so instead of returning silently
        import warnings

        warnings.warn(
            f"float64 ALS polish did not converge in {max_iter} iterations "
            f"(last update {delta:.3e} >= tol {tol:.1e}); the polished "
            "factors may still depend on the starting iterate",
            stacklevel=3,
        )

    # Canonicalize: the masked ALS map is invariant under any invertible
    # rotation Q of the unobserved block (fu -> fu Q, lam_u -> lam_u Q^-T
    # maps fixed points to fixed points — every masked regression
    # reparametrizes exactly), so fixed points form a GL(nfac) manifold and
    # the polished iterate inherits its trajectory's arbitrary rotation.
    # Project to the standard DFM representative — fu'fu/Tw = I, lam_u'lam_u
    # diagonal descending, column signs fixed by the largest-|loading| entry
    # — so two polishes from different trajectories (f32 vs f64, CPU vs TPU)
    # return the SAME array, not merely the same column space.
    lam_u0 = lam_step(fu)[:, nfac_o:]
    S = _sym_sqrt(fu.T @ fu)
    # pinv, not inv: a rank-deficient panel (effective rank < nfac) drives
    # a factor column to ~0 at the fixed point and S goes singular — the
    # same minimum-norm convention every ALS solve in this module uses
    S_inv = np.linalg.pinv(S, hermitian=True)
    if S[np.diag_indices_from(S)].min() < 1e-10 * max(S.max(), 1.0):
        import warnings

        warnings.warn(
            "float64 ALS polish: factor Gram is (near-)rank-deficient — "
            "the panel supports fewer than nfac factors; null columns are "
            "canonicalized to zero, not noise",
            stacklevel=3,
        )
    F1 = fu @ S_inv * np.sqrt(Tw)
    L1 = lam_u0 @ S / np.sqrt(Tw)
    evals, V = np.linalg.eigh(L1.T @ L1)
    order = np.argsort(evals)[::-1]
    V = V[:, order]
    fu = F1 @ V
    L = L1 @ V
    sign = np.sign(L[np.abs(L).argmax(axis=0), np.arange(L.shape[1])])
    sign[sign == 0] = 1.0
    fu = fu * sign[None, :]

    lam = lam_step(fu)
    lam_u = lam[:, nfac_o:]
    xr_full = x - (fo @ lam[:, :nfac_o].T if nfac_o else 0.0)
    ssr = (W * (xr_full - fu @ lam_u.T) ** 2).sum()
    return np.concatenate([fo, fu], axis=1), lam, ssr, n_it, bool(delta < tol)


def _sym_sqrt(A):
    """Symmetric PSD square root via eigendecomposition (host, float64)."""
    w, V = np.linalg.eigh(A)
    return (V * np.sqrt(np.clip(w, 0.0, None))) @ V.T


def estimate_factor(
    data,
    inclcode,
    initperiod: int,
    lastperiod: int,
    config: DFMConfig,
    constraint: LambdaConstraint | None = None,
    max_iter: int | None = None,
    compute_R2: bool = True,
    observed_factor=None,
    backend: str | None = None,
    gram_dtype: str | None = None,
    polish: str | None = None,
):
    """Iterated-PCA factor extraction (reference cell 20, `estimate_factor!`).

    Window bounds are 0-based inclusive.  Returns (factor, fes) with factor
    full-length, NaN outside the window.

    gram_dtype="bfloat16" (the only non-None value accepted) runs the ALS
    Gram contractions on bf16 operands
    (mixed precision: f32 accumulation and solves — see ops/pallas_gram.py),
    then polishes with exact-precision iterations from the bf16 fixed
    point, so the returned factors are the EXACT map's fixed point at
    roughly half the Gram memory traffic per bulk iteration.  The phases
    share the max_iter budget (total n_iter <= max_iter, +1 only when the
    bulk phase exhausts it, since the polish always gets one iteration).
    Default None is the unchanged exact path.

    polish="float64" appends a host-side NumPy float64 fixed-point polish
    (`_polish_fixed_point_f64`) after the jitted loop, so the returned
    factors/SSR sit at the float64 ALS fixed point on ANY backend and
    ambient precision — the north-star 1e-5 factor-parity path.  Not
    supported together with `constraint` (the polish iterates the
    unconstrained map).

    `observed_factor` (T, nfac_o) supplies the observed factors when
    config.nfac_o > 0 — the FAVAR-style capability the reference declares
    (`nfac_o`, dfm_functions.ipynb cells 6-7) but never implements: observed
    factors enter every loading regression; only the unobserved block is
    solved for in the F-step.  Output factor columns are ordered
    [observed, unobserved].
    """
    from ..utils.compile import configure_compilation_cache

    configure_compilation_cache()
    if gram_dtype not in (None, "bfloat16"):
        # fp16's 5-bit exponent overflows on ordinary standardized panels;
        # only bf16 (f32 exponent range) is a safe Gram operand narrowing
        raise ValueError(
            f"gram_dtype must be None or 'bfloat16', got {gram_dtype!r}"
        )
    if polish not in (None, "float64"):
        raise ValueError(f"polish must be None or 'float64', got {polish!r}")
    if polish is not None and constraint is not None:
        # the host polish iterates the unconstrained ALS map; silently
        # dropping the constraint would return a different fixed point
        raise ValueError("polish='float64' is not supported with a constraint")
    if config.nfac_o:
        if observed_factor is None:
            raise ValueError("config.nfac_o > 0 requires observed_factor")
        observed_factor = jnp.asarray(observed_factor)
        if observed_factor.ndim != 2 or observed_factor.shape[1] != config.nfac_o:
            raise ValueError(
                f"observed_factor must be 2-D with config.nfac_o = "
                f"{config.nfac_o} columns, got shape {observed_factor.shape}"
            )
        if observed_factor.shape[0] != np.asarray(data).shape[0]:
            raise ValueError(
                f"observed_factor must be full-length like data "
                f"({np.asarray(data).shape[0]} rows, the window is sliced "
                f"internally), got {observed_factor.shape[0]} rows"
            )
    from ..utils.telemetry import run_record

    with on_backend(backend), run_record(
        "estimate_factor",
        config={
            "gram_dtype": gram_dtype, "polish": polish,
            "constrained": constraint is not None, "nfac_o": config.nfac_o,
        },
    ) as rec:
        data = jnp.asarray(data)
        inclcode = np.asarray(inclcode)
        est = data[:, inclcode == 1]
        xw = est[initperiod : lastperiod + 1]
        Tw, ns = xw.shape
        nfac = config.nfac_u
        rec.set(shapes={"T": int(Tw), "N": int(ns), "r": int(nfac)})

        xstd, stds = standardize_data(xw)
        mask = mask_of(xstd)
        m = mask.astype(xstd.dtype)
        xz = fillz(xstd)

        tss = (xz**2 * m).sum()
        nobs = m.sum()
        lam_ok = m.sum(axis=0) >= config.nt_min_factor

        fo_kwargs = {}
        fo = None
        if config.nfac_o:
            fo = observed_factor[initperiod : lastperiod + 1].astype(xz.dtype)
            if not bool(np.asarray(mask_of(fo).all())):
                raise ValueError("observed_factor must be NaN-free in the window")
            fo_kwargs = dict(nfac_o=config.nfac_o, fo=fo)

        # PCA init on the fully-balanced column block (cells 9-10, 20:18-21);
        # with observed factors, on that block's residual after projecting
        # them out, so the unobserved block starts orthogonal to them
        balanced = np.asarray(mask.all(axis=0))
        if int(balanced.sum()) < nfac:
            raise ValueError(
                f"nfac_u={nfac} exceeds the {int(balanced.sum())} fully-observed "
                "series available for PCA initialization in this window"
            )
        xb = xz[:, balanced]
        if fo is not None:
            bo = solve_normal(fo.T @ fo, fo.T @ xb)
            xb = xb - fo @ bo
        f0 = pca_score(xb, nfac)

        kwargs = {}
        n_constr = 0
        if constraint is not None:
            n_constr = len(constraint.series)
            kwargs = dict(
                c_series=jnp.asarray(constraint.series),
                c_R=constraint.R,
                c_r=constraint.standardized(stds),
            )
        with span("als_core"):
            tol_scaled = config.tol * Tw * ns
            cap = max_iter if max_iter is not None else config.max_iter
            phase2_kwargs = {}
            if gram_dtype is not None:
                # phase 1: bulk iterations on bf16 Grams to (near) the
                # reduced-precision fixed point, under a LOOSENED tolerance
                # (the bf16 map's SSR fluctuates at operand precision near
                # its fixed point, so the caller's tight tol would never
                # trigger and the bulk would burn the whole budget).  The
                # two phases SHARE the caller's max_iter budget (n_iter
                # stays a valid convergence flag); the polish always gets
                # >= 1 iteration so its outputs are real even when phase 1
                # exhausts cap
                bulk_tol_scaled = max(config.tol, 1e-4) * Tw * ns
                f1, _, _, n1 = _als_core(
                    xz, m, lam_ok, f0, bulk_tol_scaled, nfac, cap, n_constr,
                    **kwargs, **fo_kwargs, gram_dtype=gram_dtype,
                )
                f0 = f1[:, config.nfac_o :]
                n_pre = n1
                phase2_kwargs = dict(
                    n_iter_cap=jnp.maximum(
                        jnp.asarray(cap, jnp.int32) - n1.astype(jnp.int32), 1
                    )
                )
            else:
                n_pre = 0
            # phase 2 (or the only phase): exact-precision iterations
            f, lam, ssr, n_iter = _als_core(
                xz,
                m,
                lam_ok,
                f0,
                tol_scaled,
                nfac,
                cap,
                n_constr,
                **kwargs,
                **fo_kwargs,
                **phase2_kwargs,
            )
            n_iter = n_iter + n_pre

        polish_converged = None
        if polish is not None:
            with span("als_polish_f64"):
                f_np, lam_np, ssr_np, _, polish_converged = (
                    _polish_fixed_point_f64(
                        np.asarray(xz),
                        np.asarray(m),
                        np.asarray(lam_ok),
                        np.asarray(f),
                        nfac_o=config.nfac_o,
                        fo=None if fo is None else np.asarray(fo),
                    )
                )
                f = jnp.asarray(f_np, xz.dtype)
                ssr = jnp.asarray(ssr_np, xz.dtype)

        R2 = _r2_pass(xz, m, f, lam_ok) if compute_R2 else jnp.full(ns, jnp.nan)
        factor = jnp.full((data.shape[0], config.nfac_t), jnp.nan, data.dtype)
        factor = factor.at[initperiod : lastperiod + 1].set(f)
        fes = FactorEstimateStats(
            Tw, ns, nobs, tss, ssr, R2, n_iter, polish_converged
        )
        if rec.active:  # int()/float() force a device sync — telemetry only
            rec.set(
                n_iter=int(n_iter),
                converged=bool(int(n_iter) < cap),
                final_loglik=None,  # ALS objective is SSR, not a loglik
                ssr=float(ssr),
            )
        return factor, fes


# ---------------------------------------------------------------------------
# batched factor extraction: many ALS fits in one vmapped while_loop
# ---------------------------------------------------------------------------


@partial(jax.jit, static_argnames=("rmax", "max_iter", "compute_R2"))
def _als_core_batch(
    xz, m, lam_ok, f0, tol_scaled, rmax: int, max_iter: int, compute_R2: bool = True
):
    """vmap of `_als_core` over a leading batch axis.

    Inert-padding semantics make heterogeneous fits batchable with one
    static shape: factor columns beyond an element's r are exactly zero in
    f0 and stay zero through the iteration (a zero column produces zero
    rows/cols in every Gram matrix, and the eigh-based pinv of
    `solve_normal` zeroes those components of the solution), and rows
    outside an element's sample window carry zero weight, so they drop out
    of every contraction.  JAX's while_loop batching rule freezes elements
    whose own tolerance test has passed, so per-element convergence matches
    the sequential runs.
    """

    def one(xz_i, m_i, ok_i, f0_i, tol_i):
        f, lam, ssr, n_iter = _als_core(
            xz_i, m_i, ok_i, f0_i, tol_i, rmax, max_iter
        )
        r2 = (
            _r2_pass(xz_i, m_i, f, ok_i)
            if compute_R2
            else jnp.full(xz_i.shape[1], jnp.nan, xz_i.dtype)
        )
        return f, lam, ssr, n_iter, r2

    return jax.vmap(one)(xz, m, lam_ok, f0, tol_scaled)


class BatchFactorResults(NamedTuple):
    """Stacked outputs of `estimate_factor_batch` (leading axis = element)."""

    factor: jnp.ndarray  # (B, T, rmax), NaN outside window / beyond r
    lam: jnp.ndarray  # (B, ns, rmax)
    ssr: jnp.ndarray  # (B,)
    tss: jnp.ndarray  # (B,)
    nobs: jnp.ndarray  # (B,)
    Tw: np.ndarray  # (B,) window lengths
    n_iter: jnp.ndarray  # (B,)
    R2: jnp.ndarray  # (B, ns)
    nfac: np.ndarray  # (B,) active factor counts


def estimate_factor_batch(
    panels,
    config: DFMConfig,
    max_iter: int | None = None,
    backend: str | None = None,
    mesh=None,
    compute_R2: bool = True,
) -> BatchFactorResults:
    """Run many independent ALS factor extractions as ONE vmapped while_loop.

    `panels` is a sequence of (data, inclcode, initperiod, lastperiod, nfac)
    tuples that share the panel shape after inclcode selection.  This is the
    fan-out the reference runs serially — `estimate_factor_numbers`'s
    O(max_nfac^2) refit loop and the Figure 3/6 sample-window sweeps
    (SURVEY.md section 3.3: "embarrassingly parallel across nfac") — turned
    into a single batched program: elements are padded to a common
    (T, ns, rmax) shape with inert zero factor columns and zero-weight
    out-of-window rows (see `_als_core_batch`), standardization/PCA
    initialization happen per element on host, and one jit covers every fit.

    Pass `mesh` (a 1-D jax.sharding.Mesh, any axis name) to shard the batch
    axis across its devices: each chip runs its shard of the fits with no
    cross-chip traffic until the results gather — the sweep-fan-out design
    of SURVEY.md section 3.3.  The batch is padded to a device-count
    multiple with duplicates of the first element (dropped on return).

    Observed factors and loading constraints are not supported in the batch
    path; use the serial `estimate_factor` for those fits.
    """
    if config.nfac_o:
        raise ValueError(
            "estimate_factor_batch does not support observed factors "
            "(config.nfac_o > 0); use estimate_factor per fit"
        )
    rmax = max(int(p[4]) for p in panels)
    B_real = len(panels)
    if mesh is not None:
        n_dev = int(np.prod(mesh.devices.shape))
        pad = (-B_real) % n_dev
        panels = list(panels) + [panels[0]] * pad
    xzs, ms, oks, f0s, tols, Tws, nfacs = [], [], [], [], [], [], []
    for data, inclcode, initperiod, lastperiod, nfac in panels:
        est = np.asarray(data)[:, np.asarray(inclcode) == 1]
        T, ns = est.shape
        xw = np.full_like(est, np.nan)
        xw[initperiod : lastperiod + 1] = est[initperiod : lastperiod + 1]
        # population-std standardization (quirk 2.5-6) + PCA init via the
        # NumPy twins of the jitted kernels (ops/linalg.py)
        xz, m, _ = standardize_data_np(xw)
        lam_ok = m.sum(axis=0) >= config.nt_min_factor
        Tw = lastperiod - initperiod + 1
        balanced = m[initperiod : lastperiod + 1].all(axis=0)
        if int(balanced.sum()) < nfac:
            raise ValueError(
                f"nfac={nfac} exceeds the {int(balanced.sum())} fully-observed "
                "series available for PCA initialization in this window"
            )
        xb = xz[initperiod : lastperiod + 1][:, balanced]
        f0 = np.zeros((T, rmax), est.dtype)
        f0[initperiod : lastperiod + 1, :nfac] = pca_score_np(xb, nfac)
        xzs.append(xz)
        ms.append(m.astype(est.dtype))
        oks.append(lam_ok)
        f0s.append(f0)
        tols.append(config.tol * Tw * ns)
        Tws.append(Tw)
        nfacs.append(nfac)

    with on_backend(backend):
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec

            axis = mesh.axis_names[0]
            put = lambda a, nd: jax.device_put(
                a, NamedSharding(mesh, PartitionSpec(axis, *([None] * (nd - 1))))
            )
        else:
            put = lambda a, nd: jnp.asarray(a)
        xz_b = put(np.stack(xzs), 3)
        m_b = put(np.stack(ms), 3)
        ok_b = put(np.stack(oks), 2)
        f0_b = put(np.stack(f0s), 3)
        tol_b = put(np.stack(tols).astype(xzs[0].dtype), 1)
        with span("als_core_batch"):
            f, lam, ssr, n_iter, r2 = _als_core_batch(
                xz_b,
                m_b,
                ok_b,
                f0_b,
                tol_b,
                rmax,
                max_iter if max_iter is not None else config.max_iter,
                compute_R2,
            )
        # NaN outside each element's window and beyond its active r
        active = jnp.asarray(np.arange(rmax)[None, :] < np.asarray(nfacs)[:, None])
        rows = []
        for data, inclcode, initperiod, lastperiod, nfac in panels:
            row = np.zeros(xz_b.shape[1], bool)
            row[initperiod : lastperiod + 1] = True
            rows.append(row)
        in_window = jnp.asarray(np.stack(rows))
        f = jnp.where(in_window[:, :, None] & active[:, None, :], f, jnp.nan)
        lam = jnp.where(active[:, None, :], lam, jnp.nan)
        tss = (xz_b**2 * m_b).sum(axis=(1, 2))
        nobs = m_b.sum(axis=(1, 2))
        return BatchFactorResults(
            f[:B_real],
            lam[:B_real],
            ssr[:B_real],
            tss[:B_real],
            nobs[:B_real],
            np.asarray(Tws)[:B_real],
            n_iter[:B_real],
            r2[:B_real],
            np.asarray(nfacs)[:B_real],
        )


class RollingFactorResults(NamedTuple):
    starts: np.ndarray  # (B,) first panel row of each window
    window: int
    batch: BatchFactorResults  # factor rows are WINDOW-relative (window, rmax)


def rolling_factor_estimates(
    data,
    inclcode,
    window: int,
    nfac: int,
    config: DFMConfig = DFMConfig(),
    step: int = 1,
    initperiod: int = 0,
    lastperiod: int | None = None,
    backend: str | None = None,
    mesh=None,
) -> RollingFactorResults:
    """Rolling-window factor estimation: every window is one element of a
    single `estimate_factor_batch` call.

    The reference studies parameter instability only through one 1984Q4
    split (Stock_Watson.ipynb cell 57); rolling windows are the
    continuous-time version of that exercise — trace R^2 / SSR per window
    tracks how factor structure evolves — and here they cost one batched
    while_loop regardless of the number of windows (shard the batch over a
    mesh for multi-chip).  Window i covers panel rows
    [starts[i], starts[i] + window - 1]; batch elements are SLICED to the
    window (so memory/compute scale with `window`, not the panel length)
    and `batch.factor[i]` rows are window-relative.
    """
    data = np.asarray(data)
    T = data.shape[0]
    last = T - 1 if lastperiod is None else lastperiod
    if not 0 <= initperiod <= last < T:
        raise ValueError(
            f"invalid rows {initperiod}..{last} for a {T}-row panel"
        )
    if not 1 <= window <= last - initperiod + 1:
        raise ValueError(
            f"window={window} does not fit in rows {initperiod}..{last}"
        )
    starts = np.arange(initperiod, last - window + 2, step)
    panels = [
        (data[s : s + window], inclcode, 0, window - 1, nfac) for s in starts
    ]
    batch = estimate_factor_batch(panels, config, backend=backend, mesh=mesh)
    return RollingFactorResults(starts, window, batch)


# ---------------------------------------------------------------------------
# loadings + idiosyncratic AR (reference cell 21)
# ---------------------------------------------------------------------------


@partial(jax.jit, static_argnames=("n_uarlag", "nt_min", "n_constr"))
def _loading_core(
    yw,  # (Tw, ns) raw data window
    fw,  # (Tw, nfac) factors in window
    nt_min: int,
    n_uarlag: int,
    n_constr: int = 0,
    c_series=None,
    c_R=None,  # (nc, k, nfac+1) with const column
    c_r=None,
):
    Tw, ns = yw.shape
    X = jnp.hstack([fillz(fw), jnp.ones((Tw, 1), yw.dtype)])
    # rows where any factor is missing are dropped for every series, matching
    # the reference's drop_missing_row([y fac]) (cell 21:7)
    W = (mask_of(yw) & mask_of(fw).all(axis=1)[:, None]).astype(yw.dtype)
    A = jnp.einsum("tk,ti,tl->ikl", X, W, X)
    rhs = jnp.einsum("tk,ti->ik", X, W * fillz(yw))
    b = jax.vmap(solve_normal)(A, rhs)  # (ns, nfac+1)
    count = W.sum(axis=0)
    ok = count >= nt_min
    if n_constr:
        constraint = LambdaConstraint(c_series, c_R, c_r)
        b = apply_constraint_batch(b, A, constraint, ok=ok)

    e = jnp.where(W.astype(bool), fillz(yw) - X @ b.T, jnp.nan)
    ssr = (fillz(e) ** 2 * W).sum(axis=0)
    ybar = (W * fillz(yw)).sum(axis=0) / count
    tss = (W * (fillz(yw) - ybar[None, :]) ** 2).sum(axis=0)
    r2 = 1.0 - ssr / tss

    def fit_uar(e_i, w_i):
        vals, valid = compact(e_i, w_i)
        return uar(vals, n_uarlag, valid)

    coef, ser = jax.vmap(fit_uar, in_axes=1)(e, W.astype(bool))
    # R^2 ~ 1: residual is numerically zero; reference zeroes the AR
    degenerate = r2 >= 0.9999
    coef = jnp.where(degenerate[:, None], 0.0, coef)
    ser = jnp.where(degenerate, 0.0, ser)

    # series below nt_min: no estimate (the reference silently reuses the
    # previous series' AR state here — SURVEY.md section 2.5 quirk 3, fixed)
    lam = jnp.where(ok[:, None], b[:, :-1], jnp.nan)
    r2 = jnp.where(ok, r2, jnp.nan)
    coef = jnp.where(ok[:, None], coef, jnp.nan)
    ser = jnp.where(ok, ser, jnp.nan)
    const = jnp.where(ok, b[:, -1], jnp.nan)
    return lam, r2, coef, ser, const


def estimate_factor_loading(
    data,
    factor,
    initperiod: int,
    lastperiod: int,
    config: DFMConfig,
    constraint: LambdaConstraint | None = None,
    backend: str | None = None,
):
    """Full-sample loadings + idiosyncratic AR(n_uarlag) per series (cell 21).

    Runs over ALL panel columns (not just inclcode==1).  Returns
    (lam, r2, uar_coef, uar_ser, const) with const the regression intercepts
    (the level term the forecasting layer needs).
    """
    with on_backend(backend):
        data = jnp.asarray(data)
        yw = data[initperiod : lastperiod + 1]
        fw = jnp.asarray(factor)[initperiod : lastperiod + 1]
        kwargs = {}
        n_constr = 0
        if constraint is not None:
            n_constr = len(constraint.series)
            kwargs = dict(
                c_series=jnp.asarray(constraint.series),
                c_R=constraint.with_const_column(),
                c_r=constraint.r,
            )
        return _loading_core(
            yw, fw, config.nt_min_loading, config.n_uarlag, n_constr, **kwargs
        )


# ---------------------------------------------------------------------------
# full pipeline (reference cell 27, `estimate!`)
# ---------------------------------------------------------------------------


def estimate_dfm(
    data,
    inclcode,
    initperiod: int,
    lastperiod: int,
    config: DFMConfig = DFMConfig(),
    constraint_factor: LambdaConstraint | None = None,
    constraint_loading: LambdaConstraint | None = None,
    observed_factor=None,
    backend: str | None = None,
    polish: str | None = None,
) -> DFMResults:
    """Non-parametric DFM: factors -> loadings -> factor VAR (cell 27).

    The parametric (state-space EM) path is `models.ssm.estimate_dfm_em` —
    a capability the reference declared but never implemented.
    polish="float64" passes through to `estimate_factor` (the backend- and
    precision-independent canonical fixed point; loadings, the factor VAR,
    and everything downstream then inherit it).
    """
    with on_backend(backend):
        factor, fes = estimate_factor(
            data,
            inclcode,
            initperiod,
            lastperiod,
            config,
            constraint_factor,
            observed_factor=observed_factor,
            polish=polish,
        )
        lam, r2, uar_coef, uar_ser, lam_const = estimate_factor_loading(
            data, factor, initperiod, lastperiod, config, constraint_loading
        )
        var = estimate_var(
            factor, config.n_factorlag, initperiod, lastperiod, withconst=True
        )
        return DFMResults(factor, lam, uar_coef, uar_ser, r2, fes, var, lam_const)


def compute_series(results: DFMResults, series_idx) -> jnp.ndarray:
    """Common component F lam_i' of one or more series (reference cell 28)."""
    return results.factor @ results.lam[series_idx].T
