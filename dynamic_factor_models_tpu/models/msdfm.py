"""Markov-switching dynamic factor model (Kim-Nelson / Chauvet).

The classic business-cycle-dating DFM (Chauvet 1998; Kim-Nelson 1999 ch.5;
the model behind Chauvet-Piger recession probabilities) — a single common
factor whose MEAN switches with a latent Markov regime:

    x_t = lam (mu_{S_t} + z_t) + e_t,   e_t ~ N(0, diag(R))
    z_t = phi z_{t-1} + u_t,            u_t ~ N(0, sigma2_{S_t})
    S_t in {0..M-1},  P[i, j] = Pr(S_t = j | S_{t-1} = i)

with sigma2_0 = 1 fixed as the scale anchor.  The plain mean-switching
model is sigma2 = ones (the fit default); `switching_variance=True` frees
the remaining variances — Kim-Nelson ch.5 switching volatility, the
innovation variance entering with the ARRIVING regime.

The reference has nothing in this family; the spec is the papers.

TPU-first design:
  * the observation enters ONLY through the Jungbacker-Koopman collapsed
    statistics (ssm._collapse_obs with Hq = lam): per-step scalars
    C_t = lam'R^-1 lam, b_t = lam'R^-1 x_t, x'R^-1x, log|R|_obs — two
    (T, N) GEMMs precomputed before the scan, so the Kim filter's scan
    body is O(M^2) scalar algebra with no N-dependence;
  * the regime-switching mean shifts the observation intercept only, so
    the regime-pair branches differ in MEANS and (through Kim collapse
    spread) variances — all (M, M) pairs evaluated by broadcasting inside
    one ``lax.scan`` step (M = 2 default, any M compiles);
  * the exact Kim (1994) moment-matching collapse: per-regime posterior
    means/variances re-mixed each step (variance carries the cross-regime
    mean spread);
  * estimation is DIFFERENTIABLE maximum likelihood: the filter loglik is
    a pure jax function of the parameters, maximized with optax.adam
    under an unconstrained reparametrization (softplus/tanh/sigmoid) —
    the JAX-native alternative to Kim-Nelson's approximate EM.

Mask semantics as everywhere in this framework: NaN = missing, collapsed
statistics weight missing rows to zero exactly.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.masking import fillz, mask_of
from ..utils.backend import on_backend
from .ssm import _collapse_obs

__all__ = [
    "MSDFMParams",
    "MSDFMResults",
    "MSForecast",
    "MSStandardErrors",
    "ms_standard_errors",
    "kim_filter",
    "kim_smoother_probs",
    "fit_ms_dfm",
    "forecast_ms",
]

_LOG2PI = float(np.log(2.0 * np.pi))


class MSDFMParams(NamedTuple):
    """lam: (N,) loadings; R: (N,) idio variances; mu: (M,) regime means
    (ascending by convention — regime 0 is the low-mean/recession state);
    phi: AR(1) coefficient of the demeaned factor; P: (M, M) transition
    matrix, rows sum to 1; sigma2: (M,) regime-dependent factor-innovation
    variances (Kim-Nelson ch.5 switching volatility).  sigma2[0] = 1 is
    the scale anchor — the plain mean-switching model has sigma2 = ones."""

    lam: jnp.ndarray
    R: jnp.ndarray
    mu: jnp.ndarray
    phi: jnp.ndarray
    P: jnp.ndarray
    sigma2: jnp.ndarray

    @property
    def n_regimes(self) -> int:
        return self.mu.shape[0]


class MSDFMResults(NamedTuple):
    params: MSDFMParams
    loglik: float
    filt_probs: jnp.ndarray  # (T, M) Pr(S_t | x_{1:t})
    smoothed_probs: jnp.ndarray  # (T, M) Pr(S_t | x_{1:T})
    factor: jnp.ndarray  # (T,) E[mu_{S_t} + z_t | x_{1:t}] filtered factor
    loss_path: np.ndarray  # optimizer loss per step
    stds: jnp.ndarray
    means: jnp.ndarray


def _kim_scan(params: MSDFMParams, x, mask):
    """Shared Kim recursion; returns (lls (T,), filt_probs, pred_probs,
    m_filt, P_filt).  `kim_filter` sums the step terms; the OPG standard
    errors differentiate them individually."""
    M = params.n_regimes
    dtype = x.dtype
    lam = params.lam[:, None]  # (N, 1)
    C, b, ld_R, xRx, n_obs = _collapse_obs(
        lam, params.R, fillz(x), mask.astype(dtype)
    )
    C = C[:, 0, 0]  # (T,) scalar information
    b = b[:, 0]  # (T,)
    mu = params.mu  # (M,)
    phi = params.phi
    Pm = params.P  # (M, M) rows: from-regime i
    log_Pm = jnp.log(jnp.clip(Pm, 1e-30, 1.0))

    sig2 = params.sigma2  # (M,) innovation variance entering WITH regime j

    # stationary init for z; uniform-ish regime prior from P's stationarity
    # (simple uniform keeps the filter parameter-smooth for the optimizer)
    m0 = jnp.zeros(M, dtype)
    P0 = sig2 / jnp.maximum(1.0 - phi**2, 1e-3)
    p0 = jnp.full(M, 1.0 / M, dtype)

    def step(carry, inp):
        m_i, P_i, logp_i = carry  # per-regime (M,), (M,), (M,) log probs
        Ct, bt, ldt, xRxt, nt = inp

        # per-pair prediction (i -> j): the mean recursion is regime-free;
        # the innovation variance enters with the ARRIVING regime j
        a = phi * m_i  # (M,) predicted mean, indexed by i
        Pp = phi**2 * P_i[:, None] + sig2[None, :]  # (i, j) predicted var

        # regime-j observation: x_t - lam*mu_j = lam z_t + e
        b_j = bt - Ct * mu  # (M,) indexed by j
        xRx_j = xRxt - 2.0 * mu * bt + Ct * mu**2  # (M,)

        # information update per (i, j): precision 1/Pp_ij + Ct
        Pu = 1.0 / (1.0 / Pp + Ct)  # (i, j)
        rhs = b_j[None, :] - Ct * a[:, None]  # (i, j) innovation information
        m_u = a[:, None] + Pu * rhs  # (i, j) posterior mean
        # determinant-lemma loglik of the pair (see ssm._info_filter_scan)
        ld_pp = jnp.log(Pp)
        ld_pu = jnp.log(Pu)
        quad0 = xRx_j[None, :] - 2.0 * a[:, None] * b_j[None, :] + Ct * a[:, None] ** 2
        quad = quad0 - rhs * Pu * rhs
        ll_ij = -0.5 * (nt * _LOG2PI + ldt + ld_pp - ld_pu + quad)

        # Hamilton step in log space
        log_joint = logp_i[:, None] + log_Pm + ll_ij  # (i, j)
        step_ll = jax.scipy.special.logsumexp(log_joint)
        log_post = log_joint - step_ll  # normalized log w_ij
        logp_j = jax.scipy.special.logsumexp(log_post, axis=0)  # (j,)
        w = jnp.exp(log_post - logp_j[None, :])  # (i, j), cols sum to 1

        # Kim collapse: re-mix means, variances carry the mean spread
        m_j = (w * m_u).sum(axis=0)
        P_j = (w * (Pu + (m_u - m_j[None, :]) ** 2)).sum(axis=0)

        pred_probs = jnp.exp(
            jax.scipy.special.logsumexp(logp_i[:, None] + log_Pm, axis=0)
        )
        return (m_j, P_j, logp_j), (
            step_ll,
            jnp.exp(logp_j),
            pred_probs,
            m_j,
            P_j,
        )

    (_, _, _), (lls, filt_probs, pred_probs, m_filt, P_filt) = jax.lax.scan(
        step, (m0, P0, jnp.log(p0)), (C, b, ld_R, xRx, n_obs)
    )
    return lls, filt_probs, pred_probs, m_filt, P_filt


@jax.jit
def kim_filter(params: MSDFMParams, x, mask):
    """Kim (1994) filter on the collapsed observation statistics.

    Returns (loglik, filt_probs (T, M), pred_probs (T, M), m_filt (T, M),
    P_filt (T, M)) where m/P are the per-regime posterior mean/variance of
    the demeaned factor z_t.  Exact Hamilton recursion over regimes; the
    Gaussian branch collapse is Kim's moment-matching approximation.
    """
    lls, filt_probs, pred_probs, m_filt, P_filt = _kim_scan(params, x, mask)
    return lls.sum(), filt_probs, pred_probs, m_filt, P_filt


@jax.jit
def kim_smoother_probs(params: MSDFMParams, filt_probs, pred_probs):
    """Kim (1994) backward smoother for the regime probabilities:
    Pr(S_t | x_{1:T}) from the stored filtered and one-step-ahead
    probabilities."""
    Pm = params.P

    def back(sm_next, inp):
        filt_t, pred_next = inp
        # Pr(S_t=i | T) = filt_i * sum_j P_ij * sm_next_j / pred_next_j
        ratio = sm_next / jnp.maximum(pred_next, 1e-30)
        sm = filt_t * (Pm @ ratio)
        sm = sm / jnp.maximum(sm.sum(), 1e-30)
        return sm, sm

    sm_T = filt_probs[-1]
    _, sm_rev = jax.lax.scan(
        back, sm_T, (filt_probs[:-1][::-1], pred_probs[1:][::-1])
    )
    return jnp.concatenate([sm_rev[::-1], sm_T[None]], axis=0)


def _pack(params: MSDFMParams):
    """Unconstrained reparametrization for gradient-based MLE."""
    mu = params.mu
    dmu = jnp.diff(mu)
    return {
        "lam": params.lam,
        # emit exactly _unpack's clip range [-12, 12]: an R outside
        # [e^-12, e^12] would otherwise land in a clip dead zone whose zero
        # gradient silently kills that coordinate's score, while any R that
        # _unpack itself can emit (including the e^-12 floor a fit can
        # reach) round-trips exactly
        "log_R": jnp.clip(jnp.log(params.R), -12.0, 12.0),
        "mu0": mu[0],
        "log_dmu": jnp.log(jnp.maximum(dmu, 1e-12)),
        # 1e-6 margin: representable in f32 (1 - 1e-9 rounds to 1.0f and
        # arctanh(1) = inf); the round-trip error in phi is <= 1e-6
        "atanh_phi": jnp.arctanh(
            jnp.clip(params.phi / 0.98, -1.0 + 1e-6, 1.0 - 1e-6)
        ),
        "log_P": jnp.log(jnp.clip(params.P, 1e-8, 1.0)),
        # regime innovation variances relative to the regime-0 anchor
        "log_sig": jnp.log(jnp.clip(params.sigma2[1:] / params.sigma2[0], 1e-4, 1e4)),
    }


def _unpack(theta, switching_variance: bool) -> MSDFMParams:
    mu = theta["mu0"] + jnp.concatenate(
        [jnp.zeros(1), jnp.cumsum(jnp.exp(theta["log_dmu"]))]
    )
    P_un = jax.nn.softmax(theta["log_P"], axis=1)
    M = mu.shape[0]
    if switching_variance:
        # sigma2[0] = 1 is the scale anchor (the factor's overall scale is
        # identified by the regime-0 innovation variance)
        sigma2 = jnp.concatenate(
            [jnp.ones(1), jnp.exp(jnp.clip(theta["log_sig"], -8.0, 8.0))]
        )
    else:
        sigma2 = jnp.ones(M)
    return MSDFMParams(
        lam=theta["lam"],
        R=jnp.exp(jnp.clip(theta["log_R"], -12.0, 12.0)),
        mu=mu,
        phi=0.98 * jnp.tanh(theta["atanh_phi"]),
        P=P_un,
        sigma2=sigma2,
    )


@partial(jax.jit, static_argnames=("n_steps", "switching_variance"))
def _fit_adam(theta0, xz_nan, mask, n_steps: int, lr, switching_variance: bool):
    import optax

    opt = optax.adam(lr)

    def loss_fn(theta):
        p = _unpack(theta, switching_variance)
        ll, *_ = kim_filter(p, xz_nan, mask)
        return -ll / xz_nan.shape[0]

    def step(carry, _):
        theta, state = carry
        loss, g = jax.value_and_grad(loss_fn)(theta)
        updates, state = opt.update(g, state, theta)
        theta = optax.apply_updates(theta, updates)
        return (theta, state), loss

    (theta, _), losses = jax.lax.scan(
        step, (theta0, opt.init(theta0)), None, length=n_steps
    )
    return theta, losses


def fit_ms_dfm(
    x,
    n_regimes: int = 2,
    n_steps: int = 600,
    lr: float = 0.02,
    backend: str | None = None,
    seed: int = 0,
    n_restarts: int = 4,
    switching_variance: bool = False,
) -> MSDFMResults:
    """Fit the MS-DFM by differentiable MLE on a (T, N) panel (NaN =
    missing).  The panel is standardized internally; regime 0 is the
    low-mean regime (recession, for business-cycle panels), so
    `results.smoothed_probs[:, 0]` is the recession probability path.

    The MS likelihood is multimodal (a weak-regime mode where the AR
    factor absorbs the switching exists essentially always), so the
    optimizer runs `n_restarts` perturbed initializations — regime means
    seeded from lower/upper quantile means of the first PC — as ONE
    vmapped adam program, and returns the best final likelihood.

    switching_variance=True additionally frees the regime innovation
    variances (Kim-Nelson switching volatility; sigma2[0] = 1 stays the
    scale anchor, so the RATIOS are what is identified and fitted).
    """
    from ..utils.telemetry import run_record

    with on_backend(backend), run_record(
        "fit_ms_dfm",
        config={
            "n_regimes": n_regimes, "n_steps": n_steps, "lr": lr,
            "n_restarts": n_restarts,
            "switching_variance": switching_variance,
        },
    ) as rec:
        from ..ops.linalg import standardize_data

        x = jnp.asarray(x)
        rec.set(shapes={"T": int(x.shape[0]), "N": int(x.shape[1]), "r": 1})
        xstd, stds = standardize_data(x)  # preserves the NaN pattern
        mask = mask_of(xstd)
        n_mean = (fillz(x) * mask).sum(axis=0) / jnp.maximum(mask.sum(axis=0), 1)
        N = x.shape[1]

        # init: loadings from the first PC of the filled panel; regime
        # means from lower/upper quantile means of that factor (data-driven
        # separation); persistence from the factor's own autocorrelation
        from ..ops.linalg import pca_score

        f0 = pca_score(fillz(xstd), 1)[:, 0]
        f0 = f0 / jnp.maximum(f0.std(), 1e-6)
        W = mask.astype(xstd.dtype)
        lam0 = (W * fillz(xstd) * f0[:, None]).sum(0) / jnp.maximum(
            (W * f0[:, None] ** 2).sum(0), 1e-6
        )
        # sign convention: majority-positive loadings so "high mean" = boom
        sgn = jnp.sign(jnp.sign(lam0).sum())
        sgn = jnp.where(sgn == 0, 1.0, sgn)
        lam0, f0 = lam0 * sgn, f0 * sgn
        qs = jnp.quantile(f0, jnp.linspace(0.0, 1.0, n_regimes + 1))

        def _band_mean(k):
            band = (f0 >= qs[k]) & (f0 <= qs[k + 1])
            return jnp.where(band, f0, 0.0).sum() / jnp.maximum(band.sum(), 1)

        mu_grid = jnp.asarray([_band_mean(k) for k in range(n_regimes)])
        phi0 = jnp.clip(
            (f0[1:] * f0[:-1]).mean() / jnp.maximum((f0**2).mean(), 1e-6),
            0.1,
            0.9,
        )
        P0 = jnp.full((n_regimes, n_regimes), 0.1 / max(n_regimes - 1, 1))
        P0 = P0.at[jnp.arange(n_regimes), jnp.arange(n_regimes)].set(0.9)
        init = MSDFMParams(
            lam=lam0,
            R=jnp.ones(N, xstd.dtype),
            mu=jnp.sort(mu_grid).astype(xstd.dtype),
            phi=phi0.astype(xstd.dtype),
            P=P0.astype(xstd.dtype),
            sigma2=jnp.ones(n_regimes, xstd.dtype),
        )

        # perturbed restarts as one vmapped program: jitter the regime
        # separation, base mean, and persistence; restart 0 is the base
        theta0 = _pack(init)
        keys = jax.random.split(jax.random.PRNGKey(seed), 3)
        scale = jnp.concatenate(
            [jnp.zeros(1), 0.6 * jax.random.normal(keys[0], (n_restarts - 1,))]
        )
        mu0_jit = jnp.concatenate(
            [jnp.zeros(1), 0.4 * jax.random.normal(keys[1], (n_restarts - 1,))]
        )
        phi_jit = jnp.concatenate(
            [jnp.zeros(1), 0.5 * jax.random.normal(keys[2], (n_restarts - 1,))]
        )

        def _restart(s, dm, dp):
            t = dict(theta0)
            t["log_dmu"] = theta0["log_dmu"] + s
            t["mu0"] = theta0["mu0"] + dm
            t["atanh_phi"] = theta0["atanh_phi"] + dp
            return t

        thetas = jax.vmap(_restart)(scale, mu0_jit, phi_jit)
        theta_all, losses_all = jax.vmap(
            lambda t: _fit_adam(t, xstd, mask, n_steps, lr, switching_variance)
        )(thetas)
        # select by each restart's RETURNED parameters' own likelihood:
        # losses[i] is evaluated before adam update i, so the recorded
        # final loss describes the penultimate theta — ranking by it could
        # both miss a last-step blowup and pick a worse-likelihood mode
        candidates = []
        for k in range(n_restarts):
            params_k = _unpack(
                jax.tree.map(lambda a: a[k], theta_all), switching_variance
            )
            out_k = kim_filter(params_k, xstd, mask)
            ll_k = float(out_k[0])
            if np.isfinite(ll_k):
                candidates.append((ll_k, k, params_k, out_k))
        if not candidates:
            raise RuntimeError("all MS-DFM restarts diverged (non-finite loss)")
        _, best, params, (ll, filt_probs, pred_probs, m_filt, _) = max(
            candidates, key=lambda c: c[0]
        )
        losses = losses_all[best]
        rec.set(
            n_iter=n_steps,
            converged=len(candidates) == n_restarts,
            final_loglik=float(ll),
            n_finite_restarts=len(candidates),
            best_restart=int(best),
        )
        smoothed = kim_smoother_probs(params, filt_probs, pred_probs)
        factor = (filt_probs * (params.mu[None, :] + m_filt)).sum(axis=1)
        return MSDFMResults(
            params=params,
            loglik=float(ll),
            filt_probs=filt_probs,
            smoothed_probs=smoothed,
            factor=factor,
            loss_path=np.asarray(losses),
            stds=stds,
            means=n_mean,
        )


class MSForecast(NamedTuple):
    regime_probs: jnp.ndarray  # (h, M) Pr(S_{T+k} | x_{1:T})
    factor_mean: jnp.ndarray  # (h,) E[f_{T+k} | x_{1:T}]
    factor_var: jnp.ndarray  # (h,) Var(f_{T+k} | x_{1:T})
    series_mean: jnp.ndarray  # (h, N) lam * factor_mean (standardized units)


@partial(jax.jit, static_argnames=("horizon",))
def forecast_ms(params: MSDFMParams, filt_probs, m_filt, P_filt, horizon: int):
    """h-step-ahead forecast distribution from the end-of-sample Kim
    filter state: regime probabilities propagate through P^k, the demeaned
    factor through the AR(1) (its variance accumulating the
    regime-probability-weighted innovation variance), and the factor mean
    mixes the regime means with the forecast regime probabilities.

    `filt_probs`, `m_filt`, `P_filt` are `kim_filter` outputs; the state
    used is their LAST row (time T).  Recession-probability forecasts are
    `regime_probs[:, 0]`.  Exact for the regime chain; the factor moments
    are the standard Kim-filter mixture approximation (the filtered
    cross-regime spread enters the h=1 variance).
    """
    mu, phi, Pm, sig2 = params.mu, params.phi, params.P, params.sigma2
    p_T = filt_probs[-1]
    # collapse the per-regime filtered state to one mixture moment pair
    m0 = (p_T * m_filt[-1]).sum()
    V0 = (p_T * (P_filt[-1] + (m_filt[-1] - m0) ** 2)).sum()

    def step(carry, _):
        p, m, V = carry
        p_next = p @ Pm
        m_next = phi * m
        V_next = phi**2 * V + (p_next * sig2).sum()
        fmean = (p_next * mu).sum() + m_next
        fvar = V_next + (p_next * (mu - (p_next * mu).sum()) ** 2).sum()
        return (p_next, m_next, V_next), (p_next, fmean, fvar)

    _, (probs, fmean, fvar) = jax.lax.scan(
        step, (p_T, m0, V0), None, length=horizon
    )
    series_mean = fmean[:, None] * params.lam[None, :]
    return MSForecast(probs, fmean, fvar, series_mean)


class MSStandardErrors(NamedTuple):
    """Delta-method OPG standard errors on the natural parameter scale.
    P entries and sigma2[0] carry the constraint structure (rows sum to 1,
    anchor fixed), so their SEs are for the constrained estimates."""

    mu: jnp.ndarray  # (M,)
    phi: jnp.ndarray  # scalar
    P: jnp.ndarray  # (M, M)
    sigma2: jnp.ndarray  # (M,) — entry 0 is the anchor: SE = 0
    lam: jnp.ndarray  # (N,)
    R: jnp.ndarray  # (N,)


def ms_standard_errors(
    params: MSDFMParams,
    x,
    mask=None,
    switching_variance: bool | None = None,
    which: str = "structural",
    cov: str = "sandwich",
    x_raw=None,
    hac_lags: int | None = None,
) -> MSStandardErrors:
    """Sandwich/OPG standard errors for a fitted MS-DFM.

    The per-step log-likelihood contributions are differentiable through
    the whole Kim recursion, so the score matrix is one forward-mode
    jacobian over the unconstrained parameter vector; the information
    estimate defaults to the SANDWICH H^-1 (S'S) H^-1 — the Kim
    likelihood is a quasi-likelihood (the collapse is an approximation),
    so the information equality behind bare OPG fails and cov="opg"
    understates uncertainty (calibrated against Monte-Carlo spread in the
    tests; adam stops near, not at, the optimum, so treat these as
    first-order inference either way).  SEs are mapped to the natural
    scale by the delta method through the same reparametrization the
    optimizer used.

    which="structural" (default) differentiates only the regime-dynamics
    block (mu, phi, P, sigma2) holding the measurement parameters
    (lam, R) fixed — the standard two-step practice, and the only
    well-posed choice on wide panels where the full parameter count
    exceeds T (their SE fields return NaN).  which="all" scores the full
    vector and REQUIRES T > #params (raises otherwise: an OPG information
    with T < d is rank-deficient by construction and pinv would return
    spuriously tight SEs).

    `x` is the STANDARDIZED panel (NaN = missing) the model was fitted
    on — rebuild it as `(x_raw - res.means) / res.stds`.  When
    `switching_variance` is None it is inferred from sigma2 != ones.

    `x_raw` (the UNSTANDARDIZED panel) switches on standardization
    propagation: the fit conditions on per-series sample means/stds that
    are themselves estimates, and with a persistent regime chain the
    realized regime mix moves them enough to dominate the cross-sample
    spread of mu-hat (measured free-path Monte-Carlo ratios ~0.3-0.5
    without the correction).  The two stages are treated as one stacked
    M-estimator: the first-stage moment contributions u_t (mean and
    population-std estimating equations per series) enter through the
    adjusted score s_t - C u_t with C = (d2 ll / d theta d gamma)
    (d u / d gamma)^-1, and the meat uses a Bartlett long-run covariance
    (`hac_lags`, default floor(1.3 sqrt(T))) because u_t inherits the
    regime chain's serial correlation.  Newey-McFadden (1994, ch. 36,
    sec. 6) two-step form, specialized to exactly-identified first-stage
    moments.
    """
    from jax.flatten_util import ravel_pytree

    x = jnp.asarray(x)
    if mask is None:
        mask = mask_of(x)
    if switching_variance is None:
        switching_variance = bool(
            np.any(np.asarray(params.sigma2[1:]) != 1.0)
        )
    if which not in ("structural", "all"):
        raise ValueError(f"which must be 'structural' or 'all', got {which!r}")
    if cov not in ("sandwich", "opg"):
        raise ValueError(f"cov must be 'sandwich' or 'opg', got {cov!r}")
    if not np.isclose(float(params.sigma2[0]), 1.0):
        # _pack stores regime variances as ratios to sigma2[0] and _unpack
        # re-anchors sigma2[0] = 1, so hand-built params with a different
        # anchor would be silently rescaled and the scores evaluated at the
        # wrong point; fit_ms_dfm output is always anchored
        raise ValueError(
            f"params.sigma2[0] must be 1.0 (the scale anchor), got "
            f"{float(params.sigma2[0])!r}; rescale sigma2 by sigma2[0] "
            "(and fold the scale into lam/R) before requesting SEs"
        )
    R_np = np.asarray(params.R)
    bad_R = (R_np < np.exp(-12.0)) | (R_np > np.exp(12.0))
    if bad_R.any():
        # _pack clips log(R) to [-12, 12]; an R outside that range would be
        # silently projected onto the clip boundary, the scores evaluated at
        # the projected (wrong) point, and the clip's zero gradient would
        # make that coordinate's SE spuriously zero/NaN
        idx = np.flatnonzero(bad_R)
        raise ValueError(
            f"params.R outside the packable range [e^-12, e^12] at series "
            f"{idx.tolist()} (values {R_np[idx].tolist()}); such a fit is "
            "degenerate (near-zero or explosive idiosyncratic variance) — "
            "rescale the panel or refit before requesting SEs"
        )
    theta0 = _pack(params)
    struct_keys = ("mu0", "log_dmu", "atanh_phi", "log_P", "log_sig")
    if which == "structural":
        free0 = {k: theta0[k] for k in struct_keys}
        fixed = {k: v for k, v in theta0.items() if k not in struct_keys}
    else:
        free0 = dict(theta0)
        fixed = {}
    flat0, unravel = ravel_pytree(free0)
    d = flat0.shape[0]
    T = x.shape[0]
    # structural null directions carry zero score by construction: the
    # per-row softmax shift of log_P (M directions) and, without switching
    # variance, log_sig (M-1); they are excluded from the rank requirement
    # and handled by pinv
    M = params.n_regimes
    n_null = M + (0 if switching_variance else M - 1)
    if T <= d - n_null:
        raise ValueError(
            f"score-based inference needs more time steps than free "
            f"parameters: T={T} vs "
            f"{d - n_null} effective parameters (which={which!r}); use "
            "which='structural' or a longer sample"
        )

    def lls_of(flat):
        theta = dict(fixed)
        theta.update(unravel(flat))
        p = _unpack(theta, switching_variance)
        lls, *_ = _kim_scan(p, x, mask)
        return lls

    # forward-mode: d is small (structural: M + 1 + M^2 + (M-1)), so d
    # JVP passes through the T-step scan beat T reverse passes
    from .ssm import _score_covariance

    adjust, n_hac = None, 0
    if x_raw is not None:
        x_raw = jnp.asarray(x_raw)
        if x_raw.shape != x.shape:
            raise ValueError(
                f"x_raw shape {x_raw.shape} must match x {x.shape}"
            )
        mr = mask.astype(x_raw.dtype)
        n_i = mr.sum(axis=0)
        xf = fillz(x_raw)
        # fully-missing (n_i = 0) or constant (std = 0) series contribute
        # NOTHING to the fit, so their standardization moments have zero
        # influence — the safe divisors make their u columns and C columns
        # exactly zero instead of NaN-poisoning every adjusted score
        n_safe = jnp.maximum(n_i, 1.0)
        mean_i = (mr * xf).sum(axis=0) / n_safe
        dev = jnp.where(mask, xf - mean_i, 0.0)
        std_i = jnp.sqrt((dev**2).sum(axis=0) / n_safe)  # population std
        std_i = jnp.where(std_i > 0, std_i, 1.0)
        if not bool(
            jnp.nanmax(jnp.abs(jnp.where(mask, dev / std_i, 0.0) - fillz(x)))
            < 1e-3
        ):
            raise ValueError(
                "x_raw does not standardize to x under the fit's "
                "population-std convention; pass the exact raw panel the "
                "model was fitted on"
            )
        Np = x.shape[1]
        gamma0 = jnp.concatenate([mean_i, std_i])

        def ll_total_g(flat, gamma):
            theta = dict(fixed)
            theta.update(unravel(flat))
            p = _unpack(theta, switching_variance)
            xs = jnp.where(mask, (xf - gamma[:Np]) / gamma[Np:], 0.0)
            lls, *_ = _kim_scan(p, xs, mask)
            return lls.sum()

        # cross-information (d, 2N): how the score moves when the
        # standardization constants do
        Jsum = jax.jit(jax.jacfwd(jax.grad(ll_total_g, argnums=0), argnums=1))(
            flat0, gamma0
        )
        # first-stage Jacobian sum_t du_t/dgamma is diagonal by series:
        # d(mean eq)/dmean = -n_i; d(std eq)/dstd = -2 std_i n_i; the
        # cross block sum_t -2 m dev = 0 exactly at the fitted moments
        denom = jnp.concatenate([-n_safe, -2.0 * std_i * n_safe])
        C = Jsum / denom[None, :]
        # zero out the columns of excluded (fully-missing) series: their
        # u columns are already all-zero, so this only protects against a
        # spurious Jsum entry meeting the placeholder divisor
        live = jnp.concatenate([n_i > 0, n_i > 0])
        C = C * live[None, :]
        u = jnp.concatenate([dev, dev**2 - mr * std_i**2], axis=1)

        def adjust(scores):
            return scores - u @ C.T

        n_hac = (
            hac_lags if hac_lags is not None else max(1, int(1.3 * np.sqrt(T)))
        )
    elif hac_lags is not None:
        n_hac = hac_lags

    cov_theta = _score_covariance(
        lls_of, flat0, cov, adjust_scores=adjust, hac_lags=n_hac
    )

    def natural(flat):
        theta = dict(fixed)
        theta.update(unravel(flat))
        p = _unpack(theta, switching_variance)
        return jnp.concatenate(
            [
                p.mu,
                jnp.atleast_1d(p.phi),
                p.P.ravel(),
                p.sigma2,
                p.lam,
                p.R,
            ]
        )

    G = jax.jacobian(natural)(flat0)  # (n_natural, d)
    var_nat = jnp.einsum("ij,jk,ik->i", G, cov_theta, G)
    se = jnp.sqrt(jnp.maximum(var_nat, 0.0))
    N = params.lam.shape[0]
    i = 0
    se_mu = se[i : i + M]; i += M
    se_phi = se[i]; i += 1
    se_P = se[i : i + M * M].reshape(M, M); i += M * M
    se_sig = se[i : i + M]; i += M
    se_lam = se[i : i + N]; i += N
    se_R = se[i : i + N]
    if which == "structural":
        # lam/R were held fixed: no inference on them in this mode
        se_lam = jnp.full(N, jnp.nan)
        se_R = jnp.full(N, jnp.nan)
    return MSStandardErrors(
        mu=se_mu, phi=se_phi, P=se_P, sigma2=se_sig, lam=se_lam, R=se_R
    )
