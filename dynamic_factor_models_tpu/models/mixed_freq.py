"""Mixed-frequency DFM: monthly factors, quarterly series as lag aggregates.

The canonical nowcasting setup (Giannone-Reichlin-Small 2008 /
Banbura-Rünstler 2011; aggregation per Mariano-Murasawa 2003) that the
reference side-steps by averaging monthly data to quarterly in ingest
(readin_functions.jl:83-96).  Here the panel stays at MONTHLY frequency:

    monthly series:    x_it = lam_i' f_t + eps_it
    quarterly series:  x_it = lam_i' (w_0 f_t + ... + w_4 f_{t-4}) + eps_it
                       observed only in quarter-end months (NaN elsewhere)

with w = (1, 2, 3, 2, 1)/3 the Mariano-Murasawa growth-rate aggregation
weights and f_t a monthly VAR(p) factor process, p >= 5 so the five factor
lags live in the state s_t = [f_t .. f_{t-p+1}].

TPU design: the per-series observation rows h_i = sum_j W_ij [0..lam_i..0]
make H dense over the first 5r state dims; the filter reuses
ssm._info_filter_scan, and every EM M-step moment is one einsum over the
smoothed state second moments — the cross-lag covariances E[f_{t-j} f_{t-l}']
are just blocks of E[s s'], so no extra smoother passes are needed.
"""

from __future__ import annotations

from functools import lru_cache
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.linalg import solve_normal, standardize_data
from ..ops.masking import fillz, mask_of
from ..utils.backend import on_backend
from .ssm import (
    SSMParams,
    _bf16_gemm,
    _collapse_obs,
    _collapse_obs_stats,
    _collapse_obs_stats_partial,
    _companion,
    _info_filter_scan,
    _psd_floor,
    _rts_scan,
    _solve_loadings_and_R,
    _sym_pack_idx,
    _unpack_collapsed,
    _var_moments,
    compute_panel_stats,
)

__all__ = [
    "MixedFreqParams",
    "em_step_mf",
    "em_step_mf_stats",
    "em_step_mf_sharded",
    "estimate_mixed_freq_dfm",
    "steady_gains",
    "MFResults",
]

_MM_WEIGHTS = np.array([1.0, 2.0, 3.0, 2.0, 1.0]) / 3.0  # Mariano-Murasawa
_N_AGG = 5


class MixedFreqParams(NamedTuple):
    """lam: (N, r); R: (N,) idio variances; A: (p, r, r) with p >= 5;
    Q: (r, r); agg: (N, 5) per-series aggregation weights over factor lags
    ((1,0,0,0,0) for monthly series, Mariano-Murasawa for quarterly)."""

    lam: jnp.ndarray
    R: jnp.ndarray
    A: jnp.ndarray
    Q: jnp.ndarray
    agg: jnp.ndarray

    @property
    def r(self) -> int:
        return self.lam.shape[1]

    @property
    def p(self) -> int:
        return self.A.shape[0]


def _as_ssm(params: MixedFreqParams) -> SSMParams:
    return SSMParams(params.lam, params.R, params.A, params.Q)


def _obs_matrix(params: MixedFreqParams) -> jnp.ndarray:
    """H (N, k): series i loads lam_i on each of the first 5 factor-lag
    blocks, scaled by its aggregation weight."""
    r, p = params.r, params.p
    N = params.lam.shape[0]
    k = r * p
    H = jnp.zeros((N, k), params.lam.dtype)
    for j in range(_N_AGG):
        H = H.at[:, j * r : (j + 1) * r].set(params.agg[:, j : j + 1] * params.lam)
    return H


@jax.jit
def _filter_mf(params: MixedFreqParams, x, mask, stats=None):
    """Collapsed masked filter: observations load only on the first
    q5 = 5r state dims through H[:, :q5], so the Jungbacker-Koopman
    precompute (ssm._collapse_obs with Hq = H[:, :q5]) moves the
    O(N (5r)^2) per-step work out of the scan as batched matmuls, exactly
    as in ssm._filter_scan (exactness pinned in tests/test_collapsed.py).
    `stats` (ssm.PanelStats) switches to the two-GEMM loop formulation and
    returns the x'R^-1x quadratic as a total-log-likelihood correction."""
    Tm, Qs = _companion(_as_ssm(params))
    q5 = _N_AGG * params.r
    H5 = _obs_matrix(params)[:, :q5]
    dtype = x.dtype
    k = Tm.shape[0]
    s0 = jnp.zeros(k, dtype)
    P0 = 1e2 * jnp.eye(k, dtype=dtype)
    if stats is None:
        C, b, ld_R, xRx, n_obs = _collapse_obs(
            H5, params.R, x, mask.astype(dtype)
        )
        ll_corr = jnp.asarray(0.0, dtype)
    else:
        C, b, ld_R, xRx, n_obs, ll_corr = _collapse_obs_stats(
            H5, params.R, x, stats
        )

    def obs_step(inp, sp):
        Ct, bt, ld, xr, no = inp
        g = sp[:q5]
        Cf = jnp.zeros((k, k), dtype).at[:q5, :q5].set(Ct)
        rhs = jnp.zeros(k, dtype).at[:q5].set(bt - Ct @ g)
        quad0 = xr - 2.0 * (g @ bt) + g @ Ct @ g
        return Cf, rhs, ld, quad0, no

    means, covs, pmeans, pcovs, lls = _info_filter_scan(
        Tm, Qs, (C, b, ld_R, xRx, n_obs), obs_step, s0, P0
    )
    return means, covs, pmeans, pcovs, lls.sum() + ll_corr


def steady_gains(params: MixedFreqParams, pattern=None):
    """Cyclostationary steady-state gain set for the mixed-frequency
    observation cycle (steady.periodic_dare over the monthly/quarterly
    mask pattern).

    The mixed-freq panel is never time-invariant — quarterly series are
    observed only every third month — so there is no single Riccati fixed
    point, but the mask IS periodic, so the Riccati recursion converges to
    a period-3 cycle of covariances/gains.  This returns that cycle as a
    `steady.PeriodicSteadyState` whose phase-j information matrix is

        C_j = H5' diag(pattern_j / R) H5       (embedded in the full state)

    with H5 the dense (N, 5r) observation block of `_obs_matrix`.

    pattern: (d, N) per-phase observation indicators.  Default: the
    canonical 3-month cycle implied by `params.agg` — monthly series
    (agg row = (1,0,0,0,0)) observed in every phase, quarterly series
    only in the quarter-end phase d-1.  Phase j of the result then
    describes month `t` with `t % 3 == j` under the convention that
    quarter-end months are t % 3 == 2.

    Constant-gain tails for mixed-freq filtering consume `K[j][:, :q5]`
    and `Abar[j]` phase-by-phase; this function only derives the gain
    set (the mixed-freq EM loop itself stays on the exact path — ragged
    real-world publication lags rarely leave a long periodic tail).
    """
    r, p = params.r, params.p
    q5 = _N_AGG * r
    k = r * p
    dtype = params.lam.dtype
    # Gate on parameter health before deriving gains: periodic_dare iterates
    # the Riccati map to a fixed cycle, and a NaN/Inf anywhere in (A, Q, lam,
    # R) turns that into a silently-NaN gain set that poisons every filtered
    # month downstream.  Only checkable on concrete values — inside a trace
    # the guarded EM loop's own sentinel covers this.
    leaves = [params.lam, params.R, params.A, params.Q]
    if not any(isinstance(l, jax.core.Tracer) for l in leaves):
        if not all(bool(jnp.all(jnp.isfinite(l))) for l in leaves):
            raise ValueError(
                "steady_gains: non-finite values in MixedFreqParams "
                "(NaN/Inf in lam, R, A, or Q); the periodic Riccati "
                "recursion would propagate them into every phase gain — "
                "recover the parameters first (see utils.guards ladder)"
            )
    if pattern is None:
        is_q = jnp.any(params.agg[:, 1:] != 0.0, axis=1)
        monthly = (~is_q).astype(dtype)
        pattern = jnp.stack([monthly, monthly, jnp.ones_like(monthly)])
    pattern = jnp.asarray(pattern, dtype)
    if pattern.ndim != 2 or pattern.shape[1] != params.lam.shape[0]:
        raise ValueError(
            f"pattern must be (d, N) with N={params.lam.shape[0]}, "
            f"got {pattern.shape}"
        )
    from .steady import periodic_dare

    Tm, Qs = _companion(_as_ssm(params))
    H5 = _obs_matrix(params)[:, :q5]
    # per-phase collapsed information matrices, embedded in the full state
    C5 = jnp.einsum("nq,dn,ns->dqs", H5 / params.R[:, None], pattern, H5)
    Cs = jnp.zeros((pattern.shape[0], k, k), dtype).at[:, :q5, :q5].set(C5)
    return periodic_dare(Tm, Cs, Qs)


def _em_mf_impl(params: MixedFreqParams, x, mask, stats):
    """Shared EM iteration body; `stats` is an ssm.PanelStats or None.

    The aggregated regressor of series i is g_it = sum_j agg_ij f_{t-j};
    every panel-sized moment reduces to two contractions of the panel with
    the PACKED 5r-block state second moments,

        Z_i   = sum_t m_it E[s5 s5' | T]      via  (N, T) @ (T, q5(q5+1)/2)
        Sxg5_i = sum_t x_it E[s5_t | T]'      via  (N, T) @ (T, 5r)

    after which Sgg_i = (agg_i ⊗ I) Z_i (agg_i ⊗ I)' and
    Sxg_i = (agg_i ⊗ I) Sxg5_i are tiny per-series einsums, and the
    loading/R updates are the shared `ssm._solve_loadings_and_R` —
    no (T, N, r, r) intermediates, no residual panel.
    """
    r, p = params.r, params.p
    q5 = _N_AGG * r
    Tn = x.shape[0]

    params = params._replace(Q=_psd_floor(params.Q), R=jnp.maximum(params.R, 1e-8))
    means, covs, pmeans, pcovs, ll = _filter_mf(params, x, mask, stats=stats)
    Tm, _ = _companion(_as_ssm(params))
    s_sm, P_sm, lag1 = _rts_scan(Tm, means, covs, pmeans, pcovs)

    if stats is None:
        m = mask.astype(x.dtype)
        mT, xT = m.T, x.T
        Sxx = (x * x).sum(axis=0)
        n_i = m.sum(axis=0)
    else:
        mT, xT, Sxx, n_i = stats.mT, stats.xT, stats.Sxx, stats.n_i

    s5 = s_sm[:, :q5]
    iu, iv, unpack = _sym_pack_idx(q5)
    Ess_u = s5[:, iu] * s5[:, iv] + P_sm[:, iu, iv]  # packed E[s5 s5' | T]
    if stats is not None and stats.mT16 is not None:
        # mixed-precision twins present: the two panel GEMMs run on bf16
        # operands (ssm._bf16_gemm contract), everything downstream exact
        Zu = _bf16_gemm("nt,tc->nc", stats.mT16, Ess_u, x.dtype)
        Sxg5 = _bf16_gemm("nt,tq->nq", stats.xT16, s5, x.dtype)
    else:
        Zu = mT @ Ess_u
        Sxg5 = xT @ s5
    Z = Zu[:, unpack].reshape(-1, _N_AGG, r, _N_AGG, r)
    Sgg = jnp.einsum("ij,ijrls,il->irs", params.agg, Z, params.agg)
    Sxg = jnp.einsum("ij,ijr->ir", params.agg, Sxg5.reshape(-1, _N_AGG, r))
    lam, R = _solve_loadings_and_R(Sgg, Sxg, Sxx, n_i)

    # factor VAR + Q from the full state moments (as in ssm.em_step);
    # stats.tw keeps shape-bucketed padding periods out of the moments
    tw = None if stats is None else stats.tw
    S11, S00, S10, Tn_eff = _var_moments(s_sm, P_sm, lag1, r, Tn, tw)
    Ak = S10 @ jnp.linalg.pinv(S00, hermitian=True)
    Q = _psd_floor((S11 - Ak @ S10.T) / (Tn_eff - 1))
    A = jnp.stack([Ak[:, i * r : (i + 1) * r] for i in range(p)])
    return MixedFreqParams(lam, R, A, Q, params.agg), ll


@jax.jit
def _smooth_xhat_mf(params: MixedFreqParams, x, mask):
    """Final smoothing readout: smoothed state path + fitted panel x_hat.

    Module-level jitted on purpose: an eager `_rts_scan` call builds a
    fresh scan-body closure per invocation, so XLA's dispatch cache never
    hits and the backward pass recompiles every estimate call (measured
    3.6 s per call on the monthly panel — 10x the EM loop itself)."""
    means, covs, pmeans, pcovs, _ = _filter_mf(params, x, mask)
    Tm, _ = _companion(_as_ssm(params))
    s_sm, _, _ = _rts_scan(Tm, means, covs, pmeans, pcovs)
    q5 = _N_AGG * params.r
    x_hat = s_sm[:, :q5] @ _obs_matrix(params)[:, :q5].T
    return s_sm, x_hat


@jax.jit
def em_step_mf(params: MixedFreqParams, x, mask):
    """One EM iteration; returns (new_params, loglik of current params)."""
    return _em_mf_impl(params, x, mask, None)


@jax.jit
def em_step_mf_stats(params: MixedFreqParams, x, mask, stats):
    """`em_step_mf` with loop-invariant ssm.PanelStats supplied — the
    production path of `estimate_mixed_freq_dfm` (same update, the
    per-iteration cost excludes transposes and data sums)."""
    return _em_mf_impl(params, x, mask, stats)


@jax.jit
def em_step_mf_stats_bulk(params: MixedFreqParams, x, mask, stats):
    """`em_step_mf_stats` with idiosyncratic variances floored at 1e-3:
    the mixed-precision bulk map (see ssm.em_step_stats_bulk — the
    collapse's 1/R weighting amplifies bf16 operand error by
    max_i(lam_i^2 / R_i), and quarterly series fit nearly exactly drive
    R_i small enough to turn rounding into likelihood garbage).  The
    exact polish phase removes the floor."""
    return _em_mf_impl(
        params._replace(
            R=jnp.maximum(params.R, jnp.asarray(1e-3, params.R.dtype))
        ),
        x,
        mask,
        stats,
    )


def _mf_sharded_step_for(n_shards: int, hosts: int = 0):
    """The mixed-frequency EM step sharded over the cross-section —
    same (params, x, mask, stats) -> (params, loglik) contract as
    `em_step_mf_stats`, N must be a shard multiple
    (`estimate_mixed_freq_dfm(n_shards=)` pads with inert series first).

    Why this shards at all: each series' contribution to the E-step is an
    independent sum term even through the Mariano-Murasawa aggregation
    rows.  The aggregation row of a quarterly series couples that series
    to 5 state LAGS — `_obs_matrix` makes its observation row dense over
    the first q5 = 5r state dims — but never to another series, so the
    collapsed statistics C_t = H5' R^-1 H5, b_t = H5' R^-1 x_t and the
    M-step Grams all remain plain sums over series.  The per-shard half
    is exactly `ssm._collapse_obs_stats_partial` with Hq = H5 (it is
    generic in the observation block); the payload is all-reduced once
    per iteration (flat ring on one host, hierarchical ICI-ring + DCN
    psum across hosts), then the N-free O(k^3) filter/smoother scans and
    the factor-VAR moments run replicated, and the per-series
    loading/R solves — including the tiny agg-row einsums — stay
    shard-local.

    Inert-padding contract (the exact gap the old `NotImplementedError`
    cited): a padded series carries lam = 0, R = 1, a monthly
    aggregation row (1,0,0,0,0), and an all-False mask column, so its H5
    row is zero and every payload column, Gram, rhs and log-det term it
    contributes is exactly zero EVEN under the period-3 quarterly mask
    cycle — the mask never resurrects a zero loading row.  Pinned in
    tests/test_multihost.py (padded-aggregation-row inertness) and
    sharded == sequential parity at 1e-10 in tests/test_sharding.py.

    `hosts` follows `ssm._sharded_step_for` (0 = the runtime's process
    count; dispatcher over an lru_cached impl so `f(2)` and
    `f(2, hosts=0)` are one object)."""
    from .ssm import _resolve_mesh_hosts

    return _mf_sharded_step_impl(int(n_shards), _resolve_mesh_hosts(hosts))


@lru_cache(maxsize=None)
def _mf_sharded_step_impl(n_shards: int, hosts: int):
    from ..ops.pallas_gram import hierarchical_allreduce, ring_allreduce
    from ..parallel import shard_map_nocheck
    from ..parallel.mesh import P, data_mesh

    mesh = data_mesh(n_shards, hosts=hosts)
    if hosts > 1:
        dax = ("dcn", "ici")
        n_ici = n_shards // hosts

        def _reduce(payload):
            return hierarchical_allreduce(payload, "ici", "dcn", n_ici)

        name = f"em_step_mf_sharded_d{n_shards}_h{hosts}"
    else:
        dax = "data"

        def _reduce(payload):
            return ring_allreduce(payload, "data", n_shards)

        name = f"em_step_mf_sharded_d{n_shards}"

    def step(params: MixedFreqParams, x, mask, stats):
        del mask  # collapse statistics already carry the mask
        r, p = params.r, params.p
        q5 = _N_AGG * r
        Tn = x.shape[0]
        params = params._replace(
            Q=_psd_floor(params.Q), R=jnp.maximum(params.R, 1e-8)
        )
        H5 = _obs_matrix(params)[:, :q5]
        payload, llc = _collapse_obs_stats_partial(H5, params.R, x, stats)
        payload = _reduce(payload)
        llc = jax.lax.psum(llc, dax)
        C, b, ld_R = _unpack_collapsed(payload, q5)

        # replicated filter/smoother: `_filter_mf`'s scan assembly on the
        # pre-reduced collapsed statistics (xRx is identically zero on the
        # stats path — the quadratic is the ll_corr scalar)
        Tm, Qs = _companion(_as_ssm(params))
        k = Tm.shape[0]
        dtype = x.dtype
        s0 = jnp.zeros(k, dtype)
        P0 = 1e2 * jnp.eye(k, dtype=dtype)
        xRx = jnp.zeros(b.shape[0], dtype)

        def obs_step(inp, sp):
            Ct, bt, ld, xr, no = inp
            g = sp[:q5]
            Cf = jnp.zeros((k, k), dtype).at[:q5, :q5].set(Ct)
            rhs = jnp.zeros(k, dtype).at[:q5].set(bt - Ct @ g)
            quad0 = xr - 2.0 * (g @ bt) + g @ Ct @ g
            return Cf, rhs, ld, quad0, no

        means, covs, pmeans, pcovs, lls = _info_filter_scan(
            Tm, Qs, (C, b, ld_R, xRx, stats.n_obs), obs_step, s0, P0
        )
        ll = lls.sum() + llc
        s_sm, P_sm, lag1 = _rts_scan(Tm, means, covs, pmeans, pcovs)

        # shard-local M-step on the local N-slice (see `_em_mf_impl`)
        s5 = s_sm[:, :q5]
        iu, iv, unpack = _sym_pack_idx(q5)
        Ess_u = s5[:, iu] * s5[:, iv] + P_sm[:, iu, iv]
        Zu = stats.mT @ Ess_u
        Sxg5 = stats.xT @ s5
        Z = Zu[:, unpack].reshape(-1, _N_AGG, r, _N_AGG, r)
        Sgg = jnp.einsum("ij,ijrls,il->irs", params.agg, Z, params.agg)
        Sxg = jnp.einsum("ij,ijr->ir", params.agg, Sxg5.reshape(-1, _N_AGG, r))
        lam, R = _solve_loadings_and_R(Sgg, Sxg, stats.Sxx, stats.n_i)

        S11, S00, S10, Tn_eff = _var_moments(s_sm, P_sm, lag1, r, Tn, stats.tw)
        Ak = S10 @ jnp.linalg.pinv(S00, hermitian=True)
        Q = _psd_floor((S11 - Ak @ S10.T) / (Tn_eff - 1))
        A = jnp.stack([Ak[:, i * r : (i + 1) * r] for i in range(p)])
        return MixedFreqParams(lam, R, A, Q, params.agg), ll

    step.__name__ = step.__qualname__ = name
    step.__module__ = __name__

    params_spec = MixedFreqParams(
        lam=P(dax, None), R=P(dax), A=P(), Q=P(), agg=P(dax, None)
    )
    from .ssm import PanelStats

    stats_spec = PanelStats(
        m=P(None, dax), xT=P(dax, None), mT=P(dax, None),
        Sxx=P(dax), n_i=P(dax), n_obs=P(),
        m16=None, x16=None, mT16=None, xT16=None, tw=P(),
    )
    return jax.jit(
        shard_map_nocheck(
            step,
            mesh=mesh,
            in_specs=(params_spec, P(None, dax), P(None, dax), stats_spec),
            out_specs=(params_spec, P()),
        )
    )


def em_step_mf_sharded(params: MixedFreqParams, x, mask, stats, n_shards: int):
    """One sharded mixed-frequency EM iteration (see `_mf_sharded_step_for`)."""
    return _mf_sharded_step_for(int(n_shards))(params, x, mask, stats)


class MFResults(NamedTuple):
    params: MixedFreqParams
    factors: jnp.ndarray  # (T, r) smoothed MONTHLY factors
    x_hat: jnp.ndarray  # (T, N) smoothed fitted panel (standardized units)
    loglik_path: np.ndarray
    n_iter: int
    stds: jnp.ndarray
    means: jnp.ndarray
    trace: object | None = None  # ConvergenceTrace when collect_path=True
    converged: bool = False  # actual tolerance break (not n_iter < cap)
    health: int = 0  # final utils.guards health code (0 = healthy)


def _project_params_mf(params: MixedFreqParams) -> MixedFreqParams:
    """Feasibility projection after SQUAREM extrapolation: R floored
    positive, Q symmetrized/eigenvalue-floored.  `agg` is a constant of
    the model — the EM map never moves it, so its extrapolation increments
    are identically zero and it passes through untouched."""
    return params._replace(
        R=jnp.maximum(params.R, jnp.asarray(1e-8, params.R.dtype)),
        Q=_psd_floor(params.Q),
    )


def estimate_mixed_freq_dfm(
    x,
    is_quarterly,
    r: int = 1,
    p: int = 5,
    max_em_iter: int = 100,
    tol: float = 1e-6,
    backend: str | None = None,
    collect_path: bool = False,
    checkpoint_path: str | None = None,
    checkpoint_every: int = 25,
    accel: str | None = None,
    gram_dtype: str | None = None,
    bucket=None,
    n_shards: int | None = None,
) -> MFResults:
    """Fit the mixed-frequency DFM on a MONTHLY-frequency (T, N) panel.

    x: monthly panel; quarterly series carry values in quarter-end months and
    NaN elsewhere (any extra missingness is fine — the filter masks it).
    is_quarterly: (N,) bool.  p >= 5 is required for the aggregation lags.

    `x_hat` gives the model's smoothed value of every cell — including the
    monthly path of each quarterly series (the nowcasting readout).

    accel="squarem" wraps the EM step in one SQUAREM extrapolation cycle
    per loop iteration (`emaccel.squarem`; n_iter then counts cycles of
    three EM-map evaluations each).

    bucket pads (T, N) up to a shape bucket (utils.compile, same contract
    as `ssm.estimate_dfm_em`): padded series are fully masked with
    monthly-pattern aggregation rows (inert in every moment), padded
    periods are excluded from the factor-VAR moments via `PanelStats.tw`;
    one compiled MF executable then serves every panel in the bucket.

    n_shards > 1 shards the cross-section over the data mesh
    (`_mf_sharded_step_for`), exactly as `ssm.estimate_dfm_em`: the panel
    is padded with inert series up to a shard multiple — zero loadings,
    unit R, monthly aggregation rows, all-False mask, exactly inert under
    the period-3 quarterly mask cycle — and in a `jax.distributed`
    runtime the mesh spans processes with a hierarchical ICI+DCN
    reduction.  Parity with the sequential run is pinned at 1e-10; see
    docs/sharding.md.
    """
    from ..utils.compile import (
        bucket_shape,
        configure_compilation_cache,
        pad_panel,
        resolve_buckets,
    )

    configure_compilation_cache()
    buckets = resolve_buckets(bucket)
    if p < _N_AGG:
        raise ValueError(f"p={p} must be >= {_N_AGG} for Mariano-Murasawa lags")
    if accel not in (None, "squarem"):
        raise ValueError(f"accel must be None or 'squarem', got {accel!r}")
    if gram_dtype not in (None, "bfloat16"):
        raise ValueError(
            f"gram_dtype must be None or 'bfloat16', got {gram_dtype!r}"
        )
    if gram_dtype is not None and checkpoint_path is not None:
        raise ValueError("gram_dtype is not combinable with checkpoint_path")
    ns = int(n_shards) if n_shards is not None else 0
    if ns > 1:
        if gram_dtype is not None:
            raise ValueError(
                "n_shards is not combinable with gram_dtype: the bf16 "
                "panel twins are not sharded"
            )
        if ns > jax.device_count():
            raise ValueError(
                f"n_shards={ns} exceeds the {jax.device_count()} visible "
                "devices"
            )
        if jax.process_count() > 1 and ns % jax.process_count() != 0:
            raise ValueError(
                f"n_shards={ns} must be a multiple of "
                f"jax.process_count()={jax.process_count()} so every host "
                "owns the same number of local shards"
            )
    from ..utils.telemetry import run_record

    with on_backend(backend), run_record(
        "estimate_mixed_freq_dfm",
        config={
            "accel": accel, "gram_dtype": gram_dtype, "tol": tol,
            "max_em_iter": max_em_iter,
            "checkpointed": checkpoint_path is not None,
        },
    ) as rec:
        x = jnp.asarray(x)
        is_q = np.asarray(is_quarterly, bool)
        if is_q.shape != (x.shape[1],):
            raise ValueError("is_quarterly must have one flag per column")
        xstd, stds = standardize_data(x)
        m_arr = mask_of(xstd)
        xz = fillz(xstd)
        mw = mask_of(x)
        n_mean = (fillz(x) * mw).sum(axis=0) / mw.sum(axis=0)

        N = x.shape[1]
        agg = np.zeros((N, _N_AGG))
        agg[~is_q, 0] = 1.0
        agg[is_q] = _MM_WEIGHTS
        dtype = xz.dtype

        # init: PCA-style factor from the monthly block, zero-lag loadings
        from ..ops.linalg import pca_score

        monthly = np.nonzero(~is_q)[0]
        if monthly.size < r:
            raise ValueError("need at least r monthly series to initialize")
        f0 = pca_score(jnp.where(m_arr, xz, 0.0)[:, monthly], r)
        f0 = f0 / jnp.maximum(f0.std(axis=0), 1e-8)
        W = m_arr.astype(dtype)
        Sff = jnp.einsum("ti,tr,ts->irs", W, f0, f0)
        Sxf = jnp.einsum("ti,tr->ir", W * xz, f0)
        lam0 = jax.vmap(solve_normal)(Sff, Sxf)
        params = MixedFreqParams(
            lam=lam0,
            R=jnp.ones(N, dtype),
            A=jnp.concatenate(
                [0.7 * jnp.eye(r, dtype=dtype)[None], jnp.zeros((p - 1, r, r), dtype)]
            ),
            Q=jnp.eye(r, dtype=dtype),
            agg=jnp.asarray(agg, dtype),
        )

        from .emloop import run_em_loop

        T0, N0 = xz.shape
        rec.set(shapes={
            "T": T0, "N": N0, "r": r, "p": p,
            "n_quarterly": int(is_q.sum()),
        })
        if buckets is not None or ns > 1:
            # pad up to the bucket and/or a shard multiple (see
            # ssm.estimate_dfm_em): padded series carry zero loadings,
            # unit R, a monthly aggregation row and an all-False mask —
            # inert in every moment, including under the period-3
            # quarterly mask cycle (pinned in tests/test_multihost.py)
            if buckets is not None:
                Tb, Nb = bucket_shape(T0, N0, *buckets)
            else:
                Tb, Nb = T0, N0
            if ns > 1:
                from ..parallel.mesh import series_pad

                Nb = series_pad(Nb, ns)
            if buckets is not None:
                rec.set(bucket=[Tb, Nb])
            xz, m_arr, tw = pad_panel(xz, m_arr, Tb, Nb)
            agg_pad = jnp.zeros((Nb, _N_AGG), dtype).at[:N0].set(params.agg)
            agg_pad = agg_pad.at[N0:, 0].set(1.0)
            params = params._replace(
                lam=jnp.zeros((Nb, r), dtype).at[:N0].set(params.lam),
                R=jnp.ones(Nb, dtype).at[:N0].set(params.R),
                agg=agg_pad,
            )
            stats = compute_panel_stats(xz, m_arr)._replace(tw=tw)
        else:
            stats = compute_panel_stats(xz, m_arr)
        # step selection stays in the one table models/transforms owns:
        # the bare mixed-frequency core, or the shard transform over it
        from . import transforms as tfm

        fallback_step = None
        fallback_unwrap = None
        if ns > 1:
            # a tripped sharded run demotes to the exact sequential MF
            # step: same (xz, mask, stats) args
            res_t = tfm.resolve(tfm.Stack("mf", (tfm.shard(ns),)))
            step, fallback_step = res_t.step, res_t.fallback_step
            nproc = jax.process_count()
            if nproc > 1:
                # multi-process SPMD: hand the loop host (numpy) arrays —
                # identical on every process by construction — so jit can
                # shard them onto the global ("dcn", "ici") mesh (a
                # committed single-device array cannot be resharded
                # across processes)
                to_host = lambda t: jax.tree.map(np.asarray, t)
                xz, m_arr = np.asarray(xz), np.asarray(m_arr)
                params, stats = to_host(params), to_host(stats)
                rec.set(
                    mesh_shape=[nproc, ns // nproc], sharded=True,
                    process_count=nproc,
                )
            else:
                rec.set(mesh_shape=[ns], sharded=True)
        else:
            step = tfm.resolve(tfm.Stack("mf")).step
        if accel == "squarem":
            from .emaccel import squarem, squarem_state, unwrap_state

            step = squarem(step, _project_params_mf)
            params = squarem_state(params)
            # recovery ladder's demote rung: peel the SquaremState and
            # continue on the exact sequential EM map
            if fallback_step is None:
                fallback_step = em_step_mf_stats
            fallback_unwrap = unwrap_state

        if gram_dtype is not None:
            # mixed-precision bulk + exact polish — see
            # emloop.run_bulk_then_exact
            from .emloop import run_bulk_then_exact
            from .ssm import _with_bf16_twins

            bulk_step = em_step_mf_stats_bulk
            if accel == "squarem":
                # same wrapper on both phases: the SquaremState flows from
                # the bulk loop into the exact loop unchanged
                bulk_step = squarem(em_step_mf_stats_bulk, _project_params_mf)
            res = run_bulk_then_exact(
                bulk_step, step, params,
                (xz, m_arr, _with_bf16_twins(stats, xz)),
                (xz, m_arr, stats), tol, max_em_iter,
                trace_name="em_mixed_freq", collect_path=collect_path,
                fallback_step=fallback_step,
                fallback_unwrap=fallback_unwrap,
            )
        else:
            res = run_em_loop(
                step, params, (xz, m_arr, stats), tol, max_em_iter,
                collect_path=collect_path, trace_name="em_mixed_freq",
                checkpoint_path=checkpoint_path,
                checkpoint_every=checkpoint_every,
                fallback_step=fallback_step,
                fallback_unwrap=fallback_unwrap,
            )
        params, llpath, it, trace = res
        if accel == "squarem":
            from .emaccel import SquaremState

            if isinstance(params, SquaremState):  # demote may have peeled
                params = params.params
        rec.set(
            n_iter=it,
            converged=res.converged,
            final_loglik=float(llpath[-1]) if len(llpath) else None,
        )
        if res.faults_detected:
            from ..utils.guards import HEALTH_NAMES

            rec.set(
                faults_detected=res.faults_detected,
                recoveries=res.recoveries,
                ladder_rung=res.ladder_rung,
                final_health=HEALTH_NAMES[res.health],
            )

        if ns > 1 and jax.process_count() > 1:
            # gather the mesh-sharded loop output to replicated host
            # copies before the local smoother readout (fully-replicated
            # arrays are locally addressable on every process)
            from jax.sharding import NamedSharding

            from ..parallel.mesh import P as _P, data_mesh

            gmesh = data_mesh(ns, hosts=0)
            gather = jax.jit(
                lambda t: t, out_shardings=NamedSharding(gmesh, _P())
            )
            params = jax.tree.map(np.asarray, gather(params))
        # bucketed/sharded path: smooth at the padded shape, then slice
        # the readout (and the params) back to the raw panel
        s_sm, x_hat = _smooth_xhat_mf(params, xz, m_arr)
        if buckets is not None or ns > 1:
            params = params._replace(
                lam=params.lam[:N0], R=params.R[:N0], agg=params.agg[:N0]
            )
        return MFResults(
            params=params,
            factors=s_sm[:T0, :r],
            x_hat=x_hat[:T0, :N0],
            loglik_path=llpath,
            n_iter=it,
            stds=stds,
            means=n_mean,
            trace=trace,
            converged=res.converged,
            health=res.health,
        )
