"""Generalized dynamic factor model: spectral-density (dynamic) PCA.

New capability (BASELINE.json config 4, `Forni-Gambetti (2010) dynamic PCA /
spectral-density factor estimator`); the reference contains no spectral
estimator.  Method of Forni-Hallin-Lippi-Reichlin (2000) as used by
Forni-Gambetti (2010) for structural FAVAR analysis:

  1. lag-window estimate of the spectral density matrix: Bartlett-weighted
     autocovariances, one FFT over the 2M+1 frequency grid;
  2. eigendecomposition at every frequency (one batched ``eigh`` — the
     frequency axis is embarrassingly parallel on the MXU);
  3. the top-q eigenspaces give the common-component spectral density, whose
     inverse FFT yields the common autocovariances and the two-sided dynamic
     principal-component filter;
  4. dynamic eigenvalue shares give the number-of-dynamic-factors diagnostics
     (Hallin-Liska style variance-share criterion).

Everything after the host-side masking is jitted; autocovariances use
pairwise-complete masking so unbalanced panels work.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import jax.scipy.linalg as jsl
import numpy as np

from ..ops.linalg import standardize_data
from ..ops.masking import fillz, mask_of
from ..utils.backend import on_backend

__all__ = [
    "DynamicPCAResults",
    "HallinLiskaResults",
    "spectral_density",
    "dynamic_pca",
    "dynamic_eigenvalue_shares",
    "hallin_liska_q",
    "forecast_common_component",
    "one_sided_common_component",
    "coherence",
]


class DynamicPCAResults(NamedTuple):
    frequencies: jnp.ndarray  # (H,) grid on [0, 2pi)
    eigenvalues: jnp.ndarray  # (H, N) dynamic eigenvalues, descending
    common_spectrum: jnp.ndarray  # (H, N, N) complex spectral density of chi
    common_autocov: jnp.ndarray  # (2M+1, N, N) real autocovariances of chi
    common_component: jnp.ndarray  # (T, N) two-sided filter estimate of chi
    variance_share: jnp.ndarray  # scalar: var(chi)/var(x) implied by q
    q: int
    M: int


def _masked_autocovariances(xz: jnp.ndarray, m: jnp.ndarray, M: int) -> jnp.ndarray:
    """Gamma_k (N, N) for k = 0..M with pairwise-complete normalization."""

    def gamma(k):
        a, b = xz[k:], xz[: xz.shape[0] - k]
        wa, wb = m[k:], m[: m.shape[0] - k]
        num = jnp.einsum("ti,tj->ij", a * wa, b * wb)
        den = jnp.einsum("ti,tj->ij", wa, wb)
        return num / jnp.maximum(den, 1.0)

    return jnp.stack([gamma(k) for k in range(M + 1)])


@partial(jax.jit, static_argnames=("M",))
def _spectrum(xz, m, M: int):
    """Lag-window spectral density on the 2M+1 grid + autocovariances."""
    N = xz.shape[1]
    H = 2 * M + 1
    gammas = _masked_autocovariances(xz, m, M)  # (M+1, N, N)
    w = 1.0 - jnp.arange(M + 1) / (M + 1)  # Bartlett lag window

    # two-sided weighted autocovariance sequence ordered k = 0..M, -M..-1
    # (natural FFT ordering); Gamma_{-k} = Gamma_k'
    seq = jnp.zeros((H, N, N), xz.dtype)
    seq = seq.at[: M + 1].set(w[:, None, None] * gammas)
    seq = seq.at[M + 1 :].set(
        (w[1:, None, None] * gammas[1:].transpose(0, 2, 1))[::-1]
    )
    # Sigma(theta_h) = (1/2pi) sum_k seq_k e^{-i k theta_h}: one FFT over lags
    spec = jnp.fft.fft(seq, axis=0) / (2.0 * jnp.pi)  # (H, N, N) complex
    spec = 0.5 * (spec + jnp.conj(spec).transpose(0, 2, 1))  # hermitianize
    return spec, gammas


@partial(jax.jit, static_argnames=("M", "q"))
def _dynpca_core(xz, m, M: int, q: int):
    T, N = xz.shape
    H = 2 * M + 1
    spec, gammas = _spectrum(xz, m, M)

    evals, evecs = jnp.linalg.eigh(spec)  # ascending
    evals = evals[:, ::-1].real  # (H, N) descending
    evecs = evecs[:, :, ::-1]  # (H, N, N)

    P = evecs[:, :, :q]  # top-q dynamic eigenvectors per frequency
    lam_q = evals[:, :q]
    common_spec = jnp.einsum("hik,hk,hjk->hij", P, lam_q.astype(spec.dtype), jnp.conj(P))

    # inverse transform: Gamma_chi(k) = int Sigma_chi e^{i k theta} dtheta
    #                  ~ (2pi/H) sum_h Sigma_chi(theta_h) e^{i k theta_h}
    common_acov = jnp.fft.ifft(common_spec, axis=0) * (2.0 * jnp.pi)
    common_acov = common_acov.real  # (H, N, N), index k = 0..M, -M..-1

    # two-sided dynamic PC filter: chi_t = sum_{|k|<=M} K_k x_{t-k} where
    # K(L) projects on the top-q dynamic eigenspace:
    # K_k = (1/H) sum_h P P* e^{i k theta_h}
    proj = jnp.einsum("hik,hjk->hij", P, jnp.conj(P))  # (H, N, N)
    K = jnp.fft.ifft(proj, axis=0).real  # k = 0..M, -M..-1

    def filt_at(t):
        # chi_t = sum_{k=-M..M} K_k x_{t-k}, zero-padded at the edges
        def one_lag(k_idx):
            k = jnp.where(k_idx <= M, k_idx, k_idx - H)  # signed lag
            src = jnp.clip(t - k, 0, T - 1)
            valid = ((t - k) >= 0) & ((t - k) < T)
            return jnp.where(valid, K[k_idx] @ xz[src], jnp.zeros(N, xz.dtype))

        return jax.vmap(one_lag)(jnp.arange(H)).sum(axis=0)

    chi = jax.vmap(filt_at)(jnp.arange(T))

    total_var = jnp.trace(gammas[0])
    common_var = jnp.trace(common_acov[0])
    share = common_var / total_var
    freqs = 2.0 * jnp.pi * jnp.arange(H) / H
    return freqs, evals, common_spec, common_acov, chi, share


def spectral_density(x, M: int = 20, backend: str | None = None):
    """Lag-window spectral density matrix of a (T, N) panel on the 2M+1
    frequency grid; returns (frequencies, spectra (H, N, N) complex)."""
    with on_backend(backend):
        x = jnp.asarray(x)
        if M >= x.shape[0]:
            raise ValueError(
                f"lag-window half-width M={M} must be smaller than T={x.shape[0]}"
            )
        xstd, _ = standardize_data(x)
        m = mask_of(xstd).astype(xstd.real.dtype)
        spec, _ = _spectrum(fillz(xstd), m, M)
        freqs = 2.0 * jnp.pi * jnp.arange(2 * M + 1) / (2 * M + 1)
        return freqs, spec


def dynamic_pca(
    x,
    q: int,
    M: int = 20,
    backend: str | None = None,
) -> DynamicPCAResults:
    """Dynamic PCA with q dynamic factors on a (T, N) panel (standardized
    internally).  M is the lag-window half-width (grid has 2M+1 frequencies)."""
    with on_backend(backend):
        x = jnp.asarray(x)
        if M >= x.shape[0]:
            raise ValueError(
                f"lag-window half-width M={M} must be smaller than T={x.shape[0]}"
            )
        if not 1 <= q <= x.shape[1]:
            raise ValueError(
                f"q={q} dynamic factors out of range for an N={x.shape[1]} panel"
            )
        xstd, _ = standardize_data(x)
        m = mask_of(xstd).astype(xstd.dtype)
        freqs, evals, cspec, cacov, chi, share = _dynpca_core(fillz(xstd), m, M, q)
        return DynamicPCAResults(freqs, evals, cspec, cacov, chi, share, q, M)


def dynamic_eigenvalue_shares(results: DynamicPCAResults) -> np.ndarray:
    """Cumulative variance share of the first j dynamic eigenvalues,
    averaged over frequencies (the q-selection diagnostic)."""
    ev = np.asarray(results.eigenvalues)
    tot = ev.sum(axis=1, keepdims=True)
    cum = np.cumsum(ev, axis=1) / tot
    return cum.mean(axis=0)


class HallinLiskaResults(NamedTuple):
    """Output of `hallin_liska_q`.

    q: the selected number of dynamic factors (second stability interval);
    c_grid: the penalty-constant grid; q_by_c: (n_c,) full-sample q-hat at
    each c; stability: (n_c,) empirical variance of q-hat across the nested
    subsamples at each c (S_c in the paper — 0 marks a stability interval);
    q_subsamples: (J, n_c) the per-subsample selections; sub_sizes: the
    (n_j, T_j) ladder actually used."""

    q: int
    c_grid: np.ndarray
    q_by_c: np.ndarray
    stability: np.ndarray
    q_subsamples: np.ndarray
    sub_sizes: list


def _hl_eig_means(x: np.ndarray, M: int) -> np.ndarray:
    """Frequency-averaged dynamic eigenvalues of a (T, N) panel: (N,),
    descending — the ingredients of the Hallin-Liska criterion."""
    xj = jnp.asarray(x)
    xstd, _ = standardize_data(xj)
    m = mask_of(xstd).astype(xstd.dtype)
    spec, _ = _spectrum(fillz(xstd), m, M)
    evals = jnp.linalg.eigvalsh(spec)[:, ::-1].real  # (H, N) descending
    return np.asarray(evals.mean(axis=0))


def hallin_liska_q(
    x,
    q_max: int = 10,
    M: int | None = None,
    n_subsamples: int = 4,
    c_grid=None,
    criterion: str = "log",
    backend: str | None = None,
) -> HallinLiskaResults:
    """Hallin-Liska (2007, JASA 102(478)) information criterion for the
    number q of DYNAMIC factors in a generalized dynamic factor model.

    The criterion penalizes the tail of the frequency-averaged dynamic
    eigenvalues of the lag-window spectral estimate,

        IC_c(k) = crit(k) + k c p(n, T),
        crit(k) = (1/n) sum_{j>k} mean_h lambda_j(theta_h)   ("avg")
                  or log of that sum                          ("log"),
        p(n, T) = (M^-2 + sqrt(M/T) + 1/n) log(min(n, M^2, sqrt(T/M))),

    but its finite-sample bite depends on the undetermined constant c, so
    HL's estimator is SELF-CALIBRATING: q-hat_j(c) is computed over a grid
    of c on J nested subsamples (n_j, T_j), and the chosen q is the common
    value on the SECOND stability interval of c — the first interval (c
    near 0) trivially selects q_max everywhere, and stability means the
    selection has zero variance across subsamples (S_c = 0) while being
    constant in c.  This implements the full procedure, not the
    variance-share shortcut (`dynamic_eigenvalue_shares` remains as the
    quick diagnostic); validated on the FHLR analytic q=1 design and a
    q=2 GDFM in tests/test_config45_validation.py.

    M defaults to floor(0.75 sqrt(T_j)) per subsample (the paper's rate
    M_T ~ T^1/2).  x: (T, N) panel, NaN missing allowed (masked moments).
    """
    if criterion not in ("log", "avg"):
        raise ValueError(f"criterion must be 'log' or 'avg', got {criterion!r}")
    if n_subsamples < 2:
        raise ValueError("need at least 2 nested subsamples for stability")
    with on_backend(backend):
        x = np.asarray(x, float)
        T, N = x.shape
        if not 1 <= q_max < N:
            raise ValueError(f"q_max={q_max} out of range for N={N}")
        if c_grid is None:
            c_grid = np.linspace(0.01, 3.0, 120)
        c_grid = np.asarray(c_grid, float)
        J = n_subsamples

        # nested (n_j, T_j) ladder ending at the full panel (HL sec. 4)
        sub_sizes = []
        for j in range(1, J + 1):
            frac = 0.7 + 0.3 * j / J
            sub_sizes.append((int(round(N * frac)), int(round(T * frac))))
        sub_sizes[-1] = (N, T)
        n_min = min(n for n, _ in sub_sizes)
        if q_max >= n_min:
            raise ValueError(
                f"q_max={q_max} must be smaller than the smallest nested "
                f"subsample's series count ({n_min}); lower q_max or "
                "n_subsamples"
            )

        q_sub = np.zeros((J, c_grid.size), np.int64)
        for j, (n_j, T_j) in enumerate(sub_sizes):
            Mj = M if M is not None else max(3, int(0.75 * np.sqrt(T_j)))
            mu = _hl_eig_means(x[:T_j, :n_j], Mj)  # (n_j,) descending
            tail = np.concatenate(
                [np.cumsum(mu[::-1])[::-1], [0.0]]
            )  # tail[k] = sum_{j>=k} mu_j  (0-indexed)
            ks = np.arange(q_max + 1)
            crit = tail[ks] / n_j
            if criterion == "log":
                crit = np.log(np.maximum(crit, 1e-300))
            pen = (
                Mj ** -2.0 + np.sqrt(Mj / T_j) + 1.0 / n_j
            ) * np.log(min(n_j, Mj**2, np.sqrt(T_j / Mj)))
            # IC_c(k) for every c at once: (n_c, q_max+1)
            ic = crit[None, :] + ks[None, :] * (c_grid[:, None] * pen)
            q_sub[j] = np.argmin(ic, axis=1)

        stability = q_sub.var(axis=0)
        q_by_c = q_sub[-1]  # full panel

        # walk c upward: stability intervals are maximal runs with S_c = 0
        # and constant q-hat; the first is the q_max run at tiny c, the
        # second is the selection.  Degenerate cases (no second interval)
        # fall back to the last stable value.
        q_hat = int(q_by_c[-1])
        intervals = []
        i = 0
        while i < c_grid.size:
            if stability[i] == 0:
                k = i
                while (
                    k + 1 < c_grid.size
                    and stability[k + 1] == 0
                    and q_by_c[k + 1] == q_by_c[i]
                ):
                    k += 1
                intervals.append((i, k, int(q_by_c[i])))
                i = k + 1
            else:
                i += 1
        if intervals:
            # drop the leading trivial q_max interval if present
            cand = [iv for iv in intervals if iv[2] != q_max] or intervals
            q_hat = cand[0][2]
        return HallinLiskaResults(
            q=q_hat,
            c_grid=c_grid,
            q_by_c=q_by_c,
            stability=stability,
            q_subsamples=q_sub,
            sub_sizes=sub_sizes,
        )


def one_sided_common_component(
    x,
    q: int,
    r: int,
    M: int = 20,
    backend: str | None = None,
):
    """One-sided (real-time) common component via generalized PCA.

    The two-sided filter of `dynamic_pca` is non-causal — useless at the
    sample edge, which is where nowcasting lives.  The FHLR (2005) one-sided
    estimator fixes this: with the common/idiosyncratic covariances from the
    spectral step, take the r generalized eigenvectors W of
    (Gamma_chi(0), Gamma_xi(0)) — linear combinations maximizing the
    common/idio variance ratio — form static factors Z_t = W' x_t from
    CURRENT observations only, and project:

        chi_t|t = Gamma_chi(0) W (W' Gamma_x(0) W)^{-1} Z_t.

    Returns (chi_onesided (T, N), W (N, r), proj (N, r), results): the
    estimate is EXACTLY the contemporaneous map chi_t = proj (W' xz_t) of the
    standardized panel — row t never touches other rows (the causality
    guarantee, pinned by tests) — and `results` is the underlying two-sided
    DynamicPCAResults.
    """
    with on_backend(backend):
        xz, gamma_x0, W, res = _one_sided_pieces(x, q, r, M)
        gamma_chi0 = res.common_autocov[0]
        gamma_chi0 = 0.5 * (gamma_chi0 + gamma_chi0.T)
        Z = xz @ W  # (T, r) static factors, current observations only
        proj = gamma_chi0 @ W @ jnp.linalg.pinv(W.T @ gamma_x0 @ W)
        chi = Z @ proj.T  # (T, N)
        return chi, W, proj, res


def _one_sided_pieces(x, q: int, r: int, M: int):
    """Shared frame of the FHLR one-sided estimator/forecaster: standardized
    panel, Gamma_x(0), the generalized eigenvectors W of
    (Gamma_chi(0), Gamma_xi(0)), and the two-sided spectral results."""
    x = jnp.asarray(x)
    if M >= x.shape[0]:
        raise ValueError(
            f"lag-window half-width M={M} must be smaller than T={x.shape[0]}"
        )
    if not 1 <= q <= x.shape[1]:
        raise ValueError(f"q={q} out of range for an N={x.shape[1]} panel")
    if not 1 <= r <= x.shape[1]:
        raise ValueError(f"r={r} static factors out of range for N={x.shape[1]}")
    # one standardization + one spectral pass, shared with the two-sided
    # results we also return (only the cheap lag-0 moment is recomputed)
    xstd, _ = standardize_data(x)
    m = mask_of(xstd).astype(xstd.dtype)
    xz = fillz(xstd)
    freqs, evals, cspec, cacov, chi2s, share = _dynpca_core(xz, m, M, q)
    res = DynamicPCAResults(freqs, evals, cspec, cacov, chi2s, share, q, M)

    gamma_x0 = _masked_autocovariances(xz, m, 0)[0]
    gamma_x0 = 0.5 * (gamma_x0 + gamma_x0.T)
    gamma_chi0 = res.common_autocov[0]
    gamma_chi0 = 0.5 * (gamma_chi0 + gamma_chi0.T)
    gamma_xi0 = gamma_x0 - gamma_chi0

    # generalized symmetric eigenproblem via the idio Cholesky transform;
    # floor Gamma_xi to keep it PD (it is an estimate, PSD up to error)
    e, v = jnp.linalg.eigh(gamma_xi0)
    eps = jnp.asarray(jnp.finfo(e.dtype).eps, e.dtype)
    e = jnp.maximum(e, jnp.maximum(e[-1] * 16.0 * eps, eps))
    gamma_xi0 = (v * e) @ v.T
    L = jnp.linalg.cholesky(gamma_xi0)
    # A = L^{-1} Gamma_chi L^{-T} via two triangular solves
    A = jsl.solve_triangular(L, gamma_chi0, lower=True)
    A = jsl.solve_triangular(L, A.T, lower=True).T
    ew, U = jnp.linalg.eigh(0.5 * (A + A.T))
    W = jsl.solve_triangular(L, U[:, ::-1][:, :r], lower=True, trans=1)  # L^{-T} U
    return xz, gamma_x0, W, res


def forecast_common_component(
    x,
    q: int,
    r: int,
    h: int,
    M: int = 20,
    backend: str | None = None,
):
    """FHLR (2005, JASA 100(471)) h-step forecast of the common component:
    the one-sided projection with the lag-h common autocovariance,

        chi_{t+h|t} = Gamma_chi(h) W (W' Gamma_x(0) W)^{-1} W' x_t,

    valid because the idiosyncratic component is orthogonal to chi at all
    leads/lags, so Cov(chi_{t+h}, W'x_t) = Gamma_chi(h) W.  h=0 reduces to
    `one_sided_common_component` (pinned by tests).  h must lie in [0, M]
    (the lag window bounds the estimated autocovariances).

    Returns (chi_forecast (T, N) with row t = forecast of chi_{t+h} made at
    t, proj_h (N, r), results): standardized units, causal row-by-row like
    the one-sided estimator.
    """
    if not 0 <= h <= M:
        raise ValueError(f"h={h} must lie in [0, M={M}]")
    with on_backend(backend):
        xz, gamma_x0, W, res = _one_sided_pieces(x, q, r, M)
        gamma_chi_h = res.common_autocov[h]  # E[chi_t chi_{t-h}']
        if h == 0:
            gamma_chi_h = 0.5 * (gamma_chi_h + gamma_chi_h.T)  # exact h=0 match
        proj_h = gamma_chi_h @ W @ jnp.linalg.pinv(W.T @ gamma_x0 @ W)
        chi_f = (xz @ W) @ proj_h.T
        return chi_f, proj_h, res


def coherence(x, M: int = 20, backend: str | None = None):
    """Squared coherence and phase spectra between every pair of series.

    Frequency-domain comovement diagnostics on the shared lag-window
    spectral estimate: coh2[h, i, j] = |S_ij|^2 / (S_ii S_jj) in [0, 1]
    measures how strongly series i and j comove at frequency theta_h
    (business-cycle comovement lives at low frequencies); phase[h, i, j]
    = arg S_ij is the lead-lag relationship in radians (positive = i leads
    j at that frequency, by phase/theta periods).

    Returns (frequencies (H,), coh2 (H, N, N) real, phase (H, N, N) real).
    """
    freqs, spec = spectral_density(x, M, backend=backend)
    diag = jnp.maximum(jnp.diagonal(spec, axis1=1, axis2=2).real, 1e-12)
    denom = diag[:, :, None] * diag[:, None, :]
    coh2 = jnp.clip((jnp.abs(spec) ** 2) / denom, 0.0, 1.0)
    phase = jnp.angle(spec)
    return freqs, coh2, phase
