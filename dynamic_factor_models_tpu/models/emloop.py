"""Shared EM convergence driver: the loop itself lives on device.

The three EM estimators (`ssm.estimate_dfm_em`, `ssm_ar.estimate_dfm_em_ar`,
`mixed_freq.estimate_mixed_freq_dfm`) used to run their convergence loop on
the host, calling ``float(ll)`` once per iteration — one device->host sync
per EM step.  Here the relative-log-likelihood tolerance test is carried
inside a single ``lax.while_loop`` (the TPU-first shape the ALS core already
uses), with the per-iteration log-likelihood path written into a
preallocated carry array so no observability is lost.

``collect_path=True`` is the escape hatch: a host-synced loop that
additionally records wall-clock per iteration in a
`utils.profiling.ConvergenceTrace` (iters/sec without hand-rolled timing).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.profiling import ConvergenceTrace
from ..utils.telemetry import _heartbeat_cb, heartbeat_every, run_record, span

__all__ = ["run_em_loop", "run_bulk_then_exact"]


def _em_while_impl(
    step, carry, args, tol, max_em_iter: int, stop_at, heartbeat_every: int = 0
):
    """On-device EM loop.  Semantics match the host loop exactly: iterate
    `params, ll = step(params, *args)`; after iteration it >= 2, stop when
    |ll - ll_prev| < tol * (1 + |ll_prev|); always stop at max_em_iter.
    `stop_at` <= max_em_iter (a traced scalar, so chunked checkpointing
    reuses one compilation) bounds this invocation so a checkpointing
    driver can run the loop in chunks without changing its semantics.
    `heartbeat_every` > 0 (static, DFM_HEARTBEAT) adds a host progress
    callback every that-many iterations; at the default 0 the compiled
    program contains no callback at all."""
    dtype = jnp.result_type(tol)

    def cond(c):
        _, ll_prev, ll, it, _ = c
        unconverged = (it <= 1) | (
            jnp.abs(ll - ll_prev) >= tol * (1.0 + jnp.abs(ll_prev))
        )
        return unconverged & (it < stop_at)

    def body(c):
        params, _, ll, it, path = c
        new_params, ll_new = step(params, *args)
        path = path.at[it].set(ll_new.astype(dtype))
        if heartbeat_every:
            # unordered callback: the device never waits on the host —
            # progress reporting without a sync on the iteration path
            jax.lax.cond(
                (it + 1) % heartbeat_every == 0,
                lambda i, v: jax.debug.callback(_heartbeat_cb, i, v),
                lambda i, v: None,
                it + 1,
                ll_new,
            )
        return new_params, ll, ll_new.astype(dtype), it + 1, path

    return jax.lax.while_loop(cond, body, carry)


_em_while_plain = partial(
    jax.jit, static_argnames=("step", "max_em_iter", "heartbeat_every")
)(_em_while_impl)
# donated variant: the carry (params + convergence scalars + the
# max_em_iter-long loglik path) is input-output aliased, so XLA reuses
# its buffers instead of copying — chunked checkpoint runs re-donate each
# chunk's output into the next.  Unsupported on CPU (XLA warns and
# copies), hence the utils.compile.donation_enabled() gate in callers.
_em_while_donated = partial(
    jax.jit,
    static_argnames=("step", "max_em_iter", "heartbeat_every"),
    donate_argnums=(1,),
)(_em_while_impl)


def _em_while_jit(donate: bool):
    """The jitted on-device EM loop; donate=True is the carry-donating
    variant (callers must not reuse the carry they pass in)."""
    return _em_while_donated if donate else _em_while_plain


def _fresh_carry(params, tol, max_em_iter):
    dtype = jnp.result_type(tol)
    return (
        params,
        jnp.asarray(-jnp.inf, dtype),
        jnp.asarray(jnp.nan, dtype),
        jnp.asarray(0, jnp.int32),
        jnp.full(max_em_iter, jnp.nan, dtype),
    )


def _fingerprint(args, tol, max_em_iter: int, params=None) -> str:
    """Digest tying a checkpoint to its run: data bytes, shapes/dtypes,
    tolerance, iteration cap, and the parameter pytree STRUCTURE — a
    resume against different inputs, or across a step-transformer change
    (plain vs SQUAREM-augmented state), is a clear fingerprint error, not
    a confusing structural crash in the pytree loader."""
    import hashlib

    h = hashlib.sha256()
    h.update(repr((float(tol), int(max_em_iter))).encode())
    if params is not None:
        h.update(repr(jax.tree.structure(params)).encode())
    for leaf in jax.tree.leaves(args):
        a = np.asarray(leaf)
        h.update(repr((a.shape, str(a.dtype))).encode())
        h.update(a.tobytes())
    return h.hexdigest()


def run_em_loop(
    step,
    params,
    args: tuple,
    tol: float,
    max_em_iter: int,
    collect_path: bool = False,
    trace_name: str = "em",
    checkpoint_path: str | None = None,
    checkpoint_every: int = 25,
    stop_at=None,
):
    """Run an EM loop to convergence; returns (params, loglik_path, n_iter,
    trace).  `step(params, *args) -> (new_params, loglik-of-current-params)`
    must be a module-level jitted function (it is a static jit argument).

    trace is a ConvergenceTrace when collect_path=True, else None.

    `stop_at` (int or traced scalar <= max_em_iter) bounds THIS run's
    iterations without changing the compiled program (it feeds
    `_em_while`'s traced bound, the same mechanism checkpoint chunking
    uses) — phase-structured callers use it to share one max_em_iter
    budget across phases.  Not combinable with checkpoint_path.

    `checkpoint_path` makes a long run preemption-safe: the on-device loop
    executes in chunks of `checkpoint_every` iterations, persisting
    (params, convergence state, loglik path) to one .npz after each chunk
    (utils.checkpoint pytree round-trip, atomic rename); a rerun with the
    same path AND the same inputs (data/tol/max_em_iter, fingerprint-
    checked) resumes from the last completed chunk and produces the same
    final state as an uninterrupted run.
    """
    if max_em_iter < 0:
        raise ValueError(f"max_em_iter must be >= 0, got {max_em_iter}")
    if max_em_iter == 0:
        # zero-iteration contract (the DGR two-step estimator): parameters
        # pass through untouched — the while body cannot even be traced
        # against a zero-length loglik path.  collect_path still gets the
        # (empty) ConvergenceTrace the docstring promises.
        trace = ConvergenceTrace(trace_name) if collect_path else None
        return params, np.empty(0), 0, trace
    if checkpoint_path is not None and collect_path:
        raise ValueError(
            "collect_path=True uses a host-synced loop that does not "
            "checkpoint; drop checkpoint_path or collect_path"
        )
    if checkpoint_path is not None and checkpoint_every < 1:
        raise ValueError(f"checkpoint_every must be >= 1, got {checkpoint_every}")
    if checkpoint_path is not None and stop_at is not None:
        raise ValueError("stop_at and checkpoint_path are mutually exclusive")
    rec = run_record(
        "run_em_loop",
        config={
            "step": getattr(step, "__qualname__", repr(step)),
            "tol": tol,
            "max_em_iter": max_em_iter,
            "collect_path": collect_path,
            "trace_name": trace_name,
            "checkpointed": checkpoint_path is not None,
        },
    )
    if collect_path:
        if isinstance(stop_at, jax.core.Tracer):
            # int(tracer) below would raise an opaque
            # TracerIntegerConversionError from deep inside the loop setup
            raise ValueError(
                "collect_path=True runs a host loop and needs a concrete "
                "stop_at; pass a Python int (or None), or use "
                "collect_path=False — the on-device loop accepts a traced "
                "stop_at bound"
            )
        host_cap = max_em_iter if stop_at is None else min(max_em_iter, int(stop_at))
        trace = ConvergenceTrace(trace_name)
        llpath = []
        ll_prev = -np.inf
        it = 0
        with rec, span(trace_name):
            for it in range(1, host_cap + 1):
                params, ll = step(params, *args)
                ll = float(ll)
                llpath.append(ll)
                trace.record(ll)
                if it > 1 and abs(ll - ll_prev) < tol * (1.0 + abs(ll_prev)):
                    break
                ll_prev = ll
            rec.set(
                n_iter=it,
                converged=it < host_cap,
                final_loglik=llpath[-1] if llpath else None,
            )
        return params, np.asarray(llpath), it, trace

    from ..utils.compile import aot_call, aot_statics, donation_enabled

    with rec:
        tol_arr = jnp.asarray(tol, jnp.result_type(float))
        donate = donation_enabled()
        heartbeat = heartbeat_every()
        fp_params = params
        if donate:
            # the donated program may reuse every carry buffer, including the
            # caller-visible init params — hand the carry a copy so the
            # caller's arrays stay valid (run_bulk_then_exact re-reads the
            # init when the bulk phase goes non-finite)
            params = jax.tree.map(jnp.copy, params)
        carry = _fresh_carry(params, tol_arr, max_em_iter)
        del params  # donated with the carry; only the carry's copy is live
        loop = _em_while_jit(donate)
        # the heartbeat interval changes the compiled program, so it is part
        # of the dispatch key (utils.compile._kernel_plan mirrors the 0)
        statics = aot_statics(step, max_em_iter, donate, heartbeat)

        def _run(carry, bound):
            # dispatches to a utils.compile.precompile'd executable when one
            # matches (kernel "em_loop"); otherwise the live jit, whose
            # compile hits the persistent cache for a known program
            return aot_call(
                "em_loop",
                lambda c, a, t, s: loop(
                    step, c, a, t, max_em_iter, s, heartbeat
                ),
                carry, args, tol_arr, jnp.asarray(bound, jnp.int32),
                statics=statics,
            )

        if checkpoint_path is None:
            bound = max_em_iter if stop_at is None else stop_at
            with span(trace_name):
                carry = _run(carry, bound)
        else:
            import os
            import uuid

            from ..utils.checkpoint import load_pytree, save_pytree

            fp = _fingerprint(args, tol, max_em_iter, params=fp_params)
            if os.path.exists(checkpoint_path):
                stored = load_pytree(checkpoint_path, {"carry": carry, "fp": ""})
                if str(stored["fp"]) != fp:
                    raise ValueError(
                        f"checkpoint {checkpoint_path!r} was written for "
                        "different inputs (data/tol/max_em_iter fingerprint "
                        "mismatch); delete it or use another path"
                    )
                carry = jax.tree.map(jnp.asarray, stored["carry"])
            with span(trace_name):
                while True:
                    it = int(carry[3])
                    if it >= max_em_iter:
                        break
                    # reassign unconditionally: under donation the input
                    # carry's buffers are dead after the call (the output is
                    # value-identical when cond is false on entry, so keeping
                    # it preserves the old semantics)
                    carry = _run(carry, min(it + checkpoint_every, max_em_iter))
                    if int(carry[3]) == it:  # converged (cond false on entry)
                        break
                    # per-writer unique temp name: two concurrent runs
                    # sharing a checkpoint path must never clobber each
                    # other's half-written archive before the atomic rename
                    tmp = (
                        f"{checkpoint_path}.tmp."
                        f"{os.getpid()}.{uuid.uuid4().hex[:8]}.npz"
                    )
                    try:
                        save_pytree(tmp, {"carry": carry, "fp": fp})
                        os.replace(tmp, checkpoint_path)
                    except BaseException:
                        try:  # a failed save must not leak its temp file
                            os.remove(tmp)
                        except OSError:
                            pass
                        raise

        params, _, _, n_iter, path = carry
        n_iter = int(n_iter)
        llpath = np.asarray(path)[:n_iter]
        rec.set(
            n_iter=n_iter,
            converged=n_iter < max_em_iter,
            final_loglik=float(llpath[-1]) if n_iter else None,
            donate=donate,
            heartbeat_every=heartbeat,
        )
    return params, llpath, n_iter, None


def run_bulk_then_exact(
    bulk_step,
    exact_step,
    params,
    bulk_args: tuple,
    exact_args: tuple,
    tol: float,
    max_em_iter: int,
    trace_name: str,
    collect_path: bool = False,
):
    """Mixed-precision two-phase EM driver (the single copy of the
    gram_dtype orchestration shared by `ssm.estimate_dfm_em` and
    `mixed_freq.estimate_mixed_freq_dfm`).

    Phase 1 runs `bulk_step` on `bulk_args` (the bf16-twin stats) under a
    loosened tolerance, capped at HALF the budget — the bulk map is only
    productive in moderate signal-to-noise regimes, so the exact phase
    must always keep at least half.  A bulk phase ending in non-finite
    PARAMS (the loglik path records the loglik of each iteration's INPUT,
    so it cannot certify the final output) falls back to the original
    init with the full budget.  Phase 2 runs `exact_step` on `exact_args`
    under the caller's tol for the remaining budget (always >= 1
    iteration).  Returns (params, concatenated loglik path, total
    n_iter, trace).

    The concatenated loglik path can DROP at the phase boundary (index
    `n_pre`): the bulk entries are logliks of the bf16-Gram (R-floored)
    map, the exact entries of the exact map — two different objectives.
    A one-step decrease at the seam is the precision gap being repaid,
    not EM divergence; monotonicity diagnostics should treat the two
    segments separately.

    Build `bulk_args` inline in the call expression (don't bind the bf16
    twins in the caller): this function drops its reference before phase 2,
    so the twin arrays are freed for the exact phase's working set.

    A budget of one iteration skips the bulk phase entirely — half of one
    is zero useful bulk work, and the caller's cap is a hard bound.

    Step transformers compose transparently: when BOTH steps are wrapped
    the same way (e.g. `squarem(bulk)` and `squarem(exact)`), the
    augmented loop state flows from the bulk phase into the exact phase
    unchanged — the caller wraps the initial params once and unwraps the
    result once.
    """
    if max_em_iter < 2:
        return run_em_loop(
            exact_step, params, exact_args, tol, max_em_iter,
            collect_path=collect_path, trace_name=trace_name,
        )
    params_b, llpath_pre, n_pre, _ = run_em_loop(
        bulk_step, params, bulk_args, max(tol, 1e-4), max_em_iter,
        trace_name=trace_name + "_bf16", stop_at=max(max_em_iter // 2, 1),
    )
    del bulk_args  # the bf16 twins: freed before the exact phase runs
    params_ok = all(
        bool(np.isfinite(np.asarray(leaf)).all())
        for leaf in jax.tree.leaves(params_b)
    )
    if n_pre > 0 and params_ok:
        params = params_b
    else:
        n_pre = 0
        llpath_pre = np.empty(0)
    del params_b
    params, llpath, n_iter, trace = run_em_loop(
        exact_step, params, exact_args, tol, max_em_iter,
        collect_path=collect_path, trace_name=trace_name,
        stop_at=max(max_em_iter - n_pre, 1) if n_pre else None,
    )
    return params, np.concatenate([llpath_pre, llpath]), n_iter + n_pre, trace
