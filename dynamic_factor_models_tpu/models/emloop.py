"""Shared EM convergence driver: the loop itself lives on device.

The three EM estimators (`ssm.estimate_dfm_em`, `ssm_ar.estimate_dfm_em_ar`,
`mixed_freq.estimate_mixed_freq_dfm`) used to run their convergence loop on
the host, calling ``float(ll)`` once per iteration — one device->host sync
per EM step.  Here the relative-log-likelihood tolerance test is carried
inside a single ``lax.while_loop`` (the TPU-first shape the ALS core already
uses), with the per-iteration log-likelihood path written into a
preallocated carry array so no observability is lost.

``collect_path=True`` is the escape hatch: a host-synced loop that
additionally records wall-clock per iteration in a
`utils.profiling.ConvergenceTrace` (iters/sec without hand-rolled timing).

Numerical-health guardrails (utils/guards.py) ride the device loop by
default: the guarded while-loop variant carries the previous iterate and a
`health` flag, trips on any non-finite log-likelihood / parameter leaf or
an EM monotonicity violation, and exits with the LAST-GOOD params rolled
back on device.  `run_em_loop` then walks a bounded recovery ladder —
ridge-jitter, jitter with grown epsilon, demote to the caller-supplied
exact fallback step, promote f32 to f64 — each rung retried once, every
trip and recovery recorded in telemetry.  `DFM_GUARDS=0` restores the
PR-1 unguarded program bit-for-bit (its HLO is pinned byte-identical by
the chaos bench).  Deterministic fault injection (utils/faults.py,
`DFM_FAULTS`) is baked into the guarded program as statics, so the
default program carries no injection code at all.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..utils import faults as _faults
from ..utils import flight as _flight
from ..utils import guards as _guards
from ..utils.profiling import ConvergenceTrace
from ..utils.telemetry import (
    _heartbeat_cb,
    heartbeat_every,
    inc,
    run_record,
    span,
)

__all__ = [
    "run_em_loop",
    "run_em_loop_batched",
    "run_bulk_then_exact",
    "EMLoopResult",
    "BatchedEMResult",
]


def _em_while_impl(
    step, carry, args, tol, max_em_iter: int, stop_at, heartbeat_every: int = 0
):
    """On-device EM loop.  Semantics match the host loop exactly: iterate
    `params, ll = step(params, *args)`; after iteration it >= 2, stop when
    |ll - ll_prev| < tol * (1 + |ll_prev|); always stop at max_em_iter.
    `stop_at` <= max_em_iter (a traced scalar, so chunked checkpointing
    reuses one compilation) bounds this invocation so a checkpointing
    driver can run the loop in chunks without changing its semantics.
    `heartbeat_every` > 0 (static, DFM_HEARTBEAT) adds a host progress
    callback every that-many iterations; at the default 0 the compiled
    program contains no callback at all."""
    dtype = jnp.result_type(tol)

    def cond(c):
        _, ll_prev, ll, it, _ = c
        unconverged = (it <= 1) | (
            jnp.abs(ll - ll_prev) >= tol * (1.0 + jnp.abs(ll_prev))
        )
        return unconverged & (it < stop_at)

    def body(c):
        params, _, ll, it, path = c
        new_params, ll_new = step(params, *args)
        path = path.at[it].set(ll_new.astype(dtype))
        if heartbeat_every:
            # unordered callback: the device never waits on the host —
            # progress reporting without a sync on the iteration path
            jax.lax.cond(
                (it + 1) % heartbeat_every == 0,
                lambda i, v: jax.debug.callback(_heartbeat_cb, i, v),
                lambda i, v: None,
                it + 1,
                ll_new,
            )
        return new_params, ll, ll_new.astype(dtype), it + 1, path

    return jax.lax.while_loop(cond, body, carry)


_em_while_plain = partial(
    jax.jit, static_argnames=("step", "max_em_iter", "heartbeat_every")
)(_em_while_impl)
# donated variant: the carry (params + convergence scalars + the
# max_em_iter-long loglik path) is input-output aliased, so XLA reuses
# its buffers instead of copying — chunked checkpoint runs re-donate each
# chunk's output into the next.  Unsupported on CPU (XLA warns and
# copies), hence the utils.compile.donation_enabled() gate in callers.
_em_while_donated = partial(
    jax.jit,
    static_argnames=("step", "max_em_iter", "heartbeat_every"),
    donate_argnums=(1,),
)(_em_while_impl)


def _em_while_jit(donate: bool):
    """The jitted on-device EM loop; donate=True is the carry-donating
    variant (callers must not reuse the carry they pass in)."""
    return _em_while_donated if donate else _em_while_plain


def _em_while_guarded_impl(
    step,
    carry,
    args,
    tol,
    drop_tol,
    max_em_iter: int,
    stop_at,
    heartbeat_every: int = 0,
    inject_nan_at: int = 0,
    inject_chol_at: int = 0,
):
    """Guarded on-device EM loop: `_em_while_impl` semantics plus the
    utils.guards sentinel AND the first two recovery-ladder rungs folded
    into the carry.

    Carry: (params, prev_params, ll_prev, ll, it, path, health, rung,
    trips, resume_from).  Each body call evaluates the step; when the new
    log-likelihood or any new parameter leaf is non-finite, or the
    log-likelihood DROPS by more than `drop_tol * (1 + |ll|)` (EM is
    monotone; the relative slack covers f32 roundoff and the steady
    tail's approximate moments), the iterate is rolled back to
    `prev_params` and one of two things happens ON DEVICE:

    - `rung < guards.N_TRACED_RUNGS`: the jitter / jitter_grown repair
      (`guards.ridge_jitter` with the traced rung) is applied to the
      rolled-back params inside a `lax.cond` (the healthy path never
      evaluates it), `rung`/`trips` advance, `resume_from` is reset to
      the current iteration, and the loop CONTINUES — a jitter-recovered
      run completes in one dispatch with zero device->host transfers per
      iteration, exactly like a healthy run (pinned in
      tests/test_perf_regression.py).
    - otherwise the carry is frozen with `health` set (1 non-finite, 2
      monotonicity), the cond exits, and the host ladder takes over for
      the step/dtype-changing rungs (demote, promote_f64).

    `resume_from` rides the carry (it used to be a traced argument): the
    two-loglik convergence bootstrap and the monotonicity baseline both
    restart at the last resume point, so a rung's first post-resume step
    is never judged against the pre-trip trajectory (0 for a fresh run,
    reproducing `it <= 1` exactly).

    `inject_nan_at` / `inject_chol_at` (static, from utils.faults) bake
    a deterministic fault into THIS program: NaN the k-th iteration's
    log-likelihood, or poison the innovation covariance entering the
    k-th step so its Cholesky genuinely fails.  A POSITIVE k is a
    transient fault — it fires only while `trips == 0`, i.e. in the
    first attempt, matching the old host-ladder semantics where the
    retry program carried no injection; a NEGATIVE k is a persistent
    fault (`kind@k+`) firing on every in-trace attempt until the host
    demotes/promotes to a different program.  At the default 0 the
    traced functions are identity and the program carries no fault code.
    """
    dtype = jnp.result_type(tol)

    def cond(c):
        _, _, ll_prev, ll, it, _, health, _, _, resume_from = c
        unconverged = (it <= resume_from + 1) | (
            jnp.abs(ll - ll_prev) >= tol * (1.0 + jnp.abs(ll_prev))
        )
        return (health == 0) & unconverged & (it < stop_at)

    def body(c):
        (
            params, prev_params, ll_prev, ll, it, path, health,
            rung, trips, resume_from,
        ) = c
        step_in = params
        if inject_chol_at:
            fire = it + 1 == abs(inject_chol_at)
            if inject_chol_at > 0:
                fire = fire & (trips == 0)
            step_in = _guards.poison_cov(step_in, fire)
        new_params, ll_new = step(step_in, *args)
        if inject_nan_at:
            fire = it + 1 == abs(inject_nan_at)
            if inject_nan_at > 0:
                fire = fire & (trips == 0)
            ll_new = jnp.where(fire, jnp.full_like(ll_new, jnp.nan), ll_new)
        ll_new = ll_new.astype(dtype)
        nonfinite = (~jnp.isfinite(ll_new)) | (~_guards.tree_finite(new_params))
        drop = (it >= resume_from + 1) & (
            ll - ll_new > drop_tol * (1.0 + jnp.abs(ll))
        )
        new_health = jnp.where(
            nonfinite,
            _guards.HEALTH_NONFINITE,
            jnp.where(drop, _guards.HEALTH_DECREASE, _guards.HEALTH_OK),
        ).astype(jnp.int32)
        bad = new_health != 0
        recover = bad & (rung < _guards.N_TRACED_RUNGS)
        freeze = bad & ~recover
        # device-resident jitter rungs: evaluated only on a tripped
        # iteration (lax.cond — the healthy path skips the eigh entirely),
        # applied to the ROLLED-BACK last-good params like the host ladder
        repaired = jax.lax.cond(
            recover,
            lambda p: _guards.ridge_jitter(p, rung),
            lambda p: p,
            prev_params,
        )
        sel3 = lambda on_freeze, on_recover, on_ok: jax.tree.map(
            lambda a, b, y: jnp.where(freeze, a, jnp.where(recover, b, y)),
            on_freeze, on_recover, on_ok,
        )
        if heartbeat_every:
            jax.lax.cond(
                (it + 1) % heartbeat_every == 0,
                lambda i, v: jax.debug.callback(_heartbeat_cb, i, v),
                lambda i, v: None,
                it + 1,
                ll_new,
            )
        return (
            sel3(prev_params, repaired, new_params),
            sel3(prev_params, repaired, params),
            jnp.where(bad, ll_prev, ll),
            jnp.where(bad, ll, ll_new),
            jnp.where(bad, it, it + 1),
            path.at[it].set(jnp.where(bad, path[it], ll_new)),
            jnp.where(freeze, new_health, _guards.HEALTH_OK).astype(jnp.int32),
            jnp.where(recover, rung + 1, rung),
            jnp.where(bad, trips + 1, trips),
            jnp.where(recover, it, resume_from),
        )

    return jax.lax.while_loop(cond, body, carry)


_GUARDED_STATICS = (
    "step",
    "max_em_iter",
    "heartbeat_every",
    "inject_nan_at",
    "inject_chol_at",
)
_em_while_guarded_plain = partial(jax.jit, static_argnames=_GUARDED_STATICS)(
    _em_while_guarded_impl
)
_em_while_guarded_donated = partial(
    jax.jit, static_argnames=_GUARDED_STATICS, donate_argnums=(1,)
)(_em_while_guarded_impl)


def _em_while_guarded_jit(donate: bool):
    return _em_while_guarded_donated if donate else _em_while_guarded_plain


def _fresh_carry(params, tol, max_em_iter):
    dtype = jnp.result_type(tol)
    return (
        params,
        jnp.asarray(-jnp.inf, dtype),
        jnp.asarray(jnp.nan, dtype),
        jnp.asarray(0, jnp.int32),
        jnp.full(max_em_iter, jnp.nan, dtype),
    )


def _fresh_guarded_carry(params, tol, max_em_iter):
    dtype = jnp.result_type(tol)
    # prev_params gets its own buffers: under donation the whole carry is
    # donated, and two leaves aliasing one buffer cannot both be donated
    return (
        params,
        jax.tree.map(jnp.copy, params),
        jnp.asarray(-jnp.inf, dtype),
        jnp.asarray(jnp.nan, dtype),
        jnp.asarray(0, jnp.int32),
        jnp.full(max_em_iter, jnp.nan, dtype),
        jnp.asarray(0, jnp.int32),  # health
        jnp.asarray(0, jnp.int32),  # next ladder rung (traced rungs spent)
        jnp.asarray(0, jnp.int32),  # cumulative sentinel trips
        jnp.asarray(0, jnp.int32),  # resume_from
    )


class EMLoopResult(tuple):
    """`run_em_loop` result: unpacks as the historical 4-tuple
    (params, loglik_path, n_iter, trace) so every existing call site
    keeps working, while carrying the guardrail outcome as attributes:

    converged        True iff the relative-loglik tolerance actually broke
                     the loop (NOT the old `n_iter < cap` proxy, which
                     misreported convergence-on-the-final-iteration)
    health           final utils.guards health code (0 = healthy)
    faults_detected  sentinel trips over the whole run
    recoveries       trips the ladder recovered from (run ended healthy)
    ladder_rung      1-based index into guards.LADDER_RUNGS of the last
                     rung applied (0 = ladder never engaged)
    rungs_used       names of the rungs applied, in order
    """

    def __new__(
        cls,
        params,
        llpath,
        n_iter,
        trace,
        *,
        converged,
        health=0,
        faults_detected=0,
        recoveries=0,
        ladder_rung=0,
        rungs_used=(),
    ):
        self = super().__new__(cls, (params, llpath, n_iter, trace))
        self.converged = bool(converged)
        self.health = int(health)
        self.faults_detected = int(faults_detected)
        self.recoveries = int(recoveries)
        self.ladder_rung = int(ladder_rung)
        self.rungs_used = tuple(rungs_used)
        return self

    params = property(lambda self: self[0])
    loglik_path = property(lambda self: self[1])
    n_iter = property(lambda self: self[2])
    trace = property(lambda self: self[3])


# (B,) per-batch-member finiteness; the shared sentinel primitive moved to
# utils.guards so scenarios/gibbs.py reuses the identical check
_batched_finite = _guards.batched_tree_finite


def _em_while_batched_impl(
    step,
    carry,
    args,
    tol,
    drop_tol,
    max_em_iter: int,
    stop_at,
    inject_nan_at: int = 0,
):
    """Vmapped multi-tenant EM loop: B panels of identical (bucketed)
    shape advance together under ONE `lax.while_loop`, each tenant
    carrying its own convergence scalars and utils.guards health flag.

    Per-tenant semantics replicate the scalar guarded loop exactly: a
    tenant is ACTIVE while healthy, unconverged (|ll - ll_prev| >=
    tol * (1 + |ll_prev|), bootstrapped by it <= 1) and under `stop_at`;
    the loop runs while any tenant is active.  Each body call evaluates
    the vmapped step for the whole batch; a tenant whose new
    log-likelihood or parameter leaves are non-finite, or whose
    log-likelihood drops by more than drop_tol * (1 + |ll|), is rolled
    back to its previous iterate and FROZEN with its health flag set —
    the one-bad-tenant isolation contract: the other tenants keep
    iterating, their carries untouched by the divergent panel (vmap is
    elementwise across the batch axis).  Converged/frozen tenants still
    ride through the vmapped step (batched shapes are static) but every
    result is discarded by the per-tenant select.

    Carry: (params_B, prev_params_B, ll_prev (B,), ll (B,), it (B,),
    path (B, max_em_iter), health (B,)).  `inject_nan_at` (static, from
    utils.faults nan_estep) NaNs TENANT 0's log-likelihood at that
    iteration — the deterministic one-bad-tenant drill; 0 compiles no
    injection code."""
    dtype = jnp.result_type(tol)
    vstep = jax.vmap(step)

    def active_of(c):
        _, _, ll_prev, ll, it, _, health = c
        unconverged = (it <= 1) | (
            jnp.abs(ll - ll_prev) >= tol * (1.0 + jnp.abs(ll_prev))
        )
        return (health == 0) & unconverged & (it < stop_at)

    def cond(c):
        return jnp.any(active_of(c))

    def body(c):
        params, prev_params, ll_prev, ll, it, path, health = c
        act = active_of(c)
        new_params, ll_new = vstep(params, *args)
        ll_new = ll_new.astype(dtype)
        if inject_nan_at:
            ll_new = ll_new.at[0].set(
                jnp.where(it[0] + 1 == inject_nan_at, jnp.nan, ll_new[0])
            )
        nonfinite = (~jnp.isfinite(ll_new)) | (~_batched_finite(new_params))
        drop = (it >= 1) & (ll - ll_new > drop_tol * (1.0 + jnp.abs(ll)))
        bad = act & (nonfinite | drop)
        adv = act & ~bad

        def bwhere(cnd, x, y):
            return jax.tree.map(
                lambda a, b: jnp.where(
                    cnd.reshape(cnd.shape + (1,) * (a.ndim - 1)), a, b
                ),
                x,
                y,
            )

        B = ll.shape[0]
        rows = jnp.arange(B)
        slot = jnp.minimum(it, max_em_iter - 1)
        return (
            bwhere(bad, prev_params, bwhere(adv, new_params, params)),
            bwhere(bad, prev_params, bwhere(adv, params, prev_params)),
            jnp.where(adv, ll, ll_prev),
            jnp.where(adv, ll_new, ll),
            jnp.where(adv, it + 1, it),
            path.at[rows, slot].set(jnp.where(adv, ll_new, path[rows, slot])),
            jnp.where(
                bad,
                jnp.where(
                    nonfinite, _guards.HEALTH_NONFINITE, _guards.HEALTH_DECREASE
                ),
                health,
            ).astype(jnp.int32),
        )

    return jax.lax.while_loop(cond, body, carry)


_em_while_batched = partial(
    jax.jit, static_argnames=("step", "max_em_iter", "inject_nan_at")
)(_em_while_batched_impl)


def _fresh_batched_carry(params_B, tol, max_em_iter, B: int):
    dtype = jnp.result_type(tol)
    return (
        params_B,
        jax.tree.map(jnp.copy, params_B),
        jnp.full((B,), -jnp.inf, dtype),
        jnp.full((B,), jnp.nan, dtype),
        jnp.zeros((B,), jnp.int32),
        jnp.full((B, max_em_iter), jnp.nan, dtype),
        jnp.zeros((B,), jnp.int32),
    )


class BatchedEMResult(NamedTuple):
    """`run_em_loop_batched` result, everything per-tenant along the
    leading batch axis: `params` the stacked parameter pytree, `llpath`
    (B, max_em_iter) log-likelihood paths (NaN past each tenant's
    n_iter), `n_iter` (B,), `converged` (B,) the actual tolerance-break
    replay, `health` (B,) utils.guards codes (0 healthy; a non-zero
    tenant was rolled back to its last-good iterate and frozen)."""

    params: object
    llpath: np.ndarray
    n_iter: np.ndarray
    converged: np.ndarray
    health: np.ndarray


def run_em_loop_batched(
    step,
    params_B,
    args_B: tuple,
    tol: float,
    max_em_iter: int,
    stop_at=None,
):
    """Run EM to convergence for B same-shape panels in one vmapped
    device loop (the serving layer's batched re-estimation path; panels
    are made shape-identical by utils.compile.pad_panel).

    `step` is the SCALAR per-panel step (e.g. ssm.em_step_stats) —
    vmapping happens inside the compiled loop.  `params_B` / every leaf
    of `args_B` carry a leading batch axis of size B.  Per-tenant
    semantics match `run_em_loop(guard=True)` up to the recovery ladder:
    the in-loop sentinel and rollback are identical, but a tripped
    tenant is frozen at its last-good iterate (health reported in the
    result) instead of escalating the host ladder — re-running one
    divergent tenant alone is the caller's policy decision, and the
    other B-1 tenants' results are unaffected either way.

    Dispatches through the AOT registry (kernel "em_loop_batched") so a
    `precompile` with CompileSpec(em_batch=B) serves the whole loop.
    `DFM_FAULTS=nan_estep@k` injects a NaN into tenant 0's k-th
    iteration (the chaos drill for one-bad-tenant isolation)."""
    from ..utils.compile import aot_call, aot_statics

    if max_em_iter < 1:
        raise ValueError(f"max_em_iter must be >= 1, got {max_em_iter}")
    B = int(jax.tree.leaves(params_B)[0].shape[0])
    plan = _faults.active_plan()
    inject_nan_at = plan.nan_estep or 0
    rec = run_record(
        "run_em_loop_batched",
        kind="refit_batch",
        config={
            "step": getattr(step, "__qualname__", repr(step)),
            "tol": tol,
            "max_em_iter": max_em_iter,
            "batch": B,
        },
    )
    with rec:
        if inject_nan_at:
            _faults.fault_fired("nan_estep")
        ld = jnp.result_type(float)
        tol_arr = jnp.asarray(tol, ld)
        drop_arr = jnp.asarray(_guards.drop_tol(), ld)
        carry = _fresh_batched_carry(params_B, tol_arr, max_em_iter, B)
        statics = aot_statics(step, max_em_iter, inject_nan_at)
        bound = max_em_iter if stop_at is None else stop_at
        with span("em_batched"):
            carry = aot_call(
                "em_loop_batched",
                lambda c, a, t, d, s: _em_while_batched(
                    step, c, a, t, d, max_em_iter, s, inject_nan_at
                ),
                carry, args_B, tol_arr, drop_arr,
                jnp.asarray(bound, jnp.int32),
                statics=statics,
            )
        params, _, ll_prev, ll, n_iter, path, health = carry
        n_iter = np.asarray(n_iter)
        health = np.asarray(health)
        ll_prev = np.asarray(ll_prev)
        ll = np.asarray(ll)
        converged = np.array(
            [
                health[b] == _guards.HEALTH_OK
                and n_iter[b] >= 2
                and _tol_break(ll_prev[b], ll[b], tol)
                for b in range(B)
            ],
            bool,
        )
        n_bad = int((health != _guards.HEALTH_OK).sum())
        if n_bad:
            inc("em_guard.faults_detected", n_bad)
        rec.set(
            n_iter=int(n_iter.max()) if B else 0,
            n_iter_per_tenant=[int(v) for v in n_iter],
            converged=bool(converged.all()),
            final_loglik=float(np.nanmax(ll)) if B else None,
            batch=B,
            tenants_unhealthy=n_bad,
        )
    return BatchedEMResult(
        params=params,
        llpath=np.asarray(path),
        n_iter=n_iter,
        converged=converged,
        health=health,
    )


def _fingerprint(args, tol, max_em_iter: int, params=None) -> str:
    """Digest tying a checkpoint to its run: data bytes, shapes/dtypes,
    tolerance, iteration cap, and the parameter pytree STRUCTURE — a
    resume against different inputs, or across a step-transformer change
    (plain vs SQUAREM-augmented state), is a clear fingerprint error, not
    a confusing structural crash in the pytree loader."""
    import hashlib

    h = hashlib.sha256()
    h.update(repr((float(tol), int(max_em_iter))).encode())
    if params is not None:
        h.update(repr(jax.tree.structure(params)).encode())
    for leaf in jax.tree.leaves(args):
        a = np.asarray(leaf)
        h.update(repr((a.shape, str(a.dtype))).encode())
        h.update(a.tobytes())
    return h.hexdigest()


def _tol_break(ll_prev, ll, tol) -> bool:
    """Host-side replay of the loop's convergence test on the final two
    loglik values — the actual tolerance break, not an iteration-count
    proxy (a run converging exactly on the last permitted iteration is
    converged)."""
    ll_prev = float(ll_prev)
    ll = float(ll)
    return (
        np.isfinite(ll)
        and np.isfinite(ll_prev)
        and abs(ll - ll_prev) < float(tol) * (1.0 + abs(ll_prev))
    )


class _CheckpointDriver:
    """Chunked checkpoint persistence shared by the guarded and unguarded
    device loops: resume (with corruption quarantine), atomic save, and
    the utils.faults checkpoint fault sites (`ckpt_corrupt@n` damages the
    archive after the n-th save of this run; `preempt@n` raises
    SimulatedPreemption after the n-th save, the checkpoint already on
    disk so the next run resumes)."""

    def __init__(self, path, like_carry, fp, rec, plan):
        self.path = path
        self.fp = fp
        self.rec = rec
        self.plan = plan
        self.saves = 0
        self.like = like_carry

    def resume(self, carry):
        import os

        from ..utils.checkpoint import CheckpointCorruptError, load_pytree

        if not os.path.exists(self.path):
            return carry
        try:
            stored = load_pytree(self.path, {"carry": self.like, "fp": ""})
        except CheckpointCorruptError:
            # the loader already quarantined the file to <path>.corrupt;
            # restart cleanly from the fresh carry instead of crashing
            inc("checkpoint.quarantined")
            self.rec.set(checkpoint_quarantined=True)
            return carry
        if str(stored["fp"]) != self.fp:
            raise ValueError(
                f"checkpoint {self.path!r} was written for different "
                "inputs (data/tol/max_em_iter fingerprint mismatch); "
                "delete it or use another path"
            )
        return jax.tree.map(jnp.asarray, stored["carry"])

    def save(self, carry):
        import os
        import uuid

        from ..utils.checkpoint import save_pytree

        # per-writer unique temp name: two concurrent runs sharing a
        # checkpoint path must never clobber each other's half-written
        # archive before the atomic rename
        tmp = f"{self.path}.tmp.{os.getpid()}.{uuid.uuid4().hex[:8]}.npz"
        try:
            save_pytree(tmp, {"carry": carry, "fp": self.fp})
            os.replace(tmp, self.path)
        except BaseException:
            try:  # a failed save must not leak its temp file
                os.remove(tmp)
            except OSError:
                pass
            raise
        self.saves += 1
        if self.plan.ckpt_corrupt is not None and self.saves == self.plan.ckpt_corrupt:
            _faults.corrupt_file(self.path)
        if self.plan.preempt is not None and self.saves == self.plan.preempt:
            _faults.fault_fired("preempt")
            raise _faults.SimulatedPreemption(
                f"injected preemption after checkpoint chunk "
                f"{self.saves} ({self.path})"
            )


def run_em_loop(
    step,
    params,
    args: tuple,
    tol: float,
    max_em_iter: int,
    collect_path: bool = False,
    trace_name: str = "em",
    checkpoint_path: str | None = None,
    checkpoint_every: int = 25,
    stop_at=None,
    fallback_step=None,
    fallback_unwrap=None,
    fallback_args=None,
    guard: bool | None = None,
):
    """Run an EM loop to convergence; returns an `EMLoopResult`, which
    unpacks as (params, loglik_path, n_iter, trace).
    `step(params, *args) -> (new_params, loglik-of-current-params)`
    must be a module-level jitted function (it is a static jit argument).

    trace is a ConvergenceTrace when collect_path=True, else None.

    `stop_at` (int or traced scalar <= max_em_iter) bounds THIS run's
    iterations without changing the compiled program (it feeds
    `_em_while`'s traced bound, the same mechanism checkpoint chunking
    uses) — phase-structured callers use it to share one max_em_iter
    budget across phases.  Not combinable with checkpoint_path.

    `checkpoint_path` makes a long run preemption-safe: the on-device loop
    executes in chunks of `checkpoint_every` iterations, persisting
    (params, convergence state, loglik path) to one .npz after each chunk
    (utils.checkpoint pytree round-trip, atomic rename); a rerun with the
    same path AND the same inputs (data/tol/max_em_iter, fingerprint-
    checked) resumes from the last completed chunk and produces the same
    final state as an uninterrupted run.  A corrupted/unreadable
    checkpoint is quarantined to `<path>.corrupt` and the run restarts
    cleanly.

    `guard` (default: utils.guards.guards_enabled(), env DFM_GUARDS)
    selects the guarded while-loop: a health sentinel trips on non-finite
    values or an EM log-likelihood decrease, rolls back to the last-good
    iterate, and escalates a bounded recovery ladder — ridge-jitter the
    innovation covariance (twice, growing epsilon), demote to
    `fallback_step` (the caller's exact sequential step; `fallback_unwrap`
    converts the tripped loop state to the fallback's parameter type,
    `fallback_args` its argument tuple when it differs), then promote f32
    to f64.  Each rung is tried once; an exhausted ladder returns the
    last-good params with `EMLoopResult.health != 0` rather than raising.
    With guard=False the PR-1 unguarded program runs unchanged.
    """
    if max_em_iter < 0:
        raise ValueError(f"max_em_iter must be >= 0, got {max_em_iter}")
    if max_em_iter == 0:
        # zero-iteration contract (the DGR two-step estimator): parameters
        # pass through untouched — the while body cannot even be traced
        # against a zero-length loglik path.  collect_path still gets the
        # (empty) ConvergenceTrace the docstring promises.
        trace = ConvergenceTrace(trace_name) if collect_path else None
        return EMLoopResult(params, np.empty(0), 0, trace, converged=False)
    if checkpoint_path is not None and collect_path:
        raise ValueError(
            "collect_path=True uses a host-synced loop that does not "
            "checkpoint; drop checkpoint_path or collect_path"
        )
    if checkpoint_path is not None and checkpoint_every < 1:
        raise ValueError(f"checkpoint_every must be >= 1, got {checkpoint_every}")
    if checkpoint_path is not None and stop_at is not None:
        raise ValueError("stop_at and checkpoint_path are mutually exclusive")
    guard_on = _guards.guards_enabled() if guard is None else bool(guard)
    plan = _faults.active_plan()
    rec = run_record(
        "run_em_loop",
        config={
            "step": getattr(step, "__qualname__", repr(step)),
            "tol": tol,
            "max_em_iter": max_em_iter,
            "collect_path": collect_path,
            "trace_name": trace_name,
            "checkpointed": checkpoint_path is not None,
            "guarded": guard_on,
        },
    )
    if collect_path:
        if isinstance(stop_at, jax.core.Tracer):
            # int(tracer) below would raise an opaque
            # TracerIntegerConversionError from deep inside the loop setup
            raise ValueError(
                "collect_path=True runs a host loop and needs a concrete "
                "stop_at; pass a Python int (or None), or use "
                "collect_path=False — the on-device loop accepts a traced "
                "stop_at bound"
            )
        return _run_host_loop(
            step, params, args, tol, max_em_iter, stop_at, trace_name,
            rec, guard_on,
        )

    if not guard_on:
        return _run_device_unguarded(
            step, params, args, tol, max_em_iter, checkpoint_path,
            checkpoint_every, stop_at, trace_name, rec, plan,
        )
    return _run_device_guarded(
        step, params, args, tol, max_em_iter, checkpoint_path,
        checkpoint_every, stop_at, trace_name, rec, plan,
        fallback_step, fallback_unwrap, fallback_args,
    )


def _run_host_loop(
    step, params, args, tol, max_em_iter, stop_at, trace_name, rec, guard_on
):
    """collect_path escape hatch: host-synced loop with per-iteration wall
    clock.  Carries a lightweight sentinel (non-finite / monotonicity stop
    preserving the last-good params) but NOT the recovery ladder — this
    path exists for interactive diagnosis, where a preserved trip state is
    worth more than an automatic retry."""
    host_cap = max_em_iter if stop_at is None else min(max_em_iter, int(stop_at))
    dtol = _guards.drop_tol()
    trace = ConvergenceTrace(trace_name)
    llpath = []
    ll_prev = -np.inf
    it = 0
    hit_tol = False
    health = _guards.HEALTH_OK
    prev_params = params
    with rec, span(trace_name):
        for it in range(1, host_cap + 1):
            new_params, ll = step(params, *args)
            ll = float(ll)
            if guard_on and not np.isfinite(ll):
                health = _guards.HEALTH_NONFINITE
            elif guard_on and it > 1 and (
                ll_prev - ll > dtol * (1.0 + abs(ll_prev))
            ):
                health = _guards.HEALTH_DECREASE
            if health != _guards.HEALTH_OK:
                # `ll` certifies this call's INPUT params as bad: discard
                # them (same two-state rollback as the device loop) and
                # report the last iterate whose loglik was observed good
                params = prev_params
                it -= 1
                inc("em_guard.faults_detected")
                break
            prev_params = params
            params = new_params
            llpath.append(ll)
            trace.record(ll)
            if it > 1 and abs(ll - ll_prev) < tol * (1.0 + abs(ll_prev)):
                hit_tol = True
                break
            ll_prev = ll
        rec.set(
            n_iter=it,
            converged=hit_tol,
            final_loglik=llpath[-1] if llpath else None,
            final_health=_guards.HEALTH_NAMES[health],
            faults_detected=int(health != _guards.HEALTH_OK),
        )
    return EMLoopResult(
        params, np.asarray(llpath), it, trace,
        converged=hit_tol, health=health,
        faults_detected=int(health != _guards.HEALTH_OK),
    )


def _run_device_unguarded(
    step, params, args, tol, max_em_iter, checkpoint_path, checkpoint_every,
    stop_at, trace_name, rec, plan,
):
    """The PR-1 on-device loop, program-for-program: when guards are off
    the dispatched executable (kernel "em_loop", identical statics) and
    its HLO are byte-identical to the pre-guardrail code path."""
    from ..utils.compile import aot_call, aot_statics, donation_enabled

    with rec:
        tol_arr = jnp.asarray(tol, jnp.result_type(float))
        donate = donation_enabled()
        heartbeat = heartbeat_every()
        fp_params = params
        if donate:
            # the donated program may reuse every carry buffer, including the
            # caller-visible init params — hand the carry a copy so the
            # caller's arrays stay valid (run_bulk_then_exact re-reads the
            # init when the bulk phase goes non-finite)
            params = jax.tree.map(jnp.copy, params)
        carry = _fresh_carry(params, tol_arr, max_em_iter)
        del params  # donated with the carry; only the carry's copy is live
        loop = _em_while_jit(donate)
        # the heartbeat interval changes the compiled program, so it is part
        # of the dispatch key (utils.compile._kernel_plan mirrors the 0)
        statics = aot_statics(step, max_em_iter, donate, heartbeat)

        def _run(carry, bound):
            # dispatches to a utils.compile.precompile'd executable when one
            # matches (kernel "em_loop"); otherwise the live jit, whose
            # compile hits the persistent cache for a known program
            return aot_call(
                "em_loop",
                lambda c, a, t, s: loop(
                    step, c, a, t, max_em_iter, s, heartbeat
                ),
                carry, args, tol_arr, jnp.asarray(bound, jnp.int32),
                statics=statics,
            )

        if checkpoint_path is None:
            bound = max_em_iter if stop_at is None else stop_at
            with span(trace_name):
                carry = _run(carry, bound)
        else:
            ckpt = _CheckpointDriver(
                checkpoint_path, carry,
                _fingerprint(args, tol, max_em_iter, params=fp_params),
                rec, plan,
            )
            carry = ckpt.resume(carry)
            with span(trace_name):
                while True:
                    it = int(carry[3])
                    if it >= max_em_iter:
                        break
                    # reassign unconditionally: under donation the input
                    # carry's buffers are dead after the call (the output is
                    # value-identical when cond is false on entry, so keeping
                    # it preserves the old semantics)
                    carry = _run(carry, min(it + checkpoint_every, max_em_iter))
                    if int(carry[3]) == it:  # converged (cond false on entry)
                        break
                    ckpt.save(carry)

        params, ll_prev, ll, n_iter, path = carry
        n_iter = int(n_iter)
        converged = n_iter >= 2 and _tol_break(ll_prev, ll, tol)
        llpath = np.asarray(path)[:n_iter]
        rec.set(
            n_iter=n_iter,
            converged=converged,
            final_loglik=float(llpath[-1]) if n_iter else None,
            donate=donate,
            heartbeat_every=heartbeat,
        )
    return EMLoopResult(params, llpath, n_iter, None, converged=converged)


def _promote_args_f64(args):
    return jax.tree.map(
        lambda x: (
            jnp.asarray(x, jnp.float64)
            if jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating)
            and jnp.asarray(x).dtype != jnp.float64
            else x
        ),
        args,
    )


def _has_f32_leaf(tree) -> bool:
    return any(
        jnp.asarray(x).dtype == jnp.float32 for x in jax.tree.leaves(tree)
    )


def _signed_inj(k, persistent: bool) -> int:
    """Injection static for the guarded program: 0 = none, +k transient
    (fires only while the carry's trip counter is zero — the in-trace
    equivalent of "only the first attempt's program is poisoned"), -k
    persistent (`kind@k+`: fires on every in-trace attempt until the
    host demotes/promotes to a different program)."""
    if not k:
        return 0
    return -int(k) if persistent else int(k)


def _run_device_guarded(
    step, params, args, tol, max_em_iter, checkpoint_path, checkpoint_every,
    stop_at, trace_name, rec, plan,
    fallback_step, fallback_unwrap, fallback_args,
):
    from ..utils.compile import aot_call, aot_statics, donation_enabled

    with rec:
        ld = jnp.result_type(float)
        tol_arr = jnp.asarray(tol, ld)
        drop_arr = jnp.asarray(_guards.drop_tol(), ld)
        donate = donation_enabled()
        heartbeat = heartbeat_every()
        fp_params = params
        if donate:
            params = jax.tree.map(jnp.copy, params)
        carry = _fresh_guarded_carry(params, tol_arr, max_em_iter)
        del params
        gloop = _em_while_guarded_jit(donate)
        # in-loop injections are STATICS: with no fault plan the compiled
        # guarded program contains no injection code, and its dispatch key
        # (kernel "em_loop_guarded") matches the utils.compile plan
        inj = (
            _signed_inj(plan.nan_estep, "nan_estep" in plan.persistent),
            _signed_inj(plan.chol_fail, "chol_fail" in plan.persistent),
        )
        cur_step, cur_args = step, args

        def _run(carry, bound, cur_step, cur_args, inj):
            statics = aot_statics(
                cur_step, max_em_iter, donate, heartbeat, inj[0], inj[1]
            )
            return aot_call(
                "em_loop_guarded",
                lambda c, a, t, d, s: gloop(
                    cur_step, c, a, t, d, max_em_iter, s, heartbeat,
                    inj[0], inj[1],
                ),
                carry, cur_args, tol_arr, drop_arr,
                jnp.asarray(bound, jnp.int32),
                statics=statics,
            )

        ckpt = None
        if checkpoint_path is not None:
            ckpt = _CheckpointDriver(
                checkpoint_path, carry,
                _fingerprint(args, tol, max_em_iter, params=fp_params),
                rec, plan,
            )
            carry = ckpt.resume(carry)

        def _drive(carry, cur_step, cur_args, inj):
            """Run to completion / trip, in checkpoint chunks when asked;
            a tripped chunk is NOT saved (the ladder resumes in-process
            and later healthy chunks persist)."""
            if ckpt is None:
                bound = max_em_iter if stop_at is None else stop_at
                return _run(carry, bound, cur_step, cur_args, inj)
            while True:
                it = int(carry[4])
                if it >= max_em_iter:
                    return carry
                carry = _run(
                    carry, min(it + checkpoint_every, max_em_iter),
                    cur_step, cur_args, inj,
                )
                if int(carry[6]) != _guards.HEALTH_OK:
                    return carry
                if int(carry[4]) == it:  # converged (cond false on entry)
                    return carry
                ckpt.save(carry)

        faults_detected = 0
        rungs_used = []
        traced_recorded = 0
        trips_seen = 0
        final_health = _guards.HEALTH_OK
        rung_skips = []
        with span(trace_name):
            while True:
                # in-loop faults are compiled statics, so the host counts
                # each attempt that dispatches a poisoned program
                if inj[0]:
                    _faults.fault_fired("nan_estep")
                if inj[1]:
                    _faults.fault_fired("chol_fail")
                carry = _drive(carry, cur_step, cur_args, inj)
                health = int(carry[6])
                # reconcile the device-resident bookkeeping: sentinel
                # trips and in-trace jitter rungs accumulated since the
                # last dispatch (a healthy or jitter-recovered run makes
                # exactly ONE dispatch — this readback happens after the
                # loop exits, never per iteration)
                trips = int(carry[8])
                if trips > trips_seen:
                    new_trips = trips - trips_seen
                    faults_detected += new_trips
                    inc("em_guard.faults_detected", new_trips)
                    trips_seen = trips
                    # flight recorder: every sentinel trip is a
                    # pre-mortem moment, even one the traced jitter
                    # rungs recover in-loop — ring event + one
                    # (throttled) bundle dump with the preceding
                    # injections and the kernel-ledger snapshot
                    _flight.record(
                        "em_guard.trip",
                        health=_guards.HEALTH_NAMES[health],
                        iter=int(carry[4]), trips=trips_seen,
                        rungs_used=list(rungs_used),
                    )
                    _flight.dump(
                        "guard_trip",
                        health=_guards.HEALTH_NAMES[health],
                    )
                n_traced = min(int(carry[7]), _guards.N_TRACED_RUNGS)
                for i in range(traced_recorded, n_traced):
                    rungs_used.append(_guards.LADDER_RUNGS[i])
                    inc("em_guard.rung." + _guards.LADDER_RUNGS[i])
                traced_recorded = n_traced
                if health == _guards.HEALTH_OK:
                    final_health = health
                    break
                inc("em_guard.trip." + _guards.HEALTH_NAMES[health])
                # the device loop froze only after spending the traced
                # rungs; pick the next applicable host rung (each tried
                # exactly once)
                next_i = (
                    _guards.LADDER_RUNGS.index(rungs_used[-1]) + 1
                    if rungs_used else 0
                )
                rung = None
                while next_i < len(_guards.LADDER_RUNGS):
                    name = _guards.LADDER_RUNGS[next_i]
                    if name == "demote" and fallback_step is None:
                        rung_skips.append("demote:no_fallback")
                    elif name == "promote_f64" and not jax.config.jax_enable_x64:
                        rung_skips.append("promote_f64:x64_disabled")
                    elif name == "promote_f64" and not _has_f32_leaf(carry[0]):
                        rung_skips.append("promote_f64:already_f64")
                    else:
                        rung = name
                        break
                    next_i += 1
                if rung is None:
                    final_health = health  # ladder exhausted: return last-good
                    inc("em_guard.exhausted")
                    _flight.record(
                        "em_guard.exhausted",
                        health=_guards.HEALTH_NAMES[health],
                        rungs_used=list(rungs_used),
                        rung_skips=list(rung_skips),
                    )
                    _flight.dump(
                        "ladder_exhausted",
                        health=_guards.HEALTH_NAMES[health],
                    )
                    break
                # the device loop already rolled back: carry[0] is last-good
                last_good, it = carry[0], int(carry[4])
                if rung == "demote":
                    new_params = (
                        fallback_unwrap(last_good)
                        if fallback_unwrap is not None else last_good
                    )
                    cur_step = fallback_step
                    cur_args = args if fallback_args is None else fallback_args
                elif rung == "promote_f64":
                    new_params = _guards.promote_f64(last_good)
                    cur_args = _promote_args_f64(cur_args)
                else:  # jitter rungs are device-resident; unreachable here
                    new_params = _guards.ridge_jitter(
                        last_good, _guards.LADDER_RUNGS.index(rung)
                    )
                # a transient injected fault fires only while the trip
                # counter is zero (baked into the program); a persistent
                # one (`kind@k+`) re-fires on every attempt until demote/
                # promote changes the step or dtype — then it no longer
                # applies by construction
                if rung in ("demote", "promote_f64"):
                    inj = (0, 0)
                rungs_used.append(rung)
                inc("em_guard.rung." + rung)
                _flight.record(
                    "em_guard.rung", severity="info", rung=rung,
                )
                carry = (
                    new_params,
                    jax.tree.map(jnp.copy, new_params),
                    carry[2], carry[3], carry[4], carry[5],
                    jnp.asarray(0, jnp.int32),  # health
                    # traced rungs stay spent after a host rung: the
                    # in-trace ladder never re-tries jitter
                    jnp.asarray(_guards.N_TRACED_RUNGS, jnp.int32),
                    carry[8],  # cumulative trips
                    jnp.asarray(it, jnp.int32),  # resume_from
                )

        params, _, ll_prev, ll, n_iter, path = carry[:6]
        n_iter = int(n_iter)
        resume_from = int(carry[9])
        converged = (
            final_health == _guards.HEALTH_OK
            and n_iter >= max(2, resume_from + 2)
            and _tol_break(ll_prev, ll, tol)
        )
        recoveries = faults_detected - int(final_health != _guards.HEALTH_OK)
        if recoveries:
            inc("em_guard.recoveries", recoveries)
        llpath = np.asarray(path)[:n_iter]
        rec.set(
            n_iter=n_iter,
            converged=converged,
            final_loglik=float(llpath[-1]) if n_iter else None,
            donate=donate,
            heartbeat_every=heartbeat,
            faults_detected=faults_detected,
            recoveries=recoveries,
            ladder_rung=(
                _guards.LADDER_RUNGS.index(rungs_used[-1]) + 1
                if rungs_used else 0
            ),
            final_health=_guards.HEALTH_NAMES[final_health],
            rungs_used=list(rungs_used),
            rung_skips=rung_skips or None,
        )
    return EMLoopResult(
        params, llpath, n_iter, None,
        converged=converged,
        health=final_health,
        faults_detected=faults_detected,
        recoveries=recoveries,
        ladder_rung=(
            _guards.LADDER_RUNGS.index(rungs_used[-1]) + 1 if rungs_used else 0
        ),
        rungs_used=rungs_used,
    )


def run_em_stack(
    stack,
    params,
    args: tuple,
    tol: float,
    max_em_iter: int,
    **kwargs,
):
    """Run the convergence loop for a transform `Stack` (or an already
    `Resolved` stack) from BARE parameters: resolve the step, wrap the
    params into the carry the step iterates (SteadyEMState /
    ARSteadyState for steady stacks), dispatch the matching loop driver
    — `run_em_loop_batched` for `batch(B)` stacks, `run_em_loop` with
    the resolved guard-ladder fallback otherwise — and unwrap the carry
    in the returned params.

    The estimation entry points (ssm / ssm_ar) keep calling `run_em_loop`
    directly because they thread plan-derived warm starts and telemetry
    through the wrap; this driver is the one-call form for callers with
    no such state (serving/batch.py, tests, benches).  `kwargs` pass
    through to the underlying driver.
    """
    from . import transforms as tfm

    res = stack if isinstance(stack, tfm.Resolved) else tfm.resolve(stack)
    if res.batch:
        out = run_em_loop_batched(
            res.step, params, args, tol, max_em_iter, **kwargs
        )
        return out
    carry = tfm.wrap_params(res, params)
    if res.fallback_step is not None:
        kwargs.setdefault("fallback_step", res.fallback_step)
        if res.carry != "bare":
            from .emaccel import unwrap_state

            kwargs.setdefault("fallback_unwrap", unwrap_state)
    if res.guard is not None:
        kwargs.setdefault("guard", res.guard)
    out = run_em_loop(res.step, carry, args, tol, max_em_iter, **kwargs)
    final = out[0]
    # unwrap by TYPE, not by the requested stack: the recovery ladder's
    # demote rung may already have peeled the carry
    if res.carry != "bare" and hasattr(final, "params"):
        out = EMLoopResult(
            final.params, out[1], out[2], out[3],
            converged=out.converged,
            health=out.health,
            faults_detected=out.faults_detected,
            recoveries=out.recoveries,
            ladder_rung=out.ladder_rung,
            rungs_used=out.rungs_used,
        )
    return out


def run_bulk_then_exact(
    bulk_step,
    exact_step,
    params,
    bulk_args: tuple,
    exact_args: tuple,
    tol: float,
    max_em_iter: int,
    trace_name: str,
    collect_path: bool = False,
    fallback_step=None,
    fallback_unwrap=None,
    fallback_args=None,
):
    """Mixed-precision two-phase EM driver (the single copy of the
    gram_dtype orchestration shared by `ssm.estimate_dfm_em` and
    `mixed_freq.estimate_mixed_freq_dfm`).

    Phase 1 runs `bulk_step` on `bulk_args` (the bf16-twin stats) under a
    loosened tolerance, capped at HALF the budget — the bulk map is only
    productive in moderate signal-to-noise regimes, so the exact phase
    must always keep at least half.  A bulk phase ending in non-finite
    PARAMS (the loglik path records the loglik of each iteration's INPUT,
    so it cannot certify the final output) falls back to the original
    init with the full budget.  Phase 2 runs `exact_step` on `exact_args`
    under the caller's tol for the remaining budget (always >= 1
    iteration).  Returns an EMLoopResult over (params, concatenated
    loglik path, total n_iter, trace); convergence and guardrail health
    are the EXACT phase's (the bulk phase optimizes a different
    objective, so its outcome cannot certify the run), fault counters
    are summed across both phases.

    The concatenated loglik path can DROP at the phase boundary (index
    `n_pre`): the bulk entries are logliks of the bf16-Gram (R-floored)
    map, the exact entries of the exact map — two different objectives.
    A one-step decrease at the seam is the precision gap being repaid,
    not EM divergence; the guarded loop never sees it (each phase is its
    own run_em_loop call with its own monotonicity baseline).

    Build `bulk_args` inline in the call expression (don't bind the bf16
    twins in the caller): this function drops its reference before phase 2,
    so the twin arrays are freed for the exact phase's working set.

    A budget of one iteration skips the bulk phase entirely — half of one
    is zero useful bulk work, and the caller's cap is a hard bound.

    Step transformers compose transparently: when BOTH steps are wrapped
    the same way (e.g. `squarem(bulk)` and `squarem(exact)`), the
    augmented loop state flows from the bulk phase into the exact phase
    unchanged — the caller wraps the initial params once and unwraps the
    result once.  `fallback_*` pass through to the exact phase's recovery
    ladder (the bulk phase's demote target would be the exact map, which
    phase 2 already is).
    """
    if max_em_iter < 2:
        return run_em_loop(
            exact_step, params, exact_args, tol, max_em_iter,
            collect_path=collect_path, trace_name=trace_name,
            fallback_step=fallback_step, fallback_unwrap=fallback_unwrap,
            fallback_args=fallback_args,
        )
    pre = run_em_loop(
        bulk_step, params, bulk_args, max(tol, 1e-4), max_em_iter,
        trace_name=trace_name + "_bf16", stop_at=max(max_em_iter // 2, 1),
    )
    params_b, llpath_pre, n_pre, _ = pre
    del bulk_args  # the bf16 twins: freed before the exact phase runs
    params_ok = all(
        bool(np.isfinite(np.asarray(leaf)).all())
        for leaf in jax.tree.leaves(params_b)
    )
    if n_pre > 0 and params_ok:
        params = params_b
    else:
        n_pre = 0
        llpath_pre = np.empty(0)
    del params_b
    res = run_em_loop(
        exact_step, params, exact_args, tol, max_em_iter,
        collect_path=collect_path, trace_name=trace_name,
        stop_at=max(max_em_iter - n_pre, 1) if n_pre else None,
        fallback_step=fallback_step, fallback_unwrap=fallback_unwrap,
        fallback_args=fallback_args,
    )
    params, llpath, n_iter, trace = res
    return EMLoopResult(
        params, np.concatenate([llpath_pre, llpath]), n_iter + n_pre, trace,
        converged=res.converged,
        health=res.health,
        faults_detected=res.faults_detected + pre.faults_detected,
        recoveries=res.recoveries + pre.recoveries,
        ladder_rung=res.ladder_rung,
        rungs_used=res.rungs_used,
    )
