"""Shared EM convergence driver: the loop itself lives on device.

The three EM estimators (`ssm.estimate_dfm_em`, `ssm_ar.estimate_dfm_em_ar`,
`mixed_freq.estimate_mixed_freq_dfm`) used to run their convergence loop on
the host, calling ``float(ll)`` once per iteration — one device->host sync
per EM step.  Here the relative-log-likelihood tolerance test is carried
inside a single ``lax.while_loop`` (the TPU-first shape the ALS core already
uses), with the per-iteration log-likelihood path written into a
preallocated carry array so no observability is lost.

``collect_path=True`` is the escape hatch: a host-synced loop that
additionally records wall-clock per iteration in a
`utils.profiling.ConvergenceTrace` (iters/sec without hand-rolled timing).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.profiling import ConvergenceTrace, annotate

__all__ = ["run_em_loop"]


@partial(jax.jit, static_argnames=("step", "max_em_iter"))
def _em_while(step, params, args, tol, max_em_iter: int):
    """On-device EM loop.  Semantics match the host loop exactly: iterate
    `params, ll = step(params, *args)`; after iteration it >= 2, stop when
    |ll - ll_prev| < tol * (1 + |ll_prev|); always stop at max_em_iter."""
    dtype = jnp.result_type(tol)
    neg_inf = jnp.asarray(-jnp.inf, dtype)

    def cond(carry):
        _, ll_prev, ll, it, _ = carry
        unconverged = (it <= 1) | (
            jnp.abs(ll - ll_prev) >= tol * (1.0 + jnp.abs(ll_prev))
        )
        return unconverged & (it < max_em_iter)

    def body(carry):
        params, _, ll, it, path = carry
        new_params, ll_new = step(params, *args)
        path = path.at[it].set(ll_new.astype(dtype))
        return new_params, ll, ll_new.astype(dtype), it + 1, path

    init = (
        params,
        neg_inf,
        jnp.asarray(jnp.nan, dtype),
        jnp.asarray(0, jnp.int32),
        jnp.full(max_em_iter, jnp.nan, dtype),
    )
    params, _, _, n_iter, path = jax.lax.while_loop(cond, body, init)
    return params, n_iter, path


def run_em_loop(
    step,
    params,
    args: tuple,
    tol: float,
    max_em_iter: int,
    collect_path: bool = False,
    trace_name: str = "em",
):
    """Run an EM loop to convergence; returns (params, loglik_path, n_iter,
    trace).  `step(params, *args) -> (new_params, loglik-of-current-params)`
    must be a module-level jitted function (it is a static jit argument).

    trace is a ConvergenceTrace when collect_path=True, else None.
    """
    if collect_path:
        trace = ConvergenceTrace(trace_name)
        llpath = []
        ll_prev = -np.inf
        it = 0
        with annotate(trace_name):
            for it in range(1, max_em_iter + 1):
                params, ll = step(params, *args)
                ll = float(ll)
                llpath.append(ll)
                trace.record(ll)
                if it > 1 and abs(ll - ll_prev) < tol * (1.0 + abs(ll_prev)):
                    break
                ll_prev = ll
        return params, np.asarray(llpath), it, trace

    tol_arr = jnp.asarray(tol, jnp.result_type(float))
    with annotate(trace_name):
        params, n_iter, path = _em_while(step, params, args, tol_arr, max_em_iter)
        n_iter = int(n_iter)
    return params, np.asarray(path)[:n_iter], n_iter, None
