"""State-space DFM with AR(1) idiosyncratic components (full Banbura-Modugno).

`models/ssm.py` treats the idiosyncratic terms as iid measurement noise; the
full Banbura-Modugno (2014) specification the `Parametric` path calls for
(SURVEY.md section 0; reference never implemented it) models them as AR(1)
processes, which matters for ragged-edge nowcasting — a persistent
idiosyncratic deviation should carry into the missing tail:

    x_t = Lam f_t + e_t + nu_t,     nu_t ~ N(0, kappa I)  (kappa tiny)
    f_t = A_1 f_{t-1} + ... + A_p f_{t-p} + u_t,   u_t ~ N(0, Q)
    e_it = phi_i e_{i,t-1} + v_it,  v_it ~ N(0, sigv_i^2)

TPU design: the state s_t = [f_t .. f_{t-p+1}, e_t] (k = r*p + N) makes the
observation map H = [Lam 0 .. I] dense in the idio block, so the masked
update builds the full k x k information matrix H' diag(m/kappa) H — two
matmuls feeding Cholesky factorizations inside one `lax.scan`; everything in
an EM iteration is a single jitted function, as in ssm.py.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.linalg import solve_normal
from ..ops.masking import fillz, mask_of
from ..utils.backend import on_backend
from .dfm import DFMConfig
from .ssm import _info_filter_scan, _psd_floor, _rts_scan, estimate_dfm_em

__all__ = [
    "SSMARParams",
    "em_step_ar",
    "estimate_dfm_em_ar",
    "EMARResults",
    "nowcast_em_ar",
]

# Measurement-noise floor: the idio dynamics live in the state, so kappa is
# a numerical regularizer, not a model parameter.  1e-3 (std ~3% of a
# standardized series) is the empirically safe stiffness: at 1e-4 the
# information-form inverses lose enough precision that the EM log-likelihood
# drifts non-monotonically on the real panel.
_KAPPA = 1e-3


class SSMARParams(NamedTuple):
    """lam: (N, r); phi: (N,) idio AR(1); sigv2: (N,) idio innovation vars;
    A: (p, r, r) factor VAR blocks; Q: (r, r) factor innovation cov."""

    lam: jnp.ndarray
    phi: jnp.ndarray
    sigv2: jnp.ndarray
    A: jnp.ndarray
    Q: jnp.ndarray

    @property
    def r(self) -> int:
        return self.lam.shape[1]

    @property
    def p(self) -> int:
        return self.A.shape[0]

    @property
    def N(self) -> int:
        return self.lam.shape[0]


def _transition(params: SSMARParams):
    r, p, N = params.r, params.p, params.N
    k = r * p + N
    dtype = params.lam.dtype
    Tm = jnp.zeros((k, k), dtype)
    Tm = Tm.at[:r, : r * p].set(jnp.concatenate([params.A[i] for i in range(p)], 1))
    if p > 1:
        Tm = Tm.at[r : r * p, : r * (p - 1)].set(jnp.eye(r * (p - 1), dtype=dtype))
    Tm = Tm.at[r * p :, r * p :].set(jnp.diag(params.phi))
    Qs = jnp.zeros((k, k), dtype)
    Qs = Qs.at[:r, :r].set(params.Q)
    Qs = Qs.at[r * p :, r * p :].set(jnp.diag(params.sigv2))
    return Tm, Qs


def _obs_matrix(params: SSMARParams):
    """H (N, k): x_t = [Lam, 0, I] s_t + nu."""
    r, p, N = params.r, params.p, params.N
    H = jnp.zeros((N, r * p + N), params.lam.dtype)
    H = H.at[:, :r].set(params.lam)
    return H.at[:, r * p :].set(jnp.eye(N, dtype=params.lam.dtype))


@jax.jit
def _filter_ar(params: SSMARParams, x, mask):
    """Masked information-form filter with the structured observation map.

    Reuses ssm._info_filter_scan — only the obs_step differs.  The
    Jungbacker-Koopman collapse cannot shrink this model's per-step cost
    the way it does ssm.py's: the N idiosyncratic states live IN the state
    vector (k = r*p + N), so the O(k^3) information-matrix Cholesky is
    inherent.  What the H = [Lam, 0, I] block structure does buy is the
    information matrix and gain assembled in O(N r^2) —

        C = [[Lam'D Lam, 0, Lam'D], [0,0,0], [D Lam, 0, D]],  D = diag(m/kappa)

    — instead of the dense (k,N)@(N,k) product's O(N k^2) ~ O(N^3).
    """
    Tm, Qs = _transition(params)
    r, p, N = params.r, params.p, params.N
    rp = r * p
    dtype = x.dtype
    k = Tm.shape[0]
    s0 = jnp.zeros(k, dtype)
    P0 = 1e2 * jnp.eye(k, dtype=dtype)
    log_kappa = jnp.log(jnp.asarray(_KAPPA, dtype))
    idio = jnp.arange(rp, k)

    def obs_step(inp, sp):
        xt, mt = inp
        d = mt / _KAPPA  # (N,), 0 at missing
        v = xt - params.lam @ sp[:r] - sp[rp:]  # garbage at missing; weight 0
        dv = d * v
        dlam = d[:, None] * params.lam  # (N, r)
        C = jnp.zeros((k, k), dtype)
        C = C.at[:r, :r].set(params.lam.T @ dlam)
        C = C.at[:r, rp:].set(dlam.T)
        C = C.at[rp:, :r].set(dlam)
        C = C.at[idio, idio].set(d)
        rhs = jnp.zeros(k, dtype).at[:r].set(params.lam.T @ dv).at[rp:].set(dv)
        n_obs = mt.sum()
        return C, rhs, n_obs * log_kappa, (dv * v).sum(), n_obs

    means, covs, pmeans, pcovs, lls = _info_filter_scan(
        Tm, Qs, (x, mask.astype(dtype)), obs_step, s0, P0
    )
    return means, covs, pmeans, pcovs, lls.sum()


@jax.jit
def _smoother_ar(params: SSMARParams, means, covs, pmeans, pcovs):
    Tm, _ = _transition(params)
    return _rts_scan(Tm, means, covs, pmeans, pcovs)


@jax.jit
def em_step_ar(params: SSMARParams, x, mask):
    """One EM iteration; returns (new_params, loglik of current params)."""
    r, p, N = params.r, params.p, params.N
    rp = r * p
    m = mask.astype(x.dtype)

    params = params._replace(
        Q=_psd_floor(params.Q), sigv2=jnp.maximum(params.sigv2, 1e-8)
    )
    means, covs, pmeans, pcovs, ll = _filter_ar(params, x, mask)
    s_sm, P_sm, lag1 = _smoother_ar(params, means, covs, pmeans, pcovs)

    f = s_sm[:, :r]
    e = s_sm[:, rp:]
    Pff = P_sm[:, :r, :r]
    Pee_d = jnp.diagonal(P_sm[:, rp:, rp:], axis1=1, axis2=2)  # (T, N)
    Pef = P_sm[:, rp:, :r]  # (T, N, r)

    # --- loadings: x - e regressed on f, accounting for E[e f'] ---
    Eff = jnp.einsum("tr,ts->trs", f, f) + Pff
    Sff = jnp.einsum("ti,trs->irs", m, Eff)
    # Sxf_i = sum_t m (x_it E[f'] - E[e_i f'])
    Exef = jnp.einsum("ti,tr->tir", e, f) + Pef  # (T, N, r)
    Sxf = jnp.einsum("ti,tr->ir", m * x, f) - jnp.einsum("ti,tir->ir", m, Exef)
    lam = jax.vmap(solve_normal)(Sff, Sxf)

    # --- idio AR(1): phi_i and sigv_i from smoothed e moments ---
    Ee2 = e**2 + Pee_d  # (T, N) E[e_t^2]
    lag1_ee = jnp.diagonal(lag1[:, rp:, rp:], axis1=1, axis2=2)  # (T-1, N)
    Eee1 = e[1:] * e[:-1] + lag1_ee  # E[e_t e_{t-1}]
    num = Eee1.sum(axis=0)
    den = Ee2[:-1].sum(axis=0)
    phi = jnp.clip(num / jnp.maximum(den, 1e-12), -0.99, 0.99)
    Tn = x.shape[0]
    sigv2 = (
        Ee2[1:].sum(axis=0) - 2.0 * phi * num + phi**2 * den
    ) / (Tn - 1)
    sigv2 = jnp.maximum(sigv2, 1e-8)

    # --- factor VAR blocks + Q from the f-lag state moments ---
    S11 = jnp.einsum("tr,ts->rs", s_sm[1:, :r], s_sm[1:, :r]) + P_sm[1:, :r, :r].sum(0)
    S00 = (
        jnp.einsum("tk,tl->kl", s_sm[:-1, :rp], s_sm[:-1, :rp])
        + P_sm[:-1, :rp, :rp].sum(0)
    )
    S10 = (
        jnp.einsum("tr,tk->rk", s_sm[1:, :r], s_sm[:-1, :rp])
        + lag1[:, :r, :rp].sum(0)
    )
    Ak = S10 @ jnp.linalg.pinv(S00, hermitian=True)
    Q = _psd_floor((S11 - Ak @ S10.T) / (Tn - 1))
    A = jnp.stack([Ak[:, i * r : (i + 1) * r] for i in range(p)])
    return SSMARParams(lam, phi, sigv2, A, Q), ll


class EMARResults(NamedTuple):
    params: SSMARParams
    factors: jnp.ndarray  # (T, r) smoothed factors
    idio: jnp.ndarray  # (T, N) smoothed idiosyncratic components
    loglik_path: np.ndarray
    n_iter: int
    stds: jnp.ndarray
    means: jnp.ndarray
    trace: object | None = None  # ConvergenceTrace when collect_path=True
    converged: bool = False  # actual tolerance break (not n_iter < cap)
    health: int = 0  # final utils.guards health code (0 = healthy)


def _project_params_ar(params: SSMARParams) -> SSMARParams:
    """Feasibility projection after SQUAREM extrapolation: idiosyncratic
    AR roots clipped inside the unit circle, variances floored, Q
    symmetrized/eigenvalue-floored (em_step_ar re-projects Q/sigv2 at
    entry; the phi clip is the addition extrapolation makes necessary)."""
    return params._replace(
        phi=jnp.clip(params.phi, -0.99, 0.99),
        sigv2=jnp.maximum(params.sigv2, jnp.asarray(1e-8, params.sigv2.dtype)),
        Q=_psd_floor(params.Q),
    )


def estimate_dfm_em_ar(
    data,
    inclcode,
    initperiod: int,
    lastperiod: int,
    config: DFMConfig = DFMConfig(nfac_u=4),
    max_em_iter: int = 100,
    tol: float = 1e-6,
    backend: str | None = None,
    collect_path: bool = False,
    checkpoint_path: str | None = None,
    checkpoint_every: int = 25,
    accel: str | None = None,
) -> EMARResults:
    """Full Banbura-Modugno EM: factors + AR(1) idiosyncratic states.

    Initialized from the iid-noise EM fit (`ssm.estimate_dfm_em`), whose R
    becomes the initial sigv2 with phi = 0.

    accel="squarem" wraps the EM step in one SQUAREM extrapolation cycle
    per loop iteration (`emaccel.squarem`; n_iter then counts cycles of
    three EM-map evaluations each).
    """
    from ..utils.compile import configure_compilation_cache

    configure_compilation_cache()
    if accel not in (None, "squarem"):
        raise ValueError(f"accel must be None or 'squarem', got {accel!r}")
    from ..utils.telemetry import run_record

    with on_backend(backend), run_record(
        "estimate_dfm_em_ar",
        config={
            "accel": accel, "tol": tol, "max_em_iter": max_em_iter,
            "checkpointed": checkpoint_path is not None,
        },
    ) as rec:
        data = jnp.asarray(data)
        inclcode = np.asarray(inclcode)
        em0 = estimate_dfm_em(
            data, inclcode, initperiod, lastperiod, config,
            max_em_iter=25, tol=tol,
        )
        # standardize with the init fit's own means/stds (one convention)
        xw = data[:, inclcode == 1][initperiod : lastperiod + 1]
        xz_nan = (xw - em0.means[None, :]) / em0.stds[None, :]
        m_arr = mask_of(xz_nan)
        xz = fillz(xz_nan)
        stds, n_mean = em0.stds, em0.means
        params = SSMARParams(
            lam=em0.params.lam,
            phi=jnp.zeros(em0.params.lam.shape[0], xz.dtype),
            sigv2=em0.params.R,
            A=em0.params.A,
            Q=em0.params.Q,
        )

        from .emloop import run_em_loop

        rec.set(shapes={
            "T": int(xz.shape[0]), "N": int(xz.shape[1]),
            "r": config.nfac_u, "p": config.n_factorlag,
        })
        step = em_step_ar
        fallback_step = None
        fallback_unwrap = None
        if accel == "squarem":
            from .emaccel import squarem, squarem_state, unwrap_state

            step = squarem(em_step_ar, _project_params_ar)
            params = squarem_state(params)
            # recovery-ladder demotion: drop the SQUAREM cycle back to the
            # plain AR EM map on the same args
            fallback_step = em_step_ar
            fallback_unwrap = unwrap_state
        res = run_em_loop(
            step, params, (xz, m_arr), tol, max_em_iter,
            collect_path=collect_path, trace_name="em_dfm_ar",
            checkpoint_path=checkpoint_path, checkpoint_every=checkpoint_every,
            fallback_step=fallback_step, fallback_unwrap=fallback_unwrap,
        )
        params, llpath, it, trace = res
        from .emaccel import SquaremState

        if isinstance(params, SquaremState):  # by type: demote may have peeled
            params = params.params
        rec.set(
            n_iter=it,
            converged=res.converged,
            final_loglik=float(llpath[-1]) if len(llpath) else None,
        )
        if res.faults_detected:
            from ..utils.guards import HEALTH_NAMES

            rec.set(
                faults_detected=res.faults_detected,
                recoveries=res.recoveries,
                ladder_rung=res.ladder_rung,
                final_health=HEALTH_NAMES[res.health],
            )

        means, covs, pmeans, pcovs, _ = _filter_ar(params, xz, m_arr)
        s_sm, _, _ = _smoother_ar(params, means, covs, pmeans, pcovs)
        r, rp = config.nfac_u, config.nfac_u * config.n_factorlag
        return EMARResults(
            params=params,
            factors=s_sm[:, :r],
            idio=s_sm[:, rp:],
            loglik_path=llpath,
            n_iter=it,
            stds=stds,
            means=n_mean,
            trace=trace,
            converged=res.converged,
            health=res.health,
        )


def nowcast_em_ar(
    em: EMARResults,
    data,
    inclcode,
    initperiod: int,
    lastperiod: int,
    h: int = 0,
    backend: str | None = None,
):
    """Ragged-edge nowcast in ORIGINAL units from the BM-AR fit.

    Unlike the iid-noise model (forecast.nowcast_em), the filtered AR(1)
    idiosyncratic state carries each series' persistent deviation into its
    unreleased periods: x_hat = Lam f + e with e evolved by phi.  Returns a
    forecast.Nowcast (x_hat (T+h, N_incl), factor, filled).
    """
    from .forecast import _check_included_columns, _predict_and_fill

    with on_backend(backend):
        data = jnp.asarray(data)
        inclcode = np.asarray(inclcode)
        xw = data[initperiod : lastperiod + 1][:, inclcode == 1]
        _check_included_columns(xw, em.params.N)
        xz = (xw - em.means[None, :]) / em.stds[None, :]
        m = mask_of(xz)
        # same guard the public kalman_filter applies: a checkpoint-round-
        # tripped or hand-built params with singular Q/sigv2 must degrade
        # gracefully, not NaN the whole nowcast
        params = em.params._replace(
            Q=_psd_floor(em.params.Q), sigv2=jnp.maximum(em.params.sigv2, 1e-8)
        )
        means, _, _, _, _ = _filter_ar(params, fillz(xz), m)
        Tm, _ = _transition(params)
        return _predict_and_fill(
            xw, m, means, _obs_matrix(params), Tm, params.r, h,
            em.stds[None, :], em.means[None, :],
        )
