"""State-space DFM with AR(1) idiosyncratic components (full Banbura-Modugno).

`models/ssm.py` treats the idiosyncratic terms as iid measurement noise; the
full Banbura-Modugno (2014) specification the `Parametric` path calls for
(SURVEY.md section 0; reference never implemented it) models them as AR(1)
processes, which matters for ragged-edge nowcasting — a persistent
idiosyncratic deviation should carry into the missing tail:

    x_t = Lam f_t + e_t + nu_t,     nu_t ~ N(0, kappa I)  (kappa tiny)
    f_t = A_1 f_{t-1} + ... + A_p f_{t-p} + u_t,   u_t ~ N(0, Q)
    e_it = phi_i e_{i,t-1} + v_it,  v_it ~ N(0, sigv_i^2)

TPU design: the state s_t = [f_t .. f_{t-p+1}, e_t] (k = r*p + N) makes the
observation map H = [Lam 0 .. I] dense in the idio block, so the masked
update builds the full k x k information matrix H' diag(m/kappa) H — two
matmuls feeding Cholesky factorizations inside one `lax.scan`; everything in
an EM iteration is a single jitted function, as in ssm.py.
"""

from __future__ import annotations

import os
import warnings
from typing import NamedTuple

import jax
import jax.numpy as jnp
import jax.scipy.linalg as jsl
import numpy as np

from ..ops.linalg import solve_normal
from ..ops.masking import fillz, mask_of
from ..utils.backend import on_backend
from .dfm import DFMConfig
from .ssm import (
    _info_filter_scan,
    _psd_floor,
    _rts_scan,
    _sym_pack_idx,
    _var_moments,
    estimate_dfm_em,
)

__all__ = [
    "SSMARParams",
    "QDStats",
    "compute_qd_stats",
    "qd_mask_supported",
    "em_step_ar",
    "em_step_ar_qd",
    "em_step_ar_dense0",
    "check_dense_ar_budget",
    "estimate_dfm_em_ar",
    "EMARResults",
    "nowcast_em_ar",
]

# Measurement-noise floor: the idio dynamics live in the state, so kappa is
# a numerical regularizer, not a model parameter.  1e-3 (std ~3% of a
# standardized series) is the empirically safe stiffness: at 1e-4 the
# information-form inverses lose enough precision that the EM log-likelihood
# drifts non-monotonically on the real panel.
_KAPPA = 1e-3


class SSMARParams(NamedTuple):
    """lam: (N, r); phi: (N,) idio AR(1); sigv2: (N,) idio innovation vars;
    A: (p, r, r) factor VAR blocks; Q: (r, r) factor innovation cov."""

    lam: jnp.ndarray
    phi: jnp.ndarray
    sigv2: jnp.ndarray
    A: jnp.ndarray
    Q: jnp.ndarray

    @property
    def r(self) -> int:
        return self.lam.shape[1]

    @property
    def p(self) -> int:
        return self.A.shape[0]

    @property
    def N(self) -> int:
        return self.lam.shape[0]


def _transition(params: SSMARParams):
    r, p, N = params.r, params.p, params.N
    k = r * p + N
    dtype = params.lam.dtype
    Tm = jnp.zeros((k, k), dtype)
    Tm = Tm.at[:r, : r * p].set(jnp.concatenate([params.A[i] for i in range(p)], 1))
    if p > 1:
        Tm = Tm.at[r : r * p, : r * (p - 1)].set(jnp.eye(r * (p - 1), dtype=dtype))
    Tm = Tm.at[r * p :, r * p :].set(jnp.diag(params.phi))
    Qs = jnp.zeros((k, k), dtype)
    Qs = Qs.at[:r, :r].set(params.Q)
    Qs = Qs.at[r * p :, r * p :].set(jnp.diag(params.sigv2))
    return Tm, Qs


def _obs_matrix(params: SSMARParams):
    """H (N, k): x_t = [Lam, 0, I] s_t + nu."""
    r, p, N = params.r, params.p, params.N
    H = jnp.zeros((N, r * p + N), params.lam.dtype)
    H = H.at[:, :r].set(params.lam)
    return H.at[:, r * p :].set(jnp.eye(N, dtype=params.lam.dtype))


@jax.jit
def _filter_ar(params: SSMARParams, x, mask):
    """Masked information-form filter with the structured observation map.

    Reuses ssm._info_filter_scan — only the obs_step differs.  The
    Jungbacker-Koopman collapse cannot shrink this model's per-step cost
    the way it does ssm.py's: the N idiosyncratic states live IN the state
    vector (k = r*p + N), so the O(k^3) information-matrix Cholesky is
    inherent.  What the H = [Lam, 0, I] block structure does buy is the
    information matrix and gain assembled in O(N r^2) —

        C = [[Lam'D Lam, 0, Lam'D], [0,0,0], [D Lam, 0, D]],  D = diag(m/kappa)

    — instead of the dense (k,N)@(N,k) product's O(N k^2) ~ O(N^3).
    """
    Tm, Qs = _transition(params)
    r, p, N = params.r, params.p, params.N
    rp = r * p
    dtype = x.dtype
    k = Tm.shape[0]
    s0 = jnp.zeros(k, dtype)
    P0 = 1e2 * jnp.eye(k, dtype=dtype)
    log_kappa = jnp.log(jnp.asarray(_KAPPA, dtype))
    idio = jnp.arange(rp, k)

    def obs_step(inp, sp):
        xt, mt = inp
        d = mt / _KAPPA  # (N,), 0 at missing
        v = xt - params.lam @ sp[:r] - sp[rp:]  # garbage at missing; weight 0
        dv = d * v
        dlam = d[:, None] * params.lam  # (N, r)
        C = jnp.zeros((k, k), dtype)
        C = C.at[:r, :r].set(params.lam.T @ dlam)
        C = C.at[:r, rp:].set(dlam.T)
        C = C.at[rp:, :r].set(dlam)
        C = C.at[idio, idio].set(d)
        rhs = jnp.zeros(k, dtype).at[:r].set(params.lam.T @ dv).at[rp:].set(dv)
        n_obs = mt.sum()
        return C, rhs, n_obs * log_kappa, (dv * v).sum(), n_obs

    means, covs, pmeans, pcovs, lls = _info_filter_scan(
        Tm, Qs, (x, mask.astype(dtype)), obs_step, s0, P0
    )
    return means, covs, pmeans, pcovs, lls.sum()


@jax.jit
def _smoother_ar(params: SSMARParams, means, covs, pmeans, pcovs):
    Tm, _ = _transition(params)
    return _rts_scan(Tm, means, covs, pmeans, pcovs)


@jax.jit
def em_step_ar(params: SSMARParams, x, mask):
    """One EM iteration; returns (new_params, loglik of current params)."""
    r, p, N = params.r, params.p, params.N
    rp = r * p
    m = mask.astype(x.dtype)

    params = params._replace(
        Q=_psd_floor(params.Q), sigv2=jnp.maximum(params.sigv2, 1e-8)
    )
    means, covs, pmeans, pcovs, ll = _filter_ar(params, x, mask)
    s_sm, P_sm, lag1 = _smoother_ar(params, means, covs, pmeans, pcovs)

    f = s_sm[:, :r]
    e = s_sm[:, rp:]
    Pff = P_sm[:, :r, :r]
    Pee_d = jnp.diagonal(P_sm[:, rp:, rp:], axis1=1, axis2=2)  # (T, N)
    Pef = P_sm[:, rp:, :r]  # (T, N, r)

    # --- loadings: x - e regressed on f, accounting for E[e f'] ---
    Eff = jnp.einsum("tr,ts->trs", f, f) + Pff
    Sff = jnp.einsum("ti,trs->irs", m, Eff)
    # Sxf_i = sum_t m (x_it E[f'] - E[e_i f'])
    Exef = jnp.einsum("ti,tr->tir", e, f) + Pef  # (T, N, r)
    Sxf = jnp.einsum("ti,tr->ir", m * x, f) - jnp.einsum("ti,tir->ir", m, Exef)
    lam = jax.vmap(solve_normal)(Sff, Sxf)

    # --- idio AR(1): phi_i and sigv_i from smoothed e moments ---
    Ee2 = e**2 + Pee_d  # (T, N) E[e_t^2]
    lag1_ee = jnp.diagonal(lag1[:, rp:, rp:], axis1=1, axis2=2)  # (T-1, N)
    Eee1 = e[1:] * e[:-1] + lag1_ee  # E[e_t e_{t-1}]
    num = Eee1.sum(axis=0)
    den = Ee2[:-1].sum(axis=0)
    phi = jnp.clip(num / jnp.maximum(den, 1e-12), -0.99, 0.99)
    Tn = x.shape[0]
    sigv2 = (
        Ee2[1:].sum(axis=0) - 2.0 * phi * num + phi**2 * den
    ) / (Tn - 1)
    sigv2 = jnp.maximum(sigv2, 1e-8)

    # --- factor VAR blocks + Q from the f-lag state moments ---
    S11 = jnp.einsum("tr,ts->rs", s_sm[1:, :r], s_sm[1:, :r]) + P_sm[1:, :r, :r].sum(0)
    S00 = (
        jnp.einsum("tk,tl->kl", s_sm[:-1, :rp], s_sm[:-1, :rp])
        + P_sm[:-1, :rp, :rp].sum(0)
    )
    S10 = (
        jnp.einsum("tr,tk->rk", s_sm[1:, :r], s_sm[:-1, :rp])
        + lag1[:, :r, :rp].sum(0)
    )
    Ak = S10 @ jnp.linalg.pinv(S00, hermitian=True)
    Q = _psd_floor((S11 - Ak @ S10.T) / (Tn - 1))
    A = jnp.stack([Ak[:, i * r : (i + 1) * r] for i in range(p)])
    return SSMARParams(lam, phi, sigv2, A, Q), ll


# ===================== Large-N collapsed path (quasi-differencing) ==========
#
# The dense state s = [f-lags, e] caps this model near N ~ 300: the info
# filter's per-step Cholesky is O(k^3) and the E-step stores six (T, k, k)
# covariance stacks, k = r*p + N.  For the EXACT model (kappa = 0) the state
# does not need the idio block at all: quasi-differencing each series by its
# own AR root,
#
#     z_it = x_it - phi_i x_{i,t-1}          (previous period observed)
#     z_it = x_it                            (series' first observation)
#
# is a unit-Jacobian linear transform of the observed data whose measurement
# noise is INDEPENDENT across time — v_it = e_it - phi_i e_{i,t-1} ~
# N(0, sigv_i^2) at interior cells, e_it ~ N(0, sigv_i^2/(1-phi_i^2))
# (stationary) at each series' first cell — so the transformed model
#
#     z_it = lam_i' f_t - beta_it lam_i' f_{t-1} + v_it,  beta_it in {0, phi_i}
#
# is a time-varying-loading DFM over the FACTOR LAGS ONLY (state dim
# r * max(p, 2)), and the Jungbacker-Koopman collapse applies verbatim: the
# per-step information matrix over [f_t, f_{t-1}] is assembled from (T, N)
# panel GEMMs outside the scan and nothing N-shaped enters the scan body.
# Exact for the contiguous per-series observation class (ragged heads/tails,
# the nowcasting case); `qd_mask_supported` gates it, interior gaps fall
# back to the dense path (an interior gap would need e to re-enter through
# a phi^gap cross-covariance that the one-lag difference cannot express).
#
# kappa = 0 is the EXACT Banbura-Modugno model; the kappa = 1e-3 dense path
# above is the legacy regularized variant and is kept untouched.  Parity is
# pinned against `em_step_ar_dense0` — a dense covariance-form filter of the
# same kappa = 0 model sharing this module's M-step — at 1e-8.


class QDStats(NamedTuple):
    """Loop-invariant quasi-differencing statistics (the AR-model analogue
    of ssm.PanelStats), computed once per panel and threaded through the EM
    loop.  Both orientations of the indicator panels are stored because the
    E-step collapse contracts (T, N) @ (N, cols) while the M-step's
    series-side Grams contract (N, T) @ (T, cols), and XLA does not hoist a
    transpose of a loop constant out of ``lax.while_loop``."""

    m: jnp.ndarray  # (T, N) float mask
    first: jnp.ndarray  # (T, N) 1 at each series' first observed period
    interior: jnp.ndarray  # (T, N) 1 at observations whose previous period is observed
    x_prev: jnp.ndarray  # (T, N) panel shifted one period (zero row at t=0)
    mT: jnp.ndarray  # (N, T)
    firstT: jnp.ndarray  # (N, T)
    interiorT: jnp.ndarray  # (N, T)
    xT: jnp.ndarray  # (N, T) zero-filled panel, transposed
    x_prevT: jnp.ndarray  # (N, T)
    n_int: jnp.ndarray  # (N,) per-series interior-transition counts
    n_obs: jnp.ndarray  # (T,) per-period observation counts


def compute_qd_stats(x, mask) -> QDStats:
    """Materialize the quasi-differencing indicators for (x zero-filled,
    mask).  `first` marks cells observed with the previous period missing —
    for the supported contiguous mask class that is exactly each series'
    first observation."""
    m = mask.astype(x.dtype)
    m_prev = jnp.concatenate([jnp.zeros_like(m[:1]), m[:-1]], axis=0)
    x_prev = jnp.concatenate([jnp.zeros_like(x[:1]), x[:-1]], axis=0)
    first = m * (1.0 - m_prev)
    interior = m * m_prev
    return QDStats(
        m=m,
        first=first,
        interior=interior,
        x_prev=x_prev,
        mT=jnp.asarray(m.T),
        firstT=jnp.asarray(first.T),
        interiorT=jnp.asarray(interior.T),
        xT=jnp.asarray(x.T),
        x_prevT=jnp.asarray(x_prev.T),
        n_int=interior.sum(axis=0),
        n_obs=m.sum(axis=1),
    )


def qd_mask_supported(mask) -> bool:
    """Host-side gate for the collapsed path's mask class: every series'
    observations must form at most one contiguous run (ragged-edge heads
    and tails).  An interior gap makes the one-lag quasi-difference
    inexact — those panels fall back to the dense path."""
    m = np.asarray(mask, bool)
    starts = (np.diff(m.astype(np.int8), axis=0) == 1).sum(axis=0) + m[0]
    return bool((starts <= 1).all())


def qd_gap_report(mask):
    """The actionable half of the `qd_mask_supported` gate: which series
    are outside the collapsed path's exact mask class, and where.

    Returns (bad, first_gap): `bad` is the array of series indices whose
    observations form more than one contiguous run, `first_gap[j]` the
    time index of series `bad[j]`'s first interior missing cell (the
    first gap after its first observation run).  A caller seeing the
    dense-fallback warning can re-release or interpolate exactly these
    cells to re-enter the N-free path."""
    m = np.asarray(mask, bool)
    starts = (np.diff(m.astype(np.int8), axis=0) == 1).sum(axis=0) + m[0]
    bad = np.nonzero(starts > 1)[0]
    first_gap = []
    for i in bad:
        col = m[:, i]
        t0 = int(np.argmax(col))  # first observation
        first_gap.append(t0 + int(np.nonzero(~col[t0:])[0][0]))
    return bad, first_gap


def _qd_companion(params: SSMARParams):
    """Factor-lag companion at pt = max(p, 2) lags: the quasi-differenced
    observation loads [f_t, f_{t-1}], so even a p = 1 VAR carries one extra
    (dynamically inert) lag slot in the state."""
    r, p = params.r, params.p
    pt = max(p, 2)
    k = r * pt
    dtype = params.lam.dtype
    Tm = jnp.zeros((k, k), dtype)
    Tm = Tm.at[:r, : r * p].set(
        jnp.concatenate([params.A[i] for i in range(p)], 1)
    )
    Tm = Tm.at[r:, : r * (pt - 1)].set(jnp.eye(r * (pt - 1), dtype=dtype))
    Qs = jnp.zeros((k, k), dtype).at[:r, :r].set(params.Q)
    return Tm, Qs


def _qd_weight_panels(params: SSMARParams, qd: QDStats, transposed: bool):
    """The per-iteration quasi-differencing weights, in either panel
    orientation: Vinv = m_it / Var(v_it) (so (1-phi^2)/sigv^2 at first
    cells, 1/sigv^2 interior, 0 missing) and beta = phi at interior cells,
    0 elsewhere."""
    phi2 = params.phi * params.phi
    if transposed:
        Vinv = (qd.mT - qd.firstT * phi2[:, None]) / params.sigv2[:, None]
        beta = params.phi[:, None] * qd.interiorT
    else:
        Vinv = (qd.m - qd.first * phi2[None, :]) / params.sigv2[None, :]
        beta = params.phi[None, :] * qd.interior
    return Vinv, beta


def _collapse_obs_qd(params: SSMARParams, x, qd: QDStats):
    """Collapsed observation statistics of the quasi-differenced model:
    the per-step information matrix over [f_t, f_{t-1}],

        C[t] = Lam2_t' V_t^-1 Lam2_t,   Lam2_t row i = [lam_i, -beta_it lam_i]
        b[t] = Lam2_t' V_t^-1 z_t,      z_t = x_t - beta_t * x_{t-1}

    plus log|V_t| over observed rows, the data quadratic z'V^-1z, and the
    per-step counts — five (T, N)-panel GEMMs/GEMVs total, nothing inside
    any scan.  Each C block is a weighted sum of the same lam_i lam_i'
    outer products, so the three blocks ride one packed-symmetric loading
    matrix (`_sym_pack_idx`)."""
    r = params.r
    iu, iv, unpack = _sym_pack_idx(r)
    Vinv, beta = _qd_weight_panels(params, qd, transposed=False)
    z = x - beta * qd.x_prev
    u = Vinv * z
    w1 = -Vinv * beta
    pair = params.lam[:, iu] * params.lam[:, iv]  # (N, r(r+1)/2)
    C00 = (Vinv @ pair)[:, unpack].reshape(-1, r, r)
    C01 = (w1 @ pair)[:, unpack].reshape(-1, r, r)  # symmetric itself
    C11 = ((-w1 * beta) @ pair)[:, unpack].reshape(-1, r, r)
    C = jnp.concatenate(
        [
            jnp.concatenate([C00, C01], axis=2),
            jnp.concatenate([C01, C11], axis=2),
        ],
        axis=1,
    )
    b = jnp.concatenate([u @ params.lam, (w1 * z) @ params.lam], axis=1)
    ld_V = qd.m @ jnp.log(params.sigv2) - qd.first @ jnp.log1p(
        -params.phi * params.phi
    )
    xRx = (u * z).sum(axis=1)
    return C, b, ld_V, xRx, qd.n_obs


def _filter_ar_qd(params: SSMARParams, x, qd: QDStats, want_pinv=False):
    """Masked filter of the quasi-differenced model: state = factor lags
    only (k = r * max(p, 2)), scan body O(k^3) with no N-sized operand
    (pinned in tests/test_perf_regression.py).  Likelihood is the exact
    kappa = 0 model likelihood (unit-Jacobian transform)."""
    r = params.r
    Tm, Qs = _qd_companion(params)
    k = Tm.shape[0]
    dtype = x.dtype
    s0 = jnp.zeros(k, dtype)
    P0 = 1e2 * jnp.eye(k, dtype=dtype)
    C, b, ld_V, xRx, n_obs = _collapse_obs_qd(params, x, qd)
    q2 = 2 * r

    def obs_step(inp, sp):
        Ct, bt, ld, xr, no = inp
        f2 = sp[:q2]
        Cf = jnp.zeros((k, k), dtype).at[:q2, :q2].set(Ct)
        rhs = jnp.zeros(k, dtype).at[:q2].set(bt - Ct @ f2)
        quad0 = xr - 2.0 * (f2 @ bt) + f2 @ Ct @ f2
        return Cf, rhs, ld, quad0, no

    return _info_filter_scan(
        Tm, Qs, (C, b, ld_V, xRx, n_obs), obs_step, s0, P0,
        want_pinv=want_pinv,
    )


def _m_step_ar_qd(params: SSMARParams, x, qd: QDStats, s_sm, P_sm, lag1):
    """ECM M-step of the kappa = 0 model from FACTOR-LAG moments only —
    shared verbatim by the collapsed path and the dense parity oracle, so
    parameter parity reduces to E-step exactness.

    s_sm (T, r*pt), P_sm (T, r*pt, r*pt), lag1 (T-1, r*pt, r*pt) with
    pt = max(p, 2).

    With kappa = 0 the loadings cannot come from the iid-model regression
    (e_it = x_it - lam_i'f_t is deterministic given f at observed cells, so
    that update is a fixed point); the information lives in the idio
    TRANSITION likelihood of v_it = z_it - lam_i' xi_it,
    xi_it = f_t - beta_it f_{t-1}:

      * lam_i: per-series WLS against E[xi xi'] — three (N, T)-side Grams
        over the packed factor second moments, one batched r x r solve;
      * phi_i | lam: smoothed autocovariance ratio of e_i = x_i - lam_i'f
        over interior transitions (lam'P lam corrections via the packed
        pair trick);
      * sigv_i^2 | lam, phi: interior innovation variance.  The first-obs
        stationary term is excluded from the phi/sigv update (conditional-
        likelihood ECM choice; it still enters lam's weights) — identical
        choice on both paths, so parity is unaffected;
      * A, Q: `ssm._var_moments` on the leading r*p lag moments.
    """
    r, p = params.r, params.p
    rp = r * p
    iu, iv, unpack = _sym_pack_idx(r)
    f0 = s_sm[:, :r]
    f1 = s_sm[:, r : 2 * r]
    P00 = P_sm[:, :r, :r]
    P01 = P_sm[:, :r, r : 2 * r]
    P11 = P_sm[:, r : 2 * r, r : 2 * r]
    F00u = f0[:, iu] * f0[:, iv] + P00[:, iu, iv]  # (T, r(r+1)/2)
    F11u = f1[:, iu] * f1[:, iv] + P11[:, iu, iv]
    F01 = f0[:, :, None] * f1[:, None, :] + P01
    F01su = (F01 + jnp.swapaxes(F01, 1, 2))[:, iu, iv]

    VinvT, betaT = _qd_weight_panels(params, qd, transposed=True)
    w1T = -VinvT * betaT
    w2T = -w1T * betaT
    G = VinvT @ F00u + w1T @ F01su + w2T @ F11u  # (N, r(r+1)/2)
    Gram = G[:, unpack].reshape(-1, r, r)
    zT = qd.xT - betaT * qd.x_prevT
    uT = VinvT * zT
    rhs = uT @ f0 + (w1T * zT) @ f1  # (N, r)
    lam = jax.vmap(solve_normal)(Gram, rhs)

    # --- phi / sigv2 given the new loadings ---
    ehat = x - f0 @ lam.T  # E[e_t | data] at observed cells
    ehat_p = qd.x_prev - f1 @ lam.T
    dupe = jnp.where(iu == iv, 1.0, 2.0).astype(x.dtype)
    pair2 = (lam[:, iu] * lam[:, iv]) * dupe[None, :]  # (N, npack)
    q00 = P00[:, iu, iv] @ pair2.T  # (T, N) lam_i' P00 lam_i
    q11 = P11[:, iu, iv] @ pair2.T
    P01s = 0.5 * (P01 + jnp.swapaxes(P01, 1, 2))
    q01 = P01s[:, iu, iv] @ pair2.T
    num = jnp.einsum("tn,tn->n", qd.interior, ehat * ehat_p + q01)
    den = jnp.einsum("tn,tn->n", qd.interior, ehat_p * ehat_p + q11)
    S2 = jnp.einsum("tn,tn->n", qd.interior, ehat * ehat + q00)
    phi = jnp.clip(num / jnp.maximum(den, 1e-12), -0.99, 0.99)
    sigv2 = (S2 - 2.0 * phi * num + phi * phi * den) / jnp.maximum(
        qd.n_int, 1.0
    )
    sigv2 = jnp.maximum(sigv2, 1e-8)
    # series without interior transitions carry no phi/sigv information
    has = qd.n_int > 0
    phi = jnp.where(has, phi, params.phi)
    sigv2 = jnp.where(has, sigv2, params.sigv2)

    # --- factor VAR blocks + Q from the leading r*p lag moments ---
    Tn = x.shape[0]
    S11, S00, S10, Tn_eff = _var_moments(
        s_sm[:, :rp], P_sm[:, :rp, :rp], lag1[:, :rp, :rp], r, Tn
    )
    Ak = S10 @ jnp.linalg.pinv(S00, hermitian=True)
    Q = _psd_floor((S11 - Ak @ S10.T) / (Tn_eff - 1))
    A = jnp.stack([Ak[:, i * r : (i + 1) * r] for i in range(p)])
    return SSMARParams(lam, phi, sigv2, A, Q)


def _guard_params_qd(params: SSMARParams) -> SSMARParams:
    return params._replace(
        Q=_psd_floor(params.Q),
        sigv2=jnp.maximum(params.sigv2, 1e-8),
        phi=jnp.clip(params.phi, -0.99, 0.99),
    )


@jax.jit
def em_step_ar_qd(params: SSMARParams, x, qd: QDStats):
    """One collapsed-AR EM iteration (exact kappa = 0 model); returns
    (new_params, loglik of current params).  Per-iteration cost: a fixed
    set of (T, N) panel GEMMs plus an N-free O(T k^3) scan, k = r*max(p,2)."""
    params = _guard_params_qd(params)
    means, covs, pmeans, pcovs, lls, pinvs = _filter_ar_qd(
        params, x, qd, want_pinv=True
    )
    Tm, _ = _qd_companion(params)
    s_sm, P_sm, lag1 = _rts_scan(Tm, means, covs, pmeans, pcovs, pinvs=pinvs)
    return _m_step_ar_qd(params, x, qd, s_sm, P_sm, lag1), lls.sum()


def _idio_fill(phi, e_obs, m):
    """O(T N) recovery of the smoothed idio means at unobserved cells from
    the observed-cell values e_it = x_it - lam_i'E[f_t | data] (exact at
    kappa = 0): tail cells decay forward from the last observation
    (E[e_{t+j}] = phi^j e_last), head cells decay backward (stationary
    AR(1) time-reversibility).  Exact for the contiguous mask class."""

    def fill(carry, inp):
        e_t, m_t = inp
        c = jnp.where(m_t > 0, e_t, phi * carry)
        return c, c

    zeros = jnp.zeros((e_obs.shape[1],), e_obs.dtype)
    _, fwd = jax.lax.scan(fill, zeros, (e_obs, m))
    _, bwd = jax.lax.scan(fill, zeros, (e_obs, m), reverse=True)
    seen = jnp.cumsum(m, axis=0) > 0  # an observation at or before t
    return jnp.where(m > 0, e_obs, jnp.where(seen, fwd, bwd))


def idio_moments_qd(params: SSMARParams, x, qd: QDStats, s_sm):
    """Smoothed idiosyncratic means in O(N r) per step from the collapsed
    smoother output (the dense path reads them off s_sm[:, rp:])."""
    e_obs = qd.m * (x - s_sm[:, : params.r] @ params.lam.T)
    return _idio_fill(params.phi, e_obs, qd.m)


# --------------------- dense kappa = 0 parity oracle ------------------------


def _dense0_system(params: SSMARParams):
    r, p, N = params.r, params.p, params.N
    pt = max(p, 2)
    rpt = r * pt
    k = rpt + N
    dtype = params.lam.dtype
    Tf, Qf = _qd_companion(params)
    idio = jnp.arange(rpt, k)
    Tm = jnp.zeros((k, k), dtype).at[:rpt, :rpt].set(Tf)
    Tm = Tm.at[idio, idio].set(params.phi)
    Qs = jnp.zeros((k, k), dtype).at[:rpt, :rpt].set(Qf)
    Qs = Qs.at[idio, idio].set(params.sigv2)
    P0 = jnp.zeros((k, k), dtype)
    P0 = P0.at[:rpt, :rpt].set(1e2 * jnp.eye(rpt, dtype=dtype))
    # stationary idio prior — the marginalization the quasi-difference's
    # first-observation variance encodes; required for likelihood parity
    P0 = P0.at[idio, idio].set(
        params.sigv2 / (1.0 - params.phi * params.phi)
    )
    return Tm, Qs, jnp.zeros(k, dtype), P0


@jax.jit
def _filter_ar_dense0(params: SSMARParams, x, mask):
    """Dense covariance-form masked filter of the EXACT (kappa = 0) BM-AR
    model: state [f-lags at max(p,2), e (N)], R = 0.  The information form
    cannot express exact-observation rows (d = m/R diverges), so this
    oracle runs the covariance recursion with unit dummy rows on missing
    entries — their innovations are zeroed, contribute log|1| = 0, and
    their gain columns vanish, so the likelihood and posteriors are those
    of the observed subvector exactly.  O(T (N + k)^3): a parity oracle,
    not a production path (see `check_dense_ar_budget`)."""
    r, p = params.r, params.p
    rpt = r * max(p, 2)
    Tm, Qs, s0, P0 = _dense0_system(params)
    lam = params.lam
    dtype = x.dtype
    m_f = mask.astype(dtype)
    log2pi = jnp.asarray(np.log(2.0 * np.pi), dtype)

    def step(carry, inp):
        s, P = carry
        xt, mt = inp
        sp = Tm @ s
        Pp = Tm @ P @ Tm.T + Qs
        Pp = 0.5 * (Pp + Pp.T)
        PHt = Pp[:, :r] @ lam.T + Pp[:, rpt:]  # (k, N) Pp H'
        HPH = lam @ PHt[:r] + PHt[rpt:]  # (N, N)
        S = mt[:, None] * HPH * mt[None, :] + jnp.diag(1.0 - mt)
        v = mt * (xt - lam @ sp[:r] - sp[rpt:])
        Ls = jnp.linalg.cholesky(0.5 * (S + S.T))
        PHm = PHt * mt[None, :]
        K = jsl.cho_solve((Ls, True), PHm.T).T  # (k, N)
        su = sp + K @ v
        Pu = Pp - K @ PHm.T
        Pu = 0.5 * (Pu + Pu.T)
        ll = -0.5 * (
            mt.sum() * log2pi
            + 2.0 * jnp.log(jnp.diagonal(Ls)).sum()
            + v @ jsl.cho_solve((Ls, True), v)
        )
        return (su, Pu), (su, Pu, sp, Pp, ll)

    (_, _), outs = jax.lax.scan(step, (s0, P0), (x, m_f))
    return outs


@jax.jit
def em_step_ar_dense0(params: SSMARParams, x, mask, qd: QDStats):
    """Dense parity oracle of `em_step_ar_qd`: identical kappa = 0 model,
    IDENTICAL M-step function, E-step through the full r*max(p,2) + N
    state.  tests/test_ar_collapsed.py pins <= 1e-8 agreement."""
    params = _guard_params_qd(params)
    means, covs, pmeans, pcovs, lls = _filter_ar_dense0(params, x, mask)
    Tm, _, _, _ = _dense0_system(params)
    s_sm, P_sm, lag1 = _rts_scan(Tm, means, covs, pmeans, pcovs)
    rpt = params.r * max(params.p, 2)
    new = _m_step_ar_qd(
        params, x, qd,
        s_sm[:, :rpt], P_sm[:, :rpt, :rpt], lag1[:, :rpt, :rpt],
    )
    return new, lls.sum()


# --------------------- dense-path memory budget guard -----------------------

# Default ceiling for the dense AR E-step's covariance stacks (bytes).
# Override with DFM_MEM_BUDGET (plain bytes, float syntax accepted).
_DEFAULT_MEM_BUDGET = 8e9


def _dense_ar_mem_bytes(T: int, N: int, r: int, p: int, itemsize: int = 8):
    # filtered + predicted covariances (+ their inverses when want_pinv),
    # smoothed covariances, lag-one covariances: six (T, k, k) stacks
    k = r * p + N
    return 6 * T * k * k * itemsize


def check_dense_ar_budget(T: int, N: int, r: int, p: int, itemsize: int = 8):
    """Fail loudly BEFORE the dense AR path's (T, k, k) allocations when
    they would exceed the DFM_MEM_BUDGET ceiling, instead of OOM-ing
    mid-scan, and point at the collapsed path."""
    need = _dense_ar_mem_bytes(T, N, r, p, itemsize)
    budget = int(float(os.environ.get("DFM_MEM_BUDGET", _DEFAULT_MEM_BUDGET)))
    if need > budget:
        raise MemoryError(
            f"dense AR state is k = r*p + N = {r * p + N}; the E-step "
            f"stores ~6 (T={T}, k, k) covariance stacks "
            f"~= {need / 1e9:.2f} GB > DFM_MEM_BUDGET="
            f"{budget / 1e9:.2f} GB. Use estimate_dfm_em_ar("
            "method='collapsed') — the N-free quasi-differenced path, "
            "exact for contiguous per-series observation runs — or raise "
            "DFM_MEM_BUDGET."
        )


class EMARResults(NamedTuple):
    params: SSMARParams
    factors: jnp.ndarray  # (T, r) smoothed factors
    idio: jnp.ndarray  # (T, N) smoothed idiosyncratic components
    loglik_path: np.ndarray
    n_iter: int
    stds: jnp.ndarray
    means: jnp.ndarray
    trace: object | None = None  # ConvergenceTrace when collect_path=True
    converged: bool = False  # actual tolerance break (not n_iter < cap)
    health: int = 0  # final utils.guards health code (0 = healthy)


def _project_params_ar(params: SSMARParams) -> SSMARParams:
    """Feasibility projection after SQUAREM extrapolation: idiosyncratic
    AR roots clipped inside the unit circle, variances floored, Q
    symmetrized/eigenvalue-floored (em_step_ar re-projects Q/sigv2 at
    entry; the phi clip is the addition extrapolation makes necessary)."""
    return params._replace(
        phi=jnp.clip(params.phi, -0.99, 0.99),
        sigv2=jnp.maximum(params.sigv2, jnp.asarray(1e-8, params.sigv2.dtype)),
        Q=_psd_floor(params.Q),
    )


def estimate_dfm_em_ar(
    data,
    inclcode,
    initperiod: int,
    lastperiod: int,
    config: DFMConfig = DFMConfig(nfac_u=4),
    max_em_iter: int = 100,
    tol: float = 1e-6,
    backend: str | None = None,
    collect_path: bool = False,
    checkpoint_path: str | None = None,
    checkpoint_every: int = 25,
    accel: str | None = None,
    method: str = "dense",
    steady: bool = False,
    n_shards: int | None = None,
    t_blocks: int | None = None,
) -> EMARResults:
    """Full Banbura-Modugno EM: factors + AR(1) idiosyncratic states.

    Initialized from the iid-noise EM fit (`ssm.estimate_dfm_em`), whose R
    becomes the initial sigv2 with phi = 0.

    accel="squarem" wraps the EM step in one SQUAREM extrapolation cycle
    per loop iteration (`emaccel.squarem`; n_iter then counts cycles of
    three EM-map evaluations each).

    method="dense" is the legacy kappa-regularized path (state
    k = r*p + N; O(k^3) per step, subject to `check_dense_ar_budget`);
    method="collapsed" is the N-free quasi-differenced path
    (`em_step_ar_qd`; exact kappa = 0 model) — the large-N production
    path.  Panels whose series have interior observation gaps are outside
    the collapsed path's exact mask class and fall back to dense with a
    warning naming the offending series and their first gap positions
    (telemetry records `collapse_gated`; `qd_gap_report` gives the full
    list).

    steady=True (collapsed only) additionally splits the time axis at the
    Riccati convergence horizon — exact head scan, constant-gain tail
    with closed-form tail moments (`emcore.em_step_ar_steady`) — so a
    long-history panel pays neither N nor T per iteration.  Host-gated by
    `emcore.ar_steady_plan` (the tail must be interior and the model
    fast-mixing); gated-off runs fall back to plain collapsed with
    telemetry `steady_gated`.

    n_shards > 1 (collapsed only) shards the collapse's pre-scan (T, N)
    GEMMs over the ``("data",)`` device mesh with one ring all-reduce of
    the packed payload per iteration (`emcore.em_step_ar_sharded`); the
    panel is padded with inert series to a shard multiple.  Composes with
    steady=True (`emcore._ar_steady_sharded_step_for`): all three speed
    axes — collapsed x steady x sharded — on one panel.

    t_blocks > 1 (collapsed only; exclusive with steady/n_shards on this
    core) runs the E-step scans parallel in time over that many
    contiguous per-device slabs (`emtime.em_step_ar_tp_for`): the
    quasi-differenced collapsed payload feeds fused O(k^3) scan elements
    and only O(k^2) slab boundaries cross devices
    (`parallel.timescan.sharded_scan`).  Parity with the sequential
    collapsed run is pinned at 1e-10 in tests/test_timeparallel.py.

    The step for any combination is resolved from a transform stack
    (models/transforms), not hand-picked: `Stack("ar", (collapse(),
    steady_tail(t*), shard(n)))` and its sub-stacks map to the same
    module-level jitted objects this function always dispatched.
    """
    from ..utils.compile import configure_compilation_cache

    configure_compilation_cache()
    if accel not in (None, "squarem"):
        raise ValueError(f"accel must be None or 'squarem', got {accel!r}")
    if method not in ("dense", "collapsed"):
        raise ValueError(
            f"method must be 'dense' or 'collapsed', got {method!r}"
        )
    ns = int(n_shards) if n_shards is not None else 0
    if steady and method != "collapsed":
        raise ValueError(
            "steady=True requires method='collapsed' (the steady tail is "
            "defined on the quasi-differenced collapse)"
        )
    if steady and accel is not None:
        raise ValueError(
            "accel is not combinable with steady=True: the steady EM "
            "carry (ARSteadyState: params + warm-start Pp∞ + solver "
            "counters) is not an extrapolable parameter vector"
        )
    if ns > 1:
        if method != "collapsed":
            raise ValueError(
                "n_shards requires method='collapsed' (only the collapsed "
                "pre-scan is sharded)"
            )
        if ns > jax.device_count():
            raise ValueError(
                f"n_shards={ns} exceeds the {jax.device_count()} visible "
                "devices"
            )
        if jax.process_count() > 1 and ns % jax.process_count() != 0:
            raise ValueError(
                f"n_shards={ns} must be a multiple of "
                f"jax.process_count()={jax.process_count()} so every host "
                "owns the same number of local shards"
            )
    tb = int(t_blocks) if t_blocks is not None else 0
    if tb > 1:
        if method != "collapsed":
            raise ValueError(
                "t_blocks requires method='collapsed' (only the "
                "quasi-differenced payload feeds the fused slab scan)"
            )
        if steady or ns > 1:
            raise ValueError(
                "t_blocks is exclusive with steady/n_shards on the AR "
                "core: the time axis composes with 'collapse' only "
                "(models/transforms refuses the other products)"
            )
        if tb > jax.device_count():
            raise ValueError(
                f"t_blocks={tb} exceeds the {jax.device_count()} visible "
                "devices"
            )
    from ..utils.telemetry import run_record

    with on_backend(backend), run_record(
        "estimate_dfm_em_ar",
        config={
            "accel": accel, "tol": tol, "max_em_iter": max_em_iter,
            "checkpointed": checkpoint_path is not None, "method": method,
            "steady": steady, "n_shards": ns, "t_blocks": tb,
        },
    ) as rec:
        data = jnp.asarray(data)
        inclcode = np.asarray(inclcode)
        em0 = estimate_dfm_em(
            data, inclcode, initperiod, lastperiod, config,
            max_em_iter=25, tol=tol,
        )
        # standardize with the init fit's own means/stds (one convention)
        xw = data[:, inclcode == 1][initperiod : lastperiod + 1]
        xz_nan = (xw - em0.means[None, :]) / em0.stds[None, :]
        m_arr = mask_of(xz_nan)
        xz = fillz(xz_nan)
        stds, n_mean = em0.stds, em0.means
        params = SSMARParams(
            lam=em0.params.lam,
            phi=jnp.zeros(em0.params.lam.shape[0], xz.dtype),
            sigv2=em0.params.R,
            A=em0.params.A,
            Q=em0.params.Q,
        )

        from . import emcore, transforms as tfm
        from .emloop import run_em_loop

        use_collapsed = method == "collapsed"
        if use_collapsed and not qd_mask_supported(np.asarray(m_arr)):
            bad, gaps = qd_gap_report(np.asarray(m_arr))
            shown = ", ".join(
                f"{int(i)} (first gap at t={int(g)})"
                for i, g in list(zip(bad, gaps))[:8]
            )
            more = f", ... and {len(bad) - 8} more" if len(bad) > 8 else ""
            warnings.warn(
                f"estimate_dfm_em_ar(method='collapsed'): {len(bad)} series "
                "have interior observation gaps (non-contiguous per-series "
                "runs) outside the quasi-differenced path's exact mask "
                f"class — series {shown}{more}; falling back to "
                "method='dense' (qd_gap_report(mask) lists every gap)",
                stacklevel=2,
            )
            rec.set(
                collapse_gated=True,
                gap_series=[int(i) for i in bad[:32]],
            )
            use_collapsed = False
        T_n, N_n = int(xz.shape[0]), int(xz.shape[1])
        r_n, p_n = config.nfac_u, config.n_factorlag
        if not use_collapsed:
            check_dense_ar_budget(
                T_n, N_n, r_n, p_n, itemsize=xz.dtype.itemsize
            )
        state_dim = (
            r_n * max(p_n, 2) if use_collapsed else r_n * p_n + N_n
        )
        rec.set(
            shapes={"T": T_n, "N": N_n, "r": r_n, "p": p_n},
            n_series=N_n, state_dim=state_dim,
        )

        # build the transform stack for the requested axes; each gate that
        # fails drops its axis (with telemetry) rather than erroring, so
        # the call degrades to the strongest supported sub-stack
        axes: list = []
        t_star = None
        st0 = None
        if use_collapsed:
            axes.append(tfm.collapse())
            if steady:
                # host gate on the UNPADDED mask (an all-missing padded
                # series would push the complete-tail point to T)
                plan = emcore.ar_steady_plan(params, np.asarray(m_arr))
                if plan is None:
                    rec.set(steady_gated=True, steady_frac=0.0)
                else:
                    t_star, st0, rho = plan
                    axes.append(tfm.steady_tail(t_star))
                    rec.set(
                        t_star=t_star,
                        steady_frac=float(T_n - t_star) / float(T_n),
                        riccati_rho=float(rho),
                    )
            if tb > 1:
                axes.append(tfm.time_shard(tb))
                rec.set(t_blocks=tb, mesh_shape=[1, tb, 1])
        elif steady or ns > 1 or tb > 1:
            rec.set(steady_gated=steady, shard_gated=ns > 1, tp_gated=tb > 1)

        xz_em, m_em, params_em = xz, m_arr, params
        if use_collapsed and ns > 1:
            axes.append(tfm.shard(ns))
            from ..parallel.mesh import series_pad

            Npad = series_pad(N_n, ns)
            if Npad != N_n:
                # inert series padding: zero loadings, zero data, all-False
                # mask — zero payload contribution (pinned by
                # tests/test_transform_stack.py)
                zcols = jnp.zeros((T_n, Npad - N_n), xz.dtype)
                xz_em = jnp.concatenate([xz, zcols], axis=1)
                m_em = jnp.concatenate(
                    [m_arr, jnp.zeros(zcols.shape, bool)], axis=1
                )
                params_em = emcore.pad_ar_params(params, Npad)
            nproc = jax.process_count()
            if nproc > 1:
                rec.set(
                    mesh_shape=[nproc, ns // nproc], sharded=True,
                    n_padded=Npad, process_count=nproc,
                )
            else:
                rec.set(mesh_shape=[ns], sharded=True, n_padded=Npad)

        res_t = tfm.resolve(tfm.Stack("ar", tuple(axes)))
        base_step = res_t.step
        fallback_step = None
        fallback_unwrap = None
        fallback_args = None
        if use_collapsed:
            qd = compute_qd_stats(xz_em, m_em)
            em_args = (xz_em, qd)
            if t_star is not None:
                em_args = (
                    xz_em, qd, emcore.compute_qd_tail_stats(qd, t_star)
                )
                # warm-start iteration 1 from the init-params solve the
                # plan already paid for; a tripped steady run demotes to
                # the plain collapsed step on (x, qd) args
                params_em = emcore.ARSteadyState(
                    params=params_em,
                    Pp=jnp.asarray(st0.Pp, xz.dtype),
                    riccati_iters=jnp.asarray(0, jnp.int32),
                )
                from .emaccel import unwrap_state

                fallback_step = res_t.fallback_step
                fallback_unwrap = unwrap_state
                fallback_args = (xz_em, qd)
            elif ns > 1 or tb > 1:
                # a tripped sharded / time-sharded run demotes to the
                # exact single-device collapsed step: same (x, qd) args,
                # padding stays inert
                fallback_step = res_t.fallback_step
        else:
            em_args = (xz_em, m_em)
        step = base_step
        if accel == "squarem":
            from .emaccel import squarem, squarem_state, unwrap_state

            step = squarem(base_step, _project_params_ar)
            params_em = squarem_state(params_em)
            # recovery-ladder demotion: drop the SQUAREM cycle back to the
            # plain AR EM map on the same args
            fallback_step = base_step
            fallback_unwrap = unwrap_state
            fallback_args = None
        if ns > 1 and jax.process_count() > 1:
            # multi-process SPMD: hand the loop host (numpy) arrays —
            # identical on every process by construction — so jit can
            # shard them onto the global ("dcn", "ici") mesh (a committed
            # single-device array cannot be resharded across processes)
            to_host = lambda t: jax.tree.map(np.asarray, t)
            params_em = to_host(params_em)
            em_args = to_host(em_args)
            if fallback_args is not None:
                fallback_args = to_host(fallback_args)
        res = run_em_loop(
            step, params_em, em_args, tol, max_em_iter,
            collect_path=collect_path,
            trace_name="em_dfm_ar_qd" if use_collapsed else "em_dfm_ar",
            checkpoint_path=checkpoint_path, checkpoint_every=checkpoint_every,
            fallback_step=fallback_step, fallback_unwrap=fallback_unwrap,
            fallback_args=fallback_args,
        )
        params, llpath, it, trace = res
        from .emaccel import SquaremState

        if isinstance(params, SquaremState):  # by type: demote may have peeled
            params = params.params
        if isinstance(params, emcore.ARSteadyState):
            rec.set(riccati_iters=int(params.riccati_iters))
            params = params.params
        if ns > 1 and jax.process_count() > 1:
            # gather the mesh-sharded loop output to replicated host
            # copies before the local readout (fully-replicated arrays
            # are locally addressable on every process)
            from jax.sharding import NamedSharding

            from ..parallel.mesh import P as _P, data_mesh

            gmesh = data_mesh(ns, hosts=0)
            gather = jax.jit(
                lambda t: t, out_shardings=NamedSharding(gmesh, _P())
            )
            params = jax.tree.map(np.asarray, gather(params))
        if int(params.lam.shape[0]) != N_n:  # sharded padding
            params = emcore.unpad_ar_params(params, N_n)
        rec.set(
            n_iter=it,
            converged=res.converged,
            final_loglik=float(llpath[-1]) if len(llpath) else None,
        )
        if res.faults_detected:
            from ..utils.guards import HEALTH_NAMES

            rec.set(
                faults_detected=res.faults_detected,
                recoveries=res.recoveries,
                ladder_rung=res.ladder_rung,
                final_health=HEALTH_NAMES[res.health],
            )

        r, rp = config.nfac_u, config.nfac_u * config.n_factorlag
        if use_collapsed:
            params = _guard_params_qd(params)
            if int(qd.n_int.shape[0]) != N_n:  # readout at the real width
                qd = compute_qd_stats(xz, m_arr)
            means, covs, pmeans, pcovs, _ = _filter_ar_qd(params, xz, qd)
            Tmq, _ = _qd_companion(params)
            s_sm, _, _ = _rts_scan(Tmq, means, covs, pmeans, pcovs)
            factors = s_sm[:, :r]
            idio = idio_moments_qd(params, xz, qd, s_sm)
        else:
            means, covs, pmeans, pcovs, _ = _filter_ar(params, xz, m_arr)
            s_sm, _, _ = _smoother_ar(params, means, covs, pmeans, pcovs)
            factors = s_sm[:, :r]
            idio = s_sm[:, rp:]
        return EMARResults(
            params=params,
            factors=factors,
            idio=idio,
            loglik_path=llpath,
            n_iter=it,
            stds=stds,
            means=n_mean,
            trace=trace,
            converged=res.converged,
            health=res.health,
        )


def nowcast_em_ar(
    em: EMARResults,
    data,
    inclcode,
    initperiod: int,
    lastperiod: int,
    h: int = 0,
    backend: str | None = None,
    method: str = "dense",
):
    """Ragged-edge nowcast in ORIGINAL units from the BM-AR fit.

    Unlike the iid-noise model (forecast.nowcast_em), the filtered AR(1)
    idiosyncratic state carries each series' persistent deviation into its
    unreleased periods: x_hat = Lam f + e with e evolved by phi.  Returns a
    forecast.Nowcast (x_hat (T+h, N_incl), factor, filled).

    method="collapsed" runs the N-free quasi-differenced filter (the path
    for fits produced by `estimate_dfm_em_ar(method="collapsed")` at large
    N): the idio contribution is recovered in O(T N) from the filtered
    factors (e = x - Lam f at observed cells, phi-decay into the ragged
    tail) instead of carrying N idio states through a (T, k, k) scan.
    """
    from .forecast import Nowcast, _check_included_columns, _predict_and_fill

    if method not in ("dense", "collapsed"):
        raise ValueError(
            f"method must be 'dense' or 'collapsed', got {method!r}"
        )
    with on_backend(backend):
        data = jnp.asarray(data)
        inclcode = np.asarray(inclcode)
        xw = data[initperiod : lastperiod + 1][:, inclcode == 1]
        _check_included_columns(xw, em.params.N)
        xz = (xw - em.means[None, :]) / em.stds[None, :]
        m = mask_of(xz)
        # same guard the public kalman_filter applies: a checkpoint-round-
        # tripped or hand-built params with singular Q/sigv2 must degrade
        # gracefully, not NaN the whole nowcast
        params = em.params._replace(
            Q=_psd_floor(em.params.Q), sigv2=jnp.maximum(em.params.sigv2, 1e-8)
        )
        if method == "collapsed":
            params = _guard_params_qd(params)
            r = params.r
            xzf = fillz(xz)
            qd = compute_qd_stats(xzf, m)
            f_means = _filter_ar_qd(params, xzf, qd)[0]  # (T, r*pt) filtered
            e = _idio_fill(
                params.phi, qd.m * (xzf - f_means[:, :r] @ params.lam.T), qd.m
            )
            Tmq, _ = _qd_companion(params)

            def step(carry, _):
                s, e_t = carry
                nxt = (Tmq @ s, params.phi * e_t)
                return nxt, nxt

            _, (sf, ef) = jax.lax.scan(
                step, (f_means[-1], e[-1]), None, length=h
            )
            fit = f_means[:, :r] @ params.lam.T + e
            x_hat_z = jnp.concatenate([fit, sf[:, :r] @ params.lam.T + ef], 0)
            scale, shift = em.stds[None, :], em.means[None, :]
            return Nowcast(
                x_hat=x_hat_z * scale + shift,
                factor=jnp.concatenate([f_means[:, :r], sf[:, :r]], axis=0),
                filled=jnp.where(m, xw, fit * scale + shift),
            )
        check_dense_ar_budget(
            int(xz.shape[0]), params.N, params.r, params.p,
            itemsize=jnp.asarray(xz).dtype.itemsize,
        )
        means, _, _, _, _ = _filter_ar(params, fillz(xz), m)
        Tm, _ = _transition(params)
        return _predict_and_fill(
            xw, m, means, _obs_matrix(params), Tm, params.r, h,
            em.stds[None, :], em.means[None, :],
        )
