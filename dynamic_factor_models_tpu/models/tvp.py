"""Time-varying factor loadings: per-series random-walk TVP regressions on
the estimated factors, vmapped across the panel.

New capability: the reference *tests* for loading instability (Table 4 Chow/
QLR scans, Stock_Watson.ipynb cell 57) but has no model that lets loadings
move.  This module models the instability the tests detect (Stock-Watson
TVP tradition, Cogley-Sargent style random-walk drift):

    x_{i,t} = lam_{i,t}' F_t + e_{i,t},      e_{i,t} ~ N(0, sig2_i)
    lam_{i,t} = lam_{i,t-1} + v_{i,t},       v_{i,t} ~ N(0, q_i sig2_i I)

Given factors (ALS or EM point estimates — the standard two-step), each
series is an r-state univariate-observation Kalman problem with missing
observations masked.  TPU-first shape: ONE series' filter/smoother is a
``lax.scan``; the panel is a ``vmap`` over series; the signal-to-noise
ratio q_i is chosen per series by prediction-error likelihood over a grid —
a second ``vmap`` over grid points, so model selection is a (series x grid)
batch of scans with an argmax, no host loop.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.linalg import solve_normal
from ..ops.masking import fillz, mask_of
from ..utils.backend import on_backend
from .ssm import _rts_scan

__all__ = ["TVPLoadings", "tvp_loadings"]

_DEFAULT_GRID = (0.0, 1e-5, 1e-4, 1e-3, 1e-2, 1e-1)


class TVPLoadings(NamedTuple):
    lam_path: jnp.ndarray  # (T, N, r) smoothed loading paths
    lam_var: jnp.ndarray  # (T, N, r) smoothed loading variances (diagonal)
    sigma2: jnp.ndarray  # (N,) measurement variances
    q: jnp.ndarray  # (N,) selected signal-to-noise ratios tau2/sig2
    loglik: jnp.ndarray  # (N,) prediction-error loglik at the selected q
    grid_loglik: jnp.ndarray  # (N, n_grid) loglik over the whole grid
    drift: jnp.ndarray  # (N,) total smoothed loading movement per series


def _tvp_filter(y, F, m, lam0, P0_diag, sig2, tau2):
    """Masked random-walk-coefficient Kalman filter for ONE series.

    Returns filtered (lam, P) paths, predicted (lam, P) paths, loglik."""
    r = F.shape[1]
    dtype = y.dtype
    log2pi = jnp.asarray(np.log(2.0 * np.pi), dtype)
    eye_r = jnp.eye(r, dtype=dtype)

    def step(carry, inp):
        lam, P = carry
        y_t, f_t, m_t = inp
        Pp = P + tau2 * eye_r  # random-walk prediction
        v = y_t - f_t @ lam
        S = f_t @ Pp @ f_t + sig2
        K = (Pp @ f_t) / S
        lam_u = lam + m_t * K * v
        P_u = Pp - m_t * jnp.outer(K, f_t) @ Pp
        P_u = 0.5 * (P_u + P_u.T)
        ll = -0.5 * m_t * (log2pi + jnp.log(S) + v * v / S)
        return (lam_u, P_u), (lam_u, P_u, lam, Pp, ll)

    init = (lam0, jnp.diag(P0_diag))
    (_, _), (lams, Ps, lams_p, Ps_p, lls) = jax.lax.scan(step, init, (y, F, m))
    return lams, Ps, lams_p, Ps_p, lls.sum()


@jax.jit
def _tvp_panel(xz, W, F, grid):
    """Grid-select q per series, then smooth at the winner; all vmapped."""
    dtype = xz.dtype

    # per-series OLS init: loading lam0 and residual variance sig2
    Fg = jnp.einsum("ti,tr,ts->irs", W, F, F)
    Fx = jnp.einsum("ti,tr->ir", W * xz, F)
    lam0 = jax.vmap(solve_normal)(Fg, Fx)  # (N, r)
    resid = jnp.where(W.astype(bool), xz - jnp.einsum("tr,ir->ti", F, lam0), 0.0)
    n_i = jnp.maximum(W.sum(axis=0), 1.0)
    sig2 = jnp.maximum((resid**2).sum(axis=0) / n_i, 1e-10)

    P0 = 10.0 * jnp.ones(F.shape[1], dtype)

    def series_grid_ll(y_i, w_i, lam0_i, sig2_i):
        def at_q(qv):
            *_, ll = _tvp_filter(y_i, F, w_i, lam0_i, P0, sig2_i, qv * sig2_i)
            return ll

        return jax.vmap(at_q)(grid)  # (n_grid,)

    grid_ll = jax.vmap(series_grid_ll, in_axes=(1, 1, 0, 0))(
        xz, W, lam0, sig2
    )  # (N, n_grid)
    best = jnp.argmax(grid_ll, axis=1)
    q_sel = grid[best]

    def series_smooth(y_i, w_i, lam0_i, sig2_i, q_i):
        lams, Ps, lams_p, Ps_p, ll = _tvp_filter(
            y_i, F, w_i, lam0_i, P0, sig2_i, q_i * sig2_i
        )
        # shared RTS body (ssm._rts_scan) with the identity transition of
        # the random-walk state; lag-one covariances discarded
        lam_s, P_s, _ = _rts_scan(
            jnp.eye(F.shape[1], dtype=dtype), lams, Ps, lams_p, Ps_p
        )
        return lam_s, jnp.diagonal(P_s, axis1=1, axis2=2), ll

    lam_path, lam_var, ll_sel = jax.vmap(
        series_smooth, in_axes=(1, 1, 0, 0, 0), out_axes=(1, 1, 0)
    )(xz, W, lam0, sig2, q_sel)
    drift = jnp.abs(jnp.diff(lam_path, axis=0)).sum(axis=(0, 2))
    return lam_path, lam_var, sig2, q_sel, ll_sel, grid_ll, drift


def tvp_loadings(
    x,
    F,
    grid=_DEFAULT_GRID,
    backend: str | None = None,
) -> TVPLoadings:
    """Random-walk time-varying loadings of every series on the factors.

    x: (T, N) panel (NaN missing) — typically standardized, the units the
    factors were estimated in; F: (T, r) factor point estimates (rows with
    NaN factors are masked out of every series).  `grid` is the candidate
    signal-to-noise set for q = tau2/sig2; q=0 reproduces constant-loading
    GLS, so series whose loadings are stable select ~0 and series the
    Table-4 scans flag as unstable select larger q.

    Returns smoothed loading paths with variances, selected q per series,
    and the per-series total loading drift (a scalar instability measure).
    """
    with on_backend(backend):
        x = jnp.asarray(x)
        F = jnp.asarray(F)
        f_ok = mask_of(F).all(axis=1)
        W = (mask_of(x) & f_ok[:, None]).astype(x.dtype)
        xz = fillz(x)
        Fz = fillz(F)
        grid_arr = jnp.asarray(grid, x.dtype)
        out = _tvp_panel(xz, W, Fz, grid_arr)
        return TVPLoadings(*out)
