"""DFM forecasting and ragged-edge nowcasting.

New capability beyond the reference (which estimates factors and IRFs but
never forecasts): the standard Stock-Watson diffusion-index forecasting
recipe on top of the non-parametric DFM, and Kalman-prediction nowcasting on
top of the state-space DFM (Banbura-Modugno style: the masked filter walks
through a ragged-edge panel — series released at different delays — and the
state prediction fills the missing tail).

TPU design: both horizons are ``lax.scan`` recursions over static shapes;
per-series idiosyncratic AR forecasts are one vmapped scan.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.masking import fillz, mask_of
from ..utils.backend import on_backend
from .dfm import DFMResults
from .ssm import EMResults, SSMParams, _companion, kalman_filter, kalman_smoother
from .var import VARResults

__all__ = [
    "DFMForecast",
    "ConditionalForecast",
    "conditional_forecast",
    "forecast_factors",
    "forecast_series",
    "nowcast_ssm",
    "nowcast_em",
]


class DFMForecast(NamedTuple):
    factor: jnp.ndarray  # (h, nfac) factor forecasts
    common: jnp.ndarray  # (h, ns) common-component forecasts lam f + const
    idio: jnp.ndarray  # (h, ns) idiosyncratic AR forecasts
    series: jnp.ndarray  # (h, ns) common + idio


def forecast_factors(var: VARResults, factor, h: int) -> jnp.ndarray:
    """h-step factor forecasts by iterating the estimated companion form.

    `factor` is the (T, nfac) factor matrix (NaN outside the estimation
    window); the last `nlag` complete rows seed the recursion.
    """
    f = jnp.asarray(factor)
    nfac = f.shape[1]
    nlag = var.nlag
    complete = np.asarray(mask_of(f).all(axis=1))
    if not complete.any():
        raise ValueError("factor matrix has no complete rows to forecast from")
    last = int(np.max(np.nonzero(complete)[0]))
    if last + 1 < nlag or not complete[last - nlag + 1 : last + 1].all():
        raise ValueError(f"need {nlag} complete trailing factor rows to forecast")
    lags = f[last - nlag + 1 : last + 1][::-1]  # (nlag, nfac), most recent first

    if var.betahat.shape[0] != 1 + nfac * nlag:
        raise ValueError(
            f"betahat has {var.betahat.shape[0]} rows; forecast_factors needs "
            f"the const-first layout 1 + nfac*nlag = {1 + nfac * nlag} "
            "(fit the VAR with withconst=True)"
        )
    const = var.betahat[0]
    blocks = [var.betahat[1 + i * nfac : 1 + (i + 1) * nfac].T for i in range(nlag)]

    def step(lags, _):
        f_next = const
        for i in range(nlag):
            f_next = f_next + blocks[i] @ lags[i]
        return jnp.concatenate([f_next[None], lags[:-1]], axis=0), f_next

    _, path = jax.lax.scan(step, lags, None, length=h)
    return path


def _forecast_idio(resid_hist, coef, h: int):
    """Per-series AR(p) forecasts from the residual history (vmapped scan).

    resid_hist: (p, ns) most-recent-first residuals; coef: (ns, p).
    Series with NaN coefficients (below nt_min, or zeroed degenerate fits)
    forecast zero.
    """
    coef = jnp.nan_to_num(coef)
    hist = jnp.nan_to_num(resid_hist)

    def step(hist, _):
        e_next = (coef * hist.T).sum(axis=1)  # (ns,)
        return jnp.concatenate([e_next[None], hist[:-1]], axis=0), e_next

    _, path = jax.lax.scan(step, hist, None, length=h)
    return path


def forecast_series(
    results: DFMResults,
    data,
    initperiod: int,
    lastperiod: int,
    h: int,
    backend: str | None = None,
) -> DFMForecast:
    """Diffusion-index h-step forecasts for every series in the panel.

    series = (lam f_{T+h} + const) + AR(n_uarlag) idiosyncratic forecast,
    with the idiosyncratic history rebuilt from the estimation window.
    Requires `results` from `estimate_dfm` (needs var + lam_const).
    """
    if results.var is None or results.lam_const is None:
        raise ValueError("forecast_series needs DFMResults from estimate_dfm")
    with on_backend(backend):
        fpath = forecast_factors(results.var, results.factor, h)
        lam = jnp.nan_to_num(results.lam)
        const = jnp.nan_to_num(results.lam_const)
        common = fpath @ lam.T + const[None, :]

        # idiosyncratic residual lag state at the forecast origin: walk the
        # window once, substituting the AR's conditional expectation at
        # missing rows.  This keeps lag slots aligned (no treating a lag-3
        # residual as lag-1) and discounts release gaps correctly: a series
        # last observed d periods ago contributes coef-iterated-(d) times,
        # not its stale residual at full weight.
        data = jnp.asarray(data)
        yw = data[initperiod : lastperiod + 1]
        fw = jnp.asarray(results.factor)[initperiod : lastperiod + 1]
        W = mask_of(yw) & mask_of(fw).all(axis=1)[:, None]
        e = jnp.where(W, fillz(yw) - (fillz(fw) @ lam.T + const[None, :]), 0.0)
        p = results.uar_coef.shape[1]
        coef = jnp.nan_to_num(results.uar_coef)  # (ns, p)

        def walk(lags, inp):
            e_obs, w = inp  # (ns,), (ns,)
            e_pred = jnp.einsum("ik,ki->i", coef, lags)
            e_t = jnp.where(w, e_obs, e_pred)
            return jnp.concatenate([e_t[None], lags[:-1]], axis=0), None

        lags0 = jnp.zeros((p, e.shape[1]), e.dtype)
        hist, _ = jax.lax.scan(walk, lags0, (e, W))
        idio = _forecast_idio(hist, results.uar_coef, h)
        # series whose loadings were never estimated (below nt_min_loading)
        # must forecast NaN, not a silent 0 in raw data units
        no_loading = jnp.isnan(results.lam).any(axis=1)[None, :]
        common = jnp.where(no_loading, jnp.nan, common)
        idio = jnp.where(no_loading, jnp.nan, idio)
        return DFMForecast(fpath, common, idio, common + idio)


class Nowcast(NamedTuple):
    x_hat: jnp.ndarray  # (T + h, N) fitted/predicted panel in input units
    factor: jnp.ndarray  # (T + h, r) filtered then predicted factors
    filled: jnp.ndarray  # (T, N) input with missing entries replaced by x_hat


def _predict_and_fill(
    x_units, mask, state_means, H, Tm, r: int, h: int, scale, shift
) -> Nowcast:
    """Shared nowcast core: observation map over the filtered states, h-step
    state prediction, rescale to input units, fill the missing entries.

    Serves all three entry points (`nowcast_ssm`, `nowcast_em`,
    `ssm_ar.nowcast_em_ar`); only the filter and the observation map differ
    per model.  state_means are filtered means in standardized units.
    """
    fit = state_means @ H.T  # (T, N) standardized fitted values

    def step(s, _):
        s2 = Tm @ s
        return s2, s2

    _, future = jax.lax.scan(step, state_means[-1], None, length=h)
    x_hat_z = jnp.concatenate([fit, future @ H.T], axis=0)
    f_all = jnp.concatenate([state_means[:, :r], future[:, :r]], axis=0)
    return Nowcast(
        x_hat=x_hat_z * scale + shift,
        factor=f_all,
        filled=jnp.where(mask, x_units, fit * scale + shift),
    )


def _check_included_columns(xw, n_model: int) -> None:
    if xw.shape[1] != n_model:
        raise ValueError(
            f"panel has {xw.shape[1]} included columns but the model was "
            f"fitted on {n_model}"
        )


def _iid_obs(params: SSMParams):
    """(H, Tm) for the iid-noise model: only the first r state dims load."""
    Tm, _ = _companion(params)
    H = jnp.zeros((params.lam.shape[0], Tm.shape[0]), params.lam.dtype)
    return H.at[:, : params.r].set(params.lam), Tm


def nowcast_ssm(params: SSMParams, x, h: int = 0, backend: str | None = None) -> Nowcast:
    """Ragged-edge nowcast: masked Kalman filter through the panel, state
    prediction h steps past the end, observation map applied throughout.

    x is a (T, N) panel with NaN at unreleased observations (the masked
    filter skips them — no balancing or truncation needed); the returned
    `filled` panel replaces exactly those entries with model predictions.
    Works in the model's (standardized) units; `nowcast_em` handles units.
    """
    with on_backend(backend):
        x = jnp.asarray(x)
        # public filter: applies the PSD floor on Q and the NaN prefill
        filt = kalman_filter(params, x)
        H, Tm = _iid_obs(params)
        one = jnp.ones((), x.dtype)
        return _predict_and_fill(
            x, mask_of(x), filt.means, H, Tm, params.r, h, one, 0.0 * one
        )


def nowcast_em(
    em: EMResults,
    data,
    inclcode,
    initperiod: int,
    lastperiod: int,
    h: int = 0,
    backend: str | None = None,
) -> Nowcast:
    """Ragged-edge nowcast in ORIGINAL data units, from `estimate_dfm_em`.

    Handles the bookkeeping `nowcast_ssm` leaves to the caller: subsets to
    the inclcode==1 columns the EM model was fitted on, standardizes with the
    fit's per-series means/stds, filters + predicts, and rescales every
    output back to input units.
    """
    with on_backend(backend):
        data = jnp.asarray(data)
        inclcode = np.asarray(inclcode)
        xw = data[initperiod : lastperiod + 1][:, inclcode == 1]
        _check_included_columns(xw, em.params.lam.shape[0])
        xz = (xw - em.means[None, :]) / em.stds[None, :]
        params = em.params
        filt = kalman_filter(params, xz)
        H, Tm = _iid_obs(params)
        return _predict_and_fill(
            xw, mask_of(xw), filt.means, H, Tm, params.r, h,
            em.stds[None, :], em.means[None, :],
        )


class ConditionalForecast(NamedTuple):
    mean: jnp.ndarray  # (h, N) predictive mean of every series
    sd: jnp.ndarray  # (h, N) predictive sd (common-component + idio)
    factor_mean: jnp.ndarray  # (h, r) smoothed factor path over the horizon
    factor_cov: jnp.ndarray  # (h, r, r)


def conditional_forecast(
    params: SSMParams,
    x,
    horizon: int,
    conditions=None,
    backend: str | None = None,
) -> ConditionalForecast:
    """Scenario / conditional forecasts from the state-space DFM.

    New capability (central-bank scenario analysis; Banbura-Giannone-Lenza
    style conditional forecasting): append `horizon` future rows to the
    panel in which `conditions` (horizon, N; NaN = unconstrained) pins the
    assumed paths of a subset of series, and run the masked Kalman smoother
    over the extended panel — the machinery that already handles arbitrary
    missing patterns does conditioning for free.  Unconditional forecasts
    are the conditions=None special case.

    x: (T, N) standardized panel the params were fitted on (NaN missing).
    Conditioned entries are treated as observed through their measurement
    equation, so their predictive mean tracks the assumed path up to the
    idiosyncratic noise weighting.
    """
    if horizon < 1:
        raise ValueError(f"horizon must be >= 1, got {horizon}")
    with on_backend(backend):
        x = jnp.asarray(x)
        N = x.shape[1]
        if conditions is None:
            cond = jnp.full((horizon, N), jnp.nan, x.dtype)
        else:
            cond = jnp.asarray(conditions, x.dtype)
            if cond.shape != (horizon, N):
                raise ValueError(
                    f"conditions must be (horizon, N) = ({horizon}, {N}), "
                    f"got {cond.shape}"
                )
        x_ext = jnp.concatenate([x, cond], axis=0)
        means, covs, _ = kalman_smoother(params, x_ext)
        r = params.r
        f = means[-horizon:, :r]
        Pf = covs[-horizon:, :r, :r]
        mean = f @ params.lam.T
        var_common = jnp.einsum("nr,hrs,ns->hn", params.lam, Pf, params.lam)
        sd = jnp.sqrt(var_common + params.R[None, :])
        return ConditionalForecast(mean, sd, f, Pf)
