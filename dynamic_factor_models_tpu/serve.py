"""CLI entry for the multi-tenant serving demo (implementation:
serving/engine.py).

    python -m dynamic_factor_models_tpu.serve --tenants 3 --ticks 12

registers synthetic tenants, streams O(1) online ticks, serves a nowcast,
and runs one batched EM refit flush, printing one JSON line per phase.
"""

from .serving.engine import ServingEngine, main  # noqa: F401

if __name__ == "__main__":
    import sys

    sys.exit(main())
