"""Batched scenario engine: vmapped fan-out over the estimation zoo.

Three pieces (the fourth ROADMAP pillar after compile-once, serving and
guardrails):

* `gibbs`  — multi-chain Gibbs for the Bayesian DFM: n_chains chains as
  one scan-outside / vmap-inside program with the utils.guards health
  sentinel vectorized per chain, so a divergent chain is rolled back,
  frozen and dropped from the posterior without perturbing its lane-mates
  (the `run_em_loop_batched` isolation contract, applied to MCMC).
* `fanout` — simulation-smoother and forward-simulation fan-out kernels:
  conditional-forecast fans, stress paths, posterior-predictive draw
  fans, all one vmap instead of a host loop, AOT-registered through
  utils.compile keyed on (bucket, n_draws).
* `api`    — ScenarioRequest/ScenarioResult and the `run_scenario`
  dispatcher the serving engine routes `kind="scenario"` requests to.
* `particles` / `smc` — the composable SMC subsystem: pure per-step
  kernels (systematic resampling, adaptive ESS triggering, Liu-West
  jitter) assembled into ONE guarded scan-outside/vmap-inside particle
  filter over scenario lanes, with linear-Gaussian, stochastic-
  volatility, Markov-switching and TVP-loading particle models — the
  nonlinear density backends behind `kind="nowcast_density"` /
  `"regime_stress"` / `"hierarchical"` requests.
"""

from .api import (
    ScenarioRequest,
    ScenarioResult,
    ScenarioValidationError,
    run_scenario,
)
from .fanout import conditional_fan, draw_fan, forecast_fan, stress_fan
from .gibbs import MultiChainResult, sample_chains
from .smc import SMCResult, smc_filter

__all__ = [
    "ScenarioRequest",
    "ScenarioResult",
    "ScenarioValidationError",
    "run_scenario",
    "conditional_fan",
    "draw_fan",
    "forecast_fan",
    "stress_fan",
    "MultiChainResult",
    "sample_chains",
    "SMCResult",
    "smc_filter",
]
