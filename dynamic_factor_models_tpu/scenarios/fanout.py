"""Fan-out kernels: many scenarios / many draws as one vmapped program.

The paper's conditional-forecast and simulation-smoother machinery
(models/forecast.py, models/bayes.py) runs one scenario at a time; a
"what if oil +30%, across 10k draws" serving request is a fan of
thousands of such runs that differ only in a conditioning path or a PRNG
key.  Everything here vmaps the existing cores over stacked inputs —
no new numerics, just batch structure — and dispatches through the
utils.compile AOT registry so a `precompile(CompileSpec(scenario_draws=
...))` serves the whole fan from one executable, keyed on (bucket,
n_draws) via the traced shapes + the static horizon:

* `conditional_fan`  — S conditioning paths through the masked smoother
  (the `conditional_forecast` math, exactly; parity pinned at 1e-12).
* `draw_fan`         — S paths x D simulation-smoother draws: sampled
  factor paths + posterior-predictive observable fans per scenario.
* `stress_fan`       — S factor-shock vectors propagated through the
  companion dynamics on top of the baseline forecast.
* `forecast_fan`     — D forward-simulation draws from D parameter
  draws (the `posterior_forecast` kernel; bayes routes through here so
  posterior forecasts and scenario fans share one compiled program).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from ..models.bayes import _simulation_smoother_core
from ..models.ssm import (
    SSMParams,
    _companion,
    _filter_scan,
    _psd_floor,
    _smoother_scan,
)
from ..ops.masking import fillz, mask_of

__all__ = [
    "conditional_fan",
    "draw_fan",
    "forecast_fan",
    "stress_fan",
    "extend_panel",
]


def extend_panel(x, horizon: int, conditions=None):
    """Stack S condition paths onto a shared history: (S, T+h, N) panels.

    `conditions` (S, horizon, N) pins assumed future paths per scenario,
    NaN = unconstrained (None = one unconditional lane); the validation
    mirrors `forecast.conditional_forecast` so the fan and the loop
    reject the same inputs."""
    if horizon < 1:
        raise ValueError(f"horizon must be >= 1, got {horizon}")
    x = jnp.asarray(x)
    N = x.shape[1]
    if conditions is None:
        cond = jnp.full((1, horizon, N), jnp.nan, x.dtype)
    else:
        cond = jnp.asarray(conditions, x.dtype)
        if cond.ndim == 2:
            cond = cond[None]
        if cond.ndim != 3 or cond.shape[1:] != (horizon, N):
            raise ValueError(
                f"conditions must be (S, horizon, N) = (*, {horizon}, {N}), "
                f"got {tuple(cond.shape)}"
            )
    S = cond.shape[0]
    x_ext = jnp.concatenate(
        [jnp.broadcast_to(x, (S,) + x.shape), cond], axis=1
    )
    return fillz(x_ext), mask_of(x_ext)


@partial(jax.jit, static_argnames=("horizon",))
def _conditional_fan_impl(params, xz_stack, mask_stack, horizon: int):
    """(mean, sd, factor_mean, factor_cov) per scenario — the
    `conditional_forecast` tail math vmapped over the stacked panels."""

    def one(xe, me):
        filt = _filter_scan(params, xe, me)
        sm, cov, _ = _smoother_scan(params, filt)
        r = params.r
        f = sm[-horizon:, :r]
        Pf = cov[-horizon:, :r, :r]
        mean = f @ params.lam.T
        var_common = jnp.einsum("nr,hrs,ns->hn", params.lam, Pf, params.lam)
        sd = jnp.sqrt(var_common + params.R[None, :])
        return mean, sd, f, Pf

    return jax.vmap(one)(xz_stack, mask_stack)


def conditional_fan(params: SSMParams, x, horizon: int, conditions=None):
    """Conditional-forecast fan: S scenarios through ONE vmapped masked
    smoother.  Returns (mean (S, h, N), sd, factor_mean (S, h, r),
    factor_cov (S, h, r, r)); lane s equals
    `conditional_forecast(params, x, horizon, conditions[s])` to float
    tolerance (pinned at 1e-12)."""
    from ..utils.compile import aot_call, aot_statics

    params = params._replace(Q=_psd_floor(params.Q))
    xz, mask = extend_panel(x, horizon, conditions)
    return aot_call(
        "scenario_cond_fan",
        lambda pa, xe, me: _conditional_fan_impl(pa, xe, me, horizon),
        params, xz, mask,
        statics=aot_statics(horizon),
    )


@partial(jax.jit, static_argnames=("horizon",))
def _draw_fan_impl(params, xz_stack, mask_stack, keys, horizon: int):
    """Simulation-smoother fan: draws x scenarios, one double vmap.

    keys (S, D, 2).  Returns (f_draws (S, D, h, r), y_draws (S, D, h, N),
    loglik (S, D)); y adds measurement noise to the drawn common
    component — genuine posterior-predictive paths per scenario."""

    def one_path(xe, me, ks):
        def one_draw(k):
            kf, ke = jax.random.split(k)
            f, ll = _simulation_smoother_core(params, xe, me, kf)
            fh = f[-horizon:]
            eps = jax.random.normal(
                ke, (horizon, params.lam.shape[0]), xe.dtype
            )
            y = fh @ params.lam.T + eps * jnp.sqrt(params.R)
            return fh, y, ll

        return jax.vmap(one_draw)(ks)

    return jax.vmap(one_path)(xz_stack, mask_stack, keys)


def draw_fan(
    params: SSMParams,
    x,
    horizon: int,
    n_draws: int,
    conditions=None,
    seed: int = 0,
):
    """Sampled scenario fans: for each of S conditioning paths, D
    Durbin-Koopman factor-path draws + posterior-predictive observable
    paths over the horizon.  One compiled program for the whole
    S x D fan (kernel "scenario_draw_fan")."""
    from ..utils.compile import aot_call, aot_statics

    if n_draws < 1:
        raise ValueError(f"n_draws must be >= 1, got {n_draws}")
    params = params._replace(Q=_psd_floor(params.Q))
    xz, mask = extend_panel(x, horizon, conditions)
    S = xz.shape[0]
    keys = jax.random.split(
        jax.random.PRNGKey(seed), S * n_draws
    ).reshape(S, n_draws, 2)
    return aot_call(
        "scenario_draw_fan",
        lambda pa, xe, me, ks: _draw_fan_impl(pa, xe, me, ks, horizon),
        params, xz, mask, keys,
        statics=aot_statics(horizon),
    )


@partial(jax.jit, static_argnames=("horizon",))
def _stress_fan_impl(params, shocks, horizon: int):
    """Factor-shock responses: propagate each (r,) innovation impulse
    through the companion dynamics, map to observables.  (S, h, N)."""
    Tm, _ = _companion(params)
    k = Tm.shape[0]
    r = params.r

    def one(delta):
        s0 = jnp.zeros((k,), delta.dtype).at[:r].set(delta)

        def step(s, _):
            return Tm @ s, s[:r]

        _, fpath = jax.lax.scan(step, s0, None, length=horizon)
        return fpath, fpath @ params.lam.T

    return jax.vmap(one)(shocks)


def stress_fan(params: SSMParams, x, horizon: int, shocks):
    """Stress-path fan: shock the factor innovations by each row of
    `shocks` (S, r) at the forecast origin and propagate.  Returns
    (mean (S, h, N), sd (S, h, N), factor_mean (S, h, r)) where mean =
    baseline conditional mean + shock response — linearity of the state
    space makes the superposition exact, so one baseline smoother run
    serves every stress lane."""
    shocks = jnp.asarray(shocks)
    if shocks.ndim == 1:
        shocks = shocks[None]
    if shocks.ndim != 2 or shocks.shape[1] != params.r:
        raise ValueError(
            f"shocks must be (S, r) = (*, {params.r}), got "
            f"{tuple(shocks.shape)}"
        )
    base_mean, base_sd, base_f, _ = conditional_fan(params, x, horizon)
    f_shift, y_shift = _stress_fan_impl(params, shocks, horizon)
    return (
        base_mean + y_shift,
        jnp.broadcast_to(base_sd, y_shift.shape),
        base_f + f_shift,
    )


def _forecast_one(lam_i, R_i, A_i, Q_i, s, key, horizon: int):
    """One posterior-predictive forward simulation: iterate the factor
    VAR from terminal companion state `s` with fresh innovations, add
    measurement noise.  (h, N) in standardized units."""
    params = SSMParams(lam=lam_i, R=R_i, A=A_i, Q=_psd_floor(Q_i))
    Tm, _ = _companion(params)
    r = params.r
    ku, ke = jax.random.split(key)
    Lq = jnp.linalg.cholesky(params.Q)
    u = jax.random.normal(ku, (horizon, r), lam_i.dtype) @ Lq.T

    def step(s_prev, u_t):
        s_t = (Tm @ s_prev).at[:r].add(u_t)
        return s_t, s_t[:r]

    _, f_path = jax.lax.scan(step, s, u)
    eps = jax.random.normal(ke, (horizon, lam_i.shape[0]), lam_i.dtype)
    return f_path @ lam_i.T + eps * jnp.sqrt(R_i)


@partial(jax.jit, static_argnames=("horizon",))
def _forecast_fan_impl(lam_d, r_d, a_d, q_d, s_term, keys, horizon: int):
    return jax.vmap(
        lambda l, R, A, Q, s, k: _forecast_one(l, R, A, Q, s, k, horizon)
    )(lam_d, r_d, a_d, q_d, s_term, keys)


def forecast_fan(lam_d, r_d, a_d, q_d, s_term, keys, horizon: int):
    """Forward-simulation fan over D parameter draws (kernel
    "scenario_fan"): the `posterior_forecast` device program, shared
    with scenario draw requests.  lam_d (D, N, r), r_d (D, N), a_d
    (D, p, r, r), q_d (D, r, r), s_term (D, r*p), keys (D, 2); returns
    (D, h, N) standardized predictive draws."""
    from ..utils.compile import aot_call, aot_statics

    return aot_call(
        "scenario_fan",
        lambda *a: _forecast_fan_impl(*a, horizon=horizon),
        lam_d, r_d, a_d, q_d, s_term, keys,
        statics=aot_statics(horizon),
    )
