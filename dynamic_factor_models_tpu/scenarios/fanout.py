"""Fan-out kernels: many scenarios / many draws as one vmapped program.

The paper's conditional-forecast and simulation-smoother machinery
(models/forecast.py, models/bayes.py) runs one scenario at a time; a
"what if oil +30%, across 10k draws" serving request is a fan of
thousands of such runs that differ only in a conditioning path or a PRNG
key.  Everything here vmaps the existing cores over stacked inputs —
no new numerics, just batch structure — and dispatches through the
utils.compile AOT registry so a `precompile(CompileSpec(scenario_draws=
...))` serves the whole fan from one executable, keyed on (bucket,
n_draws) via the traced shapes + the static horizon:

* `conditional_fan`  — S conditioning paths through the masked smoother
  (the `conditional_forecast` math, exactly; parity pinned at 1e-12).
* `draw_fan`         — S paths x D simulation-smoother draws: sampled
  factor paths + posterior-predictive observable fans per scenario.
* `stress_fan`       — S factor-shock vectors propagated through the
  companion dynamics on top of the baseline forecast.
* `forecast_fan`     — D forward-simulation draws from D parameter
  draws (the `posterior_forecast` kernel; bayes routes through here so
  posterior forecasts and scenario fans share one compiled program).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from ..models.bayes import (
    _simulation_smoother_core,
    _simulation_smoother_core_collapsed,
)
from ..models.ssm import (
    LARGE_N_THRESHOLD,
    SSMParams,
    _collapse_obs,
    _companion,
    _filter_scan,
    _filter_scan_collapsed_stats,
    _psd_floor,
    _psd_sqrt,
    _smoother_scan,
)
from ..ops.masking import fillz, mask_of

__all__ = [
    "conditional_fan",
    "draw_fan",
    "forecast_fan",
    "stress_fan",
    "extend_panel",
]


def _validate_conditions(x, horizon: int, conditions):
    """Shared condition-stack validation: returns (S, horizon, N) with NaN
    at unconstrained cells (None = one unconditional lane)."""
    if horizon < 1:
        raise ValueError(f"horizon must be >= 1, got {horizon}")
    N = x.shape[1]
    if conditions is None:
        return jnp.full((1, horizon, N), jnp.nan, x.dtype)
    cond = jnp.asarray(conditions, x.dtype)
    if cond.ndim == 2:
        cond = cond[None]
    if cond.ndim != 3 or cond.shape[1:] != (horizon, N):
        raise ValueError(
            f"conditions must be (S, horizon, N) = (*, {horizon}, {N}), "
            f"got {tuple(cond.shape)}"
        )
    return cond


def extend_panel(x, horizon: int, conditions=None):
    """Stack S condition paths onto a shared history: (S, T+h, N) panels.

    `conditions` (S, horizon, N) pins assumed future paths per scenario,
    NaN = unconstrained (None = one unconditional lane); the validation
    mirrors `forecast.conditional_forecast` so the fan and the loop
    reject the same inputs."""
    x = jnp.asarray(x)
    cond = _validate_conditions(x, horizon, conditions)
    S = cond.shape[0]
    x_ext = jnp.concatenate(
        [jnp.broadcast_to(x, (S,) + x.shape), cond], axis=1
    )
    return fillz(x_ext), mask_of(x_ext)


def _collapse_fan_stats(params: SSMParams, x, horizon: int, conditions):
    """Collapsed observation statistics of the whole fan: the HISTORY is
    collapsed ONCE — the one (T, N) projection every lane shares — and
    only each lane's h condition rows pay a per-lane collapse.  Returns
    (C (S, T+h, r, r), b (S, T+h, r), ld_R (S, T+h), xrx_sum (S,),
    n_obs (S, T+h)) — the memory footprint of a 1k-lane fan at N = 10k
    drops from the (S, T+h, N) panel stacks (~GBs) to the r-sized stacks
    (~MBs)."""
    x = jnp.asarray(x)
    cond = _validate_conditions(x, horizon, conditions)
    xh = fillz(x)
    mh = mask_of(x).astype(xh.dtype)
    Ch, bh, ldh, xrxh, noh = _collapse_obs(params.lam, params.R, xh, mh)
    xc = fillz(cond)
    mc = mask_of(cond).astype(xh.dtype)
    Cc, bc, ldc, xrxc, noc = jax.vmap(
        lambda xs, ms: _collapse_obs(params.lam, params.R, xs, ms)
    )(xc, mc)
    S = cond.shape[0]
    tile = lambda a: jnp.broadcast_to(a[None], (S,) + a.shape)
    C = jnp.concatenate([tile(Ch), Cc], axis=1)
    b = jnp.concatenate([tile(bh), bc], axis=1)
    ld = jnp.concatenate([tile(ldh), ldc], axis=1)
    no = jnp.concatenate([tile(noh), noc], axis=1)
    xrx_sum = xrxh.sum() + xrxc.sum(axis=1)  # (S,)
    return C, b, ld, xrx_sum, no


@partial(jax.jit, static_argnames=("horizon",))
def _conditional_fan_impl(params, xz_stack, mask_stack, horizon: int):
    """(mean, sd, factor_mean, factor_cov) per scenario — the
    `conditional_forecast` tail math vmapped over the stacked panels."""

    def one(xe, me):
        filt = _filter_scan(params, xe, me)
        sm, cov, _ = _smoother_scan(params, filt)
        r = params.r
        f = sm[-horizon:, :r]
        Pf = cov[-horizon:, :r, :r]
        mean = f @ params.lam.T
        var_common = jnp.einsum("nr,hrs,ns->hn", params.lam, Pf, params.lam)
        sd = jnp.sqrt(var_common + params.R[None, :])
        return mean, sd, f, Pf

    return jax.vmap(one)(xz_stack, mask_stack)


@partial(jax.jit, static_argnames=("horizon", "observables"))
def _conditional_fan_collapsed_impl(
    params, C, b, ld, xrx, no, horizon: int, observables: bool
):
    """Collapsed conditional fan: each lane filters/smooths the r*p-state
    collapsed statistics — no N-sized operand inside the vmapped scans.
    `observables=False` skips the (S, h, N) mean/sd projection entirely
    (the 10k-series outputs usually ARE the memory bill at large fans)."""

    def one(C_s, b_s, ld_s, xr_s, no_s):
        filt = _filter_scan_collapsed_stats(
            params, C_s, b_s, ld_s, no_s, -0.5 * xr_s
        )
        sm, cov, _ = _smoother_scan(params, filt)
        r = params.r
        return sm[-horizon:, :r], cov[-horizon:, :r, :r]

    f, Pf = jax.vmap(one)(C, b, ld, xrx, no)
    if not observables:
        return f, Pf
    mean = f @ params.lam.T
    var_common = jnp.einsum("nr,shrq,nq->shn", params.lam, Pf, params.lam)
    sd = jnp.sqrt(var_common + params.R[None, None, :])
    return mean, sd, f, Pf


def conditional_fan(
    params: SSMParams,
    x,
    horizon: int,
    conditions=None,
    collapsed: bool | None = None,
    observables: bool = True,
):
    """Conditional-forecast fan: S scenarios through ONE vmapped masked
    smoother.  Returns (mean (S, h, N), sd, factor_mean (S, h, r),
    factor_cov (S, h, r, r)); lane s equals
    `conditional_forecast(params, x, horizon, conditions[s])` to float
    tolerance (pinned at 1e-12).

    `collapsed` routes through the shared-projection variant: the history
    is collapsed once for ALL lanes and each lane's scan touches only
    r-sized statistics (default None auto-enables for
    N > ssm.LARGE_N_THRESHOLD — exact, not an approximation).
    `observables=False` returns just (factor_mean, factor_cov), keeping
    every output N-free."""
    from ..utils.compile import aot_call, aot_statics

    params = params._replace(Q=_psd_floor(params.Q))
    x = jnp.asarray(x)
    if collapsed is None:
        collapsed = x.shape[1] > LARGE_N_THRESHOLD
    if collapsed:
        stats = _collapse_fan_stats(params, x, horizon, conditions)
        return aot_call(
            "scenario_cond_fan_collapsed",
            lambda pa, *st: _conditional_fan_collapsed_impl(
                pa, *st, horizon=horizon, observables=observables
            ),
            params, *stats,
            statics=aot_statics(horizon, observables),
        )
    xz, mask = extend_panel(x, horizon, conditions)
    out = aot_call(
        "scenario_cond_fan",
        lambda pa, xe, me: _conditional_fan_impl(pa, xe, me, horizon),
        params, xz, mask,
        statics=aot_statics(horizon),
    )
    return out if observables else out[2:]


@partial(jax.jit, static_argnames=("horizon",))
def _draw_fan_impl(params, xz_stack, mask_stack, keys, horizon: int):
    """Simulation-smoother fan: draws x scenarios, one double vmap.

    keys (S, D, 2).  Returns (f_draws (S, D, h, r), y_draws (S, D, h, N),
    loglik (S, D)); y adds measurement noise to the drawn common
    component — genuine posterior-predictive paths per scenario."""

    def one_path(xe, me, ks):
        def one_draw(k):
            kf, ke = jax.random.split(k)
            f, ll = _simulation_smoother_core(params, xe, me, kf)
            fh = f[-horizon:]
            eps = jax.random.normal(
                ke, (horizon, params.lam.shape[0]), xe.dtype
            )
            y = fh @ params.lam.T + eps * jnp.sqrt(params.R)
            return fh, y, ll

        return jax.vmap(one_draw)(ks)

    return jax.vmap(one_path)(xz_stack, mask_stack, keys)


@partial(jax.jit, static_argnames=("horizon", "observables"))
def _draw_fan_collapsed_impl(
    params, C, b, ld, xrx, no, keys, horizon: int, observables: bool
):
    """Collapsed simulation-smoother fan: one shared collapse feeds every
    (lane, draw); each draw is ONE r*p-state filter+RTS pass on the
    mean-correction difference (see bayes._simulation_smoother_core_
    collapsed).  The real-data loglik is computed once per LANE — it is
    draw-independent — and broadcast across draws.  `observables=False`
    keeps the whole fan N-free (no (S, D, h, N) panel ever built)."""

    def one_path(C_s, b_s, ld_s, xr_s, no_s, ks):
        ll_corr = -0.5 * xr_s
        filt = _filter_scan_collapsed_stats(
            params, C_s, b_s, ld_s, no_s, ll_corr
        )
        sqrtC = _psd_sqrt(C_s)

        def one_draw(k):
            kf, ke = jax.random.split(k)
            f = _simulation_smoother_core_collapsed(
                params, C_s, b_s, ld_s, no_s, ll_corr, sqrtC, kf
            )
            fh = f[-horizon:]
            if not observables:
                return fh
            eps = jax.random.normal(
                ke, (horizon, params.lam.shape[0]), b_s.dtype
            )
            y = fh @ params.lam.T + eps * jnp.sqrt(params.R)
            return fh, y

        out = jax.vmap(one_draw)(ks)
        ll = jnp.broadcast_to(filt.loglik, (ks.shape[0],))
        if not observables:
            return out, ll
        return out[0], out[1], ll

    return jax.vmap(one_path)(C, b, ld, xrx, no, keys)


def draw_fan(
    params: SSMParams,
    x,
    horizon: int,
    n_draws: int,
    conditions=None,
    seed: int = 0,
    collapsed: bool | None = None,
    observables: bool = True,
):
    """Sampled scenario fans: for each of S conditioning paths, D
    Durbin-Koopman factor-path draws + posterior-predictive observable
    paths over the horizon.  One compiled program for the whole
    S x D fan (kernel "scenario_draw_fan").

    `collapsed` (default None = auto for N > ssm.LARGE_N_THRESHOLD)
    shares one observation collapse across the fan and draws through the
    N-free one-scan DK core — same posterior, different PRNG stream, so
    draws match the dense path in DISTRIBUTION, not elementwise.
    `observables=False` drops the (S, D, h, N) predictive panel from the
    outputs, returning (f_draws, loglik)."""
    from ..utils.compile import aot_call, aot_statics

    if n_draws < 1:
        raise ValueError(f"n_draws must be >= 1, got {n_draws}")
    params = params._replace(Q=_psd_floor(params.Q))
    x = jnp.asarray(x)
    if collapsed is None:
        collapsed = x.shape[1] > LARGE_N_THRESHOLD
    if collapsed:
        stats = _collapse_fan_stats(params, x, horizon, conditions)
        S = stats[0].shape[0]
        keys = jax.random.split(
            jax.random.PRNGKey(seed), S * n_draws
        ).reshape(S, n_draws, 2)
        return aot_call(
            "scenario_draw_fan_collapsed",
            lambda pa, *a: _draw_fan_collapsed_impl(
                pa, *a, horizon=horizon, observables=observables
            ),
            params, *stats, keys,
            statics=aot_statics(horizon, observables),
        )
    xz, mask = extend_panel(x, horizon, conditions)
    S = xz.shape[0]
    keys = jax.random.split(
        jax.random.PRNGKey(seed), S * n_draws
    ).reshape(S, n_draws, 2)
    out = aot_call(
        "scenario_draw_fan",
        lambda pa, xe, me, ks: _draw_fan_impl(pa, xe, me, ks, horizon),
        params, xz, mask, keys,
        statics=aot_statics(horizon),
    )
    return out if observables else (out[0], out[2])


@partial(jax.jit, static_argnames=("horizon",))
def _stress_fan_impl(params, shocks, horizon: int):
    """Factor-shock responses: propagate each (r,) innovation impulse
    through the companion dynamics, map to observables.  (S, h, N)."""
    Tm, _ = _companion(params)
    k = Tm.shape[0]
    r = params.r

    def one(delta):
        s0 = jnp.zeros((k,), delta.dtype).at[:r].set(delta)

        def step(s, _):
            return Tm @ s, s[:r]

        _, fpath = jax.lax.scan(step, s0, None, length=horizon)
        return fpath, fpath @ params.lam.T

    return jax.vmap(one)(shocks)


def stress_fan(params: SSMParams, x, horizon: int, shocks):
    """Stress-path fan: shock the factor innovations by each row of
    `shocks` (S, r) at the forecast origin and propagate.  Returns
    (mean (S, h, N), sd (S, h, N), factor_mean (S, h, r)) where mean =
    baseline conditional mean + shock response — linearity of the state
    space makes the superposition exact, so one baseline smoother run
    serves every stress lane."""
    shocks = jnp.asarray(shocks)
    if shocks.ndim == 1:
        shocks = shocks[None]
    if shocks.ndim != 2 or shocks.shape[1] != params.r:
        raise ValueError(
            f"shocks must be (S, r) = (*, {params.r}), got "
            f"{tuple(shocks.shape)}"
        )
    base_mean, base_sd, base_f, _ = conditional_fan(params, x, horizon)
    f_shift, y_shift = _stress_fan_impl(params, shocks, horizon)
    return (
        base_mean + y_shift,
        jnp.broadcast_to(base_sd, y_shift.shape),
        base_f + f_shift,
    )


def _forecast_one(lam_i, R_i, A_i, Q_i, s, key, horizon: int):
    """One posterior-predictive forward simulation: iterate the factor
    VAR from terminal companion state `s` with fresh innovations, add
    measurement noise.  (h, N) in standardized units."""
    params = SSMParams(lam=lam_i, R=R_i, A=A_i, Q=_psd_floor(Q_i))
    Tm, _ = _companion(params)
    r = params.r
    ku, ke = jax.random.split(key)
    Lq = jnp.linalg.cholesky(params.Q)
    u = jax.random.normal(ku, (horizon, r), lam_i.dtype) @ Lq.T

    def step(s_prev, u_t):
        s_t = (Tm @ s_prev).at[:r].add(u_t)
        return s_t, s_t[:r]

    _, f_path = jax.lax.scan(step, s, u)
    eps = jax.random.normal(ke, (horizon, lam_i.shape[0]), lam_i.dtype)
    return f_path @ lam_i.T + eps * jnp.sqrt(R_i)


@partial(jax.jit, static_argnames=("horizon",))
def _forecast_fan_impl(lam_d, r_d, a_d, q_d, s_term, keys, horizon: int):
    return jax.vmap(
        lambda l, R, A, Q, s, k: _forecast_one(l, R, A, Q, s, k, horizon)
    )(lam_d, r_d, a_d, q_d, s_term, keys)


def forecast_fan(lam_d, r_d, a_d, q_d, s_term, keys, horizon: int):
    """Forward-simulation fan over D parameter draws (kernel
    "scenario_fan"): the `posterior_forecast` device program, shared
    with scenario draw requests.  lam_d (D, N, r), r_d (D, N), a_d
    (D, p, r, r), q_d (D, r, r), s_term (D, r*p), keys (D, 2); returns
    (D, h, N) standardized predictive draws."""
    from ..utils.compile import aot_call, aot_statics

    return aot_call(
        "scenario_fan",
        lambda *a: _forecast_fan_impl(*a, horizon=horizon),
        lam_d, r_d, a_d, q_d, s_term, keys,
        statics=aot_statics(horizon),
    )
