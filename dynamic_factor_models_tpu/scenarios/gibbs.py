"""Guarded multi-chain Gibbs: n_chains DFM samplers in one device program.

`models/bayes._chain` runs one Gibbs chain as a pair of ``lax.scan``s
(carry-only burn-in, then a keep-phase scan materializing every thin-th
sweep).  Here the same sweep — key-split for key-split, so a healthy run
reproduces the single-chain draws — advances ALL chains together: the
scans stay on the outside and every sweep body is one
``jax.vmap(_gibbs_sweep)`` over the chain axis, the structure of the
batched multi-tenant EM loop (models/emloop._em_while_batched_impl).

The point of the restructure is the per-chain health sentinel.  Gibbs
log-likelihoods are stochastic, so unlike EM there is no monotonicity
check — but a non-finite draw (exploding factor path, failed Cholesky)
means the chain has left the posterior and every subsequent sweep is
garbage.  After each vmapped sweep a per-lane finiteness check
(utils.guards.batched_tree_finite) marks such chains: the lane's carry is
rolled back to the last-good (key, params) and FROZEN — subsequent
sweeps still ride through the vmapped body (batched shapes are static)
but every result is discarded by the per-lane select, so surviving
chains' draws are bit-identical to a run without the divergence (vmap is
elementwise across lanes; pinned by tests/test_scenario_engine.py).  The
caller drops frozen chains from the posterior host-side.

``DFM_FAULTS=nan_draw@k`` (utils/faults) NaNs chain 0's k-th sweep's
factor draw — the deterministic divergent-chain drill.  The injection is
a compiled STATIC: 0 compiles no injection code, so production programs
are byte-identical to pre-guard ones.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..models.bayes import _gibbs_sweep
from ..utils import faults as _faults
from ..utils import guards as _guards
from ..utils.telemetry import run_record

__all__ = ["MultiChainResult", "sample_chains"]


class MultiChainResult(NamedTuple):
    """Stacked multi-chain Gibbs output, chain axis leading everywhere.

    `health` carries utils.guards codes per chain (0 ok; HEALTH_NONFINITE
    means the chain was rolled back and frozen at the flagged sweep — its
    draws are stale repeats of the last-good state and must be excluded
    from the posterior).  `loglik_path` keeps ALL chains, frozen included
    (a frozen lane shows the injected/diverged sweep, then a constant
    tail) — the diagnostic trace, not the posterior."""

    factor_draws: jnp.ndarray  # (chains, keep, T, r)
    lam_draws: jnp.ndarray  # (chains, keep, N, r)
    r_draws: jnp.ndarray  # (chains, keep, N)
    a_draws: jnp.ndarray  # (chains, keep, p, r, r)
    q_draws: jnp.ndarray  # (chains, keep, r, r)
    loglik_path: jnp.ndarray  # (chains, n_burn + n_keep*thin)
    health: np.ndarray  # (chains,) guards codes


@partial(
    jax.jit, static_argnames=("n_burn", "n_keep", "thin", "p", "inject_at")
)
def _multi_chain(
    keys,
    params0,
    xz,
    m,
    n_burn: int,
    n_keep: int,
    thin: int,
    p: int,
    priors: tuple,
    inject_at: int = 0,
):
    """All chains through the burn + keep scans together, guarded.

    `keys` (C, 2) per-chain PRNG keys (shard this axis over a mesh to
    spread chains across devices); `params0` the shared init (broadcast
    to the chain axis inside).  Sweep indices ride the scans as xs so the
    global 1-based sweep number reaches the injection site; memory holds
    n_keep draws per chain, exactly like the single-chain program."""
    C = keys.shape[0]
    params_C = jax.tree_util.tree_map(
        lambda a: jnp.broadcast_to(a, (C,) + a.shape), params0
    )
    vsweep = jax.vmap(
        lambda k, pa: _gibbs_sweep((k, pa), xz, m, p, priors)
    )

    def gsweep(carry, i):
        (ks, ps), health = carry
        (nks, nps), (f, lam, R, A, Q, ll) = vsweep(ks, ps)
        if inject_at:
            hit = i + 1 == inject_at
            f = f.at[0].set(
                jnp.where(hit, jnp.full_like(f[0], jnp.nan), f[0])
            )
            ll = ll.at[0].set(jnp.where(hit, jnp.nan, ll[0]))
        finite = _guards.batched_tree_finite((f, lam, R, A, Q)) & (
            jnp.isfinite(ll)
        )
        ok = health == _guards.HEALTH_OK
        adv = ok & finite
        ks2, ps2 = _guards.batched_where(adv, (nks, nps), (ks, ps))
        health = jnp.where(
            ok & ~finite, _guards.HEALTH_NONFINITE, health
        ).astype(jnp.int32)
        return ((ks2, ps2), health), (f, lam, R, A, Q, ll)

    def sweep_ll(carry, i):
        carry, outs = gsweep(carry, i)
        return carry, outs[5]

    def keep_body(carry, base):
        carry, lls_thin = jax.lax.scan(
            sweep_ll, carry, base + jnp.arange(thin - 1)
        )
        carry, outs = gsweep(carry, base + thin - 1)
        return carry, (
            outs[:5],
            jnp.concatenate([lls_thin, outs[5][None]], axis=0),
        )

    carry = ((keys, params_C), jnp.zeros((C,), jnp.int32))
    carry, ll_burn = jax.lax.scan(sweep_ll, carry, jnp.arange(n_burn))
    bases = n_burn + jnp.arange(n_keep) * thin
    carry, (kept, ll_keep) = jax.lax.scan(keep_body, carry, bases)
    _, health = carry
    # scan stacks sweeps leading: (keep, C, ...) -> (C, keep, ...);
    # lls (n_burn, C) + (keep, thin, C) -> (C, n_burn + keep*thin)
    kept = tuple(jnp.swapaxes(a, 0, 1) for a in kept)
    lls = jnp.concatenate(
        [ll_burn, ll_keep.reshape(-1, C)], axis=0
    ).T
    return kept + (lls, health)


def sample_chains(
    keys,
    params0,
    xz,
    m,
    n_burn: int,
    n_keep: int,
    thin: int,
    p: int,
    priors: tuple,
) -> MultiChainResult:
    """Run the guarded multi-chain sampler; the `estimate_dfm_bayes`
    device path.  Applies the active fault plan (``nan_draw@k``) as a
    compile-time static and brackets the run in a RunRecord so divergent
    chains show up in `telemetry summarize` next to EM faults."""
    plan = _faults.active_plan()
    inject_at = plan.nan_draw or 0
    C = int(keys.shape[0])
    with run_record(
        "gibbs_multichain",
        kind="scenario",
        config={
            "n_chains": C,
            "n_sweeps": n_burn + n_keep * thin,
            "n_keep": n_keep,
        },
    ) as rec:
        if inject_at:
            _faults.fault_fired("nan_draw")
        f, lam, R, A, Q, lls, health = _multi_chain(
            keys, params0, xz, m, n_burn, n_keep, thin, p, priors,
            inject_at,
        )
        health = np.asarray(health)
        n_bad = int((health != _guards.HEALTH_OK).sum())
        if n_bad:
            from ..utils.telemetry import inc

            inc("gibbs_guard.chains_dropped", n_bad)
        rec.set(
            final_loglik=float(np.asarray(lls)[health == 0, -1].max())
            if (health == 0).any()
            else None,
            chains_unhealthy=n_bad,
            faults_detected=n_bad or None,
        )
    return MultiChainResult(f, lam, R, A, Q, lls, health)
