"""ScenarioRequest/ScenarioResult: the serving surface of the fan-out.

One request describes one fan — a set of conditioning paths, stress
shocks, or news targets plus an optional draw count — and `run_scenario`
dispatches it to the right kernel.  `serving/engine.py` routes
``{"kind": "scenario", "tenant": id, "scenario": {...}}`` dicts here,
RunRecord-bracketed with kind="scenario" so scenario traffic shows up in
`telemetry summarize` next to ticks and refits.

Request kinds:

    conditional_fan  S conditioning paths -> smoothed mean/sd fans
                     (+ a posterior-predictive draw fan when n_draws > 0)
    stress           S factor-shock vectors -> shifted forecast fans
    draw_fan         S paths x n_draws simulation-smoother draws
    news             batched nowcast-news decomposition over targets
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp

from ..models.ssm import SSMParams
from ..utils.telemetry import run_record
from . import fanout

__all__ = ["ScenarioRequest", "ScenarioResult", "run_scenario", "KINDS"]

KINDS = ("conditional_fan", "stress", "draw_fan", "news")


class ScenarioRequest(NamedTuple):
    """One scenario fan.  Unused fields stay None/0 per kind:
    `conditions` (S, horizon, N) NaN-unconstrained paths
    (conditional_fan / draw_fan; None = one unconditional lane);
    `shocks` (S, r) factor-innovation impulses (stress); `x_new` +
    `targets` the new vintage and (n_tgt, 2) target entries (news)."""

    kind: str
    horizon: int = 12
    conditions: object | None = None
    shocks: object | None = None
    n_draws: int = 0
    seed: int = 0
    x_new: object | None = None
    targets: object | None = None


class ScenarioResult(NamedTuple):
    """Fan output; populated fields depend on the request kind.  mean/sd
    are (S, horizon, N); factor_mean (S, horizon, r); draws
    (S, n_draws, horizon, N) posterior-predictive paths; news is a
    models.news.NowcastNewsBatch for kind="news"."""

    kind: str
    mean: jnp.ndarray | None = None
    sd: jnp.ndarray | None = None
    factor_mean: jnp.ndarray | None = None
    factor_cov: jnp.ndarray | None = None
    draws: jnp.ndarray | None = None
    factor_draws: jnp.ndarray | None = None
    news: object | None = None


def run_scenario(
    params: SSMParams, x, req: ScenarioRequest
) -> ScenarioResult:
    """Dispatch one ScenarioRequest against a fitted model and its
    (standardized) panel.  Each kind is one or two vmapped device
    programs (scenarios/fanout.py) — never a host loop over scenarios
    or draws."""
    if req.kind not in KINDS:
        raise ValueError(
            f"unknown scenario kind {req.kind!r}; valid: {', '.join(KINDS)}"
        )
    with run_record(
        "scenario",
        kind=req.kind,
        config={
            "horizon": int(req.horizon),
            "n_draws": int(req.n_draws or 0),
        },
    ) as rec:
        if req.kind == "conditional_fan":
            mean, sd, f, Pf = fanout.conditional_fan(
                params, x, req.horizon, req.conditions
            )
            draws = f_draws = None
            if req.n_draws:
                f_draws, draws, _ = fanout.draw_fan(
                    params, x, req.horizon, req.n_draws,
                    conditions=req.conditions, seed=req.seed,
                )
            rec.set(n_paths=int(mean.shape[0]))
            return ScenarioResult(
                req.kind, mean=mean, sd=sd, factor_mean=f,
                factor_cov=Pf, draws=draws, factor_draws=f_draws,
            )
        if req.kind == "stress":
            if req.shocks is None:
                raise ValueError("stress scenarios need `shocks` (S, r)")
            mean, sd, f = fanout.stress_fan(
                params, x, req.horizon, req.shocks
            )
            rec.set(n_paths=int(mean.shape[0]))
            return ScenarioResult(
                req.kind, mean=mean, sd=sd, factor_mean=f
            )
        if req.kind == "draw_fan":
            n_draws = int(req.n_draws or 0)
            if n_draws < 1:
                raise ValueError("draw_fan needs n_draws >= 1")
            f_draws, draws, _ = fanout.draw_fan(
                params, x, req.horizon, n_draws,
                conditions=req.conditions, seed=req.seed,
            )
            rec.set(n_paths=int(draws.shape[0]), n_draws=n_draws)
            return ScenarioResult(
                req.kind,
                mean=draws.mean(axis=1),
                sd=draws.std(axis=1),
                draws=draws,
                factor_draws=f_draws,
            )
        # news
        if req.x_new is None or req.targets is None:
            raise ValueError("news scenarios need `x_new` and `targets`")
        from ..models.news import nowcast_news_batch

        nb = nowcast_news_batch(params, x, req.x_new, req.targets)
        rec.set(n_paths=int(nb.targets.shape[0]))
        return ScenarioResult(req.kind, news=nb)
