"""ScenarioRequest/ScenarioResult: the serving surface of the fan-out.

One request describes one fan — a set of conditioning paths, stress
shocks, or news targets plus an optional draw count — and `run_scenario`
dispatches it to the right kernel.  `serving/engine.py` routes
``{"kind": "scenario", "tenant": id, "scenario": {...}}`` dicts here,
RunRecord-bracketed with kind="scenario" so scenario traffic shows up in
`telemetry summarize` next to ticks and refits.

Request kinds:

    conditional_fan  S conditioning paths -> smoothed mean/sd fans
                     (+ a posterior-predictive draw fan when n_draws > 0)
    stress           S factor-shock vectors -> shifted forecast fans
    draw_fan         S paths x n_draws simulation-smoother draws
    news             batched nowcast-news decomposition over targets
    nowcast_density  particle quantile-BAND densities from the SMC
                     subsystem (scenarios/smc.py) under `model` ("sv"
                     stochastic volatility, "tvp" drifting loadings,
                     "lg" the linear-Gaussian check model) — densities,
                     not point nowcasts
    regime_stress    Markov-switching stress fans: shocks applied with
                     the latent regime distributed per `msdfm.kim_filter`
                     filtered probabilities (model="msdfm")
    hierarchical     multilevel scenarios: shock a GLOBAL factor, fan the
                     response out per block (model="multilevel")

Validation raises `ScenarioValidationError`, a ValueError subclass that
NAMES the offending request field (`.field`) — the serving engine maps
it onto the `serving/resilience.ErrorInfo.field` slot, so a malformed
scenario comes back as a typed client error pointing at the exact field
instead of a generic message.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp
import numpy as np

from ..models.ssm import SSMParams
from ..utils.telemetry import run_record
from . import fanout

__all__ = [
    "ScenarioRequest",
    "ScenarioResult",
    "ScenarioValidationError",
    "run_scenario",
    "KINDS",
    "NL_KINDS",
]

KINDS = (
    "conditional_fan", "stress", "draw_fan", "news",
    "nowcast_density", "regime_stress", "hierarchical",
)
# the particle/nonlinear kinds added with the SMC subsystem; the first
# four dispatch to scenarios/fanout.py exactly as before (their device
# programs are untouched — the clean-path HLO pin)
NL_KINDS = ("nowcast_density", "regime_stress", "hierarchical")

_NL_MODELS = {
    "nowcast_density": ("sv", "lg", "tvp"),  # first entry = default
    "regime_stress": ("msdfm",),
    "hierarchical": ("multilevel",),
}


class ScenarioValidationError(ValueError):
    """A malformed ScenarioRequest; `field` names the offending request
    field (the `serving/resilience.ErrorInfo.field` convention, so the
    engine's typed client-error envelope can point at it)."""

    def __init__(self, field: str, message: str):
        super().__init__(message)
        self.field = field


def _fail(field: str, message: str):
    raise ScenarioValidationError(field, message)


class ScenarioRequest(NamedTuple):
    """One scenario fan.  Unused fields stay None/0 per kind:
    `conditions` (S, horizon, N) NaN-unconstrained paths
    (conditional_fan / draw_fan; None = one unconditional lane);
    `shocks` (S, r) factor-innovation impulses (stress / the particle
    kinds; regime_stress shocks the scalar factor, so (S, 1)); `x_new` +
    `targets` the new vintage and (n_tgt, 2) target entries (news).

    The nonlinear kinds add: `model` selecting the particle model
    (default per kind — see NL_KINDS in the module docstring),
    `particles` the particle count (0 = default 1024), `ess_floor` the
    adaptive-resampling ESS fraction, `quantiles` the density-band
    levels (None = (.05, .25, .5, .75, .95)), `blocks` the per-block
    column-index lists (hierarchical), and `model_config` a dict of
    model knobs (sv: mu_h/phi_h/sig_h; tvp: q; msdfm: msdfm_params to
    skip the fit, or fit_steps/fit_restarts; hierarchical: r_global /
    r_block)."""

    kind: str
    horizon: int = 12
    conditions: object | None = None
    shocks: object | None = None
    n_draws: int = 0
    seed: int = 0
    x_new: object | None = None
    targets: object | None = None
    model: str | None = None
    particles: int = 0
    ess_floor: float = 0.5
    quantiles: object | None = None
    blocks: object | None = None
    model_config: dict | None = None


class ScenarioResult(NamedTuple):
    """Fan output; populated fields depend on the request kind.  mean/sd
    are (S, horizon, N); factor_mean (S, horizon, r); draws
    (S, n_draws, horizon, N) posterior-predictive paths; news is a
    models.news.NowcastNewsBatch for kind="news".

    The particle kinds return density BANDS instead of draws:
    `bands` (S, horizon, n_quantiles, N) predictive quantile bands at
    the `quantiles` levels, plus per-lane weight/ESS telemetry —
    `ess` (S, T) the pre-resample effective-sample-size trace,
    `ess_min` (S,) its per-lane minimum, `resample_rate` (S,) the
    ESS-floor trip rate, `health` (S,) utils.guards codes (a frozen
    degenerate lane reports nonzero health; its bands are stale) —
    and kind-specific extras: `regime_probs` (T, M) Kim-filtered regime
    probabilities (regime_stress), `block_means` (S, horizon, n_blocks)
    per-block mean responses (hierarchical)."""

    kind: str
    mean: jnp.ndarray | None = None
    sd: jnp.ndarray | None = None
    factor_mean: jnp.ndarray | None = None
    factor_cov: jnp.ndarray | None = None
    draws: jnp.ndarray | None = None
    factor_draws: jnp.ndarray | None = None
    news: object | None = None
    bands: jnp.ndarray | None = None
    quantiles: tuple | None = None
    ess: jnp.ndarray | None = None
    ess_min: jnp.ndarray | None = None
    resample_rate: jnp.ndarray | None = None
    health: np.ndarray | None = None
    regime_probs: jnp.ndarray | None = None
    block_means: jnp.ndarray | None = None


def run_scenario(
    params: SSMParams, x, req: ScenarioRequest
) -> ScenarioResult:
    """Dispatch one ScenarioRequest against a fitted model and its
    (standardized) panel.  Each kind is one or two vmapped device
    programs (scenarios/fanout.py for the linear-Gaussian kinds,
    scenarios/smc.py's guarded multi-lane particle filter for the
    nonlinear ones) — never a host loop over scenarios or draws."""
    if req.kind not in KINDS:
        _fail(
            "kind",
            f"unknown scenario kind {req.kind!r}; valid: {', '.join(KINDS)}",
        )
    if req.kind in NL_KINDS:
        return _run_nonlinear(params, x, req)
    with run_record(
        "scenario",
        kind=req.kind,
        config={
            "horizon": int(req.horizon),
            "n_draws": int(req.n_draws or 0),
        },
    ) as rec:
        if req.kind == "conditional_fan":
            mean, sd, f, Pf = fanout.conditional_fan(
                params, x, req.horizon, req.conditions
            )
            draws = f_draws = None
            if req.n_draws:
                f_draws, draws, _ = fanout.draw_fan(
                    params, x, req.horizon, req.n_draws,
                    conditions=req.conditions, seed=req.seed,
                )
            rec.set(n_paths=int(mean.shape[0]))
            return ScenarioResult(
                req.kind, mean=mean, sd=sd, factor_mean=f,
                factor_cov=Pf, draws=draws, factor_draws=f_draws,
            )
        if req.kind == "stress":
            if req.shocks is None:
                _fail("shocks", "stress scenarios need `shocks` (S, r)")
            mean, sd, f = fanout.stress_fan(
                params, x, req.horizon, req.shocks
            )
            rec.set(n_paths=int(mean.shape[0]))
            return ScenarioResult(
                req.kind, mean=mean, sd=sd, factor_mean=f
            )
        if req.kind == "draw_fan":
            n_draws = int(req.n_draws or 0)
            if n_draws < 1:
                _fail("n_draws", "draw_fan needs n_draws >= 1")
            f_draws, draws, _ = fanout.draw_fan(
                params, x, req.horizon, n_draws,
                conditions=req.conditions, seed=req.seed,
            )
            rec.set(n_paths=int(draws.shape[0]), n_draws=n_draws)
            return ScenarioResult(
                req.kind,
                mean=draws.mean(axis=1),
                sd=draws.std(axis=1),
                draws=draws,
                factor_draws=f_draws,
            )
        # news
        if req.x_new is None:
            _fail("x_new", "news scenarios need `x_new` and `targets`")
        if req.targets is None:
            _fail("targets", "news scenarios need `x_new` and `targets`")
        from ..models.news import nowcast_news_batch

        nb = nowcast_news_batch(params, x, req.x_new, req.targets)
        rec.set(n_paths=int(nb.targets.shape[0]))
        return ScenarioResult(req.kind, news=nb)


def _validate_nl(params, req: ScenarioRequest):
    """Shared validation of the nonlinear-kind knobs; returns the
    resolved (model, particles, quantiles, ess_floor, config)."""
    valid = _NL_MODELS[req.kind]
    model = req.model or valid[0]
    if model not in valid:
        _fail(
            "model",
            f"scenario kind {req.kind!r} needs model in "
            f"{valid}; got {model!r}",
        )
    particles = int(req.particles or 1024)
    if particles < 2:
        _fail("particles", f"particles must be >= 2, got {req.particles}")
    ess_floor = float(req.ess_floor)
    if not 0.0 < ess_floor <= 1.0:
        _fail(
            "ess_floor",
            f"ess_floor must be in (0, 1], got {req.ess_floor}",
        )
    from . import smc as _smc

    if req.quantiles is None:
        quantiles = _smc.DEFAULT_QUANTILES
    else:
        quantiles = tuple(float(q) for q in np.asarray(req.quantiles).ravel())
        if not quantiles or any(not 0.0 < q < 1.0 for q in quantiles):
            _fail(
                "quantiles",
                "quantiles must be a non-empty sequence inside (0, 1)",
            )
    if int(req.horizon) < 1:
        _fail("horizon", f"horizon must be >= 1, got {req.horizon}")
    config = req.model_config or {}
    if not isinstance(config, dict):
        _fail(
            "model_config",
            f"model_config must be a dict, got {type(config).__name__}",
        )
    return model, particles, quantiles, ess_floor, config


def _nl_shocks(req: ScenarioRequest, sd: int, required: bool):
    """Coerce/validate the stress shocks for a particle kind; returns a
    (S, sd) float array (S = 1 unshocked lane when optional + absent)."""
    if req.shocks is None:
        if required:
            _fail("shocks", f"{req.kind} scenarios need `shocks` (S, {sd})")
        return None
    shocks = np.asarray(req.shocks, float)
    if shocks.ndim == 1:
        shocks = shocks[:, None] if sd == 1 else shocks[None, :]
    if shocks.ndim != 2 or shocks.shape[1] != sd:
        _fail(
            "shocks",
            f"{req.kind} shocks must be (S, {sd}), "
            f"got {tuple(shocks.shape)}",
        )
    return shocks


def _particle_result(req, res, quantiles, **extra) -> ScenarioResult:
    """Fold an smc.SMCResult into the ScenarioResult envelope with the
    per-lane weights/ESS telemetry every particle kind reports."""
    return ScenarioResult(
        req.kind,
        mean=res.mean,
        sd=res.sd,
        bands=res.bands,
        quantiles=tuple(quantiles),
        ess=res.ess,
        ess_min=res.ess.min(axis=1),
        resample_rate=res.resampled.mean(axis=1),
        health=res.health,
        **extra,
    )


def _rec_particles(rec, res, particles: int) -> None:
    rec.set(
        n_paths=int(res.ess.shape[0]),
        n_particles=int(particles),
        ess_min=float(np.asarray(res.ess.min())),
        faults_detected=int((res.health != 0).sum()) or None,
    )


def _run_nonlinear(params, x, req: ScenarioRequest) -> ScenarioResult:
    model, particles, quantiles, ess_floor, config = _validate_nl(params, req)
    from . import smc as _smc

    with run_record(
        "scenario",
        kind=req.kind,
        config={
            "horizon": int(req.horizon),
            "model": model,
            "particles": particles,
        },
    ) as rec:
        if req.kind == "nowcast_density":
            r = params.r
            shocks = _nl_shocks(req, _smc.shock_dim(model, r), required=False)
            aux = ()
            if model == "sv":
                to_r = lambda v, d: jnp.broadcast_to(  # noqa: E731
                    jnp.asarray(float(config.get(v, d))), (r,)
                ).astype(params.lam.dtype)
                aux = (to_r("mu_h", 0.0), to_r("phi_h", 0.95),
                       to_r("sig_h", 0.2))
            elif model == "tvp":
                from ..models.ssm import kalman_filter

                F = kalman_filter(params, x).means[:, :r]
                aux = (F, jnp.asarray(float(config.get("q", 1e-3)),
                                      params.lam.dtype))
            res = _smc.smc_filter(
                params, x, model=model, aux=aux, n_particles=particles,
                n_lanes=1 if shocks is None else None, shocks=shocks,
                horizon=int(req.horizon), quantiles=quantiles,
                ess_frac=ess_floor, seed=int(req.seed),
            )
            _rec_particles(rec, res, particles)
            return _particle_result(req, res, quantiles)

        if req.kind == "regime_stress":
            from ..models.msdfm import MSDFMParams, kim_filter
            from ..ops.masking import mask_of

            shocks = _nl_shocks(req, 1, required=True)
            mp = config.get("msdfm_params")
            if mp is not None:
                mp = MSDFMParams(*[jnp.asarray(a) for a in mp])
                xs = jnp.asarray(x)
            else:
                from ..models.msdfm import fit_ms_dfm

                fit = fit_ms_dfm(
                    x,
                    n_regimes=int(config.get("n_regimes", 2)),
                    n_steps=int(config.get("fit_steps", 300)),
                    n_restarts=int(config.get("fit_restarts", 1)),
                    seed=int(req.seed),
                )
                mp = fit.params
                # the fit standardizes internally; filter the same panel
                xs = (jnp.asarray(x) - fit.means) / fit.stds
            _, filt_probs, _, _, _ = kim_filter(
                mp, jnp.nan_to_num(xs), mask_of(xs)
            )
            res = _smc.smc_filter(
                mp, xs, model="msdfm", n_particles=particles,
                shocks=shocks, horizon=int(req.horizon),
                quantiles=quantiles, ess_frac=ess_floor,
                seed=int(req.seed),
            )
            _rec_particles(rec, res, particles)
            return _particle_result(
                req, res, quantiles, regime_probs=filt_probs
            )

        # hierarchical (model == "multilevel")
        if req.blocks is None:
            _fail(
                "blocks",
                "hierarchical scenarios need `blocks` (per-block "
                "column-index lists)",
            )
        try:
            blocks = [np.asarray(b, int) for b in req.blocks]
        except (TypeError, ValueError):
            _fail("blocks", "blocks must be a sequence of index sequences")
        if not blocks or any(b.ndim != 1 or b.size == 0 for b in blocks):
            _fail("blocks", "blocks must be non-empty index sequences")
        r_global = int(config.get("r_global", 1))
        shocks = _nl_shocks(req, r_global, required=True)
        from ..models.multilevel import estimate_multilevel_dfm

        mr = estimate_multilevel_dfm(
            x, blocks, r_global, int(config.get("r_block", 1)),
            max_outer=int(config.get("max_outer", 50)),
        )
        gf = np.asarray(mr.global_factors)  # (T, r_g)
        # AR(1) persistence per global factor drives the impulse decay
        num = (gf[1:] * gf[:-1]).sum(axis=0)
        den = (gf[:-1] ** 2).sum(axis=0)
        rho = np.clip(num / np.maximum(den, 1e-12), -0.99, 0.99)
        H = int(req.horizon)
        decay = rho[None, :] ** np.arange(H)[:, None]  # (H, r_g)
        f_path = shocks[:, None, :] * decay[None, :, :]  # (S, H, r_g)
        gl = np.asarray(mr.global_loadings)  # (N, r_g)
        mean = np.einsum("shr,nr->shn", f_path, gl)
        block_means = np.stack(
            [mean[:, :, b].mean(axis=2) for b in blocks], axis=2
        )
        rec.set(n_paths=int(mean.shape[0]))
        return ScenarioResult(
            req.kind,
            mean=jnp.asarray(mean),
            factor_mean=jnp.asarray(f_path),
            block_means=jnp.asarray(block_means),
        )
