"""Pure SMC kernel primitives, BlackJAX-style: small stateless functions.

Each primitive does ONE thing on a flat particle axis and composes into
the `scenarios/smc.py` scan program exactly like the transform stack
composes EM pieces — policy (when to resample, whether to jitter
parameters) lives in the caller, numerics live here, and nothing in this
module knows about lanes, guards, or serving.

Conventions:

* log-weights are carried UN-exponentiated everywhere; `normalize_logw`
  is the only place a normalizer is computed, so the particle loglik
  estimator (sum of per-step increments) and the ESS share one numeric
  path;
* resampling is systematic via the sorted-uniform construction: the
  stratified uniforms ``(i + u)/P`` are already sorted, so the inverse
  CDF lookup is one cumulative-sum scan plus one monotone merge
  (`jnp.searchsorted`) — no per-particle host loop, no O(P^2) compare;
* `adaptive_resample` wraps the resampler in a ``lax.cond`` on the
  effective sample size, so the clean-path HLO contains both branches
  but executes the cheap one when the ESS is healthy — under an outer
  ``vmap`` over scenario lanes the cond lowers to a per-lane select,
  which is exactly the lane-isolation property the degenerate-lane
  drill pins;
* `liu_west_jitter` is the opt-in parameter-learning kernel (Liu-West
  kernel shrinkage / Storvik-style rejuvenation): it never runs unless a
  model asks for it, so state-only filters pay nothing.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.scipy.special import logsumexp

__all__ = [
    "normalize_logw",
    "ess_of",
    "systematic_indices",
    "systematic_resample",
    "adaptive_resample",
    "liu_west_jitter",
]


def normalize_logw(logw: jnp.ndarray):
    """Normalize log-weights; returns (normalized logw, log normalizer).

    The normalizer ``logsumexp(logw)`` is the per-step marginal-likelihood
    increment when `logw` entered as (previous normalized weights +
    observation log-density), which is how the smc.py scan calls it."""
    lse = logsumexp(logw)
    return logw - lse, lse


def ess_of(logw: jnp.ndarray) -> jnp.ndarray:
    """Effective sample size 1/sum(w_i^2) of NORMALIZED log-weights.

    P for uniform weights, 1.0 when one particle carries everything;
    NaN weights propagate to a NaN ESS (the guard layer's freeze
    signal, never silently clipped here)."""
    return jnp.exp(-logsumexp(2.0 * logw))


def systematic_indices(key, logw: jnp.ndarray) -> jnp.ndarray:
    """Systematic-resampling ancestor indices from normalized log-weights.

    One shared uniform strata offset: positions ``(i + u)/P`` are sorted
    by construction, so inverting the empirical CDF is ``cumsum`` (the
    scan) + ``searchsorted`` (a monotone merge of two sorted sequences).
    Returns (P,) int32 ancestor indices; low-variance (each particle's
    offspring count differs from P*w_i by < 1)."""
    P = logw.shape[0]
    w = jnp.exp(logw - logsumexp(logw))
    u = (jax.random.uniform(key, dtype=w.dtype) + jnp.arange(P, dtype=w.dtype)) / P
    cw = jnp.cumsum(w)
    # guard the top edge: float cumsum can land at 1 - eps, and the last
    # stratum must still find an ancestor
    cw = cw.at[-1].set(jnp.maximum(cw[-1], 1.0))
    return jnp.searchsorted(cw, u).astype(jnp.int32)


def systematic_resample(key, particles, logw: jnp.ndarray):
    """Resample a particle pytree (leading axis P) to uniform weights.

    Returns (resampled particles, uniform normalized log-weights)."""
    idx = systematic_indices(key, logw)
    parts = jax.tree_util.tree_map(lambda a: a[idx], particles)
    P = logw.shape[0]
    return parts, jnp.full((P,), -jnp.log(float(P)), logw.dtype)


def adaptive_resample(key, particles, logw: jnp.ndarray, ess_frac: float):
    """ESS-triggered systematic resampling as a ``lax.cond``.

    `logw` must be normalized.  When ``ESS < ess_frac * P`` the particles
    are resampled and the weights reset to uniform; otherwise both pass
    through untouched.  Returns (particles, logw, resampled?, ess) with
    `ess` the PRE-resample value — the telemetry the floor-trip-rate
    counters and the degenerate-lane guard read."""
    P = logw.shape[0]
    e = ess_of(logw)

    def _do(_):
        parts, lw = systematic_resample(key, particles, logw)
        return parts, lw

    def _skip(_):
        return particles, logw

    trip = e < ess_frac * P
    parts, lw = jax.lax.cond(trip, _do, _skip, None)
    return parts, lw, trip, e


def liu_west_jitter(key, theta: jnp.ndarray, logw: jnp.ndarray,
                    delta: float = 0.98) -> jnp.ndarray:
    """Liu-West kernel-shrinkage jitter of (P, d) parameter particles.

    Shrinks each particle toward the weighted mean by ``a = (3δ-1)/(2δ)``
    and adds N(0, (1-a²) diag(V)) noise, so the first two weighted
    moments of the parameter cloud are preserved exactly while ties from
    resampling are broken — the opt-in rejuvenation wrapper for models
    that carry static parameters in the particle state.  `logw` must be
    normalized."""
    w = jnp.exp(logw)[:, None]
    a = (3.0 * delta - 1.0) / (2.0 * delta)
    mean = (w * theta).sum(axis=0)
    var = (w * (theta - mean) ** 2).sum(axis=0)
    eps = jax.random.normal(key, theta.shape, theta.dtype)
    return a * theta + (1.0 - a) * mean + eps * jnp.sqrt((1.0 - a * a) * var)
