"""Guarded multi-lane particle filtering: S scenario lanes in one program.

The nonlinear counterpart of `scenarios/gibbs.py`, with the same shape:
the time scan stays on the OUTSIDE and every step body is one
``jax.vmap`` over the scenario-lane axis; inside a lane the particle
axis is plain batched array algebra, so the whole filter is a single
``lax.scan`` program — no host loop over lanes, steps, or particles.
The per-step kernels (proposal, weighting, ESS-triggered systematic
resampling, optional Liu-West jitter) come from `scenarios/particles.py`
and compose BlackJAX-style: a *model* is four closures (init / propose /
log_obs / forecast + a summarize reducer), and the program is model-
agnostic — adding a state-space model means writing four small functions
here, never touching the scan.

Models (built inside the jit trace from traced parameters, selected by a
static name so each model compiles its own specialized program):

    lg     bootstrap filter on the linear-Gaussian companion DFM — the
           validation model: its loglik and filtered means must match
           `models/ssm.kalman_filter` within Monte-Carlo error
           (~1/sqrt(P), pinned by tests/test_scenario_nl.py)
    sv     stochastic-volatility factors (models/sv.py's model): factor
           VAR with log-variance AR(1) states riding in the particle
    msdfm  Markov-switching factor (models/msdfm.py's model): the
           particle carries (z, S_t) and regime probabilities are the
           weighted regime frequencies — validated against `kim_filter`
    tvp    random-walk time-varying loadings (models/tvp.py's model)
           given a factor path, the particle carries vec(Lambda_t)

Degenerate-weight lanes freeze via the PR 7 guarded pattern, verbatim
from gibbs.py: after each vmapped step a per-lane
`utils.guards.batched_tree_finite` check marks lanes whose particles,
weights, or loglik went non-finite (an all-zero weight step collapses to
``logsumexp = -inf`` and is caught here too — ESS floor breaches above
total collapse resample adaptively, only a fully degenerate lane goes
non-finite); the lane's carry rolls back to last-good and is FROZEN —
later steps still ride through the vmapped body but every result is
discarded by the per-lane select, so surviving lanes are bit-identical
to a fault-free run (vmap is elementwise across lanes).  The host drops
frozen lanes afterwards.  ``DFM_FAULTS=nan_draw@k`` NaNs lane 0's k-th
step's weights — the same deterministic drill grammar as the Gibbs
divergence drill, compiled as a static so the clean-path HLO carries no
injection code.

AOT: `utils/compile._kernel_plan` registers one ``smc_filter@<model>``
plan per `models/transforms.enumerate_smc` entry (gated on
``CompileSpec.particle_count``); `aot_plan` below builds the generic
(avals, statics, warmup) triple so there is no hand-written plan body
per model — the transform-stack doctrine applied to SMC.
"""

from __future__ import annotations

from functools import partial
from typing import Callable, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..models.msdfm import MSDFMParams
from ..models.ssm import SSMParams, _companion
from ..ops.masking import fillz, mask_of
from ..utils import faults as _faults
from ..utils import guards as _guards
from ..utils.compile import aot_call, aot_statics
from ..utils.telemetry import inc
from . import particles as _pk

__all__ = [
    "ParticleModel",
    "SMCResult",
    "smc_filter",
    "shock_dim",
    "summary_dim",
    "aot_plan",
    "DEFAULT_QUANTILES",
    "SMC_MODELS",
]

DEFAULT_QUANTILES = (0.05, 0.25, 0.5, 0.75, 0.95)

# models the serving/AOT layer knows; "tvp" filters through the jit
# cache only (its aux carries a panel-length factor path, so an AOT
# entry would key on data, not shape — see transforms.enumerate_smc)
SMC_MODELS = ("lg", "sv", "msdfm", "tvp")


class ParticleModel(NamedTuple):
    """One state-space model as four pure closures over a (P, d) particle
    block.  `init(key) -> (P, d)`; `propose(key, parts, t) -> (P, d)`
    advances one transition; `log_obs(parts, y_t, m_t, t) -> (P,)` is the
    observation log-density with a {0,1} mask; `forecast(key, parts,
    shock) -> (parts, y_pred)` simulates one unconditional step INCLUDING
    measurement noise (the predictive-density sample the quantile bands
    are cut from), with `shock` added to the latent innovation mean;
    `summarize(parts, w) -> (d_sum,)` reduces the weighted cloud to the
    per-step filtered summary the scan materializes."""

    init: Callable
    propose: Callable
    log_obs: Callable
    forecast: Callable
    summarize: Callable


class SMCResult(NamedTuple):
    """Multi-lane SMC output, lane axis leading everywhere.

    `loglik` (S,) particle marginal-likelihood estimates; `summary`
    (S, T, d_sum) per-step filtered summaries (model-specific layout —
    see `summary_dim`; a frozen lane repeats its last-good summary);
    `ess` (S, T) PRE-resample effective sample sizes — the diagnostic
    trace, live even after a freeze; `resampled` (S, T) ESS-floor trips
    (False after a freeze); `health` (S,) utils.guards codes, 0 = ok;
    `bands`/`mean`/`sd` the predictive fan over `horizon` steps —
    bands (S, horizon, n_quantiles, N), None when horizon == 0."""

    loglik: jnp.ndarray
    summary: jnp.ndarray
    ess: jnp.ndarray
    resampled: jnp.ndarray
    health: np.ndarray
    bands: jnp.ndarray | None = None
    mean: jnp.ndarray | None = None
    sd: jnp.ndarray | None = None


def _masked_gauss_ll(mu, y_t, m_t, Rdiag):
    """(P, N) predicted means -> (P,) masked diag-Gaussian log-density."""
    log2pi = jnp.asarray(np.log(2.0 * np.pi), mu.dtype)
    e2 = (y_t[None, :] - mu) ** 2 / Rdiag[None, :]
    per = e2 + jnp.log(Rdiag)[None, :] + log2pi
    return -0.5 * (m_t[None, :] * per).sum(axis=1)


def _lg_model(params: SSMParams, aux, P: int) -> ParticleModel:
    """Bootstrap filter on the companion-form linear-Gaussian DFM.

    Matches `kalman_filter`'s generative model exactly — same diffuse
    init N(0, 100 I) (ssm._init_state), same transition, same masked
    diagonal observation density — so the parity pin has no model gap,
    only Monte-Carlo error."""
    r = params.r
    Tm, _ = _companion(params)
    Lq = jnp.linalg.cholesky(params.Q)
    k = Tm.shape[0]

    def init(key):
        return 10.0 * jax.random.normal(key, (P, k), params.lam.dtype)

    def propose(key, parts, t):
        eps = jax.random.normal(key, (P, r), parts.dtype)
        sp = parts @ Tm.T
        return sp.at[:, :r].add(eps @ Lq.T)

    def log_obs(parts, y_t, m_t, t):
        return _masked_gauss_ll(parts[:, :r] @ params.lam.T, y_t, m_t, params.R)

    def forecast(key, parts, shock):
        k1, k2 = jax.random.split(key)
        sp = propose(k1, parts, 0).at[:, :r].add(shock[None, :])
        eps = jax.random.normal(k2, (P, params.lam.shape[0]), parts.dtype)
        y = sp[:, :r] @ params.lam.T + eps * jnp.sqrt(params.R)[None, :]
        return sp, y

    def summarize(parts, w):
        return (w[:, None] * parts).sum(axis=0)

    return ParticleModel(init, propose, log_obs, forecast, summarize)


def _sv_model(params: SSMParams, aux, P: int) -> ParticleModel:
    """Stochastic-volatility factor DFM (models/sv.py's model): the
    factor VAR innovation variance is exp(h_t) with h AR(1); the particle
    is [companion state (k,), h (r,)].  aux = (mu_h, phi_h, sig_h), each
    (r,).  Summary = [filtered state mean (k,), filtered vol exp(h/2)
    mean (r,)]."""
    r = params.r
    Tm, _ = _companion(params)
    k = Tm.shape[0]
    mu_h, phi_h, sig_h = aux

    def init(key):
        k1, k2 = jax.random.split(key)
        s = 10.0 * jax.random.normal(k1, (P, k), params.lam.dtype)
        h_sd = sig_h / jnp.sqrt(1.0 - phi_h**2)
        h = mu_h[None, :] + h_sd[None, :] * jax.random.normal(
            k2, (P, r), params.lam.dtype
        )
        return jnp.concatenate([s, h], axis=1)

    def _step(key, parts):
        k1, k2 = jax.random.split(key)
        s, h = parts[:, :k], parts[:, k:]
        h2 = mu_h + phi_h * (h - mu_h) + sig_h * jax.random.normal(
            k1, (P, r), parts.dtype
        )
        eps = jax.random.normal(k2, (P, r), parts.dtype) * jnp.exp(0.5 * h2)
        sp = (s @ Tm.T).at[:, :r].add(eps)
        return sp, h2

    def propose(key, parts, t):
        sp, h2 = _step(key, parts)
        return jnp.concatenate([sp, h2], axis=1)

    def log_obs(parts, y_t, m_t, t):
        return _masked_gauss_ll(parts[:, :r] @ params.lam.T, y_t, m_t, params.R)

    def forecast(key, parts, shock):
        k1, k2 = jax.random.split(key)
        sp, h2 = _step(k1, parts)
        sp = sp.at[:, :r].add(shock[None, :])
        eps = jax.random.normal(k2, (P, params.lam.shape[0]), parts.dtype)
        y = sp[:, :r] @ params.lam.T + eps * jnp.sqrt(params.R)[None, :]
        return jnp.concatenate([sp, h2], axis=1), y

    def summarize(parts, w):
        sm = (w[:, None] * parts[:, :k]).sum(axis=0)
        vol = (w[:, None] * jnp.exp(0.5 * parts[:, k:])).sum(axis=0)
        return jnp.concatenate([sm, vol])

    return ParticleModel(init, propose, log_obs, forecast, summarize)


def _ms_model(params: MSDFMParams, aux, P: int) -> ParticleModel:
    """Markov-switching single-factor DFM (models/msdfm.py's model):
    x_t = lam (mu_{S_t} + z_t) + e, z AR(1) with regime-switching
    innovation variance.  The particle is [z, S_t] with the regime
    carried as a float index; regime probabilities are the weighted
    regime frequencies.  Summary = [filtered z mean, regime probs (M,)]."""
    M = params.mu.shape[0]
    dtype = params.lam.dtype
    # ergodic regime distribution for the init (M tiny: a matrix power
    # is cheaper and simpler than an eigensolve inside the trace)
    pi = jnp.linalg.matrix_power(params.P, 64)[0]
    sig_bar = (pi * params.sigma2).sum()

    def init(key):
        k1, k2 = jax.random.split(key)
        z_sd = jnp.sqrt(sig_bar / jnp.maximum(1.0 - params.phi**2, 1e-6))
        z = z_sd * jax.random.normal(k1, (P,), dtype)
        u = jax.random.uniform(k2, (P,), dtype)
        s = (jnp.cumsum(pi)[None, :] < u[:, None]).sum(axis=1)
        return jnp.stack([z, s.astype(dtype)], axis=1)

    def _trans(key, parts, shock):
        k1, k2 = jax.random.split(key)
        z, s = parts[:, 0], parts[:, 1].astype(jnp.int32)
        u = jax.random.uniform(k1, (P,), dtype)
        cdf = jnp.cumsum(params.P[s], axis=1)
        s2 = jnp.minimum((cdf < u[:, None]).sum(axis=1), M - 1)
        eps = jax.random.normal(k2, (P,), dtype)
        z2 = params.phi * z + shock + jnp.sqrt(params.sigma2[s2]) * eps
        return jnp.stack([z2, s2.astype(dtype)], axis=1)

    def propose(key, parts, t):
        return _trans(key, parts, 0.0)

    def log_obs(parts, y_t, m_t, t):
        z, s = parts[:, 0], parts[:, 1].astype(jnp.int32)
        mu = params.lam[None, :] * (params.mu[s] + z)[:, None]
        return _masked_gauss_ll(mu, y_t, m_t, params.R)

    def forecast(key, parts, shock):
        k1, k2 = jax.random.split(key)
        p2 = _trans(k1, parts, shock[0])
        z, s = p2[:, 0], p2[:, 1].astype(jnp.int32)
        mu = params.lam[None, :] * (params.mu[s] + z)[:, None]
        eps = jax.random.normal(k2, mu.shape, dtype)
        return p2, mu + eps * jnp.sqrt(params.R)[None, :]

    def summarize(parts, w):
        zm = (w * parts[:, 0]).sum()
        onehot = parts[:, 1].astype(jnp.int32)[:, None] == jnp.arange(M)[None, :]
        probs = (w[:, None] * onehot).sum(axis=0)
        return jnp.concatenate([zm[None], probs])

    return ParticleModel(init, propose, log_obs, forecast, summarize)


def _tvp_model(params: SSMParams, aux, P: int) -> ParticleModel:
    """Random-walk time-varying loadings (models/tvp.py's model) given a
    factor path: the particle is vec(Lambda_t) (N*r,), proposed as a
    random walk with per-step variance q, weighted against x_t = Lam_t
    f_t + e.  aux = (F (T, r) factor path, q scalar).  The forecast stage
    freezes the factor at F[-1] (+ shock) and keeps the loadings walking.
    Summary = weighted vec(Lambda_t) mean."""
    N, r = params.lam.shape
    F, q = aux
    sq = jnp.sqrt(q)
    lam0 = params.lam.reshape(-1)

    def init(key):
        return lam0[None, :] + 3.0 * sq * jax.random.normal(
            key, (P, N * r), params.lam.dtype
        )

    def propose(key, parts, t):
        return parts + sq * jax.random.normal(key, parts.shape, parts.dtype)

    def _mu(parts, f):
        return jnp.einsum("pnr,r->pn", parts.reshape(P, N, r), f)

    def log_obs(parts, y_t, m_t, t):
        return _masked_gauss_ll(_mu(parts, F[t]), y_t, m_t, params.R)

    def forecast(key, parts, shock):
        k1, k2 = jax.random.split(key)
        p2 = propose(k1, parts, 0)
        y = _mu(p2, F[-1] + shock) + jax.random.normal(
            k2, (P, N), parts.dtype
        ) * jnp.sqrt(params.R)[None, :]
        return p2, y

    def summarize(parts, w):
        return (w[:, None] * parts).sum(axis=0)

    return ParticleModel(init, propose, log_obs, forecast, summarize)


_MODELS = {
    "lg": _lg_model,
    "sv": _sv_model,
    "msdfm": _ms_model,
    "tvp": _tvp_model,
}


def shock_dim(model: str, r: int) -> int:
    """Width of one stress-shock vector for `model` (msdfm's factor is
    scalar; every other model shocks the r factor innovations)."""
    return 1 if model == "msdfm" else r


def summary_dim(model: str, params, M: int = 2) -> int:
    """Trailing width of SMCResult.summary for `model` (layout doc:
    lg = companion state (k,); sv = state (k,) + vols (r,); msdfm =
    [z, regime probs (M,)]; tvp = vec(Lambda) (N*r,))."""
    if model == "msdfm":
        return 1 + M
    r, p = params.r, params.p
    k = r * p
    if model == "lg":
        return k
    if model == "sv":
        return k + r
    return params.lam.shape[0] * r


@partial(
    jax.jit,
    static_argnames=("model", "n_particles", "horizon", "ess_frac", "inject_at"),
)
def _smc_impl(
    params,
    aux,
    keys,
    yz,
    m,
    shocks,
    quantiles,
    *,
    model: str,
    n_particles: int,
    horizon: int,
    ess_frac: float,
    inject_at: int = 0,
):
    """All S lanes through the filter (+ forecast) scans together, guarded.

    `keys` (S, 2) per-lane PRNG keys; `yz` (T, N) zero-filled panel with
    `m` its {0,1} mask; `shocks` (S, shock_dim) latent-innovation
    impulses applied at the first forecast step (zeros = plain
    predictive density); `quantiles` (Q,) band levels.  Statics select
    the model program and size the particle block, so one executable per
    (model, P, horizon, ess_frac) serves every panel of the same shape."""
    S = keys.shape[0]
    T = yz.shape[0]
    P = n_particles
    pm = _MODELS[model](params, aux, P)

    ks2 = jax.vmap(lambda k_: jax.random.split(k_))(keys)  # (S, 2, 2)
    k_init, k_scan = ks2[:, 0], ks2[:, 1]
    parts0 = jax.vmap(pm.init)(k_init)
    logw0 = jnp.full((S, P), -jnp.log(float(P)), yz.dtype)
    ll0 = jnp.zeros((S,), yz.dtype)

    def lane_step(key, parts, logw, inp):
        t, y_t, m_t = inp
        key, kp, kr = jax.random.split(key, 3)
        newp = pm.propose(kp, parts, t)
        lw, ll_inc = _pk.normalize_logw(logw + pm.log_obs(newp, y_t, m_t, t))
        newp, lw, trip, e = _pk.adaptive_resample(kr, newp, lw, ess_frac)
        return key, newp, lw, ll_inc, trip, e

    vstep = jax.vmap(lane_step, in_axes=(0, 0, 0, None))

    def body(carry, inp):
        (ks, parts, logw, ll), health = carry
        nk, np_, nlw, llinc, trip, e = vstep(ks, parts, logw, inp)
        nll = ll + llinc
        if inject_at:
            hit = inp[0] + 1 == inject_at
            nlw = nlw.at[0].set(
                jnp.where(hit, jnp.full_like(nlw[0], jnp.nan), nlw[0])
            )
        finite = _guards.batched_tree_finite((np_, nlw, nll))
        ok = health == _guards.HEALTH_OK
        adv = ok & finite
        ks2, parts2, logw2, ll2 = _guards.batched_where(
            adv, (nk, np_, nlw, nll), (ks, parts, logw, ll)
        )
        health = jnp.where(
            ok & ~finite, _guards.HEALTH_NONFINITE, health
        ).astype(jnp.int32)
        summ = jax.vmap(lambda p_, lw: pm.summarize(p_, jnp.exp(lw)))(
            parts2, logw2
        )
        return ((ks2, parts2, logw2, ll2), health), (summ, e, trip & adv)

    carry = ((k_scan, parts0, logw0, ll0), jnp.zeros((S,), jnp.int32))
    xs = (jnp.arange(T), yz, m.astype(yz.dtype))
    ((ks, parts, logw, ll), health), (summ, ess, trips) = jax.lax.scan(
        body, carry, xs
    )
    # scan stacks steps leading: (T, S, ...) -> (S, T, ...)
    summ = jnp.swapaxes(summ, 0, 1)
    ess = ess.T
    trips = trips.T

    if horizon == 0:
        return ll, summ, ess, trips, health, None, None, None

    def lane_forecast(key, parts_l, logw_l, shock):
        key, kr = jax.random.split(key)
        # equalize weights once so the band quantiles are unweighted
        parts_l, _ = _pk.systematic_resample(kr, parts_l, logw_l)

        def fstep(c, t):
            key, pl = c
            key, k1 = jax.random.split(key)
            pl, y = pm.forecast(
                k1, pl, jnp.where(t == 0, shock, jnp.zeros_like(shock))
            )
            return (key, pl), y

        _, ypred = jax.lax.scan(fstep, (key, parts_l), jnp.arange(horizon))
        return ypred  # (horizon, P, N)

    ypred = jax.vmap(lane_forecast)(ks, parts, logw, shocks)
    bands = jnp.moveaxis(
        jnp.quantile(ypred, quantiles, axis=2), 0, 2
    )  # (S, horizon, Q, N)
    return (
        ll, summ, ess, trips, health,
        bands, ypred.mean(axis=2), ypred.std(axis=2),
    )


def smc_filter(
    params,
    x,
    *,
    model: str = "lg",
    aux: tuple = (),
    n_particles: int = 1024,
    n_lanes: int | None = None,
    shocks=None,
    horizon: int = 0,
    quantiles=DEFAULT_QUANTILES,
    ess_frac: float = 0.5,
    seed: int = 0,
) -> SMCResult:
    """Run the guarded multi-lane particle filter over a (T, N) NaN-masked
    panel; the production entry the scenario API dispatches to.

    `shocks` (S, shock_dim) sets the lane count AND the per-lane stress
    impulse (None = `n_lanes` unshocked density lanes, default 1); lanes
    differ only in PRNG key and shock, so their Monte-Carlo error is
    independent.  Applies the active fault plan (``nan_draw@k``) as a
    compile-time static and dispatches through `aot_call` so a
    `CompileSpec.particle_count` precompile serves matching requests
    without retracing."""
    if model not in _MODELS:
        raise ValueError(
            f"unknown particle model {model!r}; valid: {', '.join(_MODELS)}"
        )
    x = jnp.asarray(x)
    mask = mask_of(x)
    yz = fillz(x)
    # empty aux is carried as a (0,)-shaped sentinel so the aot_call
    # signature matches the registered plan (an empty tuple has no
    # leaves and would vanish from the precompile key)
    aux = (
        tuple(jnp.asarray(a, yz.dtype) for a in aux)
        if aux else (jnp.zeros((0,), yz.dtype),)
    )
    sd = shock_dim(model, 0 if model == "msdfm" else params.r)
    if shocks is None:
        S = int(n_lanes or 1)
        shocks = jnp.zeros((S, sd), yz.dtype)
    else:
        shocks = jnp.asarray(shocks, yz.dtype)
        if shocks.ndim != 2 or shocks.shape[1] != sd:
            raise ValueError(
                f"shocks must be (S, {sd}) for model {model!r}, "
                f"got {tuple(shocks.shape)}"
            )
        S = int(shocks.shape[0])
    keys = jax.random.split(jax.random.PRNGKey(seed), S)
    q = jnp.asarray(quantiles, yz.dtype)
    plan = _faults.active_plan()
    inject_at = int(plan.nan_draw or 0)
    if inject_at:
        _faults.fault_fired("nan_draw")
    fb = partial(
        _smc_impl,
        model=model,
        n_particles=int(n_particles),
        horizon=int(horizon),
        ess_frac=float(ess_frac),
        inject_at=inject_at,
    )
    out = aot_call(
        "smc_filter",
        fb,
        params, aux, keys, yz, mask, shocks, q,
        statics=aot_statics(
            model, int(n_particles), int(horizon), float(ess_frac), inject_at
        ),
    )
    ll, summ, ess, trips, health, bands, mean, sdv = out
    health = np.asarray(health)
    n_bad = int((health != _guards.HEALTH_OK).sum())
    if n_bad:
        inc("smc_guard.lanes_frozen", n_bad)
    n_trips = int(np.asarray(trips).sum())
    if n_trips:
        inc("smc.ess_floor_trips", n_trips)
    return SMCResult(ll, summ, ess, trips, health, bands, mean, sdv)


def aot_plan(model: str, P: int, spec):
    """Build the (fn, lower_args, lower_kwargs, statics, mk_inputs)
    plan tuple for one ``smc_filter@<model>`` registry entry — called by
    `utils/compile._kernel_plan` for every `transforms.enumerate_smc`
    entry, so SMC kernels have no hand-written plan body either."""
    dt = jnp.dtype(spec.dtype)
    Tb, Nb = spec.padded_shape()
    r, p = spec.r, spec.p
    S = spec.scenario_paths
    h = spec.scenario_horizon
    sds = jax.ShapeDtypeStruct

    if model == "msdfm":
        M = 2
        params_s = MSDFMParams(
            lam=sds((Nb,), dt), R=sds((Nb,), dt), mu=sds((M,), dt),
            phi=sds((), dt), P=sds((M, M), dt), sigma2=sds((M,), dt),
        )
        aux_s = (sds((0,), dt),)
        sdim = 1
    else:
        params_s = SSMParams(
            sds((Nb, r), dt), sds((Nb,), dt), sds((p, r, r), dt),
            sds((r, r), dt),
        )
        aux_s = (
            (sds((r,), dt),) * 3 if model == "sv" else (sds((0,), dt),)
        )
        sdim = r
    lower_args = (
        params_s, aux_s, sds((S, 2), jnp.uint32), sds((Tb, Nb), dt),
        sds((Tb, Nb), jnp.bool_), sds((S, sdim), dt),
        sds((len(DEFAULT_QUANTILES),), dt),
    )
    lower_kwargs = dict(
        model=model, n_particles=int(P), horizon=int(h),
        ess_frac=0.5, inject_at=0,
    )
    statics = aot_statics(model, int(P), int(h), 0.5, 0)

    def mk_inputs():
        rng = np.random.default_rng(0)
        if model == "msdfm":
            pa = MSDFMParams(
                lam=jnp.asarray(0.5 + 0.1 * rng.standard_normal(Nb), dt),
                R=jnp.ones(Nb, dt),
                mu=jnp.asarray([-1.0, 1.0], dt),
                phi=jnp.asarray(0.5, dt),
                P=jnp.asarray([[0.9, 0.1], [0.1, 0.9]], dt),
                sigma2=jnp.ones(2, dt),
            )
            aux = (jnp.zeros((0,), dt),)
        else:
            lam = jnp.asarray(0.3 * rng.standard_normal((Nb, r)), dt)
            A = jnp.zeros((p, r, r), dt).at[0].set(0.5 * jnp.eye(r, dtype=dt))
            pa = SSMParams(lam, jnp.ones(Nb, dt), A, jnp.eye(r, dtype=dt))
            aux = (
                (jnp.zeros(r, dt), jnp.full((r,), 0.95, dt),
                 jnp.full((r,), 0.2, dt))
                if model == "sv" else (jnp.zeros((0,), dt),)
            )
        return (
            pa, aux, jax.random.split(jax.random.PRNGKey(0), S),
            jnp.asarray(0.3 * rng.standard_normal((Tb, Nb)), dt),
            jnp.ones((Tb, Nb), bool),
            jnp.zeros((S, sdim), dt),
            jnp.asarray(DEFAULT_QUANTILES, dt),
        )

    return _smc_impl, lower_args, lower_kwargs, statics, mk_inputs
