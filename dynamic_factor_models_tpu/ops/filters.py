"""Filter weights and spectral gains (reference cells 31-33; Figure 2).

The gain is evaluated for all frequencies at once as |W e^{i l w}| via a
single complex matmul instead of the reference's per-frequency Horner loop.
"""

from __future__ import annotations

import jax.numpy as jnp

__all__ = [
    "compute_bw_weight",
    "compute_gain",
    "ma_weight",
    "baxter_king_lowpass_weight",
    "hp_trend_weight",
]


def compute_bw_weight(B: int) -> jnp.ndarray:
    """Tukey biweight lag window on [-B, B], normalized to sum 1 (cell 31)."""
    i = jnp.abs(jnp.arange(-B, B + 1))
    w = (1.0 - (i / B) ** 2) ** 2
    return w / w.sum()


def compute_gain(w: jnp.ndarray, lam: jnp.ndarray) -> jnp.ndarray:
    """|gain| of a two-sided filter with weights w at frequencies lam (cell 33).

    w has odd length 2B+1 covering lags -B..B; lam may be a vector.
    """
    B = (w.shape[0] - 1) // 2
    lags = jnp.arange(-B, B + 1)
    lam = jnp.atleast_1d(lam)
    phase = jnp.exp(1j * jnp.outer(lam, lags))  # e^{i lam l}, l = -B..B
    gain = phase @ w.astype(phase.dtype)
    return jnp.abs(gain)


def ma_weight(B: int, half_width: int) -> jnp.ndarray:
    """Flat two-sided MA over +/- half_width on the [-B, B] lag grid
    (Stock_Watson.ipynb cell 26)."""
    lags = jnp.arange(-B, B + 1)
    w = (jnp.abs(lags) <= half_width).astype(float)
    return w / w.sum()


def baxter_king_lowpass_weight(maxlag: int) -> jnp.ndarray:
    """Baxter-King low-pass weights, cutoff period 2*maxlag quarters
    (Stock_Watson.ipynb cell 26)."""
    nper = 2 * maxlag
    ombar = 2 * jnp.pi / nper
    t1 = jnp.arange(1, maxlag + 1)
    tmp0 = ombar / jnp.pi
    tmp1 = (1.0 / (jnp.pi * t1)) * jnp.sin(t1 * ombar)
    w = jnp.concatenate([tmp1[::-1], jnp.array([tmp0]), tmp1])
    return w / w.sum()


def hp_trend_weight(maxlag: int, lam: float = 1600.0) -> jnp.ndarray:
    """Two-sided Hodrick-Prescott trend-filter weights on the [-B, B] grid.

    The reference ships these precomputed (data/hpfilter_trend.asc, 201
    weights; Stock_Watson.ipynb cell 26) — here they are computed directly:
    the HP trend is tau = (I + lam D'D)^{-1} y on a window of length
    2*maxlag+1, and the middle row of that smoother matrix is the symmetric
    weight vector applied to leads/lags of y.  Matches the shipped file to
    float precision for maxlag=100, lam=1600 (tests/test_replication_utils.py).
    """
    n = 2 * maxlag + 1
    # second-difference operator: (n-2) x n
    D = (
        jnp.zeros((n - 2, n))
        .at[jnp.arange(n - 2), jnp.arange(n - 2)]
        .set(1.0)
        .at[jnp.arange(n - 2), jnp.arange(1, n - 1)]
        .set(-2.0)
        .at[jnp.arange(n - 2), jnp.arange(2, n)]
        .set(1.0)
    )
    S = jnp.eye(n) + lam * (D.T @ D)
    return jnp.linalg.solve(S, jnp.eye(n)[maxlag])
