"""Missing-data primitives.

The reference represents missing data as Julia ``Union{Missing,Float64}`` and
drops ragged row subsets per regression (reference: dfm_functions.ipynb cells
8-9, ``drop_missing_row``/``drop_missing_col``).  Ragged shapes do not jit, so
the TPU-native representation is a (values-with-NaN, boolean-mask) pair and
every kernel carries the mask through weighted normal equations instead of
dropping rows.  ``compact`` provides the jit-safe analogue of row dropping for
the few places where order-sensitive compaction matters (idiosyncratic AR on
residual series).
"""

from __future__ import annotations

import jax.numpy as jnp

__all__ = ["mask_of", "fillz", "compact", "row_mask"]


def mask_of(x: jnp.ndarray) -> jnp.ndarray:
    """True where observed."""
    return ~jnp.isnan(x)


def fillz(x: jnp.ndarray) -> jnp.ndarray:
    """NaN -> 0, for masked arithmetic."""
    return jnp.nan_to_num(x, nan=0.0, posinf=jnp.inf, neginf=-jnp.inf)


def row_mask(*arrays: jnp.ndarray) -> jnp.ndarray:
    """Rows where every column of every array is observed.

    Equivalent of the reference's ``drop_missing_row([y X])`` row selector
    (dfm_functions.ipynb cell 8) without changing shapes.
    """
    m = None
    for a in arrays:
        am = mask_of(a)
        if a.ndim > 1:
            am = am.all(axis=-1)
        m = am if m is None else (m & am)
    return m


def compact(x: jnp.ndarray, mask: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Stable-move observed entries of a vector to the front (jit-safe).

    Returns (values, valid) where values[:count] are the observed entries in
    original order and valid marks the live prefix.  This is the static-shape
    analogue of Julia's row dropping: downstream kernels weight by ``valid``.
    """
    order = jnp.argsort(~mask, stable=True)
    vals = x[order]
    count = mask.sum()
    valid = jnp.arange(x.shape[0]) < count
    vals = jnp.where(valid, vals, 0.0)
    return vals, valid
