"""Masked least squares, PCA, and standardization.

TPU-native replacements for the reference's regression kernels
(dfm_functions.ipynb cells 10-17, 25, 29).  Ragged row-dropping becomes
0/1-weighted normal equations `(X'WX) b = X'Wy` solved with a pseudo-inverse,
which makes every per-series / per-period regression uniformly shaped and
batchable with ``vmap`` — the reference's ``ols_skipmissing(Unbalanced)``
per-column loop (cell 17) is one batched solve here.

The pseudo-inverse (eigh-based, normal matrices are symmetric PSD) also covers
the rank-deficient regressions the reference hits in the Figure-6 sweep
(r up to 60 factors with as few as 20 observations per series).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .masking import fillz, mask_of

__all__ = [
    "solve_normal",
    "chol_guarded",
    "ols",
    "ols_masked",
    "ols_batched_series",
    "pca_score",
    "pca_score_np",
    "standardize_data",
    "standardize_data_np",
    "compute_r2",
    "varimax",
]


def solve_normal(A: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Minimum-norm solve of the (possibly singular) normal equations A x = b.

    A is symmetric PSD (a Gram matrix X'WX).  pinv(A) @ b equals the
    Moore-Penrose least-squares solution pinv(sqrt(W)X) sqrt(W)y.

    A non-finite Gram matrix or right-hand side raises immediately with a
    clear message when the inputs are concrete: the eigh inside pinv would
    otherwise turn one NaN into silently-NaN OLS coefficients downstream.
    Under jit/vmap the inputs are tracers and the check is skipped — there
    the loop-level health sentinel (utils.guards) owns detection, keeping
    the hot program free of host syncs.
    """
    if not isinstance(A, jax.core.Tracer) and not isinstance(b, jax.core.Tracer):
        if not (bool(jnp.all(jnp.isfinite(A))) and bool(jnp.all(jnp.isfinite(b)))):
            raise ValueError(
                "solve_normal: non-finite values in the normal equations "
                "(NaN/Inf in the Gram matrix or right-hand side); the "
                "eigh-based pinv would propagate them silently into the "
                "OLS coefficients — clean or re-mask the inputs"
            )
    return jnp.linalg.pinv(A, hermitian=True) @ b


def chol_guarded(M: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Cholesky factorization that REPORTS failure instead of emitting NaNs.

    Returns ``(L, ok)``: ``ok`` is a scalar bool, True iff the
    factorization succeeded (M symmetric positive definite, all entries
    finite).  On failure L is returned with non-finite entries zeroed, so
    downstream linear algebra stays finite while the caller branches on
    ``ok`` — the checkify-style contract the recovery ladder relies on
    when it verifies a ridge-jittered covariance is factorizable before
    resuming the loop.  Trace-safe: usable under jit/vmap (``ok`` is a
    traced value, not a host assertion).
    """
    L = jnp.linalg.cholesky(M)
    ok = jnp.all(jnp.isfinite(L))
    return jnp.where(jnp.isfinite(L), L, 0.0), ok


def ols(y: jnp.ndarray, X: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Dense OLS `b = X \\ y; e = y - Xb` (reference cell 12)."""
    A = X.T @ X
    b = solve_normal(A, X.T @ y)
    return b, y - X @ b


def ols_masked(
    y: jnp.ndarray, X: jnp.ndarray, w: jnp.ndarray
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Weighted OLS of a vector y on X with 0/1 row weights w.

    Returns (beta, residual) with residual NaN outside the weighted rows —
    the masked analogue of `ols_skipmissing(..., Balanced())` (cell 15).
    """
    Xw = X * w[:, None]
    A = Xw.T @ X
    beta = solve_normal(A, Xw.T @ fillz(y))
    resid = jnp.where(w, fillz(y) - X @ beta, jnp.nan)
    return beta, resid


def ols_batched_series(
    Y: jnp.ndarray, X: jnp.ndarray, W: jnp.ndarray
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Batched masked OLS: each column of Y regressed on shared X.

    Y: (T, N) with NaN missing; X: (T, K); W: (T, N) 0/1 weights.
    Returns betas (K, N) and residuals (T, N) with NaN at unweighted rows.
    Replaces the reference's per-column `Unbalanced` loop (cell 17) with one
    fused masked-Gram contraction + batched solve — MXU-friendly; large
    panels on TPU route through the Pallas kernel (ops/pallas_gram.py).
    """
    from .pallas_gram import masked_gram

    Yz = fillz(Y)
    A, rhs = masked_gram(X, Yz, W)  # (N, K, K), (N, K)
    betas = jax.vmap(solve_normal)(A, rhs).T  # K x N
    resid = jnp.where(W.astype(bool), Yz - X @ betas, jnp.nan)
    return betas, resid


def pca_score(X: jnp.ndarray, nfac: int) -> jnp.ndarray:
    """First `nfac` principal-component scores X V[:, :nfac] (cell 10)."""
    _, _, Vt = jnp.linalg.svd(X, full_matrices=False)
    return X @ Vt[:nfac].T


def standardize_data(x: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Per-column z-score over observed entries, population-std convention.

    Mirrors reference cell 25 exactly, including the sqrt((n-1)/n) correction
    that converts sample std to the population std of the original
    Stock-Watson GAUSS code (SURVEY.md section 2.5 quirk 6 — required for
    parity).
    Returns (standardized, std-row).
    """
    m = mask_of(x)
    n = m.sum(axis=0)
    xz = fillz(x)
    mean = xz.sum(axis=0) / n
    dev = jnp.where(m, xz - mean, 0.0)
    var_sample = (dev**2).sum(axis=0) / (n - 1)
    std = jnp.sqrt(var_sample) * jnp.sqrt((n - 1) / n)
    out = jnp.where(m, (xz - mean) / std, jnp.nan)
    return out, std


def standardize_data_np(x):
    """NumPy twin of `standardize_data` for host-side batch preparation
    (models.dfm.estimate_factor_batch) — same population-std convention
    (quirk 2.5-6); kept adjacent so the two implementations stay in sync
    (pinned equal by tests/test_ops.py).

    Returns (standardized with 0 at missing, mask, std-row)."""
    import numpy as np

    m = ~np.isnan(x)
    n = m.sum(axis=0)
    with np.errstate(invalid="ignore", divide="ignore"):
        mean = np.where(m, x, 0.0).sum(axis=0) / n
        dev = np.where(m, x - mean, 0.0)
        std = np.sqrt((dev**2).sum(axis=0) / (n - 1)) * np.sqrt((n - 1) / n)
        xz = np.where(m, (x - mean) / std, 0.0).astype(x.dtype, copy=False)
    return xz, m, std


def pca_score_np(X, nfac: int):
    """NumPy twin of `pca_score` (host-side PCA initialization)."""
    import numpy as np

    _, _, Vt = np.linalg.svd(X, full_matrices=False)
    return X @ Vt[:nfac].T


def compute_r2(y: jnp.ndarray, e: jnp.ndarray, w=None) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """R^2 = 1 - SSR/TSS with TSS about the (weighted) mean of y (cell 29)."""
    if w is None:
        w = jnp.ones_like(y)
    n = w.sum()
    ybar = (fillz(y) * w).sum() / n
    ssr = (fillz(e) ** 2 * w).sum()
    tss = ((fillz(y) - ybar) ** 2 * w).sum()
    return 1.0 - ssr / tss, ssr, tss


def varimax(lam: jnp.ndarray, n_iter: int = 100, tol: float = 1e-8):
    """Varimax rotation of a loading matrix (Kaiser 1958, SVD algorithm).

    Factors from PCA/ALS are identified only up to rotation (SURVEY.md
    section 7.3); varimax picks the orthogonal rotation maximizing the
    variance of squared loadings, the standard interpretability aid the
    reference leaves to the reader.  Returns (rotated loadings, R) with
    lam_rot = lam @ R, R orthogonal; apply F @ R to keep F lam' invariant.

    Implemented as a jitted ``lax.while_loop`` of SVD steps.
    """
    lam = jnp.asarray(lam)
    N, r = lam.shape
    if r == 1:
        return lam, jnp.eye(1, dtype=lam.dtype)

    def body(state):
        R, d_prev, _, i = state
        L = lam @ R
        mid = L**3 - L * (L**2).sum(axis=0) / N
        u, s, vt = jnp.linalg.svd(lam.T @ mid)
        d = s.sum()
        return u @ vt, d, jnp.abs(d - d_prev), i + 1

    def cond(state):
        _, _, delta, i = state
        return (delta > tol) & (i < n_iter)

    R0 = jnp.eye(r, dtype=lam.dtype)
    R, *_ = jax.lax.while_loop(
        cond, body, (R0, jnp.asarray(0.0, lam.dtype), jnp.asarray(jnp.inf, lam.dtype), 0)
    )
    return lam @ R, R
