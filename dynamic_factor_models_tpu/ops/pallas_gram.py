"""Pallas TPU kernel for batched masked normal equations (the hot op).

Every estimator in this framework reduces its inner loop to masked least
squares of many series on a shared regressor block (SURVEY.md section 7.1):
the ALS loading step, the per-period F-step, the EM M-step, and the
Chow/QLR instability scans all compute, for each series n,

    A_n  = sum_t w_tn x_tk x_tl      (K x K Gram matrix)
    b_n  = sum_t w_tn x_tk y_tn      (K right-hand side)

The XLA path (`ops/linalg.ols_batched_series`) materializes the (T, K, K)
outer-product tensor and the (T, N) masked panel in HBM between two
contractions.  This kernel fuses the whole reduction: for each (series-tile,
time-tile) grid cell it forms the regressor products on the VPU in VMEM and
feeds two MXU matmuls with the series dimension in the MXU lanes

    A[i] (K^2 x Nt)  += P_tile (K^2 x Tt) @ W_tile (Tt x Nt)
    b[i] (K   x Nt)  += X_tile' (K x Tt) @ (W_tile * Y_tile) (Tt x Nt)

accumulating in VMEM across the time grid — one pass over X, Y, W in HBM
and no intermediate tensors.  Keeping N in the lanes matters: the transposed
layout (series in sublanes, K^2 in lanes) measured 4-5x slower on a v5e
because each matmul then has only K=8 useful lanes.  This is the
bandwidth-optimal layout for the large-panel regime (T, N in the thousands)
the framework targets beyond the reference's 224 x 233 panel; at reference
sizes the XLA path is already fine, so `masked_gram` auto-dispatches by
problem size and platform.

Estimation code never differentiates through the normal equations, so no
custom VJP is provided; the kernel is forward-only by design.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = [
    "masked_gram",
    "masked_gram_pallas",
    "masked_gram_xla",
    "ring_allreduce",
    "hierarchical_allreduce",
]


def _gram_kernel(x_ref, y_ref, w_ref, a_ref, b_ref):
    """One (series-tile i, time-tile j) cell; accumulates over j in VMEM."""
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _():
        a_ref[:] = jnp.zeros_like(a_ref)
        b_ref[:] = jnp.zeros_like(b_ref)

    xT = x_ref[:].T  # (K, Tt)
    w = w_ref[:]  # (Tt, Nt)
    wy = w * y_ref[:]  # (Tt, Nt)
    k = xT.shape[0]
    # regressor-pair products (K*K, Tt), built by concatenation — a 3D→2D
    # reshape of the outer-product tensor is rejected by Mosaic's vector
    # layout pass on TPU, row-broadcast products are not
    p = jnp.concatenate([xT * xT[kk][None, :] for kk in range(k)], axis=0)
    a_ref[:] += jnp.dot(p, w, preferred_element_type=a_ref.dtype)
    b_ref[:] += jnp.dot(xT, wy, preferred_element_type=b_ref.dtype)


@functools.partial(jax.jit, static_argnames=("tile_t", "tile_n", "interpret"))
def masked_gram_pallas(
    X: jnp.ndarray,
    Y: jnp.ndarray,
    W: jnp.ndarray,
    *,
    tile_t: int = 256,
    tile_n: int = 512,
    interpret: bool = False,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Fused masked Gram: returns (A (N, K, K), rhs (N, K)).

    X: (T, K) shared regressors; Y: (T, N) targets (NaN-free — pre-fill
    missing with 0); W: (T, N) 0/1 weights.  Zero-weight padding rows and
    columns contribute nothing, so inputs are zero-padded to tile multiples.

    bfloat16 inputs are the HBM-bandwidth option for the large-panel
    regime (the kernel is bandwidth-bound: one pass over Y and W dominates
    its cost, and bf16 halves it).  Accumulation is always at least f32 —
    the MXU takes bf16 operands with an f32 accumulator natively — and the
    returned Grams are f32, so the per-series solves downstream are
    unaffected.  Cast the panel ONCE outside an iteration loop: a cast at
    every call spends the pass it is meant to save.  The VPU-side
    regressor products are formed in bf16 too (~3 decimal digits), so this
    is an opt-in for iterative refinement at scale, not for golden-parity
    paths.
    """
    T, K = X.shape
    N = Y.shape[1]
    dtype = X.dtype
    acc_dtype = jnp.promote_types(dtype, jnp.float32)
    Tp = -(-T // tile_t) * tile_t
    Np = -(-N // tile_n) * tile_n
    Xp = jnp.zeros((Tp, K), dtype).at[:T].set(X)
    Yp = jnp.zeros((Tp, Np), dtype).at[:T, :N].set(Y)
    Wp = jnp.zeros((Tp, Np), dtype).at[:T, :N].set(W.astype(dtype))

    grid = (Np // tile_n, Tp // tile_t)
    a, b = pl.pallas_call(
        _gram_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((tile_t, K), lambda i, j: (j, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((tile_t, tile_n), lambda i, j: (j, i), memory_space=pltpu.VMEM),
            pl.BlockSpec((tile_t, tile_n), lambda i, j: (j, i), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((K * K, tile_n), lambda i, j: (0, i), memory_space=pltpu.VMEM),
            pl.BlockSpec((K, tile_n), lambda i, j: (0, i), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((K * K, Np), acc_dtype),
            jax.ShapeDtypeStruct((K, Np), acc_dtype),
        ],
        cost_estimate=pl.CostEstimate(
            flops=2 * Tp * Np * K * (K + 1) + Tp * K * K,
            bytes_accessed=(Tp * K + 2 * Tp * Np) * dtype.itemsize
            + Np * K * (K + 1) * jnp.dtype(acc_dtype).itemsize,
            transcendentals=0,
        ),
        interpret=interpret,
    )(Xp, Yp, Wp)
    return a[:, :N].T.reshape(N, K, K), b[:, :N].T


def masked_gram_xla(
    X: jnp.ndarray, Y: jnp.ndarray, W: jnp.ndarray
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Reference XLA path: the einsum pair the kernel fuses.

    Same dtype contract as the kernel: bf16 inputs contract with an f32
    accumulator and return f32 Grams."""
    acc_dtype = jnp.promote_types(X.dtype, jnp.float32)
    W = W.astype(X.dtype)
    A = jnp.einsum("tk,tn,tl->nkl", X, W, X, preferred_element_type=acc_dtype)
    rhs = jnp.einsum(
        "tk,tn->nk", X, W * Y, preferred_element_type=acc_dtype
    )
    return A, rhs


# dispatch: two live-v5e measurements exist and disagree on the win size
# (both via the bench.py harness, K=8, f32, tunneled chip):
#   - crossover table (r2 mid-round): XLA 1.7x faster at 224x256, parity
#     at 512x512, kernel 1.4-1.7x faster from 1024x2048 up;
#   - final r2 bench at the flagship 2048x4096: kernel 1.09x faster.
# Neither run saw the kernel LOSE past 512x512 = 2^18 cells, so the
# dispatch stays at 1<<19 (safely past the crossover); the win-size
# discrepancy is recorded honestly in docs/CHANGELOG.md and the standing
# action when hardware is reachable is `python bench.py --crossover` to
# re-measure and collapse these two claims into one table.
_PALLAS_MIN_CELLS = 1 << 19
_TPU_PLATFORMS = ("tpu", "axon")  # axon = tunneled TPU plugin


def _context_platform() -> str:
    """Platform the computation will actually run on: the `backend=` kwargs
    set ``jax.default_device`` (utils/backend.on_backend), which
    ``jax.default_backend()`` ignores — so consult the context first."""
    dev = jax.config.jax_default_device
    return dev.platform if dev is not None else jax.default_backend()


def _ring_reduce_kernel(
    n_dev, axis_name, local_ref, out_ref, comm_ref, send_sem, recv_sem
):
    """Ring-permute all-reduce over `axis_name` (n_dev devices).

    Double-buffered: while the accumulator adds the chunk that just landed
    in one comm slot, the RDMA engine is already pushing the other slot to
    the right neighbour, so the n_dev-1 ICI hops overlap with the local
    adds (and, at the XLA schedule level, with the masked-GEMM tiles of
    the collapse that feeds this reduction).  After step s every device
    holds the partial buffer originally computed by the device s+1 hops to
    its left; summing all n_dev-1 arrivals into the local copy yields the
    full cross-section reduction with no host involvement.
    """
    my_id = jax.lax.axis_index(axis_name)
    out_ref[:] = local_ref[:]
    comm_ref[0] = local_ref[:]
    for step in range(n_dev - 1):
        send_slot = step % 2
        recv_slot = (step + 1) % 2
        dst = jax.lax.rem(my_id + 1, n_dev)
        rdma = pltpu.make_async_remote_copy(
            src_ref=comm_ref.at[send_slot],
            dst_ref=comm_ref.at[recv_slot],
            send_sem=send_sem.at[send_slot],
            recv_sem=recv_sem.at[recv_slot],
            device_id=(dst,),
            device_id_type=pltpu.DeviceIdType.LOGICAL,
        )
        rdma.start()
        rdma.wait()
        out_ref[:] += comm_ref[recv_slot]


def _ring_allreduce_pallas(x: jnp.ndarray, axis_name: str, n_dev: int) -> jnp.ndarray:
    """TPU ring all-reduce as a Pallas kernel (call inside shard_map)."""
    return pl.pallas_call(
        functools.partial(_ring_reduce_kernel, n_dev, axis_name),
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((2,) + x.shape, x.dtype),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
        ],
        compiler_params=pltpu.TPUCompilerParams(collective_id=0),
    )(x)


def ring_allreduce(x: jnp.ndarray, axis_name: str, n_dev: int) -> jnp.ndarray:
    """Sum `x` across mesh axis `axis_name` (must be called under shard_map).

    Platform dispatch mirrors `masked_gram`: on TPU the reduction is the
    Pallas ring kernel above (remote DMA hops overlapped with the local
    accumulate); on CPU / interpret-mode platforms it lowers to XLA's
    `lax.psum`, which is what every CI test exercises — the two are the
    same mathematical reduction over the same ring order, so parity tests
    on the virtual CPU mesh validate the sharded numerics while the
    kernel path stays TPU-only.

    Comm accounting (PR 17): the payload bytes are a static property of
    the traced program, so they are recorded host-side HERE, at trace
    time — a ring moves the full per-device payload across n_dev - 1
    links per call (utils/roofline.py tags the entry with the mesh
    axis).  Nothing is added to the compiled computation.
    """
    from ..utils.roofline import record_collective, tensor_nbytes

    record_collective(
        "pallas_gram.ring_allreduce", axis_name, tensor_nbytes(x),
        hops=max(1, n_dev - 1), collective="ring", dtype=str(x.dtype),
    )
    if _context_platform() in _TPU_PLATFORMS and n_dev > 1:
        return _ring_allreduce_pallas(x, axis_name, n_dev)
    return jax.lax.psum(x, axis_name)


def hierarchical_allreduce(
    x: jnp.ndarray, ici_axis: str, dcn_axis: str, n_ici: int
) -> jnp.ndarray:
    """Two-level all-reduce for a process-spanning ``("dcn", "ici")`` mesh
    (must be called under shard_map).

    Stage 1 sums over the intra-host `ici_axis` with `ring_allreduce` —
    the Pallas RDMA ring on TPU, `lax.psum` elsewhere — so the bulk of
    the cross-section combine rides the fast intra-host interconnect.
    Stage 2 is ONE `lax.psum` over the cross-host `dcn_axis`: after
    stage 1 every device on a host holds the identical host-local sum,
    so only host-count-many distinct values cross the (slow, per-hop
    expensive) data-center network, and each device participates in a
    single DCN collective of the already-reduced payload.

    With `n_ici` devices per host the result equals the flat reduction
    over the flattened ``(dcn, ici)`` axis tuple up to summation order;
    the tier-1 proxy pins hierarchical == flat at 1e-12 on the virtual
    CPU mesh (tests/test_multihost.py).

    The DCN stage's payload bytes are recorded at trace time (one psum
    of the already-reduced payload per call) — this is the measured
    counterpart of the hand-derived bench field
    ``dcn_payload_bytes_per_iter``, pinned equal on the 2-process proxy
    in tests/test_obs.py.
    """
    from ..utils.roofline import record_collective, tensor_nbytes

    x = ring_allreduce(x, ici_axis, n_ici)
    record_collective(
        "pallas_gram.hierarchical_allreduce.dcn", dcn_axis,
        tensor_nbytes(x), hops=1, collective="psum", dtype=str(x.dtype),
    )
    return jax.lax.psum(x, dcn_axis)


def masked_gram(
    X: jnp.ndarray, Y: jnp.ndarray, W: jnp.ndarray, use_pallas: bool | None = None
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Batched masked normal equations with size/platform auto-dispatch."""
    if use_pallas is None:
        on_tpu = _context_platform() in _TPU_PLATFORMS
        use_pallas = on_tpu and X.shape[0] * Y.shape[1] >= _PALLAS_MIN_CELLS
    if use_pallas:
        return masked_gram_pallas(X, Y, W)
    return masked_gram_xla(X, Y, W)
