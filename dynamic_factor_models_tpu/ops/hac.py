"""Newey-West HAC covariance, Chow and QLR (sup-Wald) break tests.

TPU-native rewrite of reference cells 46-58.  The QLR scan over break dates —
the reference's widest hot loop (SURVEY.md section 3.5, thousands of small HAC
regressions) — is a single ``vmap`` over breaks here, and callers further
``vmap`` over series.

Inputs are dense (already compacted) series: the driver compacts [y X] rows
before testing, exactly as the reference does (Stock_Watson.ipynb cell 57).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from .linalg import ols, solve_normal

__all__ = [
    "form_kernel",
    "hac",
    "hac_weighted",
    "regress_hac",
    "compute_chow",
    "compute_qlr",
]


def form_kernel(q: int) -> jnp.ndarray:
    """Bartlett kernel weights 1 - i/(q+1), i = 0..q (cell 46)."""
    return 1.0 - jnp.arange(q + 1) / (q + 1)


def hac(u: jnp.ndarray, X: jnp.ndarray, q: int):
    """HAC covariance of OLS coefficients and its standard errors (cell 53)."""
    return hac_weighted(u, X, form_kernel(q))


def hac_weighted(u: jnp.ndarray, X: jnp.ndarray, kernel: jnp.ndarray):
    """HAC covariance with an explicit lag-weight vector of length q_max+1.

    The truncation may be traced: pass Bartlett weights
    ``max(0, 1 - i/(q+1))`` with a traced q and zeros beyond it, so callers
    can ``vmap`` over different truncation lags at a shared static q_max.
    """
    z = X * u[:, None]
    T = z.shape[0]
    v = kernel[0] * z.T @ z
    for i in range(1, kernel.shape[0]):
        gamma = z[i:].T @ z[: T - i]
        v = v + kernel[i] * (gamma + gamma.T)
    XX = X.T @ X
    XXinv = jnp.linalg.pinv(XX, hermitian=True)
    vbeta = XXinv @ v @ XXinv
    return vbeta, jnp.sqrt(jnp.diag(vbeta))


def regress_hac(y: jnp.ndarray, X: jnp.ndarray, q: int):
    """OLS with HAC variance (cell 51)."""
    betahat, ehat = ols(y, X)
    vbeta, se_beta = hac(ehat, X, q)
    return betahat, vbeta, se_beta


@partial(jax.jit, static_argnames=("q",))
def compute_chow(y: jnp.ndarray, X: jnp.ndarray, q: int, n_pre) -> jnp.ndarray:
    """Chow break-test Wald statistic with HAC(q) variance (cell 49).

    `n_pre` is the number of pre-break rows (the reference's `T_break`,
    i.e. D = [zeros(n_pre); ones(T-n_pre)]); may be a traced value so QLR can
    vmap over break dates.
    """
    k = X.shape[1]
    T = y.shape[0]
    D = (jnp.arange(T) >= n_pre).astype(X.dtype)
    Xfull = jnp.hstack([X, X * D[:, None]])
    betahat, vbeta, _ = regress_hac(y, Xfull, q)
    gamma = betahat[k:]
    v1 = vbeta[k:, k:]
    return gamma @ solve_normal(v1, gamma)


@partial(jax.jit, static_argnames=("ccut", "q"))
def compute_qlr(
    y: jnp.ndarray,
    X2: jnp.ndarray,
    ccut: float,
    q: int,
    X1: jnp.ndarray | None = None,
):
    """QLR sup-Wald over central break dates (cell 58).

    Returns (max Chow with q=0, max Chow with HAC(q)).  When exogenous
    regressors X1 are supplied, only X2's coefficients break — the reference's
    vcat shape bug on this path (SURVEY.md section 2.5 quirk 2) is fixed here;
    the reference only ever exercises X1=None.
    """
    T = y.shape[0]
    n1t = int(ccut * T)
    n2t = T - n1t
    breaks = jnp.arange(n1t, n2t + 1)

    if X1 is None:
        chow0 = jax.vmap(lambda b: compute_chow(y, X2, 0, b))(breaks)
        chowq = jax.vmap(lambda b: compute_chow(y, X2, q, b))(breaks)
    else:
        k = X2.shape[1]

        def chow_partial(qq, n_pre):
            D = (jnp.arange(T) >= n_pre).astype(X2.dtype)
            Xfull = jnp.hstack([X1, X2, X2 * D[:, None]])
            betahat, vbeta, _ = regress_hac(y, Xfull, qq)
            gamma = betahat[-k:]
            v1 = vbeta[-k:, -k:]
            return gamma @ solve_normal(v1, gamma)

        chow0 = jax.vmap(lambda b: chow_partial(0, b))(breaks)
        chowq = jax.vmap(lambda b: chow_partial(q, b))(breaks)
    return chow0.max(), chowq.max()
