"""Lag matrices and univariate autoregressions (reference cell 18)."""

from __future__ import annotations

import jax.numpy as jnp

from .linalg import ols_masked
from .masking import compact, mask_of

__all__ = ["lagmat", "uar", "detrended_year_growth"]


def lagmat(X: jnp.ndarray, lags) -> jnp.ndarray:
    """Stack lagged copies of X's columns with leading NaN padding.

    lags is a static sequence (e.g. range(1, 5)).  Column block i holds
    X lagged by lags[i].
    """
    X = jnp.atleast_2d(X.T).T  # promote vectors to (T, 1)
    T, nc = X.shape
    blocks = []
    for lag in lags:
        pad = jnp.full((lag, nc), jnp.nan, dtype=X.dtype)
        blocks.append(jnp.vstack([pad, X[: T - lag]]))
    return jnp.hstack(blocks)


def uar(y: jnp.ndarray, n_lags: int, valid: jnp.ndarray | None = None):
    """AR(n_lags) on a (compacted) series by OLS; returns (coef, ser).

    `valid` marks the live prefix when y comes from ``masking.compact``.
    The ser uses the reference's dof convention sqrt(ssr / (T_valid - n_lags))
    (dfm_functions.ipynb cell 18, `uar`).
    """
    if valid is None:
        valid = mask_of(y)
    x = lagmat(y, range(1, n_lags + 1))
    # a row is usable when it is in the live prefix, beyond the lag padding,
    # and none of its lag values are missing (compacted prefixes satisfy the
    # last condition automatically)
    w = valid & mask_of(x).all(axis=1) & (jnp.arange(y.shape[0]) >= n_lags)
    coef, ehat = ols_masked(y, jnp.nan_to_num(x), w)
    ssr = jnp.where(w, jnp.nan_to_num(ehat), 0.0) ** 2
    ser = jnp.sqrt(ssr.sum() / (valid.sum() - n_lags))
    return coef, ser


def detrended_year_growth(y: jnp.ndarray) -> jnp.ndarray:
    """4-quarter rolling sum via lagmat (reference cell 28)."""
    return lagmat(y, range(0, 4)).sum(axis=1)
