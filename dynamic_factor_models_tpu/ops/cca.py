"""Canonical correlation analysis via SVD whitening.

Replacement for the reference's MultivariateStats.jl `fit(CCA, ...,
method=:svd)` calls (Stock_Watson.ipynb cells 60-61).  Columns are centered,
each block is whitened by its thin SVD, and the canonical correlations are
the singular values of the cross product of the whitened blocks.
"""

from __future__ import annotations

import jax.numpy as jnp

__all__ = ["canonical_correlations"]


def canonical_correlations(X: jnp.ndarray, Y: jnp.ndarray) -> jnp.ndarray:
    """Canonical correlations between X (n, p) and Y (n, q), descending.

    Observations in rows.  Returns min(p, q) values in [0, 1].
    """
    Xc = X - X.mean(axis=0)
    Yc = Y - Y.mean(axis=0)
    Ux, sx, _ = jnp.linalg.svd(Xc, full_matrices=False)
    Uy, sy, _ = jnp.linalg.svd(Yc, full_matrices=False)
    # drop numerically null directions to keep correlations <= 1
    Ux = jnp.where(sx > sx.max() * 1e-12, 1.0, 0.0)[None, :] * Ux
    Uy = jnp.where(sy > sy.max() * 1e-12, 1.0, 0.0)[None, :] * Uy
    s = jnp.linalg.svd(Ux.T @ Uy, compute_uv=False)
    k = min(X.shape[1], Y.shape[1])
    return jnp.clip(s[:k], 0.0, 1.0)
