from .masking import mask_of, fillz, compact, row_mask
from .linalg import (
    solve_normal,
    ols,
    ols_masked,
    ols_batched_series,
    pca_score,
    standardize_data,
    compute_r2,
    varimax,
)
from .lags import lagmat, uar, detrended_year_growth
from .hac import form_kernel, hac, regress_hac, compute_chow, compute_qlr
from .filters import (
    compute_bw_weight,
    compute_gain,
    ma_weight,
    baxter_king_lowpass_weight,
)
