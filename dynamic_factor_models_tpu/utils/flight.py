"""Fault-dump flight recorder: a bounded in-memory ring of recent
guard / ladder / breaker / fault events, dumped as ONE timestamped JSON
bundle when something goes wrong.

The JSONL sink answers "what happened over the run"; what it cannot
answer at 3am is "what were the last 200 things that happened BEFORE the
guard tripped, and what had the device been doing" — by the time someone
attaches, the interesting tail is interleaved with a million healthy
lines.  The flight recorder keeps that tail pre-assembled:

- ``record(kind, **fields)`` appends one event to a bounded ring
  (``collections.deque(maxlen=...)``).  Gated on ``telemetry.enabled()``
  — the ring is allocated LAZILY on the first recorded event, so a
  clean disabled-telemetry run performs zero allocations here (pinned
  in tests/test_obs.py).
- ``dump(trigger, ...)`` writes ``flight-<utc>-<trigger>.json`` —
  trigger event, ring contents, the telemetry record tail, the roofline
  kernel-ledger snapshot (``utils/roofline.py``), and the counter/gauge
  registry — via tmp+rename+fsync so a crash mid-dump can't leave a
  truncated bundle.  Dumps are throttled (one per
  ``DFM_FLIGHT_MIN_INTERVAL_S``, default 5s) unless forced, so a fault
  storm produces a bundle per episode, not per envelope.

Triggers wired in this PR: EM guard trips / ladder exhaustion
(models/emloop.py), serving typed ``system_fault`` envelopes, breaker
opens and injected ``engine_crash`` kills (serving/engine.py), SLO pages
(engine.flush_metrics), injected faults (utils/faults.fault_fired),
router-worker deaths (serving/router.py — FORCED, one bundle per death
even inside the throttle window, carrying the worker id, death reason
and detect latency), and SIGTERM/atexit (installed on the first
*event*-severity record; the exit dump fires only when an armed event
is still undumped).  Drills ride the
existing ``DFM_FAULTS`` grammar — ``DFM_FAULTS=nan_estep@3`` produces a
bundle with no bespoke test plumbing.

Dump directory: ``DFM_FLIGHT_DIR``, else the telemetry sink's directory,
else ``build/flight``.
"""

from __future__ import annotations

import atexit
import collections
import json
import os
import signal
import threading
import time

__all__ = [
    "armed",
    "dump",
    "dump_dir",
    "install",
    "last_dump_path",
    "record",
    "reset",
    "ring",
    "ring_len",
]

_lock = threading.RLock()

# the ring: None until the first enabled record — the disabled clean
# path must allocate NOTHING (acceptance-pinned)
_ring: collections.deque | None = None
_seq = 0
_armed = False          # an event-severity record awaits a dump
_installed = False      # atexit/SIGTERM hooks registered
_last_dump_t = 0.0
_last_dump_path: str | None = None


def _ring_maxlen() -> int:
    raw = os.environ.get("DFM_FLIGHT_RING", "256") or "256"
    try:
        return max(8, int(raw))
    except ValueError:
        return 256


def _min_interval_s() -> float:
    raw = os.environ.get("DFM_FLIGHT_MIN_INTERVAL_S", "5") or "5"
    try:
        return max(0.0, float(raw))
    except ValueError:
        return 5.0


def dump_dir() -> str:
    d = os.environ.get("DFM_FLIGHT_DIR")
    if d:
        return d
    from . import telemetry as T

    sink = T.sink_path()
    if sink:
        parent = os.path.dirname(sink)
        if parent:
            return os.path.join(parent, "flight")
    return os.path.join("build", "flight")


def record(event: str, severity: str = "event", **fields) -> bool:
    """Append one event to the ring (its type lands under the ring key
    ``kind``); returns True when recorded.

    No-op (False) while telemetry is disabled.  ``severity="event"``
    arms the exit-time dump and installs the SIGTERM/atexit hooks on
    first use; ``severity="info"`` is breadcrumb context (spans, metric
    deltas) that never arms anything.  The parameter is named `event`
    so callers can attach a ``kind=...`` payload field (serving request
    kinds do)."""
    from . import telemetry as T

    if not T.enabled():
        return False
    global _ring, _seq, _armed
    ev = {
        "seq": 0,
        "time_unix": round(time.time(), 6),
        "kind": str(event),
        "severity": severity,
    }
    for k, v in fields.items():
        try:
            json.dumps(v)
            ev[k] = v
        except (TypeError, ValueError):
            ev[k] = repr(v)
    with _lock:
        if _ring is None:
            _ring = collections.deque(maxlen=_ring_maxlen())
        _seq += 1
        ev["seq"] = _seq
        _ring.append(ev)
        if severity == "event":
            _armed = True
    if severity == "event":
        install()
    return True


def ring() -> list[dict]:
    with _lock:
        return list(_ring) if _ring is not None else []


def ring_len() -> int:
    with _lock:
        return len(_ring) if _ring is not None else 0


def armed() -> bool:
    with _lock:
        return _armed


def last_dump_path() -> str | None:
    with _lock:
        return _last_dump_path


def dump(trigger: str, force: bool = False, **fields) -> str | None:
    """Write the flight bundle; returns its path, or None when skipped
    (telemetry disabled, or inside the dump throttle window and not
    forced).  Never raises — a broken disk must not turn a pre-mortem
    into the mortem."""
    from . import telemetry as T

    if not T.enabled():
        return None
    global _last_dump_t, _last_dump_path, _armed
    now = time.time()
    with _lock:
        if not force and (now - _last_dump_t) < _min_interval_s():
            return None
        _last_dump_t = now
    try:
        from . import roofline

        trig = {"trigger": str(trigger), "time_unix": round(now, 6)}
        for k, v in fields.items():
            try:
                json.dumps(v)
                trig[k] = v
            except (TypeError, ValueError):
                trig[k] = repr(v)
        snap = T.snapshot()
        bundle = {
            "version": 1,
            "time_unix": round(now, 6),
            "trigger": trig,
            "ring": ring(),
            "records_tail": T.records()[-32:],
            "kernel_ledger": roofline.ledger_snapshot(),
            "counters": snap["counters"],
            "gauges": snap["gauges"],
        }
        d = dump_dir()
        os.makedirs(d, exist_ok=True)
        ts = time.strftime("%Y%m%dT%H%M%S", time.gmtime(now))
        safe = "".join(
            ch if ch.isalnum() or ch in "-_." else "_"
            for ch in str(trigger)
        )
        path = os.path.join(d, f"flight-{ts}-{os.getpid()}-{safe}.json")
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(bundle, f, indent=1, default=repr)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        with _lock:
            _last_dump_path = path
            _armed = False
        T.inc("flight.dumps")
        return path
    except Exception:
        return None


# ---------------------------------------------------------------------------
# process-exit triggers
# ---------------------------------------------------------------------------


def _exit_dump() -> None:
    if armed():
        dump("atexit", force=True)


def _sigterm(signum, frame) -> None:
    dump("sigterm", force=True)
    signal.signal(signal.SIGTERM, signal.SIG_DFL)
    os.kill(os.getpid(), signal.SIGTERM)


def install() -> None:
    """Register the atexit hook (always) and a SIGTERM handler (only
    when no application handler is present and we are on the main
    thread).  Idempotent; called automatically on the first
    event-severity `record`."""
    global _installed
    with _lock:
        if _installed:
            return
        _installed = True
    atexit.register(_exit_dump)
    try:
        if (
            threading.current_thread() is threading.main_thread()
            and signal.getsignal(signal.SIGTERM) == signal.SIG_DFL
        ):
            signal.signal(signal.SIGTERM, _sigterm)
    except (ValueError, OSError):
        pass  # non-main thread / restricted env: atexit still covers us


def reset() -> None:
    """Drop the ring and disarm (tests).  Installed process hooks stay
    — they are idempotent no-ops while disarmed."""
    global _ring, _seq, _armed, _last_dump_t, _last_dump_path
    with _lock:
        _ring = None
        _seq = 0
        _armed = False
        _last_dump_t = 0.0
        _last_dump_path = None
