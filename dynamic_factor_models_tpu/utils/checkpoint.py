"""Model checkpointing: serialize fitted results as flat npz archives.

The reference recomputes everything from the xlsx each run (SURVEY.md
section 5.4).  Here fitted models (pytrees of arrays) round-trip to a single
.npz; long bootstrap/EM runs can checkpoint per-shard RNG keys and partial
state the same way.
"""

from __future__ import annotations

import numpy as np

import jax

__all__ = ["save_pytree", "load_pytree"]

_SEP = "__"


def save_pytree(path: str, tree) -> None:
    """Save an arbitrary pytree of arrays/scalars to one .npz file."""
    leaves, treedef = jax.tree.flatten(tree)
    payload = {f"leaf{_SEP}{i}": np.asarray(leaf) for i, leaf in enumerate(leaves)}
    payload["treedef"] = np.array(str(treedef))
    np.savez_compressed(path, **payload)


def load_pytree(path: str, like):
    """Load a pytree saved by save_pytree; `like` supplies the structure
    (e.g. a template DFMResults/SSMParams with dummy leaves)."""
    z = np.load(path, allow_pickle=False)
    leaves_like, treedef = jax.tree.flatten(like)
    n = len([k for k in z.files if k.startswith("leaf" + _SEP)])
    if n != len(leaves_like):
        raise ValueError(
            f"checkpoint has {n} leaves but template expects {len(leaves_like)}"
        )
    stored_def = str(z["treedef"])
    if stored_def != str(treedef):
        raise ValueError(
            "checkpoint tree structure does not match the template:\n"
            f"  stored:   {stored_def}\n  template: {treedef}"
        )
    leaves = [z[f"leaf{_SEP}{i}"] for i in range(n)]
    return jax.tree.unflatten(treedef, leaves)
