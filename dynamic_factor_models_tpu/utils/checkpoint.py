"""Model checkpointing: serialize fitted results as flat npz archives.

The reference recomputes everything from the xlsx each run (SURVEY.md
section 5.4).  Here fitted models (pytrees of arrays) round-trip to a single
.npz; long bootstrap/EM runs can checkpoint per-shard RNG keys and partial
state the same way.

Every archive carries a sha256 content checksum over the leaf bytes and
the tree structure, verified on load.  A checkpoint that fails the
checksum, or cannot be read at all (truncated write, media corruption), is
QUARANTINED — renamed to ``<path>.corrupt`` so the evidence survives —
and `CheckpointCorruptError` is raised; `run_em_loop`'s resume path
catches it and restarts the run cleanly instead of crashing mid-resume.
Structural mismatches against the caller's template stay ordinary
ValueErrors: the file is intact, the caller is wrong.
"""

from __future__ import annotations

import hashlib
import os

import numpy as np

import jax

__all__ = [
    "save_pytree",
    "load_pytree",
    "list_entries",
    "CheckpointCorruptError",
]

_SEP = "__"
_CHECKSUM_KEY = "content_sha256"


class CheckpointCorruptError(RuntimeError):
    """A checkpoint failed its content checksum or could not be read; the
    file has been moved to ``<path>.corrupt`` (when possible)."""


def _content_digest(leaves, treedef_str: str) -> str:
    h = hashlib.sha256()
    h.update(treedef_str.encode())
    for leaf in leaves:
        a = np.asarray(leaf)
        h.update(repr((a.shape, str(a.dtype))).encode())
        h.update(np.ascontiguousarray(a).tobytes())
    return h.hexdigest()


def _quarantine(path: str) -> str | None:
    dest = path + ".corrupt"
    try:
        os.replace(path, dest)
        return dest
    except OSError:
        return None


def list_entries(directory: str) -> list[str]:
    """Names (stems) of the checkpoints currently live in `directory`,
    sorted: every ``<name>.npz``, EXCLUDING quarantined ``*.corrupt``
    files and in-flight ``*.npz.tmp.*`` temporaries from the atomic-write
    protocol.  A missing directory is an empty store, not an error — the
    serving tenant store enumerates ids with this before any save has
    happened."""
    try:
        names = os.listdir(directory)
    except FileNotFoundError:
        return []
    return sorted(
        n[: -len(".npz")]
        for n in names
        if n.endswith(".npz") and ".npz.tmp." not in n
    )


def save_pytree(path: str, tree, compress: bool = True) -> None:
    """Save an arbitrary pytree of arrays/scalars to one .npz file,
    including a sha256 checksum of the content for load-time verification.

    ``compress=False`` writes a stored (uncompressed) archive — the
    serving tenant store uses it for its small per-tenant snapshots,
    where deflate costs more wall time than the bytes it saves at
    eviction rates of thousands of snapshots per minute.  The two forms
    load identically."""
    leaves, treedef = jax.tree.flatten(tree)
    leaves = [np.asarray(leaf) for leaf in leaves]
    payload = {f"leaf{_SEP}{i}": leaf for i, leaf in enumerate(leaves)}
    payload["treedef"] = np.array(str(treedef))
    payload[_CHECKSUM_KEY] = np.array(_content_digest(leaves, str(treedef)))
    (np.savez_compressed if compress else np.savez)(path, **payload)


def load_pytree(path: str, like):
    """Load a pytree saved by save_pytree; `like` supplies the structure
    (e.g. a template DFMResults/SSMParams with dummy leaves).

    Raises CheckpointCorruptError (after quarantining the file to
    ``<path>.corrupt``) when the archive is unreadable or its content
    checksum does not match; raises ValueError when the archive is intact
    but its structure does not match `like`.
    """
    import zipfile
    import zlib

    try:
        z = np.load(path, allow_pickle=False)
        files = set(z.files)
        n = len([k for k in files if k.startswith("leaf" + _SEP)])
        stored_def = str(z["treedef"]) if "treedef" in files else None
        leaves = [z[f"leaf{_SEP}{i}"] for i in range(n)]
        stored_sum = str(z[_CHECKSUM_KEY]) if _CHECKSUM_KEY in files else None
    except (
        OSError, ValueError, KeyError, EOFError, zipfile.BadZipFile, zlib.error,
    ) as e:
        dest = _quarantine(path)
        raise CheckpointCorruptError(
            f"checkpoint {path!r} is unreadable ({e}); "
            + (f"quarantined to {dest!r}" if dest else "quarantine failed")
        ) from e
    if stored_def is None:
        dest = _quarantine(path)
        raise CheckpointCorruptError(
            f"checkpoint {path!r} has no tree structure entry; "
            + (f"quarantined to {dest!r}" if dest else "quarantine failed")
        )
    # checksum verifies content integrity BEFORE any structural comparison:
    # a flipped byte must never masquerade as a template mismatch.  Archives
    # from before checksums were stored load uncheck-summed.
    if stored_sum is not None and stored_sum != _content_digest(leaves, stored_def):
        dest = _quarantine(path)
        raise CheckpointCorruptError(
            f"checkpoint {path!r} failed its content checksum; "
            + (f"quarantined to {dest!r}" if dest else "quarantine failed")
        )
    leaves_like, treedef = jax.tree.flatten(like)
    if n != len(leaves_like):
        raise ValueError(
            f"checkpoint has {n} leaves but template expects {len(leaves_like)}"
        )
    if stored_def != str(treedef):
        raise ValueError(
            "checkpoint tree structure does not match the template:\n"
            f"  stored:   {stored_def}\n  template: {treedef}"
        )
    return jax.tree.unflatten(treedef, leaves)
