"""Log-bucketed HDR-style latency histograms.

The serving engine handles a tick in ~500µs; "millions of users" is
judged on p99/p99.9 request latency, which per-run RunRecord wall
clocks cannot express — a JSONL line per request would cost more than
the request.  This module supplies the fixed-cost aggregate: a
fixed-size integer count array over LOG-SPACED latency buckets, in the
HdrHistogram spirit (bounded relative quantile error by construction,
O(1) recording, exact mergeability) but sized for this workload:

* range 100 ns .. 1000 s (``MIN_S`` .. ``MAX_S``), values outside are
  clamped into the edge buckets and tracked exactly via min/max;
* ``SUB_PER_DECADE = 40`` buckets per decade — bucket i covers
  ``[MIN_S * 10^(i/40), MIN_S * 10^((i+1)/40))``, so a quantile read
  off a bucket's geometric midpoint is within ``REL_ERR`` (~2.9%)
  relative error of the true order statistic (pinned by
  tests/test_request_obs.py against exact sorts of adversarial
  bimodal / heavy-tail samples);
* ``record()`` is one ``math.log10`` + one integer add on a
  preallocated flat Python list — no locks, no allocation, never a
  device sync (a list increment is one interpreter op under the GIL,
  ~3x cheaper than a numpy scalar increment, which matters against
  the serving envelope's ~20µs budget);
* ``merge()`` is elementwise count addition: associative and exactly
  equal to the histogram of the concatenated samples, so per-process /
  per-window histograms combine losslessly (load-generator workers,
  ring-buffer windows);
* ``to_dict``/``from_dict`` serialize sparsely (only occupied buckets)
  for the telemetry JSONL sink and the OpenMetrics exporter.

`quantile(q)` uses the nearest-rank definition: the smallest recorded
value whose cumulative count reaches ``ceil(q * n)`` — the same
definition the correctness tests compute from a full sort.
"""

from __future__ import annotations

import math

__all__ = [
    "MIN_S",
    "MAX_S",
    "SUB_PER_DECADE",
    "N_BUCKETS",
    "REL_ERR",
    "LatencyHistogram",
]

MIN_S = 1e-7
DECADES = 10
SUB_PER_DECADE = 40
N_BUCKETS = DECADES * SUB_PER_DECADE
MAX_S = MIN_S * 10.0 ** DECADES

# A value in bucket i lies within [lo, lo*g) with g = 10^(1/SUB); its
# geometric midpoint lo*sqrt(g) is within sqrt(g)-1 of any value in the
# bucket, relatively.  (Clamped out-of-range values are excluded: their
# error is unbounded by design and min/max track them exactly.)
REL_ERR = 10.0 ** (1.0 / (2 * SUB_PER_DECADE)) - 1.0

_LOG_MIN = math.log10(MIN_S)
_INV_LOG_G = SUB_PER_DECADE  # 1 / log10(g)
_log10 = math.log10


def _bucket_index(seconds: float) -> int:
    if not seconds > MIN_S:  # also catches NaN / zero / negative
        return 0
    i = int((math.log10(seconds) - _LOG_MIN) * _INV_LOG_G)
    return i if i < N_BUCKETS else N_BUCKETS - 1


def bucket_lower(i: int) -> float:
    """Lower edge of bucket i, seconds."""
    return MIN_S * 10.0 ** (i / SUB_PER_DECADE)


def bucket_rep(i: int) -> float:
    """Representative value of bucket i: the geometric midpoint."""
    return MIN_S * 10.0 ** ((i + 0.5) / SUB_PER_DECADE)


class LatencyHistogram:
    """Fixed-size log-bucketed latency histogram (module docstring)."""

    __slots__ = ("counts", "n", "sum_s", "min_s", "max_s")

    def __init__(self):
        self.counts = [0] * N_BUCKETS
        self.n = 0
        self.sum_s = 0.0
        self.min_s = math.inf
        self.max_s = 0.0

    # -- recording (the hot path) ----------------------------------------

    def record(self, seconds: float) -> None:
        """O(1) host-side increment; no allocation, no locking.
        (`_bucket_index` is inlined — the call frame alone is ~15% of
        this method's budget on the serving hot path.)"""
        if seconds > MIN_S:  # False for NaN/zero/negative -> bucket 0
            i = int((_log10(seconds) - _LOG_MIN) * _INV_LOG_G)
            if i >= N_BUCKETS:
                i = N_BUCKETS - 1
        else:
            i = 0
        self.counts[i] += 1
        self.n += 1
        self.sum_s += seconds
        if seconds < self.min_s:
            self.min_s = seconds
        if seconds > self.max_s:
            self.max_s = seconds

    # -- merging ---------------------------------------------------------

    def merge(self, other: "LatencyHistogram") -> "LatencyHistogram":
        """Fold `other` into self (elementwise count add — associative,
        exactly the histogram of the concatenated samples).  Returns
        self for chaining."""
        self.counts = [a + b for a, b in zip(self.counts, other.counts)]
        self.n += other.n
        self.sum_s += other.sum_s
        self.min_s = min(self.min_s, other.min_s)
        self.max_s = max(self.max_s, other.max_s)
        return self

    @classmethod
    def merged(cls, hists) -> "LatencyHistogram":
        out = cls()
        for h in hists:
            out.merge(h)
        return out

    # -- quantiles -------------------------------------------------------

    def quantile(self, q: float) -> float:
        """Nearest-rank quantile: the geometric midpoint of the bucket
        holding the ceil(q*n)-th smallest sample (min/max returned
        exactly for q at the extremes).  NaN when empty."""
        if self.n == 0:
            return math.nan
        if q <= 0.0:
            return self.min_s
        if q >= 1.0:
            return self.max_s
        rank = max(1, math.ceil(q * self.n))
        cum = 0
        for i, c in enumerate(self.counts):
            if not c:
                continue
            cum += c
            if cum >= rank:
                # clamp into the exactly-tracked envelope so edge-bucket
                # reps can never fall outside the observed range
                return min(max(bucket_rep(i), self.min_s), self.max_s)
        return self.max_s

    def percentiles(self) -> dict:
        """The serving headline set, in milliseconds."""
        return {
            "p50_ms": 1e3 * self.quantile(0.50),
            "p90_ms": 1e3 * self.quantile(0.90),
            "p99_ms": 1e3 * self.quantile(0.99),
            "p999_ms": 1e3 * self.quantile(0.999),
            "max_ms": 1e3 * self.max_s if self.n else math.nan,
            "mean_ms": 1e3 * self.sum_s / self.n if self.n else math.nan,
            "n": self.n,
        }

    # -- (de)serialization ------------------------------------------------

    def to_dict(self) -> dict:
        """Sparse JSON form: only occupied buckets."""
        return {
            "v": 1,
            "n": int(self.n),
            "sum_s": float(self.sum_s),
            "min_s": float(self.min_s) if self.n else None,
            "max_s": float(self.max_s) if self.n else None,
            "counts": {i: c for i, c in enumerate(self.counts) if c},
        }

    @classmethod
    def from_dict(cls, d: dict) -> "LatencyHistogram":
        h = cls()
        h.n = int(d.get("n", 0))
        h.sum_s = float(d.get("sum_s", 0.0))
        h.min_s = d.get("min_s")
        h.min_s = math.inf if h.min_s is None else float(h.min_s)
        h.max_s = float(d.get("max_s") or 0.0)
        for i, c in (d.get("counts") or {}).items():
            h.counts[int(i)] = int(c)
        return h

    def cumulative_below(self, bucket: int) -> int:
        """Samples recorded in buckets [0, bucket) — the OpenMetrics
        `_bucket{le=bucket_lower(bucket)}` cumulative count, exact by
        working in bucket indices rather than float edges."""
        return sum(self.counts[:bucket])

    def __repr__(self):
        return (
            f"LatencyHistogram(n={self.n}, "
            f"p50={1e3 * self.quantile(0.5):.3g}ms, "
            f"p99={1e3 * self.quantile(0.99):.3g}ms)"
        )
