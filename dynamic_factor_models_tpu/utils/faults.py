"""Deterministic fault injection: the chaos half of the guardrail layer.

`utils/guards.py` gives the EM loop a health sentinel and a recovery
ladder; this module supplies the *reproducible faults* that prove the
ladder works — every failure mode the guards claim to survive can be
forced, at an exact iteration or checkpoint chunk, from one environment
variable.  tests/test_chaos.py and `bench.py --chaos` are the consumers;
`tools/tpu_watch.sh` runs one injected-preemption resume per live window.

Spec grammar (``DFM_FAULTS``, also `inject()` below)::

    DFM_FAULTS="<clause>[;<clause>...]"       # ';' or ',' separated
    clause := kind [@ n ['+']]                # n: positive int site index

    nan_estep@k     force the k-th EM iteration's log-likelihood to NaN
                    (1-based; the sentinel sees a non-finite E-step)
    chol_fail@k     poison the factor innovation covariance Q entering
                    the k-th EM iteration with NaN, so the filter's
                    Cholesky factorization fails and floods the step
    nan_draw@k      force chain 0's k-th Gibbs sweep to draw a NaN
                    factor path (scenarios/gibbs.py multi-chain
                    sampler) — the divergent-chain drop drill
    ckpt_corrupt@n  after the n-th successful checkpoint chunk save,
                    corrupt the archive in place (truncate to half) —
                    the next resume must quarantine and restart
    preempt@n       raise SimulatedPreemption immediately after the
                    n-th checkpoint chunk save — a mid-run kill whose
                    resume must be bit-identical to an unkilled run

Serving-path kinds (counted by serving/engine + serving/store, the
chaos-serving drills in tests/test_chaos_serving.py and
``bench.py --chaos-serving``)::

    tick_nan@n      poison the RESULT of the n-th online tick through a
                    ServingEngine (a transient compute fault: the input
                    row stays clean in the replay buffer, so recovery
                    must reconcile to the fault-free run)
    store_io@n      the n-th tenant-store I/O operation (snapshot save
                    or journal append) raises OSError — the transient
                    fault the engine's bounded retry must absorb
    slow_req@n      stall the n-th engine request past its deadline
                    (the request must come back deadline_exceeded, not
                    hang or corrupt state)
    engine_crash@n  raise SimulatedCrash at admission of the n-th
                    engine request — a process kill whose restart must
                    replay the tick journal bit-identically
    crash_io@n      raise SimulatedCrash immediately BEFORE the n-th
                    tenant-store I/O operation (the same shared counter
                    as ``store_io``) — the kill-at-every-step drill:
                    because each store op is atomic (temp+rename or a
                    single fsynced append), killing before op n models
                    every possible crash point in an evict / fault-in /
                    batch-commit sequence
    stall_commit@n  the n-th committed serving round's COMMIT stage
                    sleeps past the round's deadline budget before
                    applying the (already journaled, hence durable)
                    lane states — the pipelined-serving drill: acks
                    arrive late, SLO burn shows it, and a flight
                    bundle records the stall; state stays exact
    queue_full@n    the n-th pipeline admission is shed as if the
                    bounded admission queue were saturated — the
                    request comes back a typed ``queue_full`` system
                    fault without ever forming a lane
    kill_worker@n   SIGKILL the engine worker targeted by the n-th
                    router→worker RPC (TenantRouter; the inproc
                    backend discards the worker's in-memory engine,
                    the exact state a process kill loses) — the
                    supervision drill: detect, shed typed
                    ``worker_unavailable``, respawn, recover
    stall_worker@n  the worker targeted by the n-th router→worker RPC
                    stops responding (the process backend really
                    sleeps the worker; inproc degenerates to a kill)
                    — the deadline-bounded-RPC drill: the router must
                    declare the worker dead within the heartbeat
                    deadline, never hang on the pipe

Unsuffixed ``ckpt_corrupt`` / ``preempt`` / ``engine_crash`` default to
n=1; every other kind requires an explicit site.

By default an in-loop fault (`nan_estep`, `chol_fail`) is TRANSIENT: it
is baked only into the FIRST guarded-loop attempt's program, so the
recovery ladder's retries run clean — the chaos tests pin the recovered
run against an uninjected one.  A trailing ``+`` (``nan_estep@3+``)
makes it PERSISTENT: it re-fires on every same-program retry (the jitter
rungs) and only stops applying when a rung changes the step or its dtype
(demote / promote_f64) — the shape of a fault tied to one compiled
program, used to exercise the deeper rungs deterministically.  The
checkpoint faults fire once per `run_em_loop` call when the chunk
counter hits n and ignore ``+``.

For the serving kinds ``+`` means a fault STORM rather than a one-shot:
``tick_nan@1+`` poisons EVERY tick from site 1 onward while the plan is
active (the circuit-breaker open drill), ``store_io@2+`` fails every
store op from the 2nd on (retry exhaustion), ``slow_req@1+`` stalls
every request, ``stall_commit@1+`` stalls every round's commit stage
(the sustained-backpressure drill) and ``queue_full@1+`` sheds every
admission from site 1 on (total saturation).  ``engine_crash``,
``crash_io``, ``kill_worker`` and ``stall_worker`` are kills — they fire
once and cannot be persistent.

Everything here is host-side and import-cheap; with no spec active every
probe returns the empty plan and the guarded program is unchanged.
"""

from __future__ import annotations

import contextlib
import os
import threading
from typing import NamedTuple

__all__ = [
    "FaultPlan",
    "EMPTY_PLAN",
    "SimulatedPreemption",
    "SimulatedCrash",
    "parse_spec",
    "active_plan",
    "inject",
    "fault_fired",
    "site_hits",
    "corrupt_file",
]

_lock = threading.RLock()
_override: "FaultPlan | None" = None

_KINDS = (
    "nan_estep", "chol_fail", "nan_draw", "ckpt_corrupt", "preempt",
    "tick_nan", "store_io", "slow_req", "engine_crash", "crash_io",
    "stall_commit", "queue_full", "kill_worker", "stall_worker",
)
# kinds where a bare clause means "at the first site"
_DEFAULT_SITE = {"ckpt_corrupt": 1, "preempt": 1, "engine_crash": 1}
# kinds a trailing '+' may mark persistent (in-loop retries / serving storms)
_PERSISTABLE = frozenset({
    "nan_estep", "chol_fail", "nan_draw", "tick_nan", "store_io",
    "slow_req", "stall_commit", "queue_full",
})


class SimulatedPreemption(RuntimeError):
    """Raised by the checkpointing loop at an injected `preempt@n` site.

    Deliberately NOT a KeyboardInterrupt subclass: tests and the watcher
    catch it precisely, and nothing in the library may swallow it —
    preemption recovery happens in the NEXT run, via checkpoint resume.
    """


class SimulatedCrash(RuntimeError):
    """Raised at an injected `engine_crash@n` site: a process kill at
    request admission.  Like SimulatedPreemption it models an EXTERNAL
    death — the serving engine's error envelope must NOT absorb it;
    recovery happens in the next process via tick-journal replay."""


class FaultPlan(NamedTuple):
    """Parsed DFM_FAULTS spec: 1-based site index per kind (None = off)
    plus the set of kinds flagged persistent with a trailing ``+``."""

    nan_estep: int | None = None
    chol_fail: int | None = None
    ckpt_corrupt: int | None = None
    preempt: int | None = None
    nan_draw: int | None = None
    tick_nan: int | None = None
    store_io: int | None = None
    slow_req: int | None = None
    engine_crash: int | None = None
    crash_io: int | None = None
    stall_commit: int | None = None
    queue_full: int | None = None
    kill_worker: int | None = None
    stall_worker: int | None = None
    persistent: frozenset = frozenset()

    def any(self) -> bool:
        return any(v is not None for v in self[:-1])

    def hits(self, kind: str, count: int) -> bool:
        """Does the `count`-th pass through a site-counted probe fire
        the `kind` fault?  One-shot at the exact site by default; a
        persistent kind fires at every count >= its site (the serving
        fault-storm semantics)."""
        site = getattr(self, kind)
        if site is None:
            return False
        if kind in self.persistent:
            return count >= site
        return count == site


EMPTY_PLAN = FaultPlan()


def parse_spec(spec: str | None) -> FaultPlan:
    """Parse a DFM_FAULTS spec string into a FaultPlan.

    Raises ValueError on an unknown kind, a malformed site index, or a
    kind that needs an explicit site — a chaos run with a typo'd spec
    must fail loudly, not silently run un-injected.
    """
    if not spec or not spec.strip():
        return EMPTY_PLAN
    plan: dict[str, int] = {}
    persistent: set[str] = set()
    for raw in spec.replace(",", ";").split(";"):
        clause = raw.strip()
        if not clause:
            continue
        kind, _, site = clause.partition("@")
        kind = kind.strip()
        site = site.strip()
        if kind not in _KINDS:
            raise ValueError(
                f"DFM_FAULTS: unknown fault kind {kind!r} in clause "
                f"{clause!r}; valid kinds: {', '.join(_KINDS)}"
            )
        persist = site.endswith("+")
        if persist:
            site = site[:-1].strip()
        if site:
            try:
                n = int(site)
            except ValueError:
                raise ValueError(
                    f"DFM_FAULTS: bad site index {site!r} in clause "
                    f"{clause!r} (want a positive integer)"
                ) from None
        elif kind in _DEFAULT_SITE:
            n = _DEFAULT_SITE[kind]
        else:
            raise ValueError(
                f"DFM_FAULTS: {kind!r} needs an iteration, e.g. '{kind}@3'"
            )
        if n < 1:
            raise ValueError(
                f"DFM_FAULTS: site index must be >= 1 in clause {clause!r}"
            )
        if kind in plan:
            raise ValueError(f"DFM_FAULTS: duplicate clause for {kind!r}")
        plan[kind] = n
        if persist:
            if kind not in _PERSISTABLE:
                raise ValueError(
                    f"DFM_FAULTS: '+' (persistent) does not apply to "
                    f"{kind!r} (valid for: {', '.join(sorted(_PERSISTABLE))})"
                )
            persistent.add(kind)
    return FaultPlan(persistent=frozenset(persistent), **plan)


def site_hits(kind: str, count: int) -> bool:
    """Probe the active plan at a site-counted fault point: True when the
    `count`-th pass through the `kind` site should fault (see
    FaultPlan.hits).  The caller acts on the fault and reports it via
    `fault_fired(kind)`."""
    return active_plan().hits(kind, count)


def active_plan() -> FaultPlan:
    """The currently active plan: an `inject()` override when one is
    open, else the parsed ``DFM_FAULTS`` env var, else the empty plan."""
    with _lock:
        if _override is not None:
            return _override
    return parse_spec(os.environ.get("DFM_FAULTS"))


@contextlib.contextmanager
def inject(spec: str | FaultPlan):
    """In-process fault activation for tests: ``with inject("nan_estep@3"):``
    overrides the environment for the duration of the block."""
    global _override
    plan = parse_spec(spec) if isinstance(spec, str) else plan_check(spec)
    with _lock:
        prev = _override
        _override = plan
    try:
        yield plan
    finally:
        with _lock:
            _override = prev


def plan_check(plan: FaultPlan) -> FaultPlan:
    if not isinstance(plan, FaultPlan):
        raise TypeError(f"expected FaultPlan or spec string, got {plan!r}")
    return plan


def fault_fired(kind: str) -> None:
    """Count one injected fault in the telemetry registry (and per kind),
    and drop a breadcrumb into the flight-recorder ring so a later dump
    shows the injections that preceded the trigger."""
    from .flight import record
    from .telemetry import inc

    inc("faults_injected")
    inc("faults_injected." + kind)
    record("fault_injected", severity="info", fault=kind)


def corrupt_file(path: str, mode: str = "truncate") -> None:
    """Deterministically damage a file in place.

    mode="truncate" halves it (an interrupted write); mode="flip" XORs a
    byte in the middle (silent media corruption — defeats any parser that
    doesn't checksum).  Used by the ckpt_corrupt injection site and the
    chaos tests.
    """
    size = os.path.getsize(path)
    if mode == "truncate":
        with open(path, "r+b") as f:
            f.truncate(max(size // 2, 1))
    elif mode == "flip":
        # The flipped byte must land inside a member's payload: zip
        # archives carry alignment padding between members, and a flip
        # there leaves the decoded content bit-identical (nothing to
        # detect).  For a zip (.npz) aim at the middle of the first
        # member's data; for anything else fall back to the file middle.
        target = size // 2
        import struct
        import zipfile

        try:
            with zipfile.ZipFile(path) as z:
                info = z.infolist()[0]
                with open(path, "rb") as f:
                    f.seek(info.header_offset)
                    hdr = f.read(30)
                name_len, extra_len = struct.unpack("<HH", hdr[26:30])
                data_off = info.header_offset + 30 + name_len + extra_len
                target = data_off + info.compress_size // 2
        except (zipfile.BadZipFile, IndexError, struct.error):
            pass
        with open(path, "r+b") as f:
            f.seek(target)
            b = f.read(1)
            f.seek(target)
            f.write(bytes([b[0] ^ 0xFF]) if b else b"\xff")
    else:
        raise ValueError(f"unknown corrupt_file mode {mode!r}")
    fault_fired("ckpt_corrupt")
