"""SLO objects and multi-window burn-rate monitors.

KATANA's real-time Kalman deployment (PAPERS.md) frames the serving
target: hard latency budgets verified CONTINUOUSLY, not benchmarked
once.  An `SLO` here is the standard latency objective "at least
`objective` of requests of kind `kind` complete within `threshold_s`",
and its health is judged the SRE way — by the BURN RATE of the error
budget over two windows:

    burn = (bad fraction in window) / (1 - objective)

* burn == 1 means the budget is being consumed exactly at the
  sustainable rate; burn <= 1 in the fast window is "green";
* an ALERT requires BOTH windows hot (fast 5 m AND slow 1 h by
  default, factor `alert_burn`, default 14.4 — the classic page-worthy
  multi-window rule): the slow window keeps one latency spike from
  paging, the fast window ends the alert promptly once the bleed
  stops.

Windows are ring buffers of (good, total) slot counters — O(1) per
`observe`, O(n_slots) per read, no per-request allocation — so the
monitor rides the serving envelope without touching a device.  The
clock is injectable (`clock=`) so tests and the load generator can
exercise hour-scale windows in microseconds.

`SLO.gauges()` returns the monitor state as flat gauge values; the
serving engine pushes them into the telemetry registry
(``slo.<name>.burn_fast`` etc.) where the OpenMetrics exporter picks
them up.
"""

from __future__ import annotations

import math
import time

__all__ = ["WindowedCounts", "SLO"]


class WindowedCounts:
    """Ring buffer of per-slot (good, total) counters covering the
    trailing `window_s` seconds in `n_slots` slots.  Slots are reset
    lazily on first write after their slot-id wraps, so an idle stream
    costs nothing."""

    __slots__ = ("window_s", "n_slots", "slot_w", "_ids", "_good", "_total")

    def __init__(self, window_s: float, n_slots: int = 60):
        if window_s <= 0 or n_slots < 1:
            raise ValueError("window_s and n_slots must be positive")
        self.window_s = float(window_s)
        self.n_slots = int(n_slots)
        self.slot_w = self.window_s / self.n_slots
        self._ids = [-1] * self.n_slots
        self._good = [0] * self.n_slots
        self._total = [0] * self.n_slots

    def record(self, good: bool, now: float) -> None:
        sid = int(now / self.slot_w)
        i = sid % self.n_slots
        if self._ids[i] != sid:
            self._ids[i] = sid
            self._good[i] = 0
            self._total[i] = 0
        self._total[i] += 1
        if good:
            self._good[i] += 1

    def totals(self, now: float) -> tuple[int, int]:
        """(good, total) over slots still inside the window at `now`."""
        sid = int(now / self.slot_w)
        good = total = 0
        for i in range(self.n_slots):
            if sid - self._ids[i] < self.n_slots and self._ids[i] >= 0:
                good += self._good[i]
                total += self._total[i]
        return good, total


class SLO:
    """One latency objective with a two-window burn-rate monitor."""

    __slots__ = ("name", "kind", "threshold_s", "objective", "alert_burn",
                 "clock", "fast", "slow")

    def __init__(
        self,
        name: str,
        kind: str = "tick",
        threshold_s: float = 0.05,
        objective: float = 0.99,
        fast_window_s: float = 300.0,
        slow_window_s: float = 3600.0,
        alert_burn: float = 14.4,
        clock=time.monotonic,
    ):
        if not 0.0 < objective < 1.0:
            raise ValueError(f"objective must be in (0, 1), got {objective}")
        self.name = name
        self.kind = kind
        self.threshold_s = float(threshold_s)
        self.objective = float(objective)
        self.alert_burn = float(alert_burn)
        self.clock = clock
        self.fast = WindowedCounts(fast_window_s)
        self.slow = WindowedCounts(slow_window_s)

    # -- the hot path ----------------------------------------------------

    def observe(self, latency_s: float, ok: bool, now: float | None = None):
        """Record one request: `ok` is availability (answered, possibly
        degraded); a slow-but-answered request still burns budget."""
        if now is None:
            now = self.clock()
        good = ok and latency_s <= self.threshold_s
        self.fast.record(good, now)
        self.slow.record(good, now)

    # -- reads -----------------------------------------------------------

    def _burn(self, win: WindowedCounts, now: float) -> tuple[float, int]:
        good, total = win.totals(now)
        if total == 0:
            return 0.0, 0
        bad_frac = (total - good) / total
        return bad_frac / (1.0 - self.objective), total

    def status(self, now: float | None = None) -> dict:
        """Monitor snapshot: burn rates, the multi-window alert, and the
        headline `green` flag (fast-window burn within budget)."""
        if now is None:
            now = self.clock()
        burn_fast, n_fast = self._burn(self.fast, now)
        burn_slow, n_slow = self._burn(self.slow, now)
        return {
            "name": self.name,
            "kind": self.kind,
            "threshold_ms": 1e3 * self.threshold_s,
            "objective": self.objective,
            "burn_fast": round(burn_fast, 4),
            "burn_slow": round(burn_slow, 4),
            "n_fast": n_fast,
            "n_slow": n_slow,
            "green": bool(n_fast > 0 and burn_fast <= 1.0),
            "alerting": bool(
                burn_fast > self.alert_burn and burn_slow > self.alert_burn
            ),
        }

    def gauges(self, now: float | None = None) -> dict:
        """Flat gauge dict for the telemetry registry / exporter."""
        s = self.status(now)
        p = f"slo.{self.name}."
        return {
            p + "burn_fast": s["burn_fast"],
            p + "burn_slow": s["burn_slow"],
            p + "green": float(s["green"]),
            p + "alerting": float(s["alerting"]),
            p + "objective": self.objective,
            p + "threshold_s": self.threshold_s,
        }

    def __repr__(self):
        s = self.status()
        state = "ALERT" if s["alerting"] else ("green" if s["green"] else "hot")
        return (
            f"SLO({self.name}: p(ok & <= {1e3 * self.threshold_s:g}ms) "
            f">= {self.objective}, burn {s['burn_fast']:.2f}/"
            f"{s['burn_slow']:.2f}, {state})"
        )


def _self_check():  # pragma: no cover - debugging aid
    clk = [0.0]
    slo = SLO("t", clock=lambda: clk[0])
    for i in range(1000):
        clk[0] += 0.1
        slo.observe(0.001 if i % 100 else 1.0, True)
    print(slo.status(), math.isfinite(slo.status()["burn_fast"]))


if __name__ == "__main__":  # pragma: no cover
    _self_check()
