"""Compile-once execution layer: persistent executable cache, AOT
precompilation, mask-aware shape bucketing, and donation policy.

The hot paths themselves are fast (reference-scale EM iterates at >100/s,
the 1000-rep bootstrap runs in ~0.1 s); the unmanaged cost is COMPILE time:
every process recompiles every EM/smoother/bootstrap variant for every
panel shape, and a live TPU window can die inside the first `jit`.  This
module makes compilation a managed, observable resource:

``configure_compilation_cache()``
    enables JAX's persistent compilation cache under a repo-local dir
    (``build/jax_cache``), so the SECOND process to compile a given program
    deserializes it instead (the warm-cache bench leg measures the split).
    Called by every estimation entry point, bench.py, and the replication
    CLI; idempotent and cheap after the first call.

``precompile(spec)``
    AOT-lowers and compiles the hot kernels — the ``em_step*`` family
    (ssm.py / ssm_ar.py), the ALS core (dfm.py), the collapsed/sqrt
    smoothers, the FAVAR bootstrap body (favar.py), and the whole
    on-device EM while-loop — for a declared panel shape, recording
    per-kernel compile-time vs run-time.  The compiled executables land in
    an in-process registry (`aot_call` dispatches to them with hit/miss
    counters) AND in the persistent cache, so a later jit of the same
    program in this or any process skips XLA entirely.

shape bucketing
    ``bucket_shape`` rounds a panel's (T, N) up to configured buckets and
    ``pad_panel`` zero-fills the padding under the existing missing-data
    masks.  Every estimator here handles missing data by masking — never
    by shape — so padded series are exactly inert (zero loadings, zero
    Gram contributions) and padded trailing periods contribute nothing to
    the likelihood; the one place trailing periods would leak in, the EM
    M-step's factor-VAR moments, takes the `PanelStats.tw` time-validity
    weight this module emits (see ssm._var_moments).  One compiled
    executable then serves every BASELINE panel, bootstrap resample count,
    and mixed-frequency window that lands in the same bucket.

donation policy
    ``donation_enabled()`` centralizes the `donate_argnums` decision for
    the EM while-loop carry and the bootstrap batch buffers: donation cuts
    copies and peak memory on TPU/GPU but is unimplemented on CPU (XLA
    warns and copies), so the default is platform-gated with a
    ``DFM_DONATE`` env override for tests.

Counters (`counters()`) are plain per-kernel dicts — compiles, compile
seconds, runs, run seconds, AOT hits/misses — and
`persistent_cache_events()` exposes JAX's own persistent-cache hit/miss
monitoring, so bench.py can report a compile/run split and a warm-cache
speedup as first-class fields.
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "DEFAULT_N_BUCKETS",
    "DEFAULT_T_BUCKETS",
    "BASELINE_PANEL_SHAPES",
    "CompileSpec",
    "aot_call",
    "aot_statics",
    "bucket_dim",
    "bucket_shape",
    "configure_compilation_cache",
    "counters",
    "donation_enabled",
    "pad_panel",
    "pad_ssm_params",
    "persistent_cache_events",
    "precompile",
    "reset_counters",
    "resolve_buckets",
    "unpad_ssm_params",
]

_REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
_DEFAULT_CACHE_DIR = os.path.join(_REPO_ROOT, "build", "jax_cache")

_lock = threading.RLock()
_configured_dir: str | None = None

# JAX persistent-cache monitoring events, counted process-wide from the
# moment the cache is configured (registration is idempotent).
_persist_events = {"hits": 0, "misses": 0}
_listener_registered = False


def _event_listener(event: str, **kwargs) -> None:
    if event == "/jax/compilation_cache/cache_hits":
        _persist_events["hits"] += 1
    elif event == "/jax/compilation_cache/cache_misses":
        _persist_events["misses"] += 1


def persistent_cache_events() -> dict:
    """JAX persistent-compilation-cache hit/miss counts for this process
    (0/0 until `configure_compilation_cache` has run)."""
    return dict(_persist_events)


def configure_compilation_cache(
    cache_dir: str | None = None,
    min_compile_time_s: float | None = None,
) -> str | None:
    """Enable JAX's persistent compilation cache under a repo-local dir.

    Idempotent: the first call wins the directory (later calls with
    cache_dir=None return it); an explicit different cache_dir re-points
    the cache.  Returns the active dir, or None when disabled via
    ``DFM_COMPILE_CACHE=0``.

    ``min_compile_time_s`` (env ``DFM_COMPILE_CACHE_MIN_S``, default 0.35)
    keeps trivial sub-second sub-jits out of the cache dir — only the
    programs worth deserializing are persisted.  Safe to call before or
    after backend init; the config keys are runtime-read by JAX.
    """
    global _configured_dir, _listener_registered
    if os.environ.get("DFM_COMPILE_CACHE", "1").lower() in ("0", "off", "false"):
        return None
    with _lock:
        if _configured_dir is not None and cache_dir is None:
            return _configured_dir
        d = (
            cache_dir
            or os.environ.get("DFM_COMPILE_CACHE_DIR")
            or _DEFAULT_CACHE_DIR
        )
        d = os.path.abspath(d)
        try:
            os.makedirs(d, exist_ok=True)
        except OSError:
            return None  # read-only checkout: run uncached rather than die
        if min_compile_time_s is None:
            min_compile_time_s = float(
                os.environ.get("DFM_COMPILE_CACHE_MIN_S", "0.35")
            )
        jax.config.update("jax_compilation_cache_dir", d)
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", min_compile_time_s
        )
        try:
            jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        except AttributeError:  # older jax without the knob
            pass
        if not _listener_registered:
            jax.monitoring.register_event_listener(_event_listener)
            _listener_registered = True
        _configured_dir = d
        return d


# ---------------------------------------------------------------------------
# donation policy
# ---------------------------------------------------------------------------


def donation_enabled() -> bool:
    """Whether `donate_argnums` variants should be used.

    ``DFM_DONATE=1`` forces on (tests exercise the donated program on
    CPU, where XLA falls back to copying), ``DFM_DONATE=0`` forces off;
    default: on for any non-CPU default backend, off on CPU (donation is
    unimplemented there and only produces a warning per compile).
    """
    env = os.environ.get("DFM_DONATE", "auto").lower()
    if env in ("0", "off", "false", "no"):
        return False
    if env in ("1", "on", "true", "yes", "force"):
        return True
    try:
        return jax.default_backend() != "cpu"
    except RuntimeError:
        return False


# ---------------------------------------------------------------------------
# shape bucketing
# ---------------------------------------------------------------------------

# Chosen so ALL FIVE BASELINE configs land in the single (256, 256)
# bucket (no 128 N-bucket: the euro-area panel's N=120 must share the
# Stock-Watson executables, and a 2x N overshoot on a masked panel costs
# far less than a second compile), the monthly mixed-frequency panel gets
# (704, 256), and the large-panel bench regime (2048, 4096) maps to
# itself.  Override via DFM_T_BUCKETS / DFM_N_BUCKETS (comma lists) or
# per call.
DEFAULT_T_BUCKETS = (64, 128, 256, 512, 704, 1024, 2048)
# The 16384 / 131072 tails are the large-N regime (bench.py --large-n):
# a 10k-series panel lands in 16384 and a 100k panel in 131072, so the
# N-free collapsed kernels compile once per decade of panel width
# instead of once per tenant panel.
DEFAULT_N_BUCKETS = (16, 64, 256, 512, 1024, 4096, 16384, 131072)

# Nominal (T, N) of the five BASELINE.json configs (estimation windows of
# the Stock-Watson quarterly panel and the euro-area two-level panel).
# All five land in the SAME (256, 256) bucket — the compile-once claim
# tests/test_compile_cache.py pins with counters.
BASELINE_PANEL_SHAPES = {
    "pca_real": (224, 139),  # config 1: static PCA factors, :Real panel
    "em_real": (222, 139),  # config 2: state-space EM, 1959Q3-2014Q4
    "favar_all": (224, 207),  # config 3: FAVAR panel, :All
    "dynpca_all": (224, 207),  # config 4: Forni-Gambetti dynamic PCA
    "multilevel_ea": (168, 120),  # config 5: euro-area two-level DFM
}


def _env_buckets(name: str, default: tuple) -> tuple:
    raw = os.environ.get(name)
    if not raw:
        return default
    return tuple(int(v) for v in raw.split(",") if v.strip())


def bucket_dim(n: int, buckets) -> int:
    """Smallest bucket >= n; n itself when it exceeds every bucket (an
    oversized panel compiles exactly rather than failing)."""
    for b in sorted(buckets):
        if n <= b:
            return int(b)
    return int(n)


def bucket_shape(T: int, N: int, t_buckets=None, n_buckets=None) -> tuple:
    t_buckets = t_buckets or _env_buckets("DFM_T_BUCKETS", DEFAULT_T_BUCKETS)
    n_buckets = n_buckets or _env_buckets("DFM_N_BUCKETS", DEFAULT_N_BUCKETS)
    return bucket_dim(T, t_buckets), bucket_dim(N, n_buckets)


def resolve_buckets(bucket):
    """Normalize an estimator's `bucket` argument.

    None -> env default (``DFM_SHAPE_BUCKETS=1`` turns bucketing on
    globally); False -> off; True -> default bucket tables;
    (t_buckets, n_buckets) -> custom tables.  Returns None (off) or the
    (t_buckets, n_buckets) pair.
    """
    if bucket is None:
        bucket = os.environ.get("DFM_SHAPE_BUCKETS", "0").lower() in (
            "1",
            "on",
            "true",
        )
    if bucket is False:
        return None
    if bucket is True:
        return (
            _env_buckets("DFM_T_BUCKETS", DEFAULT_T_BUCKETS),
            _env_buckets("DFM_N_BUCKETS", DEFAULT_N_BUCKETS),
        )
    tb, nb = bucket
    return tuple(tb), tuple(nb)


def pad_panel(xz, mask, t_pad: int, n_pad: int):
    """Pad a zero-filled panel + mask up to (t_pad, n_pad).

    Returns (xz_p, mask_p, tw): padded cells carry mask False / value 0,
    so every mask-weighted contraction ignores them; tw is the (t_pad,)
    time-validity weight (1 on real rows) the EM M-step's factor-VAR
    moments need (trailing unobserved periods are the ONE place padding
    would otherwise leak — their smoothed states are pure forecasts).
    """
    T, N = xz.shape
    if (T, N) == (t_pad, n_pad):
        tw = jnp.ones((t_pad,), xz.dtype)
        return xz, mask, tw
    if t_pad < T or n_pad < N:
        raise ValueError(
            f"bucket ({t_pad}, {n_pad}) smaller than panel ({T}, {N})"
        )
    xz_p = jnp.zeros((t_pad, n_pad), xz.dtype).at[:T, :N].set(xz)
    mask_p = jnp.zeros((t_pad, n_pad), mask.dtype).at[:T, :N].set(mask)
    tw = jnp.zeros((t_pad,), xz.dtype).at[:T].set(1)
    return xz_p, mask_p, tw


def pad_ssm_params(params, n_pad: int):
    """Extend SSMParams with inert padded series: zero loadings (no state
    information), unit idiosyncratic variance (keeps 1/R and log R finite;
    the first M-step re-floors them and they stay inert)."""
    N = params.lam.shape[0]
    if N == n_pad:
        return params
    dt = params.lam.dtype
    lam = jnp.zeros((n_pad, params.lam.shape[1]), dt).at[:N].set(params.lam)
    R = jnp.ones((n_pad,), params.R.dtype).at[:N].set(params.R)
    return params._replace(lam=lam, R=R)


def unpad_ssm_params(params, n: int):
    if params.lam.shape[0] == n:
        return params
    return params._replace(lam=params.lam[:n], R=params.R[:n])


# ---------------------------------------------------------------------------
# counters
# ---------------------------------------------------------------------------


def _new_counter() -> dict:
    return {
        "compiles": 0,
        "compile_s": 0.0,
        "runs": 0,
        "run_s": 0.0,
        "aot_hits": 0,
        "aot_misses": 0,
    }


_counters: dict[str, dict] = {}


def _counter(name: str) -> dict:
    return _counters.setdefault(name, _new_counter())


def counters() -> dict:
    """Per-kernel snapshot: compiles / compile_s / runs / run_s /
    aot_hits / aot_misses."""
    with _lock:
        return {k: dict(v) for k, v in _counters.items()}


def reset_counters() -> None:
    with _lock:
        _counters.clear()
        _persist_events["hits"] = 0
        _persist_events["misses"] = 0


def _sig(tree) -> tuple:
    """Abstract signature of a concrete/abstract arg pytree: what the jit
    tracing cache (and therefore a recompile) keys on, up to statics."""
    leaves, treedef = jax.tree.flatten(tree)
    return (
        str(treedef),
        tuple(
            (tuple(leaf.shape), jnp.asarray(leaf).dtype.name)
            if not isinstance(leaf, jax.ShapeDtypeStruct)
            else (tuple(leaf.shape), jnp.dtype(leaf.dtype).name)
            for leaf in leaves
        ),
    )


# AOT registry: (kernel name, statics key, traced signature) -> Compiled
_AOT: dict[tuple, object] = {}


def aot_statics(*vals) -> tuple:
    """Render static arguments (functions, ints, flags) into a hashable
    key component.  Static args are baked into an AOT executable and
    invisible in the traced-arg signature, so they MUST distinguish
    registry entries — an `em_loop` compiled for `em_step_stats` must
    never serve a call meant for `em_step_sqrt`."""
    out = []
    for v in vals:
        if callable(v):
            out.append(
                getattr(v, "__module__", "?")
                + "."
                + getattr(v, "__qualname__", repr(v))
            )
        else:
            out.append(repr(v))
    return tuple(out)


def aot_call(kernel: str, fallback, *args, statics: tuple = ()):
    """Dispatch to a precompiled executable when one matches the args'
    abstract signature (and `statics` key), else to `fallback` — a
    callable taking exactly the traced args (statics already bound).

    Counts aot_hits / aot_misses per kernel — the counters the
    zero-recompile acceptance test reads.  The miss path may compile (or
    hit JAX's own caches); either way it is the live function, so results
    are identical.
    """
    key = (kernel, statics, _sig(args))
    with _lock:
        entry = _AOT.get(key)
        c = _counter(kernel)
        if entry is not None:
            c["aot_hits"] += 1
        else:
            c["aot_misses"] += 1
    t0 = time.perf_counter()
    out = entry(*args) if entry is not None else fallback(*args)
    jax.block_until_ready(out)
    with _lock:
        c["runs"] += 1
        c["run_s"] += time.perf_counter() - t0
    return out


# ---------------------------------------------------------------------------
# AOT precompilation
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class CompileSpec:
    """Declared panel shape + kernel set for `precompile`.

    T/N are the RAW panel dims; with bucket=True (default) kernels are
    lowered at the bucketed shape, so one precompile serves every panel
    in the same bucket.  r/p mirror DFMConfig.nfac_u / n_factorlag;
    nlag/horizon/n_reps size the bootstrap body; max_em_iter sizes the
    on-device EM loop carry.
    """

    T: int
    N: int
    r: int = 4
    p: int = 4
    dtype: str = "float32"
    bucket: bool = True
    t_buckets: tuple = DEFAULT_T_BUCKETS
    n_buckets: tuple = DEFAULT_N_BUCKETS
    # EM-family kernel names are stack ALIASES: each resolves through
    # models/transforms.enumerate_stacks to a (core, transforms, loop)
    # triple and the plan is derived from the resolved calling convention
    # — there is no per-kernel plan body to add.  Composed stacks are
    # opt-in by name: "em_step_collapsed" (ssm + collapse),
    # "em_step_ar_steady" (ar + collapse + steady, needs t_star),
    # "em_step_ar_sharded" (ar + collapse + shard, needs n_shards > 1),
    # "em_step_ar_all" (all three axes, needs both).
    kernels: tuple = (
        "em_step_stats",
        "em_step",
        "em_step_sqrt",
        "em_step_sqrt_collapsed",
        "em_step_ar",
        "em_step_ar_qd",
        "als_core",
        "bootstrap_core",
        "em_loop",
        "em_loop_guarded",
        "em_step_steady",
        "em_loop@steady",
        "em_loop_guarded@steady",
    )
    max_em_iter: int = 200
    als_max_iter: int = 200_000
    nlag: int = 4
    horizon: int = 24
    n_reps: int = 1000
    ns: int | None = None  # bootstrap system width (default: r)
    # steady-state fast path (models/steady.py): the exact-head length t*
    # is a STATIC of the steady EM step (it sizes the head scan), so the
    # executable is only reusable for runs whose `_steady_plan` lands on
    # the same t_star.  None (default) skips the steady kernels entirely.
    t_star: int | None = None
    steady_block: int = 0
    # serving layer (serving/): serving_period > 0 adds the O(1) online
    # tick at that observation period (1 complete, 3 mixed-frequency);
    # em_batch > 0 adds the vmapped multi-tenant EM loop over that many
    # stacked panels; tick_batch > 0 additionally adds the lane-batched
    # tick at that lane bucket (serving/batch.LANE_BUCKETS) — derived
    # from the serving_tick plan by prepending the lane axis, the same
    # batch()-transform doctrine as em_loop_batched, never a hand-
    # written aval body.  All default off so existing specs are
    # unchanged.
    serving_period: int = 0
    em_batch: int = 0
    tick_batch: int = 0
    # dual-form burst catch-up (serving/prefill.py): prefill_depth > 0
    # additionally registers the GEMM prefill ("serving_prefill@K{2^j}")
    # and the bitwise decode-form block ("serving_tick_block@K{2^j}")
    # for every power-of-two depth bucket up to prefill_bucket(
    # prefill_depth) — the burst depth is a traced operand, so one
    # executable per bucket serves every backlog in it.  Default off so
    # existing specs are unchanged.
    prefill_depth: int = 0
    # scenario engine (scenarios/): scenario_draws > 0 adds the fan-out
    # kernels — "scenario_fan" (the posterior_forecast / draw-fan forward
    # simulation over scenario_draws parameter draws), "scenario_cond_fan"
    # and "scenario_draw_fan" (scenario_paths conditioning lanes through
    # the masked smoother at scenario_horizon).  The registry key carries
    # the bucketed panel shape via the traced avals and the draw/path
    # counts via the leading axes, so one spec serves every request of
    # the same fan size.  Default off so existing specs are unchanged.
    scenario_draws: int = 0
    scenario_paths: int = 8
    scenario_horizon: int = 12
    # particle-filter scenario kernels (scenarios/smc.py):
    # particle_count > 0 registers one "smc_filter@<model>" plan per
    # models/transforms.enumerate_smc entry at (scenario_paths lanes,
    # particle_count particles, scenario_horizon forecast steps) — the
    # plan bodies are derived by scenarios/smc.aot_plan, the same
    # no-hand-written-plan doctrine as the EM stacks.  Default off so
    # existing specs compile the same set as before.
    particle_count: int = 0
    # cross-section sharding (models/ssm._sharded_step_for): n_shards > 1
    # additionally registers the sharded EM step ("em_step_sharded") and
    # the guarded loop specialized to it, lowered at the shard-padded N
    # (parallel.mesh.series_pad on top of the bucket) over a mesh with
    # the given axis names.  0 (default) skips the sharded kernels.
    n_shards: int = 0
    mesh_axes: tuple = ("data",)
    # multi-host sharding (PR 15): mesh_hosts > 1 lowers the sharded
    # kernels onto the process-spanning ("dcn", "ici") mesh via
    # transforms.shard(n_shards, hosts) — the hierarchical-reduction
    # program.  0 (default) resolves to jax.process_count() at resolve
    # time, so existing single-process specs compile the same flat-mesh
    # programs as before.
    mesh_hosts: int = 0
    # parallel-in-time slabs (models/emtime via transforms.time_shard):
    # t_blocks > 1 registers the opt-in time-parallel EM steps
    # ("em_step_tp", "em_step_ar_tp", and "em_step_tp_sharded" when
    # n_shards > 1 too) over the blocked-slab time mesh.  0 (default)
    # skips them, so existing specs compile the same set as before.
    t_blocks: int = 0

    def padded_shape(self) -> tuple:
        if not self.bucket:
            return self.T, self.N
        return bucket_shape(self.T, self.N, self.t_buckets, self.n_buckets)


def _sds(shape, dtype):
    return jax.ShapeDtypeStruct(shape, dtype)


def _benign_em_inputs(Tb, Nb, r, p, dt):
    """Small deterministic inputs matching the EM kernels' avals — benign
    (stable filter, PD covariances) so a warmup run measures a realistic
    run time instead of NaN arithmetic."""
    from ..models.ssm import SSMParams, compute_panel_stats

    rng = np.random.default_rng(0)
    lam = jnp.asarray(0.1 * rng.standard_normal((Nb, r)), dt)
    A = jnp.zeros((p, r, r), dt).at[0].set(0.2 * jnp.eye(r, dtype=dt))
    params = SSMParams(lam, jnp.ones(Nb, dt), A, jnp.eye(r, dtype=dt))
    x = jnp.asarray(0.1 * rng.standard_normal((Tb, Nb)), dt)
    mask = jnp.ones((Tb, Nb), bool)
    stats = compute_panel_stats(x, mask)._replace(tw=jnp.ones(Tb, dt))
    return params, x, mask, stats


def _kernel_plan(spec: CompileSpec):
    """(jit_fn, lower_args, lower_kwargs, statics, mk_inputs) per kernel.

    lower_args mixes ShapeDtypeStructs (traced) and concrete statics;
    mk_inputs builds concrete warm-up inputs WITHOUT the statics (the AOT
    call convention: statics are baked into the executable); `statics` is
    the aot_statics registry-key component a production `aot_call` must
    reproduce to dispatch here.
    """
    dt = jnp.dtype(spec.dtype)
    Tb, Nb = spec.padded_shape()
    r, p = spec.r, spec.p
    plans = {}

    from ..models import ssm
    from ..models.ssm import PanelStats, SSMParams

    params_s = SSMParams(
        _sds((Nb, r), dt), _sds((Nb,), dt), _sds((p, r, r), dt), _sds((r, r), dt)
    )
    x_s = _sds((Tb, Nb), dt)
    mask_s = _sds((Tb, Nb), jnp.bool_)
    stats_s = PanelStats(
        m=_sds((Tb, Nb), dt),
        xT=_sds((Nb, Tb), dt),
        mT=_sds((Nb, Tb), dt),
        Sxx=_sds((Nb,), dt),
        n_i=_sds((Nb,), dt),
        n_obs=_sds((Tb,), dt),
        tw=_sds((Tb,), dt),
    )
    bparams, bx, bmask, bstats = (None,) * 4  # built lazily below

    def em_inputs():
        nonlocal bparams, bx, bmask, bstats
        if bparams is None:
            bparams, bx, bmask, bstats = _benign_em_inputs(Tb, Nb, r, p, dt)
        return bparams, bx, bmask, bstats

    # ------------------------------------------------------------------
    # EM family: DERIVED from the transform-stack table instead of one
    # hand-written plan body per kernel.  models/transforms.enumerate_stacks
    # yields (key, stack, loop) triples reproducing the historical keys,
    # gating, and statics exactly (tests/test_transform_stack.py pins the
    # derived registry against the frozen pre-stack kernel set); the code
    # below builds avals and warmup inputs generically from the resolved
    # calling convention, so a NEW stack precompiles with no new plan body.
    # ------------------------------------------------------------------
    from ..models import emloop
    from ..models import transforms as tfm

    ld = jnp.result_type(float)
    _benign_cache = {}

    def em_inputs_at(N):
        if N == Nb:
            return em_inputs()
        if N not in _benign_cache:
            _benign_cache[N] = _benign_em_inputs(Tb, N, r, p, dt)
        return _benign_cache[N]

    def _ssm_avals(N):
        pa = SSMParams(
            _sds((N, r), dt), _sds((N,), dt), _sds((p, r, r), dt),
            _sds((r, r), dt),
        )
        st = PanelStats(
            m=_sds((Tb, N), dt),
            xT=_sds((N, Tb), dt),
            mT=_sds((N, Tb), dt),
            Sxx=_sds((N,), dt),
            n_i=_sds((N,), dt),
            n_obs=_sds((Tb,), dt),
            tw=_sds((Tb,), dt),
        )
        return pa, _sds((Tb, N), dt), _sds((Tb, N), jnp.bool_), st

    def _ar_avals(N):
        from ..models import ssm_ar

        arp = ssm_ar.SSMARParams(
            _sds((N, r), dt), _sds((N,), dt), _sds((N,), dt),
            _sds((p, r, r), dt), _sds((r, r), dt),
        )
        qd = ssm_ar.QDStats(
            m=_sds((Tb, N), dt),
            first=_sds((Tb, N), dt),
            interior=_sds((Tb, N), dt),
            x_prev=_sds((Tb, N), dt),
            mT=_sds((N, Tb), dt),
            firstT=_sds((N, Tb), dt),
            interiorT=_sds((N, Tb), dt),
            xT=_sds((N, Tb), dt),
            x_prevT=_sds((N, Tb), dt),
            n_int=_sds((N,), dt),
            n_obs=_sds((Tb,), dt),
        )
        return arp, _sds((Tb, N), dt), _sds((Tb, N), jnp.bool_), qd

    def _ar_concrete(N):
        from ..models import ssm_ar

        pa, x, mask, _ = em_inputs_at(N)
        arp = ssm_ar.SSMARParams(
            pa.lam, jnp.zeros(N, dt), jnp.ones(N, dt) * 0.5, pa.A, pa.Q
        )
        return arp, x, mask

    def _step_plan(res):
        """(carry aval, step-arg avals past the carry, mk inputs with the
        carry first) for one resolved stack."""
        N = Nb
        if res.n_shards > 1:
            from ..parallel.mesh import series_pad

            N = series_pad(Nb, res.n_shards)
        if res.core == "mf":
            # MixedFreqParams carries the extra (N, 5) aggregation-row
            # leaf, so the SSM aval pytree below would mis-key the plan;
            # build the MF pytree explicitly.  _obs_matrix silently
            # truncates its lag slices when p < 5, so refuse early.
            from ..models.mixed_freq import _N_AGG, MixedFreqParams

            if p < _N_AGG:
                raise ValueError(
                    f"CompileSpec p={p} must be >= {_N_AGG} to plan "
                    "mixed-frequency kernels (Mariano-Murasawa lags)"
                )
            _, xa_s, ma_s, st_s = _ssm_avals(N)
            mf_s = MixedFreqParams(
                _sds((N, r), dt), _sds((N,), dt), _sds((p, r, r), dt),
                _sds((r, r), dt), _sds((N, _N_AGG), dt),
            )

            def mk_mf():
                pa, x, mask, stats = em_inputs_at(N)
                agg = jnp.zeros((N, _N_AGG), dt).at[:, 0].set(1.0)
                return (
                    MixedFreqParams(pa.lam, pa.R, pa.A, pa.Q, agg),
                    x, mask, stats,
                )

            return mf_s, (xa_s, ma_s, st_s), mk_mf
        if res.arg_kind in ("stats", "panel"):
            pa_s, xa_s, ma_s, st_s = _ssm_avals(N)
            if res.arg_kind == "panel":
                return pa_s, (xa_s, ma_s), lambda: em_inputs_at(N)[:3]
            if res.carry == "steady":
                k = r * p
                carry_s = ssm.SteadyEMState(
                    pa_s, _sds((k, k), dt), _sds((), jnp.int32)
                )

                def mk_steady():
                    pa, x, mask, stats = em_inputs_at(N)
                    st = ssm.SteadyEMState(
                        pa, jnp.zeros((k, k), dt), jnp.asarray(0, jnp.int32)
                    )
                    return st, x, mask, stats

                return carry_s, (xa_s, ma_s, st_s), mk_steady
            return pa_s, (xa_s, ma_s, st_s), lambda: em_inputs_at(N)
        arp_s, xa_s, ma_s, qd_s = _ar_avals(N)
        if res.arg_kind == "ar_panel":
            return arp_s, (xa_s, ma_s), lambda: _ar_concrete(N)
        if res.arg_kind == "qd":

            def mk_qd():
                from ..models import ssm_ar

                arp, x, mask = _ar_concrete(N)
                return arp, x, ssm_ar.compute_qd_stats(x, mask)

            return arp_s, (xa_s, qd_s), mk_qd
        # "qd_tail": steady AR carry + loop-invariant tail data moments
        from ..models import emcore

        k2 = r * max(p, 2)
        carry_s = emcore.ARSteadyState(
            arp_s, _sds((k2, k2), dt), _sds((), jnp.int32)
        )
        tail_s = emcore.QDTailStats(
            _sds((N,), dt), _sds((N,), dt), _sds((N,), dt)
        )

        def mk_qd_tail():
            from ..models import ssm_ar

            arp, x, mask = _ar_concrete(N)
            qd = ssm_ar.compute_qd_stats(x, mask)
            st = emcore.ARSteadyState(
                arp, jnp.zeros((k2, k2), dt), jnp.asarray(0, jnp.int32)
            )
            return st, x, qd, emcore.compute_qd_tail_stats(qd, res.t_star)

        return carry_s, (xa_s, qd_s, tail_s), mk_qd_tail

    for pe in tfm.enumerate_stacks(spec):
        res = tfm.resolve(pe.stack)
        carry_s, args_s, mk_step = _step_plan(res)
        if pe.loop is None:
            plans[pe.key] = (
                res.step, (carry_s,) + args_s, {}, (), mk_step
            )
            continue
        tol_c = jnp.asarray(1e-6, ld)

        if pe.loop == "plain":
            donate = donation_enabled()
            lcarry_s = (
                carry_s, _sds((), ld), _sds((), ld), _sds((), jnp.int32),
                _sds((spec.max_em_iter,), ld),
            )

            def mk_plain(mk_step=mk_step, tol_c=tol_c):
                first, *rest = mk_step()
                carry = emloop._fresh_carry(first, tol_c, spec.max_em_iter)
                # stop_at=2: the traced bound keeps the warmup to two
                # iterations of the SAME executable a full run uses
                return (carry, tuple(rest), tol_c, jnp.asarray(2, jnp.int32))

            plans[pe.key] = (
                emloop._em_while_jit(donate),
                (res.step, lcarry_s, args_s, _sds((), ld), spec.max_em_iter,
                 _sds((), jnp.int32)),
                {},
                # must mirror run_em_loop's dispatch key exactly: (step,
                # max_em_iter, donate, heartbeat_every) — precompiled loops
                # are heartbeat-free, so a DFM_HEARTBEAT run recompiles live
                aot_statics(res.step, spec.max_em_iter, donate, 0),
                mk_plain,
            )
        elif pe.loop == "guarded":
            donate = donation_enabled()
            gcarry_s = (
                carry_s, carry_s, _sds((), ld), _sds((), ld),
                _sds((), jnp.int32), _sds((spec.max_em_iter,), ld),
                _sds((), jnp.int32),  # health
                _sds((), jnp.int32),  # rung
                _sds((), jnp.int32),  # trips
                _sds((), jnp.int32),  # resume_from
            )

            def mk_guarded(mk_step=mk_step, tol_c=tol_c):
                first, *rest = mk_step()
                carry = emloop._fresh_guarded_carry(
                    first, tol_c, spec.max_em_iter
                )
                return (
                    carry, tuple(rest), tol_c, jnp.asarray(1e-3, ld),
                    jnp.asarray(2, jnp.int32),
                )

            plans[pe.key] = (
                emloop._em_while_guarded_jit(donate),
                (res.step, gcarry_s, args_s, _sds((), ld), _sds((), ld),
                 spec.max_em_iter, _sds((), jnp.int32)),
                {},
                # mirrors the guarded dispatch key: (step, max_em_iter,
                # donate, heartbeat_every, inject_nan_at, inject_chol_at) —
                # precompiled loops are heartbeat- and injection-free; a
                # DFM_FAULTS run compiles its injected program live
                aot_statics(res.step, spec.max_em_iter, donate, 0, 0, 0),
                mk_guarded,
            )
        else:  # "batched"
            B = res.batch

            def _bsds(s, B=B):
                return _sds((B,) + tuple(s.shape), s.dtype)

            bcarry_first = jax.tree.map(_bsds, carry_s)
            bcarry_s = (
                bcarry_first, bcarry_first, _sds((B,), ld), _sds((B,), ld),
                _sds((B,), jnp.int32), _sds((B, spec.max_em_iter), ld),
                _sds((B,), jnp.int32),
            )
            bargs_s = jax.tree.map(_bsds, args_s)

            def mk_batched(mk_step=mk_step, tol_c=tol_c, B=B):
                first, *rest = mk_step()
                stk = lambda t: jax.tree.map(  # noqa: E731
                    lambda a: jnp.broadcast_to(a, (B,) + a.shape), t
                )
                carry = emloop._fresh_batched_carry(
                    stk(first), tol_c, spec.max_em_iter, B
                )
                return (
                    carry, stk(tuple(rest)), tol_c,
                    jnp.asarray(1e-3, ld), jnp.asarray(2, jnp.int32),
                )

            plans[pe.key] = (
                emloop._em_while_batched,
                (res.step, bcarry_s, bargs_s, _sds((), ld), _sds((), ld),
                 spec.max_em_iter, _sds((), jnp.int32)),
                {},
                # mirrors run_em_loop_batched's dispatch key: (step,
                # max_em_iter, inject_nan_at) — precompiled loops are
                # injection-free; a DFM_FAULTS run compiles live
                aot_statics(res.step, spec.max_em_iter, 0),
                mk_batched,
            )

    if "als_core" in spec.kernels:
        from ..models import dfm

        def als_inputs():
            _, x, mask, _ = em_inputs()
            return (
                x,
                mask.astype(dt),
                jnp.ones(Nb, bool),
                jnp.zeros((Tb, r), dt),
                jnp.asarray(1e-8 * Tb * Nb, dt),
            )

        plans["als_core"] = (
            dfm._als_core,
            (x_s, _sds((Tb, Nb), dt), _sds((Nb,), jnp.bool_), _sds((Tb, r), dt),
             _sds((), dt)),
            {"nfac": r, "max_iter": spec.als_max_iter},
            aot_statics(r, spec.als_max_iter),
            als_inputs,
        )

    if "bootstrap_core" in spec.kernels:
        from ..models import favar

        ns = spec.ns or r
        Tw = Tb if not spec.bucket else spec.T  # bootstrap windows are
        # contiguous-complete (no mask), so T is NOT padded — reps are the
        # bucketed axis there (parallel.mesh.rep_pad)
        key_s = _sds((2,), jnp.uint32)

        def boot_inputs():
            rng = np.random.default_rng(1)
            yw = jnp.asarray(0.1 * rng.standard_normal((Tw, ns)), dt)
            return yw, jax.random.PRNGKey(0)

        plans["bootstrap_core"] = (
            favar._bootstrap_core,
            (_sds((Tw, ns), dt), key_s),
            {
                "nlag": spec.nlag,
                "horizon": spec.horizon,
                "n_reps": spec.n_reps,
            },
            aot_statics(spec.nlag, spec.horizon, spec.n_reps),
            boot_inputs,
        )

    if spec.serving_period > 0:
        # lazy import: serving.online imports this module for aot_call
        from ..serving import online

        d = spec.serving_period
        k = r * p
        q = r if d == 1 else 5 * r
        model_s = online.ServingModel(
            Wb=_sds((Nb, q), dt),
            H=_sds((Nb, q), dt),
            Tm=_sds((k, k), dt),
            Abar=_sds((d, k, k), dt),
            K=_sds((d, k, q), dt),
        )
        state_s = online.FilterState(
            s=_sds((k,), dt), t=_sds((), jnp.int32)
        )

        def tick_inputs():
            rng = np.random.default_rng(2)
            model = online.ServingModel(
                Wb=jnp.asarray(0.1 * rng.standard_normal((Nb, q)), dt),
                H=jnp.asarray(0.1 * rng.standard_normal((Nb, q)), dt),
                Tm=0.5 * jnp.eye(k, dtype=dt),
                Abar=jnp.broadcast_to(0.5 * jnp.eye(k, dtype=dt), (d, k, k)),
                # benign gain: identity block on the leading min(k, q)
                # square (MF specs have q = 5r > k when p < 5)
                K=jnp.zeros((d, k, q), dt)
                .at[:, : min(k, q), : min(k, q)]
                .set(0.1 * jnp.eye(min(k, q), dtype=dt)),
            )
            state = online.FilterState(
                s=jnp.zeros((k,), dt), t=jnp.asarray(0, jnp.int32)
            )
            x_t = jnp.asarray(0.1 * rng.standard_normal((Nb,)), dt)
            return model, state, x_t, jnp.ones((Nb,), bool)

        plans["serving_tick"] = (
            online._tick,
            (model_s, state_s, _sds((Nb,), dt), _sds((Nb,), jnp.bool_)),
            {},
            (),
            tick_inputs,
        )

        if spec.tick_batch > 0:
            # the lane-batched tick plans are DERIVED from the scalar
            # plan — prepend the lane axis to every aval and broadcast
            # the warmup inputs — exactly how transforms.batch() derives
            # em_loop_batched from the scalar loop; `_tick_batched` is
            # itself vmap(_tick), so neither the program nor its plan
            # has a hand-written batched variant to drift.  One plan per
            # lane bucket UP TO tick_batch: an admission flush deduped
            # into rounds shrinks through the bucket ladder (64-lane
            # flush → rounds of 64, 16, 8, ... lanes), and every round
            # must hit AOT dispatch for batched admission to beat the
            # sequential path's AOT'd scalar tick
            from ..serving.batch import lane_bucket, LANE_BUCKETS

            B_top = lane_bucket(int(spec.tick_batch))
            for B in [b for b in LANE_BUCKETS if b <= B_top]:
                lane = lambda s, B=B: _sds((B,) + s.shape, s.dtype)  # noqa: E731

                def tick_batch_inputs(B=B):
                    args = tick_inputs()
                    return jax.tree.map(
                        lambda a: jnp.broadcast_to(a, (B,) + a.shape), args
                    )

                plans[f"serving_tick_batched@B{B}"] = (
                    online._tick_batched,
                    jax.tree.map(lane, plans["serving_tick"][1]),
                    {},
                    (),
                    tick_batch_inputs,
                )

        if spec.prefill_depth > 0:
            # dual-form burst catch-up plans: both kernel forms share
            # one aval body — (model, state, (Kb, N) burst block,
            # (Kb, N) mask, traced live depth) — per power-of-two
            # depth bucket, so a cold fleet compiles ceil(log2 depth)+1
            # executables per form and every backlog in a bucket reuses
            # its plan (the actual k rides the traced operand; padding
            # is masked inert).  The lane-batched prefill
            # (batch.batched_prefill_dispatch) is vmap-derived from the
            # same scalar kernel and jit-caches in process.
            from ..serving import prefill as _prefill_mod

            K_top = _prefill_mod.prefill_bucket(int(spec.prefill_depth))
            for Kb in [
                b for b in _prefill_mod.PREFILL_BUCKETS if b <= K_top
            ]:
                burst_avals = (
                    model_s, state_s,
                    _sds((Kb, Nb), dt), _sds((Kb, Nb), jnp.bool_),
                    _sds((), jnp.int32),
                )

                def burst_inputs(Kb=Kb):
                    model, state, x_t, m_t = tick_inputs()
                    return (
                        model, state,
                        jnp.broadcast_to(x_t, (Kb,) + x_t.shape),
                        jnp.broadcast_to(m_t, (Kb,) + m_t.shape),
                        jnp.asarray(Kb, jnp.int32),
                    )

                plans[f"serving_prefill@K{Kb}"] = (
                    _prefill_mod._prefill_impl,
                    burst_avals, {}, (), burst_inputs,
                )
                plans[f"serving_tick_block@K{Kb}"] = (
                    _prefill_mod._tick_block_impl,
                    burst_avals, {}, (), burst_inputs,
                )

    if spec.scenario_draws > 0:
        # lazy import: scenarios.fanout imports this module for aot_call
        from ..scenarios import fanout

        D = spec.scenario_draws
        S = spec.scenario_paths
        h = spec.scenario_horizon
        k = r * p
        xs_s = _sds((S, Tb + h, Nb), dt)
        ms_s = _sds((S, Tb + h, Nb), jnp.bool_)

        def cond_inputs():
            pa, x, mask, _ = em_inputs()
            return (pa,) + fanout.extend_panel(
                jnp.where(mask, x, jnp.nan), h,
                jnp.full((S, h, Nb), jnp.nan, dt),
            )

        plans["scenario_cond_fan"] = (
            fanout._conditional_fan_impl,
            (params_s, xs_s, ms_s),
            {"horizon": h},
            aot_statics(h),
            cond_inputs,
        )

        def draw_inputs():
            keys = jax.random.split(
                jax.random.PRNGKey(0), S * D
            ).reshape(S, D, 2)
            return cond_inputs() + (keys,)

        plans["scenario_draw_fan"] = (
            fanout._draw_fan_impl,
            (params_s, xs_s, ms_s, _sds((S, D, 2), jnp.uint32)),
            {"horizon": h},
            aot_statics(h),
            draw_inputs,
        )

        # large-N collapsed fan variants: the traced stacks are r-sized
        # (no N anywhere past the one-time collapse), so one executable
        # serves EVERY panel width — the registry key varies only with
        # (S, T+h, r) and the (horizon, observables) statics
        Cc_s = _sds((S, Tb + h, r, r), dt)
        bc_s = _sds((S, Tb + h, r), dt)
        ldc_s = _sds((S, Tb + h), dt)
        xrxc_s = _sds((S,), dt)
        noc_s = _sds((S, Tb + h), dt)

        def cond_collapsed_inputs():
            pa, x, mask, _ = em_inputs()
            return (pa,) + fanout._collapse_fan_stats(
                pa, jnp.where(mask, x, jnp.nan), h,
                jnp.full((S, h, Nb), jnp.nan, dt),
            )

        def draw_collapsed_inputs():
            keys = jax.random.split(
                jax.random.PRNGKey(0), S * D
            ).reshape(S, D, 2)
            return cond_collapsed_inputs() + (keys,)

        for obs in (True, False):
            tag = "obs" if obs else "noobs"
            plans[f"scenario_cond_fan_collapsed@{tag}"] = (
                fanout._conditional_fan_collapsed_impl,
                (params_s, Cc_s, bc_s, ldc_s, xrxc_s, noc_s),
                {"horizon": h, "observables": obs},
                aot_statics(h, obs),
                cond_collapsed_inputs,
            )
            plans[f"scenario_draw_fan_collapsed@{tag}"] = (
                fanout._draw_fan_collapsed_impl,
                (params_s, Cc_s, bc_s, ldc_s, xrxc_s, noc_s,
                 _sds((S, D, 2), jnp.uint32)),
                {"horizon": h, "observables": obs},
                aot_statics(h, obs),
                draw_collapsed_inputs,
            )

        def fan_inputs():
            pa, _, _, _ = em_inputs()
            stk = lambda a: jnp.broadcast_to(a, (D,) + a.shape)  # noqa: E731
            return (
                stk(pa.lam), stk(pa.R), stk(pa.A), stk(pa.Q),
                jnp.zeros((D, k), dt),
                jax.random.split(jax.random.PRNGKey(1), D),
            )

        plans["scenario_fan"] = (
            fanout._forecast_fan_impl,
            (_sds((D, Nb, r), dt), _sds((D, Nb), dt),
             _sds((D, p, r, r), dt), _sds((D, r, r), dt),
             _sds((D, k), dt), _sds((D, 2), jnp.uint32)),
            {"horizon": h},
            aot_statics(h),
            fan_inputs,
        )

    # particle-filter scenario kernels: derived from the transform-stack
    # enumeration exactly like the EM family — transforms.enumerate_smc
    # lists the entries, scenarios/smc.aot_plan builds each plan tuple
    smc_entries = tfm.enumerate_smc(spec)
    if smc_entries:
        from ..scenarios import smc as _smc_mod

        for pe in smc_entries:
            plans[pe.key] = _smc_mod.aot_plan(pe.model, pe.particles, spec)

    return plans


def precompile(spec: CompileSpec, warmup: bool = True) -> dict:
    """AOT-compile the kernels in `spec` at the (bucketed) declared shape.

    Returns a report with per-kernel `compile_s` (lower+compile wall
    seconds; near-zero when the persistent cache serves the executable),
    `run_s` (one measured warmup execution), and `aot_cached` (True when
    the in-process registry already held it — no work done).  Executables
    are registered for `aot_call` dispatch; compiling here also writes
    the persistent cache, so later jits of the same program — in this
    process or the next — skip XLA.
    """
    configure_compilation_cache()
    report = {
        "cache_dir": _configured_dir,
        "shape": list(spec.padded_shape()),
        "kernels": {},
    }
    total_c = total_r = 0.0
    for name, (fn, lower_args, lower_kwargs, statics, mk_inputs) in (
        _kernel_plan(spec).items()
    ):
        # plan keys may carry an "@variant" suffix ("em_loop@steady"); the
        # registry/counter name a production aot_call reproduces is the
        # prefix — variants of one kernel differ only in their statics key
        reg = name.split("@", 1)[0]
        traced_only = tuple(
            a for a in lower_args
            if any(
                isinstance(leaf, jax.ShapeDtypeStruct)
                for leaf in jax.tree.leaves(a)
            )
        )
        key = (reg, statics, _sig(traced_only))
        with _lock:
            cached = key in _AOT
        entry = {"aot_cached": cached, "compile_s": 0.0, "run_s": None}
        if cached:
            with _lock:
                _counter(reg)["aot_hits"] += 1
                compiled = _AOT[key]
        else:
            t0 = time.perf_counter()
            compiled = fn.lower(*lower_args, **lower_kwargs).compile()
            entry["compile_s"] = round(time.perf_counter() - t0, 4)
            with _lock:
                _AOT[key] = compiled
                c = _counter(reg)
                c["compiles"] += 1
                c["compile_s"] += entry["compile_s"]
            total_c += entry["compile_s"]
        # roofline ledger: the per-call cost is a static property of the
        # compiled program, so registration is idempotent and fires on
        # cache hits too — the ledger repopulates after a roofline.reset
        # even when the executable is already warm (utils/roofline.py
        # multiplies by the run counters later — the hot path pays
        # nothing)
        try:
            from .roofline import record_kernel

            record_kernel(reg, name, compiled)
        except Exception:
            pass
        if warmup:
            compiled = _AOT[key]
            inputs = mk_inputs()
            t0 = time.perf_counter()
            jax.block_until_ready(compiled(*inputs))
            entry["run_s"] = round(time.perf_counter() - t0, 4)
            with _lock:
                c = _counter(reg)
                c["runs"] += 1
                c["run_s"] += entry["run_s"]
            total_r += entry["run_s"]
        report["kernels"][name] = entry
    report["compile_s_total"] = round(total_c, 4)
    report["run_s_total"] = round(total_r, 4)
    report["persistent_cache"] = persistent_cache_events()
    return report
