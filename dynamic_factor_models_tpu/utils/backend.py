"""Backend selection: the `backend={"cpu","tpu"}` kwarg of the entry points.

The same JAX program runs on either device; estimation entry points accept
``backend=`` and execute under ``jax.default_device`` (BASELINE.json
north-star API).  ``backend=None`` keeps JAX's default placement.
"""

from __future__ import annotations

import contextlib

import jax

__all__ = ["resolve_device", "on_backend"]

_ALIASES = {"tpu": ("tpu", "axon"), "cpu": ("cpu",), "gpu": ("gpu", "cuda", "rocm")}


def resolve_device(backend: str | None):
    if backend is None:
        return None
    platforms = _ALIASES.get(backend, (backend,))
    # scan the default devices first, then ask for each platform explicitly —
    # non-default platforms (e.g. cpu under a TPU session) are only reachable
    # via jax.devices(platform)
    for d in jax.devices():
        if d.platform in platforms:
            return d
    for p in platforms:
        try:
            return jax.devices(p)[0]
        except RuntimeError:
            continue
    raise ValueError(
        f"backend {backend!r} not available; devices = {jax.devices()}"
    )


@contextlib.contextmanager
def on_backend(backend: str | None):
    dev = resolve_device(backend)
    if dev is None:
        yield None
    else:
        with jax.default_device(dev):
            yield dev
