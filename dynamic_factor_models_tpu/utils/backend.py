"""Backend selection: the `backend={"cpu","tpu"}` kwarg of the entry points.

The same JAX program runs on either device; estimation entry points accept
``backend=`` and execute under ``jax.default_device`` (BASELINE.json
north-star API).  ``backend=None`` keeps JAX's default placement.
"""

from __future__ import annotations

import contextlib

import jax

__all__ = ["resolve_device", "on_backend", "probe_default_device"]


def probe_default_device(timeout_s: int = 240):
    """Liveness-check the default jax device in a killable subprocess.

    A wedged TPU tunnel hangs backend init inside native code where
    in-process watchdogs (signals, alarms) never fire — only a separate
    process can be bounded.  The child mirrors this process's config-level
    ``jax_platforms`` (env vars alone lose to the axon sitecustomize, which
    force-sets the config at import).  Returns (ok, detail); a CPU-only
    platform config short-circuits to ok — there is no tunnel to wedge.
    """
    import os
    import subprocess
    import sys

    plat = jax.config.jax_platforms or ""
    if plat and all(p.strip() == "cpu" for p in plat.split(",")):
        return True, "cpu-only platform config; no probe needed"
    env = dict(os.environ)
    if plat:
        env["_DFM_PROBE_PLATFORMS"] = plat
    probe = (
        "import os, jax, jax.numpy as jnp\n"
        "p = os.environ.get('_DFM_PROBE_PLATFORMS')\n"
        "if p: jax.config.update('jax_platforms', p)\n"
        "jax.block_until_ready(jnp.ones(8).sum())\n"
        "print('DEVICE_OK', jax.devices()[0])\n"
    )
    try:
        pr = subprocess.run(
            [sys.executable, "-c", probe],
            capture_output=True,
            text=True,
            timeout=timeout_s,
            env=env,
        )
    except subprocess.TimeoutExpired:
        return False, f"device probe exceeded {timeout_s}s (tunnel wedged?)"
    if pr.returncode != 0 or "DEVICE_OK" not in pr.stdout:
        return False, f"rc={pr.returncode}, stderr={pr.stderr[-300:]!r}"
    return True, pr.stdout.strip()

_ALIASES = {"tpu": ("tpu", "axon"), "cpu": ("cpu",), "gpu": ("gpu", "cuda", "rocm")}


def resolve_device(backend: str | None):
    if backend is None:
        return None
    platforms = _ALIASES.get(backend, (backend,))
    # scan the default devices first, then ask for each platform explicitly —
    # non-default platforms (e.g. cpu under a TPU session) are only reachable
    # via jax.devices(platform)
    for d in jax.devices():
        if d.platform in platforms:
            return d
    for p in platforms:
        try:
            return jax.devices(p)[0]
        except RuntimeError:
            continue
    raise ValueError(
        f"backend {backend!r} not available; devices = {jax.devices()}"
    )


@contextlib.contextmanager
def on_backend(backend: str | None):
    dev = resolve_device(backend)
    if dev is None:
        yield None
    else:
        with jax.default_device(dev):
            yield dev


def fall_back_to_cpu(detail: str, caller: str = "caller") -> None:
    """Pin jax to the CPU platform after a failed device-liveness probe
    (shared by bench.py and __graft_entry__.entry()).

    The config-level platform pin only takes effect while no jax backend is
    initialized; if one already is, the pin would be a silent no-op and the
    next array creation would hang inside native code on the wedged device
    — so that case raises instead.  Detection is a post-condition check on
    public API only: after pinning, ``jax.default_backend()`` must report
    "cpu".  If a non-CPU backend was already live, that call just reads the
    existing registry (no new init, so no hang — the hang risk is only in
    *initializing* a wedged plugin) and reports the live platform, which
    turns the would-be silent no-op into a loud error; if nothing was
    initialized, it initializes the CPU platform under the fresh pin.  No
    private jax internals are consulted, so the guard survives upgrades.
    """
    import sys

    prev = jax.config.jax_platforms
    jax.config.update("jax_platforms", "cpu")
    got = jax.default_backend()
    if got != "cpu":
        jax.config.update("jax_platforms", prev)  # undo the ineffective pin
        raise RuntimeError(
            f"{caller}: default device unusable — {detail} — and a "
            f"{got!r} jax backend is already initialized, so a CPU "
            "fallback cannot take effect in this process"
        )
    print(
        f"{caller}: TPU unreachable ({detail}); falling back to the CPU "
        "platform",
        file=sys.stderr,
        flush=True,
    )
