"""Structured telemetry layer: metrics registry, spans, and RunRecords.

The reference's only observability was a commented-out ``println("diff =
...")``; `utils.profiling.ConvergenceTrace` replaced that for a single
loop, but the compile-once layer (utils/compile.py) made runtime behavior
— AOT hits, bucket padding, donation, checkpoint chunking, bf16->exact
phase handoffs — far too rich to debug from one iters/sec number.  This
module makes every estimation call leave a machine-readable trace, in the
BlackJAX spirit of keeping the inference loop separate from its
instrumentation:

metrics registry
    Process-wide counters / gauges / timers (``inc``, ``gauge_set``,
    ``observe``), snapshot via ``snapshot()``.  A ``jax.monitoring``
    bridge folds JAX's own events — including the persistent
    compilation-cache hits/misses utils/compile.py counts — into the
    same registry (event names keyed as ``jax/...``).

spans
    ``span(name)`` pairs a ``jax.profiler.TraceAnnotation`` (visible in
    Perfetto/TensorBoard traces) with wall-clock recording into the
    registry AND into every RunRecord open on the current thread, so a
    record's ``phase_s`` splits its wall time by named phase (e.g.
    ``em_dfm_sequential_bf16`` vs ``em_dfm_sequential``).

RunRecords
    ``run_record(entry, ...)`` brackets an estimation entry point.  On
    exit it captures wall time, platform/device/precision/donation,
    per-phase span seconds, per-kernel compile/run/AOT counter DELTAS
    (utils.compile.counters) plus persistent-cache event deltas, and
    device memory stats (``device.memory_stats()`` with a live-buffer
    fallback).  Records append to an in-process ring buffer (``records``)
    and, when ``DFM_TELEMETRY=<path>`` is set, to a JSONL file — one
    line per run, written with a single append so concurrent writers
    interleave at line granularity.  ``DFM_PROFILE_DIR=<dir>`` wraps the
    OUTERMOST record in ``jax.profiler`` start/stop, so one env var
    yields a Perfetto trace with the spans as named regions.

heartbeat
    ``DFM_HEARTBEAT=k`` (off by default) adds a ``jax.debug.callback``
    every k EM iterations inside the on-device ``lax.while_loop``
    (models/emloop.py), reporting (iteration, loglik) into the registry
    without a host sync on the default path — the default program
    contains no callback at all.

traces
    ``trace_span(name, seed=...)`` opens one node of a per-request SPAN
    TREE: the root derives a deterministic ``trace_id`` from the request
    seed (sha256), children derive ``span_id`` from (trace_id, parent,
    child index) — so two engines fed the identical request stream
    produce byte-identical trees.  Spans pair with
    ``jax.profiler.TraceAnnotation`` (visible in the Perfetto sink via
    ``DFM_PROFILE_DIR``), and the completed tree is emitted as ONE JSONL
    line (``entry="trace"``) when the root closes.  ``trace_event``
    records a zero-duration child (breaker trips, retries, journal
    appends).  RunRecords opened under an active trace stamp
    ``trace_id``/``parent_span``, linking e.g. a batched refit's EM-loop
    record into the requesting span tree.  Disabled path: the shared
    no-op singleton, same guarantee as ``run_record``.

latency histograms
    ``register_hist(name, **labels)`` returns a process-registered
    ``utils.histogram.LatencyHistogram`` — log-bucketed fixed-size int
    counts, O(1) lock-free increments, mergeable — which the serving
    engine increments directly per request-kind x outcome.
    ``emit_histograms()`` snapshots every registered histogram into the
    JSONL sink (``entry="hist"`` lines; LAST snapshot per key wins —
    they are cumulative); ``dump_metrics(path)`` writes a standalone
    metrics JSON; the ``export`` CLI renders either form (or the hist
    lines of a RunRecord JSONL) as OpenMetrics text exposition.

sink rotation
    The JSONL sink rotates at ``DFM_TELEMETRY_MAX_MB`` (default 256):
    when an append pushes the file past the cap it is atomically renamed
    to ``<path>.1`` (one generation, overwritten on the next rotation)
    and a fresh file begins — a long load run cannot grow one unbounded
    file.

Disabled-path guarantee: with neither env var set and no explicit
``enable()``, ``run_record`` returns a shared no-op singleton — no
allocation, no registry traffic, nothing on the EM hot path (pinned by
tests/test_perf_regression.py).

CLI: ``python -m dynamic_factor_models_tpu.telemetry summarize run.jsonl``
renders per-run and per-entry aggregate tables (docs/observability.md).
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time
import uuid

import numpy as np

import jax

from .histogram import LatencyHistogram, bucket_lower

__all__ = [
    "enabled",
    "enable",
    "disable",
    "inc",
    "gauge_set",
    "observe",
    "snapshot",
    "reset",
    "records",
    "span",
    "run_record",
    "sink_path",
    "device_memory_stats",
    "register_jax_monitoring_bridge",
    "heartbeat_every",
    "trace_span",
    "trace_span_on",
    "null_trace",
    "trace_event",
    "current_trace",
    "traces",
    "register_hist",
    "histograms",
    "emit_histograms",
    "emit_metrics",
    "dump_metrics",
    "export_openmetrics",
    "summarize",
    "main",
]

_lock = threading.RLock()
_tls = threading.local()

# explicit override: None = follow the env vars, True/False = forced
_explicit_enabled: bool | None = None
_explicit_sink: str | None = None

_counters: dict[str, float] = {}
_gauges: dict[str, float] = {}
# timers: name -> [n, total_s, min_s, max_s]
_timers: dict[str, list] = {}
_records: list[dict] = []
_MAX_RECORDS = 256
# latency histograms: (name, sorted-label-items tuple) -> LatencyHistogram
_hists: dict[tuple, LatencyHistogram] = {}
# completed span trees (ring buffer, most recent last)
_traces: list[dict] = []
_MAX_TRACES = 64

_profile_depth = 0
_profile_active = False
_bridge_registered = False


# ---------------------------------------------------------------------------
# enablement
# ---------------------------------------------------------------------------


def enabled() -> bool:
    """Telemetry is on when ``DFM_TELEMETRY`` or ``DFM_PROFILE_DIR`` is set,
    or after an explicit ``enable()``; ``disable()`` forces off."""
    if _explicit_enabled is not None:
        return _explicit_enabled
    return bool(
        os.environ.get("DFM_TELEMETRY") or os.environ.get("DFM_PROFILE_DIR")
    )


def enable(sink: str | None = None) -> None:
    """Force telemetry on in-process; ``sink`` optionally points the JSONL
    file without touching the environment."""
    global _explicit_enabled, _explicit_sink
    _explicit_enabled = True
    if sink is not None:
        _explicit_sink = sink
    register_jax_monitoring_bridge()


def disable() -> None:
    global _explicit_enabled, _explicit_sink
    _explicit_enabled = False
    _explicit_sink = None


def sink_path() -> str | None:
    """The active JSONL sink path (``enable(sink=...)`` override, else the
    ``DFM_TELEMETRY`` env var), or None."""
    return _explicit_sink or os.environ.get("DFM_TELEMETRY") or None


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------


def inc(name: str, value: float = 1.0) -> None:
    with _lock:
        _counters[name] = _counters.get(name, 0.0) + value


def gauge_set(name: str, value: float) -> None:
    with _lock:
        _gauges[name] = float(value)


def observe(name: str, seconds: float) -> None:
    """Record one duration into the named timer (count/total/min/max)."""
    with _lock:
        t = _timers.get(name)
        if t is None:
            _timers[name] = [1, seconds, seconds, seconds]
        else:
            t[0] += 1
            t[1] += seconds
            t[2] = min(t[2], seconds)
            t[3] = max(t[3], seconds)


def snapshot() -> dict:
    """In-process view of every metric: counters, gauges, timers (as
    n/total/min/max dicts), record count, and the compile-layer counters."""
    from .compile import counters as compile_counters
    from .compile import persistent_cache_events

    with _lock:
        return {
            "counters": dict(_counters),
            "gauges": dict(_gauges),
            "timers": {
                k: {"n": t[0], "total_s": t[1], "min_s": t[2], "max_s": t[3]}
                for k, t in _timers.items()
            },
            "n_records": len(_records),
            "n_hists": len(_hists),
            "compile": compile_counters(),
            "persistent_cache": persistent_cache_events(),
        }


def reset() -> None:
    """Clear the registry and the in-process record buffer (the
    compile-layer counters have their own ``reset_counters``)."""
    with _lock:
        _counters.clear()
        _gauges.clear()
        _timers.clear()
        _records.clear()
        _hists.clear()
        _traces.clear()


def records() -> list[dict]:
    """The in-process RunRecord ring buffer (most recent last)."""
    with _lock:
        return list(_records)


# ---------------------------------------------------------------------------
# jax.monitoring bridge
# ---------------------------------------------------------------------------


def _on_event(event: str, **kwargs) -> None:
    if enabled():
        inc("jax" + event)


def _on_event_duration(event: str, duration_secs: float, **kwargs) -> None:
    if enabled():
        observe("jax" + event, float(duration_secs))


def register_jax_monitoring_bridge() -> None:
    """Fold jax.monitoring events (compilation-cache hits/misses, backend
    compile durations, ...) into the registry.  Idempotent; listeners stay
    registered for the process lifetime but record only while enabled."""
    global _bridge_registered
    with _lock:
        if _bridge_registered:
            return
        try:
            jax.monitoring.register_event_listener(_on_event)
            jax.monitoring.register_event_duration_secs_listener(
                _on_event_duration
            )
            _bridge_registered = True
        except Exception:  # monitoring API moved/absent: registry still works
            pass


# ---------------------------------------------------------------------------
# spans
# ---------------------------------------------------------------------------


def _record_stack() -> list:
    s = getattr(_tls, "stack", None)
    if s is None:
        s = _tls.stack = []
    return s


class _Span:
    """`with span("phase"): ...` — TraceAnnotation + wall clock into the
    registry and every open RunRecord on this thread."""

    __slots__ = ("name", "_t0", "_ann")

    def __init__(self, name: str):
        self.name = name

    def __enter__(self):
        self._ann = jax.profiler.TraceAnnotation(self.name)
        self._ann.__enter__()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        dt = time.perf_counter() - self._t0
        self._ann.__exit__(exc_type, exc, tb)
        if enabled():
            observe("span." + self.name, dt)
        for rec in _record_stack():
            rec.add_phase(self.name, dt)
        return False


def span(name: str) -> _Span:
    return _Span(name)


# ---------------------------------------------------------------------------
# trace contexts: deterministic per-request span trees
# ---------------------------------------------------------------------------


def _trace_stack() -> list:
    s = getattr(_tls, "trace_stack", None)
    if s is None:
        s = _tls.trace_stack = []
    return s


def _trace_id_from_seed(seed) -> str:
    return hashlib.sha256(repr(seed).encode()).hexdigest()[:32]


def _span_id(trace_id: str, parent: str, idx: int) -> str:
    return hashlib.sha256(
        f"{trace_id}:{parent}:{idx}".encode()
    ).hexdigest()[:16]


class _TraceFrame:
    __slots__ = ("name", "trace_id", "span_id", "parent_id",
                 "t0", "t0_unix", "attrs", "n_children", "spans")

    def __init__(self, name, trace_id, span_id, parent_id, attrs, spans):
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.attrs = attrs
        self.n_children = 0
        self.spans = spans  # the ROOT's completed-span list (shared)
        self.t0_unix = time.time()
        self.t0 = time.perf_counter()


class _TraceSpan:
    """One node of a request span tree (use via `trace_span`)."""

    __slots__ = ("name", "seed", "attrs", "_frame", "_ann")

    def __init__(self, name, seed, attrs):
        self.name = name
        self.seed = seed
        self.attrs = attrs

    def __enter__(self):
        stack = _trace_stack()
        if stack:
            parent = stack[-1]
            parent.n_children += 1
            frame = _TraceFrame(
                self.name, parent.trace_id,
                _span_id(parent.trace_id, parent.span_id, parent.n_children),
                parent.span_id, self.attrs, parent.spans,
            )
        else:
            tid = (
                _trace_id_from_seed(self.seed)
                if self.seed is not None else uuid.uuid4().hex[:32]
            )
            frame = _TraceFrame(
                self.name, tid, _span_id(tid, "", 0), None, self.attrs, [],
            )
        stack.append(frame)
        self._frame = frame
        self._ann = jax.profiler.TraceAnnotation(self.name)
        self._ann.__enter__()
        return self

    def set(self, **attrs) -> "_TraceSpan":
        self._frame.attrs.update(attrs)
        return self

    @property
    def trace_id(self):
        return self._frame.trace_id

    @property
    def span_id(self):
        return self._frame.span_id

    def __exit__(self, exc_type, exc, tb):
        self._ann.__exit__(exc_type, exc, tb)
        frame = self._frame
        stack = _trace_stack()
        if frame in stack:
            stack.remove(frame)
        sp = {
            "name": frame.name,
            "span_id": frame.span_id,
            "parent": frame.parent_id,
            "t_unix": round(frame.t0_unix, 6),
            "dur_s": round(time.perf_counter() - frame.t0, 6),
        }
        if frame.attrs:
            sp["attrs"] = _jsonable(frame.attrs)
        if exc_type is not None:
            sp["error"] = f"{exc_type.__name__}: {exc}"
        frame.spans.append(sp)
        if frame.parent_id is None:  # root: emit the completed tree
            data = {
                "entry": "trace",
                "trace_id": frame.trace_id,
                "time_unix": round(frame.t0_unix, 3),
                "wall_s": sp["dur_s"],
                "n_spans": len(frame.spans),
                "spans": frame.spans,
            }
            with _lock:
                _traces.append(data)
                del _traces[:-_MAX_TRACES]
            _emit_line(data)
        return False


class _NullTrace:
    """Disabled-path singleton: nothing allocated, nothing recorded."""

    __slots__ = ()

    trace_id = None
    span_id = None

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        return False

    def set(self, **attrs):
        return self


_NULL_TRACE = _NullTrace()


def trace_span(name: str, seed=None, **attrs):
    """Open a span-tree node.  At the root (no enclosing span on this
    thread), `seed` deterministically derives the trace_id; children
    derive span ids from (trace_id, parent, child index).  Returns the
    shared no-op singleton when telemetry is disabled."""
    if not enabled():
        return _NULL_TRACE
    return _TraceSpan(name, seed, dict(attrs))


def trace_span_on(name: str, seed=None, **attrs):
    """`trace_span` WITHOUT the enabled() gate, for hot loops that have
    already established telemetry is on this request (``enabled()`` costs
    ~1.6µs of env lookups — real money against the serving envelope's
    ~20µs budget).  Callers gated off must use ``null_trace()``."""
    return _TraceSpan(name, seed, dict(attrs))


def null_trace():
    """The shared no-op span (see `trace_span_on`)."""
    return _NULL_TRACE


def trace_event(name: str, **attrs) -> None:
    """Record a zero-duration child span (breaker trip, retry, journal
    append) under the current trace; no-op when disabled or when no
    trace is open on this thread."""
    if not enabled():
        return
    stack = _trace_stack()
    if not stack:
        return
    parent = stack[-1]
    parent.n_children += 1
    sp = {
        "name": name,
        "span_id": _span_id(parent.trace_id, parent.span_id,
                            parent.n_children),
        "parent": parent.span_id,
        "t_unix": round(time.time(), 6),
        "dur_s": 0.0,
    }
    if attrs:
        sp["attrs"] = _jsonable(attrs)
    parent.spans.append(sp)


def current_trace():
    """(trace_id, span_id) of the innermost open span on this thread,
    or None."""
    stack = _trace_stack()
    if not stack:
        return None
    return stack[-1].trace_id, stack[-1].span_id


def traces() -> list[dict]:
    """The in-process completed-span-tree ring buffer."""
    with _lock:
        return list(_traces)


# ---------------------------------------------------------------------------
# latency histogram registry
# ---------------------------------------------------------------------------


def _hist_key(name: str, labels: dict) -> tuple:
    return (name, tuple(sorted(labels.items())))


def register_hist(name: str, **labels) -> LatencyHistogram:
    """Get-or-create the process histogram for (name, labels).  Callers
    keep the returned object and increment it DIRECTLY (`.record(dt)`)
    — the hot path touches no lock and no registry lookup."""
    key = _hist_key(name, labels)
    h = _hists.get(key)
    if h is None:
        with _lock:
            h = _hists.setdefault(key, LatencyHistogram())
    return h


def histograms() -> list[tuple[str, dict, LatencyHistogram]]:
    """Every registered histogram as (name, labels, hist)."""
    with _lock:
        return [(name, dict(lbl), h) for (name, lbl), h in _hists.items()]


def emit_histograms() -> int:
    """Snapshot every non-empty registered histogram into the JSONL sink
    (one ``entry="hist"`` line each; snapshots are CUMULATIVE, readers
    keep the last per key).  Returns the number of lines written."""
    n = 0
    for name, labels, h in histograms():
        if h.n == 0:
            continue
        _emit_line({
            "entry": "hist",
            "time_unix": round(time.time(), 3),
            "name": name,
            "labels": labels,
            "hist": h.to_dict(),
        })
        n += 1
    return n


def emit_metrics() -> int:
    """Snapshot the counter/gauge registry into the JSONL sink as one
    ``entry="metrics"`` line (cumulative — readers keep the last line,
    exactly the hist-snapshot convention).  This is how resident-set
    accounting (``serving.resident_tenants`` / ``serving.evictions`` /
    ``serving.fault_ins``) reaches `summarize` without a live process.
    Returns the number of lines written (0 without a sink)."""
    if not sink_path():
        return 0
    with _lock:
        data = {
            "entry": "metrics",
            "time_unix": round(time.time(), 3),
            "counters": dict(_counters),
            "gauges": dict(_gauges),
        }
    _emit_line(data)
    return 1


def dump_metrics(path: str) -> None:
    """Write a standalone metrics JSON (counters, gauges, histograms)
    for the `export` CLI — the cross-process hand-off in place of a
    live scrape endpoint."""
    data = {
        "version": 1,
        "time_unix": round(time.time(), 3),
        "counters": dict(_counters),
        "gauges": dict(_gauges),
        "histograms": [
            {"name": name, "labels": labels, "hist": h.to_dict()}
            for name, labels, h in histograms()
            if h.n
        ],
    }
    parent = os.path.dirname(path)
    if parent:
        os.makedirs(parent, exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(data, f, indent=1)
        # durability, not just atomicity: os.replace alone leaves the
        # rename pointing at unflushed pages — fsync before the swap
        # (the utils/checkpoint.py crash-safety pattern) so a crash
        # mid-dump can't surface a truncated or empty export
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


# ---------------------------------------------------------------------------
# heartbeat (models/emloop.py wires this into the on-device while_loop)
# ---------------------------------------------------------------------------


def heartbeat_every() -> int:
    """``DFM_HEARTBEAT=k`` -> k (>=1) EM iterations between on-device
    progress callbacks; 0 (default/unset/invalid) keeps the compiled loop
    callback-free."""
    raw = os.environ.get("DFM_HEARTBEAT", "0") or "0"
    try:
        return max(0, int(raw))
    except ValueError:
        return 0


def _heartbeat_cb(it, ll) -> None:
    """Host-side target of the ``jax.debug.callback`` in the EM while-loop
    body.  Gated by DFM_HEARTBEAT itself, so it records even when the
    JSONL sink is unconfigured."""
    try:
        it_i, ll_f = int(it), float(ll)
    except (TypeError, ValueError):
        return
    inc("em_heartbeat_events")
    gauge_set("em_heartbeat_iter", it_i)
    gauge_set("em_heartbeat_loglik", ll_f)
    if os.environ.get("DFM_HEARTBEAT_STDERR"):
        import sys

        print(f"dfm heartbeat: iter={it_i} loglik={ll_f:.6g}",
              file=sys.stderr, flush=True)


# ---------------------------------------------------------------------------
# device memory
# ---------------------------------------------------------------------------


def device_memory_stats(device=None) -> dict:
    """Allocator stats of the (default) device: ``memory_stats()`` where
    the backend implements it (TPU/GPU), else a live-buffer byte count
    (CPU's allocator is untracked), else ``{"source": "unavailable"}``."""
    try:
        d = device if device is not None else jax.devices()[0]
    except Exception:
        return {"source": "unavailable"}
    try:
        ms = d.memory_stats()
    except Exception:
        ms = None
    if ms:
        out = {"source": "memory_stats"}
        for k in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit",
                  "largest_alloc_size"):
            if k in ms:
                out[k] = int(ms[k])
        return out
    try:
        total = 0
        n = 0
        for a in jax.live_arrays():
            try:
                if d in a.devices():
                    total += int(a.nbytes)
                    n += 1
            except Exception:
                continue
        return {"source": "live_buffers", "bytes_in_use": total, "n_buffers": n}
    except Exception:
        return {"source": "unavailable"}


# ---------------------------------------------------------------------------
# RunRecords
# ---------------------------------------------------------------------------


def _jsonable(obj):
    if obj is None or isinstance(obj, (bool, int, float, str)):
        return obj
    if isinstance(obj, dict):
        return {str(k): _jsonable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_jsonable(v) for v in obj]
    if isinstance(obj, np.ndarray):
        return _jsonable(obj.tolist())
    if hasattr(obj, "item") and getattr(obj, "ndim", None) in (0, None):
        try:
            return _jsonable(obj.item())
        except Exception:
            return repr(obj)
    return repr(obj)


def _counters_delta(before: dict, after: dict) -> dict:
    out = {}
    for kernel, c in after.items():
        b = before.get(kernel, {})
        d = {}
        for field, v in c.items():
            dv = v - b.get(field, 0)
            if dv:
                d[field] = round(dv, 6) if isinstance(dv, float) else dv
        if d:
            out[kernel] = d
    return out


def _flat_delta(before: dict, after: dict) -> dict:
    return {
        k: after[k] - before.get(k, 0)
        for k in after
        if after[k] - before.get(k, 0)
    }


def _maybe_start_profile() -> None:
    global _profile_depth, _profile_active
    pdir = os.environ.get("DFM_PROFILE_DIR")
    with _lock:
        _profile_depth += 1
        if not pdir or _profile_active or _profile_depth != 1:
            return
        try:
            jax.profiler.start_trace(pdir)
            _profile_active = True
        except Exception:  # a trace already running elsewhere: skip, not die
            pass


def _maybe_stop_profile() -> None:
    global _profile_depth, _profile_active
    with _lock:
        _profile_depth = max(0, _profile_depth - 1)
        if _profile_depth != 0 or not _profile_active:
            return
        try:
            jax.profiler.stop_trace()
        except Exception:
            pass
        _profile_active = False


def _sink_max_bytes() -> int:
    """Size-based rotation cap for the JSONL sink (DFM_TELEMETRY_MAX_MB,
    default 256; <= 0 disables rotation)."""
    raw = os.environ.get("DFM_TELEMETRY_MAX_MB", "256") or "256"
    try:
        return int(float(raw) * 1e6)
    except ValueError:
        return 256_000_000


def _emit_line(data: dict) -> None:
    """Append one JSON line to the sink, rotating the file to
    ``<path>.1`` (atomic rename, one generation kept) when the append
    pushes it past the size cap — a long load run never grows one
    unbounded file.  A broken sink is swallowed: telemetry must never
    fail the instrumented call."""
    path = sink_path()
    if not path:
        return
    line = json.dumps(data, separators=(",", ":"), default=repr) + "\n"
    try:
        parent = os.path.dirname(path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        # one append-mode write per record: concurrent writers (bench
        # children, watcher runs) interleave whole lines, never fragments
        with open(path, "a") as f:
            f.write(line)
            size = f.tell()
        cap = _sink_max_bytes()
        if cap > 0 and size > cap:
            os.replace(path, path + ".1")
            inc("telemetry.sink_rotations")
    except OSError:
        pass


def _emit(data: dict) -> None:
    with _lock:
        _records.append(data)
        del _records[:-_MAX_RECORDS]
    inc("records." + data.get("entry", "?"))
    observe("run." + data.get("entry", "?"), data.get("wall_s", 0.0))
    _emit_line(data)


class RunRecord:
    """Context manager bracketing one estimation call.  Entry points call
    ``rec.set(...)`` as facts become known (shapes, bucket, n_iter,
    converged, final_loglik); everything environmental is captured here."""

    __slots__ = ("data", "phase_s", "_t0", "_c0", "_p0")

    active = True  # guard for callers whose rec.set args would force a sync

    def __init__(self, entry: str, fields: dict):
        self.data = {
            "run_id": uuid.uuid4().hex[:12],
            "entry": entry,
            "time_unix": round(time.time(), 3),
        }
        for k, v in fields.items():
            self.data[k] = _jsonable(v)
        self.phase_s: dict[str, float] = {}

    def set(self, **kwargs) -> "RunRecord":
        for k, v in kwargs.items():
            self.data[k] = _jsonable(v)
        return self

    def add_phase(self, name: str, dt: float) -> None:
        self.phase_s[name] = round(self.phase_s.get(name, 0.0) + dt, 6)

    def __enter__(self):
        from .compile import counters, persistent_cache_events

        stack = _record_stack()
        if stack:
            self.data.setdefault("parent", stack[-1].data["run_id"])
        tr = _trace_stack()
        if tr:  # link this record into the active request span tree
            self.data.setdefault("trace_id", tr[-1].trace_id)
            self.data.setdefault("parent_span", tr[-1].span_id)
        stack.append(self)
        self._c0 = counters()
        self._p0 = persistent_cache_events()
        _maybe_start_profile()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        wall = time.perf_counter() - self._t0
        _maybe_stop_profile()
        stack = _record_stack()
        if self in stack:
            stack.remove(self)
        from .compile import counters, donation_enabled
        from .compile import persistent_cache_events

        d = self.data
        d["wall_s"] = round(wall, 6)
        d["phase_s"] = dict(self.phase_s)
        d["counters_delta"] = _counters_delta(self._c0, counters())
        d["persistent_cache_delta"] = _flat_delta(
            self._p0, persistent_cache_events()
        )
        try:
            d.setdefault("platform", jax.default_backend())
            dev = jax.devices()[0]
            d.setdefault("device_kind", dev.device_kind)
            d.setdefault("n_devices", jax.device_count())
            d.setdefault("process_count", jax.process_count())
        except Exception:
            d.setdefault("platform", "unknown")
            d.setdefault("device_kind", "unknown")
            d.setdefault("n_devices", 0)
            d.setdefault("process_count", 1)
        # sharded runs set mesh_shape via rec.set(...) in the estimator;
        # single-device records carry the explicit defaults so summarize
        # can render "-" without guessing
        d.setdefault("mesh_shape", None)
        d.setdefault("sharded", False)
        d.setdefault("t_blocks", 0)
        d.setdefault("x64", bool(jax.config.jax_enable_x64))
        try:
            d.setdefault("donate", donation_enabled())
        except Exception:
            d.setdefault("donate", False)
        d["memory"] = device_memory_stats()
        # roofline fields (PR 17): device FLOPs/bytes this run dispatched
        # — the ledger's static per-call costs x this run's counter
        # deltas; absent when no ledgered kernel ran (summarize renders
        # "-", the standing mixed-vintage contract)
        try:
            from .roofline import run_fields

            rf = run_fields(d.get("counters_delta") or {}, wall)
            if rf:
                d["roofline"] = rf
        except Exception:
            pass
        if exc_type is not None:
            d["error"] = f"{exc_type.__name__}: {exc}"
        _emit(d)
        return False


class _NullRecord:
    """Shared no-op record: the unconfigured path allocates nothing and
    touches no shared state (`run_record` returns this singleton)."""

    __slots__ = ()

    active = False

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        return False

    def set(self, **kwargs):
        return self

    def add_phase(self, name, dt):
        return None


_NULL_RECORD = _NullRecord()


def run_record(entry: str, **fields):
    """Bracket one estimation call; returns the no-op singleton when
    telemetry is unconfigured (see module docstring)."""
    if not enabled():
        return _NULL_RECORD
    register_jax_monitoring_bridge()
    return RunRecord(entry, fields)


# ---------------------------------------------------------------------------
# summarize CLI
# ---------------------------------------------------------------------------


def _load_jsonl(path: str) -> list[dict]:
    out = []
    with open(path) as f:
        for ln, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except json.JSONDecodeError:
                out.append({"entry": f"<unparseable line {ln}>", "error": "bad json"})
    return out


def _fmt_table(headers: list[str], rows: list[list[str]]) -> str:
    widths = [len(h) for h in headers]
    for row in rows:
        for i, cell in enumerate(row):
            widths[i] = max(widths[i], len(cell))
    def line(cells):
        return "  ".join(c.ljust(w) for c, w in zip(cells, widths)).rstrip()
    sep = "  ".join("-" * w for w in widths)
    return "\n".join([line(headers), sep] + [line(r) for r in rows])


def _shape_str(rec: dict) -> str:
    s = rec.get("shapes") or {}
    if "T" in s and "N" in s:
        extra = "".join(
            f",{k}={s[k]}" for k in ("r", "p", "n_reps") if k in s
        )
        return f"{s['T']}x{s['N']}{extra}"
    return ",".join(f"{k}={v}" for k, v in s.items()) or "-"


def _n_series_str(rec: dict) -> str:
    """N column: the cross-section width a run actually carried — from
    the explicit `n_series` stamp (large-N entry points) with the shapes
    dict's N as fallback, '-' when neither is recorded."""
    n = rec.get("n_series")
    if n is None:
        n = (rec.get("shapes") or {}).get("N")
    return str(int(n)) if isinstance(n, (int, float)) else "-"


def _dev_str(rec: dict) -> str:
    """Devices column: '-' for single-device records, the 'x'-joined mesh
    shape at ANY rank for a run that recorded one — '8' (flat data mesh),
    '2x4' (dcn x ici), '1x4x2' (dcn x time x ici) — else the raw device
    count when a record ran multi-device without sharding (e.g. vmapped
    tenant batches).  Rendering no longer requires the `sharded` flag:
    time-only parallel runs carry a mesh but shard no series axis."""
    mesh = rec.get("mesh_shape")
    if mesh:
        return "x".join(str(int(m)) for m in mesh)
    n = rec.get("n_devices")
    if isinstance(n, (int, float)) and n > 1 and rec.get("sharded"):
        return str(int(n))
    return "-"


def _mem_mb(rec: dict) -> str:
    m = rec.get("memory") or {}
    b = m.get("peak_bytes_in_use", m.get("bytes_in_use"))
    return f"{b / 1e6:.1f}" if isinstance(b, (int, float)) else "-"


def _aot_hm(rec: dict) -> tuple[int, int]:
    h = m = 0
    for c in (rec.get("counters_delta") or {}).values():
        h += c.get("aot_hits", 0)
        m += c.get("aot_misses", 0)
    return h, m


def _health_str(rec: dict) -> str:
    """Compact guard-ladder digest: '-' for a fault-free run, else
    'detected/recovered' plus the final health name when the ladder was
    exhausted (e.g. '2/2' healthy after two recoveries, '1/0:nonfinite'
    unrecovered)."""
    f = rec.get("faults_detected") or 0
    if not f:
        return "-"
    s = f"{f}/{rec.get('recoveries') or 0}"
    health = rec.get("final_health")
    if health and health != "ok":
        s += f":{health}"
    return s


def _latest_hists(recs: list[dict]) -> dict[tuple, LatencyHistogram]:
    """Rebuild histograms from ``entry="hist"`` snapshot lines: snapshots
    are cumulative, so the LAST line per (name, labels) wins."""
    latest: dict[tuple, dict] = {}
    for r in recs:
        if r.get("entry") != "hist":
            continue
        try:
            key = (r.get("name", "?"),
                   tuple(sorted((r.get("labels") or {}).items())))
            latest[key] = r["hist"]
        except (TypeError, KeyError):
            continue
    out = {}
    for key, d in latest.items():
        try:
            out[key] = LatencyHistogram.from_dict(d)
        except (TypeError, ValueError, KeyError):
            continue
    return out


def _kind_latency_rows(hists: dict[tuple, LatencyHistogram]):
    """Per-request-kind latency table rows from the hist snapshots:
    merge outcomes within a kind (merge is exact)."""
    by_kind: dict[str, LatencyHistogram] = {}
    for (name, lbl), h in hists.items():
        kind = dict(lbl).get("kind")
        if kind is None:
            continue
        by_kind.setdefault(kind, LatencyHistogram()).merge(h)
    rows = []
    for kind, h in sorted(by_kind.items()):
        p = h.percentiles()
        rows.append([
            kind, str(p["n"]),
            f"{p['p50_ms']:.3f}", f"{p['p99_ms']:.3f}",
            f"{p['p999_ms']:.3f}", f"{p['max_ms']:.3f}",
        ])
    return rows


def _count_str(n: int) -> str:
    """Compact count: 256 -> "256", 1000 -> "1k", 2500 -> "2.5k"."""
    if n >= 1000 and n % 100 == 0:
        k = n / 1000.0
        return f"{k:g}k"
    return str(n)


def _roofline_cols(rec: dict) -> tuple[str, str]:
    """Per-run GFLOP / MFU%% columns from the PR 17 roofline stamp;
    "-" for records written before the ledger existed (the standing
    mixed-vintage fallback contract) or runs that used no ledgered
    kernel."""
    rf = rec.get("roofline") or {}
    fl = rf.get("flops_total")
    g = _gflop_str(fl) if isinstance(fl, (int, float)) and fl > 0 else "-"
    m = rf.get("mfu_pct")
    return g, f"{m:.2f}" if isinstance(m, (int, float)) else "-"


def _gflop_str(flops: float) -> str:
    """GFLOP column: fixed-point at real-workload scale, scientific for
    the tiny CI panels (0.00 would hide them)."""
    g = flops / 1e9
    return f"{g:.2f}" if g >= 0.01 else f"{g:.2g}"


def summarize(path: str, entry: str | None = None) -> str:
    """Per-run and per-entry aggregate tables of a RunRecord JSONL file,
    plus (when the file carries ``entry="hist"`` snapshot lines) a
    per-request-kind latency table sourced from the HDR histograms.
    Files written before the histogram layer simply lack the extra
    table and show "-" in the aggregate p50/p99 columns.  A rotated
    predecessor (``<path>.1``, written by the size-capped sink) is read
    first so one invocation covers the whole retained window."""
    recs = _load_jsonl(path + ".1") if os.path.exists(path + ".1") else []
    recs += _load_jsonl(path)
    hists = _latest_hists(recs)
    n_traces = sum(1 for r in recs if r.get("entry") == "trace")
    # metrics snapshots are cumulative: the last line per file wins;
    # files from sinks predating the metrics layer simply have none
    # (the resident/evict/fault-in columns then render "-")
    metrics = None
    for r in recs:
        if r.get("entry") == "metrics":
            metrics = r
    # trace trees and hist/metrics snapshots are structural lines, not runs
    recs = [
        r for r in recs if r.get("entry") not in ("trace", "hist", "metrics")
    ]
    if entry:
        recs = [r for r in recs if r.get("entry") == entry]
    if not recs:
        return f"no records in {path}" + (f" for entry {entry!r}" if entry else "")

    rows = []
    for r in recs:
        ts = time.strftime(
            "%H:%M:%S", time.localtime(r.get("time_unix", 0))
        )
        h, m = _aot_hm(r)
        ll = r.get("final_loglik")
        # serving-tick / nowcast records are not EM runs: n_iter,
        # converged, final_loglik are legitimately absent (or null) —
        # render "-" rather than "None", and never assume wall_s exists.
        # Scenario records carry fan sizes instead of iterations: show
        # "<D>d" (draws) or "<S>p" (paths) in the iters column so fans
        # are sized at a glance next to EM runs.  Particle-filter records
        # carry both a particle count and a lane count — render
        # "<P>P/<S>s" ("1kP/8s") so SMC work is sized at a glance too.
        it = r.get("n_iter")
        if it is None:
            np_ = r.get("n_particles")
            if isinstance(np_, (int, float)) and np_:
                s = r.get("n_paths")
                it = _count_str(int(np_)) + "P" + (
                    f"/{int(s)}s" if isinstance(s, (int, float)) and s else ""
                )
            else:
                for key, suffix in (("n_draws", "d"), ("n_paths", "p")):
                    v = r.get(key)
                    if isinstance(v, (int, float)) and v:
                        it = f"{int(v)}{suffix}"
                        break
        gflop, mfu = _roofline_cols(r)
        rows.append([
            ts,
            str(r.get("entry", "?")),
            str(r.get("kind") or "-"),
            str(r.get("platform", "?")),
            _dev_str(r),
            _shape_str(r),
            _n_series_str(r),
            str(it) if isinstance(it, (int, float, str)) else "-",
            {True: "y", False: "n"}.get(r.get("converged"), "-"),
            f"{ll:.5g}" if isinstance(ll, (int, float)) else "-",
            f"{r.get('wall_s') or 0.0:.3f}",
            _mem_mb(r),
            gflop,
            mfu,
            f"{h}/{m}",
            _health_str(r),
            "ERR" if r.get("error") else "",
        ])
    per_run = _fmt_table(
        ["time", "entry", "kind", "plat", "dev", "shape", "N", "iters",
         "conv", "loglik", "wall_s", "peak_MB", "GFLOP", "MFU%",
         "aot h/m", "faults", ""],
        rows,
    )

    agg: dict[str, dict] = {}
    for r in recs:
        a = agg.setdefault(r.get("entry", "?"), {
            "runs": 0, "errors": 0, "wall": 0.0, "iters": 0, "iter_runs": 0,
            "conv": 0, "compile_s": 0.0, "hits": 0, "misses": 0,
            "faults": 0, "recovered": 0, "unhealthy": 0,
            "outcomes": 0, "answered": 0, "ess_min": None,
            "gflops": 0.0, "roofline_runs": 0,
        })
        rf = r.get("roofline") or {}
        if isinstance(rf.get("flops_total"), (int, float)):
            a["gflops"] += rf["flops_total"] / 1e9
            a["roofline_runs"] += 1
        a["runs"] += 1
        a["errors"] += 1 if r.get("error") else 0
        # availability: serving envelopes stamp `outcome` per request —
        # "ok" and "degraded" both ANSWERED (degraded mode is the point),
        # error categories did not.  Entries without outcomes show "-".
        oc = r.get("outcome")
        if oc is not None:
            a["outcomes"] += 1
            a["answered"] += 1 if oc in ("ok", "degraded") else 0
        a["wall"] += r.get("wall_s", 0.0) or 0.0
        # mean_iters averages over EM-style records only: a stream of
        # online ticks must not drag an entry's mean toward zero
        if isinstance(r.get("n_iter"), (int, float)):
            a["iters"] += r["n_iter"]
            a["iter_runs"] += 1
        a["conv"] += 1 if r.get("converged") else 0
        # particle-filter records stamp the worst per-lane ESS; the
        # aggregate keeps the minimum seen so weight collapse shows up
        # in one column ("-" for entries/sinks that never stamp it)
        em = r.get("ess_min")
        if isinstance(em, (int, float)):
            a["ess_min"] = (
                em if a["ess_min"] is None else min(a["ess_min"], em)
            )
        a["faults"] += r.get("faults_detected") or 0
        a["recovered"] += r.get("recoveries") or 0
        a["unhealthy"] += (
            1 if (r.get("final_health") or "ok") != "ok" else 0
        )
        for c in (r.get("counters_delta") or {}).values():
            a["compile_s"] += c.get("compile_s", 0.0)
        h, m = _aot_hm(r)
        a["hits"] += h
        a["misses"] += m
    # per-entry latency from the hist snapshots: merge every histogram
    # whose `entry` label matches (engine histograms carry entry=serving)
    ent_hist: dict[str, LatencyHistogram] = {}
    for (name, lbl), h in hists.items():
        d = dict(lbl)
        if "unit" in d:  # unit-labeled hists (e.g. prefill depth in
            continue     # ticks) are counts, not latencies
        e = d.get("entry", "serving")
        ent_hist.setdefault(e, LatencyHistogram()).merge(h)

    def _lat(e):
        h = ent_hist.get(e)
        if h is None or h.n == 0:
            return "-", "-"
        return (f"{1e3 * h.quantile(0.5):.3f}",
                f"{1e3 * h.quantile(0.99):.3f}")

    # resident-set columns (PR 13): the serving row shows the last
    # metrics snapshot's resident-tenant gauge and the eviction /
    # fault-in counters; other entries — and files written by sinks
    # predating the metrics layer — show "-"
    def _resident_cols(e):
        if metrics is None or e != "serving":
            return "-", "-", "-"
        g = metrics.get("gauges") or {}
        c = metrics.get("counters") or {}
        res = g.get("serving.resident_tenants")
        return (
            str(int(res)) if res is not None else "-",
            str(int(c.get("serving.evictions", 0))),
            str(int(c.get("serving.fault_ins", 0))),
        )

    # occupancy column (PR 17): the serving row shows the last metrics
    # snapshot's phase-seconds split — admit/dispatch/prefill/journal/
    # commit/envelope as percentages of accounted time (prefill is the
    # PR 20 burst-catch-up phase; pre-PR-20 sinks simply report it as
    # 0); other entries, and sinks written before the occupancy gauges
    # existed, show "-"
    def _occ_col(e):
        if metrics is None or e != "serving":
            return "-"
        g = metrics.get("gauges") or {}
        vals = [
            float(g.get(f"serving.occupancy.{p}_s") or 0.0)
            for p in (
                "admit", "dispatch", "prefill", "journal", "commit",
                "envelope",
            )
        ]
        tot = sum(vals)
        if tot <= 0:
            return "-"
        return "/".join(f"{100.0 * v / tot:.0f}" for v in vals)

    # prefill columns (PR 20): blocks replayed through the dual-form
    # burst catch-up and the ticks-per-prefill p50 from the depth
    # histogram; other entries — and sinks written before the prefill
    # layer — show "-"
    def _prefill_cols(e):
        if metrics is None or e != "serving":
            return "-", "-"
        c = metrics.get("counters") or {}
        blocks = c.get("serving.prefill.blocks")
        if not blocks:
            return "-", "-"
        dh = None
        for (name, lbl), h in hists.items():
            if name == "serving.prefill.depth":
                dh = h
        return (
            str(int(blocks)),
            f"{dh.quantile(0.5):.0f}" if dh is not None and dh.n else "-",
        )

    # worker column (PR 19): the serving row renders each router
    # worker's supervisor state as a lifecycle glyph ("w0✓ w1↻ w2✗")
    # from the last metrics snapshot's serving.worker.state gauges —
    # files from sinks predating the supervision layer show "-"
    _worker_glyphs = ("✓", "?", "✗", "↻", "↻")  # WORKER_STATES ordinals

    def _worker_col(e):
        if metrics is None or e != "serving":
            return "-"
        g = metrics.get("gauges") or {}
        states = {}
        for name, v in g.items():
            base, lbl = _split_inline_labels(name)
            if base != "serving.worker.state" or not lbl:
                continue
            try:
                states[int(lbl.get("worker"))] = int(v)
            except (TypeError, ValueError):
                continue
        if not states:
            return "-"
        return " ".join(
            f"w{w}" + (
                _worker_glyphs[c] if 0 <= c < len(_worker_glyphs) else "?"
            )
            for w, c in sorted(states.items())
        )

    arows = []
    for e, a in sorted(agg.items()):
        p50, p99 = _lat(e)
        res, evd, fin = _resident_cols(e)
        pfb, pfd = _prefill_cols(e)
        arows.append([
            e,
            str(a["runs"]),
            str(a["errors"]),
            f"{a['wall']:.3f}",
            f"{a['wall'] / a['runs']:.3f}",
            (f"{a['iters'] / a['iter_runs']:.1f}"
             if a["iter_runs"] else "-"),
            f"{100.0 * a['conv'] / a['runs']:.0f}%",
            f"{a['compile_s']:.3f}",
            f"{a['hits']}/{a['misses']}",
            (f"{a['faults']}/{a['recovered']}"
             + (f" ({a['unhealthy']} bad)" if a["unhealthy"] else "")
             if a["faults"] else "-"),
            (f"{a['ess_min']:.1f}" if a["ess_min"] is not None else "-"),
            (f"{100.0 * a['answered'] / a['outcomes']:.1f}%"
             if a["outcomes"] else "-"),
            res,
            evd,
            fin,
            pfb,
            pfd,
            (_gflop_str(a["gflops"] * 1e9) if a["roofline_runs"] else "-"),
            _occ_col(e),
            _worker_col(e),
            p50,
            p99,
        ])
    aggregate = _fmt_table(
        ["entry", "runs", "err", "wall_s", "mean_s", "mean_iters",
         "conv%", "compile_s", "aot h/m", "faults", "ess_min", "avail",
         "resident", "evict", "fault_in", "pf_blk", "pf_k50", "GFLOP",
         "occ a/d/p/j/c/e", "workers", "p50_ms", "p99_ms"],
        arows,
    )
    out = (
        f"{len(recs)} record(s) in {path}\n\n{per_run}\n\n"
        f"aggregate by entry\n{aggregate}"
    )
    lat_rows = _kind_latency_rows(hists)
    if lat_rows:
        out += "\n\nrequest latency by kind (HDR histograms)\n" + _fmt_table(
            ["kind", "n", "p50_ms", "p99_ms", "p99.9_ms", "max_ms"],
            lat_rows,
        )
    if n_traces:
        out += f"\n\n{n_traces} trace tree(s) (entry=\"trace\" lines)"
    return out


# ---------------------------------------------------------------------------
# OpenMetrics text exposition
# ---------------------------------------------------------------------------


def _om_escape(v: str) -> str:
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _om_name(name: str) -> str:
    """Sanitize into an OpenMetrics metric name ([a-zA-Z0-9_:])."""
    s = "".join(
        ch if (ch.isascii() and ch.isalnum()) or ch in "_:" else "_"
        for ch in name
    )
    return ("_" + s) if s and s[0].isdigit() else (s or "_")


def _om_labelstr(labels: dict) -> str:
    if not labels:
        return ""
    items = ",".join(
        f'{_om_name(str(k))}="{_om_escape(v)}"'
        for k, v in sorted(labels.items())
    )
    return "{" + items + "}"


def _split_inline_labels(name: str) -> tuple[str, dict]:
    """Parse label-suffixed registry names — the convention counters use
    for dimensions, e.g. ``serving.breaker.transitions{state="open"}`` —
    into (base, labels).  Malformed suffixes fall back to a sanitized
    flat name so the exposition stays parseable."""
    if not (name.endswith("}") and "{" in name):
        return name, {}
    base, _, rest = name.partition("{")
    labels = {}
    for part in rest[:-1].split(","):
        k, eq, v = part.partition("=")
        if not eq or not k.strip():
            return name, None  # caller sanitizes
        labels[k.strip()] = v.strip().strip('"')
    return base, labels


def export_openmetrics(path: str | None = None) -> str:
    """Render metrics as OpenMetrics text exposition (counters as
    ``_total``, gauges, histograms as cumulative ``_bucket{le=}`` series
    plus a ``quantile=``-labelled p50/p99/p99.9 gauge family, ``# EOF``
    terminated).

    Source: the live in-process registries when `path` is None; a
    metrics JSON written by :func:`dump_metrics`; or a RunRecord JSONL
    sink (histograms rebuilt from the last ``entry="hist"`` snapshot per
    key — counters/gauges are per-run deltas there and are omitted).
    """
    counters: dict = {}
    gauges: dict = {}
    hists: list = []
    if path is None:
        with _lock:
            counters = dict(_counters)
            gauges = dict(_gauges)
        hists = [(n, lbl, h) for n, lbl, h in histograms() if h.n]
    else:
        dump = None
        try:
            with open(path) as f:
                dump = json.load(f)
        except (ValueError, OSError):
            dump = None
        if isinstance(dump, dict) and "histograms" in dump:
            counters = dict(dump.get("counters") or {})
            gauges = dict(dump.get("gauges") or {})
            for hrec in dump["histograms"]:
                try:
                    hists.append((hrec["name"], dict(hrec.get("labels") or {}),
                                  LatencyHistogram.from_dict(hrec["hist"])))
                except (TypeError, KeyError, ValueError):
                    continue
        else:
            for (name, lbl), h in _latest_hists(_load_jsonl(path)).items():
                hists.append((name, dict(lbl), h))

    lines: list[str] = []

    def _family(raw: dict, mtype: str, suffix: str) -> None:
        fams: dict[str, list] = {}
        for name, val in sorted(raw.items()):
            base, labels = _split_inline_labels(name)
            if labels is None:
                base, labels = _om_name(name), {}
            fams.setdefault(_om_name(base), []).append((labels, val))
        for fam, series in sorted(fams.items()):
            lines.append(f"# TYPE {fam} {mtype}")
            for labels, val in series:
                lines.append(f"{fam}{suffix}{_om_labelstr(labels)} {val:g}")

    _family(counters, "counter", "_total")
    _family(gauges, "gauge", "")

    qfams = set()
    for name, labels, h in hists:
        fam = _om_name(name) + "_seconds"
        lines.append(f"# TYPE {fam} histogram")
        occupied = np.flatnonzero(h.counts)
        for i in occupied:
            le = dict(labels, le=f"{bucket_lower(int(i) + 1):.6e}")
            lines.append(
                f"{fam}_bucket{_om_labelstr(le)} "
                f"{h.cumulative_below(int(i) + 1)}"
            )
        inf = dict(labels, le="+Inf")
        lines.append(f"{fam}_bucket{_om_labelstr(inf)} {h.n}")
        lines.append(f"{fam}_sum{_om_labelstr(labels)} {h.sum_s:.9g}")
        lines.append(f"{fam}_count{_om_labelstr(labels)} {h.n}")
        qfam = fam + "_quantile"
        if qfam not in qfams:
            qfams.add(qfam)
            lines.append(f"# TYPE {qfam} gauge")
        for q in (0.5, 0.99, 0.999):
            ql = dict(labels, quantile=f"{q:g}")
            lines.append(
                f"{qfam}{_om_labelstr(ql)} {h.quantile(q):.9g}"
            )
    lines.append("# EOF")
    return "\n".join(lines) + "\n"


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m dynamic_factor_models_tpu.telemetry",
        description="Inspect RunRecord JSONL files written via DFM_TELEMETRY.",
    )
    sub = ap.add_subparsers(dest="cmd", required=True)
    sm = sub.add_parser("summarize", help="per-run + aggregate tables")
    sm.add_argument("path", help="RunRecord .jsonl file")
    sm.add_argument("--entry", default=None, help="filter to one entry point")
    sm.add_argument("--json", action="store_true",
                    help="dump the parsed records as a JSON array instead")
    ex = sub.add_parser(
        "export", help="OpenMetrics text exposition of metrics"
    )
    ex.add_argument(
        "path", nargs="?", default=None,
        help="metrics JSON from dump_metrics() or RunRecord .jsonl "
             "(default: the live in-process registry)",
    )
    ex.add_argument("-o", "--output", default=None,
                    help="write to this file instead of stdout")
    args = ap.parse_args(argv)
    if args.cmd == "export":
        if args.path is not None and not os.path.exists(args.path):
            print(f"no such file: {args.path}")
            return 1
        text = export_openmetrics(args.path)
        if args.output:
            with open(args.output, "w") as f:
                f.write(text)
        else:
            print(text, end="")
        return 0
    if not os.path.exists(args.path):
        print(f"no such file: {args.path}")
        return 1
    if args.json:
        recs = _load_jsonl(args.path)
        if args.entry:
            recs = [r for r in recs if r.get("entry") == args.entry]
        print(json.dumps(recs, indent=1))
        return 0
    print(summarize(args.path, entry=args.entry))
    return 0
