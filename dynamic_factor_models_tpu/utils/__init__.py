from .backend import on_backend, resolve_device
