from . import telemetry
from .backend import on_backend, resolve_device
from .compile import (
    BASELINE_PANEL_SHAPES,
    CompileSpec,
    bucket_shape,
    configure_compilation_cache,
    counters,
    donation_enabled,
    pad_panel,
    persistent_cache_events,
    precompile,
    reset_counters,
    resolve_buckets,
)
