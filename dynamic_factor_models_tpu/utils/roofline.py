"""Per-kernel roofline ledger + mesh comm accounting (compute-scale
observability).

PR 12 made *request*-scale time observable (spans, HDR histograms, SLO
burn rates); this module makes *compute*-scale work observable — what the
device actually did, continuously, in the production entry points instead
of a one-off ``cost_analysis()`` probe inside ``bench.py --multichip``:

kernel ledger
    ``utils/compile.py::precompile`` registers every AOT executable here
    (``record_kernel``) with the static per-call cost XLA reports —
    ``cost_analysis()`` flops and bytes accessed.  Cost is a property of
    the COMPILED PROGRAM, so it is captured exactly once per kernel x
    bucket at registration time; at runtime ``ledger_snapshot()``
    multiplies it by the existing per-kernel invocation counters
    (``compile.counters()`` runs / run_s — the hot path pays nothing new)
    to expose cumulative device FLOPs, bytes, arithmetic intensity,
    achieved FLOP/s, and MFU against the measured/datasheet peak.

comm accounting
    The collectives (``ops.pallas_gram`` rings, ``parallel.timescan``
    slab-boundary ppermutes, the cross-host psum combines) call
    ``record_collective`` AT TRACE TIME — inside shard_map the payload
    shapes are static, so bytes-per-call is a compile-time fact exactly
    like kernel flops, and the hand-derived bench field
    ``dcn_payload_bytes_per_iter`` becomes a measured registry entry
    tagged by mesh axis (``dcn`` / ``time`` / ``ici`` / ``data``).

MFU peak machinery (shared with bench.py)
    ``PEAK_FLOPS_V5E_BF16`` + ``measured_gemm_peak()`` +
    ``mfu_peak()`` — the datasheet peak on TPU, a measured f32 GEMM peak
    elsewhere, always labeled with ``mfu_peak_source`` and
    ``flop_proxy`` (ROADMAP item 5's honesty contract, enforced by
    tools/check_bench_honesty.py).  The measured peak costs ~a second,
    so ``mfu_peak()`` NEVER measures implicitly: off-TPU it returns no
    peak until ``measured_gemm_peak()`` has been called explicitly
    (bench legs do; a RunRecord exit must stay cheap).

Registries are tiny per-process dicts guarded by one lock, recorded
unconditionally like ``compile.counters()`` (registration/trace-time
only — never per execution); gauge publication (``publish_gauges``) is
what telemetry enablement gates.
"""

from __future__ import annotations

import threading
import time

import numpy as np

__all__ = [
    "PEAK_FLOPS_V5E_BF16",
    "comm_summary",
    "compiled_cost",
    "kernel_ledger",
    "ledger_snapshot",
    "measured_gemm_peak",
    "mfu_peak",
    "publish_gauges",
    "record_collective",
    "record_kernel",
    "reset",
    "run_fields",
    "tensor_nbytes",
]

# TPU v5e bf16 datasheet peak (matmul); the one chip this project's live
# windows target.  bench.py aliases this constant.
PEAK_FLOPS_V5E_BF16 = 1.97e14

_TPU_PLATFORMS = ("tpu", "axon")

_lock = threading.RLock()

# kernel registry: reg name (the `compile.counters()` key) ->
#   {"flops_per_call", "bytes_per_call", "buckets": {plan name: {...}}}
# A kernel registered at several buckets keeps the LATEST registration as
# its representative per-call cost (bucket count is reported so readers
# can see when attribution is approximate — the invocation counters are
# per registry name, not per bucket).
_kernels: dict[str, dict] = {}

# comm registry: (site, axis) -> {"collective", "bytes_per_call",
#   "hops", "dtype", "traces"}.  Bytes are PER DEVICE PER CALL of the
# traced program; `hops` scales a ring's per-link traffic.
_collectives: dict[tuple, dict] = {}

# measured-GEMM peak cache: {"peak_flops": float, "measured_at": float}
_measured: dict = {}


# ---------------------------------------------------------------------------
# cost capture
# ---------------------------------------------------------------------------


def compiled_cost(compiled) -> tuple[float | None, float | None]:
    """(flops, bytes accessed) per call of a Compiled, defensively parsed
    — ``cost_analysis()`` returns a list on some JAX versions, a dict on
    others, and CPU backends may omit either field.  None = unreported."""
    try:
        ca = compiled.cost_analysis()
    except Exception:
        return None, None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    if not isinstance(ca, dict):
        return None, None
    try:
        flops = float(ca.get("flops", 0.0) or 0.0)
    except (TypeError, ValueError):
        flops = 0.0
    try:
        byts = float(ca.get("bytes accessed", 0.0) or 0.0)
    except (TypeError, ValueError):
        byts = 0.0
    return (flops if flops > 0 else None, byts if byts > 0 else None)


def record_kernel(reg: str, name: str, compiled) -> None:
    """Register one AOT executable's static per-call cost under its
    counter registry name `reg` (plan names may carry an ``@variant``
    suffix — `name` keeps it for the bucket table).  Called by
    ``compile.precompile``; never raises."""
    flops, byts = compiled_cost(compiled)
    if flops is None and byts is None:
        return
    with _lock:
        k = _kernels.setdefault(reg, {"buckets": {}})
        k["buckets"][name] = {
            "flops": flops or 0.0, "bytes": byts or 0.0,
        }
        k["flops_per_call"] = flops or 0.0
        k["bytes_per_call"] = byts or 0.0


def kernel_ledger() -> dict:
    """Static per-call cost table: reg name -> flops/bytes per call plus
    the per-bucket breakdown."""
    with _lock:
        return {
            reg: {
                "flops_per_call": k.get("flops_per_call", 0.0),
                "bytes_per_call": k.get("bytes_per_call", 0.0),
                "buckets": {b: dict(v) for b, v in k["buckets"].items()},
            }
            for reg, k in _kernels.items()
        }


# ---------------------------------------------------------------------------
# comm accounting
# ---------------------------------------------------------------------------


def tensor_nbytes(x) -> int:
    """Per-device payload bytes of an array/tracer from its static
    aval — valid inside shard_map tracing where `x.shape` is the block
    shape."""
    try:
        return int(np.prod(x.shape, dtype=np.int64)) * int(
            np.dtype(x.dtype).itemsize
        )
    except Exception:
        return 0


def record_collective(
    site: str, axis, nbytes: int, hops: int = 1, collective: str = "psum",
    dtype: str | None = None,
) -> None:
    """Record one collective call site at trace time.

    `axis` is the mesh axis name (or tuple) the payload crosses;
    `nbytes` the per-device payload bytes of ONE traced call; `hops`
    the number of per-link transfers a single call performs (ring:
    n_dev - 1; ppermute ladder: rounds).  Re-tracing the same site
    overwrites in place (cost is a static property of the traced
    program, exactly like kernel flops) and bumps `traces`."""
    ax = (
        "+".join(str(a) for a in axis)
        if isinstance(axis, (tuple, list)) else str(axis)
    )
    with _lock:
        e = _collectives.setdefault(
            (str(site), ax),
            {"collective": collective, "bytes_per_call": 0, "hops": 1,
             "dtype": dtype, "traces": 0},
        )
        e["collective"] = collective
        e["bytes_per_call"] = int(nbytes)
        e["hops"] = int(hops)
        if dtype is not None:
            e["dtype"] = dtype
        e["traces"] += 1


def comm_summary() -> dict:
    """Comm registry snapshot: per-site rows plus per-axis payload-byte
    totals (``bytes_per_call`` summed over the sites crossing that axis
    — for the EM estimators one traced call IS one iteration, so the
    per-axis total is directly comparable to the bench field
    ``dcn_payload_bytes_per_iter``)."""
    with _lock:
        sites = [
            {"site": site, "axis": ax, **dict(e)}
            for (site, ax), e in sorted(_collectives.items())
        ]
    per_axis: dict[str, dict] = {}
    for s in sites:
        a = per_axis.setdefault(
            s["axis"], {"bytes_per_call": 0, "link_bytes_per_call": 0,
                        "sites": 0},
        )
        a["bytes_per_call"] += s["bytes_per_call"]
        a["link_bytes_per_call"] += s["bytes_per_call"] * s["hops"]
        a["sites"] += 1
    return {"sites": sites, "per_axis": per_axis}


# ---------------------------------------------------------------------------
# MFU peak machinery (bench.py aliases these)
# ---------------------------------------------------------------------------


def _platform() -> str:
    try:
        import jax

        return jax.default_backend()
    except Exception:
        return "unknown"


def measured_gemm_peak(reps: int = 3, n: int = 1024, depth: int = 10) -> float:
    """Measured f32 GEMM peak FLOP/s (best of `reps` timed chains of
    `depth` n^3 matmuls) — the honest MFU denominator on platforms with
    no datasheet number.  ~a second of work; the result is cached so
    `mfu_peak()` can use it without ever re-measuring implicitly."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def chain(a, b):
        for _ in range(depth):
            a = a @ b
        return a

    key = jax.random.PRNGKey(0)
    a = jax.random.normal(key, (n, n), jnp.float32)
    b = jax.random.normal(key, (n, n), jnp.float32)
    jax.block_until_ready(chain(a, b))  # compile outside the timing
    best = float("inf")
    for _ in range(max(1, reps)):
        t0 = time.perf_counter()
        jax.block_until_ready(chain(a, b))
        best = min(best, time.perf_counter() - t0)
    peak = depth * 2.0 * float(n) ** 3 / best
    with _lock:
        _measured["peak_flops"] = peak
        _measured["measured_at"] = time.time()
    return peak


def mfu_peak(platform: str | None = None) -> dict:
    """The MFU denominator + its provenance labels:
    ``{"peak_flops", "mfu_peak_source", "flop_proxy"}``.

    TPU platforms get the v5e bf16 datasheet peak; everywhere else the
    cached ``measured_gemm_peak()`` result (``peak_flops`` is None until
    someone has measured — never measured implicitly here) and
    ``flop_proxy=True``, because off-TPU a FLOP/s figure divides XLA's
    flop model by wall-clock rather than profiling the chip."""
    p = platform if platform is not None else _platform()
    if p in _TPU_PLATFORMS:
        return {
            "peak_flops": PEAK_FLOPS_V5E_BF16,
            "mfu_peak_source": "v5e_bf16_datasheet",
            "flop_proxy": False,
        }
    with _lock:
        peak = _measured.get("peak_flops")
    return {
        "peak_flops": peak,
        "mfu_peak_source": "measured_f32_gemm" if peak else "unmeasured",
        "flop_proxy": True,
    }


# ---------------------------------------------------------------------------
# runtime snapshots
# ---------------------------------------------------------------------------


def _totals(counts: dict) -> dict:
    """Cumulative device work implied by a `compile.counters()`-shaped
    dict: per-kernel per-call cost x that kernel's run count."""
    per_kernel = {}
    flops = byts = run_s = 0.0
    runs = 0
    with _lock:
        kern = {
            reg: (k.get("flops_per_call", 0.0), k.get("bytes_per_call", 0.0),
                  len(k["buckets"]))
            for reg, k in _kernels.items()
        }
    for reg, (f_pc, b_pc, n_buckets) in kern.items():
        c = counts.get(reg)
        if not c or not c.get("runs"):
            continue
        n = int(c["runs"])
        kf, kb, ks = f_pc * n, b_pc * n, float(c.get("run_s", 0.0))
        per_kernel[reg] = {
            "runs": n, "flops": kf, "bytes": kb, "run_s": round(ks, 6),
            "buckets": n_buckets,
        }
        flops += kf
        byts += kb
        run_s += ks
        runs += n
    return {
        "flops_total": flops, "bytes_total": byts,
        "run_s_total": round(run_s, 6), "runs_total": runs,
        "per_kernel": per_kernel,
    }


def _derived(t: dict) -> dict:
    out = dict(t)
    if t["bytes_total"] > 0:
        out["intensity_flops_per_byte"] = round(
            t["flops_total"] / t["bytes_total"], 3
        )
    if t["run_s_total"] > 0 and t["flops_total"] > 0:
        out["flops_per_sec"] = t["flops_total"] / t["run_s_total"]
    peak = mfu_peak()
    out["mfu_peak_source"] = peak["mfu_peak_source"]
    out["flop_proxy"] = peak["flop_proxy"]
    if peak["peak_flops"] and out.get("flops_per_sec"):
        out["mfu_pct"] = round(
            100.0 * out["flops_per_sec"] / peak["peak_flops"], 4
        )
    return out


def ledger_snapshot() -> dict:
    """Cumulative roofline snapshot: the kernel ledger multiplied by the
    live invocation counters, with derived intensity / achieved FLOP/s /
    MFU (labeled), plus the comm registry."""
    from .compile import counters

    snap = _derived(_totals(counters()))
    snap["comm"] = comm_summary()
    return snap


def run_fields(counters_delta: dict, wall_s: float | None = None) -> dict:
    """Roofline fields for ONE run from its RunRecord `counters_delta`
    — device FLOPs/bytes this run dispatched, intensity, achieved
    FLOP/s over the measured in-run device seconds (falling back to
    `wall_s` when the run used kernels outside `aot_call` timing), and
    labeled MFU.  Empty dict when no ledgered kernel ran."""
    t = _totals(counters_delta)
    if not t["per_kernel"]:
        return {}
    if t["run_s_total"] <= 0 and wall_s and wall_s > 0:
        t["run_s_total"] = round(float(wall_s), 6)
        t["run_s_source"] = "wall"
    out = _derived(t)
    out.pop("per_kernel", None)
    return out


def publish_gauges() -> dict:
    """Push the cumulative ledger into the telemetry gauge registry
    (flows into ``export_openmetrics`` / ``dump_metrics`` /
    ``emit_metrics`` untouched) and return the snapshot.  Inline-labeled
    comm gauges ride the existing ``name{k="v"}`` convention."""
    from . import telemetry as T

    snap = ledger_snapshot()
    T.gauge_set("roofline.device_flops_total", snap["flops_total"])
    T.gauge_set("roofline.device_bytes_total", snap["bytes_total"])
    T.gauge_set("roofline.device_run_s_total", snap["run_s_total"])
    if "intensity_flops_per_byte" in snap:
        T.gauge_set(
            "roofline.intensity_flops_per_byte",
            snap["intensity_flops_per_byte"],
        )
    if "flops_per_sec" in snap:
        T.gauge_set("roofline.flops_per_sec", snap["flops_per_sec"])
    if "mfu_pct" in snap:
        T.gauge_set("roofline.mfu_pct", snap["mfu_pct"])
    T.gauge_set(
        "roofline.flop_proxy", 1.0 if snap["flop_proxy"] else 0.0
    )
    for ax, a in snap["comm"]["per_axis"].items():
        T.gauge_set(
            f'comm.bytes_per_call{{axis="{ax}"}}', a["bytes_per_call"]
        )
        T.gauge_set(
            f'comm.link_bytes_per_call{{axis="{ax}"}}',
            a["link_bytes_per_call"],
        )
    return snap


def reset() -> None:
    """Clear the kernel + comm registries (tests).  The measured-GEMM
    peak cache survives — it is a property of the machine, not the
    workload."""
    with _lock:
        _kernels.clear()
        _collectives.clear()
