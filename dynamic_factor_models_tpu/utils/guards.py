"""Numerical-health guardrails for the EM/Kalman stack.

Three pieces, consumed by `models/emloop.py`:

* **Sentinel** — predicates folded into the guarded while-loop carry.
  EM log-likelihood is non-decreasing in exact arithmetic, so a decrease
  beyond `drop_tol()` (relative, covers the steady tail's approximate
  moments) or any non-finite loglik / parameter leaf flips the carry's
  `health` flag and exits the loop with the LAST-GOOD params preserved.
  Health codes: 0 healthy, 1 non-finite, 2 monotonicity violation.

* **Recovery ladder** — host-side escalation applied to the rolled-back
  params when the sentinel trips, each rung retried once, in order:

      1. ridge-jitter the innovation covariance, small epsilon
      2. ridge-jitter again, grown epsilon (PSD-projected both times)
      3. demote: drop method="steady" / accelerated EM to the exact
         sequential step (caller supplies the fallback via run_em_loop)
      4. promote f32 runs to f64

  The ladder is bounded: when every rung is exhausted the loop returns
  the last-good params with `final_health != 0` in telemetry rather
  than raising — a degraded answer beats a dead serving process.

* **Switches** — `DFM_GUARDS=0` disables the guarded program entirely
  (run_em_loop then dispatches the PR-1 unguarded while-loop, whose HLO
  is pinned byte-identical by the chaos bench); `DFM_GUARD_DROP_TOL`
  overrides the relative monotonicity tolerance.

All jnp helpers here are trace-safe (no python branching on values) so
they can live inside the jitted loop body; the ladder itself is pure
host code and runs only on the cold trip path.
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp

__all__ = [
    "HEALTH_OK",
    "HEALTH_NONFINITE",
    "HEALTH_DECREASE",
    "HEALTH_NAMES",
    "LADDER_RUNGS",
    "guards_enabled",
    "drop_tol",
    "tree_finite",
    "host_finite",
    "batched_tree_finite",
    "batched_where",
    "psd_project",
    "ridge_jitter",
    "promote_f64",
    "poison_cov",
]

HEALTH_OK = 0
HEALTH_NONFINITE = 1
HEALTH_DECREASE = 2
HEALTH_NAMES = {
    HEALTH_OK: "ok",
    HEALTH_NONFINITE: "nonfinite",
    HEALTH_DECREASE: "loglik_decrease",
}

# rung names in escalation order; telemetry's `ladder_rung` reports the
# 1-based index of the last rung attempted (0 = never tripped)
LADDER_RUNGS = ("jitter", "jitter_grown", "demote", "promote_f64")
# the leading rungs the guarded EM loop applies ON DEVICE (models/emloop.py):
# jitter and jitter_grown are pure covariance repairs on the rolled-back
# carry, so they run inside the traced while-loop body with no host
# round-trip; demote/promote_f64 change the step function / dtypes and
# must re-dispatch from the host
N_TRACED_RUNGS = 2

# rung epsilons for the two jitter attempts, scaled by mean diagonal
_JITTER_EPS = (1e-8, 1e-4)


def guards_enabled() -> bool:
    """In-loop sentinel + ladder on by default; DFM_GUARDS=0 disables."""
    return os.environ.get("DFM_GUARDS", "1").strip().lower() not in (
        "0",
        "false",
        "off",
        "no",
        "",
    )


def drop_tol() -> float:
    """Relative loglik-decrease tolerance before the sentinel trips.

    The default 1e-3 is loose against f32 roundoff and the steady tail's
    approximate E-step moments, but tight against genuine divergence
    (a poisoned step typically moves loglik by orders of magnitude or
    straight to NaN)."""
    raw = os.environ.get("DFM_GUARD_DROP_TOL")
    if raw is None or not raw.strip():
        return 1e-3
    v = float(raw)
    if not v >= 0.0:  # also rejects NaN
        raise ValueError(f"DFM_GUARD_DROP_TOL must be >= 0, got {raw!r}")
    return v


def tree_finite(tree) -> jnp.ndarray:
    """Scalar bool: every inexact leaf of `tree` is finite everywhere.

    Cheap relative to an EM step (one reduction per leaf, a handful of
    leaves) and trace-safe, so it rides inside the guarded loop body."""
    leaves = [
        jnp.all(jnp.isfinite(x))
        for x in jax.tree_util.tree_leaves(tree)
        if jnp.issubdtype(jnp.asarray(x).dtype, jnp.inexact)
    ]
    if not leaves:
        return jnp.asarray(True)
    out = leaves[0]
    for v in leaves[1:]:
        out = out & v
    return out


def host_finite(tree) -> bool:
    """Host-side finiteness probe: a concrete python bool, for guard
    points OUTSIDE any trace — the serving engine checks each committed
    tick result with this before journaling it, so a poisoned state
    (``tick_nan@n``) is caught at the request boundary instead of
    corrupting the tenant's committed filter.  Pulls the leaves to host
    (they are O(k) serving-state sized, not panel sized)."""
    import numpy as np

    for x in jax.tree_util.tree_leaves(tree):
        arr = np.asarray(x)
        if np.issubdtype(arr.dtype, np.inexact) and not np.isfinite(arr).all():
            return False
    return True


def batched_tree_finite(tree) -> jnp.ndarray:
    """(B,) bool: per-batch-member finiteness of every inexact leaf —
    `tree_finite` vectorized over a leading batch axis, so one lane's
    NaN flags only that lane.  The shared sentinel of the vmapped
    multi-tenant EM loop (models/emloop.py) and the multi-chain Gibbs
    sampler (scenarios/gibbs.py)."""
    checks = [
        jnp.all(jnp.isfinite(x).reshape(x.shape[0], -1), axis=1)
        for x in jax.tree_util.tree_leaves(tree)
        if jnp.issubdtype(jnp.asarray(x).dtype, jnp.inexact)
    ]
    out = checks[0]
    for v in checks[1:]:
        out = out & v
    return out


def batched_where(cnd, x, y):
    """Per-lane pytree select: `cnd` (B,) broadcast against every leaf's
    leading batch axis — lane b takes x's leaves where cnd[b], else y's.
    Trace-safe; the freeze/rollback select of the batched loops."""
    return jax.tree_util.tree_map(
        lambda a, b: jnp.where(
            cnd.reshape(cnd.shape + (1,) * (a.ndim - 1)), a, b
        ),
        x,
        y,
    )


def psd_project(M: jnp.ndarray, eps: float) -> jnp.ndarray:
    """Symmetrize and clamp eigenvalues to >= eps*scale, NaN-proof.

    Non-finite entries are zeroed before the eigh (NaN anywhere would
    otherwise NaN the whole spectrum) so a poisoned covariance comes
    back as a valid PSD matrix instead of propagating."""
    M = jnp.where(jnp.isfinite(M), M, 0.0)
    M = 0.5 * (M + M.T)
    w, v = jnp.linalg.eigh(M)
    scale = jnp.maximum(jnp.mean(jnp.abs(w)), 1.0)
    w = jnp.maximum(w, eps * scale)
    return (v * w) @ v.T


def _map_cov(params, fn_sq, fn_diag):
    """Apply fn_sq to the square innovation covariance `.Q` and fn_diag
    to the diagonal observation variance `.R` (when present), recursing
    through wrapper states that hold the real params under `.params`
    (SteadyEMState, SquaremState).  Everything else passes through."""
    if hasattr(params, "params") and not hasattr(params, "Q"):
        return params._replace(params=_map_cov(params.params, fn_sq, fn_diag))
    rep = {}
    if hasattr(params, "Q"):
        rep["Q"] = fn_sq(params.Q)
    if hasattr(params, "R") and getattr(params, "R") is not None:
        R = params.R
        if getattr(R, "ndim", 0) == 1:
            rep["R"] = fn_diag(R)
    if hasattr(params, "sigv2"):
        rep["sigv2"] = fn_diag(params.sigv2)
    if not rep:
        return params
    return params._replace(**rep)


def ridge_jitter(params, rung):
    """Rung-`rung` (0 or 1) covariance repair on rolled-back params:
    PSD-project Q with a growing eigenvalue floor, floor the diagonal
    observation variances, and scrub any non-finite leaf back to zero
    (the rollback params are last-good, so this is belt-and-braces).
    The repaired Q is verified factorizable with ops.linalg.chol_guarded;
    if even the projection cannot be factorized the covariance is
    replaced by a trace-matched identity — maximally dull, always PD.

    `rung` may be a Python int (host recovery ladder) OR a traced int32
    scalar (the device-resident jitter rungs inside the guarded EM loop):
    the epsilon lookup is an array gather, every other op was already
    trace-safe, and epsilons are cast to each leaf's dtype so a traced
    rung never promotes an f32 covariance under x64."""
    from ..ops.linalg import chol_guarded

    eps = jnp.asarray(_JITTER_EPS, jnp.result_type(float))[
        jnp.minimum(jnp.asarray(rung, jnp.int32), len(_JITTER_EPS) - 1)
    ]
    params = jax.tree_util.tree_map(
        lambda x: (
            jnp.where(jnp.isfinite(x), x, 0.0)
            if jnp.issubdtype(jnp.asarray(x).dtype, jnp.inexact)
            else x
        ),
        params,
    )

    def repair_sq(Q):
        e = eps.astype(Q.dtype)
        Qp = psd_project(Q, e)
        _, ok = chol_guarded(Qp)
        scale = jnp.maximum(jnp.trace(Qp) / Qp.shape[0], e)
        return jnp.where(ok, Qp, scale * jnp.eye(Qp.shape[0], dtype=Qp.dtype))

    def repair_diag(d):
        e = eps.astype(d.dtype)
        return jnp.maximum(jnp.where(jnp.isfinite(d), d, e), e)

    return _map_cov(params, repair_sq, repair_diag)


def promote_f64(tree):
    """Promote every floating leaf to float64 (ladder rung 4).  Returns
    the tree unchanged when x64 is not enabled — the caller checks
    `jax.config.jax_enable_x64` and skips the rung with a telemetry
    note instead of silently retrying an identical f32 program."""
    if not jax.config.jax_enable_x64:
        return tree
    return jax.tree_util.tree_map(
        lambda x: (
            jnp.asarray(x, jnp.float64)
            if jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating)
            else x
        ),
        tree,
    )


def poison_cov(params, do):
    """Fault-injection helper (chol_fail): where traced bool `do` is
    set, replace the innovation covariance with NaN so the filter's
    Cholesky factorization genuinely fails downstream.  An indefinite
    Q would be rescued by the EM step's own PSD floor; NaN survives
    `maximum` and eigh, which is exactly the point."""
    nanify = lambda Q: jnp.where(do, jnp.full_like(Q, jnp.nan), Q)
    return _map_cov(params, nanify, lambda d: d)
