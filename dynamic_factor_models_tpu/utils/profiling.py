"""Profiling/tracing hooks (SURVEY.md section 5.1).

Thin wrappers over jax.profiler so estimation loops can annotate their hot
regions; traces are viewable in TensorBoard/Perfetto.  The convergence-trace
recorder replaces the reference's commented-out `println("diff = ...")`
debugging (dfm_functions.ipynb cell 20:42) with structured data.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

import jax

__all__ = ["annotate", "trace_to", "ConvergenceTrace"]


def annotate(name: str):
    """Named region for profiler traces: `with annotate("als_step"): ...`"""
    return jax.profiler.TraceAnnotation(name)


# jax.profiler.trace already pairs start/stop with exception-safe cleanup
trace_to = jax.profiler.trace


@dataclass
class ConvergenceTrace:
    """Records per-iteration objective values + wall time of an ALS/EM loop."""

    name: str = "loop"
    values: list = field(default_factory=list)
    times: list = field(default_factory=list)
    _t0: float = field(default_factory=time.perf_counter)

    def record(self, value: float) -> None:
        self.values.append(float(value))
        self.times.append(time.perf_counter() - self._t0)

    @property
    def iters_per_sec(self) -> float:
        if len(self.times) < 2:
            return float("nan")
        dt = self.times[-1] - self.times[0]
        if dt <= 0.0:  # sub-tick loop: rate is indeterminate, not an error
            return float("nan")
        return (len(self.times) - 1) / dt
