"""Profiling/tracing hooks (SURVEY.md section 5.1).

Thin wrappers over jax.profiler so estimation loops can annotate their hot
regions; traces are viewable in TensorBoard/Perfetto.  Since PR 17
``annotate`` also opens a telemetry trace span with the SAME region name
whenever telemetry is enabled, so the Perfetto timeline and the JSONL
span trees (utils/telemetry.trace_span) agree on what a region is called
— one vocabulary across both viewers.

The convergence-trace recorder replaces the reference's commented-out
`println("diff = ...")` debugging (dfm_functions.ipynb cell 20:42) with
structured data.  Its wall-clock fields (`times`, `iters_per_sec`) are
DEPRECATED as a timing source: RunRecord's ``wall_s`` / ``phase_s`` and
the compile-layer run counters are the canonical clocks (one timebase,
visible in `telemetry summarize`); keep using ConvergenceTrace for the
objective-value sequence itself.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

import jax

__all__ = ["annotate", "trace_to", "ConvergenceTrace"]


class _AnnotatedSpan:
    """``jax.profiler.TraceAnnotation`` + ``telemetry.trace_span`` opened
    and closed together under one region name."""

    __slots__ = ("_name", "_ann", "_span")

    def __init__(self, name: str):
        self._name = name

    def __enter__(self):
        from . import telemetry as T

        self._ann = jax.profiler.TraceAnnotation(self._name)
        self._ann.__enter__()
        # enabled() was probed once in annotate(); the _on variant skips
        # the repeat (the same idiom the serving engine uses)
        self._span = T.trace_span_on(self._name)
        self._span.__enter__()
        return self

    def __exit__(self, exc_type, exc, tb):
        try:
            self._span.__exit__(exc_type, exc, tb)
        finally:
            return self._ann.__exit__(exc_type, exc, tb)


def annotate(name: str):
    """Named region for profiler traces: `with annotate("als_step"): ...`

    With telemetry enabled the same name also becomes a telemetry trace
    span (child of whatever request/run span is active), so span trees
    and Perfetto annotations line up; the disabled path returns the bare
    ``TraceAnnotation`` exactly as before."""
    from . import telemetry as T

    if T.enabled():
        return _AnnotatedSpan(name)
    return jax.profiler.TraceAnnotation(name)


# jax.profiler.trace already pairs start/stop with exception-safe cleanup
trace_to = jax.profiler.trace


@dataclass
class ConvergenceTrace:
    """Records per-iteration objective values + wall time of an ALS/EM
    loop.

    .. deprecated:: PR 17
        The wall-clock side (`times`, `iters_per_sec`) duplicates the
        RunRecord phase/wall seconds on a second timebase — prefer
        ``run_record(...)`` fields for timing.  The objective-value
        sequence (`values`) remains first-class."""

    name: str = "loop"
    values: list = field(default_factory=list)
    times: list = field(default_factory=list)
    _t0: float = field(default_factory=time.perf_counter)

    def record(self, value: float) -> None:
        self.values.append(float(value))
        self.times.append(time.perf_counter() - self._t0)

    @property
    def iters_per_sec(self) -> float:
        if len(self.times) < 2:
            return float("nan")
        dt = self.times[-1] - self.times[0]
        if dt <= 0.0:  # sub-tick loop: rate is indeterminate, not an error
            return float("nan")
        return (len(self.times) - 1) / dt
