"""Lazy ctypes loader for the native (C++) ingest kernels.

The reference is pure Julia with no native code (SURVEY.md section 2), so
there is nothing to port — these are new native components for the runtime
around the JAX compute path: the ingest hot loop (biweight detrend,
readin_functions.jl:335-348 equivalent) compiled with g++ on first use and
loaded via ctypes (no pybind11 in the image; SURVEY.md section 7 environment
notes).

Build artifacts land in <repo>/build/.  Set DFM_NATIVE=0 to force the NumPy
fallback; if g++ or a writable build dir is unavailable the fallback engages
silently — the native path is an accelerator, never a requirement.
"""

from __future__ import annotations

import ctypes
import os
import subprocess

import numpy as np

_LIB = None
_TRIED = False


def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _cpu_tag() -> str:
    """Short fingerprint of the host CPU's ISA extensions.

    -march=native output is only valid on the CPU family that built it; keying
    the cached .so by this tag forces a rebuild when the checkout moves to a
    different machine (shared volume, migrated VM) instead of SIGILL-ing."""
    import hashlib

    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith("flags"):
                    return hashlib.sha1(line.encode()).hexdigest()[:8]
    except OSError:
        pass
    import platform

    return hashlib.sha1(platform.processor().encode()).hexdigest()[:8]


def _build_and_load():
    src = os.path.join(_repo_root(), "native", "biweight.cpp")
    if not os.path.exists(src):
        return None
    build_dir = os.path.join(_repo_root(), "build")
    so_path = os.path.join(build_dir, f"libdfm_native-{_cpu_tag()}.so")
    try:
        if not os.path.exists(so_path) or os.path.getmtime(so_path) < os.path.getmtime(src):
            os.makedirs(build_dir, exist_ok=True)
            # per-process temp name: concurrent first-use builds must not
            # interleave writes to the same file before the atomic rename
            tmp = f"{so_path}.tmp.{os.getpid()}"
            try:
                subprocess.run(
                    ["g++", "-O3", "-march=native", "-funroll-loops", "-shared",
                     "-fPIC", "-o", tmp, src],
                    check=True,
                    capture_output=True,
                )
                os.replace(tmp, so_path)
            finally:
                if os.path.exists(tmp):  # failed build: no orphaned artifacts
                    os.remove(tmp)
        lib = ctypes.CDLL(so_path)
        lib.biweight_trend.argtypes = [
            ctypes.POINTER(ctypes.c_double),
            ctypes.c_long,
            ctypes.c_long,
            ctypes.c_double,
            ctypes.POINTER(ctypes.c_double),
        ]
        lib.biweight_trend.restype = None
        return lib
    except (OSError, subprocess.CalledProcessError):
        return None


def _get_lib():
    global _LIB, _TRIED
    if not _TRIED:
        _TRIED = True
        if os.environ.get("DFM_NATIVE", "1") != "0":
            _LIB = _build_and_load()
    return _LIB


def biweight_trend_native(data: np.ndarray, bandwidth: float) -> np.ndarray | None:
    """Native banded biweight trend; None when the library is unavailable."""
    lib = _get_lib()
    if lib is None:
        return None
    x = np.ascontiguousarray(data, dtype=np.float64)
    T, ns = x.shape
    out = np.empty_like(x)
    lib.biweight_trend(
        x.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        ctypes.c_long(T),
        ctypes.c_long(ns),
        ctypes.c_double(float(bandwidth)),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
    )
    return out
