from .ingest import (
    BiWeight,
    Dataset,
    Mean,
    MonthlyData,
    NoDetrend,
    QuarterlyData,
    default_data_path,
    find_row_number,
    readin_data,
)
