from .ingest import (
    BiWeight,
    Dataset,
    Mean,
    MonthlyData,
    MonthlyDataset,
    NoDetrend,
    QuarterlyData,
    default_data_path,
    find_row_number,
    readin_data,
    readin_data_monthly,
)
