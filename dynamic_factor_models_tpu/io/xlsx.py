"""Minimal stdlib .xlsx sheet reader.

The environment has no openpyxl/xlrd, so we parse the OOXML zip directly
(zipfile + regex over the worksheet XML). Only the features the Stock-Watson
panel file needs are implemented: shared strings, inline numeric values and
date-styled serial numbers.

Cell coercion mirrors the behavior the reference pipeline depends on
(reference: readin_functions.jl:217-226): numeric cells become floats,
date-styled cells become ``datetime.date``, strings stay strings, and empty
cells are ``None``.  The caller then maps non-float cells to missing.
"""

from __future__ import annotations

import datetime
import re
import zipfile
from functools import lru_cache

# Built-in OOXML number formats that render as dates, plus any custom format
# containing y/m/d tokens is detected dynamically from styles.xml.
_BUILTIN_DATE_FMTS = set(range(14, 23)) | set(range(45, 48))

_EXCEL_EPOCH = datetime.date(1899, 12, 30)


def _col_to_index(col: str) -> int:
    """'A' -> 0, 'B' -> 1, ..., 'AA' -> 26."""
    idx = 0
    for ch in col:
        idx = idx * 26 + (ord(ch) - ord("A") + 1)
    return idx - 1


def _parse_shared_strings(z: zipfile.ZipFile) -> list[str]:
    try:
        xml = z.read("xl/sharedStrings.xml").decode("utf-8")
    except KeyError:
        return []
    out = []
    for si in re.findall(r"<si>(.*?)</si>", xml, re.S):
        parts = re.findall(r"<t[^>]*>(.*?)</t>", si, re.S)
        text = "".join(parts)
        text = (
            text.replace("&amp;", "&")
            .replace("&lt;", "<")
            .replace("&gt;", ">")
            .replace("&quot;", '"')
            .replace("&apos;", "'")
        )
        out.append(text)
    return out


def _parse_date_styles(z: zipfile.ZipFile) -> set[int]:
    """Return the set of cellXfs indices whose number format is a date."""
    try:
        xml = z.read("xl/styles.xml").decode("utf-8")
    except KeyError:
        return set()
    custom_date = set()
    for m in re.finditer(r'<numFmt numFmtId="(\d+)" formatCode="([^"]*)"', xml):
        fmt_id, code = int(m.group(1)), m.group(2)
        # strip quoted literals and color/locale fields before token scan
        stripped = re.sub(r'"[^"]*"|\[[^\]]*\]|\\.', "", code)
        if re.search(r"[ymdhs]", stripped, re.I):
            custom_date.add(fmt_id)
    cellxfs = xml[xml.find("<cellXfs") : xml.find("</cellXfs>")]
    date_xfs = set()
    for i, m in enumerate(re.finditer(r"<xf [^>]*?>", cellxfs[cellxfs.find(">") + 1 :])):
        idm = re.search(r'numFmtId="(\d+)"', m.group(0))
        fmt = int(idm.group(1)) if idm else 0
        if fmt in _BUILTIN_DATE_FMTS or fmt in custom_date:
            date_xfs.add(i)
    return date_xfs


def _sheet_targets(z: zipfile.ZipFile) -> dict[str, str]:
    wb = z.read("xl/workbook.xml").decode("utf-8")
    rels = z.read("xl/_rels/workbook.xml.rels").decode("utf-8")
    rel_map = dict(
        re.findall(r'<Relationship Id="([^"]+)"[^>]*Target="([^"]+)"', rels)
    )
    out = {}
    for m in re.finditer(r'<sheet name="([^"]+)"[^>]*r:id="([^"]+)"', wb):
        name, rid = m.group(1), m.group(2)
        target = rel_map[rid]
        if not target.startswith("xl/"):
            target = "xl/" + target
        out[name] = target
    return out


def serial_to_date(serial: float) -> datetime.date:
    return _EXCEL_EPOCH + datetime.timedelta(days=int(serial))


@lru_cache(maxsize=4)
def _read_workbook(path: str):
    z = zipfile.ZipFile(path)
    return z, _parse_shared_strings(z), _parse_date_styles(z), _sheet_targets(z)


def read_sheet(path: str, sheet: str) -> list[list[object]]:
    """Read a worksheet into a dense row-major list of lists.

    Values are float, ``datetime.date``, str, or None (empty cell).
    """
    z, shared, date_xfs, targets = _read_workbook(str(path))
    xml = z.read(targets[sheet]).decode("utf-8")

    rows: dict[int, dict[int, object]] = {}
    max_row = 0
    max_col = 0
    cell_re = re.compile(
        r'<c r="([A-Z]+)(\d+)"((?:[^>/])*)(?:/>|>(.*?)</c>)', re.S
    )
    v_re = re.compile(r"<v>([^<]*)</v>")
    t_re = re.compile(r't="(\w+)"')
    s_re = re.compile(r's="(\d+)"')
    for m in cell_re.finditer(xml):
        col_s, row_s, attrs, body = m.group(1), m.group(2), m.group(3), m.group(4)
        r = int(row_s)
        c = _col_to_index(col_s)
        value: object = None
        if body:
            vm = v_re.search(body)
            if vm is not None:
                raw = vm.group(1)
                tm = t_re.search(attrs)
                ctype = tm.group(1) if tm else "n"
                if ctype == "s":
                    value = shared[int(raw)]
                elif ctype == "str":
                    value = raw
                elif ctype == "b":
                    value = float(int(raw))
                else:
                    val = float(raw)
                    sm = s_re.search(attrs)
                    if sm is not None and int(sm.group(1)) in date_xfs:
                        value = serial_to_date(val)
                    else:
                        value = val
            else:
                im = re.search(r"<is>.*?<t[^>]*>(.*?)</t>", body, re.S)
                if im is not None:
                    value = im.group(1)
        if value is not None:
            rows.setdefault(r, {})[c] = value
            max_row = max(max_row, r)
            max_col = max(max_col, c)

    grid = [[None] * (max_col + 1) for _ in range(max_row)]
    for r, cols in rows.items():
        for c, v in cols.items():
            grid[r - 1][c] = v
    return grid
