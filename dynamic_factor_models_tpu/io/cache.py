"""Dataset caching: serialize the ingested panel to .npz.

The ingest is deterministic (reference: readin_functions.jl:355-385), so the
standardized panel is cached once and reloaded by tests/benchmarks without
touching Excel (SURVEY.md section 7.2 M0).
"""

from __future__ import annotations

import os

import numpy as np

from .ingest import (
    BiWeight,
    Dataset,
    MonthlyData,
    MonthlyDataset,
    QuarterlyData,
    readin_data,
    readin_data_monthly,
)

_ARRAY_FIELDS = [
    "bpdata_raw",
    "bpcatcode",
    "bpdata",
    "bpdata_unfiltered",
    "bpdata_noa",
    "bpdata_trend",
    "inclcode",
    "calvec",
]


def save_dataset(ds: Dataset, path: str) -> None:
    payload = {f: getattr(ds, f) for f in _ARRAY_FIELDS}
    payload["bpnamevec"] = np.array(ds.bpnamevec)
    payload["calds"] = np.array(ds.calds)
    np.savez_compressed(path, **payload)


def load_dataset(path: str) -> Dataset:
    z = np.load(path, allow_pickle=False)
    return Dataset(
        **{f: z[f] for f in _ARRAY_FIELDS},
        bpnamevec=[str(s) for s in z["bpnamevec"]],
        calds=[(int(y), int(q)) for y, q in z["calds"]],
    )


def benchmark_ingest(datatype: str = "Real", path: str | None = None) -> Dataset:
    """Run the ingest with the driver's benchmark settings (Stock_Watson.ipynb
    cells 6-10): 1959-2014 panel, 148 monthly + 85 quarterly series,
    BiWeight(100) detrending.  The single source of truth for these
    hyperparameters."""
    md = MonthlyData.from_range((1959, 1), (2014, 12), 148)
    qd = QuarterlyData.from_range((1959, 1), (2014, 4), 85)
    return readin_data(md, qd, BiWeight(100.0), datatype, path=path)


def cached_dataset(datatype: str = "Real", cache_dir: str | None = None) -> Dataset:
    """Load the standard BiWeight(100) dataset, building the cache if needed."""
    if cache_dir is None:
        cache_dir = _default_cache_dir()
    os.makedirs(cache_dir, exist_ok=True)
    path = os.path.join(cache_dir, f"sw_panel_{datatype.lower()}.npz")
    if not os.path.exists(path):
        ds = benchmark_ingest(datatype)
        save_dataset(ds, path)
        return ds
    return load_dataset(path)


def _default_cache_dir() -> str:
    return os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
        "data",
    )


def cached_monthly_dataset(
    datatype: str = "All", cache_dir: str | None = None
) -> MonthlyDataset:
    """Monthly-frequency panel for the mixed-frequency DFM, cached like
    `cached_dataset`."""
    cache_dir = cache_dir or _default_cache_dir()
    os.makedirs(cache_dir, exist_ok=True)
    path = os.path.join(cache_dir, f"sw_monthly_{datatype.lower()}.npz")
    if not os.path.exists(path):
        md = MonthlyData.from_range((1959, 1), (2014, 12), 148)
        qd = QuarterlyData.from_range((1959, 1), (2014, 4), 85)
        ds = readin_data_monthly(md, qd, datatype)
        np.savez_compressed(
            path,
            data=ds.data,
            is_quarterly=ds.is_quarterly,
            catcode=ds.catcode,
            inclcode=ds.inclcode,
            names=np.array(ds.names),
            calmds=np.array(ds.calmds),
            calvec=ds.calvec,
        )
        return ds
    z = np.load(path, allow_pickle=False)
    return MonthlyDataset(
        data=z["data"],
        is_quarterly=z["is_quarterly"],
        catcode=z["catcode"],
        inclcode=z["inclcode"],
        names=[str(s) for s in z["names"]],
        calmds=[(int(y), int(m)) for y, m in z["calmds"]],
        calvec=z["calvec"],
    )
