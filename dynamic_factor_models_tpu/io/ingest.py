"""Stock-Watson (2016) panel ingest pipeline (host-side NumPy).

Re-implements the reference data layer (reference: readin_functions.jl:1-385)
as a pure-NumPy pipeline.  Missing values are NaN (the reference uses Julia
``Union{Missing,Float64}``); every downstream JAX kernel consumes
(values-with-NaN, mask) pairs.

Pipeline stages (reference line cites):
  read xlsx sheet          readin_functions.jl:204-226
  header schema            readin_functions.jl:258-283
  deflators lookup         readin_functions.jl:285-301
  Killian standardization  readin_functions.jl:306-313
  column selection         readin_functions.jl:254-256
  deflation                readin_functions.jl:40-76
  monthly->quarterly       readin_functions.jl:83-102
  stationarity transforms  readin_functions.jl:104-125
  outlier adjustment       readin_functions.jl:126-198
  merge + catcode sort     readin_functions.jl:355-367
  detrending               readin_functions.jl:317-348

The ingest runs once per dataset and is not performance critical; it stays in
float64 NumPy for bit-stable parity with the reference outputs.
"""

from __future__ import annotations

import datetime
import os
from dataclasses import dataclass, field
from typing import NamedTuple, Sequence

import numpy as np

from . import xlsx

__all__ = [
    "MonthlyData",
    "QuarterlyData",
    "BiWeight",
    "Mean",
    "NoDetrend",
    "Dataset",
    "readin_data",
    "default_data_path",
]


def default_data_path() -> str:
    """Locate hom_fac_1.xlsx: $DFM_XLSX_PATH, repo data/, then the reference."""
    env = os.environ.get("DFM_XLSX_PATH")
    if env:
        return env
    here = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    for cand in (
        os.path.join(here, "data", "hom_fac_1.xlsx"),
        "/root/reference/data/hom_fac_1.xlsx",
    ):
        if os.path.exists(cand):
            return cand
    raise FileNotFoundError(
        "hom_fac_1.xlsx not found; set DFM_XLSX_PATH or place it in data/"
    )


# ---------------------------------------------------------------------------
# frequency / detrend configuration (reference: readin_functions.jl:7-36)
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class _Frequency:
    nobs: int
    ns: int

    @classmethod
    def from_range(cls, initvec: Sequence[int], lastvec: Sequence[int], ns: int):
        ppy = cls.PERIODS_PER_YEAR
        nobs = ppy * (lastvec[0] - initvec[0] - 1) + lastvec[1] + (ppy - initvec[1] + 1)
        return cls(nobs, ns)


class MonthlyData(_Frequency):
    PERIODS_PER_YEAR = 12
    SHEET = "Monthly"
    NDESC = 2
    NCODES = 6  # agg, t, def, outlier, include, cat


class QuarterlyData(_Frequency):
    PERIODS_PER_YEAR = 4
    SHEET = "Quarterly"
    NDESC = 2
    NCODES = 5  # t, def, outlier, include, cat (no aggcode)


@dataclass(frozen=True)
class BiWeight:
    weight: float = 100.0


@dataclass(frozen=True)
class Mean:
    pass


@dataclass(frozen=True)
class NoDetrend:
    pass


class Dataset(NamedTuple):
    """The 10-field dataset namedtuple (reference: readin_functions.jl:371-380)."""

    bpdata_raw: np.ndarray
    bpcatcode: np.ndarray
    bpdata: np.ndarray
    bpdata_unfiltered: np.ndarray
    bpdata_noa: np.ndarray
    bpdata_trend: np.ndarray
    inclcode: np.ndarray
    bpnamevec: list
    calvec: np.ndarray
    calds: list


@dataclass
class _SheetData:
    data: np.ndarray  # quarterly, transformed, outlier-adjusted
    raw: np.ndarray  # quarterly, pre-transform
    noa: np.ndarray  # quarterly, transformed, no outlier adjustment
    dates: list  # list of (year, quarter)
    catcode: np.ndarray
    inclcode: np.ndarray
    names: list


# ---------------------------------------------------------------------------
# transforms (reference: readin_functions.jl:104-125)
# ---------------------------------------------------------------------------


def _transform(x: np.ndarray, tcode: int) -> np.ndarray:
    if tcode == 1:
        return x
    if tcode == 2:
        out = np.full_like(x, np.nan)
        out[1:] = x[1:] - x[:-1]
        return out
    if tcode == 3:
        out = np.full_like(x, np.nan)
        out[2:] = x[2:] - 2 * x[1:-1] + x[:-2]
        return out
    if tcode == 4:
        with np.errstate(invalid="ignore", divide="ignore"):
            return np.log(x)
    if tcode == 5:
        with np.errstate(invalid="ignore", divide="ignore"):
            return _transform(np.log(x), 2)
    if tcode == 6:
        with np.errstate(invalid="ignore", divide="ignore"):
            return _transform(np.log(x), 3)
    raise ValueError(f"unknown tcode {tcode}")


# ---------------------------------------------------------------------------
# outlier adjustment (reference: readin_functions.jl:126-198)
# ---------------------------------------------------------------------------

_OUTLIER_THRESHOLD = {1: 4.5, 2: 3.0}


def _adjust_outlier(x: np.ndarray, outliercode: int, io_method: int) -> None:
    """In-place outlier adjustment of one series; io_method 0-4."""
    if outliercode == 0:
        return
    thr = _OUTLIER_THRESHOLD[outliercode]
    finite = ~np.isnan(x)
    zm = np.median(x[finite])
    iqr = np.quantile(x[finite], 0.75) - np.quantile(x[finite], 0.25)
    if iqr < 1e-6:
        raise ValueError("error in adjusting outlier: IQR too small")
    with np.errstate(invalid="ignore"):
        i_outlier = np.abs(x - zm) > thr * iqr
    i_outlier &= finite
    if io_method == 0:
        x[i_outlier] = np.nan
    elif io_method == 1:
        sign = np.sign(x[i_outlier])
        x[i_outlier] = zm + sign * thr * iqr
    elif io_method == 2:
        x[i_outlier] = zm
    elif io_method == 3:
        for i in np.flatnonzero(i_outlier):
            lo, hi = max(0, i - 3), min(len(x), i + 4)
            x[i] = np.nanmedian(x[lo:hi])
    elif io_method == 4:
        # one-sided median of the 5 preceding obs (window includes x[i]);
        # replacements are sequential and feed later windows, matching the
        # reference's in-place loop.
        for i in np.flatnonzero(i_outlier):
            lo = max(0, i - 5)
            x[i] = np.nanmedian(x[lo : i + 1])
    else:
        raise ValueError(f"unknown io_method {io_method}")


# ---------------------------------------------------------------------------
# temporal aggregation (reference: readin_functions.jl:83-102)
# ---------------------------------------------------------------------------


def _monthly_to_quarterly(data_m: np.ndarray, dates_m: list) -> tuple[np.ndarray, list]:
    quarters = [(d.year, (d.month + 2) // 3) for d in dates_m]
    uq: list = []
    for q in quarters:
        if not uq or uq[-1] != q:
            uq.append(q)
    qarr = np.empty((len(uq), data_m.shape[1]))
    quarters_arr = np.array(quarters)
    for t, q in enumerate(uq):
        rows = (quarters_arr[:, 0] == q[0]) & (quarters_arr[:, 1] == q[1])
        # plain mean: any missing month makes the quarter missing
        qarr[t] = data_m[rows].mean(axis=0)
    return qarr, uq


# ---------------------------------------------------------------------------
# detrending (reference: readin_functions.jl:317-348)
# ---------------------------------------------------------------------------


def _biweight_trend(data: np.ndarray, bandwidth: float) -> np.ndarray:
    """Per-series biweight local mean, missing-aware.

    Prefers the native banded C++ kernel (io/native.py, O(T*bandwidth*ns)
    streaming); the vectorized NumPy O(T^2) path is the fallback and the
    parity reference (tests/test_native.py)."""
    from .native import biweight_trend_native

    native = biweight_trend_native(data, bandwidth)
    if native is not None:
        return native
    T, ns = data.shape
    t_grid = np.arange(1, T + 1, dtype=float)
    dt = (t_grid[None, :] - t_grid[:, None]) / bandwidth  # [target t, source s]
    w = 15.0 / 16.0 * (1.0 - dt**2) ** 2
    w[np.abs(dt) >= 1.0] = 0.0
    mask = ~np.isnan(data)  # T x ns
    vals = np.where(mask, data, 0.0)
    num = w @ vals  # T x ns
    den = w @ mask.astype(float)
    with np.errstate(invalid="ignore", divide="ignore"):
        trend = num / den
    trend[~mask] = np.nan
    return trend


def _detrend(data: np.ndarray, method) -> tuple[np.ndarray, np.ndarray]:
    if isinstance(method, BiWeight):
        trend = _biweight_trend(data, method.weight)
        return data - trend, trend
    if isinstance(method, Mean):
        trend = np.broadcast_to(np.nanmean(data, axis=0), data.shape).copy()
        return data - trend, trend
    if isinstance(method, NoDetrend):
        return data.copy(), np.full_like(data, np.nan)
    raise TypeError(f"unknown detrend method {method!r}")


# ---------------------------------------------------------------------------
# per-sheet ingest (reference: readin_functions.jl:200-283)
# ---------------------------------------------------------------------------


def _to_float_matrix(cells: list[list[object]]) -> np.ndarray:
    out = np.full((len(cells), len(cells[0]) if cells else 0), np.nan)
    for i, row in enumerate(cells):
        for j, v in enumerate(row):
            if isinstance(v, float):
                out[i, j] = v
    return out


def _read_sheet_data(
    freq: _Frequency,
    datatype: str,
    path: str,
    correct_outlier: bool = True,
    io_method: int = 4,
    cat_include: Sequence[int] = (1, 2, 3, 5),
    keep_monthly: bool = False,
) -> _SheetData:
    grid = xlsx.read_sheet(path, freq.SHEET)
    nheader = 1 + freq.NDESC + freq.NCODES
    ns_sheet = freq.ns
    header_rows = [r[1 : ns_sheet + 1] for r in grid[:nheader]]
    data_rows = [r[1 : ns_sheet + 1] for r in grid[nheader : nheader + freq.nobs]]
    date_cells = [r[0] for r in grid[nheader : nheader + freq.nobs]]
    dates = [
        d if isinstance(d, datetime.date) else xlsx.serial_to_date(d)
        for d in date_cells
    ]

    names = [str(v).upper() for v in header_rows[0]]
    lab_long = [str(v) for v in header_rows[1]]
    lab_short = [str(v) for v in header_rows[2]]
    code_rows = header_rows[3:]
    if isinstance(freq, MonthlyData):
        aggcode = np.array([int(v) for v in code_rows[0]])  # noqa: F841 (schema)
        code_rows = code_rows[1:]
    tcode = np.array([int(v) for v in code_rows[0]])
    defcode = np.array([int(v) for v in code_rows[1]])
    outliercode = np.array([int(v) for v in code_rows[2]])
    includecode = np.array([int(v) for v in code_rows[3]])
    catcode = np.array([float(v) for v in code_rows[4]])

    datamat = _to_float_matrix(data_rows)  # nobs x ns_sheet, NaN = missing

    # deflators from the full (unselected) sheet
    if isinstance(freq, MonthlyData):
        price_def = datamat[:, names.index("PCEPI")].copy()
        price_def_lfe = datamat[:, names.index("PCEPILFE")].copy()
        price_def_pgdp = None
        # standardize Killian activity index (z-score, sample std)
        j = names.index("GLOBAL_ACT")
        col = datamat[:, j]
        m = ~np.isnan(col)
        datamat[m, j] = (col[m] - col[m].mean()) / col[m].std(ddof=1)
    else:
        price_def = datamat[:, names.index("PCECTPI")].copy()
        price_def_lfe = datamat[:, names.index("JCXFE")].copy()
        price_def_pgdp = datamat[:, names.index("GDPCTPI")].copy()

    if datatype == "Real":
        used = (includecode != 0) & np.isin(np.floor(catcode), list(cat_include))
    elif datatype == "All":
        used = includecode != 0
    else:
        raise ValueError("datatype must be 'Real' or 'All'")

    data = datamat[:, used].copy()
    sel_def = defcode[used]
    sel_tcode = tcode[used]
    sel_outlier = outliercode[used]
    sel_names = [n for n, u in zip(names, used) if u]

    deflators = {1: price_def, 2: price_def_lfe, 3: price_def_pgdp}
    for i, dc in enumerate(sel_def):
        if dc != 0:
            data[:, i] = data[:, i] / deflators[dc]

    if isinstance(freq, MonthlyData) and not keep_monthly:
        data_q, dates_q = _monthly_to_quarterly(data, dates)
    elif isinstance(freq, MonthlyData):
        # monthly-frequency output: transforms/outlier rules run at monthly
        # frequency (replaces the within-quarter averaging of
        # readin_functions.jl:83-96 for the mixed-frequency DFM path)
        data_q = data
        dates_q = [(d.year, d.month) for d in dates]
    else:
        data_q = data
        dates_q = [(d.year, (d.month + 2) // 3) for d in dates]

    raw = data_q.copy()
    for i, tc in enumerate(sel_tcode):
        data_q[:, i] = _transform(data_q[:, i], tc)
    noa = data_q.copy()
    if correct_outlier:
        for i, oc in enumerate(sel_outlier):
            _adjust_outlier(data_q[:, i], oc, io_method)

    return _SheetData(
        data=data_q,
        raw=raw,
        noa=noa,
        dates=dates_q,
        catcode=catcode[used],
        inclcode=includecode[used],
        names=sel_names,
    )


# ---------------------------------------------------------------------------
# top-level ingest (reference: readin_functions.jl:355-385)
# ---------------------------------------------------------------------------


def readin_data(
    md: MonthlyData,
    qd: QuarterlyData,
    detrend_method=BiWeight(100.0),
    datatype: str = "Real",
    path: str | None = None,
) -> Dataset:
    path = path or default_data_path()
    m = _read_sheet_data(md, datatype, path)
    q = _read_sheet_data(qd, datatype, path)

    if m.dates != q.dates:
        raise ValueError("inconsistent sample size for monthly and quarterly data")

    catcode = np.concatenate([m.catcode, q.catcode])
    order = np.argsort(catcode, kind="stable")
    bpdata = np.hstack([m.data, q.data])[:, order]
    bpdata_unfiltered = bpdata.copy()
    bpdata, trend = _detrend(bpdata, detrend_method)

    names = m.names + q.names
    calds = q.dates
    return Dataset(
        bpdata_raw=np.hstack([m.raw, q.raw])[:, order],
        bpcatcode=catcode[order],
        bpdata=bpdata,
        bpdata_unfiltered=bpdata_unfiltered,
        bpdata_noa=np.hstack([m.noa, q.noa])[:, order],
        bpdata_trend=trend,
        inclcode=np.concatenate([m.inclcode, q.inclcode])[order],
        bpnamevec=[names[i] for i in order],
        calvec=np.array([y + (qq - 1) / 4 for y, qq in calds]),
        calds=calds,
    )


def find_row_number(date: tuple[int, int], calds: list) -> int:
    """0-based row index of (year, quarter) in the quarterly calendar."""
    return calds.index(tuple(date))


class MonthlyDataset(NamedTuple):
    """Monthly-frequency panel for the mixed-frequency (nowcasting) DFM.

    Monthly series carry transformed values every month; quarterly series
    carry their (quarterly-transformed) value in the quarter's LAST month
    and NaN elsewhere — the Mariano-Murasawa placement
    `models.mixed_freq.estimate_mixed_freq_dfm` expects.
    """

    data: np.ndarray  # (T_months, N) transformed panel
    is_quarterly: np.ndarray  # (N,) bool
    catcode: np.ndarray
    inclcode: np.ndarray
    names: list
    calmds: list  # list of (year, month)
    calvec: np.ndarray  # year + (month-1)/12


def readin_data_monthly(
    md: MonthlyData,
    qd: QuarterlyData,
    datatype: str = "All",
    path: str | None = None,
) -> MonthlyDataset:
    """Monthly-frequency counterpart of `readin_data` (VERDICT r1 item 6).

    Where `readin_data` aggregates monthly series to quarterly means
    (readin_functions.jl:83-96), this keeps the monthly sheet at monthly
    frequency — deflation, tcode transforms, and outlier adjustment all run
    on monthly observations — and scatters each quarterly series to its
    quarter-end month, producing the panel the mixed-frequency DFM
    consumes on real Stock-Watson data.
    """
    path = path or default_data_path()
    m = _read_sheet_data(md, datatype, path, keep_monthly=True)
    q = _read_sheet_data(qd, datatype, path)

    T_m = len(m.dates)
    month_index = {d: i for i, d in enumerate(m.dates)}
    q_monthly = np.full((T_m, q.data.shape[1]), np.nan)
    for qi, (year, quarter) in enumerate(q.dates):
        row = month_index.get((year, 3 * quarter))
        if row is not None:
            q_monthly[row] = q.data[qi]

    catcode = np.concatenate([m.catcode, q.catcode])
    order = np.argsort(catcode, kind="stable")
    data = np.hstack([m.data, q_monthly])[:, order]
    is_q = np.concatenate(
        [np.zeros(m.data.shape[1], bool), np.ones(q.data.shape[1], bool)]
    )[order]
    names = m.names + q.names
    return MonthlyDataset(
        data=data,
        is_quarterly=is_q,
        catcode=catcode[order],
        inclcode=np.concatenate([m.inclcode, q.inclcode])[order],
        names=[names[i] for i in order],
        calmds=list(m.dates),
        calvec=np.array([y + (mm - 1) / 12 for y, mm in m.dates]),
    )
