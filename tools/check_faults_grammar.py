#!/usr/bin/env python
"""Audit the DFM_FAULTS grammar against its docs and its drills.

The fault grammar (utils/faults.py `_KINDS`) is a contract: every kind
the injector implements is a failure mode some guard layer claims to
survive.  A kind that exists in code but not in docs/robustness.md's
grammar table is an undocumented chaos axis; a kind no test references
is an unproven claim.  This checker enforces both edges:

* every kind in ``faults._KINDS`` must appear in docs/robustness.md as
  a grammar row (the ``<kind>@`` site-suffix form the table uses);
* every kind must be referenced by at least one file under tests/ —
  an `inject("<kind>@...")` drill, a DFM_FAULTS env spec, or a
  site-probe assertion all count (plain substring, the honest floor).

Run with no arguments from anywhere in the repo; pass ``--repo PATH``
to audit another checkout.  Exit 0 clean, 1 on violations, 2 when the
inputs themselves are unreadable.  tests/test_faults_grammar.py runs
this in tier-1 (the check_bench_honesty pattern), so adding a fault
kind without its doc row and drill fails CI.
"""

from __future__ import annotations

import os
import re
import sys

__all__ = ["audit_kinds", "audit_repo", "main"]


def audit_kinds(kinds, docs_text: str, test_texts: dict) -> list:
    """Violations for `kinds` given the docs text and a mapping of
    test-file name -> contents: ``(kind, message)`` rows."""
    out = []
    for kind in kinds:
        if not re.search(rf"\b{re.escape(kind)}@", docs_text):
            out.append((
                kind,
                "not documented: no '%s@' grammar row in "
                "docs/robustness.md" % kind,
            ))
        if not any(kind in text for text in test_texts.values()):
            out.append((
                kind,
                "not drilled: no file under tests/ references '%s'" % kind,
            ))
    return out


def audit_repo(repo: str) -> list:
    sys.path.insert(0, repo)
    try:
        from dynamic_factor_models_tpu.utils import faults
    finally:
        sys.path.pop(0)
    docs_path = os.path.join(repo, "docs", "robustness.md")
    with open(docs_path) as fh:
        docs_text = fh.read()
    tests_dir = os.path.join(repo, "tests")
    test_texts = {}
    for name in sorted(os.listdir(tests_dir)):
        if not name.endswith(".py"):
            continue
        with open(os.path.join(tests_dir, name)) as fh:
            test_texts[name] = fh.read()
    if not test_texts:
        raise OSError(f"no test files under {tests_dir}")
    return audit_kinds(faults._KINDS, docs_text, test_texts)


def main(argv=None) -> int:
    args = list(sys.argv[1:] if argv is None else argv)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if args[:1] == ["--repo"]:
        if len(args) < 2:
            print("check_faults_grammar: --repo needs a path",
                  file=sys.stderr)
            return 2
        repo = args[1]
    elif args:
        print(f"check_faults_grammar: unknown arguments {args}",
              file=sys.stderr)
        return 2
    try:
        violations = audit_repo(repo)
    except (OSError, ImportError) as e:
        print(f"check_faults_grammar: cannot audit {repo}: {e}",
              file=sys.stderr)
        return 2
    for kind, msg in violations:
        print(f"{kind}: {msg}")
    if violations:
        print(f"check_faults_grammar: {len(violations)} violation(s)",
              file=sys.stderr)
        return 1
    print("check_faults_grammar: all fault kinds documented and drilled")
    return 0


if __name__ == "__main__":
    sys.exit(main())
