#!/usr/bin/env bash
# TPU tunnel watcher: probe every PROBE_INTERVAL_S seconds; the moment the
# tunnel answers, run `bench.py --run-tpu-remainder` (the TPU sections the
# salvaged 2026-07-31 live record is missing).  Every completed section is
# folded into docs/TPU_EVIDENCE.json by the bench child itself, so a wedge
# mid-remainder still keeps whatever finished.  Exit 0: remainder fully
# complete.  Exit 1: complete but the device-parity gate FAILED (surfaced,
# not swallowed).  Any other child rc: incomplete window — keep probing.
set -u
cd "$(dirname "$0")/.."
LOG=docs/tpu_probe_r05.log
INTERVAL="${PROBE_INTERVAL_S:-300}"
# every estimation call inside the live window leaves a RunRecord in the
# evidence dir (env is inherited by every bench child process); the
# summarize digest is appended to $LOG after the remainder completes
export DFM_TELEMETRY="${DFM_TELEMETRY:-docs/telemetry_live_r05.jsonl}"

# stage the CPU parity leg whenever it is missing or its code rev has
# drifted (edits to any hashed source invalidate it) so none of the scarce
# live window is spent on host-only work; freshness is bench.py's own rule
# (`--parity-staged-fresh`, one lazy npz member read, no jax import).  A
# rev that failed to stage is remembered and not retried until the
# sources change — a persistently failing stage must not starve the probe
# loop this watcher exists for.
LAST_FAILED_STAGE_REV=""
stage_if_stale() {
  if python bench.py --parity-staged-fresh 2>/dev/null \
     && python bench.py --refscale-staged-fresh 2>/dev/null; then
    return 0
  fi
  local rev
  rev=$(python -c "
import importlib.util
spec = importlib.util.spec_from_file_location('bench', 'bench.py')
b = importlib.util.module_from_spec(spec); spec.loader.exec_module(b)
print(b._parity_code_rev())" 2>/dev/null)
  if [ -n "$rev" ] && [ "$rev" = "$LAST_FAILED_STAGE_REV" ]; then
    return 0  # already failed on this exact code rev; don't retry
  fi
  local fails=""
  python bench.py --parity-staged-fresh 2>/dev/null \
    || python bench.py --stage-parity >> /tmp/tpu_watch_stage.log 2>&1 \
    || fails="$fails parity"
  python bench.py --refscale-staged-fresh 2>/dev/null \
    || python bench.py --stage-refscale >> /tmp/tpu_watch_stage.log 2>&1 \
    || fails="$fails refscale"
  if [ -z "$fails" ]; then
    echo "$(date -u +%FT%TZ) watcher: CPU legs (parity+refscale) (re)staged" >> "$LOG"
  else
    LAST_FAILED_STAGE_REV="$rev"
    echo "$(date -u +%FT%TZ) watcher: STAGING FAILED for:$fails (see /tmp/tpu_watch_stage.log) — not retrying until sources change" >> "$LOG"
  fi
}

while true; do
  stage_if_stale
  # compute probe, not just enumeration: a wedged tunnel can answer
  # jax.devices() and still hang on the first executable
  if timeout -k 10 90 python -c "
import jax, jax.numpy as jnp
assert jax.devices()[0].platform in ('tpu', 'axon')
jax.block_until_ready(jnp.ones(8).sum())
" >/dev/null 2>&1; then
    echo "$(date -u +%FT%TZ) watcher probe LIVE — warming compile cache, then bench.py --run-tpu-remainder" >> "$LOG"
    # warm the persistent compile cache for the BASELINE bucket FIRST:
    # every later section then loads executables instead of spending the
    # scarce live window inside XLA.  Best-effort — a wedge here must not
    # eat the window (short timeout, rc ignored).
    timeout -k 10 600 python bench.py --warm-cache \
      > /tmp/tpu_warm_cache.out 2> /tmp/tpu_warm_cache.err
    echo "$(date -u +%FT%TZ) watcher warm-cache rc=$? (log /tmp/tpu_warm_cache.out)" >> "$LOG"
    # one injected-preemption resume per live window: kill a small
    # checkpointed EM run after its 2nd chunk save, resume it on real
    # hardware, and log the recovery digest (resume must be
    # bit-identical).  Best-effort chaos drill — short timeout, rc logged
    # but never allowed to eat the window.
    timeout -k 10 300 python bench.py --chaos-preempt-drill \
      > /tmp/tpu_chaos_preempt.json 2> /tmp/tpu_chaos_preempt.err
    echo "$(date -u +%FT%TZ) watcher preempt-resume drill rc=$? $(tail -n 1 /tmp/tpu_chaos_preempt.json 2>/dev/null)" >> "$LOG"
    DFM_BENCH_PARTIAL=/tmp/tpu_remainder_partial.json \
      timeout -k 30 5400 python bench.py --run-tpu-remainder \
      > /tmp/tpu_remainder.out 2> /tmp/tpu_remainder.err
    rc=$?
    echo "$(date -u +%FT%TZ) watcher remainder rc=$rc (logs /tmp/tpu_remainder.{out,err})" >> "$LOG"
    if [ -s "$DFM_TELEMETRY" ]; then
      echo "$(date -u +%FT%TZ) watcher telemetry digest ($DFM_TELEMETRY):" >> "$LOG"
      python -m dynamic_factor_models_tpu.telemetry summarize "$DFM_TELEMETRY" 2>/dev/null \
        | tail -n 40 >> "$LOG"
    fi
    if [ "$rc" -eq 0 ]; then
      echo "$(date -u +%FT%TZ) watcher remainder COMPLETE — docs/TPU_EVIDENCE.json has every TPU field" >> "$LOG"
      exit 0
    elif [ "$rc" -eq 1 ]; then
      echo "$(date -u +%FT%TZ) watcher remainder COMPLETE BUT DEVICE PARITY FAILED — inspect /tmp/tpu_remainder.out" >> "$LOG"
      exit 1
    fi
  else
    echo "$(date -u +%FT%TZ) watcher probe WEDGED" >> "$LOG"
  fi
  sleep "$INTERVAL"
done
