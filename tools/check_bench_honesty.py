#!/usr/bin/env python
"""Audit docs/BENCH_*.json perf records for MFU/FLOP provenance labels.

ROADMAP item 5's honesty contract: a FLOP/s figure computed off-TPU
divides a FLOPs *model* by wall-clock (a proxy, not a hardware counter),
and an MFU percentage is meaningless without naming the peak it is
normalized by.  Every benchmark record that carries flop-derived values
must therefore say so explicitly:

* any JSON object with a flop-derived value key (``*_flops_per_sec``,
  ``*_flops_measured``, ``*_flop_reduction_*``, ``flop_partition_*``,
  ...) must carry ``flop_proxy`` in SELF-OR-ANCESTOR scope — a record
  may label once at the root for all of its nested fragments
  (BENCH_time_parallel.json does);
* any object with an MFU value key (``*_mfu_*``) must carry
  ``mfu_peak_source`` in self-or-ancestor scope;
* any object with a SPEEDUP claim key (``*speedup*``, e.g. the
  ``prefill`` fault-in A/B's ``speedup_p50_x`` or large-N's
  ``em_ar_collapse_speedup_*``) must carry ``flop_proxy`` in
  self-or-ancestor scope — a wall-clock ratio measured off-TPU is a
  CPU proxy for the accelerator claim, not a hardware measurement.

Run with no arguments from anywhere in the repo (globs docs/BENCH_*.json
next to this file's parent), or pass explicit paths.  Exit 0 clean,
1 on violations, 2 on unreadable input.  tests/test_bench_honesty.py
runs this over the committed records in tier-1.
"""

from __future__ import annotations

import glob
import json
import os
import sys

__all__ = ["audit_obj", "audit_file", "main"]

_LABELS = ("flop_proxy", "mfu_peak_source")


def _is_flop_value_key(key: str) -> bool:
    k = key.lower()
    if k in _LABELS or k == "mfu_peak_flops":
        return False
    return "flops" in k or "flop_" in k


def _is_mfu_value_key(key: str) -> bool:
    k = key.lower()
    return "mfu" in k and k != "mfu_peak_source"


def _is_speedup_value_key(key: str) -> bool:
    return "speedup" in key.lower()


def audit_obj(obj, path: str = "$", scope: frozenset = frozenset()) -> list:
    """Violations in one parsed JSON value: ``(json_path, message)``
    rows.  `scope` carries the label keys visible from ancestors."""
    out = []
    if isinstance(obj, dict):
        here = scope | {lbl for lbl in _LABELS if lbl in obj}
        flop_keys = sorted(k for k in obj if _is_flop_value_key(k))
        mfu_keys = sorted(k for k in obj if _is_mfu_value_key(k))
        speedup_keys = sorted(k for k in obj if _is_speedup_value_key(k))
        if speedup_keys and "flop_proxy" not in here:
            out.append((
                path,
                "speedup claims %s lack a flop_proxy label in "
                "self-or-ancestor scope" % speedup_keys,
            ))
        if flop_keys and "flop_proxy" not in here:
            out.append((
                path,
                "flop-derived fields %s lack a flop_proxy label in "
                "self-or-ancestor scope" % flop_keys,
            ))
        if mfu_keys and "mfu_peak_source" not in here:
            out.append((
                path,
                "MFU fields %s lack an mfu_peak_source label in "
                "self-or-ancestor scope" % mfu_keys,
            ))
        for k, v in obj.items():
            out.extend(audit_obj(v, f"{path}.{k}", here))
    elif isinstance(obj, list):
        for i, v in enumerate(obj):
            out.extend(audit_obj(v, f"{path}[{i}]", scope))
    return out


def audit_file(path: str) -> list:
    with open(path) as fh:
        return audit_obj(json.load(fh))


def main(argv=None) -> int:
    args = list(sys.argv[1:] if argv is None else argv)
    if not args:
        docs = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "docs",
        )
        args = sorted(glob.glob(os.path.join(docs, "BENCH_*.json")))
    if not args:
        print("check_bench_honesty: no BENCH_*.json records found",
              file=sys.stderr)
        return 2
    bad = 0
    for path in args:
        try:
            violations = audit_file(path)
        except (OSError, ValueError) as e:
            print(f"check_bench_honesty: cannot read {path}: {e}",
                  file=sys.stderr)
            return 2
        for where, msg in violations:
            print(f"{path}: {where}: {msg}")
            bad += 1
    if bad:
        print(f"check_bench_honesty: {bad} violation(s)", file=sys.stderr)
        return 1
    print(f"check_bench_honesty: {len(args)} record(s) clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
