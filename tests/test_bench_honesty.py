"""Bench-record honesty labels (ROADMAP item 5 via PR 17):
`tools/check_bench_honesty.py` audits every committed
``docs/BENCH_*.json`` for the `flop_proxy` / `mfu_peak_source`
provenance labels — off-TPU FLOP/s figures divide a flop *model* by
wall-clock, and an MFU% is meaningless without naming its peak."""

import glob
import importlib.util
import os

import pytest

pytestmark = pytest.mark.obs

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _checker():
    spec = importlib.util.spec_from_file_location(
        "check_bench_honesty",
        os.path.join(_REPO, "tools", "check_bench_honesty.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_committed_bench_records_are_labeled(capsys):
    chk = _checker()
    paths = sorted(
        glob.glob(os.path.join(_REPO, "docs", "BENCH_*.json"))
    )
    assert paths, "no committed BENCH records found"
    assert chk.main(paths) == 0, capsys.readouterr().out


def test_default_glob_finds_committed_records():
    assert _checker().main([]) == 0


def test_unlabeled_flop_value_is_a_violation():
    chk = _checker()
    bad = chk.audit_obj({"gram_flops_per_sec": 1.0e12})
    assert bad and "flop_proxy" in bad[0][1]
    bad = chk.audit_obj({"als_mfu_pct": 3.2})
    assert bad and "mfu_peak_source" in bad[0][1]


def test_ancestor_scope_labels_nested_fragments():
    chk = _checker()
    rec = {
        "flop_proxy": True,
        "mfu_peak_source": "measured_f32_gemm",
        "legs": [
            {"gram_flops_per_sec": 1.0e12, "als_mfu_pct": 3.2},
            {"nested": {"flop_reduction_ratio": 12.0}},
        ],
    }
    assert chk.audit_obj(rec) == []
    # the labels themselves and the peak value are not flop VALUES
    assert chk.audit_obj(
        {"flop_proxy": True, "mfu_peak_source": "x",
         "mfu_peak_flops": 1.97e14}
    ) == []


def test_unlabeled_speedup_claim_is_a_violation():
    # PR 20's prefill fault-in A/B (and every earlier *_speedup_* row)
    # is a wall-clock ratio: off-TPU it must carry flop_proxy
    chk = _checker()
    bad = chk.audit_obj({"speedup_p50_x": 11.0})
    assert bad and "speedup" in bad[0][1] and "flop_proxy" in bad[0][1]
    assert chk.audit_obj(
        {"flop_proxy": True, "speedup_p50_x": 11.0}
    ) == []
    # ancestor scope covers the nested prefill record shape
    rec = {
        "flop_proxy": True,
        "prefill": {
            "speedup_p50_x": 11.0,
            "before": {"p50_ms": 152.0},
            "after": {"p50_ms": 14.0},
        },
    }
    assert chk.audit_obj(rec) == []
    del rec["flop_proxy"]
    assert [w for w, _ in chk.audit_obj(rec)] == ["$.prefill"]


def test_sibling_scope_does_not_leak():
    chk = _checker()
    rec = {
        "labeled": {"flop_proxy": True, "a_flops_measured": 1.0},
        "unlabeled": {"b_flops_measured": 2.0},
    }
    bad = chk.audit_obj(rec)
    assert len(bad) == 1 and bad[0][0] == "$.unlabeled"


def test_unreadable_input_exits_2(tmp_path, capsys):
    chk = _checker()
    p = tmp_path / "BENCH_broken.json"
    p.write_text("{not json")
    assert chk.main([str(p)]) == 2
    assert chk.main([str(tmp_path / "missing.json")]) == 2
