"""Request-scale observability (PR 12): the HDR latency histogram's
quantile error bound against exact sorts of adversarial samples, merge
associativity and concatenation-equality, SLO burn-rate monitors under
an injected clock, deterministic request span trees across the serving
engine, breaker-transition / journal-replay counters, JSONL sink
rotation, the summarize latency columns (with the pre-PR-12 "-"
fallback), the OpenMetrics exporter, and `register_shared`'s
copy-on-append tenant cloning parity."""

import json
import math
import os

import numpy as np
import pytest

import jax.numpy as jnp

from dynamic_factor_models_tpu.models.ssm import SSMParams
from dynamic_factor_models_tpu.serving import FilterState, ServingEngine
from dynamic_factor_models_tpu.serving.resilience import CircuitBreaker
from dynamic_factor_models_tpu.utils import telemetry as T
from dynamic_factor_models_tpu.utils.histogram import (
    MIN_S,
    N_BUCKETS,
    REL_ERR,
    LatencyHistogram,
    bucket_lower,
)
from dynamic_factor_models_tpu.utils.slo import SLO, WindowedCounts

pytestmark = pytest.mark.telemetry


@pytest.fixture
def sink(tmp_path, monkeypatch):
    """Point DFM_TELEMETRY at a fresh JSONL file and clear the registry."""
    path = str(tmp_path / "runs.jsonl")
    monkeypatch.setenv("DFM_TELEMETRY", path)
    monkeypatch.delenv("DFM_PROFILE_DIR", raising=False)
    monkeypatch.delenv("DFM_TELEMETRY_MAX_MB", raising=False)
    monkeypatch.setattr(T, "_explicit_enabled", None)
    monkeypatch.setattr(T, "_explicit_sink", None)
    T.reset()
    return path


def _recs(path):
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


def _exact_quantile(samples, q):
    """Nearest-rank from a full sort — the oracle `quantile()` is judged
    against (the same definition histogram.py documents)."""
    s = np.sort(samples)
    rank = max(1, math.ceil(q * len(s)))
    return float(s[rank - 1])


def _fill(samples):
    h = LatencyHistogram()
    for v in samples:
        h.record(float(v))
    return h


_QS = (0.5, 0.9, 0.99, 0.999)


def _assert_quantiles_bounded(samples):
    h = _fill(samples)
    for q in _QS:
        exact = _exact_quantile(samples, q)
        est = h.quantile(q)
        rel = abs(est - exact) / exact
        assert rel <= REL_ERR * (1 + 1e-9), (
            f"q={q}: est {est:.6g} vs exact {exact:.6g} "
            f"(rel {rel:.4f} > bound {REL_ERR:.4f})"
        )


# ---------------------------------------------------------------------------
# 1. histogram correctness (satellite: quantile bound, merge, edge cases)
# ---------------------------------------------------------------------------


def test_quantile_bound_bimodal():
    """Two modes three decades apart — the distribution that breaks
    mean-based summaries and linear-bucket histograms."""
    rng = np.random.default_rng(0)
    fast = np.exp(rng.normal(math.log(2e-4), 0.3, size=9000))
    slow = np.exp(rng.normal(math.log(0.4), 0.2, size=1000))
    _assert_quantiles_bounded(np.concatenate([fast, slow]))


def test_quantile_bound_heavy_tail():
    """Pareto(alpha=1.2) latencies: p99.9 sits decades above p50."""
    rng = np.random.default_rng(1)
    samples = 1e-4 * (1.0 + rng.pareto(1.2, size=20_000))
    _assert_quantiles_bounded(samples)


def test_merge_is_associative_and_equals_concatenation():
    rng = np.random.default_rng(2)
    parts = [
        np.exp(rng.normal(math.log(1e-3), 1.0, size=n))
        for n in (700, 1, 2500)
    ]
    ab_c = LatencyHistogram.merged(
        [_fill(parts[0]).merge(_fill(parts[1])), _fill(parts[2])]
    )
    a_bc = _fill(parts[0]).merge(_fill(parts[1]).merge(_fill(parts[2])))
    whole = _fill(np.concatenate(parts))
    for h in (ab_c, a_bc):
        assert h.counts == whole.counts
        assert h.n == whole.n
        assert h.min_s == whole.min_s and h.max_s == whole.max_s
        assert h.sum_s == pytest.approx(whole.sum_s, rel=1e-12)
        for q in _QS:
            assert h.quantile(q) == whole.quantile(q)


def test_empty_histogram():
    h = LatencyHistogram()
    assert h.n == 0
    assert math.isnan(h.quantile(0.5))
    p = h.percentiles()
    assert p["n"] == 0 and math.isnan(p["p50_ms"])
    # merging an empty histogram is the identity
    g = _fill([1e-3]).merge(h)
    assert g.n == 1 and g.quantile(0.5) == pytest.approx(1e-3)


def test_single_sample():
    h = _fill([3.7e-3])
    # min/max clamp makes every interior quantile the exact sample
    for q in (0.0, 0.5, 0.999, 1.0):
        assert h.quantile(q) == pytest.approx(3.7e-3)
    assert h.n == 1 and h.min_s == h.max_s == pytest.approx(3.7e-3)


def test_out_of_range_clamps_min_max_exact():
    h = _fill([0.0, 1e-9, 1e7])  # below MIN_S and above the top bucket
    assert h.counts[0] == 2 and h.counts[N_BUCKETS - 1] == 1
    assert h.quantile(0.0) == 0.0      # min tracked exactly
    assert h.quantile(1.0) == 1e7      # max tracked exactly
    assert h.n == 3


def test_dict_roundtrip_is_exact():
    rng = np.random.default_rng(3)
    h = _fill(np.exp(rng.normal(math.log(5e-4), 1.5, size=4000)))
    d = json.loads(json.dumps(h.to_dict()))
    g = LatencyHistogram.from_dict(d)
    assert g.counts == h.counts
    assert (g.n, g.sum_s, g.min_s, g.max_s) == (
        h.n, h.sum_s, h.min_s, h.max_s
    )
    assert d["counts"], "sparse dict should carry only occupied buckets"
    assert len(d["counts"]) < N_BUCKETS / 2


# ---------------------------------------------------------------------------
# 2. SLO burn-rate monitors under an injected clock
# ---------------------------------------------------------------------------


def _clocked_slo(**kw):
    clk = [10_000.0]
    slo = SLO("t", clock=lambda: clk[0], **kw)
    return clk, slo


def test_slo_green_then_alert_then_recovery():
    clk, slo = _clocked_slo(
        kind="tick", threshold_s=0.1, objective=0.99
    )
    # healthy hour: everything fast
    for _ in range(600):
        slo.observe(0.01, True)
        clk[0] += 1.0
    s = slo.status()
    assert s["green"] and not s["alerting"]
    assert s["burn_fast"] == 0.0 and s["n_fast"] > 0

    # sustained bleed: every request over threshold for >5 minutes —
    # both windows hot, the multi-window rule pages
    for _ in range(600):
        slo.observe(0.5, True)
        clk[0] += 1.0
    s = slo.status()
    assert not s["green"]
    assert s["burn_fast"] > slo.alert_burn
    assert s["burn_slow"] > slo.alert_burn
    assert s["alerting"]

    # bleed stops: the fast window drains in 5 minutes and ends the
    # alert while the slow window is still hot (the promptness half of
    # the multi-window rule)
    for _ in range(400):
        slo.observe(0.01, True)
        clk[0] += 1.0
    s = slo.status()
    assert s["burn_fast"] == 0.0 and s["burn_slow"] > 1.0
    assert s["green"] and not s["alerting"]


def test_slo_failed_request_burns_budget_even_when_fast():
    clk, slo = _clocked_slo(threshold_s=1.0, objective=0.5)
    slo.observe(0.001, False)  # fast but errored
    assert slo.status()["burn_fast"] == pytest.approx(2.0)


def test_slo_empty_windows_are_not_green():
    _, slo = _clocked_slo()
    s = slo.status()
    assert not s["green"] and not s["alerting"] and s["n_fast"] == 0


def test_windowed_counts_expire():
    w = WindowedCounts(window_s=60.0, n_slots=60)
    w.record(False, now=1000.0)
    assert w.totals(now=1030.0) == (0, 1)
    assert w.totals(now=1120.0) == (0, 0)  # slot aged out of the window


def test_slo_gauges_shape():
    clk, slo = _clocked_slo()
    slo.observe(0.001, True)
    g = slo.gauges()
    assert g["slo.t.green"] == 1.0
    assert g["slo.t.alerting"] == 0.0
    assert set(g) == {
        "slo.t.burn_fast", "slo.t.burn_slow", "slo.t.green",
        "slo.t.alerting", "slo.t.objective", "slo.t.threshold_s",
    }


# ---------------------------------------------------------------------------
# 3. span trees: determinism, structure, counters
# ---------------------------------------------------------------------------


def _small_engine(store_dir=None, **kw):
    rng = np.random.default_rng(7)
    N, r = 6, 2
    lam = jnp.asarray(rng.standard_normal((N, r)))
    params = SSMParams(
        lam, jnp.ones(N), jnp.zeros((1, r, r)).at[0].set(0.5 * jnp.eye(r)),
        jnp.eye(r),
    )
    f = rng.standard_normal((30, r)) * 0.5
    x = np.asarray(f @ np.asarray(lam).T) + 0.3 * rng.standard_normal((30, N))
    eng = ServingEngine(store_dir=store_dir, max_em_iter=4, **kw)
    eng.register("acme", x, params=params)
    return eng, x


def _strip_tree(tr):
    """A span tree minus wall-clock noise: ids, names, topology, attrs."""
    return {
        "trace_id": tr["trace_id"],
        "n_spans": tr["n_spans"],
        "spans": [
            {
                "name": s["name"],
                "span_id": s["span_id"],
                "parent": s["parent"],
                "attrs": s.get("attrs"),
            }
            for s in tr["spans"]
        ],
    }


def test_trace_trees_are_deterministic(sink, tmp_path):
    """Identical request streams against fresh engines yield identical
    span trees — ids and topology, not just shapes."""
    rng = np.random.default_rng(11)
    rows = rng.standard_normal((5, 6))

    def run(tag):
        T.reset()
        eng, _ = _small_engine(store_dir=str(tmp_path / tag))
        for i, row in enumerate(rows):
            resp = eng.handle({
                "kind": "tick", "tenant": "acme", "x": row,
                "request_id": f"req-{i}",
            })
            assert resp.ok
        assert eng.handle({"kind": "nowcast", "tenant": "acme"}).ok
        return [_strip_tree(t) for t in T.traces()]

    a = run("s1")
    b = run("s2")
    assert len(a) == 6
    assert a == b
    # the trace id is the documented hash of the request id
    assert a[0]["trace_id"] == T._trace_id_from_seed("req-0")


def test_tick_span_tree_structure(sink, tmp_path):
    """A journaled tick's tree: serving.request root with the
    write-ahead journal append as a child carrying the commit index."""
    eng, _ = _small_engine(store_dir=str(tmp_path / "store"))
    resp = eng.handle({
        "kind": "tick", "tenant": "acme", "x": np.zeros(6),
        "request_id": "tick-0",
    })
    assert resp.ok
    (tr,) = T.traces()
    spans = {s["name"]: s for s in tr["spans"]}
    root = spans["serving.request"]
    assert root["parent"] is None
    assert root["attrs"]["kind"] == "tick"
    assert root["attrs"]["tenant"] == "acme"
    child = spans["tick.journal_append"]
    assert child["parent"] == root["span_id"]
    assert child["attrs"]["t"] == 30  # panel length = committed index
    # children finish (and append) before the root
    assert tr["spans"][-1] is root


def test_breaker_transitions_counted_and_traced(sink):
    br = CircuitBreaker(threshold=2, cooldown=1)
    with T.trace_span("outer", seed="breaker-test"):
        br.record_fault()
        br.record_fault()          # -> open
        assert br.state == "open"
        br.on_request()            # cooldown burnt -> half_open
        assert br.state == "half_open"
        br.record_success()        # probe succeeded -> closed
        assert br.state == "closed"
    c = T.snapshot()["counters"]
    assert c['serving.breaker.transitions{state="open"}'] == 1
    assert c['serving.breaker.transitions{state="half_open"}'] == 1
    assert c['serving.breaker.transitions{state="closed"}'] == 1
    (tr,) = T.traces()
    events = [
        s["attrs"]["state"] for s in tr["spans"]
        if s["name"] == "breaker.transition"
    ]
    assert events == ["open", "half_open", "closed"]
    assert all(
        s["parent"] is not None
        for s in tr["spans"] if s["name"] == "breaker.transition"
    )


def test_refit_bucket_span_carries_membership(sink):
    eng, _ = _small_engine()
    assert eng.handle({"kind": "refit", "tenant": "acme"}).ok
    assert eng.flush_refits().ok
    buckets = [
        s for tr in T.traces() for s in tr["spans"]
        if s["name"] == "refit.bucket"
    ]
    (b,) = buckets
    assert b["attrs"]["tenants"] == ["acme"]
    assert b["attrs"]["t_pad"] >= 30 and b["attrs"]["n_pad"] >= 6


def test_journal_replay_counter(sink, tmp_path):
    eng, _ = _small_engine(store_dir=str(tmp_path / "store"))
    for i in range(4):
        assert eng.handle(
            {"kind": "tick", "tenant": "acme", "x": np.full(6, 0.1 * i)}
        ).ok
    before = T.snapshot()["counters"].get("serving.journal.replayed_ticks", 0)
    eng2 = ServingEngine(store_dir=str(tmp_path / "store"))
    assert eng2.resume("acme")
    after = T.snapshot()["counters"]["serving.journal.replayed_ticks"]
    assert after - before == 4
    # the replayed state answers identically to the surviving engine
    a = eng.handle({"kind": "nowcast", "tenant": "acme"})
    b = eng2.handle({"kind": "nowcast", "tenant": "acme"})
    np.testing.assert_allclose(a.result, b.result, atol=1e-10)


# ---------------------------------------------------------------------------
# 4. engine histograms + SLOs on the request path
# ---------------------------------------------------------------------------


def test_engine_populates_histograms_and_slos(sink):
    slo = SLO("tick_avail", kind="tick", threshold_s=5.0, objective=0.5)
    eng, _ = _small_engine(slos=[slo])
    for i in range(20):
        assert eng.handle(
            {"kind": "tick", "tenant": "acme", "x": np.full(6, 0.01 * i)}
        ).ok
    assert eng.handle({"kind": "nowcast", "tenant": "acme"}).ok
    bad = eng.handle({"kind": "tick", "tenant": "ghost", "x": np.zeros(6)})
    assert not bad.ok

    by_key = {
        (labels["kind"], labels["outcome"]): h
        for name, labels, h in T.histograms()
        if name == "serving.request.latency"
    }
    assert by_key[("tick", "ok")].n == 20
    assert by_key[("nowcast", "ok")].n == 1
    assert by_key[("tick", "client_error")].n == 1
    assert by_key[("tick", "ok")].quantile(0.5) > 0

    # the unknown-tenant tick burns SLO budget; 1 bad / 21 total is
    # well inside a 0.5 objective
    s = slo.status()
    assert s["n_fast"] == 21 and s["green"]

    n_lines = eng.flush_metrics()
    # one snapshot line per non-empty histogram: the request-kind
    # latency series plus the PR 17 per-phase occupancy histograms
    assert n_lines == sum(1 for _, _, h in T.histograms() if h.n)
    assert n_lines > len(by_key)  # the phase histograms are in there
    hist_recs = [r for r in _recs(sink) if r["entry"] == "hist"]
    assert len(hist_recs) == n_lines
    assert T.snapshot()["gauges"]["slo.tick_avail.green"] == 1.0


def test_engine_histogram_increment_is_not_device_bound(sink):
    """`_observe` must stay O(1) host-side: 50k increments through the
    engine's accounting path complete in well under a millisecond each
    (a single device sync costs more)."""
    import time as _time

    eng, _ = _small_engine()
    t0 = _time.perf_counter()
    for _ in range(50_000):
        eng._observe("tick", "ok", 5e-4, True)
    dt = _time.perf_counter() - t0
    assert dt < 1.0, f"50k _observe calls took {dt:.3f}s"


# ---------------------------------------------------------------------------
# 5. sink rotation
# ---------------------------------------------------------------------------


def test_sink_rotates_at_size_cap(sink, monkeypatch):
    monkeypatch.setenv("DFM_TELEMETRY_MAX_MB", "0.002")  # 2000 bytes
    for i in range(40):
        T._emit_line({"entry": "x", "i": i, "pad": "z" * 120})
    assert os.path.exists(sink + ".1")
    assert T.snapshot()["counters"]["telemetry.sink_rotations"] >= 1
    # both generations hold only whole, parseable lines
    for p in (sink, sink + ".1"):
        recs = _recs(p)
        assert recs and all(r["entry"] == "x" for r in recs)
    # the live file restarted below the cap after the last rotation
    assert os.path.getsize(sink + ".1") > 2000
    T._emit_line({"entry": "x", "i": -1})
    assert _recs(sink)[-1]["i"] == -1


def test_sink_rotation_disabled_below_cap(sink, monkeypatch):
    monkeypatch.setenv("DFM_TELEMETRY_MAX_MB", "0")  # <= 0 disables
    for i in range(50):
        T._emit_line({"entry": "x", "i": i, "pad": "z" * 200})
    assert not os.path.exists(sink + ".1")
    assert len(_recs(sink)) == 50


# ---------------------------------------------------------------------------
# 6. summarize latency columns + pre-PR-12 fallback
# ---------------------------------------------------------------------------


def test_summarize_shows_latency_columns(sink):
    eng, _ = _small_engine()
    for i in range(10):
        assert eng.handle(
            {"kind": "tick", "tenant": "acme", "x": np.full(6, 0.1)}
        ).ok
    assert eng.handle({"kind": "nowcast", "tenant": "acme"}).ok
    eng.flush_metrics()
    out = T.summarize(sink)
    assert "p50_ms" in out and "p99_ms" in out
    assert "request latency by kind" in out
    assert "tick" in out and "nowcast" in out
    assert "trace tree(s)" in out


def test_summarize_pre_pr12_files_fall_back_to_dash(sink, tmp_path):
    """A sink written before histograms existed (no `hist` lines) must
    still summarize, with '-' latency columns and no per-kind table."""
    eng, _ = _small_engine()
    assert eng.handle(
        {"kind": "tick", "tenant": "acme", "x": np.zeros(6)}
    ).ok
    eng.flush_metrics()
    old = str(tmp_path / "old.jsonl")
    with open(sink) as f, open(old, "w") as g:
        for line in f:
            if json.loads(line)["entry"] not in ("hist", "trace"):
                g.write(line)
    out = T.summarize(old)
    assert "serving" in out
    assert "-" in out
    assert "request latency by kind" not in out


# ---------------------------------------------------------------------------
# 7. OpenMetrics exposition
# ---------------------------------------------------------------------------


def _parse_om_value(text, needle):
    for line in text.splitlines():
        if line.startswith(needle):
            return float(line.rsplit(" ", 1)[1])
    raise AssertionError(f"no line starting with {needle!r}:\n{text}")


def test_openmetrics_from_live_registry(sink):
    h = T.register_hist(
        "serving.request.latency", entry="serving", kind="tick",
        outcome="ok",
    )
    for v in (1e-4, 2e-4, 5e-4, 1e-3, 0.02):
        h.record(v)
    T.inc("serving.client_errors")
    T.inc('serving.breaker.transitions{state="open"}', 2)
    T.gauge_set("slo.tick.green", 1.0)
    text = T.export_openmetrics()
    assert text.endswith("# EOF\n")
    assert "# TYPE serving_request_latency_seconds histogram" in text
    assert "serving_request_latency_seconds_bucket{" in text
    assert 'le="+Inf"' in text
    # the +Inf bucket equals the sample count
    for line in text.splitlines():
        if 'le="+Inf"' in line:
            assert float(line.rsplit(" ", 1)[1]) == 5.0
    assert _parse_om_value(
        text, "serving_request_latency_seconds_count"
    ) == 5.0
    # label-suffixed registry counters come out as proper OM labels
    assert 'serving_breaker_transitions_total{state="open"} 2' in text
    assert _parse_om_value(text, "serving_client_errors_total") == 1.0
    assert _parse_om_value(text, "slo_tick_green") == 1.0
    assert 'quantile="0.99"' in text


def test_openmetrics_from_jsonl_matches_live(sink, tmp_path):
    h = T.register_hist("lat", entry="serving", kind="tick", outcome="ok")
    rng = np.random.default_rng(5)
    for v in np.exp(rng.normal(math.log(1e-3), 1.0, size=500)):
        h.record(float(v))
    T.emit_histograms()
    # cumulative snapshots: a SECOND emit must not double the export
    for v in (0.01, 0.02):
        h.record(v)
    T.emit_histograms()
    live = T.export_openmetrics()
    from_file = T.export_openmetrics(sink)
    def bucket_lines(text):
        return sorted(
            ln for ln in text.splitlines() if "lat_seconds_bucket{" in ln
        )
    assert bucket_lines(live) == bucket_lines(from_file)
    assert _parse_om_value(from_file, "lat_seconds_count") == 502.0
    assert from_file.endswith("# EOF\n")


def test_openmetrics_cli_writes_file(sink, tmp_path, capsys):
    h = T.register_hist("lat", entry="serving", kind="tick", outcome="ok")
    h.record(1e-3)
    T.emit_histograms()
    out_path = str(tmp_path / "metrics.om")
    rc = T.main(["export", sink, "-o", out_path])
    assert rc == 0
    with open(out_path) as f:
        text = f.read()
    assert text.endswith("# EOF\n") and "lat_seconds_bucket{" in text


# ---------------------------------------------------------------------------
# 8. register_shared: clone parity + copy-on-append isolation
# ---------------------------------------------------------------------------


def test_register_shared_matches_fresh_register():
    rng = np.random.default_rng(21)
    eng, x = _small_engine()
    eng.register_shared("clone", "acme")
    ref = ServingEngine(max_em_iter=4)
    ref.register("ref", x, params=eng._tenants["acme"].params)

    rows = rng.standard_normal((6, 6))
    for row in rows:
        a = eng.handle({"kind": "tick", "tenant": "clone", "x": row})
        b = ref.handle({"kind": "tick", "tenant": "ref", "x": row})
        assert a.ok and b.ok
        np.testing.assert_allclose(
            np.asarray(a.result.s), np.asarray(b.result.s), atol=1e-12
        )
    a = eng.handle({"kind": "nowcast", "tenant": "clone", "horizon": 2})
    b = ref.handle({"kind": "nowcast", "tenant": "ref", "horizon": 2})
    np.testing.assert_allclose(a.result, b.result, atol=1e-12)


def test_register_shared_history_is_copy_on_append():
    eng, x = _small_engine()
    eng.register_shared("clone", "acme")
    src = eng._tenants["acme"]
    clone = eng._tenants["clone"]
    assert clone.hist._x is src.hist._x  # shared until first append
    n0 = src.hist.n
    assert eng.handle(
        {"kind": "tick", "tenant": "clone", "x": np.ones(6)}
    ).ok
    assert clone.hist._x is not src.hist._x  # forked on first append
    assert src.hist.n == n0 and clone.hist.n == n0 + 1
    # and the fork is two-way: source appends never reach the clone
    assert eng.handle(
        {"kind": "tick", "tenant": "acme", "x": 2 * np.ones(6)}
    ).ok
    assert clone.hist.n == n0 + 1
    np.testing.assert_array_equal(clone.hist.x[-1], np.ones(6))
