"""Exactness of the Jungbacker-Koopman observation collapse.

The collapsed filters (`ssm._filter_scan`, `ssm._sqrt_filter_scan`,
`mixed_freq._filter_mf`) must agree with their uncollapsed reference forms
to float-reorder error in f64: the collapse is an algebraic identity —
states see the panel only through C_t = H'R_t^-1 H and b_t = H'R_t^-1 x_t,
and the log-likelihood constant c_t accounts exactly for the discarded
component — not an approximation (JK 2008, Thm 1).  Tolerance 1e-10 per the
round-3 verdict's done-criterion.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from dynamic_factor_models_tpu.models.mixed_freq import (
    MixedFreqParams,
    _filter_mf,
    _obs_matrix,
    em_step_mf,
)
from dynamic_factor_models_tpu.models.ssm import (
    SSMParams,
    _collapse_obs,
    _collapse_obs_stats,
    _companion,
    _filter_scan,
    _filter_scan_full,
    _info_filter_scan,
    _psd_floor,
    _sqrt_filter_scan,
    _sqrt_filter_scan_collapsed,
    em_step,
)

TOL = 1e-10


def _dgp(rng, T=60, N=25, r=3, p=2, missing=0.3):
    """Random stable DFM panel with adversarial missing patterns: one fully
    missing period, one with fewer observed series than factors (rank-
    deficient C_t), one fully observed."""
    A1 = 0.5 * np.eye(r) + 0.1 * rng.standard_normal((r, r))
    A = np.concatenate([A1[None], 0.1 * rng.standard_normal((p - 1, r, r))])
    lam = rng.standard_normal((N, r))
    Q = np.eye(r) + 0.3 * np.ones((r, r))
    R = 0.1 + rng.random(N)
    f = np.zeros((T, r))
    e = rng.multivariate_normal(np.zeros(r), Q, size=T)
    for t in range(p, T):
        f[t] = sum(A[i] @ f[t - 1 - i] for i in range(p)) + e[t]
    x = f @ lam.T + np.sqrt(R) * rng.standard_normal((T, N))
    x[rng.random((T, N)) < missing] = np.nan
    x[7, :] = np.nan  # fully missing period
    x[12, :] = np.nan
    x[12, : r - 1] = 1.0  # n_t < r: C_t rank-deficient
    x[T - 2, :] = 0.5  # fully observed period
    params = SSMParams(
        lam=jnp.asarray(lam),
        R=jnp.asarray(R),
        A=jnp.asarray(A),
        Q=_psd_floor(jnp.asarray(Q)),
    )
    m = ~np.isnan(x)
    return params, jnp.asarray(np.nan_to_num(x)), jnp.asarray(m)


def _assert_same(res_a, res_b, tol=TOL):
    assert np.abs(res_a.loglik - res_b.loglik) <= tol * (
        1.0 + np.abs(res_b.loglik)
    )
    np.testing.assert_allclose(res_a.means, res_b.means, atol=tol)
    np.testing.assert_allclose(res_a.covs, res_b.covs, atol=tol)
    np.testing.assert_allclose(res_a.pred_means, res_b.pred_means, atol=tol)
    np.testing.assert_allclose(res_a.pred_covs, res_b.pred_covs, atol=tol)


def test_info_filter_collapse_exact(rng):
    params, x, m = _dgp(rng)
    _assert_same(_filter_scan(params, x, m), _filter_scan_full(params, x, m))


def test_info_filter_collapse_exact_qdiag(rng):
    params, x, m = _dgp(rng)
    qdiag = jnp.asarray(0.5 + rng.random((x.shape[0], params.r)))
    _assert_same(
        _filter_scan(params, x, m, qdiag),
        _filter_scan_full(params, x, m, qdiag),
    )


def test_sqrt_filter_collapse_exact(rng):
    params, x, m = _dgp(rng)
    _assert_same(
        _sqrt_filter_scan_collapsed(params, x, m),
        _sqrt_filter_scan(params, x, m),
    )


def test_sqrt_collapsed_matches_sequential(rng):
    """Cross-method: the collapsed sqrt and collapsed information filters
    are different algorithms for the same model — f64 agreement to 1e-9."""
    params, x, m = _dgp(rng)
    _assert_same(
        _sqrt_filter_scan_collapsed(params, x, m),
        _filter_scan(params, x, m),
        1e-9,
    )


def test_em_step_unchanged_by_collapse(rng):
    """One EM iteration through the collapsed E-step reproduces the
    uncollapsed iteration's M-step output exactly (same smoothed moments)."""
    from dynamic_factor_models_tpu.models.ssm import _em_m_step, _smoother_scan

    params, x, m = _dgp(rng)
    new_c, ll_c = em_step(params, x, m)
    pf = params._replace(Q=_psd_floor(params.Q))
    filt = _filter_scan_full(pf, x, m)
    s_sm, P_sm, lag1 = _smoother_scan(pf, filt)
    new_f = _em_m_step(pf, x, m.astype(x.dtype), s_sm, P_sm, lag1)
    assert np.abs(ll_c - filt.loglik) <= TOL * (1.0 + np.abs(filt.loglik))
    for a, b in zip(new_c, new_f):
        np.testing.assert_allclose(a, b, atol=1e-9)


def test_em_step_stats_exact(rng):
    """The PanelStats-threaded iteration (production estimate_dfm_em path)
    reproduces em_step exactly: same params, same log-likelihood — the
    GEMM-orientation changes and the separated x'R^-1x quadratic are pure
    reassociations."""
    from dynamic_factor_models_tpu.models.ssm import (
        compute_panel_stats,
        em_step_stats,
    )

    params, x, m = _dgp(rng)
    stats = compute_panel_stats(x, m)
    new_a, ll_a = em_step(params, x, m)
    new_b, ll_b = em_step_stats(params, x, m, stats)
    assert np.abs(ll_a - ll_b) <= TOL * (1.0 + np.abs(ll_a))
    for a, b in zip(new_a, new_b):
        np.testing.assert_allclose(a, b, atol=1e-9)


def test_em_step_sqrt_collapsed_exact(rng):
    """The collapsed-sqrt EM iteration matches the sequential one in f64
    (same smoothed moments feed the same M-step), and the public
    kalman_filter routes method="sqrt_collapsed"."""
    from dynamic_factor_models_tpu.models.ssm import (
        em_step_sqrt_collapsed,
        kalman_filter,
    )

    params, x, m = _dgp(rng)
    new_a, ll_a = em_step(params, x, m)
    new_b, ll_b = em_step_sqrt_collapsed(params, x, m)
    assert np.abs(ll_a - ll_b) <= 1e-9 * (1.0 + np.abs(ll_a))
    for a, b in zip(new_a, new_b):
        np.testing.assert_allclose(a, b, atol=1e-8)
    xn = jnp.where(m, x, jnp.nan)
    res = kalman_filter(params, xn, method="sqrt_collapsed")
    ref = kalman_filter(params, xn)
    assert np.abs(res.loglik - ref.loglik) <= 1e-9 * (1.0 + np.abs(ref.loglik))


def _mf_dgp(rng, T=72, N=14, r=2, p=5):
    n_q = 4
    is_q = np.zeros(N, bool)
    is_q[-n_q:] = True
    lam = rng.standard_normal((N, r))
    R = 0.2 + rng.random(N)
    A = np.concatenate(
        [(0.6 * np.eye(r))[None], 0.05 * rng.standard_normal((p - 1, r, r))]
    )
    agg = np.zeros((N, 5))
    agg[~is_q, 0] = 1.0
    agg[is_q] = np.array([1.0, 2.0, 3.0, 2.0, 1.0]) / 3.0
    x = rng.standard_normal((T, N))
    x[rng.random((T, N)) < 0.2] = np.nan
    # quarterly series observed only every third month
    for j in np.nonzero(is_q)[0]:
        x[np.arange(T) % 3 != 2, j] = np.nan
    params = MixedFreqParams(
        lam=jnp.asarray(lam),
        R=jnp.asarray(R),
        A=jnp.asarray(A),
        Q=_psd_floor(jnp.asarray(np.eye(r))),
        agg=jnp.asarray(agg),
    )
    m = ~np.isnan(x)
    return params, jnp.asarray(np.nan_to_num(x)), jnp.asarray(m)


def test_mixed_freq_filter_collapse_exact(rng):
    """_filter_mf (collapsed over the 5r lag-aggregated dims) vs an inline
    uncollapsed dense-H information filter."""
    params, x, m = _mf_dgp(rng)
    Tm, Qs = _companion(
        SSMParams(params.lam, params.R, params.A, params.Q)
    )
    H = _obs_matrix(params)
    k = Tm.shape[0]
    dtype = x.dtype
    s0 = jnp.zeros(k, dtype)
    P0 = 1e2 * jnp.eye(k, dtype=dtype)

    def obs_step(inp, sp):
        xt, mt = inp
        rinv = mt / params.R
        Hr = H * rinv[:, None]
        C = H.T @ Hr
        v = xt - H @ sp
        rhs = Hr.T @ v
        return (
            C,
            rhs,
            (mt * jnp.log(params.R)).sum(),
            (rinv * v * v).sum(),
            mt.sum(),
        )

    *full_moments, full_lls = _info_filter_scan(
        Tm, Qs, (x, m.astype(dtype)), obs_step, s0, P0
    )
    full = (*full_moments, full_lls.sum())  # scan returns per-step terms
    coll = _filter_mf(params, x, m)
    for a, b in zip(coll, full):
        np.testing.assert_allclose(a, b, atol=TOL)
    # and one EM step runs/produces finite params through the collapsed path
    new_params, ll = em_step_mf(params, x, m)
    assert np.isfinite(float(ll))
    assert all(np.all(np.isfinite(np.asarray(leaf))) for leaf in new_params)


def test_mf_em_step_stats_exact(rng):
    """em_step_mf_stats (production loop path) == em_step_mf."""
    from dynamic_factor_models_tpu.models.mixed_freq import em_step_mf_stats
    from dynamic_factor_models_tpu.models.ssm import compute_panel_stats

    params, x, m = _mf_dgp(rng)
    stats = compute_panel_stats(x, m)
    new_a, ll_a = em_step_mf(params, x, m)
    new_b, ll_b = em_step_mf_stats(params, x, m, stats)
    assert np.abs(ll_a - ll_b) <= TOL * (1.0 + np.abs(ll_a))
    for a, b in zip(new_a, new_b):
        np.testing.assert_allclose(a, b, atol=1e-9)


def test_collapse_obs_all_missing_step(rng):
    """A fully-missing period collapses to the exact zero element — C = 0,
    b = 0, ld_R = 0, xRx = 0, n_obs = 0 — and the filter treats it as pure
    prediction: posterior == prior at that step, no NaN from the empty
    information matrix."""
    params, x, m = _dgp(rng, T=16, N=7, r=2, p=1, missing=0.0)
    t_gap = 5
    m = m.at[t_gap].set(False)
    x = x.at[t_gap].set(0.0)
    mf = m.astype(x.dtype)
    C, b, ld_R, xRx, n_obs = _collapse_obs(params.lam, params.R, x, mf)
    assert np.all(np.asarray(C[t_gap]) == 0.0)
    assert np.all(np.asarray(b[t_gap]) == 0.0)
    assert float(ld_R[t_gap]) == 0.0
    assert float(xRx[t_gap]) == 0.0
    assert float(n_obs[t_gap]) == 0.0
    res = _filter_scan(params, x, m)
    np.testing.assert_allclose(
        res.means[t_gap], res.pred_means[t_gap], atol=TOL
    )
    np.testing.assert_allclose(res.covs[t_gap], res.pred_covs[t_gap], atol=TOL)
    assert np.isfinite(float(res.loglik))
    _assert_same(res, _filter_scan_full(params, x, m))


def test_collapse_obs_q1_sym_pack(rng):
    """q = 1 degenerates the sym-pack to a single pair column (iu = iv = 0,
    unpack is the identity on one cell) — the packed GEMM must still
    produce the scalar C_t = sum_i m_it lam_i^2 / R_i."""
    T, N = 12, 6
    lam = jnp.asarray(rng.standard_normal((N, 1)))
    R = jnp.asarray(0.3 + rng.random(N))
    x = jnp.asarray(rng.standard_normal((T, N)))
    m = jnp.asarray((rng.random((T, N)) > 0.3).astype(x.dtype))
    C, b, ld_R, xRx, n_obs = _collapse_obs(lam, R, x * m, m)
    assert C.shape == (T, 1, 1) and b.shape == (T, 1)
    rinv = np.asarray(m) / np.asarray(R)
    l0 = np.asarray(lam[:, 0])
    np.testing.assert_allclose(
        C[:, 0, 0], (rinv * l0**2).sum(axis=1), atol=TOL
    )
    np.testing.assert_allclose(
        b[:, 0], (rinv * np.asarray(x * m) * l0).sum(axis=1), atol=TOL
    )
    np.testing.assert_allclose(
        ld_R, (np.asarray(m) * np.log(np.asarray(R))).sum(axis=1), atol=TOL
    )


def test_collapse_obs_stats_bf16_vs_f64(rng):
    """The bf16 PanelStats twins feed `_collapse_obs_stats` through the
    mixed-precision GEMM contract (bf16 operands, f32 accumulation).  C and
    b must track the f64 reference to bf16 resolution — loose RELATIVE
    agreement, not the f64 identity — and the exact fields (ld_R from the
    fused column, n_obs, ll_corr from full-precision Sxx) must not degrade
    beyond the panel quantization itself."""
    from dynamic_factor_models_tpu.models.ssm import compute_panel_stats

    params, x, m = _dgp(rng, T=48, N=31, r=3, p=1)
    stats64 = compute_panel_stats(x, m)
    stats16 = compute_panel_stats(x, m, bf16=True)
    assert stats16.m16 is not None and stats16.m16.dtype == jnp.bfloat16
    ref = _collapse_obs_stats(params.lam, params.R, x, stats64)
    got = _collapse_obs_stats(params.lam, params.R, x, stats16)
    C_r, b_r, ld_r, _, no_r, llc_r = ref
    C_g, b_g, ld_g, _, no_g, llc_g = got
    # bf16 keeps ~8 mantissa bits: elementwise agreement to ~0.4% of the
    # per-step statistic's scale (accumulation is f32, so no sum blowup)
    scale_C = np.abs(np.asarray(C_r)).max()
    scale_b = np.abs(np.asarray(b_r)).max()
    np.testing.assert_allclose(C_g, C_r, atol=0.02 * scale_C)
    np.testing.assert_allclose(b_g, b_r, atol=0.02 * scale_b)
    # the mask is 0/1-exact in bf16, so the fused log|R| column and counts
    # stay exact; ll_corr never routes through bf16 at all
    np.testing.assert_allclose(ld_g, ld_r, rtol=1e-2)
    np.testing.assert_allclose(no_g, no_r, atol=0)
    np.testing.assert_allclose(float(llc_g), float(llc_r), rtol=1e-12)


def test_collapse_obs_statistics(rng):
    """_collapse_obs agrees with the naive per-step loops."""
    params, x, m = _dgp(rng, T=20, N=9, r=2, p=1)
    mf = m.astype(x.dtype)
    C, b, ld_R, xRx, n_obs = _collapse_obs(params.lam, params.R, x, mf)
    for t in range(x.shape[0]):
        rinv = np.asarray(mf[t] / params.R)
        lam = np.asarray(params.lam)
        np.testing.assert_allclose(C[t], lam.T @ (rinv[:, None] * lam), atol=TOL)
        np.testing.assert_allclose(b[t], lam.T @ (rinv * np.asarray(x[t])), atol=TOL)
        np.testing.assert_allclose(
            ld_R[t], (np.asarray(mf[t]) * np.log(np.asarray(params.R))).sum(), atol=TOL
        )
        np.testing.assert_allclose(
            xRx[t], (rinv * np.asarray(x[t]) ** 2).sum(), atol=TOL
        )
        assert int(n_obs[t]) == int(np.asarray(mf[t]).sum())
