"""Wholesale numeric pins for Figures 1/4/5 and Table 3 (round-3 verdict
Missing #1/#2): committed fixture arrays from a verified run, compared at
1e-3 like the printed-table goldens — a shape-preserving regression in the
common-component arithmetic (`figure1`/`compute_series`/`table3`) now fails
CI instead of passing shape checks.

Fixture: data/golden_figures.npz (generated from the replication layer on
the cached Stock-Watson panels; reference outputs are the committed cells of
/root/reference/Stock_Watson.ipynb — Figure 1 cells 13-24, Figure 4 cells
41-43, Figure 5 cells 45-47, Table 3 cell 55).
"""

import os

import numpy as np
import pytest

pytestmark = pytest.mark.slow

_FIXTURE = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "data",
    "golden_figures.npz",
)
TOL = 1e-3


@pytest.fixture(scope="module")
def golden():
    return np.load(_FIXTURE)


def _close(a, b, tol=TOL):
    a, b = np.asarray(a), np.asarray(b)
    assert a.shape == b.shape
    m = np.isfinite(b)
    assert (np.isfinite(a) == m).all(), "NaN pattern changed"
    np.testing.assert_allclose(a[m], b[m], atol=tol, rtol=tol)


def test_figure1_values(dataset_real, golden):
    from dynamic_factor_models_tpu.replication.stock_watson import figure1

    out = figure1(dataset_real)
    for name in ("GDPC96", "INDPRO", "PAYEMS", "A0M057"):
        _close(out["series"][name]["actual"], golden[f"fig1_{name}_actual"])
        _close(out["series"][name]["common"], golden[f"fig1_{name}_common"])


def test_figure4_values(dataset_real, golden):
    from dynamic_factor_models_tpu.replication.stock_watson import figure4

    out = figure4(dataset_real)
    for k in ("gdp_growth", "common_r1", "common_r3", "common_r5"):
        _close(out[k], golden[f"fig4_{k}"])


def test_figure5_values(dataset_real, golden):
    from dynamic_factor_models_tpu.replication.stock_watson import figure5

    out = figure5(dataset_real)
    for k in ("full", "pre", "post"):
        # the factor is identified up to sign; align to the fixture before
        # comparing (the ALS sign convention is deterministic on one
        # platform, but the golden should not pin a BLAS artifact)
        a, b = np.asarray(out[k]), np.asarray(golden[f"fig5_{k}"])
        m = np.isfinite(a) & np.isfinite(b)
        sign = np.sign(np.dot(a[m], b[m]))
        _close(sign * a, b)


def test_table3_wholesale(dataset_all, golden):
    from dynamic_factor_models_tpu.replication.stock_watson import table3

    r2 = table3(dataset_all)
    ref = golden["table3"]
    assert r2.shape == (207, 10)
    _close(r2, ref)


def test_figure1_catches_arithmetic_regression(golden):
    """The pin has teeth: a shape-preserving 1% scale error fails."""
    bad = golden["fig1_GDPC96_common"] * 1.01
    with pytest.raises(AssertionError):
        _close(bad, golden["fig1_GDPC96_common"])
