"""Mixed-precision EM (bf16 PanelStats twins): the four panel GEMMs on
bf16 operands, f32 accumulation; bulk + exact polish phases share the
budget and land on the exact path's likelihood."""

import jax.numpy as jnp
import numpy as np
import pytest

from dynamic_factor_models_tpu.models.ssm import (
    SSMParams,
    _collapse_obs_stats,
    compute_panel_stats,
    em_step_stats,
    estimate_dfm_em,
)
from dynamic_factor_models_tpu.ops.masking import fillz, mask_of


@pytest.fixture
def rng():
    return np.random.default_rng(31)


def _panel(rng, T=140, N=24, r=2):
    f = np.zeros((T, r))
    for t in range(1, T):
        f[t] = 0.7 * f[t - 1] + rng.standard_normal(r)
    x = f @ rng.standard_normal((N, r)).T + rng.standard_normal((T, N))
    x[rng.random((T, N)) < 0.15] = np.nan
    return x


def _setup(x, rng, r=2):
    xj = jnp.asarray(x)
    m = mask_of(xj).astype(xj.dtype)
    xz = fillz(xj)
    params = SSMParams(
        lam=jnp.asarray(0.2 * rng.standard_normal((x.shape[1], r))),
        R=jnp.ones(x.shape[1]),
        A=0.5 * jnp.eye(r)[None],
        Q=jnp.eye(r),
    )
    return xz, m, params


def test_collapse_bf16_tracks_exact(rng):
    x = _panel(rng)
    xz, m, params = _setup(x, rng)
    exact = compute_panel_stats(xz, m)
    mixed = compute_panel_stats(xz, m, bf16=True)
    Ce, be, lde, _, _, lce = _collapse_obs_stats(params.lam, params.R, xz, exact)
    Cm, bm, ldm, _, _, lcm = _collapse_obs_stats(params.lam, params.R, xz, mixed)
    assert Cm.dtype == xz.dtype and bm.dtype == xz.dtype
    sC = float(jnp.abs(Ce).max())
    sb = float(jnp.abs(be).max())
    assert float(jnp.abs(Cm - Ce).max()) < 2e-2 * sC
    assert float(jnp.abs(bm - be).max()) < 2e-2 * sb
    # the scalar pieces come from exact statistics, not the bf16 twins
    assert float(jnp.abs(lcm - lce)) == 0.0
    np.testing.assert_allclose(np.asarray(ldm), np.asarray(lde), atol=2e-2)


def test_em_step_bf16_stats_near_exact(rng):
    x = _panel(rng)
    xz, m, params = _setup(x, rng)
    pe, lle = em_step_stats(params, xz, m, compute_panel_stats(xz, m))
    pm, llm = em_step_stats(params, xz, m, compute_panel_stats(xz, m, bf16=True))
    assert np.isfinite(float(llm))
    assert abs(float(llm) - float(lle)) < 1e-2 * (1 + abs(float(lle)))
    assert float(jnp.abs(pm.lam - pe.lam).max()) < 5e-2 * float(jnp.abs(pe.lam).max())


def test_estimate_dfm_em_gram_dtype(dataset_real):
    plain = estimate_dfm_em(
        dataset_real.bpdata, dataset_real.inclcode, 2, 223, max_em_iter=60,
        tol=1e-5,
    )
    mixed = estimate_dfm_em(
        dataset_real.bpdata, dataset_real.inclcode, 2, 223, max_em_iter=60,
        tol=1e-5, gram_dtype="bfloat16",
    )
    ll_p = plain.loglik_path[np.isfinite(plain.loglik_path)][-1]
    ll_m = mixed.loglik_path[np.isfinite(mixed.loglik_path)][-1]
    # the exact polish must close the bf16 gap to the exact path's level
    assert ll_m >= ll_p - 1e-3 * (1 + abs(ll_p)), (ll_m, ll_p)
    # shared budget: n_iter counts both phases and respects the cap (+1)
    assert int(mixed.n_iter) <= 61
    assert mixed.factors.shape == plain.factors.shape


def test_gram_dtype_validations(dataset_real):
    with pytest.raises(ValueError, match="gram_dtype"):
        estimate_dfm_em(
            dataset_real.bpdata, dataset_real.inclcode, 2, 223,
            max_em_iter=2, gram_dtype="float16",
        )
    with pytest.raises(ValueError, match="sequential"):
        estimate_dfm_em(
            dataset_real.bpdata, dataset_real.inclcode, 2, 223,
            max_em_iter=2, gram_dtype="bfloat16", method="sqrt",
        )
    with pytest.raises(ValueError, match="not combinable"):
        estimate_dfm_em(
            dataset_real.bpdata, dataset_real.inclcode, 2, 223,
            max_em_iter=2, gram_dtype="bfloat16",
            checkpoint_path="/tmp/never.npz",
        )


def test_accel_composes_with_gram_dtype(dataset_real):
    """accel='squarem' + gram_dtype='bfloat16': SQUAREM cycles on the
    cheap bf16 bulk map, SquaremState flowing through both phases."""
    both = estimate_dfm_em(
        dataset_real.bpdata, dataset_real.inclcode, 2, 223,
        max_em_iter=30, tol=1e-5, accel="squarem", gram_dtype="bfloat16",
    )
    plain = estimate_dfm_em(
        dataset_real.bpdata, dataset_real.inclcode, 2, 223,
        max_em_iter=30, tol=1e-5,
    )
    ll_b = both.loglik_path[np.isfinite(both.loglik_path)][-1]
    ll_p = plain.loglik_path[np.isfinite(plain.loglik_path)][-1]
    # 30 composed cycles cover >= 30 plain iterations of progress
    assert ll_b >= ll_p - 1e-3 * (1 + abs(ll_p)), (ll_b, ll_p)
    assert int(both.n_iter) <= 31
    assert np.isfinite(np.asarray(both.params.lam)).all()


def test_mixed_freq_gram_dtype():
    """estimate_mixed_freq_dfm(gram_dtype='bfloat16'): bulk + polish lands
    at the exact path's likelihood with a shared budget."""
    from dynamic_factor_models_tpu.models.mixed_freq import (
        estimate_mixed_freq_dfm,
    )

    # moderate signal-to-noise DGP (idio R ~ 1): the regime the bf16
    # bulk targets — near-perfect fits (R -> 1e-3) amplify bf16 rounding
    # by lam^2/R and are covered by the adverse-regime test below
    rng = np.random.default_rng(7)
    T, Nm, Nq = 240, 8, 3
    f = np.zeros(T)
    for t in range(1, T):
        f[t] = 0.8 * f[t - 1] + rng.standard_normal()
    x_m = np.outer(f, rng.standard_normal(Nm)) + 1.0 * rng.standard_normal((T, Nm))
    w = np.array([1.0, 2.0, 3.0, 2.0, 1.0]) / 3.0
    f_agg = np.full(T, np.nan)
    for t in range(4, T):
        f_agg[t] = w @ f[t - 4 : t + 1][::-1]
    x_q = np.full((T, Nq), np.nan)
    qe = np.arange(5, T, 3)
    lam_q = 0.8 + rng.random(Nq)
    x_q[qe] = np.outer(f_agg, lam_q)[qe] + 1.0 * rng.standard_normal((len(qe), Nq))
    x = np.hstack([x_m, x_q])
    is_q = np.array([False] * Nm + [True] * Nq)
    cap = 200
    plain = estimate_mixed_freq_dfm(x, is_q, r=1, max_em_iter=cap, tol=1e-6)
    assert int(plain.n_iter) < cap, "plain must converge for the comparison"
    mixed = estimate_mixed_freq_dfm(
        x, is_q, r=1, max_em_iter=cap, tol=1e-6, gram_dtype="bfloat16"
    )
    ll_p = plain.loglik_path[np.isfinite(plain.loglik_path)][-1]
    ll_m = mixed.loglik_path[np.isfinite(mixed.loglik_path)][-1]
    # both converged under the same tol: same maximum up to tol-level slack
    assert ll_m >= ll_p - 1e-3 * (1 + abs(ll_p)), (ll_m, ll_p)
    assert int(mixed.n_iter) <= cap + 1


def test_mixed_freq_gram_dtype_adverse_regime_stays_sane():
    """Near-perfect fits (tiny R) are the bf16 bulk's worst case: the
    result must stay finite and within the budget (+1), with the exact
    polish keeping at least half the budget — strict likelihood parity is
    not promised in this regime and the docstrings say so."""
    import sys, os
    sys.path.insert(0, os.path.dirname(__file__))
    from test_mixed_freq import _dgp

    from dynamic_factor_models_tpu.models.mixed_freq import (
        estimate_mixed_freq_dfm,
    )

    x, is_q, _f, _fa, _xl = _dgp(T=240, Nm=8, Nq=3, seed=7)
    cap = 60
    mixed = estimate_mixed_freq_dfm(
        x, is_q, r=1, max_em_iter=cap, tol=1e-6, gram_dtype="bfloat16"
    )
    ll = mixed.loglik_path[np.isfinite(mixed.loglik_path)]
    assert len(ll) > 0 and np.isfinite(ll[-1])
    assert np.isfinite(np.asarray(mixed.params.lam)).all()
    assert int(mixed.n_iter) <= cap + 1
    with pytest.raises(ValueError, match="gram_dtype"):
        estimate_mixed_freq_dfm(x, is_q, r=1, max_em_iter=2, gram_dtype="f16")


def test_mixed_freq_accel_composes_with_gram_dtype():
    """The composed accel+gram_dtype path on estimate_mixed_freq_dfm:
    SquaremState must flow through both phases and unwrap before the
    smoothing readout."""
    from dynamic_factor_models_tpu.models.mixed_freq import (
        MixedFreqParams,
        estimate_mixed_freq_dfm,
    )

    rng = np.random.default_rng(13)
    T, Nm, Nq = 180, 6, 2
    f = np.zeros(T)
    for t in range(1, T):
        f[t] = 0.8 * f[t - 1] + rng.standard_normal()
    x_m = np.outer(f, rng.standard_normal(Nm)) + 1.0 * rng.standard_normal((T, Nm))
    x_q = np.full((T, Nq), np.nan)
    qe = np.arange(5, T, 3)
    x_q[qe] = np.outer(f, np.ones(Nq))[qe] + 1.0 * rng.standard_normal((len(qe), Nq))
    x = np.hstack([x_m, x_q])
    is_q = np.array([False] * Nm + [True] * Nq)
    both = estimate_mixed_freq_dfm(
        x, is_q, r=1, max_em_iter=20, tol=1e-5,
        accel="squarem", gram_dtype="bfloat16",
    )
    assert isinstance(both.params, MixedFreqParams), type(both.params)
    ll = both.loglik_path[np.isfinite(both.loglik_path)]
    assert len(ll) > 0 and np.isfinite(ll[-1])
    assert int(both.n_iter) <= 21
