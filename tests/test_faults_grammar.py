"""Fault-grammar completeness (PR 19): `tools/check_faults_grammar.py`
audits that every fault kind implemented in ``utils/faults._KINDS`` is
(a) documented as a grammar row in docs/robustness.md and (b) referenced
by at least one file under tests/ — an injector axis nobody documents or
drills is an unproven robustness claim."""

import importlib.util
import os

import pytest

pytestmark = pytest.mark.obs

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _checker():
    spec = importlib.util.spec_from_file_location(
        "check_faults_grammar",
        os.path.join(_REPO, "tools", "check_faults_grammar.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_repo_grammar_is_documented_and_drilled(capsys):
    # the real contract: the committed repo must be clean
    assert _checker().main([]) == 0, capsys.readouterr().out


def test_audit_repo_covers_every_kind():
    from dynamic_factor_models_tpu.utils import faults

    chk = _checker()
    assert chk.audit_repo(_REPO) == []
    # and the audit actually iterated the full grammar, not a subset
    docs = open(os.path.join(_REPO, "docs", "robustness.md")).read()
    for kind in faults._KINDS:
        assert f"{kind}@" in docs


def test_missing_doc_row_is_a_violation():
    chk = _checker()
    bad = chk.audit_kinds(
        ("nan_estep",), "no grammar here", {"test_x.py": "nan_estep@2"}
    )
    assert len(bad) == 1 and "not documented" in bad[0][1]


def test_missing_test_reference_is_a_violation():
    chk = _checker()
    bad = chk.audit_kinds(
        ("nan_estep",), "``nan_estep@3``", {"test_x.py": "unrelated"}
    )
    assert len(bad) == 1 and "not drilled" in bad[0][1]


def test_clean_kind_passes_and_substring_kinds_do_not_leak():
    chk = _checker()
    # "stall_worker" must not satisfy a hypothetical "stall" doc row:
    # the @-anchored regex is word-bounded on the kind itself
    bad = chk.audit_kinds(
        ("stall_worker",),
        "``stall_worker@7`` row",
        {"test_y.py": "inject('stall_worker@7')"},
    )
    assert bad == []
    bad = chk.audit_kinds(
        ("stall_worker",), "``stall@7``", {"test_y.py": "stall_worker"}
    )
    assert len(bad) == 1 and "not documented" in bad[0][1]


def test_unreadable_repo_exits_2(tmp_path, capsys):
    assert _checker().main(["--repo", str(tmp_path)]) == 2
    assert "cannot audit" in capsys.readouterr().err
