"""Full-scale golden tests: every committed reference output at its full
width (VERDICT r1 items 4; BASELINE.md rows).

Sources (committed notebook outputs, /root/reference/Stock_Watson.ipynb):
- cell 37: Table 2(B) r=1..10 trace R2 / BN-ICp2 / AH-ER on the :All panel
- cell 39: Table 2(C) full Amengual-Watson ICp matrix (10 x 10 lower tri)
- cell 55: Table 3 per-series R2 (207 x 10) spot values
- cell 58: Table 4 r=8 Chow/QLR rejection ratios + correlation quantiles
- cell 61: Table 5 sets O (levels + residuals) and the stepwise set C
- cell 52: Figure 6 r<=60 single-iteration sweep (plot-only output;
  structural checks here)
"""

import numpy as np
import pytest

# full-scale goldens are the slow lane: minutes each on one core (the fast
# lane keeps the same tables at reduced width in test_dfm_golden.py)
pytestmark = pytest.mark.slow

from dynamic_factor_models_tpu.models.dfm import DFMConfig, estimate_dfm, estimate_factor
from dynamic_factor_models_tpu.models.favar_instruments import (
    choose_stepwise,
    favar_instrument_table,
)
from dynamic_factor_models_tpu.models.instability import instability_scan
from dynamic_factor_models_tpu.models.selection import (
    ahn_horenstein_er,
    estimate_factor_numbers,
)
from dynamic_factor_models_tpu.replication.stock_watson import figure6, table3

WINDOW = (2, 223)  # (1959Q3, 2014Q4), 0-based

# cell 39 stored output, columns r=1..10, rows d=1..r
AW_GOLDEN = {
    1: [-0.098],
    2: [-0.071, -0.085],
    3: [-0.072, -0.089, -0.090],
    4: [-0.068, -0.087, -0.088, -0.077],
    5: [-0.069, -0.089, -0.091, -0.080, -0.064],
    6: [-0.064, -0.084, -0.088, -0.075, -0.060, -0.045],
    7: [-0.064, -0.084, -0.088, -0.075, -0.062, -0.043, -0.024],
    8: [-0.064, -0.084, -0.086, -0.073, -0.057, -0.040, -0.022, -0.002],
    9: [-0.064, -0.085, -0.086, -0.071, -0.055, -0.037, -0.020, 0.000, 0.021],
    10: [-0.060, -0.080, -0.083, -0.069, -0.051, -0.035, -0.017, 0.003, 0.023, 0.044],
}


@pytest.fixture(scope="module")
def fnes_all_full(dataset_all):
    """Table 2(B)+(C) at full width: 11 static + 66 AW fits, batched."""
    return estimate_factor_numbers(
        dataset_all.bpdata, dataset_all.inclcode, *WINDOW, DFMConfig(), 11,
        dynamic=True,
    )


@pytest.mark.slow
def test_table2b_full_r10(fnes_all_full):
    np.testing.assert_allclose(
        fnes_all_full.trace_r2[:10],
        [0.215, 0.296, 0.358, 0.398, 0.427, 0.453, 0.478, 0.501, 0.522, 0.540],
        atol=1e-3,
    )
    np.testing.assert_allclose(
        fnes_all_full.bn_icp[:10],
        [-0.184, -0.233, -0.266, -0.271, -0.262, -0.249, -0.235, -0.223,
         -0.205, -0.185],
        atol=1e-3,
    )


@pytest.mark.slow
def test_table2b_ahn_horenstein_full(fnes_all_full):
    er = ahn_horenstein_er(fnes_all_full.marginal_r2)
    np.testing.assert_allclose(
        er[:10],
        [2.662, 1.313, 1.540, 1.369, 1.126, 1.063, 1.034, 1.152, 1.123, 1.056],
        atol=2e-3,
    )


@pytest.mark.slow
def test_table2c_full_aw_matrix(fnes_all_full):
    for r, col in AW_GOLDEN.items():
        np.testing.assert_allclose(
            fnes_all_full.aw_icp[: r, r - 1], col, atol=2e-3,
            err_msg=f"AW column r={r}",
        )
        # entries below the diagonal are undefined
        assert np.isnan(fnes_all_full.aw_icp[r:, r - 1]).all()


@pytest.mark.slow
def test_table4_r8(dataset_all):
    ds = dataset_all
    cfg = DFMConfig(nfac_u=8)
    F_full, _ = estimate_factor(ds.bpdata, ds.inclcode, 2, 223, cfg)
    F_pre, _ = estimate_factor(ds.bpdata, ds.inclcode, 2, 103, cfg)
    F_post, _ = estimate_factor(ds.bpdata, ds.inclcode, 104, 223, cfg)
    res = instability_scan(ds.bpdata, F_full, F_pre, F_post, 104, 8)
    np.testing.assert_allclose(
        res.chow_rej_ratios, [0.523, 0.665, 0.733], atol=1e-3
    )
    np.testing.assert_allclose(
        res.qlr_rej_ratios, [0.938, 0.977, 0.977], atol=1e-3
    )
    np.testing.assert_allclose(
        res.cor_pre_quantiles, [0.595, 0.834, 0.921, 0.972, 0.990], atol=1e-3
    )
    np.testing.assert_allclose(
        res.cor_post_quantiles, [0.432, 0.805, 0.940, 0.970, 0.986], atol=1e-3
    )


@pytest.fixture(scope="module")
def dfm8_all(dataset_all):
    return estimate_dfm(
        dataset_all.bpdata, dataset_all.inclcode, 2, 223, DFMConfig(nfac_u=8)
    )


@pytest.mark.slow
def test_table5_set_o(dataset_all, dfm8_all):
    r_res, r_lev = favar_instrument_table(
        dataset_all.bpdata,
        dataset_all.bpnamevec,
        ["OILPROD_SA", "GLOBAL_ACT", "WPU0561", "GDPC96",
         "PAYEMS", "PCECTPI", "FEDFUNDS", "TWEXMMTH"],
        dfm8_all.factor,
        dfm8_all.var,
        4,
        2,
        223,
    )
    np.testing.assert_allclose(
        r_res,
        [0.8286, 0.7960, 0.6942, 0.5567, 0.5043, 0.2634, 0.1589, 0.0202],
        atol=1e-3,
    )
    np.testing.assert_allclose(
        r_lev,
        [0.9762, 0.9560, 0.8766, 0.8402, 0.7155, 0.3911, 0.1790, 0.0153],
        atol=1e-3,
    )


@pytest.mark.slow
def test_table5_levels_sets_a_b(dataset_all, dfm8_all):
    _, lev_a = favar_instrument_table(
        dataset_all.bpdata, dataset_all.bpnamevec,
        ["GDPC96", "PAYEMS", "PCECTPI", "FEDFUNDS"],
        dfm8_all.factor, dfm8_all.var, 4, 2, 223,
    )
    np.testing.assert_allclose(
        lev_a, [0.9696, 0.8501, 0.7870, 0.5750], atol=1e-3
    )
    _, lev_b = favar_instrument_table(
        dataset_all.bpdata, dataset_all.bpnamevec,
        ["GDPC96", "PAYEMS", "PCECTPI", "FEDFUNDS",
         "NAPMPRI", "WPU0561", "CP90_TBILL", "GS10_TB3M"],
        dfm8_all.factor, dfm8_all.var, 4, 2, 223,
    )
    assert abs(lev_b[0] - 0.9708) < 1e-3
    assert abs(lev_b[-1] - 0.1029) < 1e-3


@pytest.mark.slow
def test_table5_stepwise_set_c(dataset_all, dfm8_all):
    """choose_stepwise must reproduce the reference's greedy selection
    outcome: the canonical correlations of its set C (cell 61)."""
    ds = dataset_all
    names_c = choose_stepwise(
        ds.bpdata, ds.bpnamevec, dfm8_all.factor, dfm8_all.var, 8, 4, 2, 223
    )
    assert len(names_c) == 8
    r_res, r_lev = favar_instrument_table(
        ds.bpdata, ds.bpnamevec, names_c, dfm8_all.factor, dfm8_all.var, 4, 2, 223
    )
    np.testing.assert_allclose(
        r_res,
        [0.8643, 0.8116, 0.7820, 0.7586, 0.7296, 0.5828, 0.4277, 0.3534],
        atol=1e-3,
    )
    np.testing.assert_allclose(
        r_lev,
        [0.9792, 0.9289, 0.9031, 0.8695, 0.7874, 0.7762, 0.5720, 0.4142],
        atol=1e-3,
    )


@pytest.mark.slow
def test_figure6_full_sweep(dataset_all):
    """The full r<=60 sweep on all three sample windows (cell 52 runs 180
    fits; the committed output is the plot, so checks are structural: the
    cumulative single-iteration trace R2 is increasing in r, bracketed by
    the converged Table 2(B) values at matching r, and NaN exactly where r
    exceeds a subsample's balanced block."""
    out = figure6(dataset_all, max_r=60)
    for label in ("all", "pre", "post"):
        tr = out[label]
        assert tr.shape == (60,)
        valid = np.isfinite(tr)
        # NaN (if any) forms a contiguous tail — the r > balanced-block guard
        if not valid.all():
            first_bad = int(np.argmin(valid))
            assert not valid[first_bad:].any()
        d = np.diff(tr[valid])
        assert (d > -1e-9).all(), f"{label}: cumulative trace R2 not increasing"
        assert 0.15 < tr[0] < 0.45
        assert tr[valid][-1] > 0.75  # 60 factors explain most of the panel
    # full-sample sweep at r=10: single iteration from PCA init lands close
    # to (and below 1.02x of) the converged trace R2 0.540 of Table 2(B)
    assert 0.45 < out["all"][9] <= 0.56


@pytest.mark.slow
def test_table3_spot_values(dataset_all):
    """Table 3 (cell 55, 207 x 10): corner spot values of the stored output."""
    r2 = table3(dataset_all, nfac_max=10)
    assert r2.shape == (207, 10)
    np.testing.assert_allclose(r2[0, 0], 0.5447, atol=1e-3)
    np.testing.assert_allclose(r2[0, 9], 0.8382, atol=1e-3)
    np.testing.assert_allclose(r2[1, 0], 0.3653, atol=1e-3)
    np.testing.assert_allclose(r2[-1, -1], 0.6950, atol=1e-3)
    np.testing.assert_allclose(r2[-1, 0], 0.0492, atol=1e-3)
