"""Nowcast news decomposition (models/news.py)."""

import jax.numpy as jnp
import numpy as np
import pytest

from dynamic_factor_models_tpu.models.news import nowcast_news
from dynamic_factor_models_tpu.models.ssm import SSMParams


@pytest.fixture(scope="module")
def setup():
    rng = np.random.default_rng(0)
    T, N = 80, 6
    f = np.zeros(T)
    for t in range(1, T):
        f[t] = 0.8 * f[t - 1] + rng.standard_normal()
    lam = np.array([1.0, 0.9, 0.8, 0.7, 0.6, 1.1])
    x = f[:, None] * lam[None, :] + 0.3 * rng.standard_normal((T, N))
    params = SSMParams(
        lam=jnp.asarray(lam[:, None]), R=0.09 * jnp.ones(N),
        A=0.8 * jnp.eye(1)[None], Q=jnp.eye(1),
    )
    x_old = x.copy()
    x_old[-1, :] = np.nan
    x_new = x.copy()
    x_new[-1, 0] = np.nan  # the nowcast target stays unreleased
    x_new[-1, 4] = np.nan
    return params, x_old, x_new


class TestNowcastNews:
    def test_news_telescopes_exactly(self, setup):
        params, x_old, x_new = setup
        res = nowcast_news(
            params, jnp.asarray(x_old), jnp.asarray(x_new), target=(79, 0)
        )
        assert res.releases.shape == (4, 2)
        assert abs(float(np.asarray(res.news).sum()) - res.total_revision) < 1e-10
        assert abs(res.total_revision - (res.new_nowcast - res.old_nowcast)) < 1e-10
        assert res.nowcast_path.shape == (5,)

    def test_positive_surprise_gives_positive_news(self, setup):
        params, x_old, x_new = setup
        x_pos = x_new.copy()
        x_pos[-1, 5] = 5.0  # large positive surprise, loading 1.1
        res = nowcast_news(
            params, jnp.asarray(x_old), jnp.asarray(x_pos), target=(79, 0)
        )
        j5 = [k for k, (t, i) in enumerate(res.releases) if i == 5][0]
        assert float(res.news[j5]) > 0.5

    def test_order_changes_attribution_not_total(self, setup):
        params, x_old, x_new = setup
        a = nowcast_news(
            params, jnp.asarray(x_old), jnp.asarray(x_new), target=(79, 0)
        )
        b = nowcast_news(
            params, jnp.asarray(x_old), jnp.asarray(x_new), target=(79, 0),
            order=[3, 2, 1, 0],
        )
        assert abs(a.total_revision - b.total_revision) < 1e-10
        # reversed order lists the same releases reversed
        assert (b.releases == a.releases[::-1]).all()

    def test_vintage_validation(self, setup):
        params, x_old, x_new = setup
        # non-nested vintages
        x_bad = x_new.copy()
        x_bad[10, 0] = np.nan
        with pytest.raises(ValueError, match="nested"):
            nowcast_news(params, jnp.asarray(x_old), jnp.asarray(x_bad),
                         target=(79, 0))
        # revised overlapping value
        x_rev = x_new.copy()
        x_rev[10, 0] += 1.0
        with pytest.raises(ValueError, match="pure releases"):
            nowcast_news(params, jnp.asarray(x_old), jnp.asarray(x_rev),
                         target=(79, 0))
        # observed target
        with pytest.raises(ValueError, match="observed in the new vintage"):
            nowcast_news(params, jnp.asarray(x_old), jnp.asarray(x_new),
                         target=(79, 1))
