"""State-space DFM tests: filter correctness vs a dense NumPy Kalman filter,
EM monotonicity, and factor recovery on synthetic data (SURVEY.md section 4:
synthetic DFM generator with known Lambda/F/AR structure)."""

import jax.numpy as jnp
import numpy as np
import pytest

from dynamic_factor_models_tpu.models.ssm import (
    SSMParams,
    em_step,
    kalman_filter,
    kalman_smoother,
)
from dynamic_factor_models_tpu.ops.cca import canonical_correlations
from dynamic_factor_models_tpu.ops.masking import fillz, mask_of


def _simulate(rng, T=200, N=10, r=2, p=2, missing=0.0):
    A1 = np.array([[0.6, 0.1], [0.0, 0.5]])
    A2 = np.array([[0.15, 0.0], [0.05, 0.1]])
    Q = np.array([[1.0, 0.2], [0.2, 0.8]])
    lam = rng.standard_normal((N, r))
    Rv = 0.3 + rng.random(N)
    f = np.zeros((T, r))
    cq = np.linalg.cholesky(Q)
    for t in range(2, T):
        f[t] = A1 @ f[t - 1] + A2 @ f[t - 2] + cq @ rng.standard_normal(r)
    x = f @ lam.T + np.sqrt(Rv) * rng.standard_normal((T, N))
    if missing:
        x[rng.random((T, N)) < missing] = np.nan
    params = SSMParams(
        jnp.asarray(lam), jnp.asarray(Rv), jnp.asarray(np.stack([A1, A2])), jnp.asarray(Q)
    )
    return x, f, params


def _dense_kalman_loglik(params, x):
    """Naive O(N^3) Kalman filter in NumPy, complete data, for cross-check."""
    lam = np.asarray(params.lam)
    Rv = np.diag(np.asarray(params.R))
    r, p = params.r, params.p
    k = r * p
    A = np.asarray(params.A)
    Tm = np.zeros((k, k))
    Tm[:r, :] = np.concatenate([A[i] for i in range(p)], axis=1)
    if p > 1:
        Tm[r:, : k - r] = np.eye(k - r)
    Qs = np.zeros((k, k))
    Qs[:r, :r] = np.asarray(params.Q)
    H = np.zeros((x.shape[1], k))
    H[:, :r] = lam
    s = np.zeros(k)
    P = 1e2 * np.eye(k)
    ll = 0.0
    for t in range(x.shape[0]):
        sp = Tm @ s
        Pp = Tm @ P @ Tm.T + Qs
        S = H @ Pp @ H.T + Rv
        v = x[t] - H @ sp
        Sinv = np.linalg.inv(S)
        K = Pp @ H.T @ Sinv
        s = sp + K @ v
        P = Pp - K @ H @ Pp
        ll += -0.5 * (
            len(v) * np.log(2 * np.pi) + np.linalg.slogdet(S)[1] + v @ Sinv @ v
        )
    return ll


def test_filter_matches_dense_kalman(rng):
    x, _, params = _simulate(rng, T=60, N=6)
    res = kalman_filter(params, x)
    ll_ref = _dense_kalman_loglik(params, x)
    np.testing.assert_allclose(float(res.loglik), ll_ref, rtol=1e-8)


def test_filter_missing_data_runs(rng):
    x, _, params = _simulate(rng, T=80, N=6, missing=0.2)
    res = kalman_filter(params, x)
    assert np.isfinite(float(res.loglik))
    # masking a series entirely must equal dropping it from the model
    x2 = x.copy()
    x2[:, 0] = np.nan
    ll_masked = float(kalman_filter(params, x2).loglik)
    params_drop = SSMParams(params.lam[1:], params.R[1:], params.A, params.Q)
    ll_drop = float(kalman_filter(params_drop, x[:, 1:]).loglik)
    np.testing.assert_allclose(ll_masked, ll_drop, rtol=1e-8)


def test_smoother_reduces_uncertainty(rng):
    x, _, params = _simulate(rng, T=100, N=8)
    filt = kalman_filter(params, x)
    means, covs, ll = kalman_smoother(params, x)
    tr_filt = np.trace(np.asarray(filt.covs), axis1=1, axis2=2)
    tr_sm = np.trace(np.asarray(covs), axis1=1, axis2=2)
    assert (tr_sm <= tr_filt + 1e-9).all()
    np.testing.assert_allclose(float(ll), float(filt.loglik))


def test_em_monotone_and_recovers_factors(rng):
    x, f_true, params_true = _simulate(rng, T=300, N=20, missing=0.1)
    N, r, p = 20, 2, 2
    params = SSMParams(
        jnp.zeros((N, r)).at[:, 0].set(1.0),
        jnp.ones(N),
        jnp.concatenate([0.5 * jnp.eye(r)[None], jnp.zeros((1, r, r))]),
        jnp.eye(r),
    )
    xj = jnp.asarray(x)
    xz, m = fillz(xj), mask_of(xj)
    lls = []
    for _ in range(40):
        params, ll = em_step(params, xz, m)
        lls.append(float(ll))
    assert all(b >= a - 1e-6 for a, b in zip(lls[1:], lls[2:]))
    means, _, _ = kalman_smoother(params, xj)
    cc = np.asarray(canonical_correlations(means[:, :r], jnp.asarray(f_true)))
    assert cc[0] > 0.95 and cc[1] > 0.9


def test_em_beats_true_params_loglik(rng):
    """ML property: converged EM loglik >= loglik at the true parameters."""
    x, _, params_true = _simulate(rng, T=250, N=12)
    ll_true = float(kalman_filter(params_true, x).loglik)
    params = params_true
    xj = jnp.asarray(x)
    xz, m = fillz(xj), mask_of(xj)
    for _ in range(30):
        params, ll = em_step(params, xz, m)
    assert float(ll) >= ll_true - 1e-6


def test_estimate_dfm_em_end_to_end(dataset_real):
    """EM entry point on the Stock-Watson panel (BASELINE config 2)."""
    from dynamic_factor_models_tpu.models.dfm import DFMConfig, estimate_factor
    from dynamic_factor_models_tpu.models.ssm import estimate_dfm_em

    res = estimate_dfm_em(
        dataset_real.bpdata, dataset_real.inclcode, 2, 223,
        DFMConfig(nfac_u=2), max_em_iter=15,
    )
    assert res.factors.shape == (222, 2)
    assert np.isfinite(res.loglik_path).all()
    # monotone likelihood
    assert all(b >= a - 1e-4 for a, b in zip(res.loglik_path, res.loglik_path[1:]))
    # means are the pre-standardization series means, not zero
    assert float(np.abs(np.asarray(res.means)).max()) > 1e-6
    # EM factors agree with ALS factors
    F_np, _ = estimate_factor(
        dataset_real.bpdata, dataset_real.inclcode, 2, 223, DFMConfig(nfac_u=2)
    )
    cc = np.asarray(
        canonical_correlations(res.factors, jnp.asarray(np.asarray(F_np)[2:224]))
    )
    assert cc[0] > 0.97


def test_em_step_singular_q_stays_finite(rng):
    # caller-supplied PSD-singular Q must not NaN-poison the Cholesky filter
    # (em_step floors Q like kalman_filter/kalman_smoother do)
    x = jnp.asarray(rng.standard_normal((60, 5)))
    m = jnp.ones((60, 5), bool)
    params = SSMParams(
        lam=jnp.asarray(rng.standard_normal((5, 2))),
        R=jnp.ones(5),
        A=jnp.asarray([[[0.5, 0.0], [0.0, 0.0]]]),
        Q=jnp.diag(jnp.asarray([1.0, 0.0])),
    )
    newp, ll = em_step(params, x, m)
    assert np.isfinite(float(ll))
    for v in newp:
        assert np.isfinite(np.asarray(v)).all()


def test_kalman_f32_f64_parity():
    # north-star parity bound (BASELINE.md): low-precision backend results
    # within 1e-5 of the f64 reference on smoothed factors
    rng2 = np.random.default_rng(7)
    T, N, r = 150, 40, 3
    f = np.zeros((T, r))
    for t in range(1, T):
        f[t] = 0.6 * f[t - 1] + rng2.standard_normal(r)
    lam = rng2.standard_normal((N, r))
    x = f @ lam.T + rng2.standard_normal((T, N))
    x[rng2.random((T, N)) < 0.1] = np.nan

    def run(dtype):
        pr = SSMParams(
            jnp.asarray(lam, dtype),
            jnp.ones(N, dtype),
            jnp.asarray(0.6 * np.eye(r)[None], dtype),
            jnp.eye(r, dtype=dtype),
        )
        m, c, ll = kalman_smoother(pr, jnp.asarray(x, dtype))
        return np.asarray(m[:, :r], np.float64)

    drift = np.abs(run(jnp.float64) - run(jnp.float32)).max()
    assert drift < 1e-5, f"f32 smoother drift {drift} exceeds parity bound"


class TestSqrtFilter:
    """Square-root array filter (method='sqrt'): exact f64 agreement with
    the information filter, and the f32 precision win it exists for."""

    def test_f64_equivalence_with_missing(self, rng):
        x, f, params = _simulate(rng, missing=0.12)
        fi = kalman_filter(params, jnp.asarray(x))
        fs = kalman_filter(params, jnp.asarray(x), method="sqrt")
        assert abs(float(fi.loglik - fs.loglik)) < 1e-8
        np.testing.assert_allclose(
            np.asarray(fi.means), np.asarray(fs.means), atol=1e-10
        )
        np.testing.assert_allclose(
            np.asarray(fi.covs), np.asarray(fs.covs), atol=1e-10
        )
        mi, ci, lli = kalman_smoother(params, jnp.asarray(x))
        ms, cs, lls = kalman_smoother(params, jnp.asarray(x), method="sqrt")
        np.testing.assert_allclose(np.asarray(mi), np.asarray(ms), atol=1e-10)
        np.testing.assert_allclose(np.asarray(ci), np.asarray(cs), atol=1e-10)

    @pytest.mark.parametrize("R_scale,rho", [(1e-4, 0.999), (1e-3, 0.99), (1e-1, 0.9)])
    def test_f32_loglik_precision_win(self, R_scale, rho):
        """Ill-conditioned DGPs (tiny R, near-unit-root factor): the f32
        sqrt filter's log-likelihood error vs the f64 truth is several
        times smaller than the information filter's (measured ~8-16x; the
        three cases here are the docs/PARITY.md table rows)."""
        rng2 = np.random.default_rng(1)
        T, N, r = 200, 30, 2
        f = np.zeros((T, r))
        for t in range(1, T):
            f[t] = rho * f[t - 1] + rng2.standard_normal(r) * np.sqrt(1 - rho**2)
        lam = rng2.standard_normal((N, r))
        x = f @ lam.T + np.sqrt(R_scale) * rng2.standard_normal((T, N))
        x[rng2.random((T, N)) < 0.08] = np.nan

        def run(dtype, method):
            pr = SSMParams(
                jnp.asarray(lam, dtype),
                R_scale * jnp.ones(N, dtype),
                jnp.asarray(rho * np.eye(r)[None], dtype),
                jnp.asarray((1 - rho**2) * np.eye(r), dtype),
            )
            return float(
                kalman_filter(pr, jnp.asarray(x, dtype), method=method).loglik
            )

        ll_true = run(jnp.float64, "sequential")
        err_info = abs(run(jnp.float32, "sequential") - ll_true)
        err_sqrt = abs(run(jnp.float32, "sqrt") - ll_true)
        # ill-conditioned rows: the sqrt filter must clearly win.  In the
        # benign row (R=0.1, rho=0.9) the collapsed information filter's
        # batched-GEMM accumulation is itself accurate to ~3e-4, so the
        # ratio loses meaning — both being tiny is the pass there.
        assert err_sqrt < 0.5 * err_info or (err_sqrt < 1e-3 and err_info < 1e-3), (
            f"sqrt filter did not improve f32 loglik: {err_sqrt} vs {err_info}"
        )

    def test_method_validation(self, rng):
        x, _, params = _simulate(rng)
        with pytest.raises(ValueError, match="method"):
            kalman_filter(params, jnp.asarray(x), method="nope")

    def test_twostep_is_zero_iteration_em(self):
        """Doz-Giannone-Reichlin two-step == estimate_dfm_em with 0 EM
        iterations: ALS-initialized params, one smoother pass, n_iter=0."""
        from dynamic_factor_models_tpu.models.dfm import DFMConfig
        from dynamic_factor_models_tpu.models.ssm import (
            estimate_dfm_em,
            estimate_dfm_twostep,
        )

        rng = np.random.default_rng(11)  # local: order-independent DGP
        x, F_true, _ = _simulate(rng)
        # ragged edge on the last columns; keep a balanced block for the
        # ALS PCA initialization
        x[rng.random(x.shape) < 0.1 * (np.arange(x.shape[1]) >= 5)] = np.nan
        incl = np.ones(x.shape[1], np.int64)
        cfg = DFMConfig(nfac_u=2, n_factorlag=2)
        ts = estimate_dfm_twostep(x, incl, 0, x.shape[0] - 1, cfg)
        em0 = estimate_dfm_em(x, incl, 0, x.shape[0] - 1, cfg, max_em_iter=0)
        assert ts.n_iter == 0 and len(ts.loglik_path) == 0
        np.testing.assert_allclose(ts.factors, em0.factors, atol=1e-12)
        for a, b in zip(ts.params, em0.params):
            np.testing.assert_allclose(a, b, atol=1e-12)
        # the smoothed two-step factors track the truth (DGR consistency);
        # canonical correlations are rotation/sign-robust
        cc = np.asarray(
            canonical_correlations(ts.factors, jnp.asarray(F_true))
        )
        assert cc[0] > 0.9 and cc[1] > 0.8

    def test_em_step_sqrt_matches_sequential(self, rng):
        from dynamic_factor_models_tpu.models.ssm import em_step, em_step_sqrt

        x, _, params = _simulate(rng, missing=0.1)
        xz, m = fillz(jnp.asarray(x)), mask_of(jnp.asarray(x))
        p1, ll1 = em_step(params, xz, m)
        p2, ll2 = em_step_sqrt(params, xz, m)
        assert abs(float(ll1 - ll2)) < 1e-8
        for a, b in zip(p1, p2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-8)


def test_em_step_assoc_matches_sequential(rng):
    """em_step_assoc (parallel-in-time E-step) == em_step to numerical
    precision: shared M-step, E-steps already pinned at 1e-10 parity."""
    import jax.numpy as jnp

    from dynamic_factor_models_tpu.models.ssm import (
        SSMParams,
        em_step,
        em_step_assoc,
    )

    T, N, r, p = 60, 8, 2, 2
    f = np.zeros((T, r))
    for t in range(1, T):
        f[t] = 0.6 * f[t - 1] + rng.standard_normal(r)
    lam = rng.standard_normal((N, r))
    x = f @ lam.T + 0.5 * rng.standard_normal((T, N))
    mask = rng.random((T, N)) > 0.1
    xz = jnp.asarray(np.where(mask, x, 0.0))
    m = jnp.asarray(mask)
    params = SSMParams(
        lam=jnp.asarray(lam * 0.5),
        R=jnp.ones(N),
        A=jnp.concatenate([0.5 * jnp.eye(r)[None], jnp.zeros((p - 1, r, r))]),
        Q=jnp.eye(r),
    )
    p1, ll1 = em_step(params, xz, m)
    p2, ll2 = em_step_assoc(params, xz, m)
    np.testing.assert_allclose(float(ll1), float(ll2), rtol=1e-8)
    np.testing.assert_allclose(np.asarray(p1.lam), np.asarray(p2.lam), atol=1e-7)
    np.testing.assert_allclose(np.asarray(p1.A), np.asarray(p2.A), atol=1e-7)
    np.testing.assert_allclose(np.asarray(p1.Q), np.asarray(p2.Q), atol=1e-7)
    np.testing.assert_allclose(np.asarray(p1.R), np.asarray(p2.R), atol=1e-7)


def test_em_loop_checkpoint_resume(tmp_path, rng):
    """Chunked+checkpointed EM == uninterrupted EM, and a rerun resumes
    from the persisted state instead of starting over."""
    import jax.numpy as jnp

    from dynamic_factor_models_tpu.models.emloop import run_em_loop
    from dynamic_factor_models_tpu.models.ssm import SSMParams, em_step

    T, N, r, p = 50, 6, 2, 1
    x = rng.standard_normal((T, N))
    xz = jnp.asarray(x)
    m = jnp.ones((T, N), bool)
    params = SSMParams(
        lam=jnp.asarray(rng.standard_normal((N, r)) * 0.5),
        R=jnp.ones(N),
        A=0.4 * jnp.eye(r)[None],
        Q=jnp.eye(r),
    )
    ck = str(tmp_path / "em_ck.npz")
    p_plain, path_plain, n_plain, _ = run_em_loop(
        em_step, params, (xz, m), 1e-8, 30
    )
    p_ck, path_ck, n_ck, _ = run_em_loop(
        em_step, params, (xz, m), 1e-8, 30,
        checkpoint_path=ck, checkpoint_every=7,
    )
    assert n_ck == n_plain
    np.testing.assert_allclose(path_ck, path_plain, rtol=1e-10)
    np.testing.assert_allclose(
        np.asarray(p_ck.lam), np.asarray(p_plain.lam), atol=1e-10
    )
    # resume: a fresh call with the same path starts from the saved state
    # (params argument is ignored in favor of the checkpoint) and returns
    # the identical converged state
    p_res, path_res, n_res, _ = run_em_loop(
        em_step, params, (xz, m), 1e-8, 30,
        checkpoint_path=ck, checkpoint_every=7,
    )
    assert n_res == n_ck
    np.testing.assert_allclose(
        np.asarray(p_res.lam), np.asarray(p_ck.lam), atol=1e-12
    )


def test_em_loop_checkpoint_guards(tmp_path, rng):
    """Checkpoint misuse fails loudly: wrong-inputs resume, bad chunk size,
    collect_path combination."""
    import jax.numpy as jnp
    import pytest

    from dynamic_factor_models_tpu.models.emloop import run_em_loop
    from dynamic_factor_models_tpu.models.ssm import SSMParams, em_step

    T, N, r = 30, 5, 1
    xz = jnp.asarray(rng.standard_normal((T, N)))
    m = jnp.ones((T, N), bool)
    params = SSMParams(
        lam=jnp.ones((N, r)) * 0.5, R=jnp.ones(N),
        A=0.4 * jnp.eye(r)[None], Q=jnp.eye(r),
    )
    ck = str(tmp_path / "ck.npz")
    run_em_loop(em_step, params, (xz, m), 1e-8, 10, checkpoint_path=ck)
    # different data -> fingerprint mismatch
    xz2 = xz + 1.0
    with pytest.raises(ValueError, match="fingerprint"):
        run_em_loop(em_step, params, (xz2, m), 1e-8, 10, checkpoint_path=ck)
    # different max_em_iter -> also a mismatch (path length differs)
    with pytest.raises(ValueError, match="fingerprint"):
        run_em_loop(em_step, params, (xz, m), 1e-8, 20, checkpoint_path=ck)
    with pytest.raises(ValueError, match="checkpoint_every"):
        run_em_loop(em_step, params, (xz, m), 1e-8, 10,
                    checkpoint_path=ck, checkpoint_every=0)
    with pytest.raises(ValueError, match="collect_path"):
        run_em_loop(em_step, params, (xz, m), 1e-8, 10,
                    checkpoint_path=ck, collect_path=True)


def test_estimate_dfm_mle_matches_em_neighborhood(dataset_real):
    """Direct gradient MLE (adam through the collapsed filter) reaches at
    least the EM path's likelihood neighborhood on the real panel from the
    same ALS init, with comparable factors."""
    from dynamic_factor_models_tpu.models.ssm import (
        estimate_dfm_em,
        estimate_dfm_mle,
    )

    em = estimate_dfm_em(
        dataset_real.bpdata, dataset_real.inclcode, 2, 223, max_em_iter=60,
        tol=1e-6,
    )
    mle = estimate_dfm_mle(
        dataset_real.bpdata, dataset_real.inclcode, 2, 223, n_steps=400,
    )
    ll_em = em.loglik_path[np.isfinite(em.loglik_path)][-1]
    ll_mle = mle.loglik_path[np.isfinite(mle.loglik_path)][-1]
    assert np.isfinite(ll_mle)
    assert ll_mle >= ll_em - 5e-3 * (1 + abs(ll_em)), (ll_mle, ll_em)
    # same object recovered: smoothed factor correlation near 1 (sign-free)
    f_em = np.asarray(em.factors[:, 0])
    f_mle = np.asarray(mle.factors[:, 0])
    corr = abs(np.corrcoef(f_em, f_mle)[0, 1])
    assert corr > 0.97, corr
    # Q positive definite by the Cholesky parametrization
    assert (np.linalg.eigvalsh(np.asarray(mle.params.Q)) > 0).all()


def test_ssm_standard_errors(dataset_real):
    """OPG SEs for the state-space DFM: per-step collapsed lls sum to the
    filter loglik exactly; structural SEs finite/positive; whole-vector
    mode refuses rank-deficient designs."""
    from dynamic_factor_models_tpu.models.ssm import (
        _ssm_step_lls,
        estimate_dfm_em,
        ssm_standard_errors,
    )
    from dynamic_factor_models_tpu.ops.linalg import standardize_data

    em = estimate_dfm_em(
        dataset_real.bpdata, dataset_real.inclcode, 2, 223, max_em_iter=30
    )
    est = np.asarray(dataset_real.bpdata)[
        :, np.asarray(dataset_real.inclcode) == 1
    ][2:224]
    xstd, _ = standardize_data(jnp.asarray(est))
    m = ~jnp.isnan(xstd)
    xz = jnp.where(m, xstd, 0.0)
    # per-step terms sum to the filter likelihood (stats-free path)
    lls = _ssm_step_lls(em.params, xz, m)
    filt = kalman_filter(em.params, jnp.where(m, xz, jnp.nan))
    np.testing.assert_allclose(
        float(lls.sum()), float(filt.loglik), rtol=1e-10
    )
    # the floored sandwich applies warning-free at an EM stop: tiny
    # noise-negative curvature directions (EM's slow tail) are excluded
    # by the eigenvalue floor, not amplified and not fatal
    import warnings as _warnings

    with _warnings.catch_warnings():
        _warnings.simplefilter("error", UserWarning)
        se = ssm_standard_errors(em.params, xstd)
    assert np.isfinite(np.asarray(se.A)).all() and (np.asarray(se.A) > 0).all()
    assert np.isfinite(np.asarray(se.Q)).all()
    assert np.isnan(np.asarray(se.lam)).all()
    se_opg = ssm_standard_errors(em.params, xstd, cov="opg")
    assert np.isfinite(np.asarray(se_opg.A)).all()
    with pytest.raises(ValueError, match="cov"):
        ssm_standard_errors(em.params, xstd, cov="hac")

    # a point FAR from any optimum (explosive A): substantially
    # indefinite -H must fall back to OPG with the warning
    bad = em.params._replace(A=em.params.A.at[0].set(1.8 * jnp.eye(4)))
    with pytest.warns(UserWarning, match="indefinite"):
        se_bad = ssm_standard_errors(bad, xstd)
    np.testing.assert_allclose(
        np.asarray(se_bad.A),
        np.asarray(ssm_standard_errors(bad, xstd, cov="opg").A),
        rtol=1e-6,
    )
    with pytest.raises(ValueError, match="time steps"):
        ssm_standard_errors(em.params, xstd[:40], which="all")
