"""Orchestration test for bench.py --run-tpu-remainder: the code path the
watcher runs UNATTENDED in a scarce tunnel window.  Sections are stubbed;
what is under test is the plumbing — section order, per-section partial
persistence, evidence-store accumulation, and the parity-failure exit."""

import importlib.util
import json
import os

import pytest


@pytest.fixture
def bench(tmp_path, monkeypatch):
    spec = importlib.util.spec_from_file_location(
        "bench_under_test",
        os.path.join(os.path.dirname(__file__), "..", "bench.py"),
    )
    b = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(b)
    monkeypatch.setattr(b, "EVIDENCE_PATH", str(tmp_path / "evidence.json"))
    monkeypatch.setenv("DFM_BENCH_PARTIAL", str(tmp_path / "partial.json"))
    monkeypatch.setattr(b, "_is_tpu_platform", lambda p: True)

    calls = []
    monkeypatch.setattr(
        b, "pallas_section",
        lambda: calls.append("pallas") or {"pallas_gram_speedup_large_panel": 1.5},
    )
    monkeypatch.setattr(
        b, "device_parity_checks",
        lambda ds: calls.append("parity") or {
            k: 1e-5 for k in b.PARITY_THRESHOLDS
        },
    )
    monkeypatch.setattr(
        b, "large_panel_section",
        lambda tpu_ok, persist=None: calls.append("large") or {
            "em_large_iters_per_sec": 9.9
        },
    )
    monkeypatch.setattr(
        b, "crossover_table", lambda: calls.append("crossover") or print("| t |")
    )
    b._real_refscale_section = b.refscale_section
    monkeypatch.setattr(
        b, "refscale_section",
        lambda: calls.append("refscale") or {"em_refscale_best_ips": 180.0},
    )

    class _FakeChild:
        stderr = ""
        returncode = 0

        def __init__(self, stdout):
            self.stdout = stdout

    def _fake_run_child(args, env_extra=None, timeout_s=3600):
        if "--run-time-parallel" in args:
            calls.append("timeparallel")
            return _FakeChild('{"time_parallel": true, "smoke": true}')
        if "--run-composed" in args:
            calls.append("composed")
            return _FakeChild('{"composed": true, "smoke": true}')
        calls.append("multichip")
        return _FakeChild('{"n_devices": 8, "tpu_unreachable": false}')

    monkeypatch.setattr(b, "_run_child", _fake_run_child)
    # the multi-host leg spawns real OS-process workers — stub the whole
    # section like the other named sections so the order test stays a
    # plumbing test
    monkeypatch.setattr(
        b, "multihost_section",
        lambda force_cpu, smoke=False: calls.append("multihost") or {
            "smoke": smoke, "flop_proxy": True
        },
    )
    # the obs-overhead leg runs a real (small) EM estimate — stub it so
    # the order test stays a plumbing test
    monkeypatch.setattr(
        b, "obs_overhead_section",
        lambda smoke=True: calls.append("obs") or {
            "obs_overhead_pct": 1.0, "flop_proxy": True,
            "mfu_peak_source": "unmeasured",
        },
    )

    class _FakeDS:
        pass

    import dynamic_factor_models_tpu.io.cache as cache

    monkeypatch.setattr(cache, "cached_dataset", lambda name: _FakeDS())
    b._test_calls = calls
    return b


def test_remainder_section_order_and_stores(bench, tmp_path, capsys):
    bench.run_tpu_remainder()
    assert bench._test_calls == [
        "pallas", "parity", "large", "refscale", "multichip", "composed",
        "timeparallel", "multihost", "obs", "crossover"
    ]
    out = capsys.readouterr().out.strip().splitlines()[-1]
    final = json.loads(out)
    assert final["parity_ok"] is True
    assert final["pallas_gram_speedup_large_panel"] == 1.5
    assert final["multichip"]["n_devices"] == 8
    assert final["composed_smoke"]["smoke"] is True
    assert final["time_parallel_smoke"]["smoke"] is True
    assert final["multihost_smoke"]["smoke"] is True
    assert final["obs_overhead"]["obs_overhead_pct"] == 1.0
    assert "crossover_markdown" in final
    # per-section persistence: the partial file holds the full accumulation
    partial = json.loads((tmp_path / "partial.json").read_text())
    assert partial["em_large_iters_per_sec"] == 9.9
    # the durable evidence store accumulated the live fields with provenance
    ev = json.loads((tmp_path / "evidence.json").read_text())
    assert ev["em_large_iters_per_sec"] == 9.9 and ev["parity_ok"] is True
    assert len(ev["windows"]) >= 1


def test_remainder_parity_failure_exits_1(bench, monkeypatch):
    monkeypatch.setattr(
        bench, "device_parity_checks",
        lambda ds: {k: 0.5 for k in bench.PARITY_THRESHOLDS},  # way over 1e-3
    )
    with pytest.raises(SystemExit) as ei:
        bench.run_tpu_remainder()
    # exit 1 = complete-but-parity-failed (the watcher surfaces it); the
    # sections after parity still ran so the window was not wasted
    assert ei.value.code == 1
    assert bench._test_calls[-1] == "crossover"


def test_remainder_no_tpu_exits_2(bench, monkeypatch):
    monkeypatch.setattr(bench, "_is_tpu_platform", lambda p: False)
    with pytest.raises(SystemExit) as ei:
        bench.run_tpu_remainder()
    assert ei.value.code == 2


def test_refscale_crossover_summary(bench, tmp_path, monkeypatch):
    """The live leg vs staged-CPU comparison: per-cell ratios and the
    measured (T, n_reps) crossover points, including the 'never crossed
    within the grid' encoding (0, not None — the evidence store drops
    nulls and a negative finding must survive)."""
    live = {
        "refscale_platform": "tpu",
        "em_refscale_best_unroll": 8,
        "em_refscale_best_ips": 160.0,   # loses at T=222
        "em_ips_T444": 150.0,            # loses
        "em_ips_T888": 120.0,            # wins (cpu 100)
        "em_ips_T1776": 90.0,            # wins (cpu 50)
        "bootstrap_1000rep_s": 0.30,     # loses (cpu 0.12)
        "bootstrap_4000rep_s": 0.40,     # wins  (cpu 0.50)
        "bootstrap_16000rep_s": 0.60,    # wins  (cpu 2.00)
    }
    staged = {
        "code_rev": bench._parity_code_rev(),
        "em_refscale_best_ips": 180.0,
        "em_ips_T444": 170.0,
        "em_ips_T888": 100.0,
        "em_ips_T1776": 50.0,
        "bootstrap_1000rep_s": 0.12,
        "bootstrap_4000rep_s": 0.50,
        "bootstrap_16000rep_s": 2.00,
    }
    monkeypatch.setattr(bench, "REFSCALE_STAGED", str(tmp_path / "rs.json"))
    (tmp_path / "rs.json").write_text(json.dumps(staged))
    monkeypatch.setattr(bench, "_refscale_measure", lambda force_cpu: dict(live))
    out = bench._real_refscale_section()
    assert out["refscale_cpu_staged"] is True
    assert out["em_T_crossover"] == 888
    assert out["bootstrap_reps_crossover"] == 4000
    assert out["em_ips_T888_tpu_over_cpu"] == 1.2
    assert out["bootstrap_16000rep_s_tpu_over_cpu"] == pytest.approx(3.333)
    # a chip that never wins reports 0, not a dropped field
    live_lose = {k: v for k, v in live.items()}
    live_lose.update(
        {"em_ips_T888": 90.0, "em_ips_T1776": 40.0,
         "bootstrap_4000rep_s": 0.6, "bootstrap_16000rep_s": 2.5}
    )
    monkeypatch.setattr(
        bench, "_refscale_measure", lambda force_cpu: dict(live_lose)
    )
    out2 = bench._real_refscale_section()
    assert out2["em_T_crossover"] == 0
    assert out2["bootstrap_reps_crossover"] == 0


def test_refscale_stale_staging_detected(bench, tmp_path, monkeypatch):
    monkeypatch.setattr(bench, "REFSCALE_STAGED", str(tmp_path / "rs.json"))
    (tmp_path / "rs.json").write_text(json.dumps({"code_rev": "stale"}))
    assert bench.refscale_staged_fresh() is False
    monkeypatch.setattr(
        bench, "_refscale_measure", lambda force_cpu: {"refscale_platform": "tpu", "em_refscale_best_ips": 1.0}
    )
    out = bench._real_refscale_section()
    # stale staging: no ratios fabricated, the flag says why
    assert out["refscale_cpu_staged"] is False
    assert not any(k.endswith("_tpu_over_cpu") for k in out)


def test_refscale_refuses_cpu_live_leg(bench, tmp_path, monkeypatch):
    """A live leg whose children silently landed on CPU must never be
    recorded as chip evidence — no ratios, no crossovers."""
    monkeypatch.setattr(bench, "REFSCALE_STAGED", str(tmp_path / "rs.json"))
    (tmp_path / "rs.json").write_text(
        json.dumps({"code_rev": bench._parity_code_rev(),
                    "em_refscale_best_ips": 100.0})
    )
    # undo the fixture's always-TPU stub: this test is about the platform
    # check itself
    monkeypatch.setattr(
        bench, "_is_tpu_platform", lambda p: p in ("tpu", "axon")
    )
    monkeypatch.setattr(
        bench, "_refscale_measure",
        lambda force_cpu: {"refscale_platform": "cpu",
                           "em_refscale_best_ips": 99.0},
    )
    out = bench._real_refscale_section()
    assert out["refscale_live_leg_on_tpu"] is False
    assert "em_T_crossover" not in out
    assert not any(k.endswith("_tpu_over_cpu") for k in out)


def test_parity_fill_from_precision_legs(bench):
    """BENCH_r05 regression: a CPU-only fragment whose device-parity fields
    are null must come back with parity_* filled from the precision legs,
    parity_ok evaluated against the documented thresholds, and the
    provenance tagged so nobody mistakes it for a two-backend check."""
    fragment = {
        "parity_factor": None,
        "parity_smoother": None,
        "parity_smoother_sqrt": None,
        "parity_irf": None,
        "parity_ok": None,
        "parity_precision_factor": 2.0e-5,
        "parity_precision_smoother": 3.0e-5,
        "parity_precision_smoother_sqrt": 4.0e-5,
        "parity_precision_irf": 5.0e-5,
    }
    out = bench._fill_parity_from_precision(fragment)
    assert out is fragment  # filled in place, the orchestrator reuses it
    assert out["parity_factor"] == 2.0e-5
    assert out["parity_smoother"] == 3.0e-5
    assert out["parity_smoother_sqrt"] == 4.0e-5
    assert out["parity_irf"] == 5.0e-5
    assert out["parity_source"] == "precision"
    assert out["parity_ok"] is True
    assert None not in {out[k] for k in bench.PARITY_THRESHOLDS}


def test_parity_fill_respects_thresholds(bench):
    """A filled value past its documented threshold must flip parity_ok to
    False — the fill is evidence plumbing, not grade inflation."""
    fragment = {
        "parity_factor": None,
        "parity_smoother": None,
        "parity_smoother_sqrt": None,
        "parity_irf": None,
        "parity_ok": None,
        "parity_precision_factor": 5.0e-2,  # >> 1e-3 threshold
        "parity_precision_smoother": 1.0e-6,
        "parity_precision_smoother_sqrt": 1.0e-6,
        "parity_precision_irf": 1.0e-6,
    }
    out = bench._fill_parity_from_precision(fragment)
    assert out["parity_source"] == "precision"
    assert out["parity_ok"] is False


def test_parity_fill_leaves_device_measurements_alone(bench):
    """When the two-backend comparison DID run, its numbers win: nothing is
    overwritten, parity_source stays 'device', and a pre-computed
    parity_ok is not second-guessed."""
    fragment = {
        "parity_factor": 1.0e-6,
        "parity_smoother": 2.0e-6,
        "parity_smoother_sqrt": 3.0e-6,
        "parity_irf": 4.0e-6,
        "parity_ok": True,
        "parity_precision_factor": 9.0e-1,  # would fail if it leaked in
        "parity_precision_smoother": 9.0e-1,
        "parity_precision_smoother_sqrt": 9.0e-1,
        "parity_precision_irf": 9.0e-1,
    }
    out = bench._fill_parity_from_precision(dict(fragment))
    assert out["parity_factor"] == 1.0e-6
    assert out["parity_irf"] == 4.0e-6
    assert out["parity_source"] == "device"
    assert out["parity_ok"] is True
