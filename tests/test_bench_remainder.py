"""Orchestration test for bench.py --run-tpu-remainder: the code path the
watcher runs UNATTENDED in a scarce tunnel window.  Sections are stubbed;
what is under test is the plumbing — section order, per-section partial
persistence, evidence-store accumulation, and the parity-failure exit."""

import importlib.util
import json
import os

import pytest


@pytest.fixture
def bench(tmp_path, monkeypatch):
    spec = importlib.util.spec_from_file_location(
        "bench_under_test",
        os.path.join(os.path.dirname(__file__), "..", "bench.py"),
    )
    b = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(b)
    monkeypatch.setattr(b, "EVIDENCE_PATH", str(tmp_path / "evidence.json"))
    monkeypatch.setenv("DFM_BENCH_PARTIAL", str(tmp_path / "partial.json"))
    monkeypatch.setattr(b, "_is_tpu_platform", lambda p: True)

    calls = []
    monkeypatch.setattr(
        b, "pallas_section",
        lambda: calls.append("pallas") or {"pallas_gram_speedup_large_panel": 1.5},
    )
    monkeypatch.setattr(
        b, "device_parity_checks",
        lambda ds: calls.append("parity") or {
            k: 1e-5 for k in b.PARITY_THRESHOLDS
        },
    )
    monkeypatch.setattr(
        b, "large_panel_section",
        lambda tpu_ok, persist=None: calls.append("large") or {
            "em_large_iters_per_sec": 9.9
        },
    )
    monkeypatch.setattr(
        b, "crossover_table", lambda: calls.append("crossover") or print("| t |")
    )

    class _FakeDS:
        pass

    import dynamic_factor_models_tpu.io.cache as cache

    monkeypatch.setattr(cache, "cached_dataset", lambda name: _FakeDS())
    b._test_calls = calls
    return b


def test_remainder_section_order_and_stores(bench, tmp_path, capsys):
    bench.run_tpu_remainder()
    assert bench._test_calls == ["pallas", "parity", "large", "crossover"]
    out = capsys.readouterr().out.strip().splitlines()[-1]
    final = json.loads(out)
    assert final["parity_ok"] is True
    assert final["pallas_gram_speedup_large_panel"] == 1.5
    assert "crossover_markdown" in final
    # per-section persistence: the partial file holds the full accumulation
    partial = json.loads((tmp_path / "partial.json").read_text())
    assert partial["em_large_iters_per_sec"] == 9.9
    # the durable evidence store accumulated the live fields with provenance
    ev = json.loads((tmp_path / "evidence.json").read_text())
    assert ev["em_large_iters_per_sec"] == 9.9 and ev["parity_ok"] is True
    assert len(ev["windows"]) >= 1


def test_remainder_parity_failure_exits_1(bench, monkeypatch):
    monkeypatch.setattr(
        bench, "device_parity_checks",
        lambda ds: {k: 0.5 for k in bench.PARITY_THRESHOLDS},  # way over 1e-3
    )
    with pytest.raises(SystemExit) as ei:
        bench.run_tpu_remainder()
    # exit 1 = complete-but-parity-failed (the watcher surfaces it); the
    # sections after parity still ran so the window was not wasted
    assert ei.value.code == 1
    assert bench._test_calls[-1] == "crossover"


def test_remainder_no_tpu_exits_2(bench, monkeypatch):
    monkeypatch.setattr(bench, "_is_tpu_platform", lambda p: False)
    with pytest.raises(SystemExit) as ei:
        bench.run_tpu_remainder()
    assert ei.value.code == 2
