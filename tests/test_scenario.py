"""Conditional forecasts (models/forecast.py) and historical decomposition
(models/var.py): exact identities and scenario behavior."""

import jax.numpy as jnp
import numpy as np
import pytest

from dynamic_factor_models_tpu.models.forecast import conditional_forecast
from dynamic_factor_models_tpu.models.ssm import SSMParams
from dynamic_factor_models_tpu.models.var import (
    estimate_var,
    historical_decomposition,
)


def _var_data(T=400, seed=0):
    rng = np.random.default_rng(seed)
    B0 = np.array([[1.0, 0.0, 0.0], [0.5, 0.8, 0.0], [-0.4, 0.3, 0.6]])
    A1 = np.array([[0.5, 0.1, 0.0], [0.0, 0.4, 0.1], [0.1, 0.0, 0.3]])
    eps = rng.standard_normal((T, 3))
    y = np.zeros((T, 3))
    for t in range(1, T):
        y[t] = 0.3 + A1 @ y[t - 1] + B0 @ eps[t]
    return y, eps


class TestHistoricalDecomposition:
    def test_exact_reconstruction(self):
        """baseline + sum of contributions == y on the estimation window."""
        y, eps = _var_data()
        var = estimate_var(jnp.asarray(y), 1, 5, y.shape[0] - 1)
        hd = historical_decomposition(var, jnp.asarray(y))
        recon = np.asarray(hd.baseline) + np.asarray(hd.contributions).sum(axis=2)
        assert np.abs(recon - y[hd.rows]).max() < 1e-10

    def test_recovers_structural_shocks(self):
        """B0 is lower-triangular, so recursive identification recovers the
        true shocks up to estimation noise."""
        y, eps = _var_data()
        var = estimate_var(jnp.asarray(y), 1, 5, y.shape[0] - 1)
        hd = historical_decomposition(var, jnp.asarray(y))
        for j in range(3):
            c = np.corrcoef(np.asarray(hd.shocks)[:, j], eps[hd.rows][:, j])[0, 1]
            assert c > 0.95

    def test_lag2_window(self):
        y, _ = _var_data(seed=1)
        var = estimate_var(jnp.asarray(y), 2, 10, y.shape[0] - 1)
        hd = historical_decomposition(var, jnp.asarray(y))
        recon = np.asarray(hd.baseline) + np.asarray(hd.contributions).sum(axis=2)
        assert np.abs(recon - y[hd.rows]).max() < 1e-10

    def test_no_constant_layout(self):
        """withconst=False betahat has no const row; the identity must still
        hold (const treated as zero, not as the first lag row)."""
        y, _ = _var_data(seed=3)
        y = y - y.mean(axis=0)
        var = estimate_var(jnp.asarray(y), 1, 5, y.shape[0] - 1, withconst=False)
        hd = historical_decomposition(var, jnp.asarray(y))
        recon = np.asarray(hd.baseline) + np.asarray(hd.contributions).sum(axis=2)
        assert np.abs(recon - y[hd.rows]).max() < 1e-10

    def test_rejects_ragged_window(self):
        y, _ = _var_data(T=100, seed=2)
        y[50] = np.nan  # hole inside the window
        var = estimate_var(jnp.asarray(y), 1, 5, 99)
        with pytest.raises(ValueError, match="contiguous"):
            historical_decomposition(var, jnp.asarray(y))


class TestConditionalForecast:
    @pytest.fixture(scope="class")
    def setup(self):
        rng = np.random.default_rng(0)
        T, N = 150, 8
        f = np.zeros((T, 1))
        for t in range(1, T):
            f[t] = 0.8 * f[t - 1] + rng.standard_normal(1)
        lam = np.ones((N, 1))
        lam[4:] = 0.8
        x = f @ lam.T + 0.3 * rng.standard_normal((T, N))
        params = SSMParams(
            lam=jnp.asarray(lam), R=0.09 * jnp.ones(N),
            A=0.8 * jnp.eye(1)[None], Q=jnp.eye(1),
        )
        return params, x

    def test_unconditional_decays_to_mean(self, setup):
        params, x = setup
        fc = conditional_forecast(params, jnp.asarray(x), 12)
        fpath = np.asarray(fc.factor_mean)[:, 0]
        # AR(0.8) forecast: |f_{h+1}| < |f_h|, geometric decay toward 0
        assert (np.abs(fpath[1:]) < np.abs(fpath[:-1]) + 1e-12).all()
        assert np.allclose(fpath[1:] / fpath[:-1], 0.8, atol=0.02)

    def test_conditioning_moves_correlated_series(self, setup):
        params, x = setup
        h, N = 8, x.shape[1]
        unc = conditional_forecast(params, jnp.asarray(x), h)
        cond = np.full((h, N), np.nan)
        cond[:, 0] = 3.0
        con = conditional_forecast(params, jnp.asarray(x), h, conditions=cond)
        # loading-1 series pulled up toward the conditioned path
        assert (np.asarray(con.mean)[:, 1] > np.asarray(unc.mean)[:, 1]).all()
        assert np.asarray(con.mean)[2:, 1].mean() > 2.0
        # conditioning reduces predictive uncertainty everywhere
        assert (np.asarray(con.sd) <= np.asarray(unc.sd) + 1e-12).all()

    def test_neutral_conditioning_is_noop(self, setup):
        """Conditioning a series ON its own unconditional mean path leaves
        the other forecasts (nearly) unchanged."""
        params, x = setup
        h, N = 6, x.shape[1]
        unc = conditional_forecast(params, jnp.asarray(x), h)
        cond = np.full((h, N), np.nan)
        cond[:, 0] = np.asarray(unc.mean)[:, 0]
        con = conditional_forecast(params, jnp.asarray(x), h, conditions=cond)
        assert np.allclose(
            np.asarray(con.mean)[:, 1:], np.asarray(unc.mean)[:, 1:], atol=1e-6
        )

    def test_shape_validation(self, setup):
        params, x = setup
        with pytest.raises(ValueError, match="conditions must be"):
            conditional_forecast(
                params, jnp.asarray(x), 4, conditions=np.zeros((3, 2))
            )
        with pytest.raises(ValueError, match="horizon"):
            conditional_forecast(params, jnp.asarray(x), 0)
