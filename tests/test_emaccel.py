"""SQUAREM EM acceleration (models/emaccel.py): same fixed point as plain
EM, loglik-guarded monotonicity, and materially fewer map evaluations on a
slow-converging (persistent-factor) panel.  The reference has no EM at all
(its `Parametric()` path is declared but unimplemented, SURVEY.md §2.3), so
these tests pin framework-side semantics, not reference parity."""

import jax.numpy as jnp
import numpy as np
import pytest

from dynamic_factor_models_tpu.models.emaccel import squarem, squarem_state
from dynamic_factor_models_tpu.models.emloop import run_em_loop
from dynamic_factor_models_tpu.models.ssm import (
    SSMParams,
    _project_params,
    compute_panel_stats,
    em_step_stats,
    kalman_filter,
)
from dynamic_factor_models_tpu.ops.masking import fillz, mask_of


@pytest.fixture
def rng():
    return np.random.default_rng(11)


def _slow_panel(rng, T=160, N=12, r=2, rho=0.95, missing=0.1):
    """Persistent factors + noisy series: the slow-EM regime (EM's
    geometric rate degrades as factor persistence and noise rise)."""
    f = np.zeros((T, r))
    for t in range(1, T):
        f[t] = rho * f[t - 1] + rng.standard_normal(r) * np.sqrt(1 - rho**2)
    lam = rng.standard_normal((N, r)) * 0.6
    x = f @ lam.T + 1.5 * rng.standard_normal((T, N))
    x[rng.random((T, N)) < missing] = np.nan
    return x


def _setup(x, rng, r=2):
    xj = jnp.asarray(x)
    m = mask_of(xj).astype(xj.dtype)
    xz = fillz(xj)
    N = x.shape[1]
    # random loading init: an exactly-zero loading column is an EM fixed
    # point of its own (the unloaded factor's smoothed mean is identically
    # zero, so its M-step loading stays zero)
    params = SSMParams(
        lam=jnp.asarray(0.1 * rng.standard_normal((N, r))),
        R=jnp.ones(N),
        A=0.5 * jnp.eye(r)[None],
        Q=jnp.eye(r),
    )
    stats = compute_panel_stats(xz, m)
    return params, (xz, m, stats)


def _loglik(params, x):
    xn = jnp.where(jnp.isnan(jnp.asarray(x)), jnp.nan, jnp.asarray(x))
    return float(kalman_filter(params, xn).loglik)


def test_squarem_reaches_plain_em_fixed_point(rng):
    x = _slow_panel(rng)
    params, args = _setup(x, rng)
    tol = 1e-7
    plain, _, n_plain, _ = run_em_loop(em_step_stats, params, args, tol, 4000)
    assert int(n_plain) < 4000, "plain EM must actually converge for this test"
    accel_step = squarem(em_step_stats, _project_params)
    state, _, n_cycles, _ = run_em_loop(
        accel_step, squarem_state(params), args, tol, 4000
    )
    accel = state.params
    ll_plain = _loglik(plain, x)
    ll_accel = _loglik(accel, x)
    # both at the same maximum: accelerated must not be below plain beyond
    # the convergence tolerance's own slack
    assert ll_accel >= ll_plain - 1e-3 * (1 + abs(ll_plain))
    # the DFM is identified only up to an invertible factor transform
    # (lam -> lam G^-1, Q -> G Q G'), so compare the scale-invariant
    # common-component covariance lam Q lam' and the idiosyncratic R
    cc_p = np.asarray(plain.lam @ plain.Q @ plain.lam.T)
    cc_a = np.asarray(accel.lam @ accel.Q @ accel.lam.T)
    scale = np.abs(cc_p).max()
    assert np.allclose(cc_a, cc_p, atol=5e-2 * scale), np.abs(cc_a - cc_p).max()
    assert np.allclose(
        np.asarray(accel.R), np.asarray(plain.R), rtol=8e-2, atol=5e-3
    ), "idiosyncratic variances diverged between plain and accelerated EM"


def test_squarem_uses_fewer_map_evaluations(rng):
    x = _slow_panel(rng)
    params, args = _setup(x, rng)
    tol = 1e-7
    _, _, n_plain, _ = run_em_loop(em_step_stats, params, args, tol, 4000)
    accel_step = squarem(em_step_stats, _project_params)
    _, _, n_cycles, _ = run_em_loop(
        accel_step, squarem_state(params), args, tol, 4000
    )
    # one cycle = three EM-map evaluations; require a real win, not parity
    assert 3 * int(n_cycles) < int(n_plain), (
        f"SQUAREM used {3 * int(n_cycles)} map evals vs plain {int(n_plain)}"
    )


def test_squarem_loglik_path_monotone(rng):
    x = _slow_panel(rng)
    params, args = _setup(x, rng)
    accel_step = squarem(em_step_stats, _project_params)
    _, llpath, n_cycles, _ = run_em_loop(
        accel_step, squarem_state(params), args, 0.0, 25, collect_path=True
    )
    ll = np.asarray(llpath)
    diffs = np.diff(ll)
    # the guard enforces per-cycle monotonicity up to float slack
    assert (diffs >= -1e-6 * (1 + np.abs(ll[:-1]))).all(), diffs.min()


def test_squarem_cache_returns_same_object():
    a = squarem(em_step_stats, _project_params)
    b = squarem(em_step_stats, _project_params)
    assert a is b, "squarem must cache on (step, project) for jit reuse"


def test_estimate_dfm_em_accel_end_to_end(dataset_real):
    from dynamic_factor_models_tpu.models.ssm import estimate_dfm_em

    plain = estimate_dfm_em(
        dataset_real.bpdata, dataset_real.inclcode, 2, 223, max_em_iter=40
    )
    accel = estimate_dfm_em(
        dataset_real.bpdata,
        dataset_real.inclcode,
        2,
        223,
        max_em_iter=40,
        accel="squarem",
    )
    # same data/init: the accelerated run must be at least as advanced
    ll_p = plain.loglik_path[~np.isnan(plain.loglik_path)]
    ll_a = accel.loglik_path[~np.isnan(accel.loglik_path)]
    assert ll_a[-1] >= ll_p[-1] - 1e-3 * (1 + abs(ll_p[-1]))
    assert accel.factors.shape == plain.factors.shape

    with pytest.raises(ValueError, match="accel"):
        estimate_dfm_em(
            dataset_real.bpdata,
            dataset_real.inclcode,
            2,
            223,
            max_em_iter=2,
            accel="anderson",
        )


def test_accel_wiring_ssm_ar(rng):
    """estimate_dfm_em_ar(accel='squarem') reaches at least the plain
    run's loglik on the same synthetic panel and init."""
    from test_ssm_ar import _dgp

    from dynamic_factor_models_tpu.models.dfm import DFMConfig
    from dynamic_factor_models_tpu.models.ssm_ar import estimate_dfm_em_ar

    x, _f, _lam, _e = _dgp()
    cfg = DFMConfig(nfac_u=1, n_factorlag=1)
    inclcode = np.ones(x.shape[1])
    plain = estimate_dfm_em_ar(
        x, inclcode, 0, x.shape[0] - 1, cfg, max_em_iter=30
    )
    accel = estimate_dfm_em_ar(
        x, inclcode, 0, x.shape[0] - 1, cfg, max_em_iter=30, accel="squarem"
    )
    ll_p = plain.loglik_path[~np.isnan(plain.loglik_path)]
    ll_a = accel.loglik_path[~np.isnan(accel.loglik_path)]
    assert ll_a[-1] >= ll_p[-1] - 1e-3 * (1 + abs(ll_p[-1]))
    assert np.abs(np.asarray(accel.params.phi)).max() < 1.0


def test_accel_wiring_mixed_freq():
    """estimate_mixed_freq_dfm(accel='squarem') matches the plain run's
    progress and keeps the aggregation weights untouched."""
    from test_mixed_freq import _dgp

    from dynamic_factor_models_tpu.models.mixed_freq import (
        estimate_mixed_freq_dfm,
    )

    x, is_q, _f, _fa, _xl = _dgp(T=240, Nm=8, Nq=3, seed=3)
    plain = estimate_mixed_freq_dfm(x, is_q, r=1, max_em_iter=25)
    accel = estimate_mixed_freq_dfm(x, is_q, r=1, max_em_iter=25, accel="squarem")
    ll_p = plain.loglik_path[~np.isnan(plain.loglik_path)]
    ll_a = accel.loglik_path[~np.isnan(accel.loglik_path)]
    assert ll_a[-1] >= ll_p[-1] - 1e-3 * (1 + abs(ll_p[-1]))
    assert np.allclose(
        np.asarray(accel.params.agg), np.asarray(plain.params.agg)
    ), "agg is a model constant; extrapolation must not move it"
