"""Forecasting/nowcasting layer: factor-VAR forecasts, diffusion-index series
forecasts, and ragged-edge Kalman nowcasts."""

import jax.numpy as jnp
import numpy as np
import pytest

from dynamic_factor_models_tpu.models.dfm import DFMConfig, estimate_dfm
from dynamic_factor_models_tpu.models.forecast import (
    forecast_factors,
    forecast_series,
    nowcast_ssm,
)
from dynamic_factor_models_tpu.models.ssm import SSMParams, estimate_dfm_em


def _ar1_factor_panel(T=300, N=30, rho=0.8, seed=0):
    rng = np.random.default_rng(seed)
    f = np.zeros((T, 1))
    for t in range(1, T):
        f[t] = rho * f[t - 1] + rng.standard_normal()
    lam = rng.standard_normal((N, 1))
    x = f @ lam.T + 0.3 * rng.standard_normal((T, N))
    return x, f, lam, rho


def test_forecast_factors_ar1_decay():
    x, f, lam, rho = _ar1_factor_panel()
    cfg = DFMConfig(nfac_u=1, n_factorlag=1, n_uarlag=1)
    res = estimate_dfm(x, np.ones(x.shape[1]), 0, x.shape[0] - 1, cfg)
    h = 12
    fpath = np.asarray(forecast_factors(res.var, res.factor, h))
    assert fpath.shape == (h, 1)
    # AR(1) factor forecasts decay geometrically toward the mean at rate
    # ~rho: successive forecast ratios approach the estimated persistence
    dev = fpath[:, 0] - fpath[:, 0][-1]
    b1 = float(res.var.betahat[1, 0])
    assert abs(b1) < 1.0
    ratios = dev[1:6] / dev[:5]
    np.testing.assert_allclose(ratios, b1, atol=0.15)


def test_forecast_series_shapes_and_consistency():
    x, *_ = _ar1_factor_panel(seed=1)
    cfg = DFMConfig(nfac_u=1, n_factorlag=2, n_uarlag=2)
    res = estimate_dfm(x, np.ones(x.shape[1]), 0, x.shape[0] - 1, cfg)
    fc = forecast_series(res, x, 0, x.shape[0] - 1, h=8)
    assert fc.series.shape == (8, x.shape[1])
    np.testing.assert_allclose(
        np.asarray(fc.series), np.asarray(fc.common + fc.idio), rtol=1e-12
    )
    assert np.isfinite(np.asarray(fc.series)).all()
    # forecasts stay within a sane multiple of the sample range
    assert np.abs(np.asarray(fc.series)).max() < 10 * np.abs(x).max()


@pytest.mark.slow
def test_nowcast_fills_ragged_edge():
    x, f, lam, rho = _ar1_factor_panel(T=200, N=20, seed=2)
    cfg = DFMConfig(nfac_u=1, n_factorlag=1, n_uarlag=1)
    # ragged edge: last 2 periods of the second half of series unreleased
    x_ragged = x.copy()
    x_ragged[-2:, 10:] = np.nan
    em = estimate_dfm_em(x_ragged, np.ones(x.shape[1]), 0, x.shape[0] - 1,
                         cfg, max_em_iter=30)
    # nowcast on the standardized panel the EM model was fitted to
    xw = (x_ragged - np.nanmean(x_ragged, axis=0)) / np.asarray(em.stds)
    nc = nowcast_ssm(em.params, xw, h=2)
    assert nc.x_hat.shape == (202, 20)
    filled = np.asarray(nc.filled)
    assert np.isfinite(filled).all()
    # the filled ragged corner correlates with the truth it never saw
    truth = ((x - np.nanmean(x_ragged, axis=0)) / np.asarray(em.stds))[-2:, 10:]
    pred = filled[-2:, 10:]
    corr = np.corrcoef(truth.ravel(), pred.ravel())[0, 1]
    assert corr > 0.5, f"nowcast uninformative: corr={corr}"


def test_forecast_requires_full_results():
    x, *_ = _ar1_factor_panel(T=120, N=10)
    cfg = DFMConfig(nfac_u=1, n_factorlag=1, n_uarlag=1)
    res = estimate_dfm(x, np.ones(x.shape[1]), 0, x.shape[0] - 1, cfg)
    bad = res._replace(var=None)
    with pytest.raises(ValueError, match="estimate_dfm"):
        forecast_series(bad, x, 0, x.shape[0] - 1, h=2)


def test_forecast_nan_for_unestimated_series():
    # a series too short for a loading must forecast NaN, not a silent 0
    x, *_ = _ar1_factor_panel(T=200, N=12, seed=4)
    x[:-20, 5] = np.nan  # only 20 obs < nt_min_loading=40
    cfg = DFMConfig(nfac_u=1, n_factorlag=1, n_uarlag=1)
    res = estimate_dfm(x, np.ones(x.shape[1]), 0, x.shape[0] - 1, cfg)
    assert np.isnan(np.asarray(res.lam)[5]).all()
    fc = forecast_series(res, x, 0, x.shape[0] - 1, h=4)
    s = np.asarray(fc.series)
    assert np.isnan(s[:, 5]).all()
    other = np.delete(s, 5, axis=1)
    assert np.isfinite(other).all()


def test_forecast_factors_rejects_noconst_var():
    from dynamic_factor_models_tpu.models.var import estimate_var

    x, f, _, _ = _ar1_factor_panel(T=150, N=8)
    var_nc = estimate_var(jnp.asarray(f), 1, 0, f.shape[0] - 1, withconst=False)
    with pytest.raises(ValueError, match="withconst"):
        forecast_factors(var_nc, f, 4)


def test_nowcast_em_original_units():
    # the high-level wrapper standardizes/rescales itself: filled values for
    # a blanked corner land near the raw truth, and observed cells pass through
    x, f, lam, rho = _ar1_factor_panel(T=200, N=20, seed=5)
    x = x * 7.0 + 3.0  # far from standardized units
    from dynamic_factor_models_tpu.models.forecast import nowcast_em

    x_ragged = x.copy()
    x_ragged[-2:, 10:] = np.nan
    cfg = DFMConfig(nfac_u=1, n_factorlag=1, n_uarlag=1)
    em = estimate_dfm_em(x_ragged, np.ones(x.shape[1]), 0, x.shape[0] - 1,
                         cfg, max_em_iter=30)
    nc = nowcast_em(em, x_ragged, np.ones(x.shape[1]), 0, x.shape[0] - 1, h=1)
    filled = np.asarray(nc.filled)
    # observed entries untouched
    obs = np.isfinite(x_ragged)
    np.testing.assert_allclose(filled[obs], x_ragged[obs])
    # blanked corner predicted in raw units, correlated with the truth
    pred, truth = filled[-2:, 10:].ravel(), x[-2:, 10:].ravel()
    assert np.corrcoef(pred, truth)[0, 1] > 0.5
    assert abs(np.mean(pred) - np.mean(truth)) < 5.0  # right scale, not z-units


@pytest.mark.slow
def test_forecast_ragged_edge_discounts_release_gap():
    # a series with a 3-period release delay: the AR(1) idio forecast must be
    # the conditional expectation coef^(d+1) * e_last — the last observed
    # residual iterated through the 3 missing periods plus the forecast step —
    # not coef * e_last at full weight, and not a fabricated zero
    x, *_ = _ar1_factor_panel(T=200, N=10, seed=6)
    x[-3:, 4] = np.nan
    cfg = DFMConfig(nfac_u=1, n_factorlag=1, n_uarlag=1)
    res = estimate_dfm(x, np.ones(x.shape[1]), 0, x.shape[0] - 1, cfg)
    fc = forecast_series(res, x, 0, x.shape[0] - 1, h=1)
    lam = np.asarray(res.lam)[4]
    const = float(np.asarray(res.lam_const)[4])
    f_last = np.asarray(res.factor)[196]  # last row where series 4 observed
    e_last = x[196, 4] - (f_last @ lam + const)
    c = float(np.asarray(res.uar_coef)[4, 0])
    np.testing.assert_allclose(float(np.asarray(fc.idio)[0, 4]), c**4 * e_last,
                               rtol=1e-8)
