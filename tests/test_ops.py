"""Property tests for the masked kernel layer (SURVEY.md section 4):
OLS vs closed form, PCA orthogonality/score equivalence, HAC PSD-ness,
lagmat shapes, masked-vs-dropped equivalence.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from dynamic_factor_models_tpu import ops


@pytest.fixture()
def xy(rng):
    T, k = 120, 3
    X = rng.standard_normal((T, k))
    beta = np.array([1.0, -2.0, 0.5])
    y = X @ beta + 0.1 * rng.standard_normal(T)
    return X, y, beta


def test_ols_matches_lstsq(xy):
    X, y, _ = xy
    b, e = ops.ols(jnp.asarray(y), jnp.asarray(X))
    b_np = np.linalg.lstsq(X, y, rcond=None)[0]
    np.testing.assert_allclose(np.asarray(b), b_np, atol=1e-10)
    np.testing.assert_allclose(np.asarray(e), y - X @ b_np, atol=1e-10)


def test_ols_masked_equals_dropped_rows(xy, rng):
    X, y, _ = xy
    miss = rng.random(len(y)) < 0.3
    y_nan = y.copy()
    y_nan[miss] = np.nan
    w = ~miss
    b_m, e_m = ops.ols_masked(jnp.asarray(y_nan), jnp.asarray(X), jnp.asarray(w))
    b_d = np.linalg.lstsq(X[w], y[w], rcond=None)[0]
    np.testing.assert_allclose(np.asarray(b_m), b_d, atol=1e-10)
    assert np.isnan(np.asarray(e_m)[miss]).all()
    np.testing.assert_allclose(np.asarray(e_m)[w], y[w] - X[w] @ b_d, atol=1e-10)


def test_ols_batched_series_equals_loop(rng):
    T, k, N = 80, 4, 7
    X = rng.standard_normal((T, k))
    Y = rng.standard_normal((T, N))
    W = (rng.random((T, N)) > 0.25).astype(float)
    Y_nan = np.where(W.astype(bool), Y, np.nan)
    B, E = ops.ols_batched_series(jnp.asarray(Y_nan), jnp.asarray(X), jnp.asarray(W))
    for i in range(N):
        w = W[:, i].astype(bool)
        b_ref = np.linalg.lstsq(X[w], Y[w, i], rcond=None)[0]
        np.testing.assert_allclose(np.asarray(B)[:, i], b_ref, atol=1e-9)


def test_rank_deficient_min_norm(rng):
    # more regressors than observations: pinv path returns min-norm solution
    T, k = 10, 20
    X = rng.standard_normal((T, k))
    y = rng.standard_normal(T)
    b, _ = ops.ols(jnp.asarray(y), jnp.asarray(X))
    b_np = np.linalg.lstsq(X, y, rcond=None)[0]
    np.testing.assert_allclose(np.asarray(b), b_np, atol=1e-8)


def test_pca_score(rng):
    X = rng.standard_normal((60, 12))
    s = np.asarray(ops.pca_score(jnp.asarray(X), 3))
    # scores equal X V; columns orthogonal with squared norms = singular values^2
    _, sv, Vt = np.linalg.svd(X, full_matrices=False)
    ref = X @ Vt[:3].T
    # sign freedom per column
    for j in range(3):
        assert np.allclose(s[:, j], ref[:, j], atol=1e-8) or np.allclose(
            s[:, j], -ref[:, j], atol=1e-8
        )
    G = s.T @ s
    np.testing.assert_allclose(G, np.diag(sv[:3] ** 2), atol=1e-8)


def test_standardize_matches_reference_convention(rng):
    x = rng.standard_normal((50, 4))
    x[rng.random((50, 4)) < 0.2] = np.nan
    out, std = ops.standardize_data(jnp.asarray(x))
    out = np.asarray(out)
    for j in range(4):
        col = x[:, j]
        m = ~np.isnan(col)
        n = m.sum()
        mu = col[m].mean()
        sd = col[m].std(ddof=1) * np.sqrt((n - 1) / n)  # population-std quirk
        np.testing.assert_allclose(out[m, j], (col[m] - mu) / sd, atol=1e-10)
        np.testing.assert_allclose(float(std[j]), sd, atol=1e-12)
        # standardized column has mean 0 over observed entries
        assert abs(out[m, j].mean()) < 1e-10


def test_lagmat_shapes_and_padding():
    X = jnp.arange(1.0, 11.0).reshape(10, 1)
    L = np.asarray(ops.lagmat(X, [1, 3]))
    assert L.shape == (10, 2)
    assert np.isnan(L[0, 0]) and np.isnan(L[:3, 1]).all()
    np.testing.assert_allclose(L[1:, 0], np.arange(1.0, 10.0))
    np.testing.assert_allclose(L[3:, 1], np.arange(1.0, 8.0))


def test_uar_recovers_ar1(rng):
    T = 2000
    y = np.zeros(T)
    eps = rng.standard_normal(T)
    for t in range(1, T):
        y[t] = 0.7 * y[t - 1] + eps[t]
    coef, ser = ops.uar(jnp.asarray(y), 2)
    assert abs(float(coef[0]) - 0.7) < 0.05
    assert abs(float(ser) - 1.0) < 0.05


def test_hac_psd_and_matches_white(rng):
    T, k = 150, 3
    X = rng.standard_normal((T, k))
    u = rng.standard_normal(T)
    vbeta, se = ops.hac(jnp.asarray(u), jnp.asarray(X), 6)
    ev = np.linalg.eigvalsh(np.asarray(vbeta))
    assert ev.min() > -1e-10  # PSD
    # q=0 equals the White sandwich
    v0, _ = ops.hac(jnp.asarray(u), jnp.asarray(X), 0)
    z = X * u[:, None]
    XXinv = np.linalg.inv(X.T @ X)
    white = XXinv @ (z.T @ z) @ XXinv
    np.testing.assert_allclose(np.asarray(v0), white, atol=1e-10)


def test_chow_detects_break(rng):
    T = 200
    X = np.ones((T, 1))
    y = np.concatenate([rng.standard_normal(100), 5 + rng.standard_normal(100)])
    stat_break = float(ops.compute_chow(jnp.asarray(y), jnp.asarray(X), 0, 100))
    y_nobreak = rng.standard_normal(T)
    stat_none = float(ops.compute_chow(jnp.asarray(y_nobreak), jnp.asarray(X), 0, 100))
    assert stat_break > 100 * stat_none


def test_qlr_max_over_breaks(rng):
    T = 120
    X = np.ones((T, 1))
    y = np.concatenate([np.zeros(70), 3 * np.ones(50)]) + 0.5 * rng.standard_normal(T)
    lm, lmr = ops.compute_qlr(jnp.asarray(y), jnp.asarray(X), 0.15, 4)
    assert float(lm) > 10 and float(lmr) > 10


def test_bw_weight_matches_reference_formula():
    B = 100
    w = np.asarray(ops.compute_bw_weight(B))
    assert w.shape == (2 * B + 1,)
    np.testing.assert_allclose(w.sum(), 1.0, atol=1e-12)
    raw = np.array([(1 - (abs(i) / B) ** 2) ** 2 for i in range(-B, B + 1)])
    np.testing.assert_allclose(w, raw / raw.sum(), atol=1e-12)


def test_gain_of_identity_filter():
    w = jnp.zeros(21).at[10].set(1.0)  # delta at lag 0
    lam = jnp.linspace(0.0, np.pi, 7)
    np.testing.assert_allclose(np.asarray(ops.compute_gain(w, lam)), 1.0, atol=1e-12)


def test_gain_ma_lowpass():
    w = ops.ma_weight(100, 40)
    g0 = float(ops.compute_gain(w, jnp.array([0.0]))[0])
    gpi = float(ops.compute_gain(w, jnp.array([np.pi]))[0])
    assert abs(g0 - 1.0) < 1e-12 and gpi < 0.05


def test_compact():
    x = jnp.array([np.nan, 1.0, np.nan, 2.0, 3.0])
    vals, valid = ops.compact(x, ops.mask_of(x))
    np.testing.assert_allclose(np.asarray(vals)[:3], [1.0, 2.0, 3.0])
    assert np.asarray(valid).sum() == 3


def test_virtual_cpu_mesh_available():
    """The 8-device virtual CPU mesh must exist for sharding tests."""
    import jax

    assert len(jax.devices()) == 8


def test_standardize_np_twin_matches_jax(rng):
    """standardize_data_np / pca_score_np (host-side batch prep) must stay
    in sync with the jitted kernels they mirror."""
    import jax.numpy as jnp

    from dynamic_factor_models_tpu.ops.linalg import (
        pca_score,
        pca_score_np,
        standardize_data,
        standardize_data_np,
    )

    x = rng.standard_normal((50, 7))
    x[rng.random((50, 7)) < 0.15] = np.nan
    out_j, std_j = standardize_data(jnp.asarray(x))
    xz_n, m_n, std_n = standardize_data_np(x)
    np.testing.assert_allclose(np.nan_to_num(np.asarray(out_j)), xz_n, atol=1e-12)
    np.testing.assert_allclose(np.asarray(std_j), std_n, atol=1e-12)
    xb = np.nan_to_num(x)
    s_j = np.asarray(pca_score(jnp.asarray(xb), 3))
    s_n = pca_score_np(xb, 3)
    # scores agree up to per-component sign
    for k in range(3):
        sgn = np.sign(s_j[:, k] @ s_n[:, k]) or 1.0
        np.testing.assert_allclose(s_j[:, k], sgn * s_n[:, k], atol=1e-8)


def test_varimax_recovers_simple_structure():
    from dynamic_factor_models_tpu.ops.linalg import varimax

    rng_local = np.random.default_rng(7)  # own stream: the shared session
    # fixture's state depends on test order
    lam_true = np.zeros((20, 2))
    lam_true[:10, 0] = 1.0
    lam_true[10:, 1] = 1.0
    lam_true += 0.05 * rng_local.standard_normal((20, 2))
    c = np.cos(np.pi / 4)
    q = np.array([[c, -c], [c, c]])  # 45 degrees: maximally mixed blocks
    lam_rot, R = varimax(jnp.asarray(lam_true @ q))
    R = np.asarray(R)
    assert np.allclose(R.T @ R, np.eye(2), atol=1e-10)
    L = np.asarray(lam_rot)

    def vscore(M):
        return (M**2).var(axis=0).sum()

    assert vscore(L) > vscore(lam_true @ q) + 0.1
    # each rotated factor loads on exactly one block (up to sign/order)
    top = np.sort(np.abs(L[:10]).mean(axis=0))
    bot = np.sort(np.abs(L[10:]).mean(axis=0))
    assert top[0] < 0.15 < 0.85 < top[1]
    assert bot[0] < 0.15 < 0.85 < bot[1]


def test_varimax_r1_identity():
    from dynamic_factor_models_tpu.ops.linalg import varimax

    lam = jnp.asarray(np.random.default_rng(1).standard_normal((8, 1)))
    out, R = varimax(lam)
    assert np.allclose(np.asarray(out), np.asarray(lam))
    assert float(R[0, 0]) == 1.0
