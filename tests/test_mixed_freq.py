"""Mixed-frequency DFM: monthly factors + quarterly lag-aggregate series."""

import numpy as np
import pytest

from dynamic_factor_models_tpu.models.mixed_freq import (
    _MM_WEIGHTS,
    estimate_mixed_freq_dfm,
)


def _dgp(T=360, Nm=12, Nq=4, seed=0):
    rng = np.random.default_rng(seed)
    f = np.zeros(T)
    for t in range(1, T):
        f[t] = 0.8 * f[t - 1] + rng.standard_normal()
    lam_m = rng.standard_normal(Nm)
    # quarterly loadings bounded away from 0 so every quarterly series
    # actually carries factor signal (a ~0 loading makes its "latent monthly
    # path" pure noise and the nowcast check meaningless)
    draws = rng.standard_normal(Nq)
    lam_q = np.sign(draws) * (0.5 + np.abs(draws))
    x_m = np.outer(f, lam_m) + 0.5 * rng.standard_normal((T, Nm))
    # quarterly series: Mariano-Murasawa aggregate of the monthly factor,
    # observed in quarter-end months only
    f_agg = np.full(T, np.nan)
    for t in range(4, T):
        f_agg[t] = _MM_WEIGHTS @ f[t - 4 : t + 1][::-1]
    x_q_latent = np.outer(f_agg, lam_q) + 0.4 * rng.standard_normal((T, Nq))
    x_q = np.full((T, Nq), np.nan)
    qe = np.arange(5, T, 3)  # quarter-end months
    x_q[qe] = x_q_latent[qe]
    x = np.hstack([x_m, x_q])
    is_q = np.array([False] * Nm + [True] * Nq)
    return x, is_q, f, f_agg, x_q_latent


def test_mixed_freq_recovers_monthly_factor():
    x, is_q, f, f_agg, _ = _dgp()
    res = estimate_mixed_freq_dfm(x, is_q, r=1, p=5, max_em_iter=50)
    lls = res.loglik_path
    assert np.isfinite(lls).all()
    assert (np.diff(lls) > -1e-6 * np.abs(lls[:-1])).all(), np.diff(lls).min()
    # the MONTHLY factor is recovered from mixed-frequency observations
    corr = abs(np.corrcoef(np.asarray(res.factors[:, 0]), f)[0, 1])
    assert corr > 0.95, corr


def test_mixed_freq_nowcasts_intra_quarter_months():
    # the model's smoothed value of a quarterly series in months where it is
    # NEVER observed must track the true latent monthly aggregate
    x, is_q, f, f_agg, x_q_latent = _dgp(seed=2)
    res = estimate_mixed_freq_dfm(x, is_q, r=1, p=5, max_em_iter=50)
    Nm = (~is_q).sum()
    x_hat = np.asarray(res.x_hat)  # standardized units
    # standardize the latent truth with the model's own convention
    qcol = Nm  # first quarterly series
    mu, sd = float(res.means[qcol]), float(res.stds[qcol])
    truth = (x_q_latent[:, 0] - mu) / sd
    observed = ~np.isnan(x[:, qcol])
    hidden = ~observed
    hidden[:5] = False  # aggregation needs 5 lags
    corr = np.corrcoef(x_hat[hidden, qcol], truth[hidden])[0, 1]
    assert corr > 0.8, f"intra-quarter nowcast weak: corr={corr}"


def test_mixed_freq_validations():
    import pytest

    x = np.random.default_rng(0).standard_normal((40, 4))
    with pytest.raises(ValueError, match=">= 5"):
        estimate_mixed_freq_dfm(x, [False] * 4, r=1, p=3)
    with pytest.raises(ValueError, match="one flag per column"):
        estimate_mixed_freq_dfm(x, [False] * 3, r=1, p=5)


@pytest.mark.slow
def test_mixed_freq_real_data_nowcast():
    """Fit the mixed-frequency DFM on the REAL Stock-Watson monthly panel
    (io.readin_data_monthly: monthly transforms + quarter-end placement,
    VERDICT r1 item 6) and nowcast held-out GDP growth quarters.

    Every 7th observed quarterly GDPC96 value (31 quarters spread over
    1959-2014) is masked before fitting; the model's smoothed quarter-end
    values must beat the unconditional (zero in standardized units)
    prediction and correlate with the truth.  Measured: RMSE ratio ~0.80,
    corr ~0.70 (r=2).
    """
    from dynamic_factor_models_tpu.io.cache import cached_monthly_dataset

    ds = cached_monthly_dataset("All")
    # timely monthly block: well-observed activity/employment series + GDP
    full_m = (~ds.is_quarterly) & (
        np.isfinite(ds.data).sum(axis=0) > 600
    ) & (ds.inclcode == 1)
    cols = np.nonzero(full_m)[0][:40].tolist()
    gdp = ds.names.index("GDPC96")
    cols.append(gdp)
    x = ds.data[:, cols].copy()
    is_q = ds.is_quarterly[cols]
    gdp_col = len(cols) - 1

    observed = np.isfinite(x[:, gdp_col])
    heldout_rows = np.nonzero(observed)[0][10::7]
    truth_raw = x[heldout_rows, gdp_col].copy()
    x[heldout_rows, gdp_col] = np.nan

    res = estimate_mixed_freq_dfm(x, is_q, r=2, p=5, max_em_iter=40)
    assert np.isfinite(res.loglik_path).all()
    mu, sd = float(res.means[gdp_col]), float(res.stds[gdp_col])
    truth = (truth_raw - mu) / sd
    pred = np.asarray(res.x_hat)[heldout_rows, gdp_col]
    rmse_model = float(np.sqrt(np.mean((pred - truth) ** 2)))
    rmse_uncond = float(np.sqrt(np.mean(truth**2)))
    assert rmse_model < 0.9 * rmse_uncond, (rmse_model, rmse_uncond)
    corr = np.corrcoef(pred, truth)[0, 1]
    assert corr > 0.55, corr
