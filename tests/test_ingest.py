"""Ingest-layer tests: panel shapes, schema facts, and transform semantics.

Golden facts from the reference (SURVEY.md sections 2.1, 6): 224 quarters,
148 monthly + 85 quarterly source series, 207 selected columns for :All,
calendar 1959Q1-2014Q4.
"""

import numpy as np
import pytest

from dynamic_factor_models_tpu.io import find_row_number
from dynamic_factor_models_tpu.io.ingest import _adjust_outlier, _biweight_trend, _transform


def test_panel_dimensions(dataset_real, dataset_all):
    assert dataset_real.bpdata.shape[0] == 224
    assert dataset_all.bpdata.shape == (224, 207)
    assert dataset_real.bpdata.shape[1] == 86
    assert int((dataset_real.inclcode == 1).sum()) == 58
    assert int((dataset_all.inclcode == 1).sum()) == 139


def test_calendar(dataset_real):
    assert dataset_real.calds[0] == (1959, 1)
    assert dataset_real.calds[-1] == (2014, 4)
    assert find_row_number((1959, 3), dataset_real.calds) == 2
    assert find_row_number((2014, 4), dataset_real.calds) == 223
    np.testing.assert_allclose(dataset_real.calvec[:4], [1959.0, 1959.25, 1959.5, 1959.75])


def test_catcode_sorted(dataset_real):
    cc = dataset_real.bpcatcode
    assert np.all(np.diff(cc) >= 0)


def test_detrend_consistency(dataset_real):
    # bpdata + trend == unfiltered wherever both observed
    s = dataset_real.bpdata + dataset_real.bpdata_trend
    m = ~np.isnan(s)
    np.testing.assert_allclose(s[m], dataset_real.bpdata_unfiltered[m], atol=1e-10)


def test_gdp_series_present(dataset_real, dataset_all):
    for ds in (dataset_real, dataset_all):
        assert "GDPC96" in ds.bpnamevec
    for name in ("WPU0561", "MCOILWTICO", "MCOILBRENTEU", "RAC_IMP", "FEDFUNDS"):
        assert name in dataset_all.bpnamevec


def test_transform_codes():
    x = np.array([1.0, 2.0, 4.0, 8.0])
    np.testing.assert_allclose(_transform(x, 1), x)
    d1 = _transform(x, 2)
    assert np.isnan(d1[0])
    np.testing.assert_allclose(d1[1:], [1, 2, 4])
    d2 = _transform(x, 3)
    assert np.isnan(d2[:2]).all()
    np.testing.assert_allclose(d2[2:], [1, 2])
    np.testing.assert_allclose(_transform(x, 4), np.log(x))
    np.testing.assert_allclose(_transform(x, 5)[1:], np.diff(np.log(x)))


def test_outlier_one_sided_median():
    x = np.sin(np.arange(41.0))
    x[20] = 50.0
    y = x.copy()
    _adjust_outlier(y, 1, 4)
    assert y[20] != 50.0
    assert abs(y[20]) <= np.nanmax(np.abs(np.delete(x, 20)))
    # untouched elsewhere
    np.testing.assert_allclose(np.delete(y, 20), np.delete(x, 20))


def test_outlier_missing_replacement():
    x = np.sin(np.arange(41.0))
    x[20] = 50.0
    _adjust_outlier(x, 2, 0)
    assert np.isnan(x[20])


def test_biweight_trend_constant():
    # a constant series has itself as trend
    data = np.ones((50, 1))
    trend = _biweight_trend(data, 10.0)
    np.testing.assert_allclose(trend, 1.0)


def test_biweight_trend_missing_aware():
    data = np.ones((50, 2))
    data[10:15, 0] = np.nan
    trend = _biweight_trend(data, 10.0)
    assert np.isnan(trend[10:15, 0]).all()
    m = ~np.isnan(trend[:, 0])
    np.testing.assert_allclose(trend[m, 0], 1.0)


def test_rebuild_from_xlsx_matches_cache(dataset_real):
    """Exercise the full xlsx->panel pipeline (not the npz cache) end to end."""
    from dynamic_factor_models_tpu.io import BiWeight, MonthlyData, QuarterlyData, readin_data

    md = MonthlyData.from_range((1959, 1), (2014, 12), 148)
    qd = QuarterlyData.from_range((1959, 1), (2014, 4), 85)
    fresh = readin_data(md, qd, BiWeight(100.0), "Real")
    # detrended panel: 1e-14-scale summation-order noise is allowed between
    # the native banded biweight kernel and the NumPy matmul fallback
    np.testing.assert_allclose(
        fresh.bpdata, dataset_real.bpdata, rtol=1e-10, atol=1e-12, equal_nan=True
    )
    # pre-detrend pipeline is exactly deterministic
    np.testing.assert_array_equal(fresh.bpdata_raw, dataset_real.bpdata_raw)
    assert fresh.bpnamevec == list(dataset_real.bpnamevec)


def test_outlier_adjustment_idempotent(rng):
    # SURVEY.md section 4: applying the outlier rule to already-adjusted data
    # must be a no-op (all replacement strategies clamp inside the IQR fence)
    from dynamic_factor_models_tpu.io.ingest import _adjust_outlier

    for io_method in range(5):
        x = rng.standard_normal(200)
        x[[10, 50, 90]] = [40.0, -35.0, 60.0]
        once = x.copy()
        _adjust_outlier(once, 1, io_method)
        twice = once.copy()
        _adjust_outlier(twice, 1, io_method)
        np.testing.assert_array_equal(once, twice, err_msg=f"io_method={io_method}")


def test_monthly_frequency_ingest():
    """readin_data_monthly: monthly panel with quarter-end-placed quarterly
    series (the mixed-frequency DFM's input; replaces readin_functions.jl's
    monthly->quarterly averaging for this path)."""
    import numpy as np

    from dynamic_factor_models_tpu.io.cache import cached_monthly_dataset

    ds = cached_monthly_dataset("All")
    assert ds.data.shape == (672, 207)  # 56 years x 12 months; :All panel
    assert ds.calmds[0] == (1959, 1) and ds.calmds[-1] == (2014, 12)
    months = np.array([m for _, m in ds.calmds])
    qcols = np.nonzero(ds.is_quarterly)[0]
    assert qcols.size > 0
    # quarterly series: NaN everywhere except quarter-end months
    off_quarter = ~np.isin(months, (3, 6, 9, 12))
    assert np.isnan(ds.data[off_quarter][:, qcols]).all()
    gdp = ds.names.index("GDPC96")
    assert ds.is_quarterly[gdp]
    # GDP growth observed in 223 of 224 quarters (one lost to the transform)
    assert np.isfinite(ds.data[:, gdp]).sum() == 223
    # monthly series stay monthly: PAYEMS nearly fully observed
    payems = ds.names.index("PAYEMS")
    assert not ds.is_quarterly[payems]
    assert np.isfinite(ds.data[:, payems]).sum() >= 660
