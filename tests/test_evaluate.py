"""Pseudo-out-of-sample forecast evaluation (models/evaluate.py)."""

import jax.numpy as jnp
import numpy as np
import pytest

from dynamic_factor_models_tpu.models.dfm import DFMConfig
from dynamic_factor_models_tpu.models.evaluate import evaluate_forecasts

CFG = DFMConfig(nfac_u=1, n_factorlag=1, tol=1e-6, max_iter=200)


def _factor_panel(T=260, N=16, seed=0, factor_share=1.0):
    rng = np.random.default_rng(seed)
    f = np.zeros((T, 1))
    for t in range(1, T):
        f[t] = 0.8 * f[t - 1] + rng.standard_normal(1)
    lam = factor_share * rng.uniform(0.8, 1.5, (N, 1))
    x = np.zeros((T, N))
    for t in range(1, T):
        x[t] = lam[:, 0] * f[t, 0] + 0.2 * x[t - 1] + 0.5 * rng.standard_normal(N)
    return x


@pytest.fixture(scope="module")
def horse_race():
    x = _factor_panel()
    return evaluate_forecasts(
        jnp.asarray(x), np.ones(x.shape[1], np.int64), window=120, nfac=1,
        horizons=(1, 2), y_lags=2, step=4, config=CFG,
    )


class TestEvaluateForecasts:
    def test_factors_beat_ar_when_factor_drives_panel(self, horse_race):
        ev = horse_race
        rel = np.asarray(ev.rel_mse)
        assert rel.shape == (2, 16)
        # factor DGP: diffusion-index forecasts beat the AR benchmark for
        # most series at h=1
        assert np.median(rel[0]) < 1.0
        assert (rel[0] < 1.0).mean() > 0.6

    def test_error_bookkeeping(self, horse_race):
        ev = horse_race
        H, W, N = ev.errors_dfm.shape
        assert W == len(ev.origins) and H == len(ev.horizons)
        assert (np.asarray(ev.n_forecasts) > 0).all()
        assert (np.asarray(ev.n_forecasts) <= W).all()
        # RMSE consistency with the stored errors
        e = np.asarray(ev.errors_dfm[0])
        both = np.isfinite(e) & np.isfinite(np.asarray(ev.errors_ar[0]))
        mse = np.where(both, e**2, 0.0).sum(axis=0) / both.sum(axis=0)
        assert np.allclose(np.asarray(ev.rmse_dfm[0]), np.sqrt(mse), atol=1e-10)

    def test_pure_noise_panel_gives_no_factor_edge(self):
        """On white noise the factor adds nothing: rel_mse ~ 1 on average
        (within sampling noise), never systematically below."""
        rng = np.random.default_rng(1)
        x = rng.standard_normal((220, 12))
        ev = evaluate_forecasts(
            jnp.asarray(x), np.ones(12, np.int64), window=120, nfac=1,
            horizons=(1,), y_lags=2, step=8, config=CFG,
        )
        rel = np.asarray(ev.rel_mse[0])
        assert 0.9 < np.median(rel) < 1.25

    def test_missing_values_handled(self):
        x = _factor_panel(T=220, N=10, seed=2)
        x[np.random.default_rng(3).random(x.shape) < 0.04] = np.nan
        x[:, :5] = np.nan_to_num(x[:, :5])  # balanced block for PCA init
        ev = evaluate_forecasts(
            jnp.asarray(x), np.ones(10, np.int64), window=120, nfac=1,
            horizons=(1,), y_lags=2, step=8, config=CFG,
        )
        assert np.isfinite(np.asarray(ev.rmse_dfm)).all()
        assert (np.asarray(ev.n_forecasts) > 0).all()

    def test_dead_series_reports_nan_not_zero(self):
        """A series with no realized values in the eval sample must report
        NaN RMSE/rel_mse, not a spurious 0 (which would read as a factor
        win in (rel_mse < 1) aggregates)."""
        x = _factor_panel(T=220, N=8, seed=4)
        x[60:, 3] = np.nan  # series 3 discontinued before any origin
        ev = evaluate_forecasts(
            jnp.asarray(x), np.ones(8, np.int64), window=120, nfac=1,
            horizons=(1,), y_lags=2, step=8, config=CFG,
        )
        assert int(ev.n_forecasts[0, 3]) == 0
        assert np.isnan(float(ev.rel_mse[0, 3]))
        assert np.isnan(float(ev.rmse_dfm[0, 3]))
        others = np.delete(np.asarray(ev.rel_mse[0]), 3)
        assert np.isfinite(others).all()

    def test_window_validation(self):
        x = _factor_panel(T=100)
        with pytest.raises(ValueError, match="does not fit"):
            evaluate_forecasts(
                jnp.asarray(x), np.ones(x.shape[1], np.int64), window=99,
                nfac=1, horizons=(4,), config=CFG,
            )


class TestDieboldMariano:
    def test_dm_on_horse_race(self, horse_race):
        from dynamic_factor_models_tpu.models.evaluate import diebold_mariano

        dm = diebold_mariano(horse_race)
        stat, p = np.asarray(dm.stat), np.asarray(dm.pvalue)
        assert stat.shape == p.shape == (2, 16)
        assert np.isfinite(stat).all()
        assert ((p >= 0) & (p <= 1)).all()
        # factor DGP: loss differentials lean negative (DFM better)
        assert np.median(stat[0]) < 0

    def test_dm_identical_forecasts_give_nan_or_zero(self):
        """Degenerate case: identical errors -> zero differential; the
        statistic must not blow up."""
        from dynamic_factor_models_tpu.models.evaluate import (
            ForecastEvaluation, diebold_mariano,
        )
        import jax.numpy as jnp

        e = jnp.asarray(np.random.default_rng(0).standard_normal((1, 30, 4)))
        ev = ForecastEvaluation(
            origins=np.arange(30), horizons=np.array([1]),
            errors_dfm=e, errors_ar=e,
            rmse_dfm=None, rmse_ar=None, rel_mse=None,
            n_forecasts=jnp.full((1, 4), 30),
        )
        dm = diebold_mariano(ev)
        assert np.allclose(np.asarray(dm.stat), 0.0)
