"""Compute-observability layer (PR 17): per-kernel roofline ledger,
mesh comm accounting, serving occupancy attribution, and the fault-dump
flight recorder.

Acceptance pins:

1. ledger cumulative FLOPs for a reference-scale `estimate_dfm_em`
   match the direct `compiled.cost_analysis()` sum within 1%;
2. ledger gauges flow into the OpenMetrics export and `summarize`
   renders the GFLOP/MFU%/occupancy columns with "-" fallbacks for
   pre-PR-17 (mixed-vintage) sink lines, including a rotated
   ``<path>.1`` predecessor;
3. the comm registry reproduces PR 15's hand-derived
   ``dcn_payload_bytes_per_iter = 15360`` on the 2-process proxy
   (T=256, q=4, f32) as a measured trace-time entry;
4. ``DFM_FAULTS=nan_estep@3`` and a serving ``engine_crash@n`` drill
   each produce a flight bundle (trigger event, preceding ring, kernel
   ledger snapshot); a clean disabled-telemetry run allocates NO ring
   and writes NO bundle.
"""

import glob
import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dynamic_factor_models_tpu.models.dfm import DFMConfig
from dynamic_factor_models_tpu.models.ssm import estimate_dfm_em
from dynamic_factor_models_tpu.utils import compile as cc
from dynamic_factor_models_tpu.utils import faults, flight, roofline, telemetry

pytestmark = pytest.mark.obs


@pytest.fixture(autouse=True)
def _obs_clean():
    """Telemetry/flight/ledger state is process-global: start and leave
    every test clean so drills cannot bleed into other modules.
    `_explicit_enabled` goes back to None (not the sticky False that
    `disable()` sets) so later env-driven tests still see DFM_TELEMETRY."""
    telemetry.disable()
    flight.reset()
    roofline.reset()
    yield
    telemetry.disable()
    telemetry._explicit_enabled = None
    flight.reset()
    roofline.reset()


def _panel(T, N, seed=0, dtype=float):
    rng = np.random.default_rng(seed)
    f = rng.standard_normal((T, 4)).cumsum(0) * 0.1
    lam = rng.standard_normal((N, 4))
    return (f @ lam.T + 0.5 * rng.standard_normal((T, N))).astype(dtype)


# ---------------------------------------------------------------------------
# 1. roofline ledger
# ---------------------------------------------------------------------------


def test_ledger_flops_match_cost_analysis_within_1pct():
    """Acceptance pin 1: kernel ledger x run counters == the direct
    cost_analysis sum over the executables the run dispatched."""
    cc.reset_counters()
    spec = cc.CompileSpec(
        T=224, N=139, dtype=str(np.dtype(float)),
        kernels=("em_loop_guarded",), max_em_iter=8,
    )
    cc.precompile(spec, warmup=False)
    assert "em_loop_guarded" in roofline.kernel_ledger()
    cc.reset_counters()  # run counts must come from the estimate only

    T, N = 224, 139
    x = _panel(T, N, seed=1)
    cfg = DFMConfig(nfac_u=4, tol=1e-5, max_iter=300)
    estimate_dfm_em(x, np.ones(N), 0, T - 1, cfg, max_em_iter=8,
                    bucket=True)
    counts = cc.counters()
    assert counts["em_loop_guarded"]["runs"] >= 1
    assert counts["em_loop_guarded"]["aot_hits"] >= 1

    snap = roofline.ledger_snapshot()
    assert snap["flops_total"] > 0 and snap["bytes_total"] > 0
    direct = 0.0
    for (reg, _statics, _sig), compiled in cc._AOT.items():
        runs = counts.get(reg, {}).get("runs", 0)
        if runs:
            flops, _ = roofline.compiled_cost(compiled)
            direct += (flops or 0.0) * runs
    assert direct > 0
    assert abs(snap["flops_total"] - direct) <= 0.01 * direct
    # derived fields are present and provenance-labeled
    assert snap["intensity_flops_per_byte"] > 0
    assert snap["mfu_peak_source"] in (
        "unmeasured", "measured_f32_gemm", "v5e_bf16_datasheet"
    )
    assert isinstance(snap["flop_proxy"], bool)


def test_run_record_carries_roofline_fields(tmp_path):
    """RunRecord exit stamps per-run roofline fields derived from its
    own counters_delta (no extra device work)."""
    cc.reset_counters()
    spec = cc.CompileSpec(
        T=64, N=12, r=2, p=1, dtype=str(np.dtype(float)), bucket=False,
        kernels=("em_loop_guarded",), max_em_iter=6,
    )
    cc.precompile(spec, warmup=False)
    sink = str(tmp_path / "t.jsonl")
    telemetry.enable(sink=sink)
    try:
        x = _panel(64, 12, seed=2)
        estimate_dfm_em(x, np.ones(12), 0, 63, DFMConfig(nfac_u=2),
                        max_em_iter=6, tol=0.0, bucket=False)
    finally:
        telemetry.disable()
    recs = [json.loads(l) for l in open(sink) if l.strip()]
    run = [r for r in recs if r.get("entry") == "estimate_dfm_em"][-1]
    rf = run["roofline"]
    assert rf["flops_total"] > 0
    assert rf["runs_total"] >= 1
    assert "mfu_peak_source" in rf and "flop_proxy" in rf
    # per_kernel is ledger detail, not per-run payload
    assert "per_kernel" not in rf


def test_run_fields_wall_fallback_and_empty():
    class _Fake:
        def cost_analysis(self):
            return [{"flops": 100.0, "bytes accessed": 50.0}]

    roofline.record_kernel("k1", "k1", _Fake())
    out = roofline.run_fields(
        {"k1": {"runs": 2, "run_s": 0.0}}, wall_s=0.5
    )
    assert out["flops_total"] == 200.0
    assert out["run_s_total"] == 0.5 and out["run_s_source"] == "wall"
    # no ledgered kernel ran -> no roofline stamp at all
    assert roofline.run_fields({}, 1.0) == {}
    assert roofline.run_fields({"other": {"runs": 3}}, 1.0) == {}


def test_ledger_gauges_reach_openmetrics():
    class _Fake:
        def cost_analysis(self):
            return [{"flops": 1.0e9, "bytes accessed": 2.0e8}]

    roofline.record_kernel("em_loop_guarded", "em_loop_guarded", _Fake())
    roofline.record_collective("site.a", "dcn", 15360, hops=1)
    roofline.publish_gauges()
    om = telemetry.export_openmetrics()
    assert "roofline_device_flops_total" in om
    assert "roofline_device_bytes_total" in om
    assert "roofline_flop_proxy" in om
    assert 'comm_bytes_per_call{axis="dcn"} 15360' in om


# ---------------------------------------------------------------------------
# 2. comm accounting
# ---------------------------------------------------------------------------


@pytest.mark.multidevice
def test_comm_registry_pins_dcn_payload_15360():
    """Acceptance pin 3: PR 15's hand-derived bench field
    `dcn_payload_bytes_per_iter` (T=256, q=r=4: T x (q(q+1)/2 + 1 + q)
    x 4B = 15360) becomes a measured comm-registry entry when the
    hosts=2 sharded step traces."""
    if jax.device_count() < 8:
        pytest.skip("needs the forced 8-device platform")
    from dynamic_factor_models_tpu.models import ssm
    from dynamic_factor_models_tpu.ops.linalg import standardize_data
    from dynamic_factor_models_tpu.ops.masking import fillz, mask_of

    T, N, r = 256, 32, 4
    x = _panel(T, N, seed=3, dtype=np.float32)
    # the PR 15 pin is an f32 payload (x64 test mode would double it)
    xstd = standardize_data(jnp.asarray(x))[0].astype(jnp.float32)
    xz, m = fillz(xstd), mask_of(xstd).astype(xstd.dtype)
    params = ssm.SSMParams(
        lam=jnp.zeros((N, r), xz.dtype).at[:, 0].set(1.0),
        R=jnp.ones(N, xz.dtype),
        A=0.5 * jnp.eye(r, dtype=xz.dtype)[None],
        Q=jnp.eye(r, dtype=xz.dtype),
    )
    stats = ssm.compute_panel_stats(xz, m)._replace(
        tw=jnp.ones(T, xz.dtype)
    )
    ssm._sharded_step_for(8, hosts=2)(params, xz, m, stats)

    comm = roofline.comm_summary()
    assert comm["per_axis"]["dcn"]["bytes_per_call"] == 15360
    dcn = [s for s in comm["sites"] if s["axis"] == "dcn"]
    assert dcn and dcn[0]["collective"] == "psum"
    assert dcn[0]["dtype"] == "float32"
    # the ICI ring carries the same payload over n_ici - 1 = 3 hops
    ici = [s for s in comm["sites"] if s["axis"] == "ici"]
    assert ici and ici[0]["hops"] == 3
    assert (
        comm["per_axis"]["ici"]["link_bytes_per_call"] == 3 * 15360
    )


@pytest.mark.multidevice
def test_mesh_topology_gauges_published():
    if jax.device_count() < 8:
        pytest.skip("needs the forced 8-device platform")
    from dynamic_factor_models_tpu.parallel.mesh import data_mesh

    data_mesh(8, hosts=2)
    g = telemetry.snapshot()["gauges"]
    assert g['mesh.axis_size{axis="dcn"}'] == 2
    assert g['mesh.axis_size{axis="ici"}'] == 4
    assert g["mesh.n_devices"] == 8


@pytest.mark.multidevice
@pytest.mark.timeparallel
def test_timescan_boundary_collective_recorded():
    """The slab-boundary ppermute ladder records its per-call boundary
    bytes and its ceil(log2)+1 round count on the "time" axis."""
    if jax.device_count() < 8:
        pytest.skip("needs the forced 8-device platform")

    T, N, r = 64, 12, 2
    x = _panel(T, N, seed=4)
    cfg = DFMConfig(nfac_u=r)
    estimate_dfm_em(x, np.ones(N), 0, T - 1, cfg, max_em_iter=3,
                    tol=0.0, t_blocks=4)
    rows = [
        s for s in roofline.comm_summary()["sites"]
        if s["site"] == "timescan.block_scan_boundary"
    ]
    assert rows, roofline.comm_summary()["sites"]
    row = rows[0]
    assert row["axis"] == "time"
    assert row["collective"] == "ppermute"
    assert row["bytes_per_call"] > 0
    # n_blocks=4 -> 1 + bit_length(3) = 3 exchange rounds
    assert row["hops"] == 3


# ---------------------------------------------------------------------------
# 3. flight recorder drills
# ---------------------------------------------------------------------------


def _flight_files(d):
    return sorted(glob.glob(os.path.join(str(d), "flight-*.json")))


def test_flight_dump_on_guard_trip_drill(tmp_path, monkeypatch):
    """Acceptance pin 4a: DFM_FAULTS=nan_estep@3 under an enabled sink
    produces ONE bundle carrying the trigger, the preceding ring (with
    the injection breadcrumb), and the kernel-ledger snapshot."""
    fdir = tmp_path / "flight"
    monkeypatch.setenv("DFM_FLIGHT_DIR", str(fdir))
    telemetry.enable(sink=str(tmp_path / "t.jsonl"))
    try:
        x = _panel(64, 12, seed=5)
        with faults.inject("nan_estep@3"):
            estimate_dfm_em(x, np.ones(12), 0, 63, DFMConfig(nfac_u=2),
                            max_em_iter=10, tol=0.0)
    finally:
        telemetry.disable()
    files = _flight_files(fdir)
    assert len(files) == 1, files
    assert "guard_trip" in os.path.basename(files[0])
    bundle = json.load(open(files[0]))
    assert bundle["trigger"]["trigger"] == "guard_trip"
    kinds = [e["kind"] for e in bundle["ring"]]
    assert "fault_injected" in kinds  # the injection preceded the trip
    assert "em_guard.trip" in kinds
    assert "kernel_ledger" in bundle and "counters" in bundle
    assert bundle["counters"].get("faults_injected", 0) >= 1
    assert flight.last_dump_path() == files[0]


def test_flight_dump_on_engine_crash_drill(tmp_path, monkeypatch):
    """Acceptance pin 4b: the serving engine_crash@n kill dumps a
    bundle (forced — a kill must never be throttled away)."""
    from dynamic_factor_models_tpu.serving.engine import ServingEngine
    from dynamic_factor_models_tpu.serving.resilience import RetryPolicy

    fdir = tmp_path / "flight"
    monkeypatch.setenv("DFM_FLIGHT_DIR", str(fdir))
    telemetry.enable(sink=str(tmp_path / "t.jsonl"))
    try:
        rng = np.random.default_rng(6)
        eng = ServingEngine(
            retry_policy=RetryPolicy(max_retries=2, backoff_base_s=0.0),
            max_em_iter=5,
        )
        eng.register("a", _panel(48, 6, seed=6))
        with faults.inject("engine_crash@2"), \
                pytest.raises(faults.SimulatedCrash):
            for _ in range(3):
                eng.handle(
                    {"kind": "tick", "tenant": "a",
                     "x": rng.standard_normal(6)}
                )
    finally:
        telemetry.disable()
    files = _flight_files(fdir)
    assert len(files) == 1 and "engine_crash" in os.path.basename(files[0])
    bundle = json.load(open(files[0]))
    assert bundle["trigger"]["trigger"] == "engine_crash"
    assert bundle["trigger"]["reqno"] == 2
    assert "fault_injected" in [e["kind"] for e in bundle["ring"]]


def test_clean_disabled_run_allocates_no_ring_and_no_dump(
    tmp_path, monkeypatch
):
    """Acceptance pin 4c: with telemetry disabled the clean path makes
    ZERO flight allocations and writes nothing."""
    fdir = tmp_path / "flight"
    monkeypatch.setenv("DFM_FLIGHT_DIR", str(fdir))
    x = _panel(64, 12, seed=7)
    estimate_dfm_em(x, np.ones(12), 0, 63, DFMConfig(nfac_u=2),
                    max_em_iter=4, tol=0.0)
    assert flight._ring is None
    assert not flight.armed()
    assert _flight_files(fdir) == []
    # even an explicit fault drill stays silent while disabled
    with faults.inject("nan_estep@2"):
        estimate_dfm_em(x, np.ones(12), 0, 63, DFMConfig(nfac_u=2),
                        max_em_iter=4, tol=0.0)
    assert flight._ring is None and _flight_files(fdir) == []


def test_flight_dump_throttled_unless_forced(tmp_path, monkeypatch):
    fdir = tmp_path / "flight"
    monkeypatch.setenv("DFM_FLIGHT_DIR", str(fdir))
    telemetry.enable(sink=str(tmp_path / "t.jsonl"))
    try:
        flight.record("ev1", severity="info")
        p1 = flight.dump("first")
        assert p1 and os.path.exists(p1)
        # inside the 5s window: skipped...
        assert flight.dump("second") is None
        # ...unless forced
        p3 = flight.dump("third", force=True)
        assert p3 and p3 != p1
    finally:
        telemetry.disable()


# ---------------------------------------------------------------------------
# 4. serving occupancy
# ---------------------------------------------------------------------------


@pytest.mark.serving
def test_serving_occupancy_gauges_and_phase_hists(tmp_path):
    from dynamic_factor_models_tpu.serving.engine import ServingEngine
    from dynamic_factor_models_tpu.serving.resilience import RetryPolicy

    telemetry.enable(sink=str(tmp_path / "t.jsonl"))
    try:
        rng = np.random.default_rng(8)
        eng = ServingEngine(
            retry_policy=RetryPolicy(max_retries=2, backoff_base_s=0.0),
            max_em_iter=5,
        )
        eng.register("a", _panel(48, 6, seed=8))
        for _ in range(5):
            assert eng.handle(
                {"kind": "tick", "tenant": "a",
                 "x": rng.standard_normal(6)}
            ).ok
        eng.flush_metrics()
        g = telemetry.snapshot()["gauges"]
        assert g.get("serving.occupancy.dispatch_s", 0) > 0
        assert g.get("serving.occupancy.commit_s", 0) > 0
        assert g.get("serving.occupancy.envelope_s", 0) > 0
        om = telemetry.export_openmetrics()
        assert 'phase="dispatch"' in om
        assert "serving_phase_latency_seconds" in om
    finally:
        telemetry.disable()


@pytest.mark.serving
def test_serving_occupancy_off_when_disabled():
    from dynamic_factor_models_tpu.serving.engine import ServingEngine
    from dynamic_factor_models_tpu.serving.resilience import RetryPolicy

    rng = np.random.default_rng(9)
    eng = ServingEngine(
        retry_policy=RetryPolicy(max_retries=2, backoff_base_s=0.0),
        max_em_iter=5,
    )
    eng.register("a", _panel(48, 6, seed=9))
    for _ in range(3):
        assert eng.handle(
            {"kind": "tick", "tenant": "a", "x": rng.standard_normal(6)}
        ).ok
    assert eng._occ_s == {}  # the disabled path never touches a timer
    assert eng._phase_hists == {}


# ---------------------------------------------------------------------------
# 5. summarize: mixed vintage + rotation
# ---------------------------------------------------------------------------


_OLD_LINE = {
    "run_id": "old1", "entry": "estimate_dfm_em", "time_unix": 1.0,
    "n_iter": 5, "wall_s": 0.5, "counters_delta": {},
}
_NEW_LINE = {
    "run_id": "new1", "entry": "estimate_dfm_em", "time_unix": 2.0,
    "n_iter": 5, "wall_s": 0.5, "counters_delta": {},
    "roofline": {
        "flops_total": 5.0e9, "bytes_total": 1.0e9, "runs_total": 1,
        "run_s_total": 0.4, "mfu_pct": 12.34,
        "mfu_peak_source": "measured_f32_gemm", "flop_proxy": True,
    },
}


def test_summarize_mixed_vintage_roofline_columns(tmp_path):
    sink = str(tmp_path / "t.jsonl")
    with open(sink, "w") as f:
        f.write(json.dumps(_OLD_LINE) + "\n")
        f.write(json.dumps(_NEW_LINE) + "\n")
    out = telemetry.summarize(sink)
    assert "GFLOP" in out and "MFU%" in out
    rows = [
        l for l in out.splitlines()
        if "estimate_dfm_em" in l and not l.startswith("estimate")
    ]
    assert len(rows) == 2
    new_row = [l for l in rows if "12.34" in l][0]
    old_row = [l for l in rows if "12.34" not in l][0]
    assert "5.00" in new_row
    # pre-PR-17 line: the new columns degrade to "-", nothing crashes
    assert "5.00" not in old_row and " - " in old_row


def test_summarize_occupancy_column_and_rotated_sink(tmp_path):
    sink = str(tmp_path / "t.jsonl")
    # rotated predecessor: one pre-PR-17 run
    with open(sink + ".1", "w") as f:
        f.write(json.dumps(_OLD_LINE) + "\n")
    serving_line = {
        "run_id": "s1", "entry": "serving", "time_unix": 3.0,
        "wall_s": 0.01, "kind": "tick", "outcome": "ok",
    }
    metrics_line = {
        "entry": "metrics", "time_unix": 4.0, "counters": {},
        "gauges": {
            "serving.occupancy.dispatch_s": 0.6,
            "serving.occupancy.journal_s": 0.2,
            "serving.occupancy.commit_s": 0.1,
            "serving.occupancy.envelope_s": 0.1,
        },
    }
    with open(sink, "w") as f:
        f.write(json.dumps(serving_line) + "\n")
        f.write(json.dumps(metrics_line) + "\n")
    out = telemetry.summarize(sink)
    # both files were read: the rotated old run + the live serving run
    assert "2 record(s)" in out
    assert "occ a/d/p/j/c/e" in out
    # a sink with no admit/prefill gauges (pre-pipeline / pre-PR-20)
    # renders those phases as 0
    srow = [
        l for l in out.splitlines()
        if l.startswith("serving") and "0/60/0/20/10/10" in l
    ]
    assert srow, out
    # the old entry's aggregate row renders "-" in the occupancy column
    erow = [
        l for l in out.splitlines() if l.startswith("estimate_dfm_em")
    ]
    assert erow and "60/0/20/10/10" not in erow[0]
