"""Bounded-memory serving: LRU tenant eviction, continuous tick
batching, and whole-process restart recovery (PR: bounded-memory
serving).

Pinned claims:

1. a resident budget (`resident_tenants` / `resident_bytes`) bounds the
   tenant table: cold tenants are EVICTED through the snapshot +
   write-ahead-journal path and faulted back in on next touch
   BIT-identical to never having been evicted;
2. batched admission (`submit` / `flush_period`) produces per-lane
   FilterStates BITWISE equal to sequential `handle` ticks — including
   non-power-of-two lane counts, where the compile-bucket padding lanes
   are exactly inert;
3. exactly-once across kills: `crash_io@n` killed at EVERY i/o site of
   a tick + eviction workload restarts to a state holding exactly the
   journaled ticks — acked ticks are never dropped, no tick is applied
   twice (replay is idempotent across repeated restarts);
4. a crash BETWEEN `TenantStore.save` and the journal reset leaves a
   stale journal (base_t <= snapshot t) that fault-in SKIPS and
   deletes — never quarantines (satellite: stale-skip regression);
5. `TenantStore.list()` admits only live ``<id>.npz`` snapshots —
   planted ``*.corrupt``, in-flight ``*.npz.tmp.*`` temporaries and
   journal siblings never leak into the id listing;
6. an OPEN circuit breaker survives eviction: the packed breaker state
   rides the snapshot and a faulted-in tenant resumes its cooldown
   instead of silently closing;
7. `engine.recover()` rebuilds the serving set lazily with bounded
   memory; `prewarm` replays the hottest journals through the batched
   dispatch, bit-identical to the pre-kill live states;
8. `telemetry summarize` renders resident / eviction / fault-in columns
   from the cumulative metrics snapshot line, and falls back to "-" for
   sinks written before the metrics layer.
"""

import glob
import os
import shutil

import numpy as np
import pytest

from dynamic_factor_models_tpu.serving.batch import batched_tick_dispatch
from dynamic_factor_models_tpu.serving.engine import ServingEngine
from dynamic_factor_models_tpu.serving.online import online_tick
from dynamic_factor_models_tpu.serving.resilience import RetryPolicy
from dynamic_factor_models_tpu.serving.store import TenantStore, template_state
from dynamic_factor_models_tpu.utils import faults, telemetry

pytestmark = [pytest.mark.serving]

_POLICY = RetryPolicy(max_retries=2, backoff_base_s=0.0)

T, N = 48, 6


def _panel(seed=0):
    rng = np.random.default_rng(seed)
    f = rng.standard_normal((T, 4)).cumsum(0) * 0.1
    lam = rng.standard_normal((N, 4))
    return f @ lam.T + 0.5 * rng.standard_normal((T, N))


def _engine(store_dir=None, **kw):
    kw.setdefault("retry_policy", _POLICY)
    kw.setdefault("max_em_iter", 5)
    return ServingEngine(store_dir=store_dir, **kw)


def _states(eng, ids):
    return {
        tid: (np.asarray(eng._tenants[tid].state.s).copy(),
              int(eng._tenants[tid].state.t))
        for tid in ids if tid in eng._tenants
    }


# ---------------------------------------------------------------------------
# 1. LRU budget + bit-identical fault-in
# ---------------------------------------------------------------------------


def test_budget_bounds_residency_and_fault_in_is_bit_identical(tmp_path):
    rng = np.random.default_rng(3)
    d = str(tmp_path / "store")
    eng = _engine(d, resident_tenants=2)
    ref = _engine()  # no store, no budget: the never-evicted control

    pan = _panel(seed=4)
    for e in (eng, ref):
        e.register("a", pan)
        for tid in ("b", "c", "d"):
            e.register_shared(tid, "a")
    assert len(eng._tenants) <= 2  # registration already enforces
    assert len(ref._tenants) == 4

    rows = [rng.standard_normal(N) for _ in range(12)]
    order = ["a", "b", "c", "d", "a", "c", "b", "d", "d", "a", "b", "c"]
    for tid, row in zip(order, rows):
        r1 = eng.handle({"kind": "tick", "tenant": tid, "x": row})
        r2 = ref.handle({"kind": "tick", "tenant": tid, "x": row})
        assert r1.ok and r2.ok
        assert len(eng._tenants) <= 2

    assert telemetry._counters.get("serving.fault_ins", 0) > 0
    for tid in ("a", "b", "c", "d"):
        budgeted = eng._lookup(tid)
        control = ref._tenants[tid]
        assert int(budgeted.state.t) == int(control.state.t)
        np.testing.assert_array_equal(
            np.asarray(budgeted.state.s), np.asarray(control.state.s)
        )


def test_resident_bytes_budget_and_clean_eviction_is_zero_io(tmp_path):
    d = str(tmp_path / "store")
    eng = _engine(d, resident_bytes=1)  # everything but the MRU evicts
    eng.register("a", _panel())
    eng.register_shared("b", "a")
    assert len(eng._tenants) == 1  # byte budget of 1 keeps only the MRU

    # fault "a" back, tick it (dirty), evict -> snapshot written
    assert eng.handle(
        {"kind": "tick", "tenant": "a", "x": np.zeros(N)}
    ).ok
    path = eng.store._path("a")
    mtime = os.path.getmtime(path)
    # "a" is the MRU and resident; "b" was evicted to make room.  A
    # second touch of "b" evicts "a" (dirty -> persists), then
    # re-touching "a" faults it in CLEAN; evicting clean is zero i/o
    assert eng.handle({"kind": "nowcast", "tenant": "b"}).ok
    assert os.path.getmtime(path) > mtime  # dirty eviction saved
    mtime = os.path.getmtime(path)
    assert eng.handle({"kind": "nowcast", "tenant": "a"}).ok  # fault in
    assert eng.handle({"kind": "nowcast", "tenant": "b"}).ok  # evict a
    assert os.path.getmtime(path) == mtime  # clean eviction: no write


def test_budget_requires_store_and_positive_values(tmp_path):
    with pytest.raises(ValueError, match="store_dir"):
        _engine(None, resident_tenants=2)
    with pytest.raises(ValueError, match=">= 1"):
        _engine(str(tmp_path / "s"), resident_tenants=0)


# ---------------------------------------------------------------------------
# 2. batched admission == sequential, padding inert
# ---------------------------------------------------------------------------


def test_batched_flush_matches_sequential_bitwise(tmp_path):
    rng = np.random.default_rng(7)
    bat = _engine(str(tmp_path / "b"))
    seq = _engine(str(tmp_path / "s"))
    for e in (bat, seq):
        e.register("a", _panel(seed=8))
        for tid in ("b", "c"):
            e.register_shared(tid, "a")

    # 7 lanes over 3 tenants: duplicates force multiple rounds, and the
    # 3-unique-lane first round pads to bucket 4 (one inert lane)
    order = ["a", "b", "c", "a", "b", "a", "c"]
    rows = [rng.standard_normal(N) for _ in order]
    for tid, row in zip(order, rows):
        bat.submit({"kind": "tick", "tenant": tid, "x": row})
        assert seq.handle({"kind": "tick", "tenant": tid, "x": row}).ok
    resps = bat.flush_period()
    assert len(resps) == len(order) and all(r.ok for r in resps)

    for tid in ("a", "b", "c"):
        np.testing.assert_array_equal(
            np.asarray(bat._tenants[tid].state.s),
            np.asarray(seq._tenants[tid].state.s),
        )
        assert int(bat._tenants[tid].state.t) == int(
            seq._tenants[tid].state.t
        )


def test_batched_dispatch_padding_lanes_are_inert():
    rng = np.random.default_rng(9)
    eng = _engine()
    eng.register("a", _panel(seed=10))
    ten = eng._tenants["a"]

    lanes, want = [], []
    state = ten.state
    for _ in range(3):  # 3 lanes -> bucket 4, one padding lane
        x = rng.standard_normal(N)
        mask = np.isfinite(x)
        lanes.append((ten.model, state, np.where(mask, x, 0.0), mask))
        want.append(online_tick(ten.model, state, np.where(mask, x, 0.0),
                                mask))
    got = batched_tick_dispatch(lanes)
    assert len(got) == 3
    for g, w in zip(got, want):
        np.testing.assert_array_equal(np.asarray(g.s), np.asarray(w.s))
        assert int(g.t) == int(w.t)


def test_flush_isolates_lanes_and_types_errors(tmp_path):
    eng = _engine(str(tmp_path / "store"))
    eng.register("a", _panel(seed=11))
    eng.register_shared("b", "a")

    eng.submit({"kind": "tick", "tenant": "a", "x": np.zeros(N)})
    eng.submit({"kind": "nowcast", "tenant": "a"})          # unbatchable
    eng.submit({"kind": "tick", "tenant": "ghost", "x": np.zeros(N)})
    eng.submit({"kind": "tick", "tenant": "b"})             # missing x
    eng.submit("not a dict")
    r = eng.flush_period()
    assert [x.ok for x in r] == [True, False, False, False, False]
    assert r[1].error.code == "unbatchable_kind"
    assert r[2].error.code == "unknown_tenant"
    assert r[3].error.code == "missing_field"
    assert r[4].error.code == "bad_request"


def test_flush_tick_nan_poisons_only_its_lane(tmp_path):
    eng = _engine(str(tmp_path / "store"))
    eng.register("a", _panel(seed=12))
    eng.register_shared("b", "a")
    # warm one tick each so the fault site lands mid-flush
    for tid in ("a", "b"):
        assert eng.handle(
            {"kind": "tick", "tenant": tid, "x": np.zeros(N)}
        ).ok
    eng.submit({"kind": "tick", "tenant": "a", "x": np.zeros(N)})
    eng.submit({"kind": "tick", "tenant": "b", "x": np.zeros(N)})
    with faults.inject("tick_nan@3"):  # 3rd computed tick = lane "a"
        r = eng.flush_period()
    assert not r[0].ok and r[0].error.code == "nonfinite_state"
    assert r[0].degraded and eng._tenants["a"].replay
    assert r[1].ok and not eng._tenants["b"].replay


# ---------------------------------------------------------------------------
# 3. kill-at-every-step: crash_io drill (chaos lane)
# ---------------------------------------------------------------------------


def _drill_workload(eng, rows):
    """Fixed tick workload over 3 tenants under a resident budget of 2:
    every third tick faults a cold tenant in (evicting a dirty one), so
    the i/o site sequence covers journal appends, snapshot saves and
    journal resets.  Returns the number of ACKED ticks."""
    order = ["a", "b", "c", "a", "c", "b"]
    acked = 0
    for tid, row in zip(order, rows):
        r = eng.handle({"kind": "tick", "tenant": tid, "x": row})
        assert r.ok, r
        acked += 1
    return acked


@pytest.mark.chaos_serving
def test_crash_io_killed_at_every_step_recovers_exactly_once(tmp_path):
    rng = np.random.default_rng(21)
    rows = [rng.standard_normal(N) for _ in range(6)]
    pan = _panel(seed=22)

    # reference: the full workload, never killed, never budgeted
    ref = _engine()
    ref.register("a", pan)
    for tid in ("b", "c"):
        ref.register_shared(tid, "a")
    _drill_workload(ref, rows)
    ref_states = _states(ref, ("a", "b", "c"))

    site = 0
    while True:
        site += 1
        d = str(tmp_path / f"store{site}")
        eng = _engine(d, resident_tenants=2)
        eng.register("a", pan)
        for tid in ("b", "c"):
            eng.register_shared(tid, "a")
        acked = 0
        crashed = True
        ops0 = eng.store._io_ops  # registration already consumed sites
        with faults.inject(f"crash_io@{ops0 + site}"):
            try:
                order = ["a", "b", "c", "a", "c", "b"]
                for tid, row in zip(order, rows):
                    r = eng.handle({"kind": "tick", "tenant": tid,
                                    "x": row})
                    if r.ok:
                        acked += 1
                crashed = False
            except faults.SimulatedCrash:
                pass
        if not crashed:
            break  # site count exceeded the workload's i/o ops: done

        # restart from disk only; acked ticks all present, none doubled
        rec = _engine(d, resident_tenants=2)
        seen = {}
        for tid in ("a", "b", "c"):
            ten = rec._lookup(tid)
            assert ten is not None, f"site {site}: {tid} lost"
            seen[tid] = int(ten.state.t) - T
        # every acked tick survived the kill; the only extra tick
        # allowed is un-acked work the journal had already made durable
        assert sum(seen.values()) >= acked, (
            f"site {site}: acked {acked}, recovered {seen}"
        )
        assert sum(seen.values()) <= acked + 1

        # replay is idempotent: a SECOND restart from the same store
        # lands on the bit-identical state (nothing applied twice)
        rec2 = _engine(d, resident_tenants=2)
        for tid in ("a", "b", "c"):
            t1, t2 = rec._lookup(tid), rec2._lookup(tid)
            np.testing.assert_array_equal(
                np.asarray(t1.state.s), np.asarray(t2.state.s)
            )
        # no stale journal was quarantined anywhere in the drill
        assert not glob.glob(os.path.join(d, "*.corrupt"))
    assert site > 6  # the drill actually exercised multiple i/o sites

    # a clean (uncrashed) budgeted run matches the reference bitwise
    d = str(tmp_path / "clean")
    eng = _engine(d, resident_tenants=2)
    eng.register("a", pan)
    for tid in ("b", "c"):
        eng.register_shared(tid, "a")
    _drill_workload(eng, rows)
    for tid, (s, t) in ref_states.items():
        ten = eng._lookup(tid)
        assert int(ten.state.t) == t
        np.testing.assert_array_equal(np.asarray(ten.state.s), s)


@pytest.mark.chaos_serving
def test_batched_flush_crash_is_exactly_once_across_restart(tmp_path):
    """Kill the batched path at every i/o site of its second flush: on
    restart each tenant holds its snapshot advanced by EXACTLY the rows
    its journal had made durable — acked flush-1 ticks always survive,
    nothing is applied twice (second restart is bit-identical)."""
    rng = np.random.default_rng(31)
    pan = _panel(seed=32)
    flush1 = [("a", rng.standard_normal(N)), ("b", rng.standard_normal(N))]
    flush2 = [("a", rng.standard_normal(N)), ("b", rng.standard_normal(N)),
              ("a", rng.standard_normal(N))]

    site = 0
    crashes = 0
    while True:
        site += 1
        d = str(tmp_path / f"store{site}")
        eng = _engine(d)
        eng.register("a", pan)
        eng.register_shared("b", "a")
        for tid, row in flush1:
            eng.submit({"kind": "tick", "tenant": tid, "x": row})
        r1 = eng.flush_period()
        assert all(r.ok for r in r1)
        acked = {"a": 1, "b": 1}
        crashed = True
        ops0 = eng.store._io_ops  # sites land inside the second flush
        with faults.inject(f"crash_io@{ops0 + site}"):
            try:
                for tid, row in flush2:
                    eng.submit({"kind": "tick", "tenant": tid, "x": row})
                eng.flush_period()
                crashed = False
            except faults.SimulatedCrash:
                crashes += 1
        if not crashed:
            break

        rec = _engine(d)
        rec2 = _engine(d)
        for tid in ("a", "b"):
            assert rec.resume(tid), f"site {site}: {tid} lost"
            assert rec2.resume(tid)
            got_t = int(rec._tenants[tid].state.t) - T
            # acked (flush-1) ticks are durable; at most this tenant's
            # flush-2 submissions can additionally have become durable
            extra = sum(1 for t2, _ in flush2 if t2 == tid)
            assert acked[tid] <= got_t <= acked[tid] + extra, (
                f"site {site}: tenant {tid} t={got_t}"
            )
            np.testing.assert_array_equal(
                np.asarray(rec._tenants[tid].state.s),
                np.asarray(rec2._tenants[tid].state.s),
            )
    assert crashes > 0  # the drill crashed at least once before passing


# ---------------------------------------------------------------------------
# 4. satellite: stale journal skipped, never quarantined
# ---------------------------------------------------------------------------


def test_stale_journal_after_save_is_skipped_not_quarantined(tmp_path):
    d = str(tmp_path / "store")
    eng = _engine(d)
    eng.register("a", _panel(seed=41))
    assert eng.handle(
        {"kind": "tick", "tenant": "a", "x": np.zeros(N)}
    ).ok  # journal now holds one row at base T

    # simulate the crash window between TenantStore.save and the
    # journal reset: persist the CURRENT state (t = T+1) through the
    # engine, then restore the stale journal file (base_t = T < T+1)
    # that the reset truncated
    ten = eng._tenants["a"]
    j = eng.store.journal("a")
    stale = open(j.path, "rb").read() if j.exists() else b""
    eng._persist("a", ten.params, ten.state, ten.breaker)
    with open(j.path, "wb") as f:
        f.write(stale)
    base, rows = j.replay()
    assert base < int(ten.state.t)  # journal is genuinely stale

    t_live = int(ten.state.t)
    s_live = np.asarray(ten.state.s).copy()
    telemetry.reset()
    rec = _engine(d)
    assert rec.resume("a")
    assert telemetry._counters.get("serving.journal.stale_skipped") == 1
    assert int(rec._tenants["a"].state.t) == t_live
    np.testing.assert_array_equal(
        np.asarray(rec._tenants["a"].state.s), s_live
    )
    assert not j.exists()  # stale journal deleted, not quarantined
    assert not glob.glob(os.path.join(d, "*.corrupt"))


# ---------------------------------------------------------------------------
# 5. satellite: list() skips corrupt + in-flight temps
# ---------------------------------------------------------------------------


def test_store_list_skips_corrupt_and_inflight_temps(tmp_path):
    d = str(tmp_path / "store")
    eng = _engine(d)
    eng.register("a", _panel(seed=51))
    eng.register("b", _panel(seed=52))
    for stray in (
        "ghost.npz.corrupt", "a.npz.tmp.1234", "weird.corrupt",
        "c.journal", "c.journal.corrupt", "c.journal.tmp.7",
    ):
        with open(os.path.join(d, stray), "wb") as f:
            f.write(b"\x00junk")
    assert TenantStore(d).list() == ["a", "b"]
    # recover() sees the same filtered view: no crash on the strays
    rec = _engine(d)
    info = rec.recover()
    assert info["tenants_on_disk"] == 2


# ---------------------------------------------------------------------------
# 6. satellite: breaker state survives eviction
# ---------------------------------------------------------------------------


def test_open_breaker_survives_eviction_and_fault_in(tmp_path):
    d = str(tmp_path / "store")
    eng = _engine(d, breaker_threshold=2, breaker_cooldown=50)
    eng.register("a", _panel(seed=61))
    with faults.inject("tick_nan@1+"):  # persistent: open the breaker
        for _ in range(3):
            eng.handle({"kind": "tick", "tenant": "a", "x": np.zeros(N)})
    assert eng._tenants["a"].breaker.state == "open"

    # an open-breaker tenant still has its replay buffer: reconcile it
    # away first (replay-pinned tenants refuse eviction)
    eng._tenants["a"].replay.clear()
    assert eng.evict("a")
    assert "a" not in eng._tenants

    ten = eng._lookup("a")  # fault back in
    assert ten is not None
    assert ten.breaker.state == "open"  # NOT silently closed
    r = eng.handle({"kind": "tick", "tenant": "a", "x": np.zeros(N)})
    assert not r.ok and r.error.code == "breaker_open"


def test_replay_pinned_tenant_refuses_eviction(tmp_path):
    d = str(tmp_path / "store")
    eng = _engine(d)
    eng.register("a", _panel(seed=62))
    with faults.inject("tick_nan@1"):
        r = eng.handle({"kind": "tick", "tenant": "a", "x": np.zeros(N)})
    assert not r.ok and eng._tenants["a"].replay
    assert not eng.evict("a")  # pinned: buffered row exists only in RAM
    assert "a" in eng._tenants


# ---------------------------------------------------------------------------
# 7. recover(): lazy + prewarm, bounded, bit-identical
# ---------------------------------------------------------------------------


def test_recover_is_lazy_prewarm_is_batched_and_bit_identical(tmp_path):
    rng = np.random.default_rng(71)
    d = str(tmp_path / "store")
    eng = _engine(d, resident_tenants=3)
    eng.register("a", _panel(seed=72))
    for tid in ("b", "c", "d", "e"):
        eng.register_shared(tid, "a")
    for k in range(10):
        tid = "abcde"[k % 5]
        assert eng.handle(
            {"kind": "tick", "tenant": tid, "x": rng.standard_normal(N)}
        ).ok
    live = {
        tid: (np.asarray(eng._lookup(tid).state.s).copy(),
              int(eng._lookup(tid).state.t))
        for tid in "abcde"
    }

    rec = _engine(d, resident_tenants=3)
    info = rec.recover(prewarm=2)
    assert info["tenants_on_disk"] == 5
    assert info["prewarmed"] == 2
    assert info["resident"] <= 3
    # prewarmed tenants replayed their journals through the batched
    # dispatch; cold ones fault in lazily — all bit-identical to live
    for tid, (s, t) in live.items():
        ten = rec._lookup(tid)
        assert ten is not None
        assert int(ten.state.t) == t, tid
        np.testing.assert_array_equal(np.asarray(ten.state.s), s)
        assert len(rec._tenants) <= 3

    with pytest.raises(ValueError, match="store"):
        _engine().recover()


# ---------------------------------------------------------------------------
# 8. summarize: resident / eviction / fault-in columns
# ---------------------------------------------------------------------------


def test_summarize_renders_resident_columns(tmp_path, monkeypatch):
    sink = str(tmp_path / "sink.jsonl")
    monkeypatch.setenv("DFM_TELEMETRY", sink)
    monkeypatch.setattr(telemetry, "_explicit_enabled", None)
    monkeypatch.setattr(telemetry, "_explicit_sink", None)
    telemetry.reset()
    assert telemetry.enabled()

    d = str(tmp_path / "store")
    eng = _engine(d, resident_tenants=2)
    eng.register("a", _panel(seed=81))
    for tid in ("b", "c"):
        eng.register_shared(tid, "a")
    for tid in ("a", "b", "c", "a"):
        assert eng.handle(
            {"kind": "tick", "tenant": tid, "x": np.zeros(N)}
        ).ok
    eng.submit({"kind": "tick", "tenant": "a", "x": np.zeros(N)})
    assert all(r.ok for r in eng.flush_period())
    eng.flush_metrics()

    out = telemetry.summarize(sink)
    assert "resident" in out and "fault_in" in out
    row = next(
        ln for ln in out.splitlines() if ln.strip().startswith("serving")
    )
    cells = row.split()
    assert "2" in cells  # resident_tenants gauge made it into the table

    # sinks from before the metrics layer render "-" in those columns
    old = str(tmp_path / "old.jsonl")
    with open(sink) as f, open(old, "w") as g:
        for ln in f:
            if '"entry": "metrics"' not in ln:
                g.write(ln)
    out_old = telemetry.summarize(old)
    row_old = next(
        ln for ln in out_old.splitlines()
        if ln.strip().startswith("serving")
    )
    assert "-" in row_old.split()


# ---------------------------------------------------------------------------
# 9. eviction drops history; refit/scenario answer typed envelopes
# ---------------------------------------------------------------------------


def test_faulted_in_tenant_answers_no_history(tmp_path):
    d = str(tmp_path / "store")
    eng = _engine(d)
    eng.register("a", _panel(seed=91))
    assert eng.evict("a")
    assert eng.handle({"kind": "nowcast", "tenant": "a"}).ok
    r = eng.handle({"kind": "scenario", "tenant": "a",
                    "scenario": {"kind": "stress"}})
    assert not r.ok and r.error.code == "no_history"
    # a queued refit for a history-less tenant is skipped, not crashed
    assert eng.handle({"kind": "refit", "tenant": "a"}).ok
    fr = eng.flush_refits()
    assert fr.ok and fr.info["installed"] == 0


# ---------------------------------------------------------------------------
# 10. coalesced journal appends (pipelined rounds): bytes-on-disk pins
# ---------------------------------------------------------------------------


def _journal_rows(k, seed=77):
    rng = np.random.default_rng(seed)
    return [
        (100 + i, rng.standard_normal(6), rng.random(6) > 0.2)
        for i in range(k)
    ]


def test_append_many_bytes_identical_to_sequential_appends(tmp_path):
    """The coalesced write (one buffered write + one fsync per round)
    must leave the journal BYTE-identical to k sequential `append()`
    calls — replay, quarantine, and checksum logic see one format."""
    from dynamic_factor_models_tpu.serving.journal import TickJournal

    rows = _journal_rows(5)
    seq = TickJournal(str(tmp_path / "seq.journal"))
    for t, x, m in rows:
        seq.append(t, x, m)
    coal = TickJournal(str(tmp_path / "coal.journal"))
    assert coal.append_many(rows) is None  # sync=True: no pending handle
    with open(seq.path, "rb") as f:
        seq_bytes = f.read()
    with open(coal.path, "rb") as f:
        coal_bytes = f.read()
    assert seq_bytes == coal_bytes
    # deferred-durability path: write-all then one fsync sweep
    lazy = TickJournal(str(tmp_path / "lazy.journal"))
    pend = lazy.append_many(rows, sync=False)
    assert pend is not None
    pend.sync()
    with open(lazy.path, "rb") as f:
        assert f.read() == seq_bytes
    # replay equivalence rides the byte equality, but pin it explicitly
    base_seq, got_seq = seq.replay()
    base_coal, got_coal = coal.replay()
    assert base_seq == base_coal == 100
    assert len(got_seq) == len(got_coal) == 5
    for (t1, x1, m1), (t2, x2, m2) in zip(got_seq, got_coal):
        assert t1 == t2
        np.testing.assert_array_equal(x1, x2)
        np.testing.assert_array_equal(m1, m2)


def test_append_many_is_one_store_op_and_write_ahead_ordered(tmp_path):
    """A coalesced round append is ONE fault-countable store op (the
    probe fires once, before any byte), and a pipelined round's journal
    keeps per-tenant append order = admission order: replayed ts are
    contiguous from base_t."""
    from dynamic_factor_models_tpu.serving.journal import TickJournal

    hits = []
    j = TickJournal(str(tmp_path / "probed.journal"),
                    io_probe=lambda: hits.append(1))
    j.append_many(_journal_rows(4))
    assert len(hits) == 1
    # probe-before-bytes: a probe that raises leaves NO file behind
    class _Boom(Exception):
        pass

    def probe():
        raise _Boom()

    j2 = TickJournal(str(tmp_path / "never.journal"), io_probe=probe)
    with pytest.raises(_Boom):
        j2.append_many(_journal_rows(2))
    assert not os.path.exists(j2.path)

    # end-to-end: a pipelined multi-round run journals every tenant's
    # ticks in admission order with no gaps
    from dynamic_factor_models_tpu.serving.pipeline import ServingPipeline

    d = str(tmp_path / "store")
    eng = _engine(d)
    eng.register("w0", _panel(seed=93))
    eng.register_shared("w1", "w0")
    rng = np.random.default_rng(3)
    with ServingPipeline(eng, backstage="serial", max_round_lanes=2) as p:
        for _ in range(3):
            for tid in ("w0", "w1"):
                p.submit({"kind": "tick", "tenant": tid,
                          "x": rng.standard_normal(N)})
        out = p.drain()
    assert len(out) == 6 and all(r.ok for r in out)
    for tid in ("w0", "w1"):
        base_t, rows = eng.store.journal(tid).replay()
        ts = [t for t, _x, _m in rows]
        assert ts == list(range(base_t, base_t + 3))
