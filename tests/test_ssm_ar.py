"""Full Banbura-Modugno EM (AR(1) idiosyncratic states)."""

import jax.numpy as jnp
import numpy as np
import pytest

from dynamic_factor_models_tpu.models.dfm import DFMConfig
from dynamic_factor_models_tpu.models.ssm_ar import (
    SSMARParams,
    em_step_ar,
    estimate_dfm_em_ar,
)


def _dgp(T=220, N=12, phi=0.7, seed=0):
    rng = np.random.default_rng(seed)
    f = np.zeros(T)
    for t in range(1, T):
        f[t] = 0.6 * f[t - 1] + rng.standard_normal()
    lam = rng.standard_normal(N)
    e = np.zeros((T, N))
    for t in range(1, T):
        e[t] = phi * e[t - 1] + 0.6 * rng.standard_normal(N)
    x = np.outer(f, lam) + e
    return x, f, lam, e


@pytest.mark.slow
def test_em_ar_loglik_monotone_and_phi_recovered():
    x, f, lam, e = _dgp()
    res = estimate_dfm_em_ar(
        x, np.ones(x.shape[1]), 0, x.shape[0] - 1,
        DFMConfig(nfac_u=1, n_factorlag=1), max_em_iter=40,
    )
    lls = res.loglik_path
    assert np.isfinite(lls).all()
    # EM monotonicity (tiny numerical slack)
    assert (np.diff(lls) > -1e-6 * np.abs(lls[:-1])).all(), np.diff(lls).min()
    # idiosyncratic persistence recovered
    phi_hat = np.asarray(res.params.phi)
    assert abs(np.median(phi_hat) - 0.7) < 0.15, np.median(phi_hat)
    # smoothed factor spans the truth
    corr = abs(np.corrcoef(np.asarray(res.factors[:, 0]), f)[0, 1])
    assert corr > 0.95, corr
    # smoothed idio components track the true e
    ce = np.corrcoef(np.asarray(res.idio).ravel(), e.ravel())[0, 1]
    assert ce > 0.8, ce


@pytest.mark.slow
def test_em_ar_ragged_edge_idio_persistence():
    # the whole point of AR(1) idio states: a persistent idiosyncratic
    # deviation carries into an unreleased period.  An iid-noise model's
    # smoothed idio at a missing cell is ~0, so the checks below (corr with
    # the AR prediction from the TRUE withheld history, and non-collapsed
    # magnitude) distinguish the models.
    x, f, lam, e = _dgp(T=260, N=16, seed=3)
    x_r = x.copy()
    blank = np.arange(0, 16, 2)
    x_r[-1, blank] = np.nan  # last release of half the series missing
    res = estimate_dfm_em_ar(
        x_r, np.ones(x.shape[1]), 0, x.shape[0] - 1,
        DFMConfig(nfac_u=1, n_factorlag=1), max_em_iter=30,
    )
    idio_pred = np.asarray(res.idio)[-1, blank] * np.asarray(res.stds)[blank]
    target = 0.7 * e[-2, blank]  # the AR prediction from the true history
    assert np.isfinite(idio_pred).all()
    corr = np.corrcoef(idio_pred, target)[0, 1]
    assert corr > 0.5, f"idio persistence not carried into missing cells: {corr}"
    assert np.std(idio_pred) > 0.3 * np.std(target), "idio collapsed toward 0"


def test_em_step_ar_jits_and_is_finite(rng):
    x = jnp.asarray(rng.standard_normal((60, 5)))
    m = jnp.asarray(rng.random((60, 5)) > 0.1)
    params = SSMARParams(
        lam=jnp.asarray(rng.standard_normal((5, 2))),
        phi=0.5 * jnp.ones(5),
        sigv2=jnp.ones(5),
        A=0.4 * jnp.eye(2)[None],
        Q=jnp.eye(2),
    )
    newp, ll = em_step_ar(params, jnp.where(m, x, 0.0), m)
    assert np.isfinite(float(ll))
    for v in newp:
        assert np.isfinite(np.asarray(v)).all()
    assert (np.abs(np.asarray(newp.phi)) <= 0.99).all()


def test_nowcast_em_ar_beats_iid_on_persistent_idio():
    # head-to-head: with persistent idio (phi=0.7), the AR nowcast of a
    # missing cell should be closer to the truth than the iid-model nowcast
    from dynamic_factor_models_tpu.models.forecast import nowcast_em
    from dynamic_factor_models_tpu.models.ssm import estimate_dfm_em
    from dynamic_factor_models_tpu.models.ssm_ar import nowcast_em_ar

    x, f, lam, e = _dgp(T=260, N=16, phi=0.8, seed=11)
    x_r = x.copy()
    blank = np.arange(0, 16, 2)
    x_r[-1, blank] = np.nan
    incl = np.ones(x.shape[1])
    cfg = DFMConfig(nfac_u=1, n_factorlag=1)

    em_ar = estimate_dfm_em_ar(x_r, incl, 0, x.shape[0] - 1, cfg, max_em_iter=30)
    nc_ar = nowcast_em_ar(em_ar, x_r, incl, 0, x.shape[0] - 1)
    em_iid = estimate_dfm_em(x_r, incl, 0, x.shape[0] - 1, cfg, max_em_iter=30)
    nc_iid = nowcast_em(em_iid, x_r, incl, 0, x.shape[0] - 1)

    truth = x[-1, blank]
    err_ar = np.abs(np.asarray(nc_ar.filled)[-1, blank] - truth).mean()
    err_iid = np.abs(np.asarray(nc_iid.filled)[-1, blank] - truth).mean()
    assert err_ar < err_iid, f"AR nowcast not better: {err_ar} vs {err_iid}"
    # observed cells pass through untouched
    obs = np.isfinite(x_r)
    np.testing.assert_allclose(np.asarray(nc_ar.filled)[obs], x_r[obs])
