"""Float64 fixed-point polish + canonical rotation (`polish="float64"`).

The north-star parity bar (BASELINE.json: factor parity at 1e-5) cannot be
met by the raw fixed-iteration ALS trajectory — f32 and f64 trajectories
diverge by ~8e-5 after 60 iterations, and the ALS fixed points form a
GL(nfac) manifold (rotational indeterminacy), so even fully-converged runs
from different trajectories land at different rotations.  The polish
(`dfm._polish_fixed_point_f64`) iterates the exact masked ALS map in host
NumPy float64 to convergence and projects onto the canonical representative
(F'F/T = I, lam'lam diagonal descending, signs fixed), making the output a
function of the data alone — not of backend, precision, or iteration count.
"""

import numpy as np
import pytest

from dynamic_factor_models_tpu.models.dfm import (
    DFMConfig,
    _polish_fixed_point_f64,
    estimate_factor,
)
from dynamic_factor_models_tpu.models.constraints import LambdaConstraint


def _panel(T=160, N=50, r=3, seed=0, missing=0.1):
    rng = np.random.default_rng(seed)
    f = rng.standard_normal((T, r))
    lam = rng.standard_normal((N, r))
    x = f @ lam.T + 0.3 * rng.standard_normal((T, N))
    # ragged missingness, but keep a fully-balanced block for the PCA init
    miss = rng.random((T, N)) < missing
    miss[:, : r + 4] = False
    x[miss] = np.nan
    return x


def _polished(x, max_iter, r=3):
    cfg = DFMConfig(nfac_u=r, tol=0.0, max_iter=max_iter)
    F, fes = estimate_factor(
        x, np.ones(x.shape[1]), 0, x.shape[0] - 1, cfg, polish="float64"
    )
    return np.asarray(F), fes


def test_polish_is_iteration_count_invariant():
    # ALS stopped at 25 vs 120 iterations lands at different points of the
    # fixed-point approach; the polish must erase that difference entirely
    x = _panel()
    Fa, fes_a = _polished(x, 25)
    Fb, fes_b = _polished(x, 120)
    np.testing.assert_allclose(Fa, Fb, atol=1e-8)
    assert abs(float(fes_a.ssr) - float(fes_b.ssr)) < 1e-6


def test_polish_reaches_fixed_point_and_canonical_form():
    x = _panel(seed=1)
    r = 3
    F, _ = _polished(x, 40)
    Tw = x.shape[0]
    # canonical scale: F'F/T = I on the unobserved block
    G = F.T @ F / Tw
    np.testing.assert_allclose(G, np.eye(r), atol=1e-8)
    # fixed point: one more exact float64 map application barely moves it
    m = (~np.isnan(x)).astype(float)
    xs = np.where(np.isnan(x), 0.0, x)
    mu = (m * xs).sum(0) / m.sum(0)
    xc = np.where(m > 0, xs - mu, 0.0)
    sd = np.sqrt((m * xc**2).sum(0) / m.sum(0))
    xz = np.where(m > 0, xc / sd, 0.0)
    F2, _, _, n_it, converged = _polish_fixed_point_f64(
        xz, m, np.ones(x.shape[1]), F, tol=1e-13, max_iter=50
    )
    np.testing.assert_allclose(F2, F, atol=1e-7)
    assert n_it < 50 and converged  # converged, not capped


def test_polish_loading_gram_is_descending_diagonal():
    x = _panel(seed=2)
    cfg = DFMConfig(nfac_u=3, tol=0.0, max_iter=60)
    m = (~np.isnan(x)).astype(float)
    xs = np.where(np.isnan(x), 0.0, x)
    mu = (m * xs).sum(0) / m.sum(0)
    xc = np.where(m > 0, xs - mu, 0.0)
    sd = np.sqrt((m * xc**2).sum(0) / m.sum(0))
    xz = np.where(m > 0, xc / sd, 0.0)
    f0 = xz[:, :3].copy()
    F, lam, _, _, _ = _polish_fixed_point_f64(xz, m, np.ones(x.shape[1]), f0)
    LtL = lam.T @ lam
    off = LtL - np.diag(np.diag(LtL))
    assert np.abs(off).max() < 1e-7 * np.abs(np.diag(LtL)).max()
    d = np.diag(LtL)
    assert np.all(np.diff(d) <= 1e-9)


def test_polish_with_observed_factors():
    rng = np.random.default_rng(3)
    T, N = 150, 40
    fo = rng.standard_normal((T, 1))
    x = np.asarray(_panel(T, N, r=2, seed=4)) + 0.8 * fo @ rng.standard_normal(
        (N, 1)
    ).T
    cfg = DFMConfig(nfac_o=1, nfac_u=2, tol=0.0)
    Fa, _ = estimate_factor(
        x, np.ones(N), 0, T - 1, cfg, observed_factor=fo,
        max_iter=25, polish="float64",
    )
    Fb, _ = estimate_factor(
        x, np.ones(N), 0, T - 1, cfg, observed_factor=fo,
        max_iter=120, polish="float64",
    )
    np.testing.assert_allclose(np.asarray(Fa), np.asarray(Fb), atol=1e-8)
    # observed column passes through verbatim
    np.testing.assert_allclose(np.asarray(Fa)[:, 0], fo[:, 0], atol=1e-12)


def test_polish_of_raw_iterate_matches_api_path():
    """The bench parity program polishes the RAW leg's terminal iterate
    directly (reconstructing xz/m/lam_ok with the same public helpers)
    instead of re-running the jitted ALS inside estimate_factor — pinned
    here: both routes land on the identical canonical fixed point."""
    from dynamic_factor_models_tpu.ops.linalg import standardize_data
    from dynamic_factor_models_tpu.ops.masking import fillz, mask_of

    x = _panel(seed=7)
    cfg = DFMConfig(nfac_u=3, tol=0.0, max_iter=60)
    init, last = 4, x.shape[0] - 3  # non-trivial window
    F_api, _ = estimate_factor(
        x, np.ones(x.shape[1]), init, last, cfg, polish="float64"
    )
    F_raw, _ = estimate_factor(x, np.ones(x.shape[1]), init, last, cfg)
    xw = np.asarray(x)[init : last + 1]
    xstd, _ = standardize_data(xw)
    m = np.asarray(mask_of(xstd), float)
    lam_ok = m.sum(axis=0) >= cfg.nt_min_factor
    F_pol_w, _, _, _, _ = _polish_fixed_point_f64(
        np.asarray(fillz(xstd)), m, lam_ok, np.asarray(F_raw)[init : last + 1]
    )
    np.testing.assert_allclose(
        F_pol_w, np.asarray(F_api)[init : last + 1], atol=1e-8
    )


def test_polish_validation():
    x = _panel()
    cfg = DFMConfig(nfac_u=2)
    with pytest.raises(ValueError, match="polish must be"):
        estimate_factor(x, np.ones(x.shape[1]), 0, x.shape[0] - 1, cfg,
                        polish="f64")
    con = LambdaConstraint(
        series=np.array([0], dtype=np.int32),
        R=np.ones((1, 1, 2)),
        r=np.ones((1, 1)),
    )
    with pytest.raises(ValueError, match="not supported with a constraint"):
        estimate_factor(x, np.ones(x.shape[1]), 0, x.shape[0] - 1, cfg,
                        constraint=con, polish="float64")


def test_polished_path_preserves_table2a_goldens(dataset_real):
    """The polish is a refinement, not a different estimator: the Table 2(A)
    trace R-squared goldens must hold on the POLISHED path at the same 1e-3
    tolerance as the raw path (tests/test_dfm_golden.py)."""
    golden = [0.385, 0.489, 0.533, 0.564, 0.594]
    for r, g in zip((1, 2, 3, 4, 5), golden):
        cfg = DFMConfig(nfac_u=r, tol=1e-8)
        _, fes = estimate_factor(
            dataset_real.bpdata, dataset_real.inclcode, 2, 223, cfg,
            polish="float64",
        )
        tr = 1.0 - float(fes.ssr) / float(fes.tss)
        np.testing.assert_allclose(tr, g, atol=1e-3)
