"""Pallas masked-Gram kernel vs the XLA einsum reference path.

Runs the kernel in interpreter mode on the CPU test mesh (SURVEY.md
section 4: TPU kernels must be testable without TPU hardware); the compiled
path is exercised on the real chip by bench.py.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from dynamic_factor_models_tpu.ops.linalg import ols_batched_series
from dynamic_factor_models_tpu.ops.masking import fillz, mask_of
from dynamic_factor_models_tpu.ops.pallas_gram import (
    masked_gram_pallas,
    masked_gram_xla,
)


@pytest.mark.parametrize("T,N,K", [(224, 207, 5), (300, 130, 9), (64, 32, 3)])
def test_pallas_matches_xla(rng, T, N, K):
    X = jnp.asarray(rng.standard_normal((T, K)))
    Y = jnp.asarray(rng.standard_normal((T, N)))
    W = jnp.asarray((rng.random((T, N)) > 0.2).astype(np.float64))
    A0, b0 = masked_gram_xla(X, Y, W)
    A1, b1 = masked_gram_pallas(X, Y, W, tile_t=128, tile_n=128, interpret=True)
    np.testing.assert_allclose(np.asarray(A1), np.asarray(A0), rtol=1e-10)
    np.testing.assert_allclose(np.asarray(b1), np.asarray(b0), rtol=1e-10)


def test_pallas_padding_exact(rng):
    # shapes deliberately not tile multiples: padding must contribute nothing
    T, N, K = 130, 70, 4
    X = jnp.asarray(rng.standard_normal((T, K)))
    Y = jnp.asarray(rng.standard_normal((T, N)))
    W = jnp.ones((T, N))
    A0, b0 = masked_gram_xla(X, Y, W)
    A1, b1 = masked_gram_pallas(X, Y, W, tile_t=128, tile_n=128, interpret=True)
    np.testing.assert_allclose(np.asarray(A1), np.asarray(A0), rtol=1e-10)
    np.testing.assert_allclose(np.asarray(b1), np.asarray(b0), rtol=1e-10)


def test_gram_feeds_batched_ols(rng):
    # the wired path: ols_batched_series solves the kernel's normal equations
    T, N, K = 96, 11, 3
    X = jnp.asarray(rng.standard_normal((T, K)))
    beta_true = rng.standard_normal((K, N))
    Y = X @ jnp.asarray(beta_true)
    Y = Y.at[rng.integers(0, T, 40), rng.integers(0, N, 40)].set(jnp.nan)
    W = mask_of(Y).astype(X.dtype)
    betas, resid = ols_batched_series(Y, X, W)
    np.testing.assert_allclose(np.asarray(betas), beta_true, atol=1e-8)
    r = np.asarray(resid)
    assert np.all(np.isnan(r[~np.asarray(W, bool)]))
    np.testing.assert_allclose(
        np.nan_to_num(r), np.zeros_like(r), atol=1e-8
    )
