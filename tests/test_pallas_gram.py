"""Pallas masked-Gram kernel vs the XLA einsum reference path.

Runs the kernel in interpreter mode on the CPU test mesh (SURVEY.md
section 4: TPU kernels must be testable without TPU hardware); the compiled
path runs on the real chip via a clean subprocess when one is present
(`test_pallas_compiled_on_tpu`) and in bench.py.
"""

import os
import subprocess
import sys

import jax.numpy as jnp
import numpy as np
import pytest

from dynamic_factor_models_tpu.ops.linalg import ols_batched_series
from dynamic_factor_models_tpu.ops.masking import fillz, mask_of
from dynamic_factor_models_tpu.ops.pallas_gram import (
    masked_gram_pallas,
    masked_gram_xla,
)


@pytest.mark.parametrize("T,N,K", [(224, 207, 5), (300, 130, 9), (64, 32, 3)])
def test_pallas_matches_xla(rng, T, N, K):
    X = jnp.asarray(rng.standard_normal((T, K)))
    Y = jnp.asarray(rng.standard_normal((T, N)))
    W = jnp.asarray((rng.random((T, N)) > 0.2).astype(np.float64))
    A0, b0 = masked_gram_xla(X, Y, W)
    A1, b1 = masked_gram_pallas(X, Y, W, tile_t=128, tile_n=128, interpret=True)
    np.testing.assert_allclose(np.asarray(A1), np.asarray(A0), rtol=1e-10)
    np.testing.assert_allclose(np.asarray(b1), np.asarray(b0), rtol=1e-10)


def test_pallas_padding_exact(rng):
    # shapes deliberately not tile multiples: padding must contribute nothing
    T, N, K = 130, 70, 4
    X = jnp.asarray(rng.standard_normal((T, K)))
    Y = jnp.asarray(rng.standard_normal((T, N)))
    W = jnp.ones((T, N))
    A0, b0 = masked_gram_xla(X, Y, W)
    A1, b1 = masked_gram_pallas(X, Y, W, tile_t=128, tile_n=128, interpret=True)
    np.testing.assert_allclose(np.asarray(A1), np.asarray(A0), rtol=1e-10)
    np.testing.assert_allclose(np.asarray(b1), np.asarray(b0), rtol=1e-10)


_COMPILED_CHECK = """
import jax, jax.numpy as jnp, numpy as np
if jax.default_backend() not in ("tpu", "axon"):
    print("NO_TPU"); raise SystemExit(0)
from dynamic_factor_models_tpu.ops.pallas_gram import masked_gram_pallas, masked_gram_xla
rng = np.random.default_rng(0)
T, N, K = 512, 384, 8
Xn = rng.standard_normal((T, K)); Yn = rng.standard_normal((T, N))
Wn = (rng.random((T, N)) > 0.2).astype(float)
X, Y, W = (jnp.asarray(a, jnp.float32) for a in (Xn, Yn, Wn))
A, b = masked_gram_pallas(X, Y, W)   # compiled, not interpret
jax.block_until_ready((A, b))
A64 = np.einsum("tk,tn,tl->nkl", Xn, Wn, Xn)
b64 = np.einsum("tk,tn->nk", Xn, Wn * Yn)
Ax, bx = masked_gram_xla(X, Y, W)
# the kernel must be no less accurate than the chip's own XLA einsum
err_pallas = np.abs(np.asarray(A, np.float64) - A64).max()
err_xla = np.abs(np.asarray(Ax, np.float64) - A64).max()
assert err_pallas <= 4 * max(err_xla, 1e-6), (err_pallas, err_xla)
assert np.abs(np.asarray(b, np.float64) - b64).max() <= 4 * max(
    np.abs(np.asarray(bx, np.float64) - b64).max(), 1e-6)
print("COMPILED_OK", err_pallas, err_xla)
"""


@pytest.mark.slow
def test_pallas_compiled_on_tpu():
    """Compiled (non-interpret) kernel correctness on real TPU hardware.

    The suite itself pins JAX to CPU (conftest), so the compiled check runs
    in a clean subprocess with the session's default platform; skipped when
    no TPU is reachable."""
    env = {
        k: v
        for k, v in os.environ.items()
        if k not in ("JAX_PLATFORMS", "XLA_FLAGS", "JAX_ENABLE_X64")
    }
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    # cheap probe first: a hung device query means the chip/tunnel is
    # unreachable (an environment condition, not a kernel failure) — bound
    # that case to ~2 min instead of stalling the whole suite
    probe = (
        "import jax, jax.numpy as jnp\n"
        "print('NO_TPU' if jax.default_backend() not in ('tpu', 'axon')\n"
        "      else ('TPU_OK', float(jnp.ones((8, 8)).sum())))\n"
    )
    try:
        pr = subprocess.run(
            [sys.executable, "-c", probe],
            cwd=repo, env=env, capture_output=True, text=True, timeout=120,
        )
    except subprocess.TimeoutExpired:
        pytest.skip("TPU unresponsive (device probe timed out)")
    if "NO_TPU" in pr.stdout:
        pytest.skip("no TPU platform in this environment")
    if pr.returncode != 0 or "TPU_OK" not in pr.stdout:
        # a chip that is present but crashes the runtime (e.g. a libtpu
        # version mismatch) is a failure to surface, not missing hardware
        pytest.fail(
            "TPU present but probe crashed "
            f"(rc={pr.returncode}, stdout={pr.stdout[-100:]!r}, "
            f"stderr={pr.stderr[-300:]!r})"
        )
    try:
        proc = subprocess.run(
            [sys.executable, "-c", _COMPILED_CHECK],
            cwd=repo,
            env=env,
            capture_output=True,
            text=True,
            timeout=1200,  # jax import + first compile is slow under load
        )
    except subprocess.TimeoutExpired as e:
        # the probe just proved the chip responsive, so a hang HERE is the
        # regression class this test exists to catch (kernel/compile
        # deadlock) — fail, don't skip
        pytest.fail(
            f"compiled Pallas check hung (>1200s) on a responsive TPU: "
            f"stdout={(e.stdout or b'')[-300:]!r}"
        )
    if "NO_TPU" in proc.stdout:
        pytest.skip("no TPU reachable in this environment")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "COMPILED_OK" in proc.stdout, proc.stdout + proc.stderr


def test_gram_feeds_batched_ols(rng):
    # the wired path: ols_batched_series solves the kernel's normal equations
    T, N, K = 96, 11, 3
    X = jnp.asarray(rng.standard_normal((T, K)))
    beta_true = rng.standard_normal((K, N))
    Y = X @ jnp.asarray(beta_true)
    Y = Y.at[rng.integers(0, T, 40), rng.integers(0, N, 40)].set(jnp.nan)
    W = mask_of(Y).astype(X.dtype)
    betas, resid = ols_batched_series(Y, X, W)
    np.testing.assert_allclose(np.asarray(betas), beta_true, atol=1e-8)
    r = np.asarray(resid)
    assert np.all(np.isnan(r[~np.asarray(W, bool)]))
    np.testing.assert_allclose(
        np.nan_to_num(r), np.zeros_like(r), atol=1e-8
    )


def test_bf16_inputs_accumulate_f32(rng):
    """bfloat16 panels are the HBM-bandwidth option: both paths must
    return f32 Grams (f32 accumulation) whose values track the f64
    reference at bf16 operand precision."""
    T, N, K = 192, 96, 6
    Xd = rng.standard_normal((T, K))
    Yd = rng.standard_normal((T, N))
    Wd = (rng.random((T, N)) > 0.2).astype(np.float64)
    A_ref, b_ref = masked_gram_xla(
        jnp.asarray(Xd), jnp.asarray(Yd), jnp.asarray(Wd)
    )
    X16 = jnp.asarray(Xd, jnp.bfloat16)
    Y16 = jnp.asarray(Yd, jnp.bfloat16)
    W16 = jnp.asarray(Wd, jnp.bfloat16)
    scale_A = float(np.abs(np.asarray(A_ref)).max())
    scale_b = float(np.abs(np.asarray(b_ref)).max())
    for A, b in (
        masked_gram_xla(X16, Y16, W16),
        masked_gram_pallas(X16, Y16, W16, tile_t=64, tile_n=64, interpret=True),
    ):
        assert A.dtype == jnp.float32 and b.dtype == jnp.float32
        # bf16 operands carry ~2-3 decimal digits; the f32 accumulator must
        # keep the reduction error at operand level, not grow with T
        assert float(jnp.abs(A - A_ref.astype(jnp.float32)).max()) < 3e-2 * scale_A
        assert float(jnp.abs(b - b_ref.astype(jnp.float32)).max()) < 3e-2 * scale_b


def test_f32_f64_dtype_contract_unchanged(rng):
    """The pre-bf16 contract is preserved: f32 in -> f32 out, f64 -> f64."""
    T, N, K = 64, 32, 3
    for dt in (jnp.float32, jnp.float64):
        X = jnp.asarray(rng.standard_normal((T, K)), dt)
        Y = jnp.asarray(rng.standard_normal((T, N)), dt)
        W = jnp.asarray((rng.random((T, N)) > 0.2), dt)
        A0, b0 = masked_gram_xla(X, Y, W)
        A1, b1 = masked_gram_pallas(X, Y, W, tile_t=64, tile_n=64, interpret=True)
        assert A0.dtype == dt and A1.dtype == dt
        assert b0.dtype == dt and b1.dtype == dt
