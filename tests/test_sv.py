"""Stochastic-volatility DFM (models/sv.py): synthetic recovery of the
factor path, the volatility regimes, and the h-AR hyperparameters."""

import jax.numpy as jnp
import numpy as np

from dynamic_factor_models_tpu.models.dfm import DFMConfig
from dynamic_factor_models_tpu.models.sv import estimate_dfm_sv

import pytest


def _simulate_sv(T=300, N=12, r=1, seed=0):
    rng = np.random.default_rng(seed)
    h = np.where(np.arange(T) < T // 2, -1.5, 0.8).astype(float)
    ar = np.zeros(T)
    for t in range(1, T):
        ar[t] = 0.95 * ar[t - 1] + 0.15 * rng.standard_normal()
    h = h + ar
    f = np.zeros((T, r))
    for t in range(1, T):
        f[t] = 0.6 * f[t - 1] + np.exp(0.5 * h[t]) * rng.standard_normal(r)
    lam = rng.standard_normal((N, r))
    x = f @ lam.T + 0.4 * rng.standard_normal((T, N))
    miss = rng.random((T, N)) < 0.05
    miss[:, : N // 2] = False
    x[miss] = np.nan
    return x, f, h, lam


@pytest.fixture(scope="module")
def sv_posterior():
    x, f, h, lam = _simulate_sv()
    res = estimate_dfm_sv(
        jnp.asarray(x), np.ones(x.shape[1], np.int64), 0, x.shape[0] - 1,
        DFMConfig(nfac_u=1, n_factorlag=1, tol=1e-6, max_iter=200),
        n_keep=150, n_burn=150, n_chains=2, seed=0,
    )
    return x, f, h, res


@pytest.mark.slow
class TestSVDFM:
    def test_recovers_factor(self, sv_posterior):
        x, f, h, res = sv_posterior
        assert res.factor_draws.shape == (2, 150, 300, 1)
        fm = np.asarray(res.factor_draws).mean(axis=(0, 1))[:, 0]
        assert abs(np.corrcoef(fm, f[:, 0])[0, 1]) > 0.95

    def test_recovers_volatility_path(self, sv_posterior):
        x, f, h, res = sv_posterior
        vol = np.asarray(res.vol_draws).mean(axis=(0, 1))[:, 0]
        assert (vol > 0).all()
        assert np.corrcoef(vol, np.exp(0.5 * h))[0, 1] > 0.7
        # regime separation: turbulent second half >= 1.5x the calm half
        T = len(vol)
        assert vol[T // 2 :].mean() > 1.5 * vol[: T // 2].mean()

    def test_hyperparameters_sane(self, sv_posterior):
        *_, res = sv_posterior
        assert 0.7 < float(res.phi_draws.mean()) <= 0.99  # persistent truth 0.95
        assert 0.02 < float(res.sig_draws.mean()) < 1.0
        assert np.isfinite(res.loglik_path).all()
        assert res.rhat_loglik < 1.3

    def test_volatility_draws_sign_invariant(self, sv_posterior):
        """Sign normalization flips factors/loadings, never volatilities."""
        *_, res = sv_posterior
        assert (np.asarray(res.vol_draws) > 0).all()
