"""Time-varying loadings (models/tvp.py): break tracking, stability
selection, and the q=0 constant-loading limit."""

import jax.numpy as jnp
import numpy as np
import pytest

from dynamic_factor_models_tpu.models.tvp import tvp_loadings


def _break_panel(T=300, N=10, r=2, seed=0):
    rng = np.random.default_rng(seed)
    F = rng.standard_normal((T, r))
    lam_a = rng.standard_normal((N, r))
    lam_b = lam_a.copy()
    lam_b[N // 2 :, 0] += 2.0  # second half of series: loading break
    lam_t = np.where(np.arange(T)[:, None, None] < T // 2, lam_a, lam_b)
    x = np.einsum("tr,tnr->tn", F, lam_t) + 0.3 * rng.standard_normal((T, N))
    x[rng.random((T, N)) < 0.05] = np.nan
    return x, F, lam_a, lam_b


@pytest.fixture(scope="module")
def tvp_fit():
    x, F, lam_a, lam_b = _break_panel()
    res = tvp_loadings(jnp.asarray(x), jnp.asarray(F))
    return x, F, lam_a, lam_b, res


class TestTVPLoadings:
    def test_tracks_loading_break(self, tvp_fit):
        x, F, lam_a, lam_b, res = tvp_fit
        T, N = x.shape
        lp = np.asarray(res.lam_path)
        for i in range(N // 2, N):
            early = lp[: T // 2 - 20, i, 0].mean()
            late = lp[T // 2 + 20 :, i, 0].mean()
            assert abs(early - lam_a[i, 0]) < 0.3
            assert abs(late - lam_b[i, 0]) < 0.3

    def test_stable_series_select_small_q(self, tvp_fit):
        *_, res = tvp_fit
        N = res.q.shape[0]
        q = np.asarray(res.q)
        drift = np.asarray(res.drift)
        assert (q[: N // 2] <= 1e-4).all()  # stable half
        assert (q[N // 2 :] >= 1e-3).all()  # breaking half
        assert drift[N // 2 :].min() > 5 * max(drift[: N // 2].max(), 0.05)

    def test_variances_positive_loglik_best(self, tvp_fit):
        *_, res = tvp_fit
        assert (np.asarray(res.lam_var) > -1e-12).all()
        assert (np.asarray(res.sigma2) > 0).all()
        # selected loglik equals the grid max
        assert np.allclose(
            np.asarray(res.loglik), np.asarray(res.grid_loglik).max(axis=1),
            atol=1e-6,
        )

    def test_q_zero_matches_constant_regression(self):
        """With the grid forced to {0}, the smoothed path is time-constant
        and equals the (masked) OLS loading."""
        rng = np.random.default_rng(1)
        T, r = 400, 2
        F = rng.standard_normal((T, r))
        lam = np.array([1.5, -0.7])
        y = F @ lam + 0.2 * rng.standard_normal(T)
        res = tvp_loadings(jnp.asarray(y[:, None]), jnp.asarray(F), grid=(0.0,))
        lp = np.asarray(res.lam_path)[:, 0, :]
        assert lp.std(axis=0).max() < 0.02  # near-constant path
        assert np.allclose(lp[-1], lam, atol=0.05)

    def test_masks_missing_factor_rows(self):
        x, F, *_ = _break_panel(T=200, seed=2)
        F = F.copy()
        F[:10] = np.nan  # factor burn-in rows (e.g. ALS window offset)
        res = tvp_loadings(jnp.asarray(x), jnp.asarray(F))
        assert np.isfinite(np.asarray(res.lam_path)).all()
        assert np.isfinite(np.asarray(res.loglik)).all()
