"""Worker for the multi-process distributed test (test_distributed_multiprocess).

Each worker is one 'host': 4 virtual CPU devices, joined into one global
8-device runtime via `jax.distributed.initialize` (coordination service +
Gloo CPU collectives — the DCN analogue this environment can actually run).
Run: python _dist_worker.py <process_id> <num_processes> <port> [mode]

Modes:
  favar (default)  the PR-13 drill: global-mesh psum + replication-sharded
                   bootstrap.
  em               the PR-15 drill: sharded EM (plain + collapsed-AR) with
                   n_shards=8 over the process-spanning ("dcn", "ici")
                   mesh; each worker ALSO runs the single-process reference
                   locally and asserts <= 1e-10 parity in-process, then
                   prints a bytes digest of the sharded results so the
                   harness can pin bit-identical SPMD output across
                   processes.
"""

import os
import sys

pid, nproc, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
mode = sys.argv[4] if len(sys.argv) > 4 else "favar"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402
import jax.numpy as jnp  # noqa: E402
from jax.sharding import NamedSharding, PartitionSpec as P  # noqa: E402

from dynamic_factor_models_tpu.parallel.distributed import (  # noqa: E402
    global_mesh,
    initialize_distributed,
)

# version-compat shim (jax.shard_map vs jax.experimental.shard_map)
from dynamic_factor_models_tpu.parallel.timescan import shard_map  # noqa: E402


def _digest(tree) -> str:
    """Order-stable bytes digest of a pytree — bit-identity probe across
    the SPMD processes (any divergence, even in the last ulp, changes it)."""
    import hashlib

    h = hashlib.sha256()
    for leaf in jax.tree.leaves(tree):
        h.update(np.ascontiguousarray(np.asarray(leaf)).tobytes())
    return h.hexdigest()


def em_mode():
    """PR-15 drill: sharded EM over the process-spanning mesh.

    Each worker runs the single-process (local, collective-free) reference
    AND the n_shards=8 global-mesh run, asserts <= 1e-10 parity in-process,
    and prints a digest of the sharded results for the cross-process
    bit-identity check in the harness.
    """
    from dynamic_factor_models_tpu.models.dfm import DFMConfig
    from dynamic_factor_models_tpu.models.ssm import estimate_dfm_em
    from dynamic_factor_models_tpu.models.ssm_ar import estimate_dfm_em_ar

    def max_leaf_diff(a, b):
        return max(
            float(np.max(np.abs(np.asarray(x) - np.asarray(y))))
            if np.asarray(x).size
            else 0.0
            for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b))
        )

    rng = np.random.default_rng(15)
    T, N, r = 60, 37, 2
    f = rng.standard_normal((T, r))
    lam = rng.standard_normal((N, r))
    x = f @ lam.T + 0.5 * rng.standard_normal((T, N))
    x[rng.random((T, N)) < 0.15 * (np.arange(N) >= r + 4)] = np.nan
    cfg = DFMConfig(nfac_u=r, n_factorlag=1)

    # plain EM: local reference vs global (dcn, ici) mesh
    base = estimate_dfm_em(x, np.ones(N), 0, T - 1, cfg, max_em_iter=6)
    shrd = estimate_dfm_em(
        x, np.ones(N), 0, T - 1, cfg, max_em_iter=6, n_shards=8
    )
    d_em = max_leaf_diff(base.params, shrd.params)
    n = min(base.n_iter, shrd.n_iter)
    d_ll = float(
        np.max(
            np.abs(
                np.asarray(shrd.loglik_path[:n])
                - np.asarray(base.loglik_path[:n])
            )
        )
    )
    assert d_em <= 1e-10, f"plain-EM parity {d_em}"
    assert d_ll <= 1e-10, f"plain-EM loglik parity {d_ll}"

    # collapsed-AR EM: the production large-N path
    base_ar = estimate_dfm_em_ar(
        x, np.ones(N), 0, T - 1, cfg, max_em_iter=4, method="collapsed"
    )
    shrd_ar = estimate_dfm_em_ar(
        x, np.ones(N), 0, T - 1, cfg, max_em_iter=4, method="collapsed",
        n_shards=8,
    )
    d_ar = max_leaf_diff(base_ar.params, shrd_ar.params)
    assert d_ar <= 1e-10, f"collapsed-AR parity {d_ar}"

    dg = _digest((shrd.params, shrd.loglik_path, shrd_ar.params))
    print(
        f"RESULT pid={pid} emdiff={d_em:.3e} lldiff={d_ll:.3e} "
        f"ardiff={d_ar:.3e} digest={dg}",
        flush=True,
    )


def main():
    ok = initialize_distributed(f"127.0.0.1:{port}", nproc, pid)
    assert ok, "expected a distributed runtime"
    assert jax.process_count() == nproc
    assert jax.local_device_count() == 4
    assert jax.device_count() == 4 * nproc

    if mode == "em":
        em_mode()
        return

    # 1. global mesh with the documented DCN-outer/ICI-inner factorization:
    #    outer axis strides across processes (device order is process-major)
    mesh = global_mesh(axis_names=("dp", "sp"), shape=(nproc, 4))
    procs = {d.process_index for d in mesh.devices[pid]}
    assert procs == {pid}, "outer mesh axis must align with processes"

    # 2. cross-process moment aggregation: psum over both axes
    x = np.arange(16.0 * 8).reshape(16, 8)
    xg = jax.make_array_from_callback(
        x.shape, NamedSharding(mesh, P("dp", "sp")), lambda idx: x[idx]
    )
    f = shard_map(
        lambda a: jax.lax.psum(a.sum().reshape(1, 1), ("dp", "sp")),
        mesh=mesh,
        in_specs=P("dp", "sp"),
        out_specs=P("dp", "sp"),
    )
    tot = float(np.asarray(jax.device_get(f(xg).addressable_shards[0].data))[0, 0])
    assert tot == x.sum(), f"psum {tot} != {x.sum()}"

    # 3. the real workload: replication-sharded bootstrap over the global
    #    mesh — every process computes the same quantiles (SPMD), with the
    #    final reduction as the only cross-process traffic
    from dynamic_factor_models_tpu.models.favar import wild_bootstrap_irfs

    rng = np.random.default_rng(0)
    y = np.zeros((200, 3))
    A1 = np.array([[0.5, 0.1, 0.0], [0.0, 0.4, 0.1], [0.1, 0.0, 0.3]])
    for t in range(1, 200):
        y[t] = A1 @ y[t - 1] + rng.standard_normal(3)
    rep_mesh = global_mesh(axis_names=("rep",))
    bs = wild_bootstrap_irfs(
        jnp.asarray(y), 1, 0, 199, horizon=8, n_reps=64, seed=0, mesh=rep_mesh
    )
    q = np.asarray(jax.device_get(bs.quantiles))
    assert np.isfinite(q).all()
    print(f"RESULT pid={pid} psum={tot:.6f} qsum={q.sum():.12f}", flush=True)


if __name__ == "__main__":
    main()
