"""Worker for the multi-process distributed test (test_distributed_multiprocess).

Each worker is one 'host': 4 virtual CPU devices, joined into one global
8-device runtime via `jax.distributed.initialize` (coordination service +
Gloo CPU collectives — the DCN analogue this environment can actually run).
Run: python _dist_worker.py <process_id> <num_processes> <port>
"""

import os
import sys

pid, nproc, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402
import jax.numpy as jnp  # noqa: E402
from jax.sharding import NamedSharding, PartitionSpec as P  # noqa: E402

from dynamic_factor_models_tpu.parallel.distributed import (  # noqa: E402
    global_mesh,
    initialize_distributed,
)

# version-compat shim (jax.shard_map vs jax.experimental.shard_map)
from dynamic_factor_models_tpu.parallel.timescan import shard_map  # noqa: E402


def main():
    ok = initialize_distributed(f"127.0.0.1:{port}", nproc, pid)
    assert ok, "expected a distributed runtime"
    assert jax.process_count() == nproc
    assert jax.local_device_count() == 4
    assert jax.device_count() == 4 * nproc

    # 1. global mesh with the documented DCN-outer/ICI-inner factorization:
    #    outer axis strides across processes (device order is process-major)
    mesh = global_mesh(axis_names=("dp", "sp"), shape=(nproc, 4))
    procs = {d.process_index for d in mesh.devices[pid]}
    assert procs == {pid}, "outer mesh axis must align with processes"

    # 2. cross-process moment aggregation: psum over both axes
    x = np.arange(16.0 * 8).reshape(16, 8)
    xg = jax.make_array_from_callback(
        x.shape, NamedSharding(mesh, P("dp", "sp")), lambda idx: x[idx]
    )
    f = shard_map(
        lambda a: jax.lax.psum(a.sum().reshape(1, 1), ("dp", "sp")),
        mesh=mesh,
        in_specs=P("dp", "sp"),
        out_specs=P("dp", "sp"),
    )
    tot = float(np.asarray(jax.device_get(f(xg).addressable_shards[0].data))[0, 0])
    assert tot == x.sum(), f"psum {tot} != {x.sum()}"

    # 3. the real workload: replication-sharded bootstrap over the global
    #    mesh — every process computes the same quantiles (SPMD), with the
    #    final reduction as the only cross-process traffic
    from dynamic_factor_models_tpu.models.favar import wild_bootstrap_irfs

    rng = np.random.default_rng(0)
    y = np.zeros((200, 3))
    A1 = np.array([[0.5, 0.1, 0.0], [0.0, 0.4, 0.1], [0.1, 0.0, 0.3]])
    for t in range(1, 200):
        y[t] = A1 @ y[t - 1] + rng.standard_normal(3)
    rep_mesh = global_mesh(axis_names=("rep",))
    bs = wild_bootstrap_irfs(
        jnp.asarray(y), 1, 0, 199, horizon=8, n_reps=64, seed=0, mesh=rep_mesh
    )
    q = np.asarray(jax.device_get(bs.quantiles))
    assert np.isfinite(q).all()
    print(f"RESULT pid={pid} psum={tot:.6f} qsum={q.sum():.12f}", flush=True)


if __name__ == "__main__":
    main()
