"""Compile-once execution layer (utils/compile.py): shape bucketing is
EXACT, the AOT registry serves every BASELINE panel from one executable,
and donated EM carries don't corrupt results.

The bucketing exactness argument (pinned numerically here): padded cells
are fully masked so every observation statistic is inert, and the one
unmasked time-sum in the M-step — the factor-VAR moments — is weighted by
`PanelStats.tw` so padded periods drop out of S11/S00/S10 and the
effective sample size.  The smoother readout at the bucket shape is exact
at real times because trailing all-missing periods add no information.
"""

import numpy as np
import pytest

from dynamic_factor_models_tpu.models.dfm import DFMConfig
from dynamic_factor_models_tpu.models.ssm import estimate_dfm_em
from dynamic_factor_models_tpu.parallel.mesh import rep_pad
from dynamic_factor_models_tpu.utils import compile as cc


def _panel(T, N, r=4, seed=0, missing=0.0):
    rng = np.random.default_rng(seed)
    f = rng.standard_normal((T, r))
    lam = rng.standard_normal((N, r))
    x = f @ lam.T + 0.5 * rng.standard_normal((T, N))
    if missing:
        # ragged missingness on the tail columns; keep a fully-balanced
        # block so the ALS PCA init has complete series to work with
        x[rng.random((T, N)) < missing * (np.arange(N) >= r + 4)] = np.nan
    return x


@pytest.fixture(autouse=True)
def _clean_compile_env(monkeypatch):
    for var in ("DFM_SHAPE_BUCKETS", "DFM_T_BUCKETS", "DFM_N_BUCKETS",
                "DFM_REP_BUCKET"):
        monkeypatch.delenv(var, raising=False)
    monkeypatch.setenv("DFM_DONATE", "0")


def test_baseline_shapes_share_one_bucket():
    buckets = {
        cc.bucket_shape(T, N)
        for T, N in cc.BASELINE_PANEL_SHAPES.values()
    }
    assert buckets == {(256, 256)}
    # the large-panel bench regime maps to itself (no padding waste)
    assert cc.bucket_shape(2048, 4096) == (2048, 4096)
    # the large-N regimes land on the round-6 wide buckets
    assert cc.bucket_shape(5000, 10000) == (5000, 16384)
    assert cc.bucket_shape(128, 100_000) == (128, 131072)
    # beyond the largest bucket: pass through unpadded rather than fail
    assert cc.bucket_shape(5000, 200_000) == (5000, 200_000)


def test_pad_panel_exact_structure():
    import jax.numpy as jnp

    x = jnp.asarray(np.arange(12.0).reshape(3, 4))
    m = jnp.ones((3, 4), bool).at[1, 2].set(False)
    xp, mp, tw = cc.pad_panel(x, m, 8, 16)
    assert xp.shape == (8, 16) and mp.shape == (8, 16) and tw.shape == (8,)
    np.testing.assert_array_equal(np.asarray(xp[:3, :4]), np.asarray(x))
    assert not np.asarray(mp[3:]).any() and not np.asarray(mp[:, 4:]).any()
    np.testing.assert_array_equal(
        np.asarray(tw), [1, 1, 1, 0, 0, 0, 0, 0]
    )
    with pytest.raises(ValueError, match="bucket"):
        cc.pad_panel(x, m, 2, 16)


def test_rep_pad_arithmetic():
    assert rep_pad(1000, 8) == 1000
    assert rep_pad(1001, 8) == 1008
    assert rep_pad(7, 1) == 7
    assert rep_pad(100, 8, bucket=256) == 256
    assert rep_pad(300, 8, bucket=256) == 512
    assert rep_pad(100, 8, bucket=0) == 104


def test_bucketed_em_matches_unbucketed():
    """The tentpole exactness bar: bucketed == unbucketed at numerical
    precision (f64 here via conftest; the padded program is a different
    schedule, so exact-zero is not expected — 1e-10 is the documented
    bar, measured ~1e-14)."""
    x = _panel(90, 17, seed=3, missing=0.1)
    incl = np.ones(x.shape[1])
    cfg = DFMConfig(nfac_u=2, n_factorlag=2)
    base = estimate_dfm_em(x, incl, 0, x.shape[0] - 1, cfg,
                           max_em_iter=25, bucket=False)
    buck = estimate_dfm_em(x, incl, 0, x.shape[0] - 1, cfg,
                           max_em_iter=25, bucket=True)
    assert buck.factors.shape == base.factors.shape
    assert buck.params.lam.shape == base.params.lam.shape
    np.testing.assert_allclose(
        np.asarray(buck.loglik_path), np.asarray(base.loglik_path),
        atol=1e-10,
    )
    np.testing.assert_allclose(
        np.asarray(buck.factors), np.asarray(base.factors), atol=1e-10
    )
    np.testing.assert_allclose(
        np.asarray(buck.params.lam), np.asarray(base.params.lam), atol=1e-10
    )


@pytest.mark.slow
def test_bucketed_mixed_freq_matches_unbucketed():
    """Same exactness bar for the mixed-frequency path, whose padding also
    extends the aggregation matrix (padded rows get the monthly identity
    row so the augmented state stays well-posed)."""
    from dynamic_factor_models_tpu.models.mixed_freq import (
        estimate_mixed_freq_dfm,
    )

    rng = np.random.default_rng(0)
    T, N, r = 90, 14, 1
    f = np.cumsum(0.3 * rng.standard_normal((T, r)), axis=0) * 0.3
    lam = rng.standard_normal((N, r))
    x = f @ lam.T + 0.5 * rng.standard_normal((T, N))
    isq = np.zeros(N, bool)
    isq[10:] = True
    # quarterly series observed only in quarter-end months
    x[(np.arange(T) % 3 != 2)[:, None] & isq[None, :]] = np.nan
    base = estimate_mixed_freq_dfm(x, isq, r=r, max_em_iter=15, bucket=False)
    buck = estimate_mixed_freq_dfm(x, isq, r=r, max_em_iter=15, bucket=True)
    assert buck.factors.shape == base.factors.shape
    assert buck.x_hat.shape == base.x_hat.shape
    np.testing.assert_allclose(
        np.asarray(buck.loglik_path), np.asarray(base.loglik_path),
        atol=1e-10,
    )
    np.testing.assert_allclose(
        np.asarray(buck.factors), np.asarray(base.factors), atol=1e-10
    )
    np.testing.assert_allclose(
        np.asarray(buck.x_hat), np.asarray(base.x_hat), atol=1e-10
    )


def test_precompile_counters_and_registry_hits():
    cc.reset_counters()
    spec = cc.CompileSpec(
        T=60, N=12, r=2, p=1, dtype=str(np.dtype(float)),
        kernels=("em_step",), max_em_iter=4,
    )
    r1 = cc.precompile(spec)
    assert not r1["kernels"]["em_step"]["aot_cached"]
    assert r1["kernels"]["em_step"]["compile_s"] > 0
    assert cc.counters()["em_step"]["compiles"] == 1
    # second precompile of the identical spec: served from the in-process
    # registry, zero XLA work
    r2 = cc.precompile(spec)
    assert r2["kernels"]["em_step"]["aot_cached"]
    assert r2["compile_s_total"] == 0.0
    c = cc.counters()["em_step"]
    assert c["compiles"] == 1 and c["aot_hits"] == 1


def test_one_executable_serves_all_baseline_configs():
    """Acceptance pin: after ONE precompile for the shared bucket, the EM
    loop of every BASELINE panel shape dispatches the SAME executable —
    zero recompiles, counter-verified."""
    cc.reset_counters()
    # production default dispatches the health-guarded while-loop, so the
    # acceptance pin tracks the "em_loop_guarded" kernel
    spec = cc.CompileSpec(
        T=224, N=139, dtype=str(np.dtype(float)),
        kernels=("em_loop_guarded",), max_em_iter=8,
    )
    assert spec.padded_shape() == (256, 256)
    cc.precompile(spec, warmup=False)
    assert cc.counters()["em_loop_guarded"]["compiles"] == 1

    cfg = DFMConfig(nfac_u=4, tol=1e-5, max_iter=300)
    for i, (T, N) in enumerate(cc.BASELINE_PANEL_SHAPES.values()):
        x = _panel(T, N, seed=10 + i)
        estimate_dfm_em(x, np.ones(N), 0, T - 1, cfg,
                        max_em_iter=8, bucket=True)
    c = cc.counters()["em_loop_guarded"]
    assert c["compiles"] == 1, "a BASELINE config recompiled the EM loop"
    assert c["aot_misses"] == 0
    assert c["aot_hits"] == len(cc.BASELINE_PANEL_SHAPES)
    assert c["runs"] == len(cc.BASELINE_PANEL_SHAPES)
    assert c["run_s"] > 0


def test_donated_carry_matches_undonated(monkeypatch):
    """DFM_DONATE=1 compiles the donated while-loop variant (on CPU XLA
    falls back to copying); results must be identical to the undonated
    program, and the caller's params must survive (run_em_loop copies
    before donating the carry)."""
    x = _panel(80, 15, seed=5, missing=0.08)
    incl = np.ones(x.shape[1])
    cfg = DFMConfig(nfac_u=2, n_factorlag=1)

    monkeypatch.setenv("DFM_DONATE", "0")
    base = estimate_dfm_em(x, incl, 0, x.shape[0] - 1, cfg, max_em_iter=12)
    monkeypatch.setenv("DFM_DONATE", "1")
    don = estimate_dfm_em(x, incl, 0, x.shape[0] - 1, cfg, max_em_iter=12)

    assert don.n_iter == base.n_iter
    np.testing.assert_allclose(
        np.asarray(don.loglik_path), np.asarray(base.loglik_path),
        atol=1e-12,
    )
    np.testing.assert_allclose(
        np.asarray(don.factors), np.asarray(base.factors), atol=1e-12
    )


@pytest.mark.slow
def test_configure_compilation_cache_round_trip(tmp_path, monkeypatch):
    """An explicit cache dir is created, adopted, and sticky for later
    default calls; DFM_COMPILE_CACHE=0 disables."""
    d = str(tmp_path / "jax_cache")
    active = cc.configure_compilation_cache(cache_dir=d)
    assert active == d
    import os

    assert os.path.isdir(d)
    # idempotent default call returns the configured dir
    assert cc.configure_compilation_cache() == d
    monkeypatch.setenv("DFM_COMPILE_CACHE", "0")
    assert cc.configure_compilation_cache() is None
